"""asyncflow_tpu — a TPU-native scenario simulator for async distributed backends.

Same capability surface as the reference AsyncFlow project (YAML/builder front
doors, event-loop server model, event injection, metrics/plots), re-designed
around a batched JAX next-event engine so Monte-Carlo scenario sweeps run as
one vmapped, mesh-sharded kernel. A sequential CPU "oracle" DES provides the
behavioral reference and single-scenario runs.
"""

from asyncflow_tpu.builder.flow import AsyncFlow

__version__ = "0.6.0"

__all__ = ["AsyncFlow", "SimulationRunner", "TelemetryConfig", "__version__"]


def __getattr__(name: str):
    # SimulationRunner pulls in the engines (and thus jax); import lazily so
    # schema-only users never pay for it.
    if name == "SimulationRunner":
        from asyncflow_tpu.runtime.runner import SimulationRunner

        return SimulationRunner
    if name == "TelemetryConfig":
        from asyncflow_tpu.observability import TelemetryConfig

        return TelemetryConfig
    msg = f"module 'asyncflow_tpu' has no attribute {name!r}"
    raise AttributeError(msg)
