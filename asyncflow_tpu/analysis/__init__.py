"""Curated public surface for post-run analysis."""

from asyncflow_tpu.metrics.analyzer import ResultsAnalyzer

__all__ = ["ResultsAnalyzer"]
