"""Monte-Carlo inference over sweep ensembles: interval estimators,
variance reduction, CRN-paired A/B comparison, and adaptive sequential
sweeps (docs/guides/mc-inference.md).

Heavy runtime imports (jax, the sweep layer) are deferred into the call
paths that need them — importing this package costs numpy only.
"""

from asyncflow_tpu.analysis.adaptive import (
    AdaptiveReport,
    AdaptiveRound,
    AdaptiveSweep,
)
from asyncflow_tpu.analysis.compare import ComparisonReport, compare
from asyncflow_tpu.analysis.estimators import (
    IntervalEstimate,
    binomial_rank_bounds,
    bootstrap_mean_ci,
    bootstrap_quantile_ci,
    bootstrap_ratio_ci,
    effective_results,
    interval_for_metric,
    paired_delta_for_metric,
    paired_delta_quantile_ci,
    paired_delta_ratio_ci,
    pooled_quantile_ci,
)
from asyncflow_tpu.analysis.vr import (
    antithetic_mean_ci,
    antithetic_pair_means,
    coupling_diagnostics,
)
from asyncflow_tpu.metrics.analyzer import ResultsAnalyzer
from asyncflow_tpu.schemas.experiment import (
    ExperimentConfig,
    PrecisionTarget,
    VarianceReduction,
)

__all__ = [
    "AdaptiveReport",
    "AdaptiveRound",
    "AdaptiveSweep",
    "ComparisonReport",
    "ExperimentConfig",
    "IntervalEstimate",
    "PrecisionTarget",
    "ResultsAnalyzer",
    "VarianceReduction",
    "antithetic_mean_ci",
    "antithetic_pair_means",
    "binomial_rank_bounds",
    "bootstrap_mean_ci",
    "bootstrap_quantile_ci",
    "bootstrap_ratio_ci",
    "compare",
    "coupling_diagnostics",
    "effective_results",
    "interval_for_metric",
    "paired_delta_for_metric",
    "paired_delta_quantile_ci",
    "paired_delta_ratio_ci",
    "pooled_quantile_ci",
]
