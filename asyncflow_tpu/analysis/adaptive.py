"""Adaptive sequential sweeps: run until the intervals are tight enough.

:class:`AdaptiveSweep` drives a :class:`SweepRunner` in growing rounds
until every :class:`PrecisionTarget` of the experiment's design is met or
the scenario budget is exhausted.  Each round runs only the INCREMENT of
the deterministic scenario grid (``first_scenario`` continuation — the
per-scenario key grid is prefix-stable, so the union of the rounds is
bit-identical to one uninterrupted sweep of the same total), re-estimates
every target metric's confidence interval on the merged ensemble, and
records the half-width trajectory.  The stop reason, the per-round
trajectory, and the final intervals all land in the report — and in the
run-record telemetry when configured (docs/guides/mc-inference.md).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass

from asyncflow_tpu.analysis.estimators import (
    IntervalEstimate,
    interval_for_metric,
)
from asyncflow_tpu.schemas.experiment import ExperimentConfig

#: stop reasons an :class:`AdaptiveReport` can carry
STOP_TARGETS_MET = "targets_met"
STOP_BUDGET_EXHAUSTED = "budget_exhausted"


@dataclass(frozen=True)
class AdaptiveRound:
    """One round of the sequential schedule."""

    index: int
    #: scenarios added this round / cumulative after it
    n_new: int
    n_total: int
    wall_seconds: float
    #: per-target interval on the CUMULATIVE ensemble after this round
    intervals: dict[str, IntervalEstimate]
    #: target metrics whose precision is still unmet after this round
    unmet: tuple[str, ...]

    def as_dict(self) -> dict:
        return {
            "index": self.index,
            "n_new": self.n_new,
            "n_total": self.n_total,
            "wall_seconds": round(self.wall_seconds, 6),
            "intervals": {m: e.as_dict() for m, e in self.intervals.items()},
            "half_widths": {
                m: e.half_width for m, e in self.intervals.items()
            },
            "unmet": list(self.unmet),
        }


@dataclass(frozen=True)
class AdaptiveReport:
    """Outcome of an adaptive sweep: merged report + stopping trace."""

    #: merged SweepReport over every scenario the driver ran
    report: object
    rounds: list[AdaptiveRound]
    stop_reason: str
    experiment: ExperimentConfig
    seed: int

    @property
    def n_scenarios(self) -> int:
        return self.rounds[-1].n_total if self.rounds else 0

    @property
    def intervals(self) -> dict[str, IntervalEstimate]:
        """Final per-target intervals (last round's)."""
        return self.rounds[-1].intervals if self.rounds else {}

    def as_dict(self) -> dict:
        return {
            "stop_reason": self.stop_reason,
            "n_scenarios": self.n_scenarios,
            "n_rounds": len(self.rounds),
            "seed": self.seed,
            "rounds": [r.as_dict() for r in self.rounds],
        }


class AdaptiveSweep:
    """Sequential-stopping driver over a :class:`SweepRunner`.

    The schedule comes from the experiment's design: round 1 runs
    ``initial_scenarios``; each later round grows the cumulative ensemble
    by ``growth_factor`` (clipped to ``max_scenarios``).  A round's
    increment continues the deterministic grid via ``first_scenario``, so
    checkpointing composes (an interrupted adaptive run resumes its rounds'
    chunks) and results match an uninterrupted sweep of the same total.

    With antithetic pairing on, increments are kept even so every round
    closes its reflected pairs.
    """

    def __init__(
        self,
        payload,
        experiment: ExperimentConfig,
        *,
        engine: str = "auto",
        use_mesh: bool = True,
        n_boot: int = 1000,
        chunk_size: int | None = None,
        checkpoint_dir: str | None = None,
        telemetry=None,
        runner=None,
    ) -> None:
        """``runner``: inject a pre-built :class:`SweepRunner` (it must
        carry the SAME experiment config); otherwise one is constructed
        from ``payload`` with the remaining knobs."""
        if not experiment.precision:
            msg = (
                "adaptive sweeps need at least one PrecisionTarget in "
                "ExperimentConfig.precision (otherwise there is nothing "
                "to stop on)"
            )
            raise ValueError(msg)
        self.experiment = experiment
        self._n_boot = n_boot
        self._chunk_size = chunk_size
        self._checkpoint_dir = checkpoint_dir
        self._telemetry = telemetry
        if runner is not None:
            self.runner = runner
        else:
            from asyncflow_tpu.parallel.sweep import SweepRunner

            self.runner = SweepRunner(
                payload,
                engine=engine,
                use_mesh=use_mesh,
                experiment=experiment,
            )

    def _schedule(self) -> list[int]:
        """Cumulative scenario totals per round (monotone, capped)."""
        exp = self.experiment
        anti = exp.variance_reduction.antithetic
        totals: list[int] = []
        total = int(exp.initial_scenarios)
        if anti and total % 2:
            total += 1
        while True:
            total = min(total, int(exp.max_scenarios))
            if anti and total % 2:
                total -= 1
            if totals and total <= totals[-1]:
                break
            totals.append(total)
            if total >= exp.max_scenarios:
                break
            total = int(math.ceil(totals[-1] * exp.growth_factor))
        return totals

    def run(self, *, seed: int = 0, overrides=None) -> AdaptiveReport:
        """Run rounds until every target is met or the budget runs out.

        ``overrides`` must be base (unbatched) values — per-scenario
        batches don't compose with a data-dependent total.
        """
        from asyncflow_tpu.parallel.sweep import (
            SweepReport,
            _concat_sweeps,
        )

        exp = self.experiment
        level = exp.confidence_level
        anti = exp.variance_reduction.antithetic
        rounds: list[AdaptiveRound] = []
        partials = []
        merged = None
        done = 0  # scenarios completed
        keys_used = 0  # rows of the key grid consumed (n/2 per antithetic n)
        wall_total = 0.0
        stop_reason = STOP_BUDGET_EXHAUSTED
        for idx, total in enumerate(self._schedule()):
            n_new = total - done
            t0 = time.perf_counter()
            rep = self.runner.run(
                n_new,
                seed=seed,
                overrides=overrides,
                chunk_size=self._chunk_size,
                checkpoint_dir=self._checkpoint_dir,
                first_scenario=keys_used,
                telemetry=self._telemetry,
            )
            wall = time.perf_counter() - t0
            wall_total += wall
            partials.append(rep.results)
            merged = _concat_sweeps(partials)
            done = total
            keys_used += n_new // 2 if anti else n_new
            intervals = {
                t.metric: interval_for_metric(
                    merged,
                    t.metric,
                    level,
                    n_boot=self._n_boot,
                    seed=seed,
                )
                for t in exp.precision
            }
            unmet = tuple(
                t.metric
                for t in exp.precision
                if not intervals[t.metric].meets(
                    t.half_width, relative=t.relative,
                )
            )
            rounds.append(
                AdaptiveRound(
                    index=idx,
                    n_new=n_new,
                    n_total=total,
                    wall_seconds=wall,
                    intervals=intervals,
                    unmet=unmet,
                ),
            )
            if not unmet:
                stop_reason = STOP_TARGETS_MET
                break

        report = SweepReport(
            results=merged,
            n_scenarios=done,
            wall_seconds=wall_total,
            plan=self.runner.plan,
            antithetic=anti,
        )
        out = AdaptiveReport(
            report=report,
            rounds=rounds,
            stop_reason=stop_reason,
            experiment=exp,
            seed=seed,
        )
        self._emit_telemetry(out)
        return out

    def _emit_telemetry(self, result: AdaptiveReport) -> None:
        """One ``kind="adaptive"`` run record: rounds, half-width
        trajectory, stop reason — beside the per-round sweep records."""
        from asyncflow_tpu.observability.telemetry import telemetry_session

        tel = telemetry_session(self._telemetry, kind="adaptive")
        if tel is None:
            return
        with tel:
            tel.add_meta(
                stop_reason=result.stop_reason,
                n_rounds=len(result.rounds),
                n_scenarios=result.n_scenarios,
                seed=result.seed,
                targets=[t.model_dump() for t in self.experiment.precision],
                rounds=[r.as_dict() for r in result.rounds],
            )
        tel.finalize()
