"""CRN-paired A/B comparison: "is topology B actually better?".

:func:`compare` runs a baseline and a candidate scenario configuration of
the SAME payload under common random numbers — both arms share the
per-scenario key grid (and, on the event engine, per-request substreams via
``crn=True``) — and reports paired-delta confidence intervals per metric.
Because the arms see the same noise, the scenario-level deltas carry far
less variance than two independently-seeded sweeps, which is the entire
point: a delta-p95 CI narrow enough to call a winner at a fraction of the
scenario budget (docs/guides/mc-inference.md has the worked example and the
measured tightening).

The delta intervals come from scenario-paired bootstrap resampling
(:func:`asyncflow_tpu.analysis.estimators.paired_delta_for_metric`), which
is valid for independently-seeded arms too — coupling only *narrows* it —
so ``candidate_seed`` exists to run the uncoupled comparison the coupled
one should be benchmarked against.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from asyncflow_tpu.analysis.estimators import (
    _QUANTILE_METRICS,
    IntervalEstimate,
    _ratio_components,
    paired_delta_for_metric,
)
from asyncflow_tpu.analysis.vr import coupling_diagnostics
from asyncflow_tpu.schemas.experiment import (
    SUPPORTED_METRICS,
    ExperimentConfig,
    VarianceReduction,
    metric_supported,
)

#: default metric set of a comparison (every SUPPORTED_METRICS entry the
#: "which arm wins" question usually turns on)
DEFAULT_COMPARE_METRICS = (
    "latency_p50_s",
    "latency_p95_s",
    "latency_p99_s",
    "goodput_fraction",
)


def per_scenario_metric(results, metric: str) -> np.ndarray:
    """(S,) per-scenario values of one summary metric (quantiles from the
    per-scenario histograms, ratios from the per-scenario totals)."""
    if metric in _QUANTILE_METRICS:
        return np.asarray(results.percentile(_QUANTILE_METRICS[metric]))
    num, den = _ratio_components(results, metric)
    return num / np.maximum(den, 1e-300)


@dataclass(frozen=True)
class ComparisonReport:
    """Paired A/B comparison outcome (candidate minus baseline)."""

    #: per-metric CI on candidate - baseline (negative latency delta =
    #: candidate is faster; positive goodput delta = candidate completes
    #: a larger share)
    deltas: dict[str, IntervalEstimate]
    #: per-metric coupling diagnostics over the per-scenario metric arrays
    #: (``correlation`` near +1 = CRN bit; ``variance_ratio_vs_independent``
    #: < 1 = the paired delta is that much tighter than independent arms)
    coupling: dict[str, dict]
    baseline: object  # SweepReport
    candidate: object  # SweepReport
    n_scenarios: int
    seed: int
    candidate_seed: int
    level: float
    engine: str
    metrics: tuple[str, ...] = field(default=DEFAULT_COMPARE_METRICS)

    @property
    def coupled(self) -> bool:
        """Did the arms share the scenario key grid (CRN)?"""
        return self.seed == self.candidate_seed

    def decisive(self, metric: str) -> bool:
        """Does the ``metric`` delta CI exclude zero?"""
        est = self.deltas[metric]
        return bool(est.lo > 0.0 or est.hi < 0.0)

    def as_dict(self) -> dict:
        return {
            "n_scenarios": self.n_scenarios,
            "seed": self.seed,
            "candidate_seed": self.candidate_seed,
            "coupled": self.coupled,
            "level": self.level,
            "engine": self.engine,
            "deltas": {m: e.as_dict() for m, e in self.deltas.items()},
            "decisive": {m: self.decisive(m) for m in self.deltas},
            "coupling": self.coupling,
        }


def compare(
    payload,
    baseline_overrides=None,
    candidate_overrides=None,
    *,
    n_scenarios: int = 256,
    seed: int = 0,
    candidate_seed: int | None = None,
    metrics: tuple[str, ...] = DEFAULT_COMPARE_METRICS,
    level: float = 0.95,
    n_boot: int = 1000,
    engine: str = "auto",
    use_mesh: bool = True,
    chunk_size: int | None = None,
    experiment: ExperimentConfig | None = None,
    telemetry=None,
) -> ComparisonReport:
    """Run both arms of an A/B experiment under CRN and interval the deltas.

    ``baseline_overrides`` / ``candidate_overrides`` are each a
    :class:`ScenarioOverrides` (base values shared by every scenario, or a
    per-scenario batch of ``n_scenarios`` rows), a dict of
    :func:`asyncflow_tpu.parallel.make_overrides` keyword arguments, or
    ``None`` for the payload as lowered.  The two arms run through ONE
    :class:`SweepRunner` — same plan, same key grid — differing only in
    their overrides, which is exactly the "two sweeps differing only in
    ScenarioOverrides share draws" CRN contract.

    ``candidate_seed`` (default: same as ``seed``) de-couples the arms to
    quantify what CRN buys; ``experiment`` overrides the default CRN-on
    design (its precision targets are ignored here — see
    :class:`asyncflow_tpu.analysis.AdaptiveSweep` for sequential stopping).
    """
    from asyncflow_tpu.parallel.sweep import SweepRunner, make_overrides

    unknown = [m for m in metrics if not metric_supported(m)]
    if unknown:
        msg = (
            f"unknown comparison metrics {unknown}; supported: "
            f"{', '.join(SUPPORTED_METRICS)}, blame_share:<phase>"
        )
        raise ValueError(msg)
    if experiment is None:
        experiment = ExperimentConfig(
            variance_reduction=VarianceReduction(crn=True),
        )
    runner = SweepRunner(
        payload,
        engine=engine,
        use_mesh=use_mesh,
        experiment=experiment,
        telemetry=telemetry,
        # asking for a blame_share:<phase> delta implies attribution: both
        # arms need the recorded blame rows the estimator pools over
        blame=any(m.startswith("blame_share:") for m in metrics),
    )

    def _arm_overrides(spec):
        if spec is None or not isinstance(spec, dict):
            return spec
        return make_overrides(runner.plan, n_scenarios, **spec)

    cand_seed = seed if candidate_seed is None else candidate_seed
    rep_a = runner.run(
        n_scenarios,
        seed=seed,
        overrides=_arm_overrides(baseline_overrides),
        chunk_size=chunk_size,
    )
    rep_b = runner.run(
        n_scenarios,
        seed=cand_seed,
        overrides=_arm_overrides(candidate_overrides),
        chunk_size=chunk_size,
    )

    deltas: dict[str, IntervalEstimate] = {}
    coupling: dict[str, dict] = {}
    for i, metric in enumerate(metrics):
        deltas[metric] = paired_delta_for_metric(
            rep_a.results,
            rep_b.results,
            metric,
            level,
            n_boot=n_boot,
            # distinct (deterministic) resample streams per metric
            seed=seed * 1000 + i,
        )
        coupling[metric] = coupling_diagnostics(
            per_scenario_metric(rep_a.results, metric),
            per_scenario_metric(rep_b.results, metric),
        )
    return ComparisonReport(
        deltas=deltas,
        coupling=coupling,
        baseline=rep_a,
        candidate=rep_b,
        n_scenarios=n_scenarios,
        seed=seed,
        candidate_seed=cand_seed,
        level=level,
        engine=runner.engine_kind,
        metrics=tuple(metrics),
    )
