"""Monte-Carlo interval estimators over sweep ensembles.

Two estimator families, both operating on the per-scenario reductions a
sweep already streams to the host (histograms, moment sums, counters) — no
per-request data is ever needed:

- **Order-statistic (binomial) CIs on pooled quantiles**
  (:func:`pooled_quantile_ci`): the classic distribution-free interval on
  the latency quantile of the POOLED request population.  This is the
  statistically meaningful interval for "p99 latency of the system" — not
  the mean of per-scenario percentiles the legacy
  ``SweepReport.percentile_ci`` reported (kept as
  ``per_scenario_percentile_mean_ci``).
- **Scenario-resampling bootstrap** (:func:`bootstrap_mean_ci`,
  :func:`bootstrap_ratio_ci`, :func:`bootstrap_quantile_ci`,
  :func:`paired_delta_quantile_ci`, :func:`paired_delta_ratio_ci`):
  resamples whole scenarios (the i.i.d. replication unit), so
  within-scenario dependence between requests is honored.  Replicates are
  weighted-histogram matmuls, so one call is a single (B, S) x (S, ...)
  contraction: NumPy on CPU, on-device via vmapped bincount + matmul for
  large ensembles on an accelerator (ABMax's ensemble-statistics idiom).

Paired estimators resample the SAME scenario indices in both arms, which is
what turns CRN coupling (``docs/guides/mc-inference.md``) into narrower
delta intervals: the common noise cancels inside each replicate.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from statistics import NormalDist

import numpy as np

from asyncflow_tpu.engines.results import hist_percentile

#: replicate count past which (and only on a live accelerator backend) the
#: resample-weight construction runs on device
_DEVICE_RESAMPLE_MIN = 4_000_000


@dataclass(frozen=True)
class IntervalEstimate:
    """A point estimate with a two-sided confidence interval.

    ``n_excluded`` notes scenarios masked out by host-fault quarantine
    (docs/guides/fault-tolerance.md) before estimation: the interval
    describes the surviving (effective-n) population only.
    """

    point: float
    lo: float
    hi: float
    level: float
    n: int
    method: str
    n_excluded: int = 0

    @property
    def half_width(self) -> float:
        """Half the interval width (NaN propagates from empty ensembles)."""
        return (self.hi - self.lo) / 2.0

    def meets(self, half_width: float, *, relative: bool = False) -> bool:
        """Does the interval resolve the metric to ``half_width``?"""
        hw = self.half_width
        if not math.isfinite(hw):
            return False
        if relative:
            scale = abs(self.point)
            return hw <= half_width * scale if scale > 0 else hw == 0.0
        return hw <= half_width

    def as_dict(self) -> dict:
        return {
            "point": self.point,
            "lo": self.lo,
            "hi": self.hi,
            "level": self.level,
            "n": self.n,
            "method": self.method,
            "half_width": self.half_width,
            "n_excluded": self.n_excluded,
        }


def _nan_interval(level: float, method: str) -> IntervalEstimate:
    nan = float("nan")
    return IntervalEstimate(nan, nan, nan, level, 0, method)


def _check_level(level: float) -> None:
    if not 0.0 < level < 1.0:
        msg = f"confidence level must be in (0, 1), got {level}"
        raise ValueError(msg)


# ---------------------------------------------------------------------------
# order-statistic (binomial) pooled-quantile CI
# ---------------------------------------------------------------------------


def binomial_rank_bounds(n: int, p: float, level: float) -> tuple[int, int]:
    """1-indexed order-statistic ranks (r, s) with
    ``P(x_(r) <= xi_p <= x_(s)) >= level`` for n i.i.d. draws.

    Exact binomial-CDF inversion for small n; the normal approximation to
    Bin(n, p) beyond (its rank error is sub-integer well before the
    crossover).  Ranks are clamped into [1, n].
    """
    _check_level(level)
    if n < 1:
        msg = f"need at least one observation, got n={n}"
        raise ValueError(msg)
    alpha = 1.0 - level
    if n <= 2000:
        k = np.arange(n + 1, dtype=np.float64)
        lg = np.vectorize(math.lgamma)
        logpmf = (
            lg(n + 1.0)
            - lg(k + 1.0)
            - lg(n - k + 1.0)
            + k * math.log(max(p, 1e-300))
            + (n - k) * math.log1p(-min(p, 1.0 - 1e-16))
        )
        cdf = np.cumsum(np.exp(logpmf))
        # largest r with F(r-1) <= alpha/2; smallest s with F(s-1) >= 1-alpha/2
        r = int(np.searchsorted(cdf, alpha / 2.0, side="right"))
        s = int(np.searchsorted(cdf, 1.0 - alpha / 2.0, side="left")) + 1
    else:
        z = NormalDist().inv_cdf(1.0 - alpha / 2.0)
        mu = n * p
        sd = math.sqrt(n * p * (1.0 - p))
        r = int(math.floor(mu - z * sd))
        s = int(math.ceil(mu + z * sd)) + 1
    return max(r, 1), min(s, n)


def pooled_quantile_ci(
    counts: np.ndarray,
    edges: np.ndarray,
    q: float,
    level: float = 0.95,
) -> IntervalEstimate:
    """Order-statistic CI on the pooled latency quantile ``q`` (percent).

    ``counts`` is the per-scenario histogram stack ``(S, B)`` (or an
    already-pooled ``(B,)`` row); the interval maps the binomial rank
    bounds through the pooled histogram's inverse CDF, so resolution is
    the log-bin width (~1.6% of the value at 1024 bins).
    """
    _check_level(level)
    counts = np.asarray(counts, np.float64)
    pooled = counts.sum(axis=0) if counts.ndim == 2 else counts
    n = int(round(float(pooled.sum())))
    if n == 0:
        return _nan_interval(level, "order-statistic")
    point = float(hist_percentile(pooled, edges, q))
    r, s = binomial_rank_bounds(n, q / 100.0, level)
    lo = float(hist_percentile(pooled, edges, 100.0 * r / n))
    hi = float(hist_percentile(pooled, edges, 100.0 * s / n))
    return IntervalEstimate(point, lo, hi, level, n, "order-statistic")


# ---------------------------------------------------------------------------
# scenario-resampling bootstrap
# ---------------------------------------------------------------------------


def resample_weights(n: int, n_boot: int, seed: int) -> np.ndarray:
    """(B, n) multinomial resample-count matrix — the bootstrap's only
    random object; every replicate statistic is a weighted reduction by one
    of its rows.  Host path draws ``numpy`` multinomials; on a live
    accelerator backend large problems build the counts on device
    (vmapped randint + bincount).  The two paths draw different (equally
    valid) resamples; each is deterministic in ``seed``.
    """
    if n < 1 or n_boot < 1:
        msg = f"need n >= 1 and n_boot >= 1, got n={n}, n_boot={n_boot}"
        raise ValueError(msg)
    use_device = False
    if n * n_boot >= _DEVICE_RESAMPLE_MIN:
        try:
            import jax

            use_device = jax.default_backend() != "cpu"
        except Exception:  # pragma: no cover - jax always importable here
            use_device = False
    if use_device:  # pragma: no cover - exercised on accelerator hosts
        import jax
        import jax.numpy as jnp

        keys = jax.random.split(jax.random.PRNGKey(seed), n_boot)

        def one(k):
            idx = jax.random.randint(k, (n,), 0, n)
            return jnp.bincount(idx, length=n)

        return np.asarray(jax.jit(jax.vmap(one))(keys), np.float64)
    rng = np.random.default_rng(seed)
    return rng.multinomial(n, np.full(n, 1.0 / n), size=n_boot).astype(
        np.float64,
    )


def _percentile_interval(
    reps: np.ndarray,
    point: float,
    level: float,
    n: int,
    method: str,
) -> IntervalEstimate:
    reps = np.asarray(reps, np.float64)
    reps = reps[np.isfinite(reps)]
    if reps.size == 0:
        return _nan_interval(level, method)
    alpha = 1.0 - level
    lo, hi = np.percentile(reps, [100.0 * alpha / 2.0, 100.0 * (1.0 - alpha / 2.0)])
    return IntervalEstimate(point, float(lo), float(hi), level, n, method)


def bootstrap_mean_ci(
    values: np.ndarray,
    level: float = 0.95,
    *,
    n_boot: int = 1000,
    seed: int = 0,
) -> IntervalEstimate:
    """Percentile-bootstrap CI on the mean of i.i.d. per-scenario values."""
    _check_level(level)
    values = np.asarray(values, np.float64)
    values = values[np.isfinite(values)]
    n = values.size
    if n == 0:
        return _nan_interval(level, "bootstrap-mean")
    w = resample_weights(n, n_boot, seed)
    reps = (w @ values) / n
    return _percentile_interval(
        reps, float(values.mean()), level, n, "bootstrap-mean",
    )


def bootstrap_ratio_ci(
    num: np.ndarray,
    den: np.ndarray,
    level: float = 0.95,
    *,
    n_boot: int = 1000,
    seed: int = 0,
) -> IntervalEstimate:
    """Percentile-bootstrap CI on ``sum(num) / sum(den)`` over scenarios.

    The ratio-of-sums estimator covers pooled means of per-scenario totals
    — mean latency (latency_sum / completed) and goodput
    (completed / offered) both take this shape.
    """
    _check_level(level)
    num = np.asarray(num, np.float64)
    den = np.asarray(den, np.float64)
    if num.shape != den.shape:
        msg = f"num/den shape mismatch: {num.shape} vs {den.shape}"
        raise ValueError(msg)
    n = num.size
    if n == 0 or den.sum() <= 0:
        return _nan_interval(level, "bootstrap-ratio")
    w = resample_weights(n, n_boot, seed)
    reps = (w @ num) / np.maximum(w @ den, 1e-300)
    return _percentile_interval(
        reps, float(num.sum() / den.sum()), level, n, "bootstrap-ratio",
    )


def bootstrap_quantile_ci(
    counts: np.ndarray,
    edges: np.ndarray,
    q: float,
    level: float = 0.95,
    *,
    n_boot: int = 1000,
    seed: int = 0,
) -> IntervalEstimate:
    """Scenario-resampled bootstrap CI on the pooled quantile ``q``.

    Unlike :func:`pooled_quantile_ci` (which treats pooled requests as
    i.i.d.), this resamples whole scenarios, honoring within-scenario
    dependence — the conservative choice when scenarios are heterogeneous.
    """
    _check_level(level)
    counts = np.atleast_2d(np.asarray(counts, np.float64))
    n = counts.shape[0]
    if n == 0 or counts.sum() == 0:
        return _nan_interval(level, "bootstrap-quantile")
    w = resample_weights(n, n_boot, seed)
    reps = hist_percentile(w @ counts, edges, q)
    point = float(hist_percentile(counts.sum(axis=0), edges, q))
    return _percentile_interval(reps, point, level, n, "bootstrap-quantile")


def paired_delta_quantile_ci(
    counts_a: np.ndarray,
    counts_b: np.ndarray,
    edges: np.ndarray,
    q: float,
    level: float = 0.95,
    *,
    n_boot: int = 1000,
    seed: int = 0,
) -> IntervalEstimate:
    """CI on ``quantile_b - quantile_a`` with scenario-paired resampling.

    Each bootstrap replicate resamples ONE set of scenario indices and
    applies it to BOTH arms — under CRN the coupled noise cancels inside
    each replicate, which is where the paired interval's narrowness comes
    from; for independently-seeded arms it degrades gracefully to the
    independent-comparison width.
    """
    _check_level(level)
    counts_a = np.atleast_2d(np.asarray(counts_a, np.float64))
    counts_b = np.atleast_2d(np.asarray(counts_b, np.float64))
    if counts_a.shape != counts_b.shape:
        msg = (
            "paired arms need matching (S, B) histogram stacks, got "
            f"{counts_a.shape} vs {counts_b.shape}"
        )
        raise ValueError(msg)
    n = counts_a.shape[0]
    if n == 0 or counts_a.sum() == 0 or counts_b.sum() == 0:
        return _nan_interval(level, "paired-bootstrap-quantile")
    w = resample_weights(n, n_boot, seed)
    reps = hist_percentile(w @ counts_b, edges, q) - hist_percentile(
        w @ counts_a, edges, q,
    )
    point = float(
        hist_percentile(counts_b.sum(axis=0), edges, q)
        - hist_percentile(counts_a.sum(axis=0), edges, q),
    )
    return _percentile_interval(
        reps, point, level, n, "paired-bootstrap-quantile",
    )


def paired_delta_ratio_ci(
    num_a: np.ndarray,
    den_a: np.ndarray,
    num_b: np.ndarray,
    den_b: np.ndarray,
    level: float = 0.95,
    *,
    n_boot: int = 1000,
    seed: int = 0,
) -> IntervalEstimate:
    """CI on ``ratio_b - ratio_a`` with scenario-paired resampling."""
    _check_level(level)
    num_a = np.asarray(num_a, np.float64)
    den_a = np.asarray(den_a, np.float64)
    num_b = np.asarray(num_b, np.float64)
    den_b = np.asarray(den_b, np.float64)
    n = num_a.size
    if not (den_a.size == num_b.size == den_b.size == n):
        msg = "paired ratio arms need four equal-length scenario arrays"
        raise ValueError(msg)
    if n == 0 or den_a.sum() <= 0 or den_b.sum() <= 0:
        return _nan_interval(level, "paired-bootstrap-ratio")
    w = resample_weights(n, n_boot, seed)
    reps = (w @ num_b) / np.maximum(w @ den_b, 1e-300) - (w @ num_a) / (
        np.maximum(w @ den_a, 1e-300)
    )
    point = float(num_b.sum() / den_b.sum() - num_a.sum() / den_a.sum())
    return _percentile_interval(
        reps, point, level, n, "paired-bootstrap-ratio",
    )


# ---------------------------------------------------------------------------
# metric dispatch over SweepResults (shared by compare() and AdaptiveSweep)
# ---------------------------------------------------------------------------

_QUANTILE_METRICS = {
    "latency_p50_s": 50.0,
    "latency_p90_s": 90.0,
    "latency_p95_s": 95.0,
    "latency_p99_s": 99.0,
}


def _ratio_components(results, metric: str) -> tuple[np.ndarray, np.ndarray]:
    """(num, den) per-scenario arrays of a ratio-of-sums metric."""
    completed = np.asarray(results.completed, np.float64)
    if metric == "latency_mean_s":
        return np.asarray(results.latency_sum, np.float64), completed
    if metric == "goodput_fraction":
        offered = np.asarray(results.total_generated, np.float64)
        if results.total_retries is not None:
            offered = offered + np.asarray(results.total_retries, np.float64)
        return completed, np.maximum(offered, 1e-300)
    if metric == "availability_fraction":
        # completions over (completions + arrivals lost to dark fault
        # windows): the chaos-campaign headline "does hedging buy
        # availability" answers as a CRN-paired interval on this ratio
        if getattr(results, "dark_lost", None) is None:
            msg = (
                "availability_fraction needs a sweep that carried the "
                "fault/hazard machinery (results.dark_lost is None): add a "
                "hazard_model or fault_timeline to the payload"
            )
            raise ValueError(msg)
        dark = np.asarray(results.dark_lost, np.float64)
        return completed, np.maximum(completed + dark, 1e-300)
    if metric == "tokens_per_s":
        # generated tokens over simulated seconds: the serving throughput
        # headline (docs/guides/serving.md); the denominator is the fixed
        # horizon per scenario so the ratio-of-sums pools correctly
        if getattr(results, "decode_tokens", None) is None:
            msg = (
                "tokens_per_s needs a sweep whose plan carries llm_serve "
                "steps (results.decode_tokens is None): add an llm_serve "
                "step and a serving policy to the payload"
            )
            raise ValueError(msg)
        decode = np.asarray(results.decode_tokens, np.float64)
        horizon = max(float(results.settings.total_simulation_time), 1e-300)
        return decode, np.full_like(decode, horizon)
    if metric.startswith("blame_share:"):
        # attributed seconds in one phase over total attributed seconds
        # (docs/guides/observability.md "Where does the tail come from"):
        # the ratio-of-sums pools across scenarios so a PrecisionTarget or
        # compare() arm can gate on where latency is spent, not just how
        # much of it there is
        from asyncflow_tpu.observability.blame import N_PHASES, PHASE_NAMES

        phase = metric.split(":", 1)[1]
        if phase not in PHASE_NAMES:
            msg = (
                f"unknown blame phase {phase!r}; supported: "
                f"{', '.join(PHASE_NAMES)}"
            )
            raise ValueError(msg)
        if getattr(results, "blame_rows", None) is None:
            msg = (
                f"{metric!r} needs an attributed sweep (results.blame_rows "
                "is None): construct SweepRunner(..., blame=True)"
            )
            raise ValueError(msg)
        rows = np.asarray(results.blame_rows, np.float64)
        grid = rows.reshape(rows.shape[0], -1, N_PHASES, rows.shape[-1])
        num = grid[:, :, PHASE_NAMES.index(phase), :].sum(axis=(1, 2))
        den = rows.sum(axis=(1, 2))
        return num, np.maximum(den, 1e-300)
    msg = f"unknown ratio metric {metric!r}"
    raise ValueError(msg)


def effective_results(results) -> tuple[object, int]:
    """(results without quarantined rows, number excluded).

    Host-fault quarantine (docs/guides/fault-tolerance.md) zeroes masked
    rows, which is harmless to pooled-histogram reductions but poisons
    anything that treats rows as i.i.d. replications (bootstrap resampling
    would sample the zeros).  Every estimator dispatch drops them first
    and notes the exclusion on the returned interval.
    """
    mask = getattr(results, "quarantined", None)
    if mask is None:
        return results, 0
    mask = np.asarray(mask, bool)
    n_excluded = int(np.count_nonzero(mask))
    if n_excluded == 0:
        return results, 0
    return results[~mask], n_excluded


def interval_for_metric(
    results,
    metric: str,
    level: float = 0.95,
    *,
    n_boot: int = 1000,
    seed: int = 0,
) -> IntervalEstimate:
    """Interval estimate of one summary metric from a ``SweepResults``.

    Quantile metrics use the pooled order-statistic CI; ratio-of-sums
    metrics (mean latency, goodput) bootstrap over scenarios.  Metric names
    match ``SweepReport.summary()`` keys and
    :data:`asyncflow_tpu.schemas.experiment.SUPPORTED_METRICS`.
    Quarantined scenarios are dropped before estimation; the interval
    reports them as ``n_excluded``.
    """
    import dataclasses

    results, n_excluded = effective_results(results)
    if metric in _QUANTILE_METRICS:
        est = pooled_quantile_ci(
            results.latency_hist, results.hist_edges,
            _QUANTILE_METRICS[metric], level,
        )
    else:
        num, den = _ratio_components(results, metric)
        est = bootstrap_ratio_ci(num, den, level, n_boot=n_boot, seed=seed)
    if n_excluded:
        est = dataclasses.replace(est, n_excluded=n_excluded)
    return est


def paired_delta_for_metric(
    results_a,
    results_b,
    metric: str,
    level: float = 0.95,
    *,
    n_boot: int = 1000,
    seed: int = 0,
) -> IntervalEstimate:
    """Paired-delta interval (arm B minus arm A) of one summary metric.

    Quarantined scenarios break the pairing on the affected rows, so the
    UNION of both arms' quarantine masks is dropped from both (keeping
    surviving pairs aligned) and reported as ``n_excluded``.
    """
    import dataclasses

    mask_a = getattr(results_a, "quarantined", None)
    mask_b = getattr(results_b, "quarantined", None)
    n_excluded = 0
    if mask_a is not None or mask_b is not None:
        n = np.asarray(results_a.completed).shape[0]
        union = np.zeros(n, bool)
        for mask in (mask_a, mask_b):
            if mask is not None:
                union |= np.asarray(mask, bool)
        n_excluded = int(np.count_nonzero(union))
        if n_excluded:
            results_a = results_a[~union]
            results_b = results_b[~union]

    def _note(est: IntervalEstimate) -> IntervalEstimate:
        return (
            dataclasses.replace(est, n_excluded=n_excluded)
            if n_excluded
            else est
        )

    if metric in _QUANTILE_METRICS:
        return _note(paired_delta_quantile_ci(
            results_a.latency_hist,
            results_b.latency_hist,
            results_a.hist_edges,
            _QUANTILE_METRICS[metric],
            level,
            n_boot=n_boot,
            seed=seed,
        ))
    num_a, den_a = _ratio_components(results_a, metric)
    num_b, den_b = _ratio_components(results_b, metric)
    return _note(paired_delta_ratio_ci(
        num_a, den_a, num_b, den_b, level, n_boot=n_boot, seed=seed,
    ))
