"""Variance-reduction analysis helpers: antithetic pairing + CRN coupling.

The engine-level hooks live where the draws happen
(:mod:`asyncflow_tpu.engines.jaxsim.sampling` for the JAX engines,
:func:`asyncflow_tpu.samplers.variates.sample_rv` for the host-side oracle)
and are gated by :class:`asyncflow_tpu.schemas.experiment.VarianceReduction`
through ``SweepRunner(..., experiment=...)``.  This module holds the
host-side estimator seam those hooks feed:

- an antithetic sweep lays out pair member A at scenario row ``i`` and its
  reflected partner at row ``n/2 + i``; :func:`antithetic_pair_means`
  collapses any per-scenario metric to the n/2 i.i.d. pair means whose
  sample variance is the correct CI input (treating the 2n halves as
  independent would understate the variance of a *positively* correlated
  pairing and overstate it for the intended negative one);
- :func:`coupling_diagnostics` quantifies how much coupling (antithetic or
  CRN) actually bought on a metric — the number to check before trusting a
  tight paired interval.
"""

from __future__ import annotations

import numpy as np

from asyncflow_tpu.analysis.estimators import IntervalEstimate


def antithetic_halves(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(primary, reflected) halves of an antithetic sweep's metric array.

    Row layout contract (``SweepRunner`` with ``antithetic=True``): pair
    ``i`` is rows ``(i, n/2 + i)``; both halves share scenario keys, the
    second ran the reflected-draw program.
    """
    values = np.asarray(values)
    n = values.shape[0]
    if n % 2:
        msg = f"antithetic sweeps have an even scenario count, got {n}"
        raise ValueError(msg)
    return values[: n // 2], values[n // 2 :]


def antithetic_pair_means(values: np.ndarray) -> np.ndarray:
    """(n/2,) i.i.d. pair means of a per-scenario metric array."""
    a, b = antithetic_halves(values)
    return (np.asarray(a, np.float64) + np.asarray(b, np.float64)) / 2.0


def antithetic_mean_ci(
    values: np.ndarray,
    level: float = 0.95,
) -> IntervalEstimate:
    """Normal-approximation CI on the mean of an antithetic sweep's metric,
    computed over pair means (the correct i.i.d. unit)."""
    # lazy: parallel.sweep imports analysis.estimators for its summary CIs
    from asyncflow_tpu.parallel.sweep import _mean_ci

    means = antithetic_pair_means(values)
    means = means[np.isfinite(means)]
    point, lo, hi = _mean_ci(means, level)
    return IntervalEstimate(
        point, lo, hi, level, means.size, "antithetic-pair-mean",
    )


def coupling_diagnostics(a: np.ndarray, b: np.ndarray) -> dict:
    """How strongly coupled are two metric arrays, and what did it buy?

    Returns ``correlation`` (Pearson, over finite pairs), and
    ``variance_ratio_vs_independent``: Var(b - a) relative to what
    independent arms with the same marginals would give (… = 1 - rho for
    equal variances; < 1 means the coupling tightened the paired delta,
    > 1 — e.g. a successful antithetic pairing — means it widened the
    *difference* while tightening the *sum*).
    """
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    if a.shape != b.shape:
        msg = f"coupled arms need matching shapes, got {a.shape} vs {b.shape}"
        raise ValueError(msg)
    ok = np.isfinite(a) & np.isfinite(b)
    a, b = a[ok], b[ok]
    if a.size < 2 or a.std() == 0 or b.std() == 0:
        return {
            "n": int(a.size),
            "correlation": float("nan"),
            "variance_ratio_vs_independent": float("nan"),
        }
    rho = float(np.corrcoef(a, b)[0, 1])
    var_indep = float(a.var(ddof=1) + b.var(ddof=1))
    var_paired = float(np.var(b - a, ddof=1))
    return {
        "n": int(a.size),
        "correlation": rho,
        "variance_ratio_vs_independent": (
            var_paired / var_indep if var_indep > 0 else float("nan")
        ),
    }
