"""Python front door: the fluent scenario builder."""

from asyncflow_tpu.builder.flow import AsyncFlow

__all__ = ["AsyncFlow"]
