"""Fluent Python builder producing a validated :class:`SimulationPayload`.

One of the two front doors of the framework (the other is YAML through
``SimulationRunner.from_yaml``), mirroring the reference builder surface
(``/root/reference/src/asyncflow/builder/asyncflow_builder.py:22-177``).
"""

from __future__ import annotations

try:
    from typing import Self
except ImportError:  # Python < 3.11
    from typing_extensions import Self

from asyncflow_tpu.config.constants import EventDescription
from asyncflow_tpu.schemas.edges import Edge
from asyncflow_tpu.schemas.events import End, EventInjection, Start
from asyncflow_tpu.schemas.graph import TopologyGraph
from asyncflow_tpu.schemas.nodes import Client, LoadBalancer, Server, TopologyNodes
from asyncflow_tpu.schemas.payload import SimulationPayload
from asyncflow_tpu.schemas.settings import SimulationSettings
from asyncflow_tpu.schemas.workload import RqsGenerator


def _require(value: object, cls: type, label: str) -> None:
    if not isinstance(value, cls):
        msg = f"You must add a {cls.__name__} instance as {label}"
        raise TypeError(msg)


class AsyncFlow:
    """Accumulates scenario pieces and validates them into one payload."""

    def __init__(self) -> None:
        self._client: Client | None = None
        self._servers: list[Server] = []
        self._edges: list[Edge] = []
        self._sim_settings: SimulationSettings | None = None
        self._load_balancer: LoadBalancer | None = None
        self._events: list[EventInjection] = []
        self._generators: list[RqsGenerator] = []

    # -- nodes & wiring -----------------------------------------------------

    def add_generator(self, rqs_generator: RqsGenerator) -> Self:
        """Add a stochastic request generator.

        Called once for the reference's single-generator shape; repeated
        calls ACCUMULATE generators (multi-generator workload
        superposition — each needs its own entry edge).  The payload
        keeps the reference's on-disk format for the single case.
        """
        _require(rqs_generator, RqsGenerator, "the generator")
        self._generators.append(rqs_generator)
        return self

    def add_client(self, client: Client) -> Self:
        """Set the client node."""
        _require(client, Client, "the client")
        self._client = client
        return self

    def add_servers(self, *servers: Server) -> Self:
        """Append one or more servers."""
        for server in servers:
            _require(server, Server, "a server")
            self._servers.append(server)
        return self

    def add_edges(self, *edges: Edge) -> Self:
        """Append one or more directed edges."""
        for edge in edges:
            _require(edge, Edge, "an edge")
            self._edges.append(edge)
        return self

    def add_load_balancer(self, load_balancer: LoadBalancer) -> Self:
        """Set the (single) load balancer."""
        _require(load_balancer, LoadBalancer, "the load balancer")
        self._load_balancer = load_balancer
        return self

    def add_simulation_settings(self, sim_settings: SimulationSettings) -> Self:
        """Set the global settings."""
        _require(sim_settings, SimulationSettings, "the settings")
        self._sim_settings = sim_settings
        return self

    # -- events -------------------------------------------------------------

    def add_network_spike(
        self,
        *,
        event_id: str,
        edge_id: str,
        t_start: float,
        t_end: float,
        spike_s: float,
    ) -> Self:
        """Add a latency spike of ``spike_s`` seconds on ``edge_id`` over a window."""
        self._events.append(
            EventInjection(
                event_id=event_id,
                target_id=edge_id,
                start=Start(
                    kind=EventDescription.NETWORK_SPIKE_START,
                    t_start=t_start,
                    spike_s=spike_s,
                ),
                end=End(kind=EventDescription.NETWORK_SPIKE_END, t_end=t_end),
            ),
        )
        return self

    def add_server_outage(
        self,
        *,
        event_id: str,
        server_id: str,
        t_start: float,
        t_end: float,
    ) -> Self:
        """Add a SERVER_DOWN -> SERVER_UP window for ``server_id``."""
        self._events.append(
            EventInjection(
                event_id=event_id,
                target_id=server_id,
                start=Start(kind=EventDescription.SERVER_DOWN, t_start=t_start),
                end=End(kind=EventDescription.SERVER_UP, t_end=t_end),
            ),
        )
        return self

    # -- build --------------------------------------------------------------

    def build_payload(self) -> SimulationPayload:
        """Validate the accumulated pieces into one :class:`SimulationPayload`."""
        if not self._generators:
            msg = "The generator input must be instantiated before the simulation"
            raise ValueError(msg)
        if self._client is None:
            msg = "The client input must be instantiated before the simulation"
            raise ValueError(msg)
        if not self._servers:
            msg = "You must instantiate at least one server before the simulation"
            raise ValueError(msg)
        if not self._edges:
            msg = "You must instantiate edges before the simulation"
            raise ValueError(msg)
        if self._sim_settings is None:
            msg = "The simulation settings must be instantiated before the simulation"
            raise ValueError(msg)

        graph = TopologyGraph(
            nodes=TopologyNodes(
                servers=self._servers,
                client=self._client,
                load_balancer=self._load_balancer,
            ),
            edges=self._edges,
        )
        rqs_input = (
            self._generators[0]
            if len(self._generators) == 1
            else self._generators
        )
        return SimulationPayload.model_validate(
            {
                "rqs_input": rqs_input,
                "topology_graph": graph,
                "sim_settings": self._sim_settings,
                "events": self._events or None,
            },
        )
