"""Static analysis for asyncflow-tpu scenarios and for the repo itself.

Layer 1 — scenario/plan diagnostics (docs/guides/diagnostics.md):
:func:`check_payload` runs a pass pipeline over a validated
:class:`~asyncflow_tpu.schemas.payload.SimulationPayload` (and its lowered
plan) and returns a :class:`CheckReport` of stable ``AF###`` diagnostics;
``python -m asyncflow_tpu.checker scenario.yml`` is the CLI (exit 0 clean /
1 warnings / 2 errors); :func:`run_preflight` is the default-on hook in
``SimulationRunner``/``SweepRunner``.

The fence registry (:data:`FENCES`, :func:`predict_routing`) is the single
source of truth for "engine X refuses feature Y": runtime refusal sites
raise through it, the checker predicts routing from it.

Layer 2 — repo-invariant AST lint (:mod:`asyncflow_tpu.checker.internal`,
``scripts/lint_invariants.py``) enforcing the codebase's own JAX
invariants in CI.
"""

from asyncflow_tpu.checker.diagnostics import CheckReport, Diagnostic, Severity
from asyncflow_tpu.checker.fences import (
    ENGINE_OPTION_SUPPORT,
    FENCES,
    Fence,
    RoutingPrediction,
    TrippedFence,
    fence_message,
    predict_routing,
    raise_fence,
    tripped_fences,
)
from asyncflow_tpu.checker.preflight import (
    PREFLIGHT_MODES,
    PreflightError,
    PreflightWarning,
    run_preflight,
)

__all__ = [
    "ENGINE_OPTION_SUPPORT",
    "FENCES",
    "PREFLIGHT_MODES",
    "CheckReport",
    "Diagnostic",
    "Fence",
    "PreflightError",
    "PreflightWarning",
    "RoutingPrediction",
    "Severity",
    "TrippedFence",
    "check_payload",
    "fence_message",
    "predict_routing",
    "raise_fence",
    "run_preflight",
    "tripped_fences",
]


def __getattr__(name: str):
    # check_payload pulls in the compiler (and with it jax); load lazily so
    # `from asyncflow_tpu.checker import raise_fence` stays feather-weight
    # on the engine import paths.
    if name == "check_payload":
        from asyncflow_tpu.checker.passes import check_payload

        return check_payload
    msg = f"module {__name__!r} has no attribute {name!r}"
    raise AttributeError(msg)
