"""CLI front door: ``python -m asyncflow_tpu.checker scenario.yml``.

Validates the scenario, runs every diagnostic pass, prints the report, and
exits 0 (clean — info findings allowed), 1 (warnings), or 2 (errors or an
invalid scenario).  ``--json`` emits machine-readable findings for CI.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m asyncflow_tpu.checker",
        description="Static scenario analyzer: stability, graph shape, "
        "time-domain contradictions, resource sanity, and engine-routing "
        "prediction (docs/guides/diagnostics.md).",
    )
    parser.add_argument("scenario", help="scenario YAML file to analyze")
    parser.add_argument(
        "--engine",
        default="auto",
        choices=("auto", "fast", "event", "pallas", "native"),
        help="engine the run would request (default: auto)",
    )
    parser.add_argument(
        "--backend",
        default=None,
        help="assume this jax backend for routing (default: probe)",
    )
    parser.add_argument(
        "--trace", action="store_true",
        help="predict routing with the flight recorder attached",
    )
    parser.add_argument(
        "--crn", action="store_true",
        help="predict routing with CRN coupling enabled",
    )
    parser.add_argument(
        "--antithetic", action="store_true",
        help="predict routing with antithetic coupling enabled",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit findings as JSON",
    )
    args = parser.parse_args(argv)

    import yaml

    from asyncflow_tpu.checker.passes import check_payload
    from asyncflow_tpu.schemas.payload import SimulationPayload

    try:
        with open(args.scenario) as fh:
            data = yaml.safe_load(fh.read())
        payload = SimulationPayload.model_validate(data)
    except Exception as err:  # noqa: BLE001 - CLI boundary
        print(f"invalid scenario {args.scenario!r}: {err}", file=sys.stderr)
        return 2

    report = check_payload(
        payload,
        engine=args.engine,
        backend=args.backend,
        trace=args.trace,
        crn=args.crn,
        antithetic=args.antithetic,
    )
    if args.json:
        print(json.dumps(
            {
                "scenario": args.scenario,
                "exit_code": report.exit_code,
                "summary": report.summary(),
                "findings": [
                    {
                        "code": d.code,
                        "severity": d.severity.value,
                        "message": d.message,
                        "path": d.path,
                        "remedy": d.remedy,
                    }
                    for d in report.diagnostics
                ],
            },
            indent=2,
        ))
    else:
        print(f"== {args.scenario}")
        print(report.render())
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
