"""Diagnostic records for the static scenario/plan analyzer.

Every finding the checker can emit is a :class:`Diagnostic` with a stable
``AF###`` code, a severity, a payload-path location, and a remedy.  Codes
are a public contract (docs/guides/diagnostics.md catalogs them): scripts
may grep for them, tests assert on them, and renumbering one is a breaking
change.

Code blocks:

- ``AF1xx`` — queueing stability (offered load rho per station)
- ``AF2xx`` — topology graph shape (unreachable nodes, dangling edges)
- ``AF3xx`` — time-domain contradictions (timeouts, fault windows, backoff)
- ``AF4xx`` — resource sanity (RAM, capacity rescale, breakpoint tables)
- ``AF5xx`` — engine routing and feature fences
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum


class Severity(str, Enum):
    """Diagnostic severity; orders ``INFO < WARNING < ERROR``."""

    INFO = "info"
    WARNING = "warning"
    ERROR = "error"

    @property
    def rank(self) -> int:
        return {"info": 0, "warning": 1, "error": 2}[self.value]


@dataclass(frozen=True)
class Diagnostic:
    """One structured finding from the static analyzer."""

    code: str  #: stable ``AF###`` identifier
    severity: Severity
    message: str  #: what is wrong, with the numbers that prove it
    path: str  #: payload path, e.g. ``topology_graph.nodes.servers[0]``
    remedy: str  #: the concrete change that clears the finding

    def render(self) -> str:
        return (
            f"{self.code} {self.severity.value}: {self.message}"
            f"\n    at: {self.path}"
            f"\n    remedy: {self.remedy}"
        )


@dataclass
class CheckReport:
    """The full output of one :func:`~asyncflow_tpu.checker.check_payload`.

    ``exit_code`` is the CLI contract: 0 clean (info-only counts as
    clean), 1 when the worst finding is a warning, 2 on any error.
    """

    diagnostics: list[Diagnostic] = field(default_factory=list)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self) -> int:
        return len(self.diagnostics)

    @property
    def errors(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.ERROR]

    @property
    def warnings(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.WARNING]

    @property
    def infos(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.INFO]

    @property
    def clean(self) -> bool:
        """No warnings and no errors (informational findings are fine)."""
        return not self.errors and not self.warnings

    @property
    def exit_code(self) -> int:
        if self.errors:
            return 2
        if self.warnings:
            return 1
        return 0

    def codes(self) -> list[str]:
        return [d.code for d in self.diagnostics]

    def summary(self) -> str:
        """One line: counts plus the codes found, worst first."""
        ordered = sorted(
            self.diagnostics, key=lambda d: -d.severity.rank,
        )
        codes = ", ".join(dict.fromkeys(d.code for d in ordered))
        return (
            f"{len(self.errors)} error(s), {len(self.warnings)} warning(s), "
            f"{len(self.infos)} info"
            + (f" [{codes}]" if codes else "")
        )

    def render(self) -> str:
        if not self.diagnostics:
            return "preflight clean: no findings"
        lines = [
            d.render()
            for d in sorted(
                self.diagnostics,
                key=lambda d: (-d.severity.rank, d.code),
            )
        ]
        lines.append(self.summary())
        return "\n".join(lines)
