"""The shared feature-fence registry and static engine-routing predictor.

One source of truth for every "engine X refuses feature Y" decision in the
codebase.  The runtime refusal sites (``parallel/sweep.py``,
``runtime/runner.py``, ``engines/jaxsim/fastpath.py``,
``engines/jaxsim/pallas_engine.py``, ``engines/oracle/native``) raise
through :func:`raise_fence`, and the static checker predicts routing with
:func:`predict_routing` from the SAME table — the runtime message and the
preflight prediction can never drift apart.

This module is deliberately light: no jax, no pydantic, no compiler
imports at module scope, so ``from asyncflow_tpu.checker.fences import
raise_fence`` costs nothing on the engine hot paths.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "FENCES",
    "Fence",
    "RoutingPrediction",
    "TrippedFence",
    "fence_message",
    "predict_routing",
    "raise_fence",
    "tripped_fences",
]


@dataclass(frozen=True)
class Fence:
    """One (feature, engine) refusal: why the engine declines the feature."""

    id: str  #: stable ``feature.engine`` identifier
    feature: str  #: human name of the feature tripping the fence
    engine: str  #: the engine that refuses ("fast" | "pallas" | "native")
    message: str  #: the full runtime refusal text (``{detail}`` slot ok)
    exc: type[Exception] = ValueError  #: what the runtime site raises


_TRACE_REMEDY = (
    "use engine='event' or 'fast' (or 'auto', which routes traced "
    "fastpath-eligible plans to the scan fast path)"
)

FENCES: dict[str, Fence] = {
    f.id: f
    for f in (
        # -- flight recorder (trace=TraceConfig) ---------------------------
        # (trace.fast was burned: the scan fast path now derives the same
        # FlightRecord rings analytically from per-lane journey state)
        Fence(
            id="trace.pallas",
            feature="flight recorder (trace=TraceConfig)",
            engine="pallas",
            message=(
                "engine='pallas' cannot run the flight recorder "
                "(trace=TraceConfig): the Pallas kernel keeps its state in "
                "VMEM, which per-request event rings do not fit; "
                + _TRACE_REMEDY
            ),
        ),
        Fence(
            id="trace.native",
            feature="flight recorder (trace=TraceConfig)",
            engine="native",
            message=(
                "engine='native' cannot run the flight recorder "
                "(trace=TraceConfig): the recorder is not wired through "
                "the native core's C ABI; " + _TRACE_REMEDY
            ),
        ),
        # -- variance-reduction coupling (CRN / antithetic) ----------------
        Fence(
            id="vr.pallas",
            feature="variance-reduction coupling (CRN / antithetic)",
            engine="pallas",
            message=(
                "engine='pallas' does not support variance-reduction "
                "coupling (CRN / antithetic draws route through the jaxsim "
                "sampling hooks); use engine='fast' or 'event'"
            ),
        ),
        Fence(
            id="vr.native",
            feature="variance-reduction coupling (CRN / antithetic)",
            engine="native",
            message=(
                "engine='native' does not support variance-reduction "
                "coupling (CRN / antithetic draws route through the jaxsim "
                "sampling hooks); use engine='fast' or 'event'"
            ),
        ),
        # -- resilience plans (fault windows / client retries) -------------
        Fence(
            id="resilience.pallas",
            feature="resilience plan (fault windows / client retries)",
            engine="pallas",
            message=(
                "engine='pallas' does not model fault windows / client "
                "retries; use engine='fast' or 'event' (or 'auto', which "
                "routes fastpath-eligible resilience plans to the scan "
                "fast path)"
            ),
        ),
        Fence(
            id="resilience.native",
            feature="resilience plan (fault windows / client retries)",
            engine="native",
            message=(
                "engine='native' does not model fault windows / client "
                "retries; use engine='fast' or 'event' (or 'auto', which "
                "routes fastpath-eligible resilience plans to the scan "
                "fast path)"
            ),
        ),
        # -- chaos campaigns (hazard_model sampled fault tables) ------------
        Fence(
            id="hazard.pallas",
            feature="chaos campaign (hazard_model)",
            engine="pallas",
            message=(
                "engine='pallas' does not model chaos campaigns "
                "(hazard_model): the sampled per-scenario fault tables "
                "ride the scenario-override seam the VMEM kernel does not "
                "carry; use engine='fast' or 'event' (or 'auto', which "
                "routes fastpath-eligible hazard plans to the scan fast "
                "path)"
            ),
        ),
        Fence(
            id="hazard.native",
            feature="chaos campaign (hazard_model)",
            engine="native",
            message=(
                "engine='native' does not model chaos campaigns "
                "(hazard_model): the sampled per-scenario fault tables "
                "ride the scenario-override seam the C++ core does not "
                "carry; use engine='fast' or 'event' (or 'auto', which "
                "routes fastpath-eligible hazard plans to the scan fast "
                "path)"
            ),
        ),
        # -- tail-tolerance plans (hedges / health gate / brownout) ---------
        Fence(
            id="tail_tolerance.pallas",
            feature="tail-tolerance plan (hedges / health gate / brownout)",
            engine="pallas",
            message=(
                "engine='pallas' does not model tail-tolerance policies "
                "(hedged requests / LB health gating / server brownout); "
                "use engine='event' (or 'auto', which routes tail-tolerance "
                "plans to the event engine)"
            ),
        ),
        Fence(
            id="tail_tolerance.native",
            feature="tail-tolerance plan (hedges / health gate / brownout)",
            engine="native",
            message=(
                "engine='native' does not model tail-tolerance policies "
                "(hedged requests / LB health gating / server brownout); "
                "use engine='event' (or 'auto', which routes tail-tolerance "
                "plans to the event engine)"
            ),
        ),
        # -- LLM serving plans (llm_serve batch/KV dynamics) ----------------
        # event-only initially: the continuous-batching admission gate and
        # KV eviction lifecycle run on the oracle and the XLA event engine;
        # AF501 prices the routing gap for the other engines.
        Fence(
            id="llm.fastpath",
            feature="LLM serving (llm_serve batch/KV dynamics)",
            engine="fast",
            message=(
                "the closed-form fast path cannot model LLM serving "
                "(continuous-batching admission and KV eviction are "
                "event-driven); use engine='event' (or 'auto', which "
                "routes serving plans to the event engine)"
            ),
        ),
        Fence(
            id="llm.pallas",
            feature="LLM serving (llm_serve batch/KV dynamics)",
            engine="pallas",
            message=(
                "engine='pallas' does not model LLM serving (the "
                "continuous-batching gate and KV eviction lifecycle ride "
                "per-server FIFO state the VMEM kernel does not carry); "
                "use engine='event' (or 'auto', which routes serving "
                "plans to the event engine)"
            ),
        ),
        Fence(
            id="llm.native",
            feature="LLM serving (llm_serve batch/KV dynamics)",
            engine="native",
            message=(
                "engine='native' does not model LLM serving (the "
                "continuous-batching gate and KV eviction lifecycle are "
                "not wired through the native core's C ABI); use "
                "engine='event' (or 'auto', which routes serving plans "
                "to the event engine)"
            ),
        ),
        # -- latency attribution plane (blame=True) --------------------------
        Fence(
            id="blame.pallas",
            feature="latency attribution (blame=True)",
            engine="pallas",
            message=(
                "engine='pallas' does not record latency attribution "
                "(blame=True): the per-(component, phase) blame grids "
                "ride the jaxsim scatter path the VMEM kernel does not "
                "carry; use engine='fast' or 'event' (or 'auto', which "
                "routes attributed sweeps off the pallas kernel)"
            ),
        ),
        Fence(
            id="blame.native",
            feature="latency attribution (blame=True)",
            engine="native",
            message=(
                "engine='native' does not record latency attribution "
                "(blame=True): the blame grids are not wired through the "
                "native core's C ABI; use engine='fast' or 'event'"
            ),
        ),
        # -- fast-path eligibility -----------------------------------------
        Fence(
            id="fastpath.ineligible",
            feature="closed-form fast path",
            engine="fast",
            message="plan not eligible for the fast path: {detail}",
        ),
        Fence(
            id="fastpath.poisson_edge",
            feature="poisson edge latency",
            engine="fast",
            message="poisson edge latency is not supported on the fast path",
            exc=NotImplementedError,
        ),
        # -- auxiliary runtime fences ---------------------------------------
        Fence(
            id="native.unavailable",
            feature="native C++ core",
            engine="native",
            message=(
                "native sweep engine requested but the C++ core is "
                "unavailable"
            ),
            exc=RuntimeError,
        ),
        # -- streaming gauge series (gauge_series=...) ----------------------
        # (gauge_series.requires_fast was burned: the XLA event engine now
        # records the same interval-endpoint coarse grid inside its scan
        # body, so only the pallas/native engines still refuse)
        Fence(
            id="gauge_series.pallas",
            feature="streaming gauge series",
            engine="pallas",
            message=(
                "engine='pallas' does not record streaming gauge series "
                "(the kernel keeps no per-tick gauge grid in VMEM); use "
                "engine='fast' or 'event' (or 'auto', which routes "
                "gauge-series sweeps off the pallas kernel)"
            ),
        ),
        Fence(
            id="gauge_series.native",
            feature="streaming gauge series",
            engine="native",
            message=(
                "engine='native' does not record streaming gauge series "
                "(the coarse gauge grid is not wired through the native "
                "core's C ABI); use engine='fast' or 'event'"
            ),
        ),
    )
}

#: which engine_options each SimulationRunner backend understands;
#: runner.py names these in its unsupported-option error so the message
#: carries a routing hint instead of a bare option list.
ENGINE_OPTION_SUPPORT: dict[str, tuple[str, ...]] = {
    "collect_gauges": ("jax", "native"),
    "collect_traces": ("oracle", "jax", "native"),
    "collect_clocks": ("jax",),
    "trace": ("oracle", "jax", "native"),
    "engine": ("jax",),
    "n_hist_bins": ("jax",),
    "max_requests": ("jax",),
    "relax_sweeps": ("jax",),
    "relax_damping": ("jax",),
}


def fence_message(fence_id: str, **fmt: object) -> str:
    """The canonical refusal text for ``fence_id`` (KeyError on unknown)."""
    return FENCES[fence_id].message.format(**fmt)


def raise_fence(fence_id: str, **fmt: object):
    """Raise the registered exception with the canonical refusal text.

    Every runtime refusal site calls this instead of hand-writing its
    message, so static predictions quote exactly what the runtime raises.
    """
    fence = FENCES[fence_id]
    raise fence.exc(fence.message.format(**fmt))


# ---------------------------------------------------------------------------
# static routing prediction (mirror of SweepRunner.__init__'s dispatch)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TrippedFence:
    """One fence this configuration trips, with the canonical reason."""

    fence_id: str
    feature: str
    engine: str  #: the engine this config can NOT use because of the fence
    message: str


@dataclass(frozen=True)
class RoutingPrediction:
    """What ``SweepRunner(engine=...)`` will do with this plan, statically."""

    requested: str  #: the engine argument ("auto" or a forced engine)
    engine: str | None  #: the engine kind that will actually run (None if refused)
    backend: str  #: jax default backend the prediction assumed
    why: str  #: one sentence explaining the routing decision
    fences: tuple[TrippedFence, ...]  #: every fence the config trips
    refusal: TrippedFence | None = None  #: set when the forced engine raises

    @property
    def ok(self) -> bool:
        return self.refusal is None


def _trip(fence_id: str, **fmt: object) -> TrippedFence:
    fence = FENCES[fence_id]
    return TrippedFence(
        fence_id=fence.id,
        feature=fence.feature,
        engine=fence.engine,
        message=fence.message.format(**fmt),
    )


def tripped_fences(
    plan,
    *,
    trace: bool = False,
    crn: bool = False,
    antithetic: bool = False,
    gauge_series: bool = False,
    blame: bool = False,
) -> tuple[TrippedFence, ...]:
    """Every fence this (plan, features) combination trips.

    ``plan`` is a :class:`~asyncflow_tpu.compiler.plan.StaticPlan`; only
    ``fastpath_ok`` / ``fastpath_reason`` / ``has_faults`` / ``has_retry``
    / ``has_tail_tolerance`` are read, so any duck-typed stand-in works in
    tests.
    """
    out: list[TrippedFence] = []
    if trace:
        out += [_trip("trace.pallas"), _trip("trace.native")]
    if crn or antithetic:
        out += [_trip("vr.pallas"), _trip("vr.native")]
    if gauge_series:
        out += [_trip("gauge_series.pallas"), _trip("gauge_series.native")]
    if blame:
        out += [_trip("blame.pallas"), _trip("blame.native")]
    if plan.has_faults or plan.has_retry:
        out += [_trip("resilience.pallas"), _trip("resilience.native")]
    if getattr(plan, "has_hazards", False):
        out += [_trip("hazard.pallas"), _trip("hazard.native")]
    if getattr(plan, "has_tail_tolerance", False):
        out += [
            _trip("tail_tolerance.pallas"),
            _trip("tail_tolerance.native"),
        ]
    if getattr(plan, "has_serving", False):
        # the llm.fastpath trip subsumes the generic ineligibility reason
        # (fastpath_reason cites the serving dynamics for these plans)
        out += [
            _trip("llm.fastpath"),
            _trip("llm.pallas"),
            _trip("llm.native"),
        ]
    elif not plan.fastpath_ok:
        out.append(_trip("fastpath.ineligible", detail=plan.fastpath_reason))
    return tuple(out)


def predict_routing(
    plan,
    *,
    engine: str = "auto",
    backend: str | None = None,
    trace: bool = False,
    crn: bool = False,
    antithetic: bool = False,
    gauge_series: bool = False,
    blame: bool = False,
    native_ok: bool | None = None,
) -> RoutingPrediction:
    """Predict the engine :class:`SweepRunner` dispatch will pick.

    This mirrors ``SweepRunner.__init__`` exactly (the fence-prediction
    parity test locks the two together): forced engines refuse tripped
    fences with the registry message; ``engine='auto'`` routes fast if the
    plan is fastpath-eligible (traced or not — the flight recorder runs on
    the fast path), else pallas on TPU when the plan is neither resilient
    nor VR-coupled nor traced nor collecting gauge series, else the XLA
    event engine (which records gauge series in its scan body).

    ``backend`` defaults to ``jax.default_backend()`` (the only jax touch,
    resolved lazily); ``native_ok`` defaults to probing the C++ core only
    when the answer matters.
    """
    if engine not in ("auto", "fast", "event", "pallas", "native"):
        msg = (
            f"engine must be 'auto', 'fast', 'event', 'pallas' or "
            f"'native', got {engine!r}"
        )
        raise ValueError(msg)
    if backend is None:
        import jax

        backend = jax.default_backend()
    vr_coupled = crn or antithetic
    tail = getattr(plan, "has_tail_tolerance", False)
    hazards = getattr(plan, "has_hazards", False)
    serving = getattr(plan, "has_serving", False)
    resilient = plan.has_faults or plan.has_retry or tail or hazards
    fences = tripped_fences(
        plan,
        trace=trace,
        crn=crn,
        antithetic=antithetic,
        gauge_series=gauge_series,
        blame=blame,
    )

    def refused(fence_id: str, **fmt: object) -> RoutingPrediction:
        return RoutingPrediction(
            requested=engine,
            engine=None,
            backend=backend,
            why=f"engine={engine!r} is refused at construction time",
            fences=fences,
            refusal=_trip(fence_id, **fmt),
        )

    # forced engines: the constructor raises on a tripped fence
    if trace and engine in ("pallas", "native"):
        return refused(f"trace.{engine}")
    if vr_coupled and engine in ("pallas", "native"):
        return refused(f"vr.{engine}")
    if gauge_series and engine in ("pallas", "native"):
        return refused(f"gauge_series.{engine}")
    if blame and engine in ("pallas", "native"):
        return refused(f"blame.{engine}")
    if (plan.has_faults or plan.has_retry) and engine in ("pallas", "native"):
        return refused(f"resilience.{engine}")
    if hazards and engine in ("pallas", "native"):
        return refused(f"hazard.{engine}")
    if tail and engine in ("pallas", "native"):
        return refused(f"tail_tolerance.{engine}")
    if serving and engine in ("pallas", "native"):
        return refused(f"llm.{engine}")
    if engine == "fast" and serving:
        return refused("llm.fastpath")
    if engine == "fast" and not plan.fastpath_ok:
        return refused("fastpath.ineligible", detail=plan.fastpath_reason)
    if engine == "native":
        if native_ok is None:
            from asyncflow_tpu.engines.oracle.native import native_available

            native_ok = native_available()
        if not native_ok:
            return refused("native.unavailable")

    if engine == "auto":
        if plan.fastpath_ok:
            kind = "fast"
            why = (
                "plan is fastpath-eligible (the flight recorder rides "
                "the fast path)"
                if trace
                else "plan is fastpath-eligible"
            )
        elif (
            backend == "tpu"
            and not resilient
            and not vr_coupled
            and not trace
            and not gauge_series
            and not blame
            and not serving
        ):
            kind = "pallas"
            why = (
                "TPU backend, no resilience/VR/trace/gauge-series/blame "
                "fences tripped"
            )
        else:
            kind = "event"
            blockers = [f.feature for f in fences if f.engine == "fast"]
            why = (
                "routed to the XLA event engine"
                + (f" ({'; '.join(blockers)})" if blockers else
                   f" (backend={backend!r} has no pallas route)")
            )
    else:
        kind = engine
        why = f"engine={engine!r} was forced and trips no fence"

    return RoutingPrediction(
        requested=engine,
        engine=kind,
        backend=backend,
        why=why,
        fences=fences,
    )
