"""Repo-invariant AST lint (Layer 2 of the checker).

The test suite can only catch these probabilistically; the lint catches
them mechanically, per commit (CI job ``lint-invariants``, driver
``scripts/lint_invariants.py``):

- ``IN901`` — ``jax.random.split`` is forbidden on scenario-key paths.
  Scenario substreams must be derived with prefix-stable
  ``jax.random.fold_in`` chains: ``split`` renumbers every sibling stream
  when one is added, silently changing all results of a grown sweep.
  Statistical consumers that legitimately split a *bootstrap* key are
  allowlisted by file.
- ``IN902`` — no host-sync calls inside device loop bodies: a function
  passed to ``lax.scan`` / ``lax.while_loop`` / ``lax.fori_loop`` must not
  call ``.item()`` / ``float()`` / ``np.asarray`` / ``np.array`` /
  ``.block_until_ready()`` on traced values; each forces a device->host
  transfer per iteration and destroys the fused program.
- ``IN903`` — every ``EngineState`` field must be initialized (registered
  in the placeholder-pruning table) in ``engine.py``'s ``_init_state``:
  a field added to the NamedTuple but not to the constructor call is a
  guaranteed TypeError at trace time on some untested branch, or worse, a
  silently default-shaped carry.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path

__all__ = ["Violation", "lint_file", "lint_source", "lint_tree"]

#: files allowed to call jax.random.split: they key bootstrap resamples /
#: synthetic benchmarks, not scenario substreams.
SPLIT_ALLOWLIST = (
    "analysis/estimators.py",
    "utils/program_size.py",
)

_LOOP_PRIMITIVES = {"scan", "while_loop", "fori_loop"}
_HOST_SYNC_METHODS = {"item", "block_until_ready"}
_HOST_SYNC_NP_FUNCS = {"asarray", "array"}


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('' when not a name chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ---------------------------------------------------------------------------
# IN901: jax.random.split on scenario-key paths
# ---------------------------------------------------------------------------


def _split_aliases(tree: ast.AST) -> tuple[set[str], set[str]]:
    """(module aliases naming jax.random, function aliases naming split)."""
    random_mods = {"jax.random"}
    split_funcs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.random" and alias.asname:
                    random_mods.add(alias.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for alias in node.names:
                    if alias.name == "random":
                        random_mods.add(alias.asname or "random")
            elif node.module == "jax.random":
                for alias in node.names:
                    if alias.name == "split":
                        split_funcs.add(alias.asname or "split")
    return random_mods, split_funcs


def _check_split(tree: ast.AST, path: str, out: list[Violation]) -> None:
    random_mods, split_funcs = _split_aliases(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        hit = name in split_funcs or (
            name.endswith(".split")
            and name.rsplit(".", 1)[0] in random_mods
        )
        if hit:
            out.append(Violation(
                rule="IN901", path=path, line=node.lineno,
                message=f"jax.random.split ({name or 'split'}) on a "
                "scenario-key path: use prefix-stable jax.random.fold_in "
                "chains (split renumbers sibling streams when one is "
                "added)",
            ))


# ---------------------------------------------------------------------------
# IN902: host sync inside device loop bodies
# ---------------------------------------------------------------------------


def _loop_body_functions(tree: ast.AST) -> list[ast.AST]:
    """Functions (defs or lambdas) passed to lax.scan/while_loop/fori_loop."""
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    bodies: list[ast.AST] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = _dotted(node.func)
        if fn.rsplit(".", 1)[-1] not in _LOOP_PRIMITIVES:
            continue
        # the body argument's position varies by primitive: scan(body, ...),
        # while_loop(cond, body, init), fori_loop(lo, hi, body, init);
        # sweep the first three to cover all conventions
        for arg in node.args[:3]:
            if isinstance(arg, ast.Lambda):
                bodies.append(arg)
            elif isinstance(arg, ast.Name):
                bodies.extend(defs.get(arg.id, []))
    return bodies


def _fn_params(fn: ast.AST) -> set[str]:
    args = fn.args
    names = [a.arg for a in (*args.posonlyargs, *args.args, *args.kwonlyargs)]
    return set(names)


def _check_host_sync(tree: ast.AST, path: str, out: list[Violation]) -> None:
    for body in _loop_body_functions(tree):
        params = _fn_params(body)
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            leaf = name.rsplit(".", 1)[-1]
            arg0 = node.args[0] if node.args else None
            # unwrap attribute/subscript chains (s.t, s[1], s.q[0]) down to
            # the base name: any projection of a loop parameter is traced
            base = arg0
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                base = base.value
            touches_param = isinstance(base, ast.Name) and base.id in params
            if isinstance(node.func, ast.Attribute) and (
                node.func.attr in _HOST_SYNC_METHODS
            ):
                out.append(Violation(
                    rule="IN902", path=path, line=node.lineno,
                    message=f".{node.func.attr}() inside a device loop "
                    "body forces a device->host sync every iteration",
                ))
            elif leaf in _HOST_SYNC_NP_FUNCS and name.startswith(
                ("np.", "numpy."),
            ) and touches_param:
                out.append(Violation(
                    rule="IN902", path=path, line=node.lineno,
                    message=f"{name}() on a traced loop-carry inside a "
                    "device loop body materializes it on the host every "
                    "iteration",
                ))
            elif name == "float" and touches_param:
                out.append(Violation(
                    rule="IN902", path=path, line=node.lineno,
                    message="float() on a traced loop-carry inside a "
                    "device loop body is a per-iteration host sync",
                ))


# ---------------------------------------------------------------------------
# IN903: EngineState fields registered in the _init_state pruning table
# ---------------------------------------------------------------------------


def _namedtuple_fields(tree: ast.AST, cls_name: str) -> list[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            return [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
    return []


def _check_engine_state(
    params_tree: ast.AST,
    engine_tree: ast.AST,
    engine_path: str,
    out: list[Violation],
) -> None:
    fields = _namedtuple_fields(params_tree, "EngineState")
    if not fields:
        return
    init_kwargs: set[str] = set()
    line = 1
    for node in ast.walk(engine_tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name != "_init_state":
            continue
        for call in ast.walk(node):
            if (
                isinstance(call, ast.Call)
                and _dotted(call.func).rsplit(".", 1)[-1] == "EngineState"
            ):
                init_kwargs |= {
                    kw.arg for kw in call.keywords if kw.arg is not None
                }
                line = call.lineno
    if not init_kwargs:
        return
    for field in fields:
        if field not in init_kwargs:
            out.append(Violation(
                rule="IN903", path=engine_path, line=line,
                message=f"EngineState field {field!r} is not initialized "
                "in _init_state's placeholder-pruning table: every field "
                "needs an explicit (possibly (1,)-placeholder) entry or "
                "tracing breaks on the first branch that carries it",
            ))


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def lint_source(
    src: str,
    path: str = "<string>",
    *,
    allow_split: bool = False,
) -> list[Violation]:
    """Lint one source string (IN901 + IN902)."""
    out: list[Violation] = []
    tree = ast.parse(src, filename=path)
    if not allow_split:
        _check_split(tree, path, out)
    _check_host_sync(tree, path, out)
    return out


def lint_file(path: str | Path, *, root: str | Path | None = None) -> list[Violation]:
    path = Path(path)
    rel = str(path.relative_to(root) if root else path)
    allow = any(rel.endswith(a) for a in SPLIT_ALLOWLIST)
    return lint_source(path.read_text(), rel, allow_split=allow)


def lint_tree(pkg_dir: str | Path) -> list[Violation]:
    """Lint every ``.py`` under ``pkg_dir`` (IN901/IN902) plus the
    cross-file IN903 EngineState registration check."""
    pkg_dir = Path(pkg_dir)
    out: list[Violation] = []
    for path in sorted(pkg_dir.rglob("*.py")):
        out.extend(lint_file(path, root=pkg_dir.parent))
    params = pkg_dir / "engines" / "jaxsim" / "params.py"
    engine = pkg_dir / "engines" / "jaxsim" / "engine.py"
    if params.exists() and engine.exists():
        _check_engine_state(
            ast.parse(params.read_text()),
            ast.parse(engine.read_text()),
            str(engine.relative_to(pkg_dir.parent)),
            out,
        )
    return out
