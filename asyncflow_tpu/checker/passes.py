"""The scenario/plan diagnostic passes behind :func:`check_payload`.

Each pass reads the validated :class:`SimulationPayload` (and, where
noted, the lowered :class:`StaticPlan`) and appends
:class:`~asyncflow_tpu.checker.diagnostics.Diagnostic` records.  Passes
are pure and ordered; none raises on a bad scenario — the report does the
talking.

The load math deliberately reuses the compiler's own models
(``_server_entry_rates``, ``_server_db_hold``) so the checker and the
capacity estimator can never disagree about offered load.
"""

from __future__ import annotations

from asyncflow_tpu.checker.diagnostics import CheckReport, Diagnostic, Severity
from asyncflow_tpu.checker.fences import predict_routing
from asyncflow_tpu.config.constants import EndpointStepIO, EventDescription

# rho thresholds (offered load per station): the published contract of the
# AF1xx block — see docs/guides/diagnostics.md before changing any.
RHO_ERROR = 1.0  #: unstable: queue grows without bound, AF102
RHO_WARNING = 0.9  #: near saturation (retry-amplified counts), AF101
RHO_NOISE = 0.6  #: ensemble-noise regime: parity/CI seed lottery, AF103


# ---------------------------------------------------------------------------
# payload arithmetic helpers (schemas only, no compiler import)
# ---------------------------------------------------------------------------


def _step_io_mean(step) -> float:
    """Expected wall seconds of one I/O step (cache/LLM dynamics included)."""
    base = float(step.quantity)
    if step.cache_hit_probability is not None:
        p = float(step.cache_hit_probability)
        return p * base + (1.0 - p) * float(step.cache_miss_time)
    if step.llm_tokens_mean is not None:
        return base + float(step.llm_tokens_mean) * float(step.llm_time_per_token)
    return base


def _step_io_floor(step) -> float:
    """Minimum achievable wall seconds of one I/O step."""
    base = float(step.quantity)
    if step.cache_hit_probability is not None:
        return min(base, float(step.cache_miss_time))
    return base  # LLM floor: Poisson token draw can be 0


def _ep_cpu(ep) -> float:
    return sum(float(s.quantity) for s in ep.steps if s.is_cpu)


def _ep_io_mean(ep) -> float:
    return sum(_step_io_mean(s) for s in ep.steps if s.is_io)


def _ep_io_floor(ep) -> float:
    return sum(_step_io_floor(s) for s in ep.steps if s.is_io)


def _ep_ram(ep) -> float:
    return sum(float(s.quantity) for s in ep.steps if s.is_ram)


def _ep_db(ep) -> float:
    return sum(
        float(s.quantity)
        for s in ep.steps
        if s.is_io and s.kind == EndpointStepIO.DB
    )


def _weighted(server, per_ep) -> float:
    """selection_weight-weighted mean of ``per_ep(endpoint)`` over a server."""
    eps = server.endpoints
    total = sum(float(ep.selection_weight) for ep in eps)
    if total <= 0.0:
        return 0.0
    return sum(per_ep(ep) * float(ep.selection_weight) for ep in eps) / total


def _service_floor(server) -> float:
    """Minimum achievable service seconds over the server's endpoints."""
    return min(
        (_ep_cpu(ep) + _ep_io_floor(ep) for ep in server.endpoints),
        default=0.0,
    )


def _entry_walk(payload, start_id: str):
    """(edges, terminal) walking ``start_id``'s out-edge chain to the first
    server or LB — the request's one-way trip, mirroring the lowering."""
    servers = {s.id for s in payload.topology_graph.nodes.servers}
    lb = payload.topology_graph.nodes.load_balancer
    out_edge = {e.source: e for e in payload.topology_graph.edges}
    node, hops = start_id, []
    for _ in range(len(payload.topology_graph.edges) + 1):
        e = out_edge.get(node)
        if e is None:
            return hops, None
        hops.append(e)
        if e.target in servers or (lb is not None and e.target == lb.id):
            return hops, e.target
        node = e.target
    return hops, None


def _retry_amplification(payload) -> float:
    """Worst-case offered-load multiplier from the client retry ladder."""
    rp = payload.retry_policy
    return float(rp.max_attempts) if rp is not None else 1.0


def _hedge_amplification(payload) -> float:
    """Worst-case offered-load multiplier from hedged requests: every
    attempt can spawn up to ``max_hedges`` speculative duplicates."""
    hp = getattr(payload, "hedge_policy", None)
    return 1.0 + float(hp.max_hedges) if hp is not None else 1.0


def _outage_windows(payload) -> dict[str, list[tuple[float, float]]]:
    """Per-server outage windows from BOTH what-if sources: the fault
    timeline (``server_outage``) and scheduled event injections
    (``server_down`` .. ``server_up``)."""
    wins: dict[str, list[tuple[float, float]]] = {}
    tl = payload.fault_timeline
    if tl is not None:
        for ev in tl.events:
            if str(ev.kind) == "server_outage":
                wins.setdefault(ev.target_id, []).append(
                    (float(ev.t_start), float(ev.t_end)),
                )
    for ev in payload.events or []:
        if ev.start.kind == EventDescription.SERVER_DOWN:
            wins.setdefault(ev.target_id, []).append(
                (float(ev.start.t_start), float(ev.end.t_end)),
            )
    return wins


def _covered(windows: list[tuple[float, float]], horizon: float) -> float:
    """Fraction of ``[0, horizon)`` covered by the union of the windows."""
    if not windows or horizon <= 0.0:
        return 0.0
    total, hi = 0.0, 0.0
    for a, b in sorted(w for w in windows):
        a, b = max(a, hi), min(b, horizon)
        if b > a:
            total += b - a
            hi = b
        hi = max(hi, min(b, horizon))
    return total / horizon


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------


def stability_pass(payload, plan, out: list[Diagnostic]) -> None:
    """AF101/AF102/AF103: per-station offered load rho.

    rho = arrival rate x mean service demand / servers-at-station, with the
    client retry ladder amplifying arrivals by up to ``max_attempts``.  The
    two stations with finite capacity are the CPU core pool and the DB
    connection pool (plain I/O waits are unbounded-concurrency sleeps).
    """
    from asyncflow_tpu.compiler.plan import _server_entry_rates

    rates = _server_entry_rates(payload)
    if rates is None:  # cyclic server chain: rates undefined, graph pass reports
        return
    amp = _retry_amplification(payload)
    hamp = _hedge_amplification(payload)
    servers = payload.topology_graph.nodes.servers
    for s, server in enumerate(servers):
        lam = float(rates[s])
        if lam <= 0.0:
            continue
        path = f"topology_graph.nodes.servers[{s}] (id={server.id!r})"
        ov = server.overload
        # an explicit shedding (or brownout) control turns saturation into
        # a loss/degraded system: the queue is bounded by design and the
        # excess lands in total_rejected / degraded_completions, so
        # rho >= 1 is a regime note, not an error
        sheds = ov is not None and any(
            getattr(ov, f, None) is not None
            for f in (
                "max_ready_queue",
                "max_connections",
                "rate_limit_rps",
                "queue_timeout_s",
                "brownout_queue_threshold",
            )
        )
        stations = [(
            "cpu",
            _weighted(server, _ep_cpu),
            int(server.server_resources.cpu_cores),
            "add cpu_cores, add servers behind the load balancer, or "
            "lower the offered rate (users x req/min)",
        )]
        pool = server.server_resources.db_connection_pool
        if pool:
            stations.append((
                "db_connection_pool",
                _weighted(server, _ep_db),
                int(pool),
                "raise db_connection_pool or shorten the io_db holds",
            ))
        for station, demand, k, remedy in stations:
            if demand <= 0.0 or k <= 0:
                continue
            rho = lam * demand / k
            rho_amp = rho * amp
            detail = (
                f"server {server.id!r} {station} station: offered load "
                f"rho={rho:.2f} (rate {lam:.1f} rq/s x demand {demand:.3f} s"
                f" / {k} slot(s))"
            )
            if rho_amp >= RHO_WARNING and sheds:
                out.append(Diagnostic(
                    code="AF104", severity=Severity.INFO,
                    message=detail + " is at/over saturation but the "
                    "server's overload policy sheds the excess "
                    "(bounded-loss system): latency stays bounded and the "
                    "signal moves to the total_rejected counters",
                    path=path,
                    remedy="intentional overload studies need no change; "
                    "otherwise " + remedy,
                ))
            elif rho >= RHO_ERROR:
                out.append(Diagnostic(
                    code="AF102", severity=Severity.ERROR,
                    message=detail + " >= 1.0: the queue grows without "
                    "bound and latency percentiles depend on the horizon, "
                    "not the system",
                    path=path, remedy=remedy,
                ))
            elif rho_amp >= RHO_WARNING:
                ampnote = (
                    f"; retry amplification x{amp:.0f} "
                    f"(retry_policy.max_attempts) lifts it to "
                    f"{rho_amp:.2f}" if amp > 1.0 and rho < RHO_WARNING
                    else ""
                )
                out.append(Diagnostic(
                    code="AF101", severity=Severity.WARNING,
                    message=detail + ampnote + ": near saturation — small "
                    "input changes produce large output swings",
                    path=path,
                    remedy=remedy + (
                        "; or lower retry_policy.max_attempts"
                        if amp > 1.0 else ""
                    ),
                ))
            elif rho >= RHO_NOISE:
                out.append(Diagnostic(
                    code="AF103", severity=Severity.INFO,
                    message=detail + f" >= {RHO_NOISE}: queueing noise "
                    "dominates — single-seed comparisons (parity "
                    "tolerances, A/B deltas) become a seed lottery",
                    path=path,
                    remedy="average more seeds (SweepRunner Monte-Carlo) "
                    "or lengthen the horizon before trusting point "
                    "estimates",
                ))
            # hedge duplication is a separate amplification channel: in
            # the worst case (every hedge timer fires) each attempt
            # re-offers x(1 + max_hedges) load, on TOP of the retry ladder
            if (
                hamp > 1.0
                and rho_amp < RHO_WARNING
                and rho_amp * hamp >= RHO_WARNING
                and not sheds
            ):
                out.append(Diagnostic(
                    code="AF105", severity=Severity.WARNING,
                    message=detail + f": hedge duplication "
                    f"(hedge_policy.max_hedges={hamp - 1.0:.0f}) can "
                    f"multiply the offered load by x{hamp:.0f}, lifting "
                    f"rho to {rho_amp * hamp:.2f} when the tail is slow "
                    "enough that every hedge timer fires — the hedge storm "
                    "regime where duplicates cause the latency they chase",
                    path=path,
                    remedy="raise hedge_delay_s past the typical tail, "
                    "lower max_hedges, or add headroom (" + remedy + ")",
                ))


def graph_pass(payload, out: list[Diagnostic]) -> None:
    """AF201/AF202/AF203: reachability of nodes and edges under traffic."""
    g = payload.topology_graph
    servers = {s.id for s in g.nodes.servers}
    lb = g.nodes.load_balancer
    by_source: dict[str, list] = {}
    for e in g.edges:
        by_source.setdefault(e.source, []).append(e)

    visited: set[str] = set()
    traversed: set[str] = set()
    frontier = [w.id for w in payload.generators]
    while frontier:
        node = frontier.pop()
        if node in visited:
            continue
        visited.add(node)
        if lb is not None and node == lb.id:
            # the LB reaches its whole cover even when an edge is implicit
            frontier.extend(lb.server_covered)
        for e in by_source.get(node, []):
            traversed.add(e.id)
            frontier.append(e.target)

    for s, server in enumerate(g.nodes.servers):
        if server.id not in visited:
            out.append(Diagnostic(
                code="AF201", severity=Severity.WARNING,
                message=f"server {server.id!r} receives no traffic: no "
                "generator entry chain or load-balancer cover reaches it",
                path=f"topology_graph.nodes.servers[{s}]",
                remedy="wire an edge (or load-balancer cover) to the "
                "server, or remove it from the topology",
            ))
    for i, e in enumerate(g.edges):
        if e.id not in traversed:
            out.append(Diagnostic(
                code="AF202", severity=Severity.WARNING,
                message=f"edge {e.id!r} ({e.source} -> {e.target}) is "
                "never traversed by any request path",
                path=f"topology_graph.edges[{i}]",
                remedy="connect its source to the traffic graph or delete "
                "the edge",
            ))
    # a reachable server must eventually route back to the client, or
    # every request that enters it never completes
    client = g.nodes.client.id
    for s, server in enumerate(g.nodes.servers):
        if server.id not in visited:
            continue
        node, ok = server.id, False
        for _ in range(len(g.edges) + 1):
            nxt = by_source.get(node, [])
            if not nxt:
                break
            node = nxt[0].target
            if node == client:
                ok = True
                break
            if node not in servers:
                ok = True  # LB / client-adjacent component closes the loop
                break
        if not ok:
            out.append(Diagnostic(
                code="AF203", severity=Severity.WARNING,
                message=f"server {server.id!r} has no edge chain back to "
                f"the client {client!r}: responses from it never complete",
                path=f"topology_graph.nodes.servers[{s}]",
                remedy="add the server -> client (response) edge",
            ))


def time_pass(payload, out: list[Diagnostic]) -> None:
    """AF301-AF304: timeout vs achievable RTT, fault blackouts, backoff."""
    horizon = float(payload.sim_settings.total_simulation_time)
    servers = {s.id: s for s in payload.topology_graph.nodes.servers}
    lb = payload.topology_graph.nodes.load_balancer
    rp = payload.retry_policy

    if rp is not None:
        timeout = float(rp.request_timeout_s)
        for workload in payload.generators:
            hops, terminal = _entry_walk(payload, workload.id)
            if terminal is None:
                continue
            targets = (
                sorted(lb.server_covered)
                if lb is not None and terminal == lb.id
                else [terminal]
            )
            floor = min(_service_floor(servers[t]) for t in targets)
            # stochastic edge draws all reach 0, so the deterministic floor
            # is the endpoint service time; edge MEANS bound the typical trip
            edge_mean = 2.0 * sum(float(e.latency.mean) for e in hops)
            if timeout < floor:
                out.append(Diagnostic(
                    code="AF301", severity=Severity.ERROR,
                    message=f"request_timeout_s={timeout:g} is below the "
                    f"minimum achievable service time {floor:g}s: every "
                    "attempt times out, goodput is zero, and each logical "
                    f"request re-offers up to x{rp.max_attempts} load (a "
                    "certain retry storm)",
                    path="retry_policy.request_timeout_s",
                    remedy=f"raise request_timeout_s above {floor:g}s or "
                    "shorten the endpoint's cpu/io steps",
                ))
            elif timeout < floor + edge_mean:
                out.append(Diagnostic(
                    code="AF302", severity=Severity.WARNING,
                    message=f"request_timeout_s={timeout:g} is below the "
                    f"typical round trip (~{floor + edge_mean:g}s = service "
                    f"floor {floor:g}s + mean edge latency {edge_mean:g}s): "
                    "most attempts will time out",
                    path="retry_policy.request_timeout_s",
                    remedy="raise request_timeout_s comfortably above the "
                    "typical RTT, or speed up the slow path it measures",
                ))

        # the full retry ladder must fit the horizon, or late logical
        # requests are truncated mid-ladder and retry metrics are biased
        backoffs = sum(
            min(
                float(rp.backoff_cap_s),
                float(rp.backoff_base_s)
                * float(rp.backoff_multiplier) ** (k - 1),
            )
            for k in range(1, int(rp.max_attempts))
        )
        ladder = int(rp.max_attempts) * float(rp.request_timeout_s) + backoffs
        if ladder > horizon:
            out.append(Diagnostic(
                code="AF304", severity=Severity.WARNING,
                message=f"the worst-case retry ladder takes {ladder:g}s "
                f"({rp.max_attempts} x timeout {rp.request_timeout_s:g}s + "
                f"{backoffs:g}s backoff) but the horizon is only "
                f"{horizon:g}s: requests are cut off mid-ladder and "
                "retry/timeout counters under-report",
                path="retry_policy",
                remedy="lengthen total_simulation_time, cap the backoff "
                "lower, or reduce max_attempts",
            ))

    hp = getattr(payload, "hedge_policy", None)
    if hp is not None and rp is not None:
        delay = float(hp.hedge_delay_s)
        timeout = float(rp.request_timeout_s)
        if delay >= timeout:
            out.append(Diagnostic(
                code="AF305", severity=Severity.ERROR,
                message=f"hedge_delay_s={delay:g} is at/above "
                f"request_timeout_s={timeout:g}: the client deadline "
                "orphans every attempt before its hedge timer can fire, "
                "so hedging never wins a race — it only duplicates load "
                "behind requests the client already gave up on "
                "(a self-defeating policy)",
                path="hedge_policy.hedge_delay_s",
                remedy="set hedge_delay_s well below request_timeout_s "
                "(typically near the latency tail you want to cut, e.g. "
                "the p95-p99 gap), or drop the hedge policy",
            ))

    cover = {
        sid: _covered(wins, horizon)
        for sid, wins in _outage_windows(payload).items()
        if sid in servers
    }
    full = [sid for sid, c in cover.items() if c >= 1.0]
    for sid in full:
        out.append(Diagnostic(
            code="AF303",
            severity=(
                Severity.ERROR if set(full) >= set(servers)
                else Severity.WARNING
            ),
            message=f"outage windows cover the entire horizon for server "
            f"{sid!r}: it never serves a single request"
            + (" — with every server dark the run has zero goodput"
               if set(full) >= set(servers) else ""),
            path="fault_timeline / events",
            remedy="shrink the outage windows or lengthen "
            "total_simulation_time past them",
        ))


def resource_pass(payload, plan, out: list[Diagnostic]) -> None:
    """AF401-AF404: RAM feasibility, capacity rescale, table cliffs."""
    from asyncflow_tpu.compiler.plan import _server_entry_rates

    rates = _server_entry_rates(payload)
    servers = payload.topology_graph.nodes.servers
    amp = _retry_amplification(payload)
    for s, server in enumerate(servers):
        ram_mb = float(server.server_resources.ram_mb)
        path = f"topology_graph.nodes.servers[{s}] (id={server.id!r})"
        for e, ep in enumerate(server.endpoints):
            need = _ep_ram(ep)
            if need > ram_mb:
                out.append(Diagnostic(
                    code="AF401", severity=Severity.ERROR,
                    message=f"endpoint {ep.endpoint_name!r} needs "
                    f"{need:g} MB of RAM but server {server.id!r} only has "
                    f"{ram_mb:g} MB: no request of this endpoint can ever "
                    "be admitted",
                    path=path + f".endpoints[{e}]",
                    remedy="raise ram_mb above the endpoint's summed "
                    "necessary_ram, or shrink the steps",
                ))
        if rates is None:
            continue
        lam = float(rates[s]) * amp
        residence = _weighted(
            server, lambda ep: _ep_cpu(ep) + _ep_io_mean(ep),
        )
        occupancy = lam * residence * _weighted(server, _ep_ram)
        if ram_mb > 0.0 and occupancy >= RHO_WARNING * ram_mb:
            out.append(Diagnostic(
                code="AF402", severity=Severity.WARNING,
                message=f"steady-state RAM occupancy on server "
                f"{server.id!r} is ~{occupancy:.0f} MB "
                f"({lam:.1f} rq/s x {residence:.3f} s residence x mean "
                f"necessary_ram) against {ram_mb:g} MB: admission blocks "
                "and the RAM queue becomes the bottleneck",
                path=path,
                remedy="raise ram_mb, lower the offered rate, or shorten "
                "the residence (cpu/io) of RAM-holding requests",
            ))

    if len(payload.generators) > 1:
        out.append(Diagnostic(
            code="AF403", severity=Severity.INFO,
            message=f"{len(payload.generators)} generators superpose: a "
            "manual max_requests override is split across generators in "
            "rate proportion, so a small cap can starve the low-rate "
            "generator's lanes entirely",
            path="rqs_input",
            remedy="leave max_requests to the compiler's capacity "
            "estimate, or size it per the combined rate",
        ))

    if plan is not None:
        from asyncflow_tpu.engines.jaxsim.sortutil import DENSE_TABLE_MAX

        tables = {
            "spike_times (event injections)": len(plan.spike_times),
            "fault_srv_times (fault timeline)": len(plan.fault_srv_times),
            "fault_edge_times (fault timeline)": len(plan.fault_edge_times),
        }
        for name, n in tables.items():
            if n > DENSE_TABLE_MAX:
                out.append(Diagnostic(
                    code="AF404", severity=Severity.WARNING,
                    message=f"breakpoint table {name} has {n} entries, "
                    f"over the {DENSE_TABLE_MAX}-entry dense-compare bound "
                    "of searchsorted_small: every lookup falls back to a "
                    "gather-heavy binary search on device",
                    path="events / fault_timeline",
                    remedy="merge adjacent windows or split the scenario; "
                    f"keep breakpoint tables within {DENSE_TABLE_MAX} "
                    "entries",
                ))


def hazard_pass(payload, out: list[Diagnostic]) -> None:
    """AF601-AF604: chaos-campaign sanity (docs/guides/resilience.md).

    The payload validator only checks that hazard targets EXIST; the
    semantic traps — a blast group that darkens a whole tier, repairs
    longer than the horizon, campaigns dense enough to blow the per-domain
    slot budget — validate fine and are refused here by name, so the
    checker CLI exits 2 before a sweep burns compute on a meaningless
    campaign.
    """
    hm = getattr(payload, "hazard_model", None)
    if hm is None:
        return
    horizon = float(payload.sim_settings.total_simulation_time)
    g = payload.topology_graph
    server_ids = {s.id for s in g.nodes.servers}
    edge_ids = {e.id for e in g.edges}
    lb = g.nodes.load_balancer
    #: the serving tier a blast group must not fully cover: the LB's
    #: replica cover when an LB exists, else every server
    tier = set(lb.server_covered) if lb is not None else set(server_ids)
    max_faults = int(hm.max_faults_per_component)
    for d, domain in enumerate(hm.domains):
        path = f"hazard_model.domains[{d}]"
        unknown = [
            t for t in domain.targets
            if t not in server_ids and t not in edge_ids
        ]
        if unknown:
            # unreachable through pydantic validation, but check_payload
            # also takes hand-constructed payloads; a hazard aimed at
            # nothing must never silently sample an empty campaign
            out.append(Diagnostic(
                code="AF601", severity=Severity.ERROR,
                message=f"failure domain {domain.domain_id!r} targets "
                f"unknown component(s) {unknown}: the campaign would "
                "sample windows no engine applies to anything",
                path=path,
                remedy="target declared server/edge ids (or delete the "
                "domain)",
            ))
            continue
        covered = {t for t in domain.targets if t in server_ids}
        if tier and tier <= covered:
            out.append(Diagnostic(
                code="AF602", severity=Severity.ERROR,
                message=f"failure domain {domain.domain_id!r} is a blast "
                f"group covering every server of the serving tier "
                f"({sorted(tier)}): each sampled window is a full outage "
                "— zero availability by construction, not a resilience "
                "measurement",
                path=path,
                remedy="split the blast group so at least one replica "
                "stays outside the correlated domain",
            ))
        mttr_mean = float(domain.mttr.mean)
        if mttr_mean >= horizon:
            out.append(Diagnostic(
                code="AF603", severity=Severity.ERROR,
                message=f"failure domain {domain.domain_id!r} repairs "
                f"slower than the simulation: MTTR mean {mttr_mean:g}s >= "
                f"horizon {horizon:g}s, so the first sampled fault "
                "typically never heals in-sim and availability measures "
                "the fault start time, not the recovery model",
                path=f"{path}.mttr",
                remedy="shorten the MTTR (or lengthen "
                "sim_settings.total_simulation_time past several "
                "MTBF+MTTR cycles)",
            ))
        cycle = float(domain.mtbf.mean) + mttr_mean
        if cycle > 0 and horizon / cycle > max_faults:
            out.append(Diagnostic(
                code="AF604", severity=Severity.WARNING,
                message=f"failure domain {domain.domain_id!r} expects "
                f"~{horizon / cycle:.1f} fault cycles over the {horizon:g}s "
                f"horizon but max_faults_per_component={max_faults}: "
                "late-horizon windows will be truncated (counted in the "
                "hazard_truncated scorecard counter, like flight-recorder "
                "ring overflow)",
                path=f"{path}.mtbf",
                remedy="raise hazard_model.max_faults_per_component or "
                "lengthen the MTBF so the expected cycle count fits the "
                "slot budget",
            ))


def serving_pass(payload, out: list[Diagnostic]) -> None:
    """AF701-AF703: LLM serving sanity (docs/guides/serving.md).

    The schema validator only checks that a serving policy EXISTS next to
    ``llm_serve`` steps; the semantic traps — a token budget too small for
    even one typical request (deterministic eviction livelock), a budget
    the p99 prompt can never be admitted under (head-of-line starvation),
    a replay trace extending past the horizon — validate fine and are
    refused here by name, before a sweep burns compute thrashing the KV
    gate.  The budget collapse mirrors the compiler's
    (``min(max_batch_tokens, kv_cache_mb / kv_mb_per_token)``).
    """
    for srv in payload.topology_graph.nodes.servers:
        pol = getattr(srv, "serving", None)
        if pol is None:
            continue
        steps = [
            (ei, st)
            for ei, ep in enumerate(srv.endpoints)
            for st in ep.steps
            if getattr(st, "is_serving", False)
        ]
        budget = float("inf")
        if pol.max_batch_tokens is not None:
            budget = float(pol.max_batch_tokens)
        if pol.kv_cache_mb is not None:
            kv_max = max(
                (float(st.kv_mb_per_token) for _, st in steps), default=0.0,
            )
            if kv_max > 0:
                budget = min(budget, float(pol.kv_cache_mb) / kv_max)
        for ei, st in steps:
            path = (
                f"servers[{srv.id}].endpoints[{ei}] (llm_serve) "
                f"vs servers[{srv.id}].serving"
            )
            footprint = float(st.input_tokens.mean) + float(
                st.output_tokens.mean,
            )
            if budget < footprint:
                out.append(Diagnostic(
                    code="AF701", severity=Severity.ERROR,
                    message=f"server {srv.id!r}: serving token budget "
                    f"{budget:g} cannot hold even one typical request "
                    f"(mean prompt {st.input_tokens.mean:g} + mean "
                    f"generation {st.output_tokens.mean:g} = {footprint:g} "
                    "resident tokens) — every decode extension evicts, so "
                    "requests thrash prefill->evict until max_evictions "
                    "rejects them: a deterministic livelock, not a "
                    "capacity measurement",
                    path=path,
                    remedy="raise max_batch_tokens / kv_cache_mb past the "
                    "mean request footprint (or shorten the workload's "
                    "token distributions)",
                ))
                continue  # AF702 is strictly weaker; don't double-report
            p99_in = float(st.input_tokens.p99)
            if budget < p99_in:
                out.append(Diagnostic(
                    code="AF702", severity=Severity.WARNING,
                    message=f"server {srv.id!r}: serving token budget "
                    f"{budget:g} < the ~p99 prompt length {p99_in:g}: "
                    "long requests can never be admitted and park at the "
                    "head of the FIFO, starving everything queued behind "
                    "them",
                    path=path,
                    remedy="raise the token budget past "
                    "input_tokens.mean + 2.326*sigma, or cap prompt "
                    "lengths upstream",
                ))
    gens = payload.generators
    replay = getattr(gens[0], "replay", None) if len(gens) == 1 else None
    if replay is not None:
        horizon = float(payload.sim_settings.total_simulation_time)
        t_max = float(replay.times[-1])
        if t_max >= horizon:
            n_lost = sum(1 for t in replay.times if t >= horizon)
            out.append(Diagnostic(
                code="AF703", severity=Severity.WARNING,
                message=f"replay trace extends past the horizon: last "
                f"arrival at {t_max:g}s >= "
                f"total_simulation_time {horizon:g}s, so the final "
                f"{n_lost} of {len(replay.times)} logged requests never "
                "replay and the run underestimates the trace's load",
                path="rqs_input.replay.times",
                remedy="lengthen sim_settings.total_simulation_time past "
                "the last arrival (plus drain time), or trim the trace",
            ))


def _bench_engine_rates() -> tuple[str, dict[str, float]] | None:
    """(bench name, {engine: scenarios/sec}) from the newest BENCH_r*.json
    at the repo root — the data source for the fence burn-down speedup
    estimates.  The headline ``value`` is the recorded engine's rate
    (``detail.engine``, the fast path since round 2), the oracle walls
    invert to oracle/native rates, and the resilient arm (round 8+)
    contributes the event engine's sweep rate.  None when no bench has
    been recorded (fresh checkout / installed package)."""
    import json
    from pathlib import Path

    root = Path(__file__).resolve().parents[2]
    for path in sorted(root.glob("BENCH_r*.json"), reverse=True):
        try:
            parsed = json.loads(path.read_text())["parsed"]
        except Exception:  # noqa: BLE001 - malformed round, try the previous
            continue
        if not isinstance(parsed, dict):
            continue
        detail = parsed.get("detail") or {}
        rates: dict[str, float] = {}
        value = parsed.get("value")
        if isinstance(value, (int, float)) and value > 0:
            rates[str(detail.get("engine", "fast"))] = float(value)
        for eng, wall_key in (
            ("oracle", "oracle_wall_s_per_scenario"),
            ("native", "native_oracle_wall_s_per_scenario"),
        ):
            wall = detail.get(wall_key)
            if isinstance(wall, (int, float)) and wall > 0:
                rates[eng] = 1.0 / float(wall)
        resilient = detail.get("resilient") or {}
        for eng, rate_key in (
            ("fast", "fast_scen_s"),
            ("event", "event_scen_s"),
        ):
            rate = resilient.get(rate_key)
            if isinstance(rate, (int, float)) and rate > 0:
                rates.setdefault(eng, float(rate))
        if rates:
            return path.stem, rates
    return None


def routing_pass(
    payload,
    plan,
    out: list[Diagnostic],
    *,
    engine: str = "auto",
    backend: str | None = None,
    trace: bool = False,
    crn: bool = False,
    antithetic: bool = False,
    gauge_series: bool = False,
) -> None:
    """AF501-AF503: which engine runs this, and every fence on the way."""
    pred = predict_routing(
        plan,
        engine=engine,
        backend=backend,
        trace=trace,
        crn=crn,
        antithetic=antithetic,
        gauge_series=gauge_series,
        # availability probe only matters for a forced native engine; the
        # static answer ("the constructor would raise") stays deterministic
        native_ok=True if engine == "native" else None,
    )
    if pred.refusal is not None:
        out.append(Diagnostic(
            code="AF503", severity=Severity.ERROR,
            message=f"engine={engine!r} will be refused at construction: "
            + pred.refusal.message,
            path="SweepRunner(engine=...)",
            remedy="use engine='auto' or an engine outside the fence",
        ))
    # expected speedup of burning each remaining fence, from the
    # per-engine scenarios/sec in the newest recorded BENCH — the
    # burn-down list is prioritized by data, not by guess
    bench = (
        _bench_engine_rates()
        if pred.fences and pred.engine is not None
        else None
    )
    cur_rate = bench[1].get(pred.engine) if bench else None

    def speedup_note(target: str) -> str:
        if pred.engine is None:
            return ""  # refused construction: there is no routed baseline
        if bench is None:
            return " (no BENCH recorded: speedup unestimated)"
        name, rates = bench
        alt = rates.get(target)
        if not cur_rate or not alt:
            return (
                f" (expected speedup unestimated: {name} records no "
                f"scen/s for {target!r} vs {pred.engine!r})"
            )
        return (
            f" — expected speedup if burned: ~{alt / cur_rate:.1f}x "
            f"({target} {alt:.1f} vs {pred.engine} {cur_rate:.1f} "
            f"scen/s, {name})"
        )

    if pred.refusal is None:
        summary = ""
        if pred.fences and bench is not None and cur_rate:
            parts = []
            for eng in sorted({f.engine for f in pred.fences}):
                alt = bench[1].get(eng)
                parts.append(
                    f"{eng} ~{alt / cur_rate:.1f}x"
                    if alt
                    else f"{eng} unmeasured"
                )
            summary = (
                f"; expected speedup from burning the remaining fences "
                f"(vs {pred.engine} at {cur_rate:.1f} scen/s, {bench[0]}): "
                + ", ".join(parts)
            )
        out.append(Diagnostic(
            code="AF501", severity=Severity.INFO,
            message=f"engine={pred.requested!r} runs this plan on the "
            f"{pred.engine!r} engine (backend={pred.backend!r}): "
            + pred.why + summary,
            path="SweepRunner(engine=...)",
            remedy="no action needed; force engine='event' to override "
            "routing",
        ))
    for f in pred.fences:
        out.append(Diagnostic(
            code="AF502", severity=Severity.INFO,
            message=f"fence {f.fence_id}: this config cannot use the "
            f"{f.engine!r} engine — {f.message}" + speedup_note(f.engine),
            path="SweepRunner(engine=...)",
            remedy="drop the feature to regain the fenced engine, or "
            "accept the routed one",
        ))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def check_payload(
    payload,
    *,
    plan=None,
    engine: str = "auto",
    backend: str | None = None,
    trace: bool = False,
    crn: bool = False,
    antithetic: bool = False,
    gauge_series: bool = False,
) -> CheckReport:
    """Run every static pass over a validated payload -> :class:`CheckReport`.

    ``plan`` (a lowered :class:`StaticPlan`) is compiled on demand when not
    provided; callers that already hold one (SweepRunner) pass it in so
    preflight costs no second lowering.  ``engine``/``backend``/``trace``/
    ``crn``/``antithetic`` describe the run being contemplated, for the
    routing prediction; the payload-shape passes ignore them.
    """
    out: list[Diagnostic] = []
    if plan is None:
        from asyncflow_tpu.compiler import compile_payload

        try:
            plan = compile_payload(payload)
        except Exception as exc:  # noqa: BLE001 - report, don't crash
            out.append(Diagnostic(
                code="AF001", severity=Severity.ERROR,
                message="scenario does not lower to a StaticPlan: "
                f"{type(exc).__name__}: {exc}",
                path="compile_payload(payload)",
                remedy="fix the scenario until compile_payload succeeds; "
                "the graph diagnostics below usually name the culprit",
            ))
    stability_pass(payload, plan, out)
    graph_pass(payload, out)
    time_pass(payload, out)
    resource_pass(payload, plan, out)
    hazard_pass(payload, out)
    serving_pass(payload, out)
    if plan is not None:
        routing_pass(
            payload, plan, out,
            engine=engine, backend=backend,
            trace=trace, crn=crn, antithetic=antithetic,
            gauge_series=gauge_series,
        )
    return CheckReport(diagnostics=out)
