"""Default-on preflight: the static analyzer wired into the runners.

``SimulationRunner`` and ``SweepRunner`` call :func:`run_preflight` before
touching an engine.  Modes:

- ``"warn"`` (default) — findings become one :class:`PreflightWarning`
  and, with telemetry enabled, a ``kind="preflight"`` JSONL run record;
  the run proceeds (deliberately-saturated studies are legitimate).
- ``"strict"`` — any warning-or-error finding raises
  :class:`PreflightError` carrying the full report.
- ``"off"`` — skip the analyzer entirely.
"""

from __future__ import annotations

import warnings

from asyncflow_tpu.checker.diagnostics import CheckReport

PREFLIGHT_MODES = ("warn", "strict", "off")


class PreflightWarning(UserWarning):
    """A scenario shipped to an engine with static findings on record."""


class PreflightError(RuntimeError):
    """Strict preflight refused a scenario; ``.report`` has the findings."""

    def __init__(self, report: CheckReport) -> None:
        self.report = report
        super().__init__(
            "preflight failed (" + report.summary() + ")\n" + report.render(),
        )


def run_preflight(
    payload,
    *,
    mode: str = "warn",
    plan=None,
    telemetry=None,
    where: str = "run",
    engine: str = "auto",
    backend: str | None = None,
    trace: bool = False,
    crn: bool = False,
    antithetic: bool = False,
    gauge_series: bool = False,
) -> CheckReport | None:
    """Analyze ``payload`` and report per ``mode`` (None when ``"off"``).

    Never raises in ``"warn"`` mode — not on findings, and not on an
    analyzer bug either (a diagnostics pass must not be able to take down
    a production run; such a failure becomes its own warning).
    """
    if mode not in PREFLIGHT_MODES:
        msg = f"preflight must be one of {PREFLIGHT_MODES}, got {mode!r}"
        raise ValueError(msg)
    if mode == "off":
        return None
    from asyncflow_tpu.checker.passes import check_payload

    try:
        report = check_payload(
            payload, plan=plan, engine=engine, backend=backend,
            trace=trace, crn=crn, antithetic=antithetic,
            gauge_series=gauge_series,
        )
    except Exception as err:  # noqa: BLE001 - see docstring
        if mode == "strict":
            raise
        warnings.warn(
            f"preflight analyzer failed ({type(err).__name__}: {err}); "
            "continuing without static checks",
            PreflightWarning,
            stacklevel=3,
        )
        return None
    if report.clean:
        return report
    if mode == "strict":
        raise PreflightError(report)
    warnings.warn(
        f"preflight found issues in this scenario ({where}): "
        + report.summary()
        + " — run `python -m asyncflow_tpu.checker` on it for the full "
        "report, or pass preflight='off' to silence",
        PreflightWarning,
        stacklevel=3,
    )
    if telemetry is not None:
        from asyncflow_tpu.observability.telemetry import emit_event_record

        emit_event_record(
            telemetry,
            kind="preflight",
            where=where,
            summary=report.summary(),
            codes=report.codes(),
            findings=[
                {
                    "code": d.code,
                    "severity": d.severity.value,
                    "message": d.message,
                    "path": d.path,
                }
                for d in report.diagnostics
            ],
        )
    return report
