"""Payload -> StaticPlan lowering for the batched engine."""

from asyncflow_tpu.compiler.faults import (
    FaultArrays,
    RetryScalars,
    lower_faults,
    lower_retry,
)
from asyncflow_tpu.compiler.plan import StaticPlan, compile_payload

__all__ = [
    "FaultArrays",
    "RetryScalars",
    "StaticPlan",
    "compile_payload",
    "lower_faults",
    "lower_retry",
]
