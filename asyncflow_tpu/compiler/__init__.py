"""Payload -> StaticPlan lowering for the batched engine."""

from asyncflow_tpu.compiler.plan import StaticPlan, compile_payload

__all__ = ["StaticPlan", "compile_payload"]
