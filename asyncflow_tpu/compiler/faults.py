"""Lower the fault timeline + retry policy to dense piecewise tables.

One lowering shared by every engine: the oracle evaluates the same arrays
host-side (``np.searchsorted``) that the JAX engine consults on device
(``searchsorted_small``), so the two can never disagree about what a fault
window means.

Fault windows become breakpoint tables exactly like the network-spike
lowering in :mod:`asyncflow_tpu.compiler.plan` — sorted unique change
times with a leading identity row at ``t = 0``, piecewise-constant values
on ``[t_k, t_{k+1})``:

- ``srv_down[k, s]`` — 1 while server ``s`` is inside a ``server_outage``
  window (overlapping windows union);
- ``edge_lat[k, e]`` — multiplicative latency factor on edge ``e``
  (superposed ``edge_degrade`` windows multiply);
- ``edge_drop[k, e]`` — additive dropout boost (superposed windows add;
  ``edge_partition`` contributes +1.0; engines clip base + boost to 1).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from asyncflow_tpu.config.constants import FaultKind
from asyncflow_tpu.schemas.payload import SimulationPayload
from asyncflow_tpu.schemas.resilience import (
    HedgePolicy,
    LbHealthPolicy,
    RetryPolicy,
)


@dataclass
class FaultArrays:
    """Dense piecewise-constant fault tables (identity when no faults)."""

    #: (K,) f32 sorted change times, srv_times[0] == 0
    srv_times: np.ndarray = field(
        default_factory=lambda: np.zeros(1, np.float32),
    )
    #: (K, NS) i32, 1 = server inside an outage window
    srv_down: np.ndarray = field(
        default_factory=lambda: np.zeros((1, 0), np.int32),
    )
    #: (M,) f32 sorted change times, edge_times[0] == 0
    edge_times: np.ndarray = field(
        default_factory=lambda: np.zeros(1, np.float32),
    )
    #: (M, NE) f32 multiplicative latency factor (1 = no fault)
    edge_lat: np.ndarray = field(
        default_factory=lambda: np.ones((1, 0), np.float32),
    )
    #: (M, NE) f32 additive dropout boost (0 = no fault)
    edge_drop: np.ndarray = field(
        default_factory=lambda: np.zeros((1, 0), np.float32),
    )

    @property
    def has_faults(self) -> bool:
        return bool(
            np.any(self.srv_down != 0)
            or np.any(self.edge_lat != 1.0)
            or np.any(self.edge_drop != 0.0),
        )

    # host-side evaluation (the oracle's view of the same tables) --------

    def server_down(self, s: int, t: float) -> bool:
        k = int(np.searchsorted(self.srv_times, t, side="right")) - 1
        return bool(self.srv_down[max(k, 0), s])

    def edge_fault(self, e: int, t: float) -> tuple[float, float]:
        """(latency factor, dropout boost) active on edge ``e`` at ``t``."""
        k = max(int(np.searchsorted(self.edge_times, t, side="right")) - 1, 0)
        return float(self.edge_lat[k, e]), float(self.edge_drop[k, e])


def lower_faults(payload: SimulationPayload) -> FaultArrays:
    """Lower the payload's fault timeline against its topology order."""
    servers = payload.topology_graph.nodes.servers
    edges = payload.topology_graph.edges
    n_servers, n_edges = len(servers), len(edges)
    server_index = {s.id: i for i, s in enumerate(servers)}
    edge_index = {e.id: i for i, e in enumerate(edges)}

    empty = FaultArrays(
        srv_down=np.zeros((1, n_servers), np.int32),
        edge_lat=np.ones((1, n_edges), np.float32),
        edge_drop=np.zeros((1, n_edges), np.float32),
    )
    faults = (
        payload.fault_timeline.events if payload.fault_timeline else []
    )
    if not faults:
        return empty

    srv_marks: list[tuple[float, int, int]] = []  # (t, delta, server)
    edge_marks: list[tuple[float, float, float, int]] = []  # (t, log_lat, drop, edge)
    for fault in faults:
        if fault.kind == FaultKind.SERVER_OUTAGE:
            s = server_index[fault.target_id]
            srv_marks.append((float(fault.t_start), 1, s))
            srv_marks.append((float(fault.t_end), -1, s))
        else:
            e = edge_index[fault.target_id]
            if fault.kind == FaultKind.EDGE_PARTITION:
                log_lat, drop = 0.0, 1.0
            else:
                log_lat = math.log(float(fault.latency_factor))
                drop = float(fault.dropout_boost)
            edge_marks.append((float(fault.t_start), log_lat, drop, e))
            edge_marks.append((float(fault.t_end), -log_lat, -drop, e))

    def _table(times: set[float]) -> tuple[np.ndarray, dict[float, int]]:
        change = sorted({0.0} | times)
        return (
            np.array(change, np.float32),
            {t: i for i, t in enumerate(change)},
        )

    srv_times, srv_pos = _table({t for t, _, _ in srv_marks})
    srv_delta = np.zeros((len(srv_times), n_servers), np.int32)
    for t, delta, s in srv_marks:
        srv_delta[srv_pos[t], s] += delta
    srv_down = (np.cumsum(srv_delta, axis=0) > 0).astype(np.int32)

    edge_times, edge_pos = _table({t for t, _, _, _ in edge_marks})
    lat_delta = np.zeros((len(edge_times), n_edges), np.float64)
    drop_delta = np.zeros((len(edge_times), n_edges), np.float64)
    for t, log_lat, drop, e in edge_marks:
        lat_delta[edge_pos[t], e] += log_lat
        drop_delta[edge_pos[t], e] += drop
    edge_lat = np.exp(np.cumsum(lat_delta, axis=0)).astype(np.float32)
    # exp/log round trips can leave 1 +- eps outside windows; snap
    edge_lat[np.isclose(edge_lat, 1.0, atol=1e-6)] = 1.0
    edge_drop = np.clip(
        np.cumsum(drop_delta, axis=0), 0.0, None,
    ).astype(np.float32)

    return FaultArrays(
        srv_times=srv_times,
        srv_down=srv_down,
        edge_times=edge_times,
        edge_lat=edge_lat,
        edge_drop=edge_drop,
    )


@dataclass
class RetryScalars:
    """The retry policy lowered to plan scalars (inert defaults = none)."""

    timeout: float = -1.0  # < 0 = no retry policy
    max_attempts: int = 1
    backoff_base: float = 0.0
    backoff_mult: float = 1.0
    backoff_cap: float = 0.0
    jitter: float = 0.0
    budget_tokens: float = -1.0  # < 0 = unlimited budget
    budget_refill: float = 0.0

    @property
    def enabled(self) -> bool:
        return self.timeout > 0


def lower_retry(policy: RetryPolicy | None) -> RetryScalars:
    if policy is None:
        return RetryScalars()
    return RetryScalars(
        timeout=float(policy.request_timeout_s),
        max_attempts=int(policy.max_attempts),
        backoff_base=float(policy.backoff_base_s),
        backoff_mult=float(policy.backoff_multiplier),
        backoff_cap=float(policy.backoff_cap_s),
        jitter=float(policy.jitter),
        budget_tokens=(
            float(policy.budget_tokens)
            if policy.budget_tokens is not None
            else -1.0
        ),
        budget_refill=float(policy.budget_refill_per_s),
    )


@dataclass
class HedgeScalars:
    """The hedge policy lowered to plan scalars (inert defaults = none)."""

    delay: float = -1.0  # < 0 = no hedge policy
    max_hedges: int = 0
    cancel: int = 1  # 1 = cancel losers at routing boundaries

    @property
    def enabled(self) -> bool:
        return self.delay > 0


def lower_hedge(policy: HedgePolicy | None) -> HedgeScalars:
    if policy is None:
        return HedgeScalars()
    return HedgeScalars(
        delay=float(policy.hedge_delay_s),
        max_hedges=int(policy.max_hedges),
        cancel=int(bool(policy.cancel_on_first)),
    )


@dataclass
class HealthScalars:
    """The LB health policy lowered to plan scalars (inert = none)."""

    alpha: float = 0.0  # <= 0 = no health policy
    threshold: float = 1.0
    readmit: float = -1.0

    @property
    def enabled(self) -> bool:
        return self.alpha > 0

    def observe(self, h: float, failed: bool) -> float:
        """One EWMA update — the single formula both engines share."""
        x = 1.0 if failed else 0.0
        return (1.0 - self.alpha) * h + self.alpha * x


def lower_health(policy: LbHealthPolicy | None) -> HealthScalars:
    if policy is None:
        return HealthScalars()
    return HealthScalars(
        alpha=float(policy.ewma_alpha),
        threshold=float(policy.ejection_threshold),
        readmit=float(policy.readmit_s),
    )
