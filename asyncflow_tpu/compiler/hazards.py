"""Chaos campaigns: sample stochastic hazard models into per-scenario
piecewise fault tables.

The hazard model (``schemas/resilience.py``: :class:`HazardModel` /
:class:`FailureDomain`) describes *random* failure processes — per-domain
MTBF/MTTR duration laws plus correlated blast groups — where the fault
timeline (``compiler/faults.py``) describes hand-authored windows.  This
module is the single lowering both worlds share:

- :func:`lower_hazards` turns the validated model into dense per-domain
  arrays carried on the :class:`~asyncflow_tpu.compiler.plan.StaticPlan`
  (``hz_*`` fields), so the plan digest covers the campaign and every
  engine sees one description.
- :func:`hazard_fault_tables` samples scenario ``i``'s window recurrence
  with lockstep inverse-CDF draws keyed by
  ``fold_in(fold_in(fold_in(scenario_key, HZ_SITE + domain), ordinal),
  0|1)`` and merges them with the plan's static tables into ``(S, ...)``
  breakpoint tables of the exact shape the engines already consume.
  The draws are a pure function of ``(seed, global scenario index)`` —
  prefix-stable across chunking, checkpoint resume, quarantine re-runs
  and adaptive rounds, and bit-identical across the oracle heap loop,
  the vmapped event engine and the scan fast path by construction (all
  three consume the same host-side numpy tables).
- the resilience-scorecard reducers (:func:`unavailable_seconds`,
  :func:`degraded_seconds_mask`, :func:`time_to_drain`) derive
  availability metrics from those tables so no engine needs new device
  counters for them.

Budget discipline: each (scenario, domain) samples ``2 * F`` window
ordinals but only the first ``F = max_faults_per_component`` enter the
tables (static shapes for vmap); later ordinals that would still start
inside the horizon are *counted* into ``truncated`` — the flight
recorder's explicit-truncation discipline, never silent.

Fold-site layout: ``HZ_SITE + d`` keeps hazard draws disjoint from every
other per-scenario family (generator streams 100000+g, retry jitter
2048+a, per-server families 64+s / 160+s).
"""

from __future__ import annotations

from dataclasses import dataclass
from statistics import NormalDist

import numpy as np

#: fold_in site base for hazard draws — disjoint from every other
#: per-scenario-key fold family (see module docstring).
HZ_SITE = 200_000

#: duration-law codes, pinned to compiler.plan._DIST_IDS (asserted in
#: :func:`lower_hazards` so the two can never drift).
D_EXPONENTIAL = 2
D_NORMAL = 3
D_LOG_NORMAL = 4

_ndtri = np.vectorize(NormalDist().inv_cdf, otypes=[np.float64])


@dataclass
class HazardSpec:
    """The hazard model lowered to dense per-domain arrays (plan fields)."""

    mtbf_dist: np.ndarray  # (D,) i32 duration-law code
    mtbf_mean: np.ndarray  # (D,) f64
    mtbf_var: np.ndarray  # (D,) f64 (0 when the law has none)
    mttr_dist: np.ndarray  # (D,) i32
    mttr_mean: np.ndarray  # (D,) f64
    mttr_var: np.ndarray  # (D,) f64
    lat_factor: np.ndarray  # (D,) f64 edge latency multiplier
    drop_boost: np.ndarray  # (D,) f64 edge dropout boost
    srv_targets: np.ndarray  # (D, NS) i8 blast-group server membership
    edge_targets: np.ndarray  # (D, NE) i8 blast-group edge membership
    max_faults: int  # F: window slots per (scenario, domain)
    domain_ids: list[str]


def lower_hazards(payload) -> HazardSpec | None:
    """Lower the payload's hazard model against its topology order."""
    model = getattr(payload, "hazard_model", None)
    if model is None:
        return None
    from asyncflow_tpu.compiler.plan import _DIST_IDS
    from asyncflow_tpu.config.constants import Distribution

    assert _DIST_IDS[Distribution.EXPONENTIAL] == D_EXPONENTIAL
    assert _DIST_IDS[Distribution.NORMAL] == D_NORMAL
    assert _DIST_IDS[Distribution.LOG_NORMAL] == D_LOG_NORMAL

    servers = payload.topology_graph.nodes.servers
    edges = payload.topology_graph.edges
    server_index = {s.id: i for i, s in enumerate(servers)}
    edge_index = {e.id: i for i, e in enumerate(edges)}
    domains = model.domains
    n_dom = len(domains)

    spec = HazardSpec(
        mtbf_dist=np.zeros(n_dom, np.int32),
        mtbf_mean=np.zeros(n_dom, np.float64),
        mtbf_var=np.zeros(n_dom, np.float64),
        mttr_dist=np.zeros(n_dom, np.int32),
        mttr_mean=np.zeros(n_dom, np.float64),
        mttr_var=np.zeros(n_dom, np.float64),
        lat_factor=np.ones(n_dom, np.float64),
        drop_boost=np.zeros(n_dom, np.float64),
        srv_targets=np.zeros((n_dom, len(servers)), np.int8),
        edge_targets=np.zeros((n_dom, len(edges)), np.int8),
        max_faults=int(model.max_faults_per_component),
        domain_ids=[d.domain_id for d in domains],
    )
    for di, dom in enumerate(domains):
        spec.mtbf_dist[di] = _DIST_IDS[dom.mtbf.distribution]
        spec.mtbf_mean[di] = float(dom.mtbf.mean)
        spec.mtbf_var[di] = float(dom.mtbf.variance or 0.0)
        spec.mttr_dist[di] = _DIST_IDS[dom.mttr.distribution]
        spec.mttr_mean[di] = float(dom.mttr.mean)
        spec.mttr_var[di] = float(dom.mttr.variance or 0.0)
        spec.lat_factor[di] = float(dom.latency_factor)
        spec.drop_boost[di] = float(dom.dropout_boost)
        for target in dom.targets:
            if target in server_index:
                spec.srv_targets[di, server_index[target]] = 1
            elif target in edge_index:
                spec.edge_targets[di, edge_index[target]] = 1
            else:
                msg = (
                    f"failure domain {dom.domain_id!r}: target {target!r} "
                    "is not a declared server or edge"
                )
                raise ValueError(msg)
    return spec


def _hz_uniforms(seed: int, first: int, count: int, n_dom: int, n_ord: int):
    """(S, D, J, 2) lockstep uniforms for scenarios [first, first+count).

    The scenario key is ``fold_in(PRNGKey(seed), i)`` — identical to
    ``engines.jaxsim.engine.scenario_keys`` — then per (domain, ordinal):
    ``base = fold_in(fold_in(key, HZ_SITE + d), j)`` and the (gap,
    duration) pair draws ``uniform(fold_in(base, 0|1))``.  Every index is
    a pure fold of the global scenario index: prefix-stable by
    construction.
    """
    import jax
    import jax.numpy as jnp

    base = jax.random.PRNGKey(seed)

    def per_scn(i):
        key = jax.random.fold_in(base, i)

        def per_dom(d):
            kd = jax.random.fold_in(key, HZ_SITE + d)

            def per_ord(j):
                kj = jax.random.fold_in(kd, j)
                return jnp.stack([
                    jax.random.uniform(jax.random.fold_in(kj, 0)),
                    jax.random.uniform(jax.random.fold_in(kj, 1)),
                ])

            return jax.vmap(per_ord)(jnp.arange(n_ord))

        return jax.vmap(per_dom)(jnp.arange(n_dom))

    idx = jnp.arange(first, first + count)
    return np.asarray(jax.vmap(per_scn)(idx), np.float64)


def _inv_cdf(dist: int, mean, var: float, u: np.ndarray) -> np.ndarray:
    """Inverse-CDF duration draw, matching ``samplers/variates.py``'s
    antithetic path exactly (the variance field IS the scale parameter,
    the vocabulary's documented quirk)."""
    if dist == D_EXPONENTIAL:
        return -mean * np.log1p(-u)
    if dist == D_NORMAL:
        return np.maximum(0.0, mean + var * _ndtri(u))
    if dist == D_LOG_NORMAL:
        return np.exp(mean + var * _ndtri(u))
    msg = f"unsupported hazard duration-law code: {dist}"
    raise ValueError(msg)


def sample_hazard_windows(
    plan,
    seed: int,
    first: int,
    count: int,
    hazard_scale=None,
    mttr_scale=None,
):
    """Sample each scenario's per-domain fault windows.

    Returns ``(starts, ends, truncated)``: ``(S, D, F)`` float64 window
    bounds (the in-budget ordinals) and the ``(S,)`` int64 count of
    in-horizon windows dropped by the slot budget.  ``hazard_scale``
    divides the MTBF mean (more chaos), ``mttr_scale`` multiplies the
    MTTR mean (slower repair); both reuse the SAME uniforms, so scale
    sweeps are CRN-paired by construction.
    """
    n_dom = int(plan.hz_mtbf_mean.shape[0])
    n_slots = int(plan.hz_max_faults)
    n_ord = 2 * n_slots
    u = np.clip(
        _hz_uniforms(seed, first, count, n_dom, n_ord),
        1e-12,
        1.0 - 1e-12,
    )
    hs = np.asarray(
        1.0 if hazard_scale is None else hazard_scale, np.float64,
    ).reshape(-1, 1)
    ms = np.asarray(
        1.0 if mttr_scale is None else mttr_scale, np.float64,
    ).reshape(-1, 1)
    gaps = np.empty((count, n_dom, n_ord), np.float64)
    durs = np.empty((count, n_dom, n_ord), np.float64)
    for d in range(n_dom):
        gaps[:, d, :] = _inv_cdf(
            int(plan.hz_mtbf_dist[d]),
            float(plan.hz_mtbf_mean[d]) / hs,
            float(plan.hz_mtbf_var[d]),
            u[:, d, :, 0],
        )
        durs[:, d, :] = _inv_cdf(
            int(plan.hz_mttr_dist[d]),
            float(plan.hz_mttr_mean[d]) * ms,
            float(plan.hz_mttr_var[d]),
            u[:, d, :, 1],
        )
    ends = np.cumsum(gaps + durs, axis=2)
    starts = ends - durs
    truncated = np.sum(
        starts[:, :, n_slots:] < float(plan.horizon), axis=(1, 2),
    ).astype(np.int64)
    return starts[:, :, :n_slots], ends[:, :, :n_slots], truncated


@dataclass
class HazardTables:
    """Per-scenario merged fault tables + the sampled windows behind them."""

    srv_times: np.ndarray  # (S, K) f32 sorted change times, [:, 0] == 0
    srv_down: np.ndarray  # (S, K, NS) i32
    edge_times: np.ndarray  # (S, M) f32
    edge_lat: np.ndarray  # (S, M, NE) f32 multiplicative
    edge_drop: np.ndarray  # (S, M, NE) f32 additive
    starts: np.ndarray  # (S, D, F) f64 sampled window starts
    ends: np.ndarray  # (S, D, F) f64 sampled window ends
    truncated: np.ndarray  # (S,) i64 in-horizon windows past the budget


def hazard_fault_tables(
    plan,
    seed: int,
    first: int,
    count: int,
    hazard_scale=None,
    mttr_scale=None,
) -> HazardTables:
    """Materialize scenarios [first, first+count)'s fault tables.

    The sampled windows are merged with the plan's static fault tables
    (union for server outages, multiplicative/additive superposition for
    edge degradation) into fixed-width per-scenario breakpoint tables —
    the exact piecewise-constant encoding every engine already evaluates
    (``compiler/faults.py``).  Rows are time-sorted per scenario with a
    stable order, so duplicate times resolve identically everywhere; the
    host/device lookup (``searchsorted(..., 'right') - 1``) reads the
    LAST row at a time, which carries the full superposed state.
    """
    starts, ends, truncated = sample_hazard_windows(
        plan, seed, first, count, hazard_scale, mttr_scale,
    )
    n_scn, n_dom, n_slots = starts.shape
    dom_of = np.repeat(np.arange(n_dom), n_slots)
    marks_t = np.concatenate(
        [starts.reshape(n_scn, -1), ends.reshape(n_scn, -1)], axis=1,
    )  # (S, 2DF): all starts, then all ends

    def merged(static_times, static_vals, hz_rows, combine):
        """One merged table: static breakpoints + per-scenario marks.

        ``hz_rows`` is the (2DF, W) per-mark delta matrix; ``combine``
        maps (static value rows, hazard cumulative rows) -> final rows.
        """
        k0 = static_times.shape[0]
        st64 = static_times.astype(np.float64)
        full_t = np.concatenate(
            [np.broadcast_to(st64, (n_scn, k0)), marks_t], axis=1,
        )
        full_delta = np.concatenate(
            [np.zeros((k0, hz_rows.shape[1]), np.float64), hz_rows], axis=0,
        )
        order = np.argsort(full_t, axis=1, kind="stable")
        sorted_t = np.take_along_axis(full_t, order, axis=1)
        hz_cum = np.cumsum(full_delta[order], axis=1)  # (S, K, W)
        sidx = np.maximum(
            np.searchsorted(st64, sorted_t.ravel(), side="right") - 1, 0,
        ).reshape(n_scn, -1)
        return sorted_t.astype(np.float32), combine(static_vals[sidx], hz_cum)

    # ---- server outage table: union of static windows + hazard windows
    srv_rows = np.concatenate(
        [
            plan.hz_srv_targets[dom_of].astype(np.float64),
            -plan.hz_srv_targets[dom_of].astype(np.float64),
        ],
        axis=0,
    )
    srv_times, srv_down = merged(
        plan.fault_srv_times,
        plan.fault_srv_down,
        srv_rows,
        lambda static, cum: ((static != 0) | (cum > 0.5)).astype(np.int32),
    )

    # ---- edge degrade tables: factors multiply (via log sums), boosts add
    edge_w = plan.hz_edge_targets.shape[1]
    log_lat = np.log(plan.hz_lat_factor)[dom_of, None] * plan.hz_edge_targets[
        dom_of
    ].astype(np.float64)
    lat_rows = np.concatenate([log_lat, -log_lat], axis=0)
    drop = plan.hz_drop_boost[dom_of, None] * plan.hz_edge_targets[
        dom_of
    ].astype(np.float64)
    drop_rows = np.concatenate([drop, -drop], axis=0)

    def combine_lat(static, cum):
        lat = static.astype(np.float64) * np.exp(cum)
        # exp/log round trips can leave 1 +- eps outside windows; snap
        lat[np.isclose(lat, 1.0, atol=1e-6)] = 1.0
        return lat.astype(np.float32)

    edge_times, edge_lat = merged(
        plan.fault_edge_times, plan.fault_edge_lat, lat_rows, combine_lat,
    )
    edge_times2, edge_drop = merged(
        plan.fault_edge_times,
        plan.fault_edge_drop,
        drop_rows,
        lambda static, cum: np.clip(
            static.astype(np.float64) + cum, 0.0, None,
        ).astype(np.float32),
    )
    assert edge_w == edge_drop.shape[2]
    np.testing.assert_array_equal(edge_times, edge_times2)

    return HazardTables(
        srv_times=srv_times,
        srv_down=srv_down,
        edge_times=edge_times,
        edge_lat=edge_lat,
        edge_drop=edge_drop,
        starts=starts,
        ends=ends,
        truncated=truncated,
    )


# ----------------------------------------------------------------------
# resilience scorecard reducers (host-side, engine-agnostic: pure
# functions of the sampled tables + already-recorded series)
# ----------------------------------------------------------------------


def unavailable_seconds(
    srv_times: np.ndarray,
    srv_down: np.ndarray,
    horizon: float,
) -> np.ndarray:
    """(S, NS) float64 per-server dark seconds inside the horizon.

    Exact integral of the piecewise-constant outage table — identical for
    every engine because the tables are."""
    t = np.minimum(srv_times.astype(np.float64), horizon)
    n_scn = t.shape[0]
    t_next = np.concatenate(
        [t[:, 1:], np.full((n_scn, 1), float(horizon))], axis=1,
    )
    span = np.maximum(t_next - t, 0.0)
    return np.einsum("sk,skn->sn", span, srv_down.astype(np.float64))


def degraded_seconds_mask(
    tables: HazardTables,
    horizon: float,
    n_buckets: int,
) -> np.ndarray:
    """(S, T) bool: 1-second throughput bucket ``b`` overlaps some active
    fault state (server dark, edge degraded) — the denominator mask for
    degraded-window goodput."""
    n_scn = tables.srv_times.shape[0]
    buckets = np.arange(n_buckets, dtype=np.float64)

    def row_mask(times: np.ndarray, active: np.ndarray) -> np.ndarray:
        t = times.astype(np.float64)
        t_next = np.concatenate(
            [t[:, 1:], np.full((n_scn, 1), np.inf)], axis=1,
        )
        t0 = np.clip(t, 0.0, horizon)
        t1 = np.clip(t_next, 0.0, horizon)
        out = np.zeros((n_scn, n_buckets), bool)
        for k in range(t.shape[1]):
            act = active[:, k]
            if not act.any():
                continue
            out |= (
                act[:, None]
                & (t0[:, k, None] < buckets + 1.0)
                & (t1[:, k, None] > buckets)
            )
        return out

    srv_active = tables.srv_down.astype(bool).any(axis=2)
    edge_active = (tables.edge_lat != 1.0).any(axis=2) | (
        tables.edge_drop != 0.0
    ).any(axis=2)
    return row_mask(tables.srv_times, srv_active) | row_mask(
        tables.edge_times, edge_active,
    )


def window_span(
    tables: HazardTables,
    horizon: float,
) -> tuple[np.ndarray, np.ndarray]:
    """(first_start, last_end) of each scenario's in-horizon sampled
    windows, both (S,) float64 (NaN when the scenario sampled none)."""
    in_h = tables.starts < horizon
    starts = np.where(in_h, tables.starts, np.inf)
    ends = np.where(in_h, np.minimum(tables.ends, horizon), -np.inf)
    first = starts.min(axis=(1, 2))
    last = ends.max(axis=(1, 2))
    none = ~in_h.any(axis=(1, 2))
    first[none] = np.nan
    last[none] = np.nan
    return first, last


def time_to_drain(
    series: np.ndarray,
    period: float,
    first_start: np.ndarray,
    last_end: np.ndarray,
) -> np.ndarray:
    """(S,) sim-seconds from the last window closing until every tracked
    ready-queue series re-enters its pre-fault band (mean + 2 sigma of the
    samples before the first window).  NaN when undefined: no sampled
    window, no pre-fault samples, or the queue never returns inside the
    horizon."""
    series = np.asarray(series, np.float64)
    n_scn, n_t, _ = series.shape
    times = (np.arange(n_t, dtype=np.float64) + 1.0) * float(period)
    out = np.full(n_scn, np.nan)
    for s in range(n_scn):
        if not (np.isfinite(first_start[s]) and np.isfinite(last_end[s])):
            continue
        pre = series[s][times < first_start[s]]
        if pre.shape[0] == 0:
            continue
        band_hi = pre.mean(axis=0) + 2.0 * pre.std(axis=0) + 1e-9
        settled = (series[s] <= band_hi[None, :]).all(axis=1) & (
            times >= last_end[s]
        )
        if settled.any():
            out[s] = times[int(np.argmax(settled))] - last_end[s]
    return out
