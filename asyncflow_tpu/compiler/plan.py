"""Compile a validated payload into a dense, static execution plan.

This layer is new relative to the reference (which wires actors dynamically in
``simulation_runner.py:205-296``): everything the batched engine needs is
lowered to fixed-shape NumPy arrays once, so the JAX engine jits a single
next-event kernel over them.

Lowering decisions:

- **Endpoint programs become alternating CPU/IO segments.**  The reference's
  lazy core lock keeps the core across consecutive CPU steps and releases it
  on I/O (``actors/server.py:199-255``), so merging runs of CPU steps (and
  runs of I/O steps) into single segments is semantics-preserving.  RAM steps
  contribute to an up-front working-set total (RAM-first admission,
  ``server.py:147-149``).
- **The pre-server path is a static edge chain.**  From the generator the
  route is deterministic until the first LB or server, so the spawn event can
  walk it in one shot.  After each server the single out-edge leads to a
  server, the LB, or the client (second client visit = completion).
- **Network spikes become a breakpoint table** (piecewise-constant cumulative
  spike per edge, superposition included) consulted with ``searchsorted`` at
  send time — no runtime events needed.  Server outages remain true timeline
  events because they mutate the LB rotation order
  (``events/injection.py:201-226``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from asyncflow_tpu.config.constants import (
    Distribution,
    EndpointStepIO,
    EventDescription,
    LbAlgorithmsName,
)
from asyncflow_tpu.schemas.endpoint import Endpoint
from asyncflow_tpu.schemas.payload import SimulationPayload

# segment kinds
SEG_END = 0
SEG_CPU = 1
SEG_IO = 2
# an io_db run on a server whose finite db_connection_pool may bind: the
# request must hold one of K FIFO connections for the segment's duration
# (core released, RAM held — the connection wait parks in the event loop).
# Only emitted when the compiler cannot prove the pool non-binding.  Modeled
# by the event engines, and by the fast path as one extra FIFO G/G/K
# station per server when every endpoint's (single) query follows its last
# CPU burst (_fastpath_lowering).
SEG_DB = 3
# an io_cache step with hit/miss dynamics: the sleep is a per-request
# two-point mixture (hit latency with probability p, else the backing
# store's miss latency).  Modeled by the event engines, and by the fast
# path as per-request duration extras on the visit tables
# (fp_cache_slot/fp_cache_miss_prob/fp_cache_extra).
SEG_CACHE = 4
# an io_llm step with call dynamics (the reference's reserved io_llm kind
# + llm_cost/llm_stats metrics, activated): per request, output tokens ~
# Poisson(tokens_mean); the sleep is base + tokens * time_per_token and
# the request accrues tokens * cost_per_token.  Modeled by the oracle,
# native, and event engines; the fast path declines with a named reason.
SEG_LLM = 5
# an llm_serve step (serving subsystem, asyncflow_tpu/serving): lowered to
# a PREFILL/DECODE segment PAIR.  Prefill runs after continuous-batching
# admission (single FIFO gated on the server's batch slot + resident-token
# budgets) and sleeps base + input_tokens * time_per_token, holding
# input_tokens KV tokens; decode extends the KV hold by output_tokens and
# sleeps output_tokens / rate, or EVICTS when the extension does not fit
# (KV freed, prefill redone from the FIFO tail, counted in kv_evictions).
# Modeled by the oracle and event engines; fast path/pallas/native decline
# behind the llm.* fences.
SEG_PREFILL = 6
SEG_DECODE = 7

# Multi-burst relaxation envelope: nominal per-server core utilization above
# which the fast path's fixed-point relaxation is measurably biased vs the
# oracle (measured boundary: inside ensemble noise at rho 0.70, +28% p95 by
# rho 0.75 — scripts/relaxation_envelope.py; docs/internals/fastpath.md §5).
RELAX_RHO_MAX = 0.70

# node kinds a hop can land on
TARGET_SERVER = 1
TARGET_LB = 2
TARGET_CLIENT = 3

_DIST_IDS = {
    Distribution.UNIFORM: 0,
    Distribution.POISSON: 1,
    Distribution.EXPONENTIAL: 2,
    Distribution.NORMAL: 3,
    Distribution.LOG_NORMAL: 4,
}


def _compile_endpoint(
    endpoint: Endpoint,
    *,
    db_pooled: bool = False,
) -> tuple[
    list[tuple[int, float]],
    float,
    list[tuple[float, float] | None],
    list[tuple[float, float, float] | None],
    list[tuple[float, ...] | None],
]:
    """Merge step runs into alternating (kind, duration) segments + RAM total
    + per-segment cache mixture / llm / serving params.

    With ``db_pooled``, each ``io_db`` step lowers to its own
    :data:`SEG_DB` segment — adjacent io_db steps must NOT merge, because
    each query releases its connection and re-acquires (joining the FIFO
    tail behind any waiters), exactly like two sequential awaits on a real
    pool and like the oracle's per-step FifoTokens discipline; otherwise
    io_db merges into plain IO exactly as before.

    Stochastic ``io_cache`` steps (hit/miss dynamics) lower to their own
    :data:`SEG_CACHE` segments carrying ``(hit_probability, miss_time)``
    in the returned ``cache`` list (aligned with the segments; None for
    deterministic segments); the segment duration is the HIT latency.

    ``llm_serve`` steps lower to a :data:`SEG_PREFILL` + :data:`SEG_DECODE`
    segment PAIR whose durations are the expected phase times; BOTH rows
    carry the same 10-tuple of serving params in the returned ``sv`` list
    (tin mean/var, tout mean/var, prefill s/token, prefill base, decode
    rate mean/var, kv MB/token, cost/token) so either segment row resolves
    the step's full dynamics.
    """
    segments: list[tuple[int, float]] = []
    cache: list[tuple[float, float] | None] = []
    llm: list[tuple[float, float, float] | None] = []
    sv: list[tuple[float, ...] | None] = []
    total_ram = 0.0
    for step in endpoint.steps:
        if getattr(step, "is_serving", False):
            params = (
                float(step.input_tokens.mean),
                float(step.input_tokens.variance),
                float(step.output_tokens.mean),
                float(step.output_tokens.variance),
                float(step.prefill_time_per_token_s),
                float(step.prefill_base_s),
                float(step.decode_tokens_per_s.mean),
                float(step.decode_tokens_per_s.variance),
                float(step.kv_mb_per_token),
                float(step.cost_per_token),
            )
            segments.append((SEG_PREFILL, step.expected_prefill_s))
            cache.append(None)
            llm.append(None)
            sv.append(params)
            segments.append((SEG_DECODE, step.expected_decode_s))
            cache.append(None)
            llm.append(None)
            sv.append(params)
            continue
        if step.is_ram:
            total_ram += step.quantity
            continue
        if step.is_cpu:
            kind = SEG_CPU
        elif step.is_stochastic_cache:
            kind = SEG_CACHE
        elif step.is_llm:
            kind = SEG_LLM
        elif db_pooled and step.kind == EndpointStepIO.DB:
            kind = SEG_DB
        else:
            kind = SEG_IO
        if (
            segments
            and segments[-1][0] == kind
            and kind not in (SEG_DB, SEG_CACHE, SEG_LLM, SEG_PREFILL, SEG_DECODE)
        ):
            segments[-1] = (kind, segments[-1][1] + step.quantity)
        else:
            segments.append((kind, step.quantity))
            cache.append(
                (float(step.cache_hit_probability), float(step.cache_miss_time))
                if kind == SEG_CACHE
                else None,
            )
            llm.append(
                (
                    float(step.llm_tokens_mean),
                    float(step.llm_time_per_token),
                    float(step.llm_cost_per_token),
                )
                if kind == SEG_LLM
                else None,
            )
            sv.append(None)
    return segments, total_ram, cache, llm, sv


# fastpath cache-placement sentinels (fp_cache_slot values < 0):
# a stochastic cache segment's miss-extra lands either in one of the
# CPU-burst pre-IO slots (slot index >= 0), in the trailing IO before the
# (single) DB segment, or in the trailing IO after it.
CACHE_PRE_DB = -2
CACHE_POST_DB = -3
CACHE_UNUSED = -1


def _fastpath_lowering(
    segs: list[tuple[int, float]],
    cache: list[tuple[float, float] | None],
) -> tuple[tuple[float, float, float], list[tuple[int, float, float]], str]:
    """Lower one endpoint's segments to the fast path's stochastic tables.

    Returns ``((db_pre, db_dur, db_post), cache_places, reason)``:

    - the trailing IO split around the endpoint's (single) :data:`SEG_DB`
      segment — ``db_pre`` seconds of plain/cache-hit IO after the last CPU
      burst, then the connection-holding query of ``db_dur`` seconds, then
      ``db_post`` (all zeros when the endpoint has no DB segment);
    - one ``(slot, miss_prob, miss_extra)`` triple per :data:`SEG_CACHE`
      segment: ``slot`` is the CPU-burst index whose pre-IO contains the
      segment, or :data:`CACHE_PRE_DB`/:data:`CACHE_POST_DB` for trailing
      placement; ``miss_extra`` is ``miss - hit`` duration;
    - a non-empty ``reason`` when the shape is outside the fast path's
      model (more than one DB segment, or a DB query before a CPU burst —
      its FIFO wait would feed back into the core-queue enqueue times).
    """
    n_cpu = sum(1 for k, _ in segs if k == SEG_CPU)
    db_seen = 0
    burst_idx = 0
    db_pre = db_dur = db_post = 0.0
    places: list[tuple[int, float, float]] = []
    for i, (kind, dur) in enumerate(segs):
        if kind == SEG_CPU:
            burst_idx += 1
            continue
        trailing = burst_idx >= n_cpu
        if kind == SEG_DB:
            if db_seen:
                return (0.0, 0.0, 0.0), [], "multiple DB queries per endpoint"
            if not trailing:
                return (
                    (0.0, 0.0, 0.0),
                    [],
                    "DB query before a CPU burst (pool wait feeds back "
                    "into the core queue)",
                )
            db_seen = 1
            db_dur = dur
            continue
        if kind == SEG_CACHE:
            hit_prob, miss = cache[i]
            slot = (
                burst_idx
                if not trailing
                else (CACHE_POST_DB if db_seen else CACHE_PRE_DB)
            )
            places.append((slot, 1.0 - hit_prob, miss - dur))
        # SEG_IO / SEG_CACHE hit duration accumulates into the split
        if trailing:
            if db_seen:
                db_post += dur
            else:
                db_pre += dur
    return (db_pre, db_dur, db_post), places, ""


def _burst_decomposition(
    segs: list[tuple[int, float]],
) -> tuple[list[float], list[float], float]:
    """Rewrite an alternating segment program as core-queue visits.

    Returns ``(burst_dur, burst_pre_io, post_io)``: the k-th CPU burst holds a
    core for ``burst_dur[k]`` seconds and is *enqueued* ``burst_pre_io[k]``
    seconds after the previous burst completed (IO sleeps hold no core —
    `/root/reference/src/asyncflow/runtime/actors/server.py:235-255`);
    ``post_io`` runs after the last burst.  A pure-IO endpoint has no bursts
    and only ``post_io``.
    """
    burst_dur: list[float] = []
    burst_pre: list[float] = []
    io_acc = 0.0
    for kind, dur in segs:
        if kind == SEG_CPU:
            burst_pre.append(io_acc)
            burst_dur.append(dur)
            io_acc = 0.0
        else:  # SEG_IO and SEG_DB both hold no core
            io_acc += dur
    return burst_dur, burst_pre, io_acc


@dataclass
class StaticPlan:
    """Dense arrays describing one scenario family for the batched engine."""

    # ---- sizes ----
    n_servers: int
    n_edges: int
    n_lb_edges: int
    max_endpoints: int
    max_segments: int

    # ---- edges ----
    edge_dist: np.ndarray  # (NE,) i32
    edge_mean: np.ndarray  # (NE,) f32
    edge_var: np.ndarray  # (NE,) f32 (0 when unused)
    edge_dropout: np.ndarray  # (NE,) f32

    # ---- entry chain: generator -> ... -> first stateful node ----
    entry_edges: np.ndarray  # (K,) i32
    entry_target_kind: int  # TARGET_LB or TARGET_SERVER
    entry_target: int  # server index when TARGET_SERVER else -1

    # ---- servers ----
    server_cores: np.ndarray  # (NS,) i32
    server_ram: np.ndarray  # (NS,) f32
    n_endpoints: np.ndarray  # (NS,) i32
    seg_kind: np.ndarray  # (NS, NEP, NSEG+1) i32 (END-terminated)
    seg_dur: np.ndarray  # (NS, NEP, NSEG+1) f32
    endpoint_ram: np.ndarray  # (NS, NEP) f32
    # core-queue visit view of the same programs (scan fast path):
    # burst k is enqueued burst_pre_io[...,k] seconds after burst k-1 ends
    max_bursts: int  # KB: max CPU bursts over all endpoints
    n_bursts: np.ndarray  # (NS, NEP) i32
    burst_dur: np.ndarray  # (NS, NEP, max(KB,1)) f32
    burst_pre_io: np.ndarray  # (NS, NEP, max(KB,1)) f32
    endpoint_post_io: np.ndarray  # (NS, NEP) f32
    exit_edge: np.ndarray  # (NS,) i32
    exit_kind: np.ndarray  # (NS,) i32 (TARGET_*)
    exit_target: np.ndarray  # (NS,) i32 (server idx when TARGET_SERVER)

    # ---- load balancer ----
    lb_algo: int  # 0 = round robin, 1 = least connections
    lb_edge_index: np.ndarray  # (EL,) i32 edge index per LB slot
    lb_target: np.ndarray  # (EL,) i32 server index per LB slot

    # ---- event injection ----
    # spike breakpoints: cumulative spike per edge on [t_k, t_{k+1})
    spike_times: np.ndarray  # (NB,) f32, spike_times[0] == 0
    spike_values: np.ndarray  # (NB, NE) f32
    # outage timeline (END before START on ties)
    timeline_times: np.ndarray  # (NTL,) f32
    timeline_down: np.ndarray  # (NTL,) i32 (1 = down, 0 = up)
    timeline_slot: np.ndarray  # (NTL,) i32 LB slot affected (-1 none)

    # ---- workload ----
    user_mean: float
    user_var: float  # < 0 => Poisson users, else truncated-Gaussian variance
    user_window: float
    req_per_user_per_sec: float


    # ---- run geometry ----
    horizon: float
    sample_period: float
    n_samples: int
    max_requests: int
    pool_size: int
    max_iterations: int

    # ---- id maps (for reporting) ----
    server_ids: list[str] = field(default_factory=list)
    edge_ids: list[str] = field(default_factory=list)

    # ---- fast-path eligibility (scan engine; see engines/jaxsim/fastpath) ----
    fastpath_ok: bool = False
    fastpath_reason: str = ""
    #: servers in topological order of the exit-chain DAG
    server_topo_order: list[int] = field(default_factory=list)
    #: per-server RAM admission treatment on the fast path: -1 = proven
    #: non-binding (not modeled), 0 = no RAM steps, k > 0 = FIFO admission
    #: queue with k concurrency slots (homogeneous needs, cap // need)
    ram_slots: np.ndarray = field(default_factory=lambda: np.empty(0, np.int32))
    #: least-connections support on the fast path: ring capacity per LB slot
    #: for outstanding delivery times (0 = round robin / no LB)
    lc_ring: int = 0
    #: highest nominal core utilization among multi-burst servers at the
    #: base workload (0 when no server is multi-burst).  The relaxation's
    #: validity envelope (RELAX_RHO_MAX) was proven at this rate; sweep
    #: overrides that scale the workload must keep
    #: relax_rho * scale <= RELAX_RHO_MAX (enforced by the sweep guard).
    relax_rho: float = 0.0
    #: (NS,) i32 modeled DB connection pool size; -1 = unlimited (no pool,
    #: or one proven non-binding and lowered away).  Servers with a value
    #: >= 0 have SEG_DB segments whose execution must hold one of the K
    #: FIFO connections (reference roadmap milestone 4, activated).
    server_db_pool: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    #: max workload-rate scale under which every lowered-away non-binding
    #: proof (DB pools, ready-queue caps) still holds; inf when nothing was
    #: lowered away.  Sweep overrides must stay below it.
    proof_rate_headroom: float = math.inf
    #: (NS,) i32 modeled ready-queue cap (load shedding); -1 = unbounded or
    #: proven effectively-unreachable and lowered away.  Servers with a
    #: value >= 0 shed requests that would join a full CPU ready queue
    #: (reference roadmap milestone 5).
    server_queue_cap: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    #: (NS,) i32 modeled socket/connection capacity; -1 = unbounded or
    #: proven effectively-unreachable.  Servers with a value >= 0 refuse
    #: arrivals when that many requests are already resident (reference
    #: roadmap milestone 1's socket capacity).
    server_conn_cap: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )

    def __post_init__(self) -> None:
        """Normalize legacy size-0 per-server arrays to explicit "-1 =
        unlimited" vectors ONCE, so no engine needs a per-call-site
        fallback (ADVICE r3: a size-0 ``server_db_pool`` handed the C++
        core a non-null pointer to a 0-length buffer; the jax engines had
        the same latent shape hazard)."""
        for name in ("server_db_pool", "server_queue_cap", "server_conn_cap"):
            if not getattr(self, name).size:
                setattr(self, name, np.full(self.n_servers, -1, np.int32))
        for name in (
            "server_rate_limit",
            "server_queue_timeout",
            "server_brownout_q",
        ):
            if not getattr(self, name).size:
                setattr(self, name, np.full(self.n_servers, -1.0, np.float32))
        for name in ("server_brownout_cpu", "server_brownout_ram"):
            if not getattr(self, name).size:
                setattr(self, name, np.ones(self.n_servers, np.float32))
        # serving budgets: hand-built / legacy plans get explicit
        # "-1 = unlimited" vectors like every other per-server control
        if not self.serve_tokens.size:
            self.serve_tokens = np.full(self.n_servers, -1.0, np.float32)
        if not self.serve_slots.size:
            self.serve_slots = np.full(self.n_servers, -1, np.int32)
        if not self.serve_evict_max.size:
            self.serve_evict_max = np.full(self.n_servers, 3, np.int32)
        if not self.server_rate_burst.size:
            self.server_rate_burst = np.zeros(self.n_servers, np.int32)
        # hand-built plans: identity fault tables at the plan's own widths
        if self.fault_srv_down.shape[1] != self.n_servers:
            self.fault_srv_times = np.zeros(1, np.float32)
            self.fault_srv_down = np.zeros((1, self.n_servers), np.int32)
        if self.fault_edge_lat.shape[1] != self.n_edges:
            self.fault_edge_times = np.zeros(1, np.float32)
            self.fault_edge_lat = np.ones((1, self.n_edges), np.float32)
            self.fault_edge_drop = np.zeros((1, self.n_edges), np.float32)
        if not self.endpoint_cum.size and self.n_endpoints.size:
            # uniform selection table for hand-built plans, at the SAME
            # row stride as every other per-endpoint array (the native
            # core indexes rows by max_endpoints)
            cum = np.ones((self.n_servers, max(self.max_endpoints, 1)), np.float32)
            for s in range(self.n_servers):
                k = max(int(self.n_endpoints[s]), 1)
                cum[s, :k] = (np.arange(1, k + 1) / k).astype(np.float32)
            self.endpoint_cum = cum

    @property
    def n_generators(self) -> int:
        """Workload sources; 0-size gen arrays mean a legacy single."""
        return max(int(self.gen_user_mean.shape[0]), 1)

    @property
    def has_queue_cap(self) -> bool:
        """True when any server's ready-queue cap is actually modeled."""
        return bool(np.any(self.server_queue_cap >= 0))

    @property
    def has_conn_cap(self) -> bool:
        """True when any server's connection capacity is actually modeled."""
        return bool(np.any(self.server_conn_cap >= 0))
    #: (NS, NEP, NSEG+1) f32 — SEG_CACHE hit probability (0 elsewhere) and
    #: miss latency; seg_dur holds the hit latency.
    seg_hit_prob: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    seg_miss_dur: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )

    #: (NS,) f32 modeled token-bucket refill rate (requests/s); -1 = no
    #: limiter or one proven effectively-unreachable and lowered away.
    server_rate_limit: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float32),
    )
    #: (NS,) i32 token-bucket capacity for modeled limiters (0 elsewhere).
    server_rate_burst: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    #: (NS,) f32 modeled ready-queue deadline (seconds); -1 = none or
    #: proven unreachable.  Checked at dequeue (see OverloadPolicy).
    server_queue_timeout: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float32),
    )
    #: LB circuit breaker (0 threshold = not modeled): consecutive-failure
    #: threshold, cooldown seconds, half-open probe slots.
    breaker_threshold: int = 0
    breaker_cooldown: float = 0.0
    breaker_probes: int = 0
    #: True when a configured breaker was lowered away because no failure
    #: channel exists — sweep overrides that could create one (raising
    #: LB-edge dropout) must be refused.
    breaker_lowered: bool = False

    #: fast-path stochastic tables (docstring: :func:`_fastpath_lowering`).
    #: (NS, NEP) f32 split of the trailing IO around the single DB segment
    #: (all zeros when no endpoint queries a modeled pool) ...
    fp_db_pre: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0), np.float32),
    )
    fp_db_dur: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0), np.float32),
    )
    fp_db_post: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0), np.float32),
    )
    #: ... and (NS, NEP, CMAX) cache-mixture placements: burst slot (or
    #: CACHE_PRE_DB/CACHE_POST_DB/CACHE_UNUSED), miss probability, and
    #: miss-minus-hit duration extra per stochastic cache segment.
    fp_cache_slot: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.int32),
    )
    fp_cache_miss_prob: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    fp_cache_extra: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )

    #: (NS, NEP) f32 cumulative endpoint-selection probabilities (uniform
    #: when every selection_weight is the default; padded columns = 1).
    endpoint_cum: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0), np.float32),
    )
    #: (G,) per-generator workload params (multi-generator superposition;
    #: G == 1 mirrors the scalar fields above).  Entry chains are
    #: (G, L) edge indexes, -1-padded, with per-generator lengths and
    #: entry targets; ``entry_edges``/``entry_target*`` stay generator 0's
    #: chain for single-generator consumers.
    gen_user_mean: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    gen_user_var: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    gen_window: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    gen_rate: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    gen_entry_edges: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0), np.int32),
    )
    gen_entry_len: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    gen_entry_target_kind: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    gen_entry_target: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    #: (G,) per-generator fast-path slot budgets (the per-stream 6-sigma
    #: count bounds; the multi-generator fast engine's slot axis is their
    #: sum, each stream owning a static contiguous slice)
    gen_slots: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int64),
    )
    #: (NS, NEP, NSEG+1) f32 SEG_LLM call dynamics: Poisson output-token
    #: mean, decode seconds per token, and cost units per token.
    seg_llm_tokens: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    seg_llm_tpt: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    seg_llm_cost: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )

    #: serving subsystem (asyncflow_tpu/serving): SEG_PREFILL/SEG_DECODE
    #: per-segment dynamics, duplicated on both rows of each pair.
    #: (NS, NEP, NSEG+1) f32 each; empty (0,0,0) when no llm_serve step.
    sv_tin_mean: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_tin_var: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_tout_mean: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_tout_var: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_prefill_tpt: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_prefill_base: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_rate_mean: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_rate_var: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_kv_mb: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    sv_cost: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0, 0), np.float32),
    )
    #: per-server continuous-batching budgets (ServingPolicy collapsed):
    #: resident-token budget = min(max_batch_tokens, kv_cache_mb / max
    #: kv_mb_per_token over serving steps); -1 = unlimited.
    serve_tokens: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float32),
    )
    #: (NS,) i32 concurrent-request batch slots; -1 = unlimited.
    serve_slots: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    #: (NS,) i32 evictions tolerated per request before terminal reject.
    serve_evict_max: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    #: trace-replay arrival table (serving/trace_replay): (R,) f64 sorted
    #: spawn times; (R,) f32 per-request token presets (-1 = draw).
    replay_times: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    replay_tok_in: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float32),
    )
    replay_tok_out: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float32),
    )

    #: resilience fault tables (compiler/faults.py): piecewise-constant
    #: breakpoints with a leading identity row at t = 0.  (K,) change
    #: times + (K, NS) outage flags; (M,) change times + (M, NE)
    #: multiplicative latency factors and additive dropout boosts.
    fault_srv_times: np.ndarray = field(
        default_factory=lambda: np.zeros(1, np.float32),
    )
    fault_srv_down: np.ndarray = field(
        default_factory=lambda: np.empty((1, 0), np.int32),
    )
    fault_edge_times: np.ndarray = field(
        default_factory=lambda: np.zeros(1, np.float32),
    )
    fault_edge_lat: np.ndarray = field(
        default_factory=lambda: np.empty((1, 0), np.float32),
    )
    fault_edge_drop: np.ndarray = field(
        default_factory=lambda: np.empty((1, 0), np.float32),
    )
    #: client retry policy scalars (compiler/faults.py RetryScalars);
    #: retry_timeout < 0 = no policy.  budget_tokens < 0 = unlimited.
    retry_timeout: float = -1.0
    retry_max_attempts: int = 1
    retry_backoff_base: float = 0.0
    retry_backoff_mult: float = 1.0
    retry_backoff_cap: float = 0.0
    retry_jitter: float = 0.0
    retry_budget_tokens: float = -1.0
    retry_budget_refill: float = 0.0
    #: tail-tolerance scalars (compiler/faults.py HedgeScalars /
    #: HealthScalars): client hedging (hedge_delay < 0 = none) and the
    #: LB's per-target EWMA health gate (health_alpha <= 0 = none).
    hedge_delay: float = -1.0
    hedge_max: int = 0
    hedge_cancel: int = 1
    health_alpha: float = 0.0
    health_threshold: float = 1.0
    health_readmit: float = -1.0
    #: (NS,) f32 brownout ready-queue threshold (-1 = no brownout) and
    #: the degraded-profile scale factors served above it (1 elsewhere).
    server_brownout_q: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float32),
    )
    server_brownout_cpu: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float32),
    )
    server_brownout_ram: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float32),
    )
    #: chaos-campaign hazard model (compiler/hazards.py HazardSpec): (D,)
    #: per-domain MTBF/MTTR duration laws (_DIST_IDS codes + mean/scale),
    #: edge degrade magnitudes, and (D, NS)/(D, NE) blast-group target
    #: masks.  Size 0 = no hazard model.  The per-scenario window tables
    #: are NOT plan state — they are sampled at sweep time from
    #: (seed, scenario index) so the plan digest stays seed-independent.
    hz_mtbf_dist: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    hz_mtbf_mean: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    hz_mtbf_var: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    hz_mttr_dist: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.int32),
    )
    hz_mttr_mean: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    hz_mttr_var: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    hz_lat_factor: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    hz_drop_boost: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.float64),
    )
    hz_srv_targets: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0), np.int8),
    )
    hz_edge_targets: np.ndarray = field(
        default_factory=lambda: np.empty((0, 0), np.int8),
    )
    #: window-slot budget F per (scenario, domain); truncation past it is
    #: counted, never silent (hazard_truncated scorecard counter).
    hz_max_faults: int = 0

    @property
    def has_weighted_endpoints(self) -> bool:
        """True when any server's selection weights deviate from uniform."""
        if not self.endpoint_cum.size:
            return False
        for s in range(self.n_servers):
            k = int(self.n_endpoints[s])
            if k > 1:
                uniform = np.arange(1, k + 1, dtype=np.float64) / k
                if not np.allclose(self.endpoint_cum[s, :k], uniform, atol=1e-6):
                    return True
        return False

    @property
    def has_llm(self) -> bool:
        """True when any segment carries LLM call dynamics."""
        return bool(self.seg_llm_tokens.size and np.any(self.seg_llm_tokens > 0))

    @property
    def has_stochastic_cache(self) -> bool:
        """True when any segment is a cache hit/miss mixture."""
        return bool(self.seg_hit_prob.size and np.any(self.seg_hit_prob > 0))

    @property
    def has_serving(self) -> bool:
        """True when any segment is an LLM serving prefill/decode pair."""
        return bool(np.any(self.seg_kind == SEG_PREFILL))

    @property
    def has_replay(self) -> bool:
        """True when a trace-replay arrival table replaces the generator."""
        return bool(self.replay_times.size)

    @property
    def has_rate_limit(self) -> bool:
        """True when any server's token-bucket limiter is actually modeled."""
        return bool(np.any(self.server_rate_limit >= 0))

    @property
    def has_queue_timeout(self) -> bool:
        """True when any server's dequeue deadline is actually modeled."""
        return bool(np.any(self.server_queue_timeout >= 0))

    @property
    def has_db_pool(self) -> bool:
        """True when any server's connection pool is actually modeled."""
        return bool(np.any(self.server_db_pool >= 0))

    @property
    def has_faults(self) -> bool:
        """True when any fault window actually mutates a server or edge."""
        return bool(
            np.any(self.fault_srv_down != 0)
            or np.any(self.fault_edge_lat != 1.0)
            or np.any(self.fault_edge_drop != 0.0),
        )

    @property
    def has_hazards(self) -> bool:
        """True when a chaos-campaign hazard model is lowered — i.e.
        fault windows are SAMPLED per scenario rather than (only)
        hand-authored.  The routing predicate behind the ``hazard.*``
        fences."""
        return bool(self.hz_mtbf_mean.size) and self.hz_max_faults > 0

    #: per-domain server/edge blast-group membership collapsed over
    #: domains — the static gates engines use to decide which per-server
    #: branches must carry the fault check at trace time.

    @property
    def hz_srv_mask(self) -> np.ndarray:
        """(NS,) bool: server is targeted by some failure domain."""
        if not self.hz_srv_targets.size:
            return np.zeros(self.n_servers, bool)
        return np.asarray(self.hz_srv_targets).any(axis=0)

    @property
    def hz_edge_mask(self) -> np.ndarray:
        """(NE,) bool: edge is targeted by some failure domain."""
        if not self.hz_edge_targets.size:
            return np.zeros(self.n_edges, bool)
        return np.asarray(self.hz_edge_targets).any(axis=0)

    @property
    def has_retry(self) -> bool:
        """True when a client retry/timeout policy is modeled."""
        return self.retry_timeout > 0

    @property
    def has_hedge(self) -> bool:
        """True when client-side hedged requests are modeled."""
        return self.hedge_delay > 0

    @property
    def has_health(self) -> bool:
        """True when the LB's EWMA health gate is modeled."""
        return self.health_alpha > 0

    @property
    def has_brownout(self) -> bool:
        """True when any server's brownout degraded mode is modeled."""
        return bool(np.any(self.server_brownout_q >= 0))

    @property
    def has_tail_tolerance(self) -> bool:
        """True when any tail-tolerance policy (hedge/health/brownout)
        is modeled — the routing predicate behind the
        ``tail_tolerance.*`` fences."""
        return self.has_hedge or self.has_health or self.has_brownout

    def array_digest(self) -> str:
        """Stable hash of every lowered plan array and scalar — the part
        of a sweep-checkpoint identity that tracks plan-level semantics,
        so ANY future plan field (fault tables, retry scalars, ...)
        invalidates stale checkpoints without a schema bump."""
        import dataclasses
        import hashlib

        digest = hashlib.sha256()
        for f in dataclasses.fields(self):
            value = getattr(self, f.name)
            digest.update(f.name.encode())
            if isinstance(value, np.ndarray):
                digest.update(str(value.dtype).encode())
                digest.update(str(value.shape).encode())
                digest.update(np.ascontiguousarray(value).tobytes())
            else:
                digest.update(repr(value).encode())
        return digest.hexdigest()

    @property
    def n_gauges(self) -> int:
        """Gauge layout: [edge conns | ready | io | ram] per component."""
        return self.n_edges + 3 * self.n_servers

    # single source of truth for the gauge array layout ------------------

    def gauge_edge(self, edge_idx: int) -> int:
        return edge_idx

    def gauge_ready(self, server_idx: int) -> int:
        return self.n_edges + server_idx

    def gauge_io(self, server_idx: int) -> int:
        return self.n_edges + self.n_servers + server_idx

    def gauge_ram(self, server_idx: int) -> int:
        return self.n_edges + 2 * self.n_servers + server_idx


def _server_entry_rates(payload: SimulationPayload) -> np.ndarray | None:
    """(NS,) nominal request rate into each server.

    The entry chain is walked ``generator -> (client ->)* first LB/server``
    (mirroring the lowering); an LB spreads the rate uniformly over covered
    servers (round-robin is uniform, least-connections levels load), and
    server->server chains pass their rate downstream in topological order.
    Returns None when the server chain graph has a cycle (rates undefined;
    callers must be conservative).  Dropout is ignored — rates are upper
    bounds used by non-binding proofs.
    """
    servers = payload.topology_graph.nodes.servers
    server_index = {server.id: s for s, server in enumerate(servers)}
    lb = payload.topology_graph.nodes.load_balancer
    out_edge = {e.source: e for e in payload.topology_graph.edges}

    srv_rate = np.zeros(len(servers))
    # entry deposits: every generator's chain lands its rate on a server
    # or spreads it over the LB cover (multi-generator workloads superpose)
    for workload in payload.generators:
        rate = (
            float(workload.avg_active_users.mean)
            * float(workload.avg_request_per_minute_per_user.mean)
            / 60.0
        )
        node = workload.id
        for _ in range(len(payload.topology_graph.edges) + 1):
            e = out_edge.get(node)
            if e is None:
                break
            if e.target in server_index:
                srv_rate[server_index[e.target]] += rate
                break
            if lb is not None and e.target == lb.id:
                covered = sorted(lb.server_covered)
                for sid in covered:
                    srv_rate[server_index[sid]] += rate / len(covered)
                break
            node = e.target

    # server -> server chain edges, propagated in topological order
    child = {}
    indeg = [0] * len(servers)
    for server in servers:
        e = out_edge.get(server.id)
        if e is not None and e.target in server_index:
            child[server_index[server.id]] = server_index[e.target]
            indeg[server_index[e.target]] += 1
    frontier = [s for s in range(len(servers)) if indeg[s] == 0]
    seen = 0
    while frontier:
        s = frontier.pop()
        seen += 1
        t = child.get(s)
        if t is not None:
            srv_rate[t] += srv_rate[s]
            indeg[t] -= 1
            if indeg[t] == 0:
                frontier.append(t)
    if seen != len(servers):
        return None  # cycle: no well-defined rates
    return srv_rate


def _pad_chains(chains: list[list[int]]) -> np.ndarray:
    """(G, L) entry-edge chains, -1-padded to the longest."""
    width = max(len(c) for c in chains)
    out = np.full((len(chains), width), -1, np.int32)
    for g, c in enumerate(chains):
        out[g, : len(c)] = c
    return out


def _server_db_hold(server) -> float:
    """Worst-case per-request DB-connection hold time (seconds): the max
    over endpoints of the summed ``io_db`` step durations.  Single source
    for the pool non-binding proof and the request-pool capacity estimate —
    the two must never disagree (ADVICE r3)."""
    return max(
        (
            sum(
                float(step.quantity)
                for step in ep.steps
                if step.is_io and step.kind == EndpointStepIO.DB
            )
            for ep in server.endpoints
        ),
        default=0.0,
    )


def _estimate_capacity(payload: SimulationPayload) -> tuple[int, int]:
    """(max_requests, pool_size) estimates.

    The pool must hold every concurrently-live request, including queue
    backlog when a server resource saturates.  We bound backlog by a fluid
    model: sustained overload accumulates ``(rate - capacity) * horizon``
    waiting requests, and bursty user re-draws add a transient term over one
    sampling window.  Overflow is still possible in pathological scenarios —
    the engine counts and surfaces it (``overflow_dropped``) rather than
    silently skewing percentiles.
    """
    settings = payload.sim_settings
    horizon = float(settings.total_simulation_time)
    # aggregate over generators: counts of independent sources add, and so
    # do their variances (multi-generator workloads superpose)
    rate = 0.0
    users = 0.0
    count_var = 0.0
    max_window = 0.0
    for workload in payload.generators:
        g_users, g_rate, window, g_count_var = _workload_count_model(
            workload, horizon,
        )
        users += g_users
        rate += g_rate
        max_window = max(max_window, window)
        # independent streams: total-count variances add (each stream's
        # g_count_var already carries its Poisson + user-draw parts)
        count_var += g_count_var
    # client retries amplify offered load: every logical request can spawn
    # up to max_attempts issues, and orphaned (timed-out) attempts keep
    # consuming server resources until they drain — scale the capacity
    # bounds by the attempt cap (an upper bound on the amplification)
    if payload.retry_policy is not None:
        amp = float(payload.retry_policy.max_attempts)
        rate *= amp
        count_var *= amp * amp
    # hedging amplifies the same way: every attempt can spawn up to
    # max_hedges racing duplicates, and uncancelled losers keep consuming
    # server resources until they drain
    if payload.hedge_policy is not None:
        amp = 1.0 + float(payload.hedge_policy.max_hedges)
        rate *= amp
        count_var *= amp * amp
    expected = rate * horizon
    max_requests = int(expected + 6.0 * math.sqrt(max(count_var, 1.0)) + 64)

    # ~3-sigma burst of the windowed user draw
    burst_rate = rate * (1.0 + 3.0 / math.sqrt(max(users, 1.0)))

    residence_max = 0.0
    backlog = 0.0
    burst_backlog = 0.0
    for server in payload.topology_graph.nodes.servers:
        cpu_req = 0.0
        io_req = 0.0
        ram_req = 0.0
        for endpoint in server.endpoints:
            segs, ram, cache, llm, sv = _compile_endpoint(endpoint)
            # capacity bounds use the worst-case duration of stochastic
            # segments — cache: the miss latency; llm: a 6-sigma token
            # draw; serving: a 6-sigma prompt/sequence draw — relabeled
            # SEG_IO so they enter the io/residence sums below (all are
            # IO sleeps)
            def _worst_seg(i: int, k: int, d: float) -> tuple[int, float]:
                if cache[i] is not None:
                    return (SEG_IO, max(d, cache[i][1]))
                if llm[i] is not None:
                    m, tpt, _ = llm[i]
                    return (SEG_IO, d + (m + 6.0 * math.sqrt(max(m, 1.0))) * tpt)
                if sv[i] is not None:
                    tin_m, tin_v, tout_m, tout_v, tpt, base, rate_m, rate_v, _, _ = sv[i]
                    if k == SEG_PREFILL:
                        tin = tin_m + 6.0 * math.sqrt(tin_v)
                        return (SEG_IO, base + tin * tpt)
                    tout = tout_m + 6.0 * math.sqrt(tout_v)
                    rate = max(rate_m - 6.0 * math.sqrt(rate_v), 0.1 * rate_m)
                    return (SEG_IO, tout / rate)
                return (k, d)

            segs = [_worst_seg(i, k, d) for i, (k, d) in enumerate(segs)]
            cpu_req = max(
                cpu_req,
                sum(dur for kind, dur in segs if kind == SEG_CPU),
            )
            io_req = max(io_req, sum(dur for kind, dur in segs if kind == SEG_IO))
            ram_req = max(ram_req, ram)
        residence = cpu_req + io_req
        residence_max = max(residence_max, residence)
        capacity = math.inf
        if cpu_req > 0:
            capacity = min(capacity, server.server_resources.cpu_cores / cpu_req)
        if ram_req > 0 and residence > 0:
            concurrent = server.server_resources.ram_mb / ram_req
            capacity = min(capacity, concurrent / residence)
        pool_k = server.server_resources.db_connection_pool
        if pool_k is not None:
            db_req = _server_db_hold(server)
            if db_req > 0:
                # a binding K-connection pool caps throughput at
                # K / hold-time; saturated pooled workloads park FIFO
                # waiters in the request pool, so the fluid backlog must
                # see the pool as a capacity (ADVICE r3: without this,
                # pooled saturation sweeps overflow unless pool_size is
                # set by hand)
                capacity = min(capacity, float(pool_k) / db_req)
        if capacity < math.inf:
            backlog += max(0.0, rate - capacity) * horizon
            # conservative across generators: the longest sampling window
            # sustains a 3-sigma burst the longest
            burst_backlog += max(0.0, burst_rate - capacity) * min(
                max_window, horizon,
            )

    # spikes park in-flight requests on an edge, and their release floods the
    # downstream queue: budget rate x (max concurrent spike) per edge, twice
    spike_delay = 0.0
    for event in payload.events or []:
        if event.start.spike_s is not None:
            spike_delay += float(event.start.spike_s)

    edge_delay = sum(edge.latency.mean for edge in payload.topology_graph.edges)
    in_flight = rate * (residence_max + edge_delay + 2.0 * spike_delay)
    want = 4.0 * in_flight + 1.5 * (backlog + burst_backlog) + 64.0
    pool = int(2 ** math.ceil(math.log2(max(64.0, want))))
    return max_requests, min(pool, 32768)


def _workload_count_model(workload, horizon: float) -> tuple[float, float, float, float]:
    """(users, rate, window, count_var) of one stream's total arrival count.

    ``count_var`` is the Poisson part plus the windowed user-draw part —
    THE variance model behind both the aggregate ``max_requests`` bound
    (:func:`_estimate_capacity`) and the per-stream slot slices
    (:func:`_gen_slot_bounds`); one shared implementation keeps the two
    bounds in lockstep.
    """
    users = float(workload.avg_active_users.mean)
    rpu = float(workload.avg_request_per_minute_per_user.mean) / 60.0
    rate = users * rpu
    window = float(workload.user_sampling_window)
    users_var = (
        float(workload.avg_active_users.variance) ** 2
        if workload.avg_active_users.variance is not None
        else users  # Poisson users
    )
    n_windows = max(1.0, horizon / window)
    count_var = rate * horizon + n_windows * users_var * (rpu * window) ** 2
    return users, rate, window, count_var


def _gen_slot_bounds(payload: SimulationPayload) -> np.ndarray:
    """(G,) per-generator 6-sigma arrival-count bounds (the multi-generator
    fast path gives each stream its own static slot slice)."""
    horizon = float(payload.sim_settings.total_simulation_time)
    out = []
    for workload in payload.generators:
        _, rate, _, count_var = _workload_count_model(workload, horizon)
        out.append(
            int(rate * horizon + 6.0 * math.sqrt(max(count_var, 1.0)) + 64),
        )
    return np.array(out, np.int64)


def compile_payload(
    payload: SimulationPayload,
    *,
    pool_size: int | None = None,
) -> StaticPlan:
    """Lower a validated payload to a :class:`StaticPlan`."""
    from asyncflow_tpu.observability.telemetry import maybe_phase

    with maybe_phase("build_plan"):
        return _compile_payload(payload, pool_size=pool_size)


def _compile_payload(
    payload: SimulationPayload,
    *,
    pool_size: int | None = None,
) -> StaticPlan:
    graph = payload.topology_graph
    settings = payload.sim_settings
    servers = graph.nodes.servers
    edges = graph.edges
    client_id = graph.nodes.client.id
    lb = graph.nodes.load_balancer
    lb_id = lb.id if lb is not None else None

    server_index = {server.id: i for i, server in enumerate(servers)}
    edge_index = {edge.id: i for i, edge in enumerate(edges)}
    n_servers, n_edges = len(servers), len(edges)

    # ---- edges ----
    edge_dist = np.array(
        [_DIST_IDS[edge.latency.distribution] for edge in edges],
        dtype=np.int32,
    )
    edge_mean = np.array([edge.latency.mean for edge in edges], dtype=np.float32)
    edge_var = np.array(
        [edge.latency.variance or 0.0 for edge in edges],
        dtype=np.float32,
    )
    edge_dropout = np.array([edge.dropout_rate for edge in edges], dtype=np.float32)

    # ---- walk maps ----
    def _target_of(node_id: str) -> tuple[int, int]:
        if node_id in server_index:
            return TARGET_SERVER, server_index[node_id]
        if node_id == lb_id:
            return TARGET_LB, -1
        if node_id == client_id:
            return TARGET_CLIENT, -1
        msg = f"unroutable node {node_id!r}"
        raise ValueError(msg)

    out_edge_of: dict[str, int] = {}
    for edge in edges:
        if edge.source != lb_id:
            out_edge_of[edge.source] = edge_index[edge.id]

    # entry chains: generator -> (client ->)* first LB/server, one per
    # generator; generator 0's chain doubles as the legacy scalar fields
    def _entry_chain(gen_id: str) -> tuple[list[int], int, int]:
        chain: list[int] = []
        cursor = gen_id
        for _ in range(n_edges + 1):
            if cursor not in out_edge_of:
                msg = f"node {cursor!r} has no outgoing edge on the entry path"
                raise ValueError(msg)
            eidx = out_edge_of[cursor]
            chain.append(eidx)
            next_id = edges[eidx].target
            kind, target = _target_of(next_id)
            if kind in (TARGET_LB, TARGET_SERVER):
                return chain, kind, target
            cursor = next_id
        msg = "entry path does not reach a server or load balancer"
        raise ValueError(msg)

    generators = payload.generators
    gen_chains = [_entry_chain(g.id) for g in generators]
    entry_edges, kind, target = gen_chains[0]

    # ---- servers ----
    max_endpoints = max(len(server.endpoints) for server in servers)

    # DB connection pools (activates the reference's reserved
    # ServerResources.db_connection_pool field — its roadmap milestone 4,
    # `/root/reference/ROADMAP.md` §4, which the reference never wired up).
    # Tiered like RAM admission: a pool proven non-binding (K comfortably
    # above the 6-sigma Poisson bound on concurrent io_db holders,
    # Little's law at the server's burst-inflated entry rate) is not
    # modeled — io_db lowers to plain IO and every engine, including the
    # fast path, stays exact.  A pool that may bind lowers io_db to SEG_DB
    # segments: the event engines model the K-connection FIFO, and the
    # fast path declines the plan.
    srv_rates_est = _server_entry_rates(payload)
    users_est = sum(
        float(g.avg_active_users.mean) for g in payload.generators
    )
    # one burst-inflation model for the non-binding proof tiers here (DB
    # pools, queue caps).  _fastpath_analysis's lc_ring bound uses the
    # per-stream variance-summed refinement of the same 3-sigma model
    # (this pooled factor understates the burst on heterogeneous
    # superpositions); at G == 1 the two are identical.
    burst_factor = 1.0 + 3.0 / math.sqrt(max(users_est, 1.0))
    db_model: list[bool] = []
    proof_rate_headroom = math.inf
    for s, server in enumerate(servers):
        pool_k = server.server_resources.db_connection_pool
        if pool_k is None:
            db_model.append(False)
            continue
        db_dur = _server_db_hold(server)
        if db_dur <= 0:
            db_model.append(False)  # a pool with no io_db steps is inert
            continue
        if srv_rates_est is None:
            db_model.append(True)  # cyclic chain: no rate bound, model it
            continue
        burst = srv_rates_est[s] * burst_factor
        m = burst * db_dur
        binding = not pool_k >= m + 6.0 * math.sqrt(max(m, 1.0)) + 8.0
        db_model.append(binding)
        if not binding and pool_k > 8:
            # the proof holds up to a rate scale f: K >= f*m + 6*sqrt(f*m)+8
            # (sweep overrides that scale the workload past this must be
            # refused — the lowered-away pool could silently bind)
            t = (-6.0 + math.sqrt(36.0 + 4.0 * (pool_k - 8.0))) / 2.0
            proof_rate_headroom = min(
                proof_rate_headroom, (t * t) / max(m, 1e-12),
            )

    # Ready-queue caps (load shedding — reference roadmap milestone 5):
    # modeled only when the cap is actually reachable.  For a stable queue
    # (rho_b < 0.9, burst-inflated) the stationary queue-length tail is
    # geometrically bounded, so a cap with rho_b^(cap-16) < 1e-12 is
    # effectively unreachable and lowers away (every engine skips it; the
    # fast path stays exact).  Reachable caps are modeled by the event
    # engines and decline the fast path.
    queue_cap_model = np.full(n_servers, -1, dtype=np.int32)
    for s_i, server in enumerate(servers):
        cap = server.overload.max_ready_queue if server.overload else None
        if cap is None:
            continue
        cpu_dur = max(
            (
                sum(st.quantity for st in ep.steps if st.is_cpu)
                for ep in server.endpoints
            ),
            default=0.0,
        )
        if cpu_dur <= 0 or srv_rates_est is None:
            queue_cap_model[s_i] = cap if cpu_dur > 0 else -1
            continue
        cores = server.server_resources.cpu_cores
        rho_b = srv_rates_est[s_i] * burst_factor * cpu_dur / max(cores, 1)
        needed = (
            math.inf
            if rho_b >= 0.9
            else math.log(1e-12) / math.log(max(rho_b, 1e-9)) + 16.0
        )
        cap = min(cap, 2**31 - 1)  # int32 table; larger = unbounded anyway
        if cap >= needed:
            # lowered away; record the rate scale that keeps the proof
            rho_max = min(0.9, math.exp(math.log(1e-12) / max(cap - 16.0, 1.0)))
            proof_rate_headroom = min(
                proof_rate_headroom, rho_max / max(rho_b, 1e-12),
            )
        else:
            queue_cap_model[s_i] = cap

    # Socket / connection capacity (the reference roadmap's network
    # baseline, milestone 1): concurrent residents ~ rate x (residence +
    # core-queue waits) by Little's law; a capacity comfortably above the
    # burst-inflated bound is effectively unreachable and lowers away.
    # Reachable capacities refuse arrivals on the event engines.
    conn_cap_model = np.full(n_servers, -1, dtype=np.int32)
    for s_i, server in enumerate(servers):
        cap = server.overload.max_connections if server.overload else None
        if cap is None:
            continue
        cap = min(cap, 2**31 - 1)
        if srv_rates_est is None or db_model[s_i]:
            # no rate bound (cyclic chain), or a MODELED (binding) DB pool
            # whose queue waits the residence bound below cannot see —
            # always model the capacity
            conn_cap_model[s_i] = cap
            continue

        def _worst(step) -> float:
            # worst-case duration: stochastic cache steps may sleep the
            # miss latency; llm/serving steps a 6-sigma token draw
            if getattr(step, "is_serving", False):
                return step.worst_duration
            if step.is_stochastic_cache:
                return max(float(step.quantity), float(step.cache_miss_time))
            if step.is_llm:
                m = float(step.llm_tokens_mean)
                return float(step.quantity) + (
                    m + 6.0 * math.sqrt(max(m, 1.0))
                ) * float(step.llm_time_per_token)
            return float(step.quantity)

        residence = max(
            (
                sum(_worst(st) for st in ep.steps if not st.is_ram)
                for ep in server.endpoints
            ),
            default=0.0,
        )
        cpu_dur = max(
            (
                sum(st.quantity for st in ep.steps if st.is_cpu)
                for ep in server.endpoints
            ),
            default=0.0,
        )
        visits = max(
            (
                sum(1 for st in ep.steps if st.is_cpu)
                for ep in server.endpoints
            ),
            default=0,
        )
        max_ram = max(
            (
                sum(st.quantity for st in ep.steps if st.is_ram)
                for ep in server.endpoints
            ),
            default=0.0,
        )
        cores = server.server_resources.cpu_cores
        capacity_mb = float(server.server_resources.ram_mb)

        def conn_proof_holds(scale: float, cap=cap, residence=residence,
                             cpu_dur=cpu_dur, visits=visits, cores=cores,
                             max_ram=max_ram, capacity_mb=capacity_mb,
                             rate_here=srv_rates_est[s_i]) -> bool:
            burst = rate_here * burst_factor * scale
            rho = burst * cpu_dur / max(cores, 1)
            if rho >= 0.95:
                return False
            wait = visits * rho / (1.0 - rho) * cpu_dur / max(cores, 1)
            if max_ram > 0:
                # RAM admission waits are not in the residence bound: the
                # proof only holds while RAM itself is tier-1 non-binding
                # (same 4x margin as _fastpath_analysis)
                if capacity_mb / max_ram < 4.0 * burst * (residence + wait) + 4.0:
                    return False
            m = burst * (residence + wait)
            return cap >= 4.0 * m + 8.0

        if conn_proof_holds(1.0):
            # bisect the largest rate scale the proof still covers
            lo, hi = 1.0, 1e6
            for _ in range(48):
                mid = (lo + hi) / 2.0
                if conn_proof_holds(mid):
                    lo = mid
                else:
                    hi = mid
            proof_rate_headroom = min(proof_rate_headroom, lo)
        else:
            conn_cap_model[s_i] = cap

    # Rate limiting (reference roadmap milestone 5): a token bucket of
    # ``effective_burst`` tokens refilled at ``rate_limit_rps`` refuses
    # arrivals that find no whole token.  With burst-inflated demand
    # comfortably below the refill rate the bucket's deficit random walk
    # has negative drift and a geometrically bounded tail, so a bucket
    # with rho_rl^(burst-8) < 1e-12 can effectively never empty and the
    # limiter lowers away; otherwise it is modeled (event engines; the
    # fast path declines).
    rate_limit_model = np.full(n_servers, -1.0, dtype=np.float32)
    rate_burst_model = np.zeros(n_servers, dtype=np.int32)
    for s_i, server in enumerate(servers):
        rps = server.overload.rate_limit_rps if server.overload else None
        if rps is None:
            continue
        burst = int(server.overload.effective_burst)
        if srv_rates_est is None:
            rate_limit_model[s_i] = rps
            rate_burst_model[s_i] = burst
            continue
        rho_rl = srv_rates_est[s_i] * burst_factor / rps
        if rho_rl < 0.9 and rho_rl ** max(burst - 8.0, 1.0) < 1e-12:
            rho_max = min(
                0.9, math.exp(math.log(1e-12) / max(burst - 8.0, 1.0)),
            )
            proof_rate_headroom = min(
                proof_rate_headroom, rho_max / max(rho_rl, 1e-12),
            )
        else:
            rate_limit_model[s_i] = rps
            rate_burst_model[s_i] = burst

    # Queue-wait deadlines (reference roadmap milestone 5): a request
    # whose ready-queue wait exceeds ``queue_timeout_s`` abandons at
    # dequeue.  A wait of D needs ~D * cores / cpu_dur requests ahead in
    # the queue, so the queue-cap geometric tail bound applies with that
    # equivalent length; deadlines it proves unreachable lower away.
    queue_timeout_model = np.full(n_servers, -1.0, dtype=np.float32)
    for s_i, server in enumerate(servers):
        deadline = server.overload.queue_timeout_s if server.overload else None
        if deadline is None:
            continue
        cpu_dur = max(
            (
                sum(st.quantity for st in ep.steps if st.is_cpu)
                for ep in server.endpoints
            ),
            default=0.0,
        )
        if cpu_dur <= 0:
            continue  # no core queue: the deadline is inert
        if srv_rates_est is None:
            queue_timeout_model[s_i] = deadline
            continue
        cores = server.server_resources.cpu_cores
        rho_b = srv_rates_est[s_i] * burst_factor * cpu_dur / max(cores, 1)
        eq_len = deadline * cores / cpu_dur
        needed = (
            math.inf
            if rho_b >= 0.9
            else math.log(1e-12) / math.log(max(rho_b, 1e-9)) + 16.0
        )
        if eq_len >= needed:
            rho_max = min(
                0.9, math.exp(math.log(1e-12) / max(eq_len - 16.0, 1.0)),
            )
            proof_rate_headroom = min(
                proof_rate_headroom, rho_max / max(rho_b, 1e-12),
            )
        else:
            queue_timeout_model[s_i] = deadline

    compiled: list[
        list[tuple[list[tuple[int, float]], float, list, list, list]]
    ] = [
        [
            _compile_endpoint(ep, db_pooled=db_model[s])
            for ep in server.endpoints
        ]
        for s, server in enumerate(servers)
    ]
    server_db_pool = np.array(
        [
            server.server_resources.db_connection_pool if db_model[s] else -1
            for s, server in enumerate(servers)
        ],
        dtype=np.int32,
    )
    max_segments = max(
        (len(segs) for per_server in compiled for segs, *_ in per_server),
        default=0,
    )

    seg_kind = np.zeros((n_servers, max_endpoints, max_segments + 1), dtype=np.int32)
    seg_dur = np.zeros((n_servers, max_endpoints, max_segments + 1), dtype=np.float32)
    # SEG_CACHE mixtures: seg_dur holds the hit latency; these two hold the
    # hit probability (0 = deterministic segment) and the miss latency
    seg_hit_prob = np.zeros(
        (n_servers, max_endpoints, max_segments + 1), dtype=np.float32,
    )
    seg_miss_dur = np.zeros(
        (n_servers, max_endpoints, max_segments + 1), dtype=np.float32,
    )
    # SEG_LLM call dynamics: Poisson token mean, seconds and cost per token
    seg_llm_tokens = np.zeros(
        (n_servers, max_endpoints, max_segments + 1), dtype=np.float32,
    )
    seg_llm_tpt = np.zeros(
        (n_servers, max_endpoints, max_segments + 1), dtype=np.float32,
    )
    seg_llm_cost = np.zeros(
        (n_servers, max_endpoints, max_segments + 1), dtype=np.float32,
    )
    # SEG_PREFILL/SEG_DECODE serving dynamics (empty unless some endpoint
    # carries an llm_serve step — the engines statically prune on that)
    any_serving = any(
        sv_p is not None
        for per_server in compiled
        for *_, sv_list in per_server
        for sv_p in sv_list
    )
    sv_shape = (n_servers, max_endpoints, max_segments + 1) if any_serving else (0, 0, 0)
    sv_tables = {
        name: np.zeros(sv_shape, dtype=np.float32)
        for name in (
            "sv_tin_mean",
            "sv_tin_var",
            "sv_tout_mean",
            "sv_tout_var",
            "sv_prefill_tpt",
            "sv_prefill_base",
            "sv_rate_mean",
            "sv_rate_var",
            "sv_kv_mb",
            "sv_cost",
        )
    }
    _SV_ORDER = (
        "sv_tin_mean",
        "sv_tin_var",
        "sv_tout_mean",
        "sv_tout_var",
        "sv_prefill_tpt",
        "sv_prefill_base",
        "sv_rate_mean",
        "sv_rate_var",
        "sv_kv_mb",
        "sv_cost",
    )
    endpoint_ram = np.zeros((n_servers, max_endpoints), dtype=np.float32)
    # cumulative endpoint-selection probabilities (selection_weight; the
    # uniform default lowers to the same evenly-spaced table the
    # reference's uniform pick implies).  Padded columns carry 1.0 so a
    # searchsorted draw never lands on them.
    endpoint_cum = np.ones((n_servers, max_endpoints), dtype=np.float32)
    for s_i, server in enumerate(servers):
        w = np.array(
            [float(ep.selection_weight) for ep in server.endpoints],
            dtype=np.float64,
        )
        endpoint_cum[s_i, : len(w)] = np.cumsum(w / w.sum())
    n_endpoints = np.zeros(n_servers, dtype=np.int32)
    bursts = [
        [_burst_decomposition(segs) for segs, *_ in per_server]
        for per_server in compiled
    ]
    max_bursts = max(
        (len(dur) for per_server in bursts for dur, _, _ in per_server),
        default=0,
    )
    kb = max(max_bursts, 1)
    n_bursts = np.zeros((n_servers, max_endpoints), dtype=np.int32)
    burst_dur = np.zeros((n_servers, max_endpoints, kb), dtype=np.float32)
    burst_pre_io = np.zeros((n_servers, max_endpoints, kb), dtype=np.float32)
    endpoint_post_io = np.zeros((n_servers, max_endpoints), dtype=np.float32)
    for s, per_server in enumerate(compiled):
        n_endpoints[s] = len(per_server)
        for e, (segs, ram, cache, llm, sv) in enumerate(per_server):
            endpoint_ram[s, e] = ram
            for k, (seg_k, dur) in enumerate(segs):
                seg_kind[s, e, k] = seg_k
                seg_dur[s, e, k] = dur
                if cache[k] is not None:
                    seg_hit_prob[s, e, k] = cache[k][0]
                    seg_miss_dur[s, e, k] = cache[k][1]
                if llm[k] is not None:
                    seg_llm_tokens[s, e, k] = llm[k][0]
                    seg_llm_tpt[s, e, k] = llm[k][1]
                    seg_llm_cost[s, e, k] = llm[k][2]
                if sv[k] is not None:
                    for name, value in zip(_SV_ORDER, sv[k]):
                        sv_tables[name][s, e, k] = value
            dur_list, pre_list, post = bursts[s][e]
            n_bursts[s, e] = len(dur_list)
            burst_dur[s, e, : len(dur_list)] = dur_list
            burst_pre_io[s, e, : len(pre_list)] = pre_list
            endpoint_post_io[s, e] = post

    # fast-path stochastic tables: trailing-IO split around the DB segment
    # + cache-mixture placements (zero-filled where the endpoint has none;
    # _fastpath_analysis declines the shapes _fastpath_lowering rejects)
    fp_lowered = [
        [_fastpath_lowering(segs, cache) for segs, _, cache, *_ in per_server]
        for per_server in compiled
    ]
    cmax = max(
        (len(places) for per_server in fp_lowered for _, places, _ in per_server),
        default=0,
    )
    fp_db_pre = np.zeros((n_servers, max_endpoints), dtype=np.float32)
    fp_db_dur = np.zeros((n_servers, max_endpoints), dtype=np.float32)
    fp_db_post = np.zeros((n_servers, max_endpoints), dtype=np.float32)
    fp_cache_slot = np.full(
        (n_servers, max_endpoints, cmax), CACHE_UNUSED, dtype=np.int32,
    )
    fp_cache_miss_prob = np.zeros(
        (n_servers, max_endpoints, cmax), dtype=np.float32,
    )
    fp_cache_extra = np.zeros((n_servers, max_endpoints, cmax), dtype=np.float32)
    for s, per_server in enumerate(fp_lowered):
        for e, ((dpre, ddur, dpost), places, reason) in enumerate(per_server):
            if reason:
                continue  # analysis declines the plan; keep zeros
            fp_db_pre[s, e] = dpre
            fp_db_dur[s, e] = ddur
            fp_db_post[s, e] = dpost
            for j, (slot, miss_p, extra) in enumerate(places):
                fp_cache_slot[s, e, j] = slot
                fp_cache_miss_prob[s, e, j] = miss_p
                fp_cache_extra[s, e, j] = extra

    # ---- serving budgets: ServingPolicy collapsed to per-server scalars.
    # The resident-token budget IS the KV-cache container: min of the
    # explicit batch-token cap and kv_cache_mb / (max kv_mb_per_token over
    # the server's serving steps); -1 = unlimited.
    serve_tokens = np.full(n_servers, -1.0, dtype=np.float32)
    serve_slots = np.full(n_servers, -1, dtype=np.int32)
    serve_evict_max = np.full(n_servers, 3, dtype=np.int32)
    for s_i, server in enumerate(servers):
        pol = getattr(server, "serving", None)
        if pol is None:
            continue
        budget = math.inf
        if pol.max_batch_tokens is not None:
            budget = float(pol.max_batch_tokens)
        if pol.kv_cache_mb is not None:
            kv_max = max(
                (
                    float(st.kv_mb_per_token)
                    for ep in server.endpoints
                    for st in ep.steps
                    if getattr(st, "is_serving", False)
                ),
                default=0.0,
            )
            if kv_max > 0:
                budget = min(budget, float(pol.kv_cache_mb) / kv_max)
        if budget < math.inf:
            serve_tokens[s_i] = budget
        if pol.max_batch_requests is not None:
            serve_slots[s_i] = int(pol.max_batch_requests)
        serve_evict_max[s_i] = int(pol.max_evictions)

    # ---- trace-replay arrival table (single generator by schema contract)
    replay = generators[0].replay if len(generators) == 1 else None
    if replay is not None:
        replay_times = np.asarray(replay.times, dtype=np.float64)
        n_replay = len(replay.times)
        replay_tok_in = (
            np.asarray(replay.input_tokens, dtype=np.float32)
            if replay.input_tokens is not None
            else np.full(n_replay, -1.0, dtype=np.float32)
        )
        replay_tok_out = (
            np.asarray(replay.output_tokens, dtype=np.float32)
            if replay.output_tokens is not None
            else np.full(n_replay, -1.0, dtype=np.float32)
        )

    server_cores = np.array(
        [server.server_resources.cpu_cores for server in servers],
        dtype=np.int32,
    )
    server_ram = np.array(
        [server.server_resources.ram_mb for server in servers],
        dtype=np.float32,
    )

    exit_edge = np.full(n_servers, -1, dtype=np.int32)
    exit_kind = np.full(n_servers, TARGET_CLIENT, dtype=np.int32)
    exit_target = np.full(n_servers, -1, dtype=np.int32)
    for server in servers:
        s = server_index[server.id]
        if server.id not in out_edge_of:
            msg = f"server {server.id!r} has no outgoing edge"
            raise ValueError(msg)
        eidx = out_edge_of[server.id]
        exit_edge[s] = eidx
        kind_s, target_s = _target_of(edges[eidx].target)
        exit_kind[s] = kind_s
        exit_target[s] = target_s

    # ---- LB ----
    lb_slots = [edge_index[e.id] for e in edges if lb_id is not None and e.source == lb_id]
    lb_edge_index = np.array(lb_slots, dtype=np.int32)
    lb_target = np.array(
        [server_index[edges[eidx].target] for eidx in lb_slots],
        dtype=np.int32,
    )
    lb_algo = (
        1
        if lb is not None and lb.algorithms == LbAlgorithmsName.LEAST_CONNECTIONS
        else 0
    )

    # ---- resilience: fault windows + client retry policy ----
    from asyncflow_tpu.compiler.faults import (
        lower_faults,
        lower_health,
        lower_hedge,
        lower_retry,
    )

    fault_arrays = lower_faults(payload)
    retry = lower_retry(payload.retry_policy)

    # ---- chaos campaign: stochastic hazard model (compiler/hazards.py);
    # only the per-domain laws live on the plan — the per-scenario window
    # tables are sampled at sweep time from (seed, scenario index)
    from asyncflow_tpu.compiler.hazards import lower_hazards

    hazards = lower_hazards(payload)

    # ---- tail tolerance: hedging, LB health gate, server brownout ----
    # (hedging over a single target still helps when the primary is parked
    # in retry backoff, so no LB requirement; the health gate is LB-only
    # by schema shape)
    hedge = lower_hedge(payload.hedge_policy)
    health = lower_health(lb.health if lb is not None else None)
    brownout_q_model = np.full(n_servers, -1.0, dtype=np.float32)
    brownout_cpu_model = np.ones(n_servers, dtype=np.float32)
    brownout_ram_model = np.ones(n_servers, dtype=np.float32)
    for s_i, server in enumerate(servers):
        b_ov = server.overload
        if b_ov is None or b_ov.brownout_queue_threshold is None:
            continue
        # modeled whenever configured: the decision is per-request at
        # endpoint start (ready-queue length vs threshold), so there is
        # no non-binding proof — an unreachable threshold never fires
        brownout_q_model[s_i] = float(b_ov.brownout_queue_threshold)
        brownout_cpu_model[s_i] = float(b_ov.brownout_cpu_factor)
        brownout_ram_model[s_i] = float(b_ov.brownout_ram_factor)

    # Circuit breaker (reference roadmap milestone 5): modeled only when a
    # failure channel exists on some covered target — a modeled refusal /
    # shed / rate-limit / deadline on a target server, dropout on an LB
    # out-edge, a server-outage fault window on a covered server, or an
    # edge fault boosting dropout on an LB out-edge.  With no channel the
    # breaker can never trip and lowers away; ``breaker_lowered`` flags
    # the plan so sweep overrides that could CREATE a channel (raising
    # LB-edge dropout) are refused.
    breaker = lb.circuit_breaker if lb is not None else None
    breaker_threshold = 0
    breaker_cooldown = 0.0
    breaker_probes = 0
    breaker_lowered = False
    if breaker is not None and lb_slots:
        covered = {server_index[edges[eidx].target] for eidx in lb_slots}
        has_channel = (
            any(
                queue_cap_model[s_c] >= 0
                or conn_cap_model[s_c] >= 0
                or rate_limit_model[s_c] >= 0
                or queue_timeout_model[s_c] >= 0
                or bool(np.any(fault_arrays.srv_down[:, s_c] != 0))
                for s_c in covered
            )
            or any(float(edges[eidx].dropout_rate) > 0 for eidx in lb_slots)
            or any(
                bool(np.any(fault_arrays.edge_drop[:, eidx] > 0))
                for eidx in lb_slots
            )
        )
        if has_channel:
            breaker_threshold = int(breaker.failure_threshold)
            breaker_cooldown = float(breaker.cooldown_s)
            breaker_probes = int(breaker.half_open_probes)
        else:
            breaker_lowered = True

    # ---- events ----
    spikes: list[tuple[float, float, int]] = []  # (time, delta, edge)
    outages: list[tuple[float, int, int, int]] = []  # (time, start_mark, down, slot)
    lb_slot_of_server = {
        int(lb_target[slot]): slot for slot in range(len(lb_slots))
    }
    for event in payload.events or []:
        if event.start.kind == EventDescription.NETWORK_SPIKE_START:
            eidx = edge_index[event.target_id]
            spike = float(event.start.spike_s or 0.0)
            spikes.append((event.start.t_start, spike, eidx))
            spikes.append((event.end.t_end, -spike, eidx))
        else:
            sidx = server_index[event.target_id]
            slot = lb_slot_of_server.get(sidx, -1)
            outages.append((event.start.t_start, 1, 1, slot))
            outages.append((event.end.t_end, 0, 0, slot))

    # spike breakpoints (cumulative, superposed)
    change_times = sorted({0.0} | {t for t, _, _ in spikes})
    spike_times = np.array(change_times, dtype=np.float32)
    spike_values = np.zeros((len(change_times), n_edges), dtype=np.float32)
    time_pos = {t: i for i, t in enumerate(change_times)}
    deltas = np.zeros((len(change_times), n_edges), dtype=np.float32)
    for t, delta, eidx in spikes:
        deltas[time_pos[t], eidx] += delta
    spike_values = np.cumsum(deltas, axis=0).astype(np.float32)

    # outage timeline, END (up) before START (down) on ties
    outages.sort(key=lambda entry: (entry[0], entry[1]))
    timeline_times = np.array([t for t, _, _, _ in outages], dtype=np.float32)
    timeline_down = np.array([down for _, _, down, _ in outages], dtype=np.int32)
    timeline_slot = np.array([slot for _, _, _, slot in outages], dtype=np.int32)

    # ---- capacities ----
    max_requests, pool_estimate = _estimate_capacity(payload)
    if replay is not None:
        # a replayed scenario must reproduce the log's arrival count
        # exactly — never let the stochastic capacity model under-bound it
        max_requests = max(max_requests, len(replay.times) + 64)
    pool = pool_size or pool_estimate
    events_per_request = (
        2 * (len(entry_edges) + 2)  # spawn + entry hops + lb + exits
        + 3 * (max_segments + 1)  # segment starts/ends + grants
        + 4
    )
    if any_serving:
        # eviction headroom: each tolerated eviction replays the pair's
        # segments plus park/grant/release bookkeeping (formula unchanged
        # for non-serving plans)
        evict_amp = int(serve_evict_max.max()) + 1
        events_per_request += evict_amp * (3 * (max_segments + 1) + 4)
    max_iterations = max_requests * events_per_request + len(outages) + 1024

    horizon = float(settings.total_simulation_time)
    sample_period = float(settings.sample_period_s)
    n_samples = max(0, math.ceil(round(horizon / sample_period, 9)) - 1)

    fastpath_ok, fastpath_reason, topo, ram_slots, lc_ring, relax_rho = (
        _fastpath_analysis(
            payload,
            compiled,
            exit_kind,
            exit_target,
            lb_algo,
            len(outages),
            lb_edge_means=[float(edge_mean[e]) for e in lb_slots],
            max_spike=float(spike_values.max()) if spike_values.size else 0.0,
            server_queue_cap=queue_cap_model,
            server_conn_cap=conn_cap_model,
            server_db_pool=server_db_pool,
            fp_lowered=fp_lowered,
            server_rate_limit=rate_limit_model,
            server_queue_timeout=queue_timeout_model,
            breaker_threshold=breaker_threshold,
            gen_targets=[(int(k), int(t)) for _, k, t in gen_chains],
        )
    )

    return StaticPlan(
        n_servers=n_servers,
        n_edges=n_edges,
        n_lb_edges=len(lb_slots),
        max_endpoints=max_endpoints,
        max_segments=max_segments,
        edge_dist=edge_dist,
        edge_mean=edge_mean,
        edge_var=edge_var,
        edge_dropout=edge_dropout,
        entry_edges=np.array(entry_edges, dtype=np.int32),
        entry_target_kind=kind,
        entry_target=target,
        server_cores=server_cores,
        server_ram=server_ram,
        n_endpoints=n_endpoints,
        seg_kind=seg_kind,
        seg_dur=seg_dur,
        endpoint_ram=endpoint_ram,
        endpoint_cum=endpoint_cum,
        max_bursts=max_bursts,
        n_bursts=n_bursts,
        burst_dur=burst_dur,
        burst_pre_io=burst_pre_io,
        endpoint_post_io=endpoint_post_io,
        exit_edge=exit_edge,
        exit_kind=exit_kind,
        exit_target=exit_target,
        lb_algo=lb_algo,
        lb_edge_index=lb_edge_index,
        lb_target=lb_target,
        spike_times=spike_times,
        spike_values=spike_values,
        timeline_times=timeline_times,
        timeline_down=timeline_down,
        timeline_slot=timeline_slot,
        user_mean=float(generators[0].avg_active_users.mean),
        user_var=(
            float(generators[0].avg_active_users.variance)
            if generators[0].avg_active_users.distribution == Distribution.NORMAL
            and generators[0].avg_active_users.variance is not None
            else -1.0
        ),
        user_window=float(generators[0].user_sampling_window),
        req_per_user_per_sec=(
            float(generators[0].avg_request_per_minute_per_user.mean) / 60.0
        ),
        gen_user_mean=np.array(
            [float(g.avg_active_users.mean) for g in generators], np.float64,
        ),
        gen_user_var=np.array(
            [
                float(g.avg_active_users.variance)
                if g.avg_active_users.distribution == Distribution.NORMAL
                and g.avg_active_users.variance is not None
                else -1.0
                for g in generators
            ],
            np.float64,
        ),
        gen_window=np.array(
            [float(g.user_sampling_window) for g in generators], np.float64,
        ),
        gen_rate=np.array(
            [
                float(g.avg_request_per_minute_per_user.mean) / 60.0
                for g in generators
            ],
            np.float64,
        ),
        gen_entry_edges=_pad_chains([c for c, _, _ in gen_chains]),
        gen_entry_len=np.array(
            [len(c) for c, _, _ in gen_chains], np.int32,
        ),
        gen_entry_target_kind=np.array(
            [k for _, k, _ in gen_chains], np.int32,
        ),
        gen_entry_target=np.array(
            [t for _, _, t in gen_chains], np.int32,
        ),
        gen_slots=_gen_slot_bounds(payload),
        horizon=horizon,
        sample_period=sample_period,
        n_samples=n_samples,
        max_requests=max_requests,
        pool_size=pool,
        max_iterations=max_iterations,
        server_ids=[server.id for server in servers],
        edge_ids=[edge.id for edge in edges],
        fastpath_ok=fastpath_ok,
        fastpath_reason=fastpath_reason,
        server_topo_order=topo,
        ram_slots=ram_slots,
        lc_ring=lc_ring,
        relax_rho=relax_rho,
        server_db_pool=server_db_pool,
        proof_rate_headroom=proof_rate_headroom,
        server_queue_cap=queue_cap_model,
        server_conn_cap=conn_cap_model,
        server_rate_limit=rate_limit_model,
        server_rate_burst=rate_burst_model,
        server_queue_timeout=queue_timeout_model,
        breaker_threshold=breaker_threshold,
        breaker_cooldown=breaker_cooldown,
        breaker_probes=breaker_probes,
        breaker_lowered=breaker_lowered,
        seg_hit_prob=seg_hit_prob,
        seg_miss_dur=seg_miss_dur,
        seg_llm_tokens=seg_llm_tokens,
        seg_llm_tpt=seg_llm_tpt,
        seg_llm_cost=seg_llm_cost,
        **(
            {
                **sv_tables,
                "serve_tokens": serve_tokens,
                "serve_slots": serve_slots,
                "serve_evict_max": serve_evict_max,
            }
            if any_serving
            else {}
        ),
        **(
            {
                "replay_times": replay_times,
                "replay_tok_in": replay_tok_in,
                "replay_tok_out": replay_tok_out,
            }
            if replay is not None
            else {}
        ),
        fp_db_pre=fp_db_pre,
        fp_db_dur=fp_db_dur,
        fp_db_post=fp_db_post,
        fp_cache_slot=fp_cache_slot,
        fp_cache_miss_prob=fp_cache_miss_prob,
        fp_cache_extra=fp_cache_extra,
        fault_srv_times=fault_arrays.srv_times,
        fault_srv_down=fault_arrays.srv_down,
        fault_edge_times=fault_arrays.edge_times,
        fault_edge_lat=fault_arrays.edge_lat,
        fault_edge_drop=fault_arrays.edge_drop,
        **(
            {
                "hz_mtbf_dist": hazards.mtbf_dist,
                "hz_mtbf_mean": hazards.mtbf_mean,
                "hz_mtbf_var": hazards.mtbf_var,
                "hz_mttr_dist": hazards.mttr_dist,
                "hz_mttr_mean": hazards.mttr_mean,
                "hz_mttr_var": hazards.mttr_var,
                "hz_lat_factor": hazards.lat_factor,
                "hz_drop_boost": hazards.drop_boost,
                "hz_srv_targets": hazards.srv_targets,
                "hz_edge_targets": hazards.edge_targets,
                "hz_max_faults": hazards.max_faults,
            }
            if hazards is not None
            else {}
        ),
        retry_timeout=retry.timeout,
        retry_max_attempts=retry.max_attempts,
        retry_backoff_base=retry.backoff_base,
        retry_backoff_mult=retry.backoff_mult,
        retry_backoff_cap=retry.backoff_cap,
        retry_jitter=retry.jitter,
        retry_budget_tokens=retry.budget_tokens,
        retry_budget_refill=retry.budget_refill,
        hedge_delay=hedge.delay,
        hedge_max=hedge.max_hedges,
        hedge_cancel=hedge.cancel,
        health_alpha=health.alpha,
        health_threshold=health.threshold,
        health_readmit=health.readmit,
        server_brownout_q=brownout_q_model,
        server_brownout_cpu=brownout_cpu_model,
        server_brownout_ram=brownout_ram_model,
    )


def _socket_cap_scan_reason(
    compiled_s: list,
    cap: int,
    fp_lowered_s: list | None,
    db_binding: bool,
) -> str:
    """Why a reachable connection capacity cannot ride the socket scan
    (empty string = eligible).  Conditions documented at the call site."""
    visits = max(
        (sum(1 for k, _ in segs if k == SEG_CPU) for segs, *_ in compiled_s),
        default=0,
    )
    if visits > 1:
        return "on a multi-burst endpoint"
    if cap > 128:
        return f"{cap} exceeds the scan ring bound (128)"
    if db_binding:
        return "with a binding DB connection pool"
    pre_offsets = set()
    for segs, *_ in compiled_s:
        _dur, pre, _post = _burst_decomposition(segs)
        if pre:  # burst endpoint: its single enqueue offset
            pre_offsets.add(round(pre[0], 12))
    if len(pre_offsets) > 1:
        return "with heterogeneous pre-burst IO offsets"
    if fp_lowered_s is not None and any(
        slot >= 0
        for _split, places, _reason in fp_lowered_s
        for slot, _p, _x in places
    ):
        return "with stochastic pre-burst cache extras"
    return ""


def _fastpath_analysis(
    payload: SimulationPayload,
    compiled: list[list[tuple[list[tuple[int, float]], float, list]]],
    exit_kind: np.ndarray,
    exit_target: np.ndarray,
    lb_algo: int,
    n_outage_marks: int,
    *,
    lb_edge_means: list[float] | None = None,
    max_spike: float = 0.0,
    server_queue_cap: np.ndarray | None = None,
    server_conn_cap: np.ndarray | None = None,
    server_db_pool: np.ndarray | None = None,
    fp_lowered: list | None = None,
    server_rate_limit: np.ndarray | None = None,
    server_queue_timeout: np.ndarray | None = None,
    breaker_threshold: int = 0,
    gen_targets: list[tuple[int, int]] | None = None,
) -> tuple[bool, str, list[int], np.ndarray, int, float]:
    """Decide whether the scan engine can execute this plan faithfully.

    "Faithfully" means exact per scenario for single-burst endpoints
    (including modeled RAM admission), and fixed-point relaxation for
    multi-burst endpoints (converged results sit inside the oracle's own
    ensemble noise, +/-2-3% p95 at rho 0.6 — see
    docs/internals/fastpath.md §5).  Conditions
    (each mirrors an assumption of the queueing-recursion model):
    round-robin routing (the rotation is deterministic given the pick/outage
    interleaving, which the fast path replays with a scan), no Poisson-latency
    edges, and an acyclic server exit DAG.  Outage windows are supported when
    an LB exists to act on.  Any alternating CPU/IO endpoint shape is
    accepted: each CPU burst is one FIFO core-queue visit, solved by the fast
    path's iterated Lindley / Kiefer-Wolfowitz recursion over the merged
    visit stream.

    RAM admission (`/root/reference/src/asyncflow/runtime/actors/
    server.py:147-149`) is handled in tiers per server: proven non-binding
    (admission can never queue -> not modeled), or homogeneous per-endpoint
    needs (admission is exactly a FIFO queue with ``ram_mb // need``
    concurrency slots -> modeled by the same KW recursion).  Only
    heterogeneous needs that can actually bind force the event engines.
    """
    servers = payload.topology_graph.nodes.servers
    n_servers = len(servers)
    no_slots = np.empty(0, np.int32)

    # LLM serving is event-engine work: continuous-batching admission is
    # a stateful two-resource FIFO and KV eviction re-queues requests mid
    # endpoint — neither fits the closed-form per-station recursions
    # (the llm.fastpath fence names this gap; AF501 prices it).
    if any(getattr(s, "serving", None) is not None for s in servers):
        return (
            False,
            "llm serving endpoints: continuous-batching admission and KV "
            "eviction are stateful event dynamics (modeled on the event "
            "engines; see the llm.fastpath fence)",
            [],
            no_slots,
            0,
            0.0,
        )
    if any(g.replay is not None for g in payload.generators):
        return (
            False,
            "trace-replay arrival table: the fast path synthesizes its "
            "own window-Poisson arrivals (modeled on the event engines)",
            [],
            no_slots,
            0,
            0.0,
        )

    # Resilience plans run on the fast path (round 8 fence burn-down):
    # fault windows lower to piecewise per-lane latency/dropout modulation
    # keyed by send time (dark-server windows hard-refuse at arrival), and
    # client retries run as lane-blocked attempt re-issues relaxed to a
    # fixed point over the analytic draws (engines/jaxsim/fastpath.py).
    # Only retry x multi-generator stays fenced: the re-issue entry chain
    # is single-generator by contract (the event engine refuses the
    # combination too).
    if payload.retry_policy is not None and len(payload.generators) > 1:
        return (
            False,
            "client retry policy with multiple generator streams: the "
            "backoff re-issue walks the single generator's entry chain "
            "(the event engine refuses this combination as well)",
            [],
            no_slots,
            0,
            0.0,
        )
    # Tail-tolerance policies are likewise event-engine work: hedges race
    # duplicate attempts through the shared queues, health ejection gates
    # the rotation on runtime failure history, and brownout rescales
    # service demand from the live ready-queue length — none of which the
    # closed-form per-station recursions can replay.
    if payload.hedge_policy is not None:
        return (
            False,
            "hedge policy: speculative duplicates race through the shared "
            "queues and dedup at the client (modeled on the event "
            "engines; use engine='event' or drop hedge_policy)",
            [],
            no_slots,
            0,
            0.0,
        )
    lb_node = payload.topology_graph.nodes.load_balancer
    if lb_node is not None and lb_node.health is not None:
        return (
            False,
            "LB health gate: EWMA outlier ejection rewires the rotation "
            "from runtime failure history (modeled on the event engines; "
            "use engine='event' or drop load_balancer.health)",
            [],
            no_slots,
            0,
            0.0,
        )
    if any(
        s.overload is not None
        and s.overload.brownout_queue_threshold is not None
        for s in servers
    ):
        return (
            False,
            "server brownout: degraded-profile service demand depends on "
            "the live ready-queue length (modeled on the event engines; "
            "use engine='event' or drop brownout_queue_threshold)",
            [],
            no_slots,
            0,
            0.0,
        )

    lb = payload.topology_graph.nodes.load_balancer
    if n_outage_marks > 0 and lb is None:
        # outages only act through the LB rotation; without one they are
        # no-ops in the event engines, but keep the exact engine for safety
        return False, "outage events without a load balancer", [], no_slots, 0, 0.0
    for edge in payload.topology_graph.edges:
        if edge.latency.distribution == Distribution.POISSON:
            return (
                False,
                f"edge {edge.id}: poisson latency unsupported",
                [],
                no_slots,
                0,
                0.0,
            )

    if len(payload.generators) > 1:
        # Superposition rides the fast path (round 5c) when every stream
        # converges on the SAME entry node: each stream synthesizes its
        # own window-Poisson arrivals and walks its own entry chain on a
        # disjoint static slot slice, and from the shared routing point on
        # the pipeline is stream-agnostic.  Mixed entry targets would need
        # per-slot routing topology — the event engines model those.
        if gen_targets is not None and len(set(gen_targets)) > 1:
            return (
                False,
                "multiple generators with distinct entry targets "
                "(modeled on the event engines)",
                [],
                no_slots,
                0,
                0.0,
            )
    # every rate/burst bound below aggregates the superposed streams
    # (identical to the single-stream values when G == 1).  The 3-sigma
    # burst allowance sums PER-STREAM variances: a heterogeneous
    # superposition (many low-rate users + few high-rate users) has a
    # larger summed-rate sigma than the pooled-user formula admits, and
    # the lc_ring below must be sized from the true bound.  Per stream the
    # rate sigma is ~rpu*sqrt(users) (Poisson-count scale); streams with
    # users < 1 cap their contribution at the full stream rate, matching
    # the old formula's sqrt(max(users, 1)) guard at G == 1.
    rate = 0.0
    rate_var = 0.0
    for g in payload.generators:
        users_g = float(g.avg_active_users.mean)
        rpu_g = float(g.avg_request_per_minute_per_user.mean) / 60.0
        rate += users_g * rpu_g
        rate_var += (
            users_g * rpu_g * rpu_g if users_g >= 1.0 else (users_g * rpu_g) ** 2
        )
    burst_rate = rate + 3.0 * math.sqrt(rate_var)

    lc_ring = 0
    if lb is not None and lb_algo != 0:
        # Least-connections reads live per-edge in-flight counts.  The scan
        # engine replays them with a bounded ring of outstanding delivery
        # times per slot: exact while the ring never overflows.  In-flight on
        # one edge is ~Poisson(rate x delay) even if every request lands on
        # it (an outage can concentrate all traffic), so a 6-sigma bound
        # with slack makes overflow astronomically unlikely; refuse when the
        # bound itself is impractically large.
        worst_delay = max(lb_edge_means or [0.0]) + max_spike
        m = burst_rate * worst_delay
        ring = int(math.ceil(m + 6.0 * math.sqrt(max(m, 1.0)) + 16.0))
        if ring > 128:
            return (
                False,
                f"least-connections in-flight bound too large ({ring} slots)",
                [],
                no_slots,
                0,
                0.0,
            )
        lc_ring = ring

    max_visits = max(
        (
            sum(1 for k, _ in segs if k == SEG_CPU)
            for per_server in compiled
            for segs, *_ in per_server
        ),
        default=0,
    )
    if max_visits > 8:
        # each extra burst adds relaxation sweeps over an n*kb merged stream;
        # beyond this the general event engine is the better engine
        return False, f"endpoint with {max_visits} CPU bursts", [], no_slots, 0, 0.0

    if breaker_threshold > 0:
        # breaker state is feedback from downstream rejections into the
        # rotation; only the event engines carry it
        return (
            False,
            "load balancer: circuit breaker with a live failure channel "
            "(modeled on the event engines)",
            [],
            no_slots,
            0,
            0.0,
        )

    ram_slots = np.zeros(n_servers, dtype=np.int32)
    for s, server in enumerate(servers):
        if server_conn_cap is not None and server_conn_cap[s] >= 0:
            # Socket capacity (round 5b): residency is a G/G/K loss system
            # — refuse iff all K connection slots hold exits beyond the
            # arrival.  Exact as one arrival-order pass (a sorted K-vector
            # of exit times rides the scan carry, like the KW core vector)
            # PROVIDED every residency endpoint is known at the lane's own
            # step: at most one CPU burst, no RAM admission tier, no
            # binding DB pool (its queue wait would feed exits), a uniform
            # burst pre-IO offset across the server's burst endpoints
            # (socket decisions are in ARRIVAL order; FIFO core grants are
            # in ENQUEUE order — a uniform offset makes them the same
            # order), and no stochastic pre-burst cache extras (same
            # reason).  K bounded like the other scan rings.
            reason = _socket_cap_scan_reason(
                compiled[s],
                int(server_conn_cap[s]),
                fp_lowered[s] if fp_lowered is not None else None,
                bool(server_db_pool is not None and server_db_pool[s] > 0),
            )
            if reason:
                return (
                    False,
                    f"server {server.id}: reachable connection capacity "
                    f"{reason} (socket refusal modeled on the event "
                    "engines)",
                    [],
                    no_slots,
                    0,
                    0.0,
                )
        # Feedback-free overload controls (round 5).  A token-bucket rate
        # limit is a pure function of the arrival sequence (arrival-order
        # scan, any server shape).  A ready-queue cap / dequeue deadline is
        # exact as a joint KW+ring arrival-order scan when the server has
        # at most one CPU burst and no RAM admission tier (FIFO starts are
        # monotone, so "cap-th most recent start still in the future" IS
        # the shed test; abandons add zero service at their grant).  Other
        # shapes keep the event-engine fence.
        cap_reachable = server_queue_cap is not None and server_queue_cap[s] >= 0
        to_reachable = (
            server_queue_timeout is not None and server_queue_timeout[s] >= 0
        )
        if cap_reachable or to_reachable:
            visits_s = max(
                (
                    sum(1 for k, _ in segs if k == SEG_CPU)
                    for segs, *_ in compiled[s]
                ),
                default=0,
            )
            max_ram_s = max(
                (ram for _, ram, *_ in compiled[s]), default=0.0,
            )
            name = "ready-queue cap" if cap_reachable else "dequeue deadline"
            if visits_s > 1:
                return (
                    False,
                    f"server {server.id}: reachable {name} on a multi-burst "
                    "endpoint (modeled on the event engines)",
                    [],
                    no_slots,
                    0,
                    0.0,
                )
            if max_ram_s > 0:
                return (
                    False,
                    f"server {server.id}: reachable {name} with a RAM "
                    "admission tier (modeled on the event engines)",
                    [],
                    no_slots,
                    0,
                    0.0,
                )
            if cap_reachable and server_queue_cap[s] > 128:
                return (
                    False,
                    f"server {server.id}: ready-queue cap {server_queue_cap[s]} "
                    "exceeds the scan ring bound (128)",
                    [],
                    no_slots,
                    0,
                    0.0,
                )
        # Stochastic cache segments are per-request duration extras and DB
        # pools are one extra FIFO G/G/K station per server on the fast
        # path (round 4) — eligible as long as every endpoint's shape fits
        # the lowering model (_fastpath_lowering): at most one DB query,
        # positioned after the last CPU burst so its FIFO wait never feeds
        # back into the core-queue enqueue times.
        if any(k == SEG_LLM for segs, *_ in compiled[s] for k, _ in segs):
            return (
                False,
                f"server {server.id}: LLM call dynamics (token draws and "
                "cost accounting modeled on the event engines)",
                [],
                no_slots,
                0,
                0.0,
            )
        if any(
            k in (SEG_PREFILL, SEG_DECODE)
            for segs, *_ in compiled[s]
            for k, _ in segs
        ):
            return (
                False,
                f"server {server.id}: LLM serving batch dynamics "
                "(continuous batching and KV eviction modeled on the "
                "event engines)",
                [],
                no_slots,
                0,
                0.0,
            )
        if fp_lowered is not None:
            for e, (_, _, reason) in enumerate(fp_lowered[s]):
                if reason:
                    return (
                        False,
                        f"server {server.id} endpoint "
                        f"{server.endpoints[e].endpoint_name}: {reason} "
                        "(modeled on the event engines)",
                        [],
                        no_slots,
                        0,
                        0.0,
                    )
        if exit_kind[s] == TARGET_LB:
            return (
                False,
                f"server {server.id}: exit to LB creates a cycle",
                [],
                no_slots,
                0,
                0.0,
            )
        max_ram = 0.0
        residence = 0.0
        cpu_dur = 0.0
        db_dur_max = 0.0
        visits = 1
        needs: set[float] = set()
        for segs, ram, cache, *_rest in compiled[s]:
            max_ram = max(max_ram, ram)
            if ram > 0:
                needs.add(ram)
            # worst-case residence: stochastic cache segments may sleep the
            # miss latency (the tier-1 proof must hold for every draw)
            residence = max(
                residence,
                sum(
                    max(d, cache[i][1]) if cache[i] is not None else d
                    for i, (_, d) in enumerate(segs)
                ),
            )
            cpu_dur = max(cpu_dur, sum(d for k, d in segs if k == SEG_CPU))
            db_dur_max = max(
                db_dur_max, sum(d for k, d in segs if k == SEG_DB),
            )
            visits = max(visits, sum(1 for k, _ in segs if k == SEG_CPU))
        has_db_station = bool(
            server_db_pool is not None and server_db_pool[s] >= 0 and db_dur_max > 0,
        )
        if max_ram <= 0:
            continue  # ram_slots[s] stays 0: nothing to admit
        # Tier 1: RAM provably non-binding.  RAM is held from admission to
        # endpoint end, INCLUDING every CPU queue wait — bound the waits with
        # an M/M/c-style estimate per core-queue visit (plus the DB pool's
        # FIFO wait when a modeled station can park the request).
        cores = server.server_resources.cpu_cores
        rho = burst_rate * cpu_dur / cores
        capacity_mb = float(server.server_resources.ram_mb)
        if rho < 0.95:
            wait_est = visits * rho / (1.0 - rho) * cpu_dur / cores
            if has_db_station:
                pool_k = int(server_db_pool[s])
                rho_db = burst_rate * db_dur_max / pool_k
                if rho_db >= 0.95:
                    return (
                        False,
                        f"server {server.id}: binding RAM with a saturated "
                        "DB pool (no wait bound; modeled on the event "
                        "engines)",
                        [],
                        no_slots,
                        0,
                        0.0,
                    )
                wait_est += rho_db / (1.0 - rho_db) * db_dur_max / pool_k
            if capacity_mb / max_ram >= 4.0 * burst_rate * (residence + wait_est) + 4.0:
                ram_slots[s] = -1
                continue
        # Tier 2: admission can queue, but with one uniform need per server it
        # is exactly a FIFO queue with ``cap // need`` slots, settled jointly
        # with the core queue in one arrival-order pass — which requires both
        # FIFO orders to coincide with arrival order: at most one CPU burst
        # per endpoint, no zero-RAM endpoints that would bypass admission and
        # overtake in the core queue, and a uniform pre-burst IO (a longer
        # pre-IO on one endpoint would let later grants enqueue earlier).
        if has_db_station:
            # the joint admission+core pass cannot carry a third (pool)
            # queue: RAM release depends on the DB wait and vice versa
            return (
                False,
                f"server {server.id}: binding RAM with a binding DB pool",
                [],
                no_slots,
                0,
                0.0,
            )
        if fp_lowered is not None and any(
            slot >= 0
            for _, places, _ in fp_lowered[s]
            for slot, _, _ in places
        ):
            # a stochastic pre-burst IO would let later RAM grants enqueue
            # earlier, breaking the arrival-order identity the joint pass
            # relies on
            return (
                False,
                f"server {server.id}: stochastic cache before a CPU burst "
                "with binding RAM",
                [],
                no_slots,
                0,
                0.0,
            )
        if len(needs) == 1 and min(ram for _, ram, *_ in compiled[s]) > 0:
            if visits > 1:
                return (
                    False,
                    f"server {server.id}: multi-burst endpoints with binding RAM",
                    [],
                    no_slots,
                    0,
                    0.0,
                )
            pre_ios = {
                _burst_decomposition(segs)[1][0]
                for segs, *_ in compiled[s]
                if any(k == SEG_CPU for k, _ in segs)
            }
            if len(pre_ios) > 1:
                return (
                    False,
                    f"server {server.id}: varying pre-burst IO with binding RAM",
                    [],
                    no_slots,
                    0,
                    0.0,
                )
            slots = int(capacity_mb // next(iter(needs)))
            if 1 <= slots <= 1024:  # scan carry is `slots` floats per lane
                ram_slots[s] = slots
                continue
            if slots < 1:
                return (
                    False,
                    f"server {server.id}: endpoint RAM exceeds server RAM",
                    [],
                    no_slots,
                    0,
                    0.0,
                )
            return (
                False,
                f"server {server.id}: RAM admission needs {slots} slots",
                [],
                no_slots,
                0,
                0.0,
            )
        return (
            False,
            f"server {server.id}: heterogeneous RAM needs can bind",
            [],
            no_slots,
            0,
            0.0,
        )

    # Socket-scan RAM condition, decidable only now that the RAM tiers are
    # settled: a MODELED admission queue (ram_slots > 0) would make exits
    # depend on admission waits the socket pass doesn't carry; tier-1
    # non-binding RAM (ram_slots == -1, admission never queues) is
    # timing-inert and stays eligible.
    for s, server in enumerate(servers):
        if (
            server_conn_cap is not None
            and server_conn_cap[s] >= 0
            and ram_slots[s] > 0
        ):
            return (
                False,
                f"server {server.id}: reachable connection capacity with a "
                "binding RAM admission tier (socket refusal modeled on the "
                "event engines)",
                [],
                no_slots,
                0,
                0.0,
            )

    # topological order of the server exit DAG
    indeg = [0] * n_servers
    for s in range(n_servers):
        if exit_kind[s] == TARGET_SERVER:
            indeg[int(exit_target[s])] += 1
    frontier = [s for s in range(n_servers) if indeg[s] == 0]
    topo: list[int] = []
    while frontier:
        s = frontier.pop()
        topo.append(s)
        if exit_kind[s] == TARGET_SERVER:
            t = int(exit_target[s])
            indeg[t] -= 1
            if indeg[t] == 0:
                frontier.append(t)
    if len(topo) != n_servers:
        return False, "server exit chain has a cycle", [], no_slots, 0, 0.0

    # Multi-burst relaxation validity envelope (measured, round 3 —
    # scripts/relaxation_envelope.py, 24-seed ensembles, 300 s horizon):
    # the fixed point sits inside the oracle's own ensemble noise up to
    # rho ~ 0.70 but is biased HIGH past it (+28% p95 / +34% mean at
    # rho 0.75, worse beyond); the bias is identical at 6 and 16 sweeps,
    # i.e. it is the fixed point itself, not under-iteration.  Single-burst
    # endpoints stay exact at any utilization (pure Lindley/KW, no
    # relaxation).  Servers running multi-burst endpoints above the
    # envelope are routed to the event engine.
    max_visits_per_server = [
        max(
            (sum(1 for k, _ in segs if k == SEG_CPU) for segs, *_ in compiled[s]),
            default=0,
        )
        for s in range(n_servers)
    ]
    relax_rho = 0.0
    if any(v > 1 for v in max_visits_per_server):
        srv_rate = _server_entry_rates(payload)
        if srv_rate is None:  # pragma: no cover - cycles rejected above
            return False, "server exit chain has a cycle", [], no_slots, 0, 0.0
        # retries amplify offered load up to the attempt cap (orphaned
        # attempts keep consuming cores): the envelope must hold at the
        # amplified rate, not the logical one
        retry_amp = (
            float(payload.retry_policy.max_attempts)
            if payload.retry_policy is not None
            else 1.0
        )
        for s in range(n_servers):
            if max_visits_per_server[s] <= 1:
                continue
            cpu_dur = max(
                (sum(d for k, d in segs if k == SEG_CPU) for segs, *_ in compiled[s]),
                default=0.0,
            )
            cores = servers[s].server_resources.cpu_cores
            rho = retry_amp * srv_rate[s] * cpu_dur / max(cores, 1)
            relax_rho = max(relax_rho, rho)
            if rho > RELAX_RHO_MAX:
                return (
                    False,
                    (
                        f"server {servers[s].id}: multi-burst endpoints at "
                        f"utilization {rho:.2f} > {RELAX_RHO_MAX} — outside "
                        "the relaxation's measured validity envelope "
                        "(docs/internals/fastpath.md §5)"
                    ),
                    [],
                    no_slots,
                    0,
                    0.0,
                )

    return True, "", topo, ram_slots, lc_ring, relax_rho
