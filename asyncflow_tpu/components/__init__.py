"""Curated public surface for topology building blocks."""

from asyncflow_tpu.schemas.edges import Edge
from asyncflow_tpu.schemas.endpoint import Endpoint, Step
from asyncflow_tpu.schemas.events import EventInjection
from asyncflow_tpu.schemas.nodes import (
    CircuitBreaker,
    Client,
    LoadBalancer,
    OverloadPolicy,
    Server,
    ServerResources,
)

__all__ = [
    "CircuitBreaker",
    "Client",
    "Edge",
    "Endpoint",
    "EventInjection",
    "LoadBalancer",
    "OverloadPolicy",
    "Server",
    "ServerResources",
    "Step",
]
