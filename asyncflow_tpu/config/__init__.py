"""Constants and defaults shared by every layer."""

from asyncflow_tpu.config import constants

__all__ = ["constants"]
