"""Single source of truth for every string that appears in scenario files.

Every enum *value* below is part of the on-disk contract: YAML scenarios written
for the reference implementation (AsyncFlow, see ``/root/reference/src/asyncflow/
config/constants.py``) must validate unchanged against this framework.  Only the
values are shared — they are the public file format, not code.

Organisation:
    - workload + distribution enums (request generator),
    - endpoint step vocabulary (the per-request server program),
    - topology node/edge kinds,
    - load-balancer algorithms,
    - event-injection kinds,
    - metric names (sampled / event / aggregated) and latency-stat keys,
    - default values grouped in small frozen namespaces.
"""

from __future__ import annotations

from enum import Enum, IntEnum

try:
    from enum import StrEnum
except ImportError:  # Python < 3.11
    class StrEnum(str, Enum):
        """Backport of :class:`enum.StrEnum`: members are their values."""

        def __str__(self) -> str:  # pragma: no cover - mirrors 3.11 behavior
            return str(self.value)

# ---------------------------------------------------------------------------
# Random variables & workload
# ---------------------------------------------------------------------------


class Distribution(StrEnum):
    """Sampling distributions accepted by :class:`RVConfig`."""

    POISSON = "poisson"
    NORMAL = "normal"
    LOG_NORMAL = "log_normal"
    EXPONENTIAL = "exponential"
    UNIFORM = "uniform"


class TimeDefaults(IntEnum):
    """Time-related defaults and validation bounds (seconds)."""

    MIN_TO_SEC = 60
    USER_SAMPLING_WINDOW = 60
    SIMULATION_TIME = 3_600
    MIN_SIMULATION_TIME = 5
    MIN_USER_SAMPLING_WINDOW = 1
    MAX_USER_SAMPLING_WINDOW = 120


# ---------------------------------------------------------------------------
# Endpoint step vocabulary
# ---------------------------------------------------------------------------


class EndpointStepIO(StrEnum):
    """I/O-bound step categories (the event loop yields, no core is held)."""

    TASK_SPAWN = "io_task_spawn"
    LLM = "io_llm"
    WAIT = "io_wait"
    DB = "io_db"
    CACHE = "io_cache"


class EndpointStepCPU(StrEnum):
    """CPU-bound step categories (a core / the GIL is held)."""

    INITIAL_PARSING = "initial_parsing"
    CPU_BOUND_OPERATION = "cpu_bound_operation"


class EndpointStepRAM(StrEnum):
    """Memory reservation steps (working set held for the whole request)."""

    RAM = "ram"


class StepOperation(StrEnum):
    """Quantity keys allowed inside a step definition."""

    CPU_TIME = "cpu_time"
    IO_WAITING_TIME = "io_waiting_time"
    NECESSARY_RAM = "necessary_ram"


# ---------------------------------------------------------------------------
# Topology
# ---------------------------------------------------------------------------


class SystemNodes(StrEnum):
    """Macro-component categories of the topology graph."""

    GENERATOR = "generator"
    SERVER = "server"
    CLIENT = "client"
    LOAD_BALANCER = "load_balancer"


class SystemEdges(StrEnum):
    """Edge categories connecting system nodes."""

    NETWORK_CONNECTION = "network_connection"


class LbAlgorithmsName(StrEnum):
    """Routing policies available on the load balancer."""

    ROUND_ROBIN = "round_robin"
    LEAST_CONNECTIONS = "least_connection"


class ServerResourcesDefaults:
    """Defaults / minima for per-server resources."""

    CPU_CORES = 1
    MINIMUM_CPU_CORES = 1
    RAM_MB = 1024
    MINIMUM_RAM_MB = 256
    DB_CONNECTION_POOL = None


class NetworkParameters:
    """Defaults / bounds for network edges."""

    MIN_DROPOUT_RATE = 0.0
    DROPOUT_RATE = 0.01
    MAX_DROPOUT_RATE = 1.0


# ---------------------------------------------------------------------------
# Event injection
# ---------------------------------------------------------------------------


class EventDescription(StrEnum):
    """Kinds of events that can be injected in a simulation window."""

    SERVER_UP = "server_up"
    SERVER_DOWN = "server_down"
    NETWORK_SPIKE_START = "network_spike_start"
    NETWORK_SPIKE_END = "network_spike_end"


class FaultKind(StrEnum):
    """Fault-injection window kinds (resilience modeling; see
    :mod:`asyncflow_tpu.schemas.resilience`).

    ``SERVER_OUTAGE`` hard-refuses arrivals at the server (the LB only
    learns through its breaker — unlike ``EventDescription.SERVER_DOWN``,
    which is a graceful rotation removal).  ``EDGE_DEGRADE`` multiplies
    edge latency and/or boosts dropout inside the window;
    ``EDGE_PARTITION`` drops every send on the edge.
    """

    SERVER_OUTAGE = "server_outage"
    EDGE_DEGRADE = "edge_degrade"
    EDGE_PARTITION = "edge_partition"


class RetryDefaults(IntEnum):
    """Defaults / bounds for the client retry policy."""

    MAX_ATTEMPTS = 3
    #: hard cap on attempts per logical request: bounds the attempts
    #: histogram width and the retry amplification of capacity estimates
    MAX_ATTEMPTS_CAP = 16


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------


class SampledMetricName(StrEnum):
    """Fixed-cadence time-series metrics."""

    READY_QUEUE_LEN = "ready_queue_len"
    EVENT_LOOP_IO_SLEEP = "event_loop_io_sleep"
    RAM_IN_USE = "ram_in_use"
    EDGE_CONCURRENT_CONNECTION = "edge_concurrent_connection"


class SamplePeriods(float, Enum):
    """Allowed range for the sampling cadence of time-series metrics."""

    STANDARD_TIME = 0.01
    MINIMUM_TIME = 0.001
    MAXIMUM_TIME = 0.1


class EventMetricName(StrEnum):
    """Per-request (event-triggered) metrics."""

    RQS_CLOCK = "rqs_clock"
    LLM_COST = "llm_cost"


class AggregatedMetricName(StrEnum):
    """Post-run aggregated metrics."""

    LATENCY_STATS = "latency_stats"
    THROUGHPUT = "throughput_rps"
    LLM_STATS = "llm_stats"


class ServerResourceName(StrEnum):
    """Keys identifying each server resource container."""

    CPU = "CPU"
    RAM = "RAM"


class LatencyKey(StrEnum):
    """Keys of the latency statistics dictionary."""

    TOTAL_REQUESTS = "total_requests"
    MEAN = "mean"
    MEDIAN = "median"
    STD_DEV = "std_dev"
    P95 = "p95"
    P99 = "p99"
    MIN = "min"
    MAX = "max"


# ---------------------------------------------------------------------------
# Engine selection (new in this framework — the reference is single-engine)
# ---------------------------------------------------------------------------


class Backend(StrEnum):
    """Execution engines available behind :class:`SimulationRunner`.

    ``ORACLE`` is the sequential CPU discrete-event engine (the behavioral
    reference, replacing the SimPy loop of the original project).  ``NATIVE``
    is the C++ implementation of the same engine (~60x faster; falls back to
    ``ORACLE`` when no compiler is available).  ``JAX`` is the batched TPU
    next-event engine used for Monte-Carlo sweeps.
    """

    ORACLE = "oracle"
    NATIVE = "native"
    JAX = "jax"
