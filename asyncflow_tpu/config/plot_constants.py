"""Frozen styling for the built-in matplotlib charts.

Mirrors the reference plot configuration surface
(``/root/reference/src/asyncflow/config/plot_constants.py:6-47``).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class PlotCfg:
    """Static configuration of one chart."""

    title: str
    x_label: str
    y_label: str
    color: str = "tab:blue"
    alpha: float = 0.85


LATENCY_PLOT = PlotCfg(
    title="Latency distribution",
    x_label="Latency (s)",
    y_label="Requests",
    color="tab:blue",
)

THROUGHPUT_PLOT = PlotCfg(
    title="Throughput (completed requests per window)",
    x_label="Time (s)",
    y_label="Requests / s",
    color="tab:green",
)

SERVER_QUEUES_PLOT = PlotCfg(
    title="Server event-loop queues",
    x_label="Time (s)",
    y_label="Queue length",
    color="tab:orange",
)

RAM_PLOT = PlotCfg(
    title="Server RAM in use",
    x_label="Time (s)",
    y_label="RAM (MB)",
    color="tab:red",
)
