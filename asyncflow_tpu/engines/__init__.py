"""Execution engines: the sequential CPU oracle and the batched JAX engine."""

from asyncflow_tpu.engines.results import SimulationResults, SweepResults

__all__ = ["SimulationResults", "SweepResults"]
