"""Batched JAX/XLA engines: the general next-event machine and the scan fast path."""

from asyncflow_tpu.engines.jaxsim.engine import Engine, run_single, scenario_keys
from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine
from asyncflow_tpu.engines.jaxsim.params import ScenarioOverrides

__all__ = ["Engine", "FastEngine", "ScenarioOverrides", "run_single", "scenario_keys"]
