"""Batched JAX/XLA next-event engine."""

from asyncflow_tpu.engines.jaxsim.engine import Engine, run_single, scenario_keys

__all__ = ["Engine", "run_single", "scenario_keys"]
