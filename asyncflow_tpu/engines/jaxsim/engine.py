"""Batched next-event simulation engine on JAX/XLA.

This is the TPU-native replacement for the reference's SimPy coroutine loop
(`/root/reference/src/asyncflow/runtime/simulation_runner.py:369`): instead of
one Python heap per scenario, every scenario's state lives in fixed-shape
arrays and a single `lax.while_loop` advances each scenario to its next event;
`jax.vmap` over the scenario axis turns Monte-Carlo sweeps into one compiled
kernel.

Engine shape (see SURVEY.md §7):

- **Requests are pool slots.**  A slot carries (next-event time, event code,
  server, endpoint, segment, ram, FIFO ticket, start time, LB slot).  The
  next event of a scenario is the min over slot times, the next arrival, and
  the next outage-timeline entry.
- **One event per iteration, predicated updates.**  Every mutation is masked
  by its (disjoint) branch predicate, so the loop body is pure vector code —
  exactly what `vmap` wants.  Zero-time cascades (resource grants) fold into
  the releasing event, keeping iterations at ~6-9 per completed request.
- **Randomness is counter-based.**  Every draw derives from
  `fold_in(scenario_key, iteration)` — no RNG state beyond the loop counter.
  Parity with the oracle is distributional, not bit-exact (SURVEY.md §7).
- **Metrics are reconstructed, not collected.**  Gauges (queue lengths, RAM,
  edge concurrency) are scatter-added as deltas at transition times into
  per-sample-tick buckets and cumsum-ed post-run — the reference's collector
  coroutine (`metrics/collector.py:50-67`) becomes a single post-pass.
  Latencies go to a log-histogram + exact moments (sweeps) or an exact clock
  table (single runs / parity tests).
"""

from __future__ import annotations

import contextlib
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from asyncflow_tpu.compiler.plan import (
    SEG_CACHE,
    SEG_CPU,
    SEG_DB,
    SEG_DECODE,
    SEG_LLM,
    SEG_END,
    SEG_IO,
    SEG_PREFILL,
    TARGET_CLIENT,
    TARGET_LB,
    TARGET_SERVER,
    StaticPlan,
    compile_payload,
)
from asyncflow_tpu.config.constants import SampledMetricName
from asyncflow_tpu.engines.jaxsim.sampling import (
    as_threefry,
    D_EXPONENTIAL as _D_EXPONENTIAL,
    D_LOGNORMAL as _D_LOGNORMAL,
    D_NORMAL as _D_NORMAL,
    D_POISSON as _D_POISSON,
    D_UNIFORM as _D_UNIFORM,
    TINY as _TINY,
    antithetic_trace,
    draw_normal,
    draw_uniform,
    exponential_from_u,
    hist_constants,
    latency_bin,
    lognormal,
    sample_bucket,
    truncated_normal,
)
from asyncflow_tpu.engines.jaxsim.sortutil import searchsorted_small
from asyncflow_tpu.observability import blame as _bl
from asyncflow_tpu.observability.simtrace import (
    FR_ABANDON,
    FR_ARRIVE_LB,
    FR_ARRIVE_SRV,
    FR_CANCEL,
    FR_COMPLETE,
    FR_DECODE,
    FR_DROP,
    FR_EVICT,
    FR_HEDGE,
    FR_PREFILL,
    FR_REJECT,
    FR_RETRY,
    FR_RUN,
    FR_SPAWN,
    FR_TIMEOUT,
    FR_TRANSIT,
    FR_WAIT_CPU,
    FR_WAIT_DB,
    FR_WAIT_RAM,
    TraceConfig,
    decode_breaker,
    decode_flight,
)
from asyncflow_tpu.observability.telemetry import instrument_jit
from asyncflow_tpu.engines.results import (
    SimulationResults,
    SweepResults,
    build_blame_hist,
)
from asyncflow_tpu.schemas.payload import SimulationPayload
from asyncflow_tpu.engines.jaxsim.rotation import (
    rotation_advance,
    rotation_insert,
    rotation_remove,
)
from asyncflow_tpu.engines.jaxsim.params import (
    EV_ARRIVE_CLIENT,
    EV_ARRIVE_LB,
    EV_ABANDON,
    EV_ARRIVE_SRV,
    EV_IDLE,
    EV_RESUME,
    EV_RETRY,
    EV_SEG_END,
    EV_SV_GRANT,
    EV_WAIT_CPU,
    EV_WAIT_DB,
    EV_WAIT_RAM,
    EV_WAIT_SV,
    INF,
    NO_TICKET,
    EngineState,
    ScenarioOverrides,
    base_overrides,
    fill_overrides,
    params_from_plan,
)




class Engine:
    """One compiled batched engine for one :class:`StaticPlan`.

    Static configuration (pool size, metric modes, bin counts) is baked into
    the jitted kernel; per-scenario randomness and parameter overrides flow in
    as arguments.
    """

    def __init__(
        self,
        plan: StaticPlan,
        *,
        collect_gauges: bool = False,
        collect_clocks: bool = False,
        collect_traces: bool = False,
        gauge_series_stride: int = 0,
        n_hist_bins: int = 1024,
        pool_size: int | None = None,
        max_requests: int | None = None,
        crn: bool = False,
        trace: TraceConfig | None = None,
        blame: bool = False,
    ) -> None:
        """``crn``: common-random-numbers keying — every draw is keyed by
        the REQUEST's identity (spawn sequence + per-request event counter)
        instead of the global iteration counter, so two runs whose event
        interleavings diverge under different :class:`ScenarioOverrides`
        still hand request r's k-th event the same substream (the coupling
        :func:`asyncflow_tpu.analysis.compare` relies on).  Off by default:
        streams stay bit-identical to pre-CRN builds.

        ``trace``: the simulation-domain flight recorder
        (:class:`asyncflow_tpu.observability.simtrace.TraceConfig`) — the
        first ``sample_requests`` spawned requests per scenario record
        their lifecycle transitions into fixed-size on-device ring buffers
        written inside the vmapped loop; breaker state transitions go to a
        per-scenario ring.  Recording consumes no random draws, so every
        non-trace output is unchanged with it on or off — bit-identical
        for all discrete outputs (histograms, clocks, counters; pinned by
        tests/parity/test_flight_recorder.py), to one float32 ulp for the
        running latency sums (the traced program is a separate XLA
        compilation, so sum fusion may differ; bench.py --trace-guard).
        """
        if collect_traces and not collect_clocks:
            msg = "collect_traces requires collect_clocks (traces index rows)"
            raise ValueError(msg)
        if gauge_series_stride < 0:
            msg = f"gauge_series_stride must be >= 0, got {gauge_series_stride}"
            raise ValueError(msg)
        self.plan = plan
        self.collect_gauges = collect_gauges
        self.collect_clocks = collect_clocks
        self.collect_traces = collect_traces
        # Streaming gauge series (sweep-scale): same interval-endpoint grid
        # as the full gauge collection, resampled onto a coarse grid of
        # n_samples // stride rows.  collect_gauges keeps the fine grid and
        # wins when both are requested (FastEngine contract, fastpath.py).
        if collect_gauges:
            self._gauge_period = plan.sample_period
            self._gauge_samples = plan.n_samples
        elif gauge_series_stride:
            self._gauge_period = plan.sample_period * gauge_series_stride
            self._gauge_samples = plan.n_samples // gauge_series_stride
        else:
            self._gauge_period = plan.sample_period
            self._gauge_samples = 0
        self._collect_gauge_grid = collect_gauges or gauge_series_stride > 0
        #: coarse-grid stride consumed by ``sweep_results`` (0 = fine grid)
        self.gauge_series_stride = 0 if collect_gauges else gauge_series_stride
        # Hop ring capacity: gen + (edge + client) per entry hop + per
        # server visit (LB + edge + server + exit edge) + final client.
        # Acyclic exit DAGs visit each server once; exit-to-LB topologies
        # CAN cycle (the event engine allows them), so the ring records
        # the FIRST `cap` hops and stops — tr_n saturates at cap, which
        # readers can treat as a truncation marker.
        max_entry = (
            int(plan.gen_entry_len.max())
            if plan.gen_entry_len.size
            else len(plan.entry_edges)
        )
        self._hop_cap = 1 + 2 * max_entry + 4 * max(plan.n_servers, 1) + 2
        self.n_hist_bins = n_hist_bins
        self.pool = pool_size or plan.pool_size
        self.max_requests = max_requests or plan.max_requests
        self.params = params_from_plan(plan)
        self.hist_lo, self.hist_scale = hist_constants(n_hist_bins)
        self.n_thr = int(np.ceil(plan.horizon)) or 1
        self._dists_present = sorted(set(plan.edge_dist.tolist()))
        # statically prune the RAM admission/grant machinery (several pool
        # scans per iteration) for the many plans with no RAM steps at all
        self._has_ram = bool(np.max(plan.endpoint_ram) > 0)
        # static pruning: db-pool machinery compiles in only when the plan
        # actually models a finite connection pool (SEG_DB segments exist)
        self._has_db = bool(np.any(plan.seg_kind == SEG_DB))
        self._has_cache = bool(np.any(plan.seg_kind == SEG_CACHE))
        self._has_shed = plan.has_queue_cap
        self._has_conn = plan.has_conn_cap
        # serving decode cost rides the same per-request accumulator as
        # SEG_LLM token cost, so serving plans compile the llm machinery in
        self._has_llm = plan.has_llm or plan.has_serving
        # LLM serving batch gate (SEG_PREFILL/SEG_DECODE pairs) and the
        # trace-replay arrival table, each statically pruned when absent
        self._has_serving = plan.has_serving
        self._has_replay = plan.has_replay
        self._has_rl = plan.has_rate_limit
        self._has_timeout = plan.has_queue_timeout
        self._has_breaker = plan.breaker_threshold > 0
        # resilience: fault-window gating + client retry machinery, each
        # statically pruned when the plan carries none
        self._has_srv_faults = bool(
            np.any(plan.fault_srv_down != 0) or np.any(plan.hz_srv_mask),
        )
        self._has_edge_faults = bool(
            np.any(plan.fault_edge_lat != 1.0)
            or np.any(plan.fault_edge_drop != 0.0)
            or np.any(plan.hz_edge_mask),
        )
        self._has_retry = plan.has_retry
        # tail tolerance: hedged requests, LB health gate, server brownout
        # — each statically pruned when the plan carries none (IN903)
        self._has_hedge = plan.has_hedge
        self._hedge_max = max(int(plan.hedge_max), 1)
        self._hedge_cancel = bool(plan.hedge_cancel)
        self._has_health = plan.has_health
        self._health_alpha = float(plan.health_alpha)
        self._health_readmit = float(plan.health_readmit)
        self._has_brownout = plan.has_brownout
        # the per-target report channel (req_cbslot bookkeeping) serves
        # both the breaker state machine and the health EWMA
        self._has_report = (plan.breaker_threshold > 0) or plan.has_health
        if self._has_hedge and plan.n_generators > 1:  # pragma: no cover
            # the payload validator forbids this combination; double-fence
            msg = "hedge policy with multiple generators is unsupported"
            raise ValueError(msg)
        self._att_bins = max(int(plan.retry_max_attempts), 1)
        #: retry-budget capacity; None = unlimited (no bucket compiled in)
        self._rb_cap = (
            float(plan.retry_budget_tokens)
            if plan.retry_budget_tokens >= 0
            else None
        )
        if self._has_retry and plan.n_generators > 1:  # pragma: no cover
            # the payload validator forbids this combination; double-fence
            # so hand-built plans fail loudly instead of mis-routing
            msg = "retry policy with multiple generators is unsupported"
            raise ValueError(msg)
        self._n_gen = plan.n_generators
        self._crn = crn
        #: flight recorder (None = statically pruned; the compiled program
        #: is then bit-identical to pre-trace builds)
        self.trace = trace
        self._fr_k = trace.sample_requests if trace is not None else 1
        self._fr_slots = trace.event_slots if trace is not None else 1
        self._bk_cap = trace.breaker_slots if trace is not None else 1
        #: latency attribution plane (observability/blame.py).  False =
        #: statically pruned — the compiled program is bit-identical to
        #: pre-blame builds (pinned by tests/parity/test_flight_recorder.py).
        self.blame = bool(blame)
        from asyncflow_tpu.observability.blame import (
            blame_stride,
            n_blame_bins,
            n_cells,
        )

        self._bl_cells = (
            n_cells(plan.n_servers, plan.n_edges) if self.blame else 1
        )
        self._bl_bins = n_blame_bins(n_hist_bins) if self.blame else 1
        self._bl_stride = blame_stride(n_hist_bins)
        self._compiled: dict = {}

    # hop codes (decoded by run_single against the payload's ids)
    HOP_GEN = 0
    HOP_EDGE = 1000  # + edge index
    HOP_SERVER = 2000  # + server index
    HOP_LB = 3000
    HOP_CLIENT = 4000

    def _hop(self, st: EngineState, i, code, t, pred) -> EngineState:
        """Append one hop to slot ``i``'s ring (no-op unless tracing)."""
        if not self.collect_traces:
            return st
        # once full, stop recording (keep the FIRST cap hops; see __init__)
        pred = pred & (st.req_hop_n[i] < self._hop_cap)
        j = jnp.minimum(st.req_hop_n[i], self._hop_cap - 1)
        return st._replace(
            req_hops=st.req_hops.at[i, j].set(
                jnp.where(pred, code, st.req_hops[i, j]),
            ),
            req_hop_t=st.req_hop_t.at[i, j].set(
                jnp.where(pred, t, st.req_hop_t[i, j]),
            ),
            req_hop_n=st.req_hop_n.at[i].add(jnp.where(pred, 1, 0)),
        )

    # ==================================================================
    # flight recorder (no-ops unless ``trace`` was given; recording never
    # consumes a draw, so the event stream is identical with it on or off)
    # ==================================================================

    def _fr_row(self, st: EngineState, row, code, node, t, pred) -> EngineState:
        """Append one lifecycle event to ring row ``row`` (device-side).

        ``fr_n`` keeps counting past the slot budget — the overflow IS the
        explicit dropped-events counter surfaced in results."""
        if self.trace is None:
            return st
        ok = pred & (row >= 0)
        r = jnp.clip(row, 0, self._fr_k - 1)
        j = st.fr_n[r]
        write = ok & (j < self._fr_slots)
        jj = jnp.clip(j, 0, self._fr_slots - 1)
        code = jnp.int32(code)
        node = jnp.int32(node)
        return st._replace(
            fr_ev=st.fr_ev.at[r, jj].set(
                jnp.where(write, code, st.fr_ev[r, jj]),
            ),
            fr_node=st.fr_node.at[r, jj].set(
                jnp.where(write, node, st.fr_node[r, jj]),
            ),
            fr_t=st.fr_t.at[r, jj].set(
                jnp.where(write, jnp.float32(t), st.fr_t[r, jj]),
            ),
            fr_n=st.fr_n.at[r].add(jnp.where(ok, 1, 0)),
        )

    def _fr(self, st: EngineState, i, code, node, t, pred) -> EngineState:
        """Record for pool slot ``i``'s request (untraced slots no-op)."""
        if self.trace is None:
            return st
        return self._fr_row(st, st.req_fr[i], code, node, t, pred)

    def _bk(self, st: EngineState, slot, state, t, pred) -> EngineState:
        """Append one circuit-breaker state transition to the scenario ring."""
        if self.trace is None:
            return st
        j = st.bk_n
        write = pred & (j < self._bk_cap)
        jj = jnp.clip(j, 0, self._bk_cap - 1)
        return st._replace(
            bk_t=st.bk_t.at[jj].set(
                jnp.where(write, jnp.float32(t), st.bk_t[jj]),
            ),
            bk_slot=st.bk_slot.at[jj].set(
                jnp.where(write, jnp.int32(slot), st.bk_slot[jj]),
            ),
            bk_state=st.bk_state.at[jj].set(
                jnp.where(write, jnp.int32(state), st.bk_state[jj]),
            ),
            bk_n=st.bk_n + jnp.where(write, 1, 0),
        )

    # ==================================================================
    # latency attribution (no-ops unless ``blame=True``; recording never
    # consumes a draw, so the event stream is identical with it on or off)
    # ==================================================================
    #
    # Cursor model: ``bl_t[i]`` is the time up to which slot ``i``'s
    # in-flight attempt is fully attributed and ``bl_cell[i]`` the cell
    # accruing since then.  Every event handler flushes the open span up to
    # ``now`` before repointing the cursor; flushing twice at the same
    # timestamp adds zero, so flushing liberally is safe.  Spans whose
    # duration is known up front (edge transits, hedge waits) skip the
    # cursor and credit directly.  Conservation — the row summing to the
    # attempt's end-to-end latency — holds by construction; a
    # mis-enumerated site can only misattribute, never leak time.

    def _bl_cs(self, s, phase):
        """Cell of (server ``s``, ``phase``) — works for traced ``s``."""
        return s * _bl.N_PHASES + phase

    def _bl_ce(self, e, phase):
        """Cell of (edge ``e``, ``phase``) — works for traced ``e``."""
        return (self.plan.n_servers + e) * _bl.N_PHASES + phase

    def _bl_cc(self, phase):
        """Cell of (virtual client, ``phase``)."""
        return _bl.cell(
            _bl.comp_client(self.plan.n_servers, self.plan.n_edges), phase,
        )

    def _bl_span(self, st: EngineState, i, c, secs, pred) -> EngineState:
        """Credit ``secs`` directly to cell ``c`` of slot ``i``'s attempt."""
        if not self.blame:
            return st
        v = jnp.where(pred, jnp.maximum(secs, 0.0), 0.0)
        return st._replace(req_bl=st.req_bl.at[i, c].add(v, mode="drop"))

    def _bl_set(self, st: EngineState, i, t, c, pred) -> EngineState:
        """Repoint the open cell WITHOUT flushing (cursor jump)."""
        if not self.blame:
            return st
        return st._replace(
            bl_t=st.bl_t.at[i].set(
                jnp.where(pred, jnp.float32(t), st.bl_t[i]), mode="drop",
            ),
            bl_cell=st.bl_cell.at[i].set(
                jnp.where(pred, jnp.int32(c), st.bl_cell[i]), mode="drop",
            ),
        )

    def _bl_flush(self, st: EngineState, i, t, pred) -> EngineState:
        """Credit the open span up to ``t`` and advance the cursor."""
        if not self.blame:
            return st
        dt = jnp.where(pred, jnp.maximum(jnp.float32(t) - st.bl_t[i], 0.0), 0.0)
        st = st._replace(
            req_bl=st.req_bl.at[i, st.bl_cell[i]].add(dt, mode="drop"),
        )
        return st._replace(
            bl_t=st.bl_t.at[i].set(
                jnp.where(pred, jnp.float32(t), st.bl_t[i]), mode="drop",
            ),
        )

    def _bl_zero(self, st: EngineState, i, t, c, pred) -> EngineState:
        """Fresh attempt in slot ``i``: clean row, cursor at ``t`` on ``c``."""
        if not self.blame:
            return st
        st = st._replace(
            req_bl=st.req_bl.at[i].set(
                jnp.where(pred, 0.0, st.req_bl[i]), mode="drop",
            ),
        )
        return self._bl_set(st, i, t, c, pred)

    def _bl_complete(self, st: EngineState, i, finish, latency, pred) -> EngineState:
        """Scatter slot ``i``'s row into the pooled grid at the attempt's
        coarse latency bin, add the latency to the conservation channel,
        and zero the row for slot reuse."""
        if not self.blame:
            return st
        st = self._bl_flush(st, i, finish, pred)
        b = jnp.clip(
            latency_bin(latency, self.hist_lo, self.hist_scale, self.n_hist_bins)
            // self._bl_stride,
            0,
            self._bl_bins - 1,
        )
        row = jnp.where(pred, st.req_bl[i], 0.0)
        st = st._replace(
            bl_grid=st.bl_grid.at[:, b].add(row),
            bl_lat=st.bl_lat.at[b].add(jnp.where(pred, latency, 0.0)),
        )
        if self.collect_clocks:
            # per-request row aligned with the clock row ``_complete`` is
            # about to claim (the conservation property test's witness)
            ridx = jnp.where(pred, st.clock_n, jnp.int32(st.bl_store.shape[0]))
            st = st._replace(
                bl_store=st.bl_store.at[ridx].set(st.req_bl[i], mode="drop"),
            )
        return st._replace(
            req_bl=st.req_bl.at[i].set(
                jnp.where(pred, 0.0, st.req_bl[i]), mode="drop",
            ),
        )

    # ==================================================================
    # small helpers
    # ==================================================================

    def _bucket(self, t):
        """Sample-tick bucket: a delta at ``t`` affects samples at ticks >= t.

        Rides the engine's gauge grid: the fine plan grid under
        ``collect_gauges``, the coarse ``n_samples // stride`` grid under
        ``gauge_series_stride`` (same interval-endpoint resample contract
        as the scan fast path)."""
        return sample_bucket(t, self._gauge_period, self._gauge_samples)

    def _g_edge(self, e):
        return self.plan.gauge_edge(e)

    def _g_ready(self, s):
        return self.plan.gauge_ready(s)

    def _g_io(self, s):
        return self.plan.gauge_io(s)

    def _g_ram(self, s):
        return self.plan.gauge_ram(s)

    def _spike(self, edge, t):
        if len(self.plan.spike_times) == 1:
            return jnp.float32(0.0)
        idx = searchsorted_small(self.params.spike_times, t, "right") - 1
        return self.params.spike_values[idx, edge]

    def _srv_faulted(self, s, t, ov):
        """1 while server ``s`` sits inside a server_outage fault window.
        Times AND value rows both ride the overrides: hand-authored
        timelines broadcast the plan table, chaos campaigns batch a
        sampled (S, K, NS) table per scenario."""
        if not self._has_srv_faults:
            return jnp.bool_(False)
        idx = jnp.maximum(
            searchsorted_small(ov.fault_srv_times, t, "right") - 1, 0,
        )
        return ov.fault_srv_down[idx, s] == 1

    def _edge_fault(self, e, t, ov):
        """(latency factor, dropout boost) active on edge ``e`` at ``t``."""
        idx = jnp.maximum(
            searchsorted_small(ov.fault_edge_times, t, "right") - 1, 0,
        )
        return (
            ov.fault_edge_lat[idx, e],
            ov.fault_edge_drop[idx, e],
        )

    def _sample_delay(self, edge, key, ov):
        """One latency draw for ``edge``; branches statically pruned to the
        distributions this plan actually uses."""
        dist = self.params.edge_dist[edge]
        mean = ov.edge_mean[edge]
        var = ov.edge_var[edge]
        u = draw_uniform(jax.random.fold_in(key, 1))
        delay = jnp.float32(0.0)
        if _D_UNIFORM in self._dists_present:
            delay = jnp.where(dist == _D_UNIFORM, u, delay)
        if _D_EXPONENTIAL in self._dists_present:
            delay = jnp.where(dist == _D_EXPONENTIAL, exponential_from_u(mean, u), delay)
        if {_D_NORMAL, _D_LOGNORMAL} & set(self._dists_present):
            z = draw_normal(jax.random.fold_in(key, 2))
            if _D_NORMAL in self._dists_present:
                delay = jnp.where(dist == _D_NORMAL, truncated_normal(mean, var, z), delay)
            if _D_LOGNORMAL in self._dists_present:
                delay = jnp.where(dist == _D_LOGNORMAL, lognormal(mean, var, z), delay)
        if _D_POISSON in self._dists_present:
            pois = jax.random.poisson(
                as_threefry(jax.random.fold_in(key, 3)),
                jnp.maximum(mean, _TINY),
            ).astype(jnp.float32)
            delay = jnp.where(dist == _D_POISSON, pois, delay)
        return delay

    def _sample_edge(self, edge, t_send, key, ov):
        """(dropped, effective delay incl. active spike) for one traversal.

        Fault windows gate the draw: an active edge fault multiplies the
        latency draw and boosts the dropout probability (partition windows
        boost it to 1), mirroring the oracle's ``_EdgeRuntime.transport``.
        """
        u = draw_uniform(jax.random.fold_in(key, 0))
        drop_p = ov.edge_dropout[edge]
        delay = self._sample_delay(edge, key, ov)
        if self._has_edge_faults:
            factor, boost = self._edge_fault(edge, t_send, ov)
            drop_p = jnp.clip(drop_p + boost, 0.0, 1.0)
            delay = delay * factor
        dropped = u < drop_p
        return dropped, delay + self._spike(edge, t_send)

    # ==================================================================
    # metric write primitives (masked; index clamped)
    # ==================================================================

    def _gauge_add(self, st: EngineState, t, gidx, val, pred) -> EngineState:
        if not self._collect_gauge_grid:
            return st
        v = jnp.where(pred, val, 0.0)
        return st._replace(gauge=st.gauge.at[self._bucket(t), gidx].add(v))

    def _edge_interval(self, st, edge, t0, t1, pred) -> EngineState:
        st = self._gauge_add(st, t0, self._g_edge(edge), 1.0, pred)
        return self._gauge_add(st, t1, self._g_edge(edge), -1.0, pred)

    def _complete(self, st: EngineState, start, finish, pred) -> EngineState:
        """Record one completed request: histogram, moments, throughput, clock."""
        latency = finish - start
        lbin = latency_bin(latency, self.hist_lo, self.hist_scale, self.n_hist_bins)
        tbin = jnp.clip(jnp.ceil(finish).astype(jnp.int32) - 1, 0, self.n_thr - 1)
        one = jnp.where(pred, 1, 0)
        lat = jnp.where(pred, latency, 0.0)
        st = st._replace(
            hist=st.hist.at[lbin].add(one),
            thr=st.thr.at[tbin].add(one),
            lat_count=st.lat_count + one,
            lat_sum=st.lat_sum + lat,
            lat_sumsq=st.lat_sumsq + lat * lat,
            lat_min=jnp.where(pred, jnp.minimum(st.lat_min, latency), st.lat_min),
            lat_max=jnp.where(pred, jnp.maximum(st.lat_max, latency), st.lat_max),
        )
        if self.collect_clocks:
            idx = jnp.where(pred, st.clock_n, jnp.int32(st.clock.shape[0]))
            st = st._replace(
                clock=st.clock.at[idx].set(
                    jnp.stack([start, finish]),
                    mode="drop",
                ),
                clock_n=st.clock_n + one,
            )
        return st

    # ==================================================================
    # client retry/timeout machinery (statically pruned without a policy)
    # ==================================================================

    def _consume_retry_token(self, st: EngineState, now, want):
        """(granted, state): lazily refill the retry-budget bucket and
        take one token for lanes in ``want``; denials count in
        ``n_budget_exhausted``.  Unlimited budgets grant unconditionally."""
        if self._rb_cap is None:
            return want, st
        refill = jnp.float32(self.plan.retry_budget_refill)
        tokens = jnp.minimum(
            jnp.float32(self._rb_cap),
            st.rb_tokens + (now - st.rb_last) * refill,
        )
        ok = want & (tokens >= 1.0)
        st = st._replace(
            rb_tokens=jnp.where(
                want, tokens - jnp.where(ok, 1.0, 0.0), st.rb_tokens,
            ),
            rb_last=jnp.where(want, now, st.rb_last),
            n_budget_exhausted=st.n_budget_exhausted
            + jnp.where(want & ~ok, 1, 0),
        )
        return ok, st

    def _backoff_delay(self, attempt, key):
        """Backoff before re-issuing after ``attempt`` failed:
        ``min(cap, base * mult**(attempt-1))`` times the jitter factor
        (uniform in [1-j, 1+j]); the draw is a pure function of the
        iteration key, so traces are seed-deterministic."""
        plan = self.plan
        expo = jnp.maximum(attempt.astype(jnp.float32) - 1.0, 0.0)
        delay = jnp.minimum(
            jnp.float32(plan.retry_backoff_cap),
            jnp.float32(plan.retry_backoff_base)
            * jnp.float32(plan.retry_backoff_mult) ** expo,
        )
        if plan.retry_jitter > 0:
            u = draw_uniform(jax.random.fold_in(key, 57))
            delay = delay * (
                1.0 + jnp.float32(plan.retry_jitter) * (2.0 * u - 1.0)
            )
        return delay

    def _record_attempts(self, st: EngineState, attempt, pred) -> EngineState:
        """A logical request ended (completed or given up): bin how many
        attempts it used."""
        if not self._has_retry:
            return st
        idx = jnp.clip(attempt - 1, 0, self._att_bins - 1)
        return st._replace(
            att_hist=st.att_hist.at[idx].add(jnp.where(pred, 1, 0)),
        )

    def _client_fail(self, st: EngineState, i, now, key, pred) -> EngineState:
        """A tracked attempt failed (edge drop, refusal, shed, abandon,
        outage) and the client notices at failure time: re-park slot ``i``
        as an EV_RETRY backoff wait, or give the logical request up.

        Runs AFTER the failure site freed the slot, so give-up lanes stay
        freed; retry lanes are re-claimed in place (no allocation race —
        spawn and pool branches are disjoint within one iteration).
        Orphaned attempts (client already timed out) just stay freed.

        Hedge duplicates are invisible to the retry ladder: a failed
        duplicate dies silently (its anchor refcount drops; the primary's
        own ladder is untouched).  A primary that gives its logical
        request up also stops the race — late siblings dedup as losers."""
        if not (self._has_retry or self._has_hedge):
            return st
        can = jnp.bool_(False)
        if self._has_retry:
            tracked = pred & (st.req_orphan[i] == 0)
            if self._has_hedge:
                tracked = tracked & (st.req_is_hedge[i] == 0)
            attempt = st.req_attempt[i]
            want = tracked & (attempt < self.plan.retry_max_attempts)
            can, st = self._consume_retry_token(st, now, want)
            delay = self._backoff_delay(attempt, key)
            st = st._replace(
                req_ev=st.req_ev.at[i].set(
                    jnp.where(can, EV_RETRY, st.req_ev[i]),
                ),
                req_t=st.req_t.at[i].set(
                    jnp.where(can, now + delay, st.req_t[i]),
                ),
                req_attempt=st.req_attempt.at[i].set(
                    jnp.where(can, attempt + 1, attempt),
                ),
                req_deadline=st.req_deadline.at[i].set(
                    jnp.where(pred, INF, st.req_deadline[i]),
                ),
                req_orphan=st.req_orphan.at[i].set(
                    jnp.where(pred, 0, st.req_orphan[i]),
                ),
                n_retries=st.n_retries + jnp.where(can, 1, 0),
            )
            if self.trace is not None:
                st = self._fr(st, i, FR_RETRY, attempt, now, can)
                st = self._fr(st, i, FR_ABANDON, attempt, now, tracked & ~can)
            st = self._record_attempts(st, attempt, tracked & ~can)
            if self._has_hedge:
                gave_up = tracked & ~can
                anchor = st.req_prime[i]
                st = st._replace(
                    hg_done=st.hg_done.at[anchor].set(
                        jnp.where(gave_up, 1, st.hg_done[anchor]),
                    ),
                    hg_t=st.hg_t.at[anchor].set(
                        jnp.where(gave_up, INF, st.hg_t[anchor]),
                    ),
                )
        if self._has_hedge:
            st = self._hedge_release(st, i, pred & ~can)
        return st

    def _timeout_branch(self, st: EngineState, i, now, key, ov, pred) -> EngineState:
        """Slot ``i``'s client deadline fired while the attempt is still in
        flight: orphan it (the server keeps processing — the retry-storm
        amplification channel) and either park a NEW slot for the backoff
        re-issue or give the logical request up."""
        if not self._has_retry:
            return st
        attempt = st.req_attempt[i]
        st = st._replace(
            n_timed_out=st.n_timed_out + jnp.where(pred, 1, 0),
            req_deadline=st.req_deadline.at[i].set(
                jnp.where(pred, INF, st.req_deadline[i]),
            ),
            req_orphan=st.req_orphan.at[i].set(
                jnp.where(pred, 1, st.req_orphan[i]),
            ),
        )
        want = pred & (attempt < self.plan.retry_max_attempts)
        can, st = self._consume_retry_token(st, now, want)
        free_mask = st.req_ev == EV_IDLE
        if self._has_hedge:
            free_mask = free_mask & (st.hg_live == 0)
        slot = jnp.argmax(free_mask).astype(jnp.int32)
        has_free = free_mask[slot]
        place = can & has_free
        overflow = can & ~has_free
        delay = self._backoff_delay(attempt, key)
        idx = jnp.where(place, slot, jnp.int32(self.pool))
        st = st._replace(
            req_ev=st.req_ev.at[idx].set(EV_RETRY, mode="drop"),
            req_t=st.req_t.at[idx].set(now + delay, mode="drop"),
            req_attempt=st.req_attempt.at[idx].set(attempt + 1, mode="drop"),
            req_deadline=st.req_deadline.at[idx].set(INF, mode="drop"),
            req_orphan=st.req_orphan.at[idx].set(0, mode="drop"),
            req_ram=st.req_ram.at[idx].set(0.0, mode="drop"),
            req_ticket=st.req_ticket.at[idx].set(NO_TICKET, mode="drop"),
            req_lbslot=st.req_lbslot.at[idx].set(-1, mode="drop"),
            n_retries=st.n_retries + jnp.where(place, 1, 0),
            n_overflow=st.n_overflow + jnp.where(overflow, 1, 0),
        )
        if self._has_hedge:
            # the backoff re-issue is one more live attempt of the SAME
            # logical request: it inherits the anchor pointer (the
            # orphaned slot keeps draining on its own); a give-up stops
            # the race so late siblings dedup as losers
            anchor = st.req_prime[i]
            st = st._replace(
                req_prime=st.req_prime.at[idx].set(anchor, mode="drop"),
                req_is_hedge=st.req_is_hedge.at[idx].set(
                    st.req_is_hedge[i], mode="drop",
                ),
                hg_live=st.hg_live.at[anchor].add(jnp.where(place, 1, 0)),
            )
            gave_up = pred & ~place
            st = st._replace(
                hg_done=st.hg_done.at[anchor].set(
                    jnp.where(gave_up, 1, st.hg_done[anchor]),
                ),
                hg_t=st.hg_t.at[anchor].set(
                    jnp.where(gave_up, INF, st.hg_t[anchor]),
                ),
            )
        if self._has_brownout:
            st = st._replace(
                req_degraded=st.req_degraded.at[idx].set(0, mode="drop"),
            )
        if self._has_llm:
            st = st._replace(req_llm=st.req_llm.at[idx].set(0.0, mode="drop"))
        if self._has_serving:
            # the re-issue redraws its token budgets from scratch
            st = st._replace(
                req_tok_in=st.req_tok_in.at[idx].set(-1.0, mode="drop"),
                req_tok_out=st.req_tok_out.at[idx].set(-1.0, mode="drop"),
                req_sv_evict=st.req_sv_evict.at[idx].set(0, mode="drop"),
                req_sv_hold=st.req_sv_hold.at[idx].set(0.0, mode="drop"),
            )
        if self.trace is not None:
            # the logical request's record rides its ring row: the orphaned
            # slot stops recording (oracle contract: orphan completions are
            # invisible) and the backoff re-issue slot inherits the row
            row0 = st.req_fr[i]
            st = self._fr_row(st, row0, FR_TIMEOUT, attempt, now, pred)
            st = self._fr_row(st, row0, FR_RETRY, attempt, now, place)
            st = self._fr_row(st, row0, FR_ABANDON, attempt, now, pred & ~place)
            st = st._replace(
                req_fr=st.req_fr.at[idx].set(row0, mode="drop"),
            )
            # the orphaned slot always detaches (the re-issue slot, when
            # placed, is a different — free — slot, so this never undoes it)
            st = st._replace(
                req_fr=st.req_fr.at[i].set(
                    jnp.where(pred, -1, st.req_fr[i]),
                ),
            )
        # gave up: attempt cap, budget denial, or pool overflow
        return self._record_attempts(st, attempt, pred & ~place)

    def _retry_branch(self, st: EngineState, i, now, key, ov, pred) -> EngineState:
        """An EV_RETRY park elapsed: re-issue the request down the (single
        generator's) entry chain — the re-issue is a fresh attempt with its
        own start time and client deadline."""
        if not self._has_retry:
            return st
        plan = self.plan
        alive = pred
        t_cur = now
        if self.trace is not None:
            st = self._fr(st, i, FR_SPAWN, 0, now, pred)
        # fresh attempt: the attribution clock restarts with the re-issue
        st = self._bl_zero(st, i, now, self._bl_cc(_bl.PH_TRANSIT), pred)
        for j, eidx in enumerate(plan.entry_edges.tolist()):
            e = jnp.int32(eidx)
            dropped, delay = self._sample_edge(
                e, t_cur, jax.random.fold_in(key, 8 + j), ov,
            )
            survives = alive & ~dropped
            st = self._edge_interval(st, e, t_cur, t_cur + delay, survives)
            st = st._replace(
                n_dropped=st.n_dropped + jnp.where(alive & dropped, 1, 0),
            )
            if self.trace is not None:
                st = self._fr(st, i, FR_DROP, e, t_cur, alive & dropped)
                st = self._fr(
                    st, i, FR_TRANSIT, e, t_cur + delay, survives,
                )
            st = self._bl_span(
                st, i, self._bl_ce(eidx, _bl.PH_TRANSIT), delay, survives,
            )
            t_cur = jnp.where(survives, t_cur + delay, t_cur)
            alive = survives
        st = self._bl_set(st, i, t_cur, self._bl_cc(_bl.PH_TRANSIT), alive)
        ev0 = (
            EV_ARRIVE_LB
            if plan.entry_target_kind == TARGET_LB
            else EV_ARRIVE_SRV
        )
        st = st._replace(
            req_ev=st.req_ev.at[i].set(jnp.where(alive, ev0, st.req_ev[i])),
            req_t=st.req_t.at[i].set(jnp.where(alive, t_cur, st.req_t[i])),
            req_srv=st.req_srv.at[i].set(
                jnp.where(
                    alive, jnp.int32(max(plan.entry_target, 0)), st.req_srv[i],
                ),
            ),
            req_start=st.req_start.at[i].set(
                jnp.where(pred, now, st.req_start[i]),
            ),
            req_deadline=st.req_deadline.at[i].set(
                jnp.where(alive, now + ov.retry_timeout, st.req_deadline[i]),
            ),
            req_lbslot=st.req_lbslot.at[i].set(
                jnp.where(pred, -1, st.req_lbslot[i]),
            ),
            req_ram=st.req_ram.at[i].set(jnp.where(pred, 0.0, st.req_ram[i])),
            req_ticket=st.req_ticket.at[i].set(
                jnp.where(pred, NO_TICKET, st.req_ticket[i]),
            ),
        )
        if self._has_serving:
            st = st._replace(
                req_tok_in=st.req_tok_in.at[i].set(
                    jnp.where(pred, -1.0, st.req_tok_in[i]),
                ),
                req_tok_out=st.req_tok_out.at[i].set(
                    jnp.where(pred, -1.0, st.req_tok_out[i]),
                ),
                req_sv_evict=st.req_sv_evict.at[i].set(
                    jnp.where(pred, 0, st.req_sv_evict[i]),
                ),
                req_sv_hold=st.req_sv_hold.at[i].set(
                    jnp.where(pred, 0.0, st.req_sv_hold[i]),
                ),
            )
        # dropped on the entry chain: this attempt failed before arriving
        dead = pred & ~alive
        st = st._replace(
            req_ev=st.req_ev.at[i].set(
                jnp.where(dead, EV_IDLE, st.req_ev[i]),
            ),
            req_t=st.req_t.at[i].set(jnp.where(dead, INF, st.req_t[i])),
        )
        return self._client_fail(st, i, now, key, dead)

    def _client_arrive_branch(self, st, i, now, key, ov, pred) -> EngineState:
        """Final delivery at the client (retry/hedge plans): a non-orphan
        arrival completes the logical request; an orphaned one is the
        server-side tail of an abandoned attempt and records nothing.
        With a hedge policy the FIRST sibling home wins the race; later
        arrivals dedup silently — one completion per logical request."""
        if not (self._has_retry or self._has_hedge):
            return st
        done = pred
        if self._has_retry:
            done = done & (st.req_orphan[i] == 0)
        anchor = i
        if self._has_hedge:
            anchor = st.req_prime[i]
            loser = done & (st.hg_done[anchor] == 1)
            done = done & ~loser
            st = st._replace(
                hg_done=st.hg_done.at[anchor].set(
                    jnp.where(done, 1, st.hg_done[anchor]),
                ),
                hg_t=st.hg_t.at[anchor].set(
                    jnp.where(done, INF, st.hg_t[anchor]),
                ),
                n_hedges_won=st.n_hedges_won
                + jnp.where(done & (st.req_is_hedge[i] == 1), 1, 0),
            )
            if self.trace is not None:
                st = self._fr_row(
                    st,
                    st.req_fr[anchor],
                    FR_CANCEL,
                    st.req_is_hedge[i],
                    now,
                    loser,
                )
        if self._has_brownout:
            st = st._replace(
                n_degraded=st.n_degraded
                + jnp.where(done & (st.req_degraded[i] == 1), 1, 0),
            )
        st = self._record_attempts(st, st.req_attempt[i], done)
        if self._has_llm:
            cost = st.req_llm[i]
            st = st._replace(
                llm_sum=st.llm_sum + jnp.where(done, cost, 0.0),
                llm_sumsq=st.llm_sumsq + jnp.where(done, cost * cost, 0.0),
            )
            if self.collect_clocks:
                lidx = jnp.where(
                    done, st.clock_n, jnp.int32(st.llm_store.shape[0]),
                )
                st = st._replace(
                    llm_store=st.llm_store.at[lidx].set(cost, mode="drop"),
                )
        if self.collect_traces:
            st = self._hop(st, i, self.HOP_CLIENT, now, done)
            idx = jnp.where(done, st.clock_n, jnp.int32(st.tr_code.shape[0]))
            st = st._replace(
                tr_code=st.tr_code.at[idx].set(st.req_hops[i], mode="drop"),
                tr_t=st.tr_t.at[idx].set(st.req_hop_t[i], mode="drop"),
                tr_n=st.tr_n.at[idx].set(
                    jnp.minimum(st.req_hop_n[i], self._hop_cap),
                    mode="drop",
                ),
            )
        if self.trace is not None:
            # the logical request's record rides the ANCHOR's ring row (a
            # winning duplicate completes the primary's record)
            st = self._fr_row(st, st.req_fr[anchor], FR_COMPLETE, -1, now, done)
        st = self._bl_complete(st, i, now, now - st.req_start[i], done)
        st = self._complete(st, st.req_start[i], now, done)
        st = st._replace(
            req_ev=st.req_ev.at[i].set(jnp.where(pred, EV_IDLE, st.req_ev[i])),
            req_t=st.req_t.at[i].set(jnp.where(pred, INF, st.req_t[i])),
        )
        if self._has_retry:
            st = st._replace(
                req_deadline=st.req_deadline.at[i].set(
                    jnp.where(pred, INF, st.req_deadline[i]),
                ),
                req_orphan=st.req_orphan.at[i].set(
                    jnp.where(pred, 0, st.req_orphan[i]),
                ),
            )
        if self._has_hedge:
            st = self._hedge_release(st, i, pred)
        return st

    # ==================================================================
    # hedged-request machinery (statically pruned without a policy)
    # ==================================================================

    def _hedge_release(self, st: EngineState, i, pred) -> EngineState:
        """Slot ``i``'s attempt drained: drop the anchor's live-attempt
        refcount.  At zero the logical request is gone — reset the
        anchor's hedge state so its slot can be reclaimed (hedging
        duplicates OUTSTANDING work; it never resurrects a request whose
        every attempt already failed)."""
        if not self._has_hedge:
            return st
        anchor = st.req_prime[i]
        live = jnp.maximum(st.hg_live[anchor] - 1, 0)
        gone = pred & (live == 0)
        return st._replace(
            hg_live=st.hg_live.at[anchor].set(
                jnp.where(pred, live, st.hg_live[anchor]),
            ),
            hg_t=st.hg_t.at[anchor].set(
                jnp.where(gone, INF, st.hg_t[anchor]),
            ),
            hg_n=st.hg_n.at[anchor].set(
                jnp.where(gone, 0, st.hg_n[anchor]),
            ),
            hg_done=st.hg_done.at[anchor].set(
                jnp.where(gone, 0, st.hg_done[anchor]),
            ),
        )

    def _hedge_checkpoint(self, st: EngineState, i, now, pred):
        """Routing-boundary cancellation (``cancel_on_first`` only): when
        the race is already won, the arriving loser — primary or duplicate
        alike — is cancelled here instead of admitted.  Work already
        inside a server runs to completion as an orphan; cancellation
        never claws back admitted work.  A cancelled attempt vanishes
        WITHOUT reporting to the breaker/health channels (its half-open
        probe reservation is returned so the round isn't starved)."""
        if not (self._has_hedge and self._hedge_cancel):
            return st, pred
        anchor = st.req_prime[i]
        cancel = pred & (st.hg_done[anchor] == 1)
        if self.trace is not None:
            # node = 0 the primary lost, 1 a duplicate lost
            st = self._fr_row(
                st,
                st.req_fr[anchor],
                FR_CANCEL,
                st.req_is_hedge[i],
                now,
                cancel,
            )
        if self._has_breaker:
            slot = st.req_cbslot[i]
            unprobe = cancel & (slot >= 0) & (st.req_probe[i] > 0)
            st = st._replace(
                cb_probes_out=st.cb_probes_out.at[jnp.clip(slot, 0, None)]
                .add(jnp.where(unprobe, -1, 0)),
            )
            st = st._replace(
                cb_probes_out=jnp.maximum(st.cb_probes_out, 0),
            )
        if self._has_report:
            st = st._replace(
                req_cbslot=st.req_cbslot.at[i].set(
                    jnp.where(cancel, -1, st.req_cbslot[i]),
                ),
                req_probe=st.req_probe.at[i].set(
                    jnp.where(cancel, 0, st.req_probe[i]),
                ),
            )
        if self._has_retry:
            st = st._replace(
                req_deadline=st.req_deadline.at[i].set(
                    jnp.where(cancel, INF, st.req_deadline[i]),
                ),
            )
        st = st._replace(
            req_ev=st.req_ev.at[i].set(
                jnp.where(cancel, EV_IDLE, st.req_ev[i]),
            ),
            req_t=st.req_t.at[i].set(jnp.where(cancel, INF, st.req_t[i])),
            n_hedges_cancelled=st.n_hedges_cancelled
            + jnp.where(cancel, 1, 0),
        )
        st = self._hedge_release(st, i, cancel)
        return st, pred & ~cancel

    def _hedge_branch(self, st: EngineState, i, now, key, ov, pred) -> EngineState:
        """Anchor ``i``'s hedge timer fired: issue a speculative duplicate
        down the (single generator's) entry chain without abandoning the
        original.  The duplicate copies the logical request's identity —
        anchor pointer, start time, attempt number — but carries no client
        deadline (hedges are invisible to the retry ladder) and records
        only FR_HEDGE here: its transit noise stays out of the flight
        record.  The timer re-arms one delay out until the per-request
        budget is spent."""
        if not self._has_hedge:
            return st
        plan = self.plan
        fire = pred & (st.hg_done[i] == 0) & (st.hg_n[i] < self._hedge_max)
        ordinal = st.hg_n[i] + 1
        st = st._replace(
            # stale timers (race won / budget spent) just disarm
            hg_t=st.hg_t.at[i].set(
                jnp.where(
                    pred,
                    jnp.where(
                        fire & (ordinal < self._hedge_max),
                        now + ov.hedge_delay,
                        INF,
                    ),
                    st.hg_t[i],
                ),
            ),
            hg_n=st.hg_n.at[i].set(jnp.where(fire, ordinal, st.hg_n[i])),
            n_hedges=st.n_hedges + jnp.where(fire, 1, 0),
        )
        if self.trace is not None:
            st = self._fr_row(st, st.req_fr[i], FR_HEDGE, ordinal, now, fire)
        alive = fire
        t_cur = now
        bl_hops = []  # (eidx, delay, survives) — replayed onto the dup slot
        for j, eidx in enumerate(plan.entry_edges.tolist()):
            e = jnp.int32(eidx)
            dropped, delay = self._sample_edge(
                e, t_cur, jax.random.fold_in(key, 8 + j), ov,
            )
            survives = alive & ~dropped
            st = self._edge_interval(st, e, t_cur, t_cur + delay, survives)
            st = st._replace(
                n_dropped=st.n_dropped + jnp.where(alive & dropped, 1, 0),
            )
            bl_hops.append((eidx, delay, survives))
            t_cur = jnp.where(survives, t_cur + delay, t_cur)
            alive = survives
        free_mask = (st.req_ev == EV_IDLE) & (st.hg_live == 0)
        slot = jnp.argmax(free_mask).astype(jnp.int32)
        has_free = free_mask[slot]
        place = alive & has_free
        overflow = alive & ~has_free
        ev0 = (
            EV_ARRIVE_LB
            if plan.entry_target_kind == TARGET_LB
            else EV_ARRIVE_SRV
        )
        idx = jnp.where(place, slot, jnp.int32(self.pool))
        st = st._replace(
            req_ev=st.req_ev.at[idx].set(ev0, mode="drop"),
            req_t=st.req_t.at[idx].set(t_cur, mode="drop"),
            req_srv=st.req_srv.at[idx].set(
                jnp.int32(max(plan.entry_target, 0)), mode="drop",
            ),
            req_start=st.req_start.at[idx].set(
                st.req_start[i], mode="drop",
            ),
            req_lbslot=st.req_lbslot.at[idx].set(-1, mode="drop"),
            req_ram=st.req_ram.at[idx].set(0.0, mode="drop"),
            req_ticket=st.req_ticket.at[idx].set(NO_TICKET, mode="drop"),
            req_prime=st.req_prime.at[idx].set(i, mode="drop"),
            req_is_hedge=st.req_is_hedge.at[idx].set(1, mode="drop"),
            hg_live=st.hg_live.at[i].add(jnp.where(place, 1, 0)),
            n_overflow=st.n_overflow + jnp.where(overflow, 1, 0),
        )
        if self.blame:
            # the duplicate inherits the anchor's start time, so a winning
            # duplicate's latency CONTAINS the hedge wait [anchor start,
            # fire): credit it to the virtual client, then replay the
            # duplicate's own entry chain
            st = self._bl_zero(
                st, idx, t_cur, self._bl_cc(_bl.PH_TRANSIT), place,
            )
            st = self._bl_span(
                st,
                idx,
                self._bl_cc(_bl.PH_HEDGE),
                now - st.req_start[i],
                place,
            )
            for eidx2, delay2, survives2 in bl_hops:
                st = self._bl_span(
                    st,
                    idx,
                    self._bl_ce(eidx2, _bl.PH_TRANSIT),
                    delay2,
                    place & survives2,
                )
        if self._has_retry:
            st = st._replace(
                req_deadline=st.req_deadline.at[idx].set(INF, mode="drop"),
                req_attempt=st.req_attempt.at[idx].set(
                    st.req_attempt[i], mode="drop",
                ),
                req_orphan=st.req_orphan.at[idx].set(0, mode="drop"),
            )
        if self._has_brownout:
            st = st._replace(
                req_degraded=st.req_degraded.at[idx].set(0, mode="drop"),
            )
        if self._has_llm:
            st = st._replace(
                req_llm=st.req_llm.at[idx].set(0.0, mode="drop"),
            )
        if self._has_serving:
            st = st._replace(
                req_tok_in=st.req_tok_in.at[idx].set(-1.0, mode="drop"),
                req_tok_out=st.req_tok_out.at[idx].set(-1.0, mode="drop"),
                req_sv_evict=st.req_sv_evict.at[idx].set(0, mode="drop"),
                req_sv_hold=st.req_sv_hold.at[idx].set(0.0, mode="drop"),
            )
        if self._crn:
            # the duplicate draws from the logical request's CRN family on
            # a disjoint draw band (primaries count draws from 0)
            st = st._replace(
                req_seq=st.req_seq.at[idx].set(st.req_seq[i], mode="drop"),
                req_draws=st.req_draws.at[idx].set(
                    10000 * ordinal, mode="drop",
                ),
            )
        if self.trace is not None:
            st = st._replace(req_fr=st.req_fr.at[idx].set(-1, mode="drop"))
        if self.collect_traces:
            st = st._replace(
                req_hop_n=st.req_hop_n.at[idx].set(0, mode="drop"),
            )
        return st

    # ==================================================================
    # arrival sampler (window-jump semantics cloned from the reference)
    # ==================================================================

    def _advance_arrival(
        self, st: EngineState, key, ov, pred, gen: int | None = None,
    ) -> EngineState:
        """Compute the next emitted gap; sim arrival time += gap (no jump time).

        `/root/reference/src/asyncflow/samplers/poisson_poisson.py:56-82`.
        ``gen`` selects a generator's stream on multi-generator plans (a
        STATIC index: callers loop generators at trace time); the arrival
        state fields are (G,) vectors there, scalars on legacy plans, and
        the workload override fields are (G,) vectors indexed per stream
        (the sweep layer validates the (S, G) shape).
        """
        plan = self.plan
        horizon = jnp.float32(plan.horizon)
        multi = gen is not None
        if multi:
            window = jnp.float32(plan.gen_window[gen])
            poisson_users = plan.gen_user_var[gen] < 0
            # workload overrides carry (G,) / (S, G) fields on multi-
            # generator plans (base_overrides): index this stream's slot
            g_user_mean = ov.user_mean[gen]
            g_user_var = jnp.float32(plan.gen_user_var[gen])
            g_rate = ov.req_rate[gen]
        else:
            window = jnp.float32(plan.user_window)
            poisson_users = plan.user_var < 0

        def cond(carry):
            return carry[4] == 0

        def body(carry):
            smp_now, window_end, lam, dctr, _status, gap = carry
            kd = jax.random.fold_in(key, 64 + dctr)
            # sampler clock past the horizon: exhausted (the oracle's
            # `if smp_now >= horizon: return -1`) — without this, a
            # zero-rate stream (user_mean override 0) would walk windows
            # forever
            at_end = smp_now >= horizon
            need_window = smp_now >= window_end
            u_mean = g_user_mean if multi else ov.user_mean
            u_rate = g_rate if multi else ov.req_rate
            u_var = g_user_var if multi else self.params.user_var
            if poisson_users:
                users = jax.random.poisson(
                    as_threefry(jax.random.fold_in(kd, 0)),
                    jnp.maximum(u_mean, _TINY),
                ).astype(jnp.float32)
            else:
                z = draw_normal(jax.random.fold_in(kd, 1))
                users = jnp.maximum(0.0, u_mean + u_var * z)
            window_end = jnp.where(need_window, smp_now + window, window_end)
            lam = jnp.where(need_window, users * u_rate, lam)

            no_users = lam <= 0.0
            u = jnp.maximum(draw_uniform(jax.random.fold_in(kd, 2)), _TINY)
            g = -jnp.log(1.0 - u) / jnp.maximum(lam, _TINY)
            beyond = smp_now + g > horizon
            crosses = smp_now + g >= window_end

            smp_next = jnp.where(
                no_users,
                window_end,
                jnp.where(beyond, smp_now, jnp.where(crosses, window_end, smp_now + g)),
            )
            status = jnp.where(
                no_users,
                0,
                jnp.where(beyond, 2, jnp.where(crosses, 0, 1)),
            ).astype(jnp.int32)
            status = jnp.where(at_end, 2, status)
            smp_next = jnp.where(at_end, smp_now, smp_next)
            return (smp_next, window_end, lam, dctr + 1, status, jnp.where(status == 1, g, gap))

        init = (
            st.smp_now[gen] if multi else st.smp_now,
            st.smp_window_end[gen] if multi else st.smp_window_end,
            st.smp_lam[gen] if multi else st.smp_lam,
            jnp.int32(0),
            jnp.where(pred, jnp.int32(0), jnp.int32(1)),  # inactive lanes: done
            jnp.float32(0.0),
        )
        smp_now, window_end, lam, _, status, gap = jax.lax.while_loop(cond, body, init)
        exhausted = status == 2
        if multi:
            next_t = jnp.where(exhausted, INF, st.next_arrival[gen] + gap)
            upd = pred
            return st._replace(
                smp_now=st.smp_now.at[gen].set(
                    jnp.where(upd, smp_now, st.smp_now[gen]),
                ),
                smp_window_end=st.smp_window_end.at[gen].set(
                    jnp.where(upd, window_end, st.smp_window_end[gen]),
                ),
                smp_lam=st.smp_lam.at[gen].set(
                    jnp.where(upd, lam, st.smp_lam[gen]),
                ),
                next_arrival=st.next_arrival.at[gen].set(
                    jnp.where(upd, next_t, st.next_arrival[gen]),
                ),
            )
        next_t = jnp.where(exhausted, INF, st.next_arrival + gap)
        return st._replace(
            smp_now=jnp.where(pred, smp_now, st.smp_now),
            smp_window_end=jnp.where(pred, window_end, st.smp_window_end),
            smp_lam=jnp.where(pred, lam, st.smp_lam),
            next_arrival=jnp.where(pred, next_t, st.next_arrival),
        )

    # ==================================================================
    # LB rotation (dense prefix of lb_order, length lb_len)
    # ==================================================================

    def _lb_pick(self, st: EngineState):
        """(slot, rotated order) per algorithm; caller guards empty rotation."""
        el = max(self.plan.n_lb_edges, 1)
        if self.plan.lb_algo == 0:  # round robin: head out, rotate to tail
            slot = st.lb_order[0]
            return slot, rotation_advance(st.lb_order, st.lb_len, True, el)
        pos = jnp.arange(el, dtype=jnp.int32)
        valid = pos < st.lb_len
        conn = st.lb_conn[st.lb_order]
        order_key = jnp.where(valid, conn * el + pos, jnp.int32(2**30))
        best = jnp.argmin(order_key).astype(jnp.int32)
        return st.lb_order[best], st.lb_order

    def _lb_pick_weighted(self, st: EngineState, weights, key, admits=None):
        """(slot, none_eligible): sample a rotation member ~ its weight.

        The RL playground's action channel (`rl/batched.py`), mirroring
        the oracle's ``lb_weights`` hook
        (`engines/oracle/engine.py:525-536`): weights index LB slots in
        topology order, eligibility (rotation membership, breaker admits)
        applies first, an all-zero eligible mass falls back to uniform,
        and the rotation order is left untouched.
        """
        el = max(self.plan.n_lb_edges, 1)
        pos = jnp.arange(el, dtype=jnp.int32)
        valid = pos < st.lb_len
        elig = valid if admits is None else valid & admits[st.lb_order]
        w = jnp.where(elig, jnp.maximum(weights[st.lb_order], 0.0), 0.0)
        total = jnp.sum(w)
        w = jnp.where(total > 0, w, elig.astype(jnp.float32))
        cum = jnp.cumsum(w)
        u = draw_uniform(key) * cum[-1]
        idx = jnp.sum((cum <= u).astype(jnp.int32))
        # float rounding can put u exactly at cum[-1] (idx == el); clamp to
        # the LAST ELIGIBLE slot, never a removed/ineligible position
        last_elig = el - 1 - jnp.argmax(jnp.flip(elig).astype(jnp.int32))
        idx = jnp.minimum(idx, last_elig)
        return st.lb_order[idx], ~jnp.any(elig)

    def _lb_pick_breaker(self, st: EngineState, admits):
        """(slot, rotated order, none_admitting) honoring breaker state.

        Round robin: FIRST admitting slot in rotation order is picked and
        moved to the tail; non-admitting slots keep their positions (the
        breaker skips, it does not reorder).  Least connections: masked
        argmin over admitting rotation members."""
        el = max(self.plan.n_lb_edges, 1)
        pos = jnp.arange(el, dtype=jnp.int32)
        valid = pos < st.lb_len
        elig = valid & admits[st.lb_order]
        any_elig = jnp.any(elig)
        if self.plan.lb_algo == 0:
            first = jnp.argmax(elig).astype(jnp.int32)
            slot = st.lb_order[first]
            order, length = self._lb_remove(
                st.lb_order, st.lb_len, slot, any_elig,
            )
            order, _ = self._lb_insert(order, length, slot, any_elig)
            return slot, order, ~any_elig
        conn = st.lb_conn[st.lb_order]
        order_key = jnp.where(elig, conn * el + pos, jnp.int32(2**30))
        best = jnp.argmin(order_key).astype(jnp.int32)
        return st.lb_order[best], st.lb_order, ~any_elig

    def _lb_remove(self, order, length, slot, pred):
        return rotation_remove(order, length, slot, pred, max(self.plan.n_lb_edges, 1))

    def _lb_insert(self, order, length, slot, pred):
        return rotation_insert(order, length, slot, pred, max(self.plan.n_lb_edges, 1))

    # ==================================================================
    # branches (all updates masked by disjoint predicates)
    # ==================================================================

    def _timeline_branch(self, st: EngineState, pred) -> EngineState:
        if len(self.plan.timeline_times) == 0:
            return st
        p = self.params
        ptr = jnp.clip(st.tl_ptr, 0, len(self.plan.timeline_times) - 1)
        slot = p.timeline_slot[ptr]
        down = p.timeline_down[ptr] == 1
        act = pred & (slot >= 0)
        order, length = self._lb_remove(st.lb_order, st.lb_len, slot, act & down)
        order, length = self._lb_insert(order, length, slot, act & ~down)
        return st._replace(
            lb_order=order,
            lb_len=length,
            tl_ptr=st.tl_ptr + jnp.where(pred, 1, 0),
        )

    def _spawn_branch(self, st: EngineState, now, key, ov, pred) -> EngineState:
        """Generator emits one request: walk the static entry chain, allocate
        a pool slot at the first stateful node, schedule the next arrival."""
        plan = self.plan
        st = st._replace(n_generated=st.n_generated + jnp.where(pred, 1, 0))
        fr_row = jnp.int32(-1)
        if self.trace is not None:
            # deterministic sampling: the first K spawns own ring rows
            # (n_generated was just incremented, so the 0-based spawn
            # sequence of this lane is n_generated - 1)
            seq = st.n_generated - 1
            fr_row = jnp.where(pred & (seq < self._fr_k), seq, jnp.int32(-1))

        if self._n_gen > 1:
            # multi-generator: the spawning stream is the earliest
            # next_arrival; its (static) chain/target apply under a mask
            g = jnp.argmin(st.next_arrival).astype(jnp.int32)
            chains = [
                plan.gen_entry_edges[gi, : plan.gen_entry_len[gi]].tolist()
                for gi in range(self._n_gen)
            ]
        else:
            g = jnp.int32(0)
            chains = [plan.entry_edges.tolist()]

        alive = pred
        t_cur = now
        hop_chain = []  # (gi, eidx, delivery time) — for the trace rings
        for gi, chain in enumerate(chains):
            pred_gi = alive & (g == gi)
            t_gi = now
            # disjoint subkey range per generator: 100000+gi cannot
            # collide with the arrival sampler's 64+dctr folds (dctr is
            # bounded by windows-per-horizon, orders of magnitude smaller)
            key_gi = (
                jax.random.fold_in(key, 100000 + gi) if len(chains) > 1 else key
            )
            if self.trace is not None:
                st = self._fr_row(st, fr_row, FR_SPAWN, gi, now, pred_gi)
            for j, eidx in enumerate(chain):
                e = jnp.int32(eidx)
                dropped, delay = self._sample_edge(
                    e,
                    t_gi,
                    jax.random.fold_in(key_gi, 8 + j),
                    ov,
                )
                survives = pred_gi & ~dropped
                st = self._edge_interval(st, e, t_gi, t_gi + delay, survives)
                st = st._replace(
                    n_dropped=st.n_dropped + jnp.where(pred_gi & dropped, 1, 0),
                )
                if self.trace is not None:
                    st = self._fr_row(
                        st, fr_row, FR_DROP, e, t_gi, pred_gi & dropped,
                    )
                    st = self._fr_row(
                        st, fr_row, FR_TRANSIT, e, t_gi + delay, survives,
                    )
                t_gi = jnp.where(survives, t_gi + delay, t_gi)
                pred_gi = survives
                hop_chain.append((gi, eidx, t_gi))
            t_cur = jnp.where(g == gi, t_gi, t_cur)
            alive = jnp.where(g == gi, pred_gi, alive)

        free_mask = st.req_ev == EV_IDLE
        if self._has_hedge:
            # a freed anchor slot stays reserved while sibling attempts
            # are still in flight (its identity fields must survive)
            free_mask = free_mask & (st.hg_live == 0)
        slot = jnp.argmax(free_mask).astype(jnp.int32)
        has_free = free_mask[slot]
        overflow = alive & ~has_free
        place = alive & has_free
        # with a retry policy, an entry-chain drop is a FAILED first
        # attempt the client retries: claim the slot as an EV_RETRY
        # backoff park instead of forgetting the request
        place_retry = jnp.bool_(False)
        retry_delay = jnp.float32(0.0)
        if self._has_retry:
            failed = pred & ~alive
            want = (
                failed
                if self.plan.retry_max_attempts > 1
                else jnp.bool_(False)
            )
            can, st = self._consume_retry_token(st, now, want)
            place_retry = can & has_free
            overflow = overflow | (can & ~has_free)
            st = self._record_attempts(
                st, jnp.int32(1), failed & ~place_retry,
            )
            st = st._replace(
                n_retries=st.n_retries + jnp.where(place_retry, 1, 0),
            )
            retry_delay = self._backoff_delay(jnp.int32(1), key)
            place = place | place_retry
        if self._n_gen > 1:
            kinds = jnp.asarray(plan.gen_entry_target_kind)
            ev0 = jnp.where(
                kinds[g] == TARGET_LB, EV_ARRIVE_LB, EV_ARRIVE_SRV,
            ).astype(jnp.int32)
            entry_target = jnp.maximum(
                jnp.asarray(plan.gen_entry_target)[g], 0,
            ).astype(jnp.int32)
        else:
            ev0 = (
                EV_ARRIVE_LB
                if plan.entry_target_kind == TARGET_LB
                else EV_ARRIVE_SRV
            )
            entry_target = jnp.int32(max(plan.entry_target, 0))
        idx = jnp.where(place, slot, jnp.int32(self.pool))
        st = st._replace(
            req_ev=st.req_ev.at[idx].set(
                jnp.where(place_retry, EV_RETRY, ev0), mode="drop",
            ),
            req_t=st.req_t.at[idx].set(
                jnp.where(place_retry, now + retry_delay, t_cur), mode="drop",
            ),
            req_srv=st.req_srv.at[idx].set(entry_target, mode="drop"),
            req_start=st.req_start.at[idx].set(now, mode="drop"),
            req_lbslot=st.req_lbslot.at[idx].set(-1, mode="drop"),
            req_ram=st.req_ram.at[idx].set(0.0, mode="drop"),
            req_ticket=st.req_ticket.at[idx].set(NO_TICKET, mode="drop"),
            n_overflow=st.n_overflow + jnp.where(overflow, 1, 0),
        )
        if self.trace is not None:
            # claim (or reset, on slot reuse) the placed slot's ring row
            st = st._replace(
                req_fr=st.req_fr.at[idx].set(fr_row, mode="drop"),
            )
            if self._has_retry:
                st = self._fr_row(st, fr_row, FR_RETRY, 1, now, place_retry)
                st = self._fr_row(
                    st, fr_row, FR_ABANDON, 1, now, failed & ~place_retry,
                )
            st = self._fr_row(st, fr_row, FR_REJECT, -1, now, overflow)
        if self.blame:
            # fresh attribution row for the placed slot; entry-chain edge
            # delays are credited directly (the walk's timestamps are all
            # known here), leaving the cursor at the target-arrival time.
            # EV_RETRY parks skip the spans — the attempt clock restarts
            # at the backoff re-issue, which rebuilds its own chain.
            st = self._bl_zero(
                st, idx, t_cur, self._bl_cc(_bl.PH_TRANSIT), place,
            )
            for gi2, chain2 in enumerate(chains):
                pl_gi = place & ~place_retry & (g == gi2)
                t_prev = now
                for _, eidx2, t_hop in (h for h in hop_chain if h[0] == gi2):
                    st = self._bl_span(
                        st,
                        idx,
                        self._bl_ce(eidx2, _bl.PH_TRANSIT),
                        t_hop - t_prev,
                        pl_gi,
                    )
                    t_prev = t_hop
        if self._crn:
            # the slot's request identity: the arrival counter at spawn
            # (already incremented for this iteration, so values are >= 1)
            st = st._replace(
                req_seq=st.req_seq.at[idx].set(st.arr_ctr, mode="drop"),
                req_draws=st.req_draws.at[idx].set(0, mode="drop"),
            )
        if self._has_retry:
            st = st._replace(
                req_deadline=st.req_deadline.at[idx].set(
                    jnp.where(place_retry, INF, now + ov.retry_timeout),
                    mode="drop",
                ),
                req_attempt=st.req_attempt.at[idx].set(
                    jnp.where(place_retry, 2, 1), mode="drop",
                ),
                req_orphan=st.req_orphan.at[idx].set(0, mode="drop"),
            )
        if self._has_hedge:
            # the primary anchors its logical request at its own slot; the
            # hedge timer arms at the emission time (a <= 0 per-scenario
            # delay override leaves it disarmed — the A/B off switch)
            st = st._replace(
                req_prime=st.req_prime.at[idx].set(slot, mode="drop"),
                req_is_hedge=st.req_is_hedge.at[idx].set(0, mode="drop"),
                hg_t=st.hg_t.at[idx].set(
                    jnp.where(ov.hedge_delay > 0, now + ov.hedge_delay, INF),
                    mode="drop",
                ),
                hg_n=st.hg_n.at[idx].set(0, mode="drop"),
                hg_live=st.hg_live.at[idx].set(1, mode="drop"),
                hg_done=st.hg_done.at[idx].set(0, mode="drop"),
            )
        if self._has_brownout:
            st = st._replace(
                req_degraded=st.req_degraded.at[idx].set(0, mode="drop"),
            )
        if self._has_llm:
            st = st._replace(
                req_llm=st.req_llm.at[idx].set(0.0, mode="drop"),
            )
        if self._has_serving:
            # token budget: undrawn (-1) unless a replay row presets it
            tin0 = jnp.float32(-1.0)
            tout0 = jnp.float32(-1.0)
            if self._has_replay:
                ridx = jnp.clip(
                    st.n_generated - 1, 0, len(plan.replay_times) - 1,
                )
                tin0 = self.params.replay_tok_in[ridx]
                tout0 = self.params.replay_tok_out[ridx]
            st = st._replace(
                req_tok_in=st.req_tok_in.at[idx].set(tin0, mode="drop"),
                req_tok_out=st.req_tok_out.at[idx].set(tout0, mode="drop"),
                req_sv_evict=st.req_sv_evict.at[idx].set(0, mode="drop"),
                req_sv_hold=st.req_sv_hold.at[idx].set(0.0, mode="drop"),
            )
        if self.collect_traces:
            # fresh ring: generator hop (code = generator index), then one
            # NETWORK + CLIENT pair per entry edge (the chain's
            # intermediate targets are clients; the LAST target is the
            # LB/server, recorded by its own branch).  EV_RETRY parks
            # record no hops (their walk was cut short by the drop).
            st = st._replace(
                req_hop_n=st.req_hop_n.at[idx].set(0, mode="drop"),
            )
            for gi, chain in enumerate(chains):
                place_gi = place & ~place_retry & (g == gi)
                st = self._hop(st, idx, self.HOP_GEN + gi, now, place_gi)
                gi_hops = [h for h in hop_chain if h[0] == gi]
                for j, (_, eidx, t_hop) in enumerate(gi_hops):
                    st = self._hop(
                        st, idx, self.HOP_EDGE + eidx, t_hop, place_gi,
                    )
                    if j < len(chain) - 1:
                        st = self._hop(
                            st, idx, self.HOP_CLIENT, t_hop, place_gi,
                        )
        if self._has_replay:
            # deterministic trace replay: the next arrival is read from the
            # lowered log table, not sampled (replay plans validate down to
            # a single generator, so next_arrival is a 1-vector)
            n_rows = len(plan.replay_times)
            ridx = jnp.clip(st.n_generated, 0, n_rows - 1)
            nxt = jnp.where(
                st.n_generated < n_rows,
                self.params.replay_times[ridx],
                jnp.float32(INF),
            )
            return st._replace(
                next_arrival=jnp.where(pred, nxt, st.next_arrival),
            )
        if self._n_gen > 1:
            for gi in range(self._n_gen):
                st = self._advance_arrival(
                    st, key, ov, pred & (g == gi), gen=gi,
                )
            return st
        return self._advance_arrival(st, key, ov, pred)

    def _seg_start(self, st, i, s, ep, seg, now, key, ov, pred) -> EngineState:
        """Begin segment ``seg`` for slot ``i``: CPU acquire-or-wait, IO sleep,
        or endpoint completion (exit flow)."""
        p = self.params
        kind = p.seg_kind[s, ep, seg]
        dur = p.seg_dur[s, ep, seg]
        if self._has_brownout:
            # degraded requests run the cheaper CPU profile
            dur = jnp.where(
                (kind == SEG_CPU) & (st.req_degraded[i] == 1),
                dur * p.server_brownout_cpu[s],
                dur,
            )
        is_cpu = pred & (kind == SEG_CPU)
        is_io = pred & (kind == SEG_IO)
        is_end = pred & (kind == SEG_END)
        if self._has_cache:
            # a SEG_CACHE is an IO sleep whose duration is a per-request
            # hit/miss mixture: hit latency (seg_dur) with probability
            # seg_hit_prob, else the backing store's miss latency
            is_cache = pred & (kind == SEG_CACHE)
            u_cache = draw_uniform(jax.random.fold_in(key, 24))
            dur = jnp.where(
                is_cache & (u_cache >= p.seg_hit_prob[s, ep, seg]),
                p.seg_miss_dur[s, ep, seg],
                dur,
            )
            is_io = is_io | is_cache
        if self._has_llm:
            # SEG_LLM: output tokens ~ Poisson(mean); the sleep stretches
            # by tokens * s/token and the request accrues tokens * cost
            is_llm = pred & (kind == SEG_LLM)
            lam = p.seg_llm_tokens[s, ep, seg]
            tokens = jax.random.poisson(
                as_threefry(jax.random.fold_in(key, 25)), jnp.maximum(lam, 1e-6),
            ).astype(jnp.float32)
            dur = jnp.where(is_llm, dur + tokens * p.seg_llm_tpt[s, ep, seg], dur)
            st = st._replace(
                req_llm=st.req_llm.at[i].add(
                    jnp.where(is_llm, tokens * p.seg_llm_cost[s, ep, seg], 0.0),
                ),
            )
            is_io = is_io | is_llm

        has_waiters = st.cpu_wait_n[s] > 0
        can_take = (st.cores_free[s] > 0) & ~has_waiters
        cpu_run = is_cpu & can_take
        cpu_wait = is_cpu & ~can_take

        shed = jnp.bool_(False)
        if self._has_shed:
            # overload policy: a request that would join a FULL ready queue
            # is shed — it releases its RAM and leaves the system, counted
            # in n_rejected (reference roadmap milestone 5's queue cap)
            cap = p.server_queue_cap[s]
            shed = cpu_wait & (cap >= 0) & (st.cpu_wait_n[s] >= cap)
            cpu_wait = cpu_wait & ~shed

        run_now = cpu_run | is_io
        db_wait = jnp.bool_(False)
        if self._has_db:
            # DB connection acquire-or-wait: same strict-FIFO discipline as
            # the core queue, but the holder sleeps (io) instead of running
            is_db = pred & (kind == SEG_DB)
            db_can = (st.db_free[s] > 0) & ~(st.db_wait_n[s] > 0)
            db_run = is_db & db_can
            db_wait = is_db & ~db_can
            run_now = run_now | db_run
            st = st._replace(
                db_free=st.db_free.at[s].add(jnp.where(db_run, -1, 0)),
                db_ticket=st.db_ticket.at[s].add(jnp.where(db_wait, 1, 0)),
                db_wait_n=st.db_wait_n.at[s].add(jnp.where(db_wait, 1, 0)),
            )
            is_io = is_io | is_db  # the io-sleep gauge counts db segments
        if self._has_timeout:
            st = st._replace(
                req_wait_t=st.req_wait_t.at[i].set(
                    jnp.where(cpu_wait, now, st.req_wait_t[i]),
                ),
            )
        if self.blame:
            # segment boundary: close the open span, then point the cursor
            # at what happens next — queue wait (core / db pool) or the
            # segment's own sleep/burst (service).  Serving segments and
            # SEG_END repoint inside their own handlers below.
            st = self._bl_flush(st, i, now, pred)
            blc = jnp.where(
                cpu_wait,
                self._bl_cs(s, _bl.PH_Q_CPU),
                jnp.where(
                    db_wait,
                    self._bl_cs(s, _bl.PH_Q_DB),
                    self._bl_cs(s, _bl.PH_SERVICE),
                ),
            )
            st = self._bl_set(
                st, i, now, blc, run_now | cpu_wait | db_wait,
            )
        st = st._replace(
            cores_free=st.cores_free.at[s].add(jnp.where(cpu_run, -1, 0)),
            cpu_ticket=st.cpu_ticket.at[s].add(jnp.where(cpu_wait, 1, 0)),
            cpu_wait_n=st.cpu_wait_n.at[s].add(jnp.where(cpu_wait, 1, 0)),
            req_ev=st.req_ev.at[i].set(
                jnp.where(
                    run_now,
                    EV_SEG_END,
                    jnp.where(
                        cpu_wait,
                        EV_WAIT_CPU,
                        jnp.where(db_wait, EV_WAIT_DB, st.req_ev[i]),
                    ),
                ),
            ),
            req_t=st.req_t.at[i].set(
                jnp.where(
                    run_now,
                    now + dur,
                    jnp.where(cpu_wait | db_wait, INF, st.req_t[i]),
                ),
            ),
            req_ticket=st.req_ticket.at[i].set(
                jnp.where(
                    cpu_wait,
                    st.cpu_ticket[s],
                    jnp.where(db_wait, st.db_ticket[s], st.req_ticket[i]),
                ),
            ),
            req_seg=st.req_seg.at[i].set(jnp.where(pred, seg, st.req_seg[i])),
        )
        if self.trace is not None:
            st = self._fr(st, i, FR_WAIT_CPU, s, now, cpu_wait)
            if self._has_db:
                st = self._fr(st, i, FR_WAIT_DB, s, now, db_wait)
            if self._has_shed:
                st = self._fr(st, i, FR_REJECT, s, now, shed)
        st = self._gauge_add(st, now, self._g_ready(s), 1.0, cpu_wait)
        st = self._gauge_add(st, now, self._g_io(s), 1.0, is_io)
        if self._has_shed:
            st = self._release_ram(st, i, s, now, shed)
            if self._has_conn:
                st = st._replace(
                    srv_conn=st.srv_conn.at[s].add(jnp.where(shed, -1, 0)),
                )
            st = st._replace(
                req_ev=st.req_ev.at[i].set(
                    jnp.where(shed, EV_IDLE, st.req_ev[i]),
                ),
                req_t=st.req_t.at[i].set(
                    jnp.where(shed, INF, st.req_t[i]),
                ),
                req_ram=st.req_ram.at[i].set(
                    jnp.where(shed, 0.0, st.req_ram[i]),
                ),
                req_ticket=st.req_ticket.at[i].set(
                    jnp.where(shed, NO_TICKET, st.req_ticket[i]),
                ),
                n_rejected=st.n_rejected + jnp.where(shed, 1, 0),
            )
            st = self._breaker_server_report(
                st, i, now, jnp.bool_(True), ov, shed,
            )
            st = self._client_fail(st, i, now, key, shed)
        if self._has_serving:
            # llm_serve lifecycle: admission gate (SEG_PREFILL) and the
            # non-blocking decode extension / eviction (SEG_DECODE).  The
            # admission park sits OUTSIDE the io gauge; the grant event
            # adds the sleep (mirroring the oracle's serving branch).
            is_pf = pred & (kind == SEG_PREFILL)
            is_dc = pred & (kind == SEG_DECODE)
            st = self._sv_prefill_admit(st, i, s, ep, seg, now, key, is_pf)
            st = self._sv_decode_start(st, i, s, ep, seg, now, key, ov, is_dc)
        return self._exit_flow(st, i, s, now, key, ov, is_end)

    def _release_ram(self, st, i, s, now, pred) -> EngineState:
        """Return slot ``i``'s RAM to server ``s`` and run the strict-FIFO
        grant cascade (no-op when the plan has no RAM steps)."""
        if not self._has_ram:
            return st
        ram_amt = st.req_ram[i]
        st = st._replace(
            ram_free=st.ram_free.at[s].add(jnp.where(pred, ram_amt, 0.0)),
        )
        st = self._gauge_add(
            st,
            now,
            self._g_ram(s),
            -ram_amt,
            pred & (ram_amt > 0),
        )

        # strict-FIFO RAM grant loop: grant heads while they fit
        def gcond(carry):
            req_ev, _t, req_tk, ram_free_s, wait_n, go = carry
            waiting = (req_ev == EV_WAIT_RAM) & (st.req_srv == s)
            tick = jnp.where(waiting, req_tk, NO_TICKET)
            head = jnp.argmin(tick).astype(jnp.int32)
            return go & (tick[head] < NO_TICKET) & (st.req_ram[head] <= ram_free_s)

        def gbody(carry):
            req_ev, req_t, req_tk, ram_free_s, wait_n, go = carry
            waiting = (req_ev == EV_WAIT_RAM) & (st.req_srv == s)
            tick = jnp.where(waiting, req_tk, NO_TICKET)
            head = jnp.argmin(tick).astype(jnp.int32)
            return (
                req_ev.at[head].set(EV_RESUME),
                req_t.at[head].set(now),
                req_tk.at[head].set(NO_TICKET),
                ram_free_s - st.req_ram[head],
                wait_n - 1,
                go,
            )

        req_ev, req_t, req_tk, ram_free_s, wait_n, _ = jax.lax.while_loop(
            gcond,
            gbody,
            (
                st.req_ev,
                st.req_t,
                st.req_ticket,
                st.ram_free[s],
                st.ram_wait_n[s],
                pred,
            ),
        )
        return st._replace(
            req_ev=req_ev,
            req_t=req_t,
            req_ticket=req_tk,
            ram_free=st.ram_free.at[s].set(ram_free_s),
            ram_wait_n=st.ram_wait_n.at[s].set(wait_n),
        )

    # ==================================================================
    # LLM serving batch gate (statically pruned without llm_serve steps)
    # ==================================================================

    def _sv_admit(self, st, i, s, now, pred) -> EngineState:
        """Run the combined slot+KV-token FIFO admission for slot ``i``
        (prompt size already drawn into ``req_tok_in``).  An immediate
        grant reserves both resources NOW and schedules EV_SV_GRANT at the
        current timestamp — the oracle gate decrements inside ``_acquire``
        and heap-schedules the resume the same way; otherwise the request
        parks as EV_WAIT_SV (outside the io gauge) with a FIFO ticket."""
        tin = st.req_tok_in[i]
        can = (
            pred
            & (st.sv_wait_n[s] == 0)
            & (st.sv_slots_free[s] > 0)
            & (st.sv_tokens_free[s] >= tin)
        )
        park = pred & ~can
        # admission wait opens here; the EV_SV_GRANT handler flushes it
        # (zero seconds for immediate grants — the event fires at ``now``)
        st = self._bl_set(st, i, now, self._bl_cs(s, _bl.PH_Q_ADMIT), pred)
        return st._replace(
            sv_slots_free=st.sv_slots_free.at[s].add(jnp.where(can, -1, 0)),
            sv_tokens_free=st.sv_tokens_free.at[s].add(
                jnp.where(can, -tin, 0.0),
            ),
            sv_ticket=st.sv_ticket.at[s].add(jnp.where(park, 1, 0)),
            sv_wait_n=st.sv_wait_n.at[s].add(jnp.where(park, 1, 0)),
            req_ev=st.req_ev.at[i].set(
                jnp.where(
                    can,
                    EV_SV_GRANT,
                    jnp.where(park, EV_WAIT_SV, st.req_ev[i]),
                ),
            ),
            req_t=st.req_t.at[i].set(
                jnp.where(can, now, jnp.where(park, INF, st.req_t[i])),
            ),
            req_ticket=st.req_ticket.at[i].set(
                jnp.where(park, st.sv_ticket[s], st.req_ticket[i]),
            ),
        )

    def _sv_prefill_admit(self, st, i, s, ep, seg, now, key, pred) -> EngineState:
        """SEG_PREFILL segment start: draw this attempt's token budget once
        (evictions redo the prefill with the SAME draw; replay presets and
        the variance-0 deterministic mean skip the normal draw entirely —
        the clamps mirror the oracle's ``draw_tokens``) and enter the batch
        admission gate."""
        p = self.params
        tin_m = p.sv_tin_mean[s, ep, seg]
        tin_v = p.sv_tin_var[s, ep, seg]
        tout_m = p.sv_tout_mean[s, ep, seg]
        tout_v = p.sv_tout_var[s, ep, seg]
        z_in = draw_normal(jax.random.fold_in(key, 26))
        z_out = draw_normal(jax.random.fold_in(key, 27))
        tin_d = jnp.maximum(
            1.0, jnp.where(tin_v > 0, tin_m + jnp.sqrt(tin_v) * z_in, tin_m),
        )
        tout_d = jnp.maximum(
            1.0,
            jnp.where(tout_v > 0, tout_m + jnp.sqrt(tout_v) * z_out, tout_m),
        )
        need_in = pred & (st.req_tok_in[i] < 0)
        need_out = pred & (st.req_tok_out[i] < 0)
        st = st._replace(
            req_tok_in=st.req_tok_in.at[i].set(
                jnp.where(need_in, tin_d, st.req_tok_in[i]),
            ),
            req_tok_out=st.req_tok_out.at[i].set(
                jnp.where(need_out, tout_d, st.req_tok_out[i]),
            ),
        )
        return self._sv_admit(st, i, s, now, pred)

    def _sv_grant_branch(self, st, i, now, key, ov, pred) -> EngineState:
        """Batch admission granted (resources were reserved at grant time):
        the prompt's KV tokens become this slot's resident hold and the
        prefill runs as an io-like sleep."""
        p = self.params
        s = st.req_srv[i]
        ep = st.req_ep[i]
        seg = st.req_seg[i]
        tin = st.req_tok_in[i]
        dur = p.sv_prefill_base[s, ep, seg] + tin * p.sv_prefill_tpt[s, ep, seg]
        if self.blame:
            # close the admission wait; the prefill sleep opens — a
            # re-admission after eviction redoes it as KV_REDO blame
            st = self._bl_flush(st, i, now, pred)
            st = self._bl_set(
                st,
                i,
                now,
                jnp.where(
                    st.req_sv_evict[i] > 0,
                    self._bl_cs(s, _bl.PH_KV_REDO),
                    self._bl_cs(s, _bl.PH_PREFILL),
                ),
                pred,
            )
        st = st._replace(
            req_sv_hold=st.req_sv_hold.at[i].set(
                jnp.where(pred, tin, st.req_sv_hold[i]),
            ),
            n_prefill_tok=st.n_prefill_tok + jnp.where(pred, tin, 0.0),
            req_ev=st.req_ev.at[i].set(
                jnp.where(pred, EV_SEG_END, st.req_ev[i]),
            ),
            req_t=st.req_t.at[i].set(jnp.where(pred, now + dur, st.req_t[i])),
        )
        if self.trace is not None:
            st = self._fr(st, i, FR_PREFILL, s, now, pred)
        return self._gauge_add(st, now, self._g_io(s), 1.0, pred)

    def _sv_decode_start(self, st, i, s, ep, seg, now, key, ov, pred) -> EngineState:
        """SEG_DECODE segment start: NON-BLOCKING token extension (running
        requests outrank queued admissions — continuous batching).  A fit
        starts generation; a miss is a KV-pressure eviction that releases
        the slot + prompt hold (cascading queued grants) and re-queues the
        attempt at the FIFO tail for a full prefill redo — or, past the
        eviction budget, terminally rejects it (shed accounting)."""
        p = self.params
        tin = st.req_tok_in[i]
        tout = st.req_tok_out[i]
        fits = pred & (st.sv_tokens_free[s] >= tout)
        # decode rate: drawn fresh per decode attempt (oracle draw_rate:
        # exactly the mean at variance 0, clamped to 0.1*mean otherwise)
        rm = p.sv_rate_mean[s, ep, seg]
        rv = p.sv_rate_var[s, ep, seg]
        z = draw_normal(jax.random.fold_in(key, 28))
        rate = jnp.maximum(
            0.1 * rm, jnp.where(rv > 0, rm + jnp.sqrt(rv) * z, rm),
        )
        rate = rate * ov.decode_rate_scale
        dur = tout / jnp.maximum(rate, _TINY)
        # _seg_start already flushed at ``now``; the decode sleep opens here
        st = self._bl_set(st, i, now, self._bl_cs(s, _bl.PH_DECODE), fits)
        st = st._replace(
            sv_tokens_free=st.sv_tokens_free.at[s].add(
                jnp.where(fits, -tout, 0.0),
            ),
            req_sv_hold=st.req_sv_hold.at[i].add(jnp.where(fits, tout, 0.0)),
            n_decode_tok=st.n_decode_tok + jnp.where(fits, tout, 0.0),
            req_llm=st.req_llm.at[i].add(
                jnp.where(fits, tout * p.sv_cost[s, ep, seg], 0.0),
            ),
            req_ev=st.req_ev.at[i].set(
                jnp.where(fits, EV_SEG_END, st.req_ev[i]),
            ),
            req_t=st.req_t.at[i].set(jnp.where(fits, now + dur, st.req_t[i])),
        )
        if self.trace is not None:
            st = self._fr(st, i, FR_DECODE, s, now, fits)
        st = self._gauge_add(st, now, self._g_io(s), 1.0, fits)

        # KV pressure: evict
        evict = pred & ~fits
        ctr = st.req_sv_evict[i] + jnp.where(evict, 1, 0)
        terminal = evict & (ctr > p.serve_evict_max[s])
        readmit = evict & ~terminal
        st = st._replace(
            n_kv_evict=st.n_kv_evict + jnp.where(evict, 1, 0),
            req_sv_evict=st.req_sv_evict.at[i].set(ctr),
        )
        if self.trace is not None:
            st = self._fr(st, i, FR_EVICT, s, now, evict)
        # release the slot + prompt hold; queued admissions cascade first,
        # THEN the evicted attempt re-queues (oracle: release -> _acquire)
        st = self._release_sv(st, i, s, now, evict)
        st = st._replace(
            req_seg=st.req_seg.at[i].set(
                jnp.where(readmit, seg - 1, st.req_seg[i]),
            ),
        )
        st = self._sv_admit(st, i, s, now, readmit)

        # eviction budget spent: terminal reject (mirror the shed path)
        st = self._release_ram(st, i, s, now, terminal)
        if self._has_conn:
            st = st._replace(
                srv_conn=st.srv_conn.at[s].add(jnp.where(terminal, -1, 0)),
            )
        st = st._replace(
            req_ev=st.req_ev.at[i].set(
                jnp.where(terminal, EV_IDLE, st.req_ev[i]),
            ),
            req_t=st.req_t.at[i].set(jnp.where(terminal, INF, st.req_t[i])),
            req_ram=st.req_ram.at[i].set(
                jnp.where(terminal, 0.0, st.req_ram[i]),
            ),
            req_ticket=st.req_ticket.at[i].set(
                jnp.where(terminal, NO_TICKET, st.req_ticket[i]),
            ),
            n_rejected=st.n_rejected + jnp.where(terminal, 1, 0),
        )
        if self.trace is not None:
            st = self._fr(st, i, FR_REJECT, s, now, terminal)
        st = self._breaker_server_report(
            st, i, now, jnp.bool_(True), ov, terminal,
        )
        return self._client_fail(st, i, now, key, terminal)

    def _release_sv(self, st, i, s, now, pred) -> EngineState:
        """Return slot ``i``'s batch slot + resident KV hold to server ``s``
        and run the strict-FIFO admission grant cascade — the
        :meth:`_release_ram` discipline lifted to two resources: a grant
        needs the head waiter to fit BOTH a free batch slot and its prompt
        tokens (``req_tok_in``)."""
        if not self._has_serving:
            return st
        hold = st.req_sv_hold[i]
        slots0 = st.sv_slots_free[s] + jnp.where(pred, 1, 0)
        tokens0 = st.sv_tokens_free[s] + jnp.where(pred, hold, 0.0)
        st = st._replace(
            req_sv_hold=st.req_sv_hold.at[i].set(
                jnp.where(pred, 0.0, hold),
            ),
        )

        def gcond(carry):
            req_ev, _t, req_tk, slots, tokens, _wait_n, go = carry
            waiting = (req_ev == EV_WAIT_SV) & (st.req_srv == s)
            tick = jnp.where(waiting, req_tk, NO_TICKET)
            head = jnp.argmin(tick).astype(jnp.int32)
            return (
                go
                & (tick[head] < NO_TICKET)
                & (slots > 0)
                & (st.req_tok_in[head] <= tokens)
            )

        def gbody(carry):
            req_ev, req_t, req_tk, slots, tokens, wait_n, go = carry
            waiting = (req_ev == EV_WAIT_SV) & (st.req_srv == s)
            tick = jnp.where(waiting, req_tk, NO_TICKET)
            head = jnp.argmin(tick).astype(jnp.int32)
            return (
                req_ev.at[head].set(EV_SV_GRANT),
                req_t.at[head].set(now),
                req_tk.at[head].set(NO_TICKET),
                slots - 1,
                tokens - st.req_tok_in[head],
                wait_n - 1,
                go,
            )

        req_ev, req_t, req_tk, slots, tokens, wait_n, _ = jax.lax.while_loop(
            gcond,
            gbody,
            (
                st.req_ev,
                st.req_t,
                st.req_ticket,
                slots0,
                tokens0,
                st.sv_wait_n[s],
                pred,
            ),
        )
        return st._replace(
            req_ev=req_ev,
            req_t=req_t,
            req_ticket=req_tk,
            sv_slots_free=st.sv_slots_free.at[s].set(slots),
            sv_tokens_free=st.sv_tokens_free.at[s].set(tokens),
            sv_wait_n=st.sv_wait_n.at[s].set(wait_n),
        )

    def _exit_flow(self, st, i, s, now, key, ov, pred) -> EngineState:
        """Endpoint finished: release RAM (FIFO grants), route the exit edge,
        complete / forward / drop."""
        p = self.params
        plan = self.plan

        st = self._release_ram(st, i, s, now, pred)
        if self._has_conn:
            st = st._replace(
                srv_conn=st.srv_conn.at[s].add(jnp.where(pred, -1, 0)),
            )
        # departing the routed target is the breaker's success signal
        st = self._breaker_server_report(st, i, now, jnp.bool_(False), ov, pred)

        # route the single exit edge of this server
        e = p.exit_edge[s]
        kind = p.exit_kind[s]
        dropped, delay = self._sample_edge(e, now, jax.random.fold_in(key, 48), ov)
        arrive = now + delay
        if self.blame:
            # close the final service span, credit the exit transit
            # directly (its duration is known here), and park the cursor
            # at the arrival — the next arrival branch (or completion)
            # picks it up with a zero-length flush
            st = self._bl_flush(st, i, now, pred)
            st = self._bl_span(
                st,
                i,
                self._bl_ce(e, _bl.PH_TRANSIT),
                delay,
                pred & ~dropped,
            )
            st = self._bl_set(
                st, i, arrive, self._bl_cc(_bl.PH_TRANSIT), pred & ~dropped,
            )
        to_server = pred & (kind == TARGET_SERVER) & ~dropped
        to_lb = pred & (kind == TARGET_LB) & ~dropped
        to_client = pred & (kind == TARGET_CLIENT) & ~dropped
        drop_here = pred & dropped

        st = self._edge_interval(st, e, now, arrive, pred & ~dropped)
        if self._has_retry or self._has_hedge:
            # the final leg stays EVENT-DRIVEN: the client deadline must
            # race the last transit exactly like the oracle's heap (a
            # timeout during the final edge orphans the attempt), so
            # completion is deferred to an EV_ARRIVE_CLIENT event at
            # ``arrive`` instead of being folded into this exit event
            # (hedging also needs it: the sibling race is settled at the
            # client, never mid-flight)
            if self.collect_traces:
                st = self._hop(st, i, self.HOP_EDGE + e, arrive, pred & ~dropped)
            if self.trace is not None:
                st = self._fr(st, i, FR_TRANSIT, e, arrive, pred & ~dropped)
                st = self._fr(st, i, FR_DROP, e, now, drop_here)
            st = st._replace(
                req_ev=st.req_ev.at[i].set(
                    jnp.where(
                        drop_here,
                        EV_IDLE,
                        jnp.where(
                            to_client,
                            EV_ARRIVE_CLIENT,
                            jnp.where(
                                to_server,
                                EV_ARRIVE_SRV,
                                jnp.where(to_lb, EV_ARRIVE_LB, st.req_ev[i]),
                            ),
                        ),
                    ),
                ),
                req_t=st.req_t.at[i].set(
                    jnp.where(
                        drop_here,
                        INF,
                        jnp.where(
                            to_server | to_lb | to_client,
                            arrive,
                            st.req_t[i],
                        ),
                    ),
                ),
                req_srv=st.req_srv.at[i].set(
                    jnp.where(to_server, p.exit_target[s], st.req_srv[i]),
                ),
                req_lbslot=st.req_lbslot.at[i].set(
                    jnp.where(pred, -1, st.req_lbslot[i]),
                ),
                req_ram=st.req_ram.at[i].set(
                    jnp.where(pred, 0.0, st.req_ram[i]),
                ),
                n_dropped=st.n_dropped + jnp.where(drop_here, 1, 0),
            )
            return self._client_fail(st, i, now, key, drop_here)
        done = to_client & (arrive < plan.horizon)
        if self._has_brownout:
            st = st._replace(
                n_degraded=st.n_degraded
                + jnp.where(done & (st.req_degraded[i] == 1), 1, 0),
            )
        if self._has_llm:
            cost = st.req_llm[i]
            st = st._replace(
                llm_sum=st.llm_sum + jnp.where(done, cost, 0.0),
                llm_sumsq=st.llm_sumsq + jnp.where(done, cost * cost, 0.0),
            )
            if self.collect_clocks:
                lidx = jnp.where(
                    done, st.clock_n, jnp.int32(st.llm_store.shape[0]),
                )
                st = st._replace(
                    llm_store=st.llm_store.at[lidx].set(cost, mode="drop"),
                )
        if self.collect_traces:
            st = self._hop(st, i, self.HOP_EDGE + e, arrive, pred & ~dropped)
            st = self._hop(st, i, self.HOP_CLIENT, arrive, done)
            # flush the completed ring to the trace store, aligned with the
            # clock row _complete is about to claim
            idx = jnp.where(done, st.clock_n, jnp.int32(st.tr_code.shape[0]))
            st = st._replace(
                tr_code=st.tr_code.at[idx].set(st.req_hops[i], mode="drop"),
                tr_t=st.tr_t.at[idx].set(st.req_hop_t[i], mode="drop"),
                tr_n=st.tr_n.at[idx].set(
                    jnp.minimum(st.req_hop_n[i], self._hop_cap),
                    mode="drop",
                ),
            )
        if self.trace is not None:
            st = self._fr(st, i, FR_TRANSIT, e, arrive, pred & ~dropped)
            st = self._fr(st, i, FR_DROP, e, now, drop_here)
            st = self._fr(st, i, FR_COMPLETE, -1, arrive, done)
        st = self._bl_complete(st, i, arrive, arrive - st.req_start[i], done)
        st = self._complete(
            st,
            st.req_start[i],
            arrive,
            done,
        )

        # a final transit that lands past the horizon stays IN FLIGHT as a
        # parked client arrival (the oracle heap still holds that event at
        # the horizon): freeing the slot here would make the request vanish
        # from the conservation identity generated = completed + dropped +
        # overflow + in-flight.  The parked event never fires — the loop
        # stops at the horizon — it only keeps the slot accounted for.
        straddle = to_client & ~done
        free = drop_here | done
        st = st._replace(
            req_ev=st.req_ev.at[i].set(
                jnp.where(
                    free,
                    EV_IDLE,
                    jnp.where(
                        straddle,
                        EV_ARRIVE_CLIENT,
                        jnp.where(
                            to_server,
                            EV_ARRIVE_SRV,
                            jnp.where(to_lb, EV_ARRIVE_LB, st.req_ev[i]),
                        ),
                    ),
                ),
            ),
            req_t=st.req_t.at[i].set(
                jnp.where(
                    free,
                    INF,
                    jnp.where(
                        to_server | to_lb | straddle, arrive, st.req_t[i],
                    ),
                ),
            ),
            req_srv=st.req_srv.at[i].set(
                jnp.where(to_server, p.exit_target[s], st.req_srv[i]),
            ),
            req_lbslot=st.req_lbslot.at[i].set(
                jnp.where(pred, -1, st.req_lbslot[i]),
            ),
            req_ram=st.req_ram.at[i].set(jnp.where(pred, 0.0, st.req_ram[i])),
            n_dropped=st.n_dropped + jnp.where(drop_here, 1, 0),
        )
        return st

    def _breaker_report(self, st, slot, is_probe, failed, now, pred):
        """Apply one success/failure report to breaker slot ``slot``.

        Mirrors the oracle's ``breaker_failure``/``breaker_success``:
        probe outcomes settle the half-open round (failure re-opens,
        ``half_open_probes`` successes close); closed-state failures count
        consecutively toward the threshold, successes reset the count.
        """
        plan = self.plan
        probe = pred & is_probe
        plain = pred & ~is_probe
        stt = st.cb_state[slot]
        # probe bookkeeping
        st = st._replace(
            cb_probes_out=st.cb_probes_out.at[slot].add(
                jnp.where(probe, -1, 0),
            ),
        )
        st = st._replace(
            cb_probes_out=st.cb_probes_out.at[slot].max(0),
        )
        # probe failure: immediate re-open
        p_fail = probe & failed
        # closed-state consecutive failures
        c_fail = plain & failed & (stt == 0)
        consec = st.cb_consec[slot] + jnp.where(c_fail, 1, 0)
        trips = c_fail & (consec >= plan.breaker_threshold)
        opens = p_fail | trips
        if self.trace is not None:
            st = self._bk(st, slot, 1, now, opens)
        st = st._replace(
            cb_consec=st.cb_consec.at[slot].set(
                jnp.where(
                    trips | (plain & ~failed & (stt == 0)),
                    0,
                    consec,
                ),
            ),
            cb_state=st.cb_state.at[slot].set(
                jnp.where(opens, 1, st.cb_state[slot]),
            ),
            cb_open_until=st.cb_open_until.at[slot].set(
                jnp.where(
                    opens,
                    now + jnp.float32(plan.breaker_cooldown),
                    st.cb_open_until[slot],
                ),
            ),
        )
        # probe success: count toward closing the half-open round
        p_ok = probe & ~failed
        probe_ok = st.cb_probe_ok[slot] + jnp.where(p_ok, 1, 0)
        closes = p_ok & (stt == 2) & (probe_ok >= plan.breaker_probes)
        if self.trace is not None:
            st = self._bk(st, slot, 0, now, closes)
        return st._replace(
            cb_probe_ok=st.cb_probe_ok.at[slot].set(probe_ok),
            cb_state=st.cb_state.at[slot].set(
                jnp.where(closes, 0, st.cb_state[slot]),
            ),
            cb_consec=st.cb_consec.at[slot].set(
                jnp.where(closes, 0, st.cb_consec[slot]),
            ),
        )

    def _breaker_server_report(self, st, i, now, failed, ov, pred):
        """Report slot ``i``'s routing outcome once (no-op after clearing).

        One report feeds BOTH outlier channels: the circuit breaker's
        consecutive-failure state machine and the LB health gate's EWMA
        ``h <- (1 - alpha) * h + alpha * x`` (x = 1 failure, 0 success —
        the formula :meth:`HealthScalars.observe` pins for the oracle).
        Crossing the ejection threshold while in rotation ejects the slot
        until ``now + readmit_s``; requests already in flight to an
        ejected slot keep updating its EWMA without re-extending the
        ejection."""
        if not self._has_report:
            return st
        slot = st.req_cbslot[i]
        act = pred & (slot >= 0)
        slot_c = jnp.clip(slot, 0, None)
        if self._has_breaker:
            st = self._breaker_report(
                st, slot_c, st.req_probe[i] > 0, failed, now, act,
            )
        if self._has_health:
            alpha = jnp.float32(self._health_alpha)
            x = jnp.where(failed, jnp.float32(1.0), jnp.float32(0.0))
            h = (1.0 - alpha) * st.hl_h[slot_c] + alpha * x
            in_rot = st.hl_until[slot_c] <= 0
            eject = act & in_rot & (h >= ov.health_threshold)
            st = st._replace(
                hl_h=st.hl_h.at[slot_c].set(
                    jnp.where(act, h, st.hl_h[slot_c]),
                ),
                hl_until=st.hl_until.at[slot_c].set(
                    jnp.where(
                        eject,
                        now + jnp.float32(self._health_readmit),
                        st.hl_until[slot_c],
                    ),
                ),
                n_ejections=st.n_ejections + jnp.where(eject, 1, 0),
            )
        return st._replace(
            req_cbslot=st.req_cbslot.at[i].set(
                jnp.where(act, -1, st.req_cbslot[i]),
            ),
            req_probe=st.req_probe.at[i].set(
                jnp.where(act, 0, st.req_probe[i]),
            ),
        )

    def _arrive_lb_branch(self, st, i, now, key, ov, pred, weights=None) -> EngineState:
        """Route one request at the LB (empty rotation drops the request;
        with a circuit breaker, open slots are skipped in place and a fully
        open rotation REJECTS the request — an overload protection)."""
        if self.plan.n_lb_edges == 0:
            return st
        p = self.params
        st, pred = self._hedge_checkpoint(st, i, now, pred)
        empty = st.lb_len <= 0
        drop_empty = pred & empty
        route = pred & ~empty

        if self._has_report:
            el = max(self.plan.n_lb_edges, 1)
            admits = jnp.ones(el, dtype=bool)
            if self._has_breaker:
                # lazy cooldown expiry: open slots whose cooldown has
                # elapsed become half-open with fresh probe slots
                wake = route & (st.cb_state == 1) & (now >= st.cb_open_until)
                st = st._replace(
                    cb_state=jnp.where(wake, 2, st.cb_state),
                    cb_probes_out=jnp.where(wake, 0, st.cb_probes_out),
                    cb_probe_ok=jnp.where(wake, 0, st.cb_probe_ok),
                )
                if self.trace is not None:
                    # lazy open -> half-open wakes, one ring entry per slot
                    for k in range(el):
                        st = self._bk(st, k, 2, now, wake[k])
                admits = (st.cb_state == 0) | (
                    (st.cb_state == 2)
                    & (st.cb_probes_out < self.plan.breaker_probes)
                )
            if self._has_health:
                # lazy readmission: elapsed ejections rejoin with a fresh
                # EWMA before this pick considers them
                ready = route & (st.hl_until > 0) & (now >= st.hl_until)
                st = st._replace(
                    hl_h=jnp.where(ready, 0.0, st.hl_h),
                    hl_until=jnp.where(ready, 0.0, st.hl_until),
                )
                healthy = st.hl_until <= 0
                admits_h = admits & healthy
                # panic bypass: when every breaker-admitted rotation member
                # is health-ejected, route on breaker admits alone — an
                # all-ejected rotation must not blackhole traffic
                pos = jnp.arange(el, dtype=jnp.int32)
                valid = pos < st.lb_len
                any_h = jnp.any(valid & admits_h[st.lb_order])
                admits = jnp.where(any_h, admits_h, admits)
            if weights is not None:
                slot, none_open = self._lb_pick_weighted(
                    st, weights, jax.random.fold_in(key, 33), admits,
                )
                rotated = st.lb_order
            else:
                slot, rotated, none_open = self._lb_pick_breaker(st, admits)
            reject = route & none_open
            route = route & ~none_open
            st = st._replace(
                n_rejected=st.n_rejected + jnp.where(reject, 1, 0),
                req_ev=st.req_ev.at[i].set(
                    jnp.where(reject, EV_IDLE, st.req_ev[i]),
                ),
                req_t=st.req_t.at[i].set(
                    jnp.where(reject, INF, st.req_t[i]),
                ),
            )
            probe = jnp.bool_(False)
            if self._has_breaker:
                probe = route & (st.cb_state[slot] == 2)
                st = st._replace(
                    cb_probes_out=st.cb_probes_out.at[slot].add(
                        jnp.where(probe, 1, 0),
                    ),
                )
            st = st._replace(
                req_cbslot=st.req_cbslot.at[i].set(
                    jnp.where(route, slot, st.req_cbslot[i]),
                ),
                req_probe=st.req_probe.at[i].set(
                    jnp.where(probe, 1, jnp.where(route, 0, st.req_probe[i])),
                ),
            )
        else:
            if weights is not None:
                slot, _none = self._lb_pick_weighted(
                    st, weights, jax.random.fold_in(key, 33),
                )
                rotated = st.lb_order
            else:
                slot, rotated = self._lb_pick(st)
        order = jnp.where(route, rotated, st.lb_order)
        e = p.lb_edge_index[slot]
        dropped, delay = self._sample_edge(e, now, jax.random.fold_in(key, 32), ov)
        arrive = now + delay
        ok = route & ~dropped
        drop_edge = route & dropped
        if self._has_report:
            # a dropped send on the routing edge is a connection failure
            st = self._breaker_server_report(
                st, i, now, jnp.bool_(True), ov, drop_edge,
            )

        st = self._hop(st, i, self.HOP_LB, now, pred)
        st = self._hop(st, i, self.HOP_EDGE + p.lb_edge_index[slot], arrive, ok)
        st = self._edge_interval(st, e, now, arrive, ok)
        if self.blame:
            # LB routing is instantaneous; the routed edge's transit is
            # credited directly and the cursor parks at the server arrival
            st = self._bl_flush(st, i, now, pred)
            st = self._bl_span(st, i, self._bl_ce(e, _bl.PH_TRANSIT), delay, ok)
            st = self._bl_set(
                st, i, arrive, self._bl_cc(_bl.PH_TRANSIT), ok,
            )
        if self.trace is not None:
            st = self._fr(st, i, FR_ARRIVE_LB, -1, now, pred)
            if self._has_report:
                st = self._fr(st, i, FR_REJECT, -1, now, reject)
            st = self._fr(st, i, FR_DROP, -1, now, drop_empty)
            st = self._fr(st, i, FR_DROP, e, now, drop_edge)
            st = self._fr(st, i, FR_TRANSIT, e, arrive, ok)
        free = drop_empty | drop_edge
        client_fail = (free | reject) if self._has_report else free
        st = st._replace(
            lb_order=order,
            lb_conn=st.lb_conn.at[slot].add(jnp.where(ok, 1, 0)),
            req_ev=st.req_ev.at[i].set(
                jnp.where(free, EV_IDLE, jnp.where(ok, EV_ARRIVE_SRV, st.req_ev[i])),
            ),
            req_t=st.req_t.at[i].set(
                jnp.where(free, INF, jnp.where(ok, arrive, st.req_t[i])),
            ),
            req_srv=st.req_srv.at[i].set(
                jnp.where(ok, p.lb_target[slot], st.req_srv[i]),
            ),
            req_lbslot=st.req_lbslot.at[i].set(
                jnp.where(ok, slot, st.req_lbslot[i]),
            ),
            n_dropped=st.n_dropped + jnp.where(free, 1, 0),
        )
        return self._client_fail(st, i, now, key, client_fail)

    def _arrive_srv_branch(self, st, i, now, key, ov, pred) -> EngineState:
        """Arrival at a server: endpoint pick, RAM-first admission."""
        p = self.params
        s = st.req_srv[i]

        # close the LB edge traversal (live least-connections counter)
        lbslot = st.req_lbslot[i]
        if self.plan.n_lb_edges > 0:
            dec = pred & (lbslot >= 0)
            st = st._replace(
                lb_conn=st.lb_conn.at[jnp.clip(lbslot, 0, None)].add(
                    jnp.where(dec, -1, 0),
                ),
                req_lbslot=st.req_lbslot.at[i].set(
                    jnp.where(pred, -1, st.req_lbslot[i]),
                ),
            )

        # server-side routing boundary: a loser arriving after the race
        # was won is cancelled BEFORE admission (outage check, rate
        # limiter, sockets) — admitted work is never clawed back
        st, pred = self._hedge_checkpoint(st, i, now, pred)

        if self._has_srv_faults:
            # server-outage fault window: the server is dark and hard-
            # refuses the arrival.  Unlike the legacy SERVER_DOWN event
            # (LB rotation removal — a graceful drain), the LB only learns
            # about this through the breaker's failure channel; the client
            # through its retry policy.
            dark = pred & self._srv_faulted(s, now, ov)
            st = st._replace(
                req_ev=st.req_ev.at[i].set(
                    jnp.where(dark, EV_IDLE, st.req_ev[i]),
                ),
                req_t=st.req_t.at[i].set(
                    jnp.where(dark, INF, st.req_t[i]),
                ),
                n_rejected=st.n_rejected + jnp.where(dark, 1, 0),
                n_dark_lost=st.n_dark_lost + jnp.where(dark, 1, 0),
            )
            if self.trace is not None:
                st = self._fr(st, i, FR_REJECT, s, now, dark)
            st = self._breaker_server_report(
                st, i, now, jnp.bool_(True), ov, dark,
            )
            st = self._client_fail(st, i, now, key, dark)
            pred = pred & ~dark
        if self._has_rl:
            # token-bucket rate limiter: lazy refill at arrival, refuse
            # when no whole token remains (runs before the socket check)
            rps = p.server_rate_limit[s]
            has_rl = pred & (rps >= 0)
            tokens = jnp.minimum(
                p.server_rate_burst[s].astype(jnp.float32),
                st.rl_tokens[s]
                + (now - st.rl_last[s]) * jnp.maximum(rps, 0.0),
            )
            limited = has_rl & (tokens < 1.0)
            st = st._replace(
                rl_tokens=st.rl_tokens.at[s].set(
                    jnp.where(
                        has_rl,
                        tokens - jnp.where(limited, 0.0, 1.0),
                        st.rl_tokens[s],
                    ),
                ),
                rl_last=st.rl_last.at[s].set(
                    jnp.where(has_rl, now, st.rl_last[s]),
                ),
                req_ev=st.req_ev.at[i].set(
                    jnp.where(limited, EV_IDLE, st.req_ev[i]),
                ),
                req_t=st.req_t.at[i].set(
                    jnp.where(limited, INF, st.req_t[i]),
                ),
                n_rejected=st.n_rejected + jnp.where(limited, 1, 0),
            )
            if self.trace is not None:
                st = self._fr(st, i, FR_REJECT, s, now, limited)
            st = self._breaker_server_report(
                st, i, now, jnp.bool_(True), ov, limited,
            )
            st = self._client_fail(st, i, now, key, limited)
            pred = pred & ~limited
        if self._has_conn:
            # socket capacity: refuse the arrival when the server is full
            cap = p.server_conn_cap[s]
            refuse = pred & (cap >= 0) & (st.srv_conn[s] >= cap)
            st = st._replace(
                req_ev=st.req_ev.at[i].set(
                    jnp.where(refuse, EV_IDLE, st.req_ev[i]),
                ),
                req_t=st.req_t.at[i].set(
                    jnp.where(refuse, INF, st.req_t[i]),
                ),
                n_rejected=st.n_rejected + jnp.where(refuse, 1, 0),
            )
            if self.trace is not None:
                st = self._fr(st, i, FR_REJECT, s, now, refuse)
            st = self._breaker_server_report(
                st, i, now, jnp.bool_(True), ov, refuse,
            )
            st = self._client_fail(st, i, now, key, refuse)
            pred = pred & ~refuse
            st = st._replace(
                srv_conn=st.srv_conn.at[s].add(jnp.where(pred, 1, 0)),
            )

        st = self._hop(st, i, self.HOP_SERVER + s, now, pred)
        if self.trace is not None:
            st = self._fr(st, i, FR_ARRIVE_SRV, s, now, pred)
        u = draw_uniform(jax.random.fold_in(key, 16))
        # weighted endpoint pick (uniform weights lower to the evenly
        # spaced cumulative table, preserving the reference's behavior)
        ep = jnp.minimum(
            searchsorted_small(p.endpoint_cum[s], u, "right"),
            p.n_endpoints[s] - 1,
        )
        st = st._replace(
            req_ep=st.req_ep.at[i].set(jnp.where(pred, ep, st.req_ep[i])),
        )
        if self._has_brownout:
            # brownout decision, latched once per arrival at endpoint
            # start: above the ready-queue threshold the endpoint serves
            # the degraded (cheaper) step profile instead of shedding
            bq = ov.brownout_q[s]
            deg = (
                pred
                & (bq >= 0)
                & (st.cpu_wait_n[s].astype(jnp.float32) >= bq)
            )
            st = st._replace(
                req_degraded=st.req_degraded.at[i].set(
                    jnp.where(pred, jnp.where(deg, 1, 0), st.req_degraded[i]),
                ),
            )
        if not self._has_ram:
            # no RAM steps anywhere in the plan: admission always succeeds
            return self._seg_start(st, i, s, ep, jnp.int32(0), now, key, ov, pred)

        need = p.endpoint_ram[s, ep]
        if self._has_brownout:
            need = jnp.where(
                st.req_degraded[i] == 1,
                need * p.server_brownout_ram[s],
                need,
            )
        st = st._replace(
            req_ram=st.req_ram.at[i].set(jnp.where(pred, need, st.req_ram[i])),
        )

        ram_waiters = st.ram_wait_n[s] > 0
        granted = pred & ((need <= 0) | (~ram_waiters & (st.ram_free[s] >= need)))
        blocked = pred & ~granted

        st = st._replace(
            ram_free=st.ram_free.at[s].add(jnp.where(granted, -need, 0.0)),
            ram_ticket=st.ram_ticket.at[s].add(jnp.where(blocked, 1, 0)),
            ram_wait_n=st.ram_wait_n.at[s].add(jnp.where(blocked, 1, 0)),
            req_ev=st.req_ev.at[i].set(
                jnp.where(blocked, EV_WAIT_RAM, st.req_ev[i]),
            ),
            req_t=st.req_t.at[i].set(jnp.where(blocked, INF, st.req_t[i])),
            req_ticket=st.req_ticket.at[i].set(
                jnp.where(blocked, st.ram_ticket[s], st.req_ticket[i]),
            ),
        )
        if self.trace is not None:
            st = self._fr(st, i, FR_WAIT_RAM, s, now, blocked)
        if self.blame:
            # park the attribution cursor on the RAM-admission queue; the
            # grant (EV_RESUME) wakes the slot at grant time and
            # _seg_start's flush credits the whole wait to this cell
            st = self._bl_flush(st, i, now, blocked)
            st = self._bl_set(st, i, now, self._bl_cs(s, _bl.PH_Q_RAM), blocked)
        st = self._gauge_add(st, now, self._g_ram(s), need, granted & (need > 0))
        return self._seg_start(st, i, s, ep, jnp.int32(0), now, key, ov, granted)

    def _resume_branch(self, st, i, now, key, ov, pred) -> EngineState:
        """RAM was granted by a releasing request: start the endpoint."""
        if not self._has_ram:
            return st  # EV_RESUME can never occur without RAM admission
        s = st.req_srv[i]
        ep = st.req_ep[i]
        st = self._gauge_add(
            st,
            now,
            self._g_ram(s),
            st.req_ram[i],
            pred & (st.req_ram[i] > 0),
        )
        if self.trace is not None:
            st = self._fr(st, i, FR_RUN, s, now, pred)
        return self._seg_start(st, i, s, ep, jnp.int32(0), now, key, ov, pred)

    def _cpu_handoff(self, st, s, now, was_cpu) -> EngineState:
        """Release one core of server ``s`` or grant it to the head FIFO
        waiter.  With dequeue deadlines, an expired grantee takes the core
        for ZERO service as an immediate EV_ABANDON event (it hands the
        core onward and leaves when that event fires — the oracle's
        acquire-check-release at the same timestamp)."""
        p = self.params
        waiting = (st.req_ev == EV_WAIT_CPU) & (st.req_srv == s)
        tick = jnp.where(waiting, st.req_ticket, NO_TICKET)
        j = jnp.argmin(tick).astype(jnp.int32)
        grant = was_cpu & (tick[j] < NO_TICKET)
        release = was_cpu & ~grant
        jdur = p.seg_dur[st.req_srv[j], st.req_ep[j], st.req_seg[j]]
        if self._has_brownout:
            jdur = jnp.where(
                st.req_degraded[j] == 1,
                jdur * p.server_brownout_cpu[s],
                jdur,
            )
        ev_next = jnp.int32(EV_SEG_END)
        t_next = now + jdur
        if self._has_timeout:
            deadline = p.server_queue_timeout[s]
            expired = (
                grant
                & (deadline >= 0)
                & (now - st.req_wait_t[j] > deadline)
            )
            ev_next = jnp.where(expired, EV_ABANDON, ev_next)
            t_next = jnp.where(expired, now, t_next)
        jidx = jnp.where(grant, j, jnp.int32(self.pool))
        st = st._replace(
            cores_free=st.cores_free.at[s].add(jnp.where(release, 1, 0)),
            cpu_wait_n=st.cpu_wait_n.at[s].add(jnp.where(grant, -1, 0)),
            req_ev=st.req_ev.at[jidx].set(ev_next, mode="drop"),
            req_t=st.req_t.at[jidx].set(t_next, mode="drop"),
            req_ticket=st.req_ticket.at[jidx].set(NO_TICKET, mode="drop"),
        )
        if self.trace is not None:
            st = self._fr(st, j, FR_RUN, s, now, grant)
        if self.blame:
            # the grantee is re-armed directly (EV_SEG_END at now + jdur,
            # no event fires at grant time), so close its ready-queue wait
            # and open its service span here rather than in a branch
            st = self._bl_flush(st, j, now, grant)
            st = self._bl_set(st, j, now, self._bl_cs(s, _bl.PH_SERVICE), grant)
        return self._gauge_add(st, now, self._g_ready(s), -1.0, grant)

    def _abandon_branch(self, st, i, now, key, ov, pred) -> EngineState:
        """Dequeue deadline exceeded: the request holds the core for zero
        service — hand it onward, release RAM/connection, count rejected."""
        if not self._has_timeout:
            return st
        s = st.req_srv[i]
        st = self._cpu_handoff(st, s, now, pred)
        st = self._release_ram(st, i, s, now, pred)
        if self._has_conn:
            st = st._replace(
                srv_conn=st.srv_conn.at[s].add(jnp.where(pred, -1, 0)),
            )
        st = st._replace(
            req_ev=st.req_ev.at[i].set(jnp.where(pred, EV_IDLE, st.req_ev[i])),
            req_t=st.req_t.at[i].set(jnp.where(pred, INF, st.req_t[i])),
            req_ram=st.req_ram.at[i].set(jnp.where(pred, 0.0, st.req_ram[i])),
            n_rejected=st.n_rejected + jnp.where(pred, 1, 0),
        )
        if self.trace is not None:
            st = self._fr(st, i, FR_REJECT, s, now, pred)
        st = self._breaker_server_report(st, i, now, jnp.bool_(True), ov, pred)
        return self._client_fail(st, i, now, key, pred)

    def _seg_end_branch(self, st, i, now, key, ov, pred) -> EngineState:
        """A CPU burst or IO sleep finished: hand off the core / leave the IO
        queue, then start the next segment."""
        p = self.params
        s = st.req_srv[i]
        ep = st.req_ep[i]
        seg = st.req_seg[i]
        kind = p.seg_kind[s, ep, seg]
        was_cpu = pred & (kind == SEG_CPU)
        was_io = pred & (kind == SEG_IO)
        if self._has_cache:
            was_io = was_io | (pred & (kind == SEG_CACHE))
        if self._has_serving:
            # the prefill/decode sleeps ride the io gauge between grant
            # (+1 at EV_SV_GRANT / decode fit) and each phase's end here;
            # generation's end releases the batch slot + KV hold and
            # cascades queued admission grants
            was_pf = pred & (kind == SEG_PREFILL)
            was_dc = pred & (kind == SEG_DECODE)
            was_io = was_io | was_pf | was_dc
            st = self._release_sv(st, i, s, now, was_dc)

        st = self._cpu_handoff(st, s, now, was_cpu)

        if self._has_db:
            # DB connection handoff, mirroring the core queue's discipline
            was_db = pred & (kind == SEG_DB)
            was_io = was_io | was_db
            dwaiting = (st.req_ev == EV_WAIT_DB) & (st.req_srv == s)
            dtick = jnp.where(dwaiting, st.req_ticket, NO_TICKET)
            dj = jnp.argmin(dtick).astype(jnp.int32)
            dgrant = was_db & (dtick[dj] < NO_TICKET)
            drelease = was_db & ~dgrant
            djdur = p.seg_dur[st.req_srv[dj], st.req_ep[dj], st.req_seg[dj]]
            djidx = jnp.where(dgrant, dj, jnp.int32(self.pool))
            st = st._replace(
                db_free=st.db_free.at[s].add(jnp.where(drelease, 1, 0)),
                db_wait_n=st.db_wait_n.at[s].add(jnp.where(dgrant, -1, 0)),
                req_ev=st.req_ev.at[djidx].set(EV_SEG_END, mode="drop"),
                req_t=st.req_t.at[djidx].set(now + djdur, mode="drop"),
                req_ticket=st.req_ticket.at[djidx].set(NO_TICKET, mode="drop"),
            )
            if self.trace is not None:
                st = self._fr(st, dj, FR_RUN, s, now, dgrant)
            if self.blame:
                # DB grantee is re-armed directly like the CPU handoff:
                # close its pool wait, open its query (service) span
                st = self._bl_flush(st, dj, now, dgrant)
                st = self._bl_set(
                    st, dj, now, self._bl_cs(s, _bl.PH_SERVICE), dgrant,
                )

        # leave the IO queue
        st = self._gauge_add(st, now, self._g_io(s), -1.0, was_io)

        return self._seg_start(st, i, s, ep, seg + 1, now, key, ov, pred)

    # ==================================================================
    # main loop
    # ==================================================================

    def _init_state(self, key, ov) -> EngineState:
        plan = self.plan
        pool = self.pool
        elp = max(plan.n_lb_edges, 1)
        n_gauge_rows = (
            self._gauge_samples + 2 if self._collect_gauge_grid else 1
        )
        n_gauges = plan.n_gauges if self._collect_gauge_grid else 1
        maxn = self.max_requests if self.collect_clocks else 1
        st = EngineState(
            req_t=jnp.full(pool, INF, jnp.float32),
            req_ev=jnp.zeros(pool, jnp.int32),
            req_srv=jnp.zeros(pool, jnp.int32),
            req_ep=jnp.zeros(pool, jnp.int32),
            req_seg=jnp.zeros(pool, jnp.int32),
            req_ram=jnp.zeros(pool, jnp.float32),
            req_ticket=jnp.full(pool, NO_TICKET, jnp.int32),
            req_start=jnp.zeros(pool, jnp.float32),
            req_lbslot=jnp.full(pool, -1, jnp.int32),
            cores_free=jnp.asarray(plan.server_cores),
            ram_free=jnp.asarray(plan.server_ram),
            cpu_ticket=jnp.zeros(plan.n_servers, jnp.int32),
            ram_ticket=jnp.zeros(plan.n_servers, jnp.int32),
            cpu_wait_n=jnp.zeros(plan.n_servers, jnp.int32),
            ram_wait_n=jnp.zeros(plan.n_servers, jnp.int32),
            # -1 (unlimited / not modeled) becomes a huge free count so the
            # acquire test never blocks without a branch
            db_free=jnp.where(
                jnp.asarray(plan.server_db_pool) >= 0,
                jnp.asarray(plan.server_db_pool),
                jnp.int32(2**30),
            ),
            srv_conn=jnp.zeros(plan.n_servers, jnp.int32),
            db_ticket=jnp.zeros(plan.n_servers, jnp.int32),
            db_wait_n=jnp.zeros(plan.n_servers, jnp.int32),
            lb_order=jnp.arange(elp, dtype=jnp.int32),
            lb_len=jnp.int32(plan.n_lb_edges),
            lb_conn=jnp.zeros(elp, jnp.int32),
            smp_now=(
                jnp.zeros(self._n_gen, jnp.float32)
                if self._n_gen > 1
                else jnp.float32(0.0)
            ),
            smp_window_end=(
                jnp.zeros(self._n_gen, jnp.float32)
                if self._n_gen > 1
                else jnp.float32(0.0)
            ),
            smp_lam=(
                jnp.zeros(self._n_gen, jnp.float32)
                if self._n_gen > 1
                else jnp.float32(0.0)
            ),
            next_arrival=(
                jnp.zeros(self._n_gen, jnp.float32)
                if self._n_gen > 1
                else jnp.float32(0.0)
            ),
            req_wait_t=(
                jnp.zeros(pool, jnp.float32)
                if self._has_timeout
                else jnp.zeros(1, jnp.float32)
            ),
            req_cbslot=(
                jnp.full(pool, -1, jnp.int32)
                if self._has_report
                else jnp.zeros(1, jnp.int32)
            ),
            req_probe=(
                jnp.zeros(pool, jnp.int32)
                if self._has_report
                else jnp.zeros(1, jnp.int32)
            ),
            rl_tokens=(
                jnp.asarray(plan.server_rate_burst, jnp.float32)
                if self._has_rl
                else jnp.zeros(1, jnp.float32)
            ),
            rl_last=jnp.zeros(
                plan.n_servers if self._has_rl else 1, jnp.float32,
            ),
            cb_state=jnp.zeros(elp if self._has_breaker else 1, jnp.int32),
            cb_consec=jnp.zeros(elp if self._has_breaker else 1, jnp.int32),
            cb_open_until=jnp.zeros(
                elp if self._has_breaker else 1, jnp.float32,
            ),
            cb_probes_out=jnp.zeros(
                elp if self._has_breaker else 1, jnp.int32,
            ),
            cb_probe_ok=jnp.zeros(elp if self._has_breaker else 1, jnp.int32),
            req_hops=(
                jnp.full((pool, self._hop_cap), -1, jnp.int32)
                if self.collect_traces
                else jnp.zeros((1, 1), jnp.int32)
            ),
            req_hop_t=(
                jnp.zeros((pool, self._hop_cap), jnp.float32)
                if self.collect_traces
                else jnp.zeros((1, 1), jnp.float32)
            ),
            req_hop_n=jnp.zeros(pool if self.collect_traces else 1, jnp.int32),
            tr_code=(
                jnp.full((maxn, self._hop_cap), -1, jnp.int32)
                if self.collect_traces
                else jnp.zeros((1, 1), jnp.int32)
            ),
            tr_t=(
                jnp.zeros((maxn, self._hop_cap), jnp.float32)
                if self.collect_traces
                else jnp.zeros((1, 1), jnp.float32)
            ),
            tr_n=jnp.zeros(maxn if self.collect_traces else 1, jnp.int32),
            req_deadline=(
                jnp.full(pool, INF, jnp.float32)
                if self._has_retry
                else jnp.zeros(1, jnp.float32)
            ),
            req_attempt=(
                jnp.ones(pool, jnp.int32)
                if self._has_retry
                else jnp.zeros(1, jnp.int32)
            ),
            req_orphan=jnp.zeros(pool if self._has_retry else 1, jnp.int32),
            rb_tokens=jnp.float32(
                self._rb_cap if self._rb_cap is not None else 0.0,
            ),
            rb_last=jnp.float32(0.0),
            att_hist=jnp.zeros(
                self._att_bins if self._has_retry else 1, jnp.int32,
            ),
            n_timed_out=jnp.int32(0),
            n_retries=jnp.int32(0),
            n_budget_exhausted=jnp.int32(0),
            req_llm=jnp.zeros(pool if self._has_llm else 1, jnp.float32),
            llm_sum=jnp.float32(0.0),
            llm_sumsq=jnp.float32(0.0),
            # serving batch gate: -1 means unlimited — lift to a huge free
            # count (slots) / level (tokens) so the admit test is branchless
            sv_slots_free=(
                jnp.where(
                    jnp.asarray(plan.serve_slots) >= 0,
                    jnp.asarray(plan.serve_slots),
                    jnp.int32(2**30),
                )
                if self._has_serving
                else jnp.zeros(1, jnp.int32)
            ),
            sv_tokens_free=(
                jnp.where(
                    ov.serve_tokens >= 0,
                    ov.serve_tokens.astype(jnp.float32),
                    jnp.float32(1e30),
                )
                if self._has_serving
                else jnp.zeros(1, jnp.float32)
            ),
            sv_ticket=jnp.zeros(
                plan.n_servers if self._has_serving else 1, jnp.int32,
            ),
            sv_wait_n=jnp.zeros(
                plan.n_servers if self._has_serving else 1, jnp.int32,
            ),
            req_tok_in=jnp.full(
                pool if self._has_serving else 1, -1.0, jnp.float32,
            ),
            req_tok_out=jnp.full(
                pool if self._has_serving else 1, -1.0, jnp.float32,
            ),
            req_sv_evict=jnp.zeros(
                pool if self._has_serving else 1, jnp.int32,
            ),
            req_sv_hold=jnp.zeros(
                pool if self._has_serving else 1, jnp.float32,
            ),
            n_prefill_tok=jnp.float32(0.0),
            n_decode_tok=jnp.float32(0.0),
            n_kv_evict=jnp.int32(0),
            llm_store=jnp.zeros(
                maxn if (self._has_llm and self.collect_clocks) else 1,
                jnp.float32,
            ),
            tl_ptr=jnp.int32(0),
            nxt_i=jnp.int32(0),
            nxt_t=jnp.float32(INF),  # empty pool
            key=key,
            it=jnp.int32(1),
            hist=jnp.zeros(self.n_hist_bins, jnp.int32),
            lat_count=jnp.int32(0),
            lat_sum=jnp.float32(0.0),
            lat_sumsq=jnp.float32(0.0),
            lat_min=INF,
            lat_max=jnp.float32(0.0),
            thr=jnp.zeros(self.n_thr, jnp.int32),
            gauge=jnp.zeros((n_gauge_rows, n_gauges), jnp.float32),
            clock=jnp.zeros((maxn, 2), jnp.float32),
            clock_n=jnp.int32(0),
            n_generated=jnp.int32(0),
            n_rejected=jnp.int32(0),
            n_dark_lost=jnp.int32(0),
            n_dropped=jnp.int32(0),
            n_overflow=jnp.int32(0),
            req_seq=jnp.zeros(pool if self._crn else 1, jnp.int32),
            req_draws=jnp.zeros(pool if self._crn else 1, jnp.int32),
            arr_ctr=jnp.int32(0),
            req_fr=(
                jnp.full(pool, -1, jnp.int32)
                if self.trace is not None
                else jnp.zeros(1, jnp.int32)
            ),
            fr_ev=jnp.zeros(
                (self._fr_k, self._fr_slots)
                if self.trace is not None
                else (1, 1),
                jnp.int32,
            ),
            fr_node=jnp.zeros(
                (self._fr_k, self._fr_slots)
                if self.trace is not None
                else (1, 1),
                jnp.int32,
            ),
            fr_t=jnp.zeros(
                (self._fr_k, self._fr_slots)
                if self.trace is not None
                else (1, 1),
                jnp.float32,
            ),
            fr_n=jnp.zeros(
                self._fr_k if self.trace is not None else 1, jnp.int32,
            ),
            bk_t=jnp.zeros(
                self._bk_cap if self.trace is not None else 1, jnp.float32,
            ),
            bk_slot=jnp.zeros(
                self._bk_cap if self.trace is not None else 1, jnp.int32,
            ),
            bk_state=jnp.zeros(
                self._bk_cap if self.trace is not None else 1, jnp.int32,
            ),
            bk_n=jnp.int32(0),
            req_bl=jnp.zeros(
                (pool, self._bl_cells) if self.blame else (1, 1),
                jnp.float32,
            ),
            bl_t=jnp.zeros(pool if self.blame else 1, jnp.float32),
            bl_cell=jnp.zeros(pool if self.blame else 1, jnp.int32),
            bl_grid=jnp.zeros(
                (self._bl_cells, self._bl_bins) if self.blame else (1, 1),
                jnp.float32,
            ),
            bl_lat=jnp.zeros(self._bl_bins if self.blame else 1, jnp.float32),
            bl_store=jnp.zeros(
                (maxn, self._bl_cells)
                if (self.blame and self.collect_clocks)
                else (1, 1),
                jnp.float32,
            ),
            req_prime=jnp.zeros(pool if self._has_hedge else 1, jnp.int32),
            req_is_hedge=jnp.zeros(
                pool if self._has_hedge else 1, jnp.int32,
            ),
            hg_t=jnp.full(
                pool if self._has_hedge else 1, INF, jnp.float32,
            ),
            hg_n=jnp.zeros(pool if self._has_hedge else 1, jnp.int32),
            hg_live=jnp.zeros(pool if self._has_hedge else 1, jnp.int32),
            hg_done=jnp.zeros(pool if self._has_hedge else 1, jnp.int32),
            n_hedges=jnp.int32(0),
            n_hedges_won=jnp.int32(0),
            n_hedges_cancelled=jnp.int32(0),
            hl_h=jnp.zeros(elp if self._has_health else 1, jnp.float32),
            hl_until=jnp.zeros(
                elp if self._has_health else 1, jnp.float32,
            ),
            n_ejections=jnp.int32(0),
            req_degraded=jnp.zeros(
                pool if self._has_brownout else 1, jnp.int32,
            ),
            n_degraded=jnp.int32(0),
        )
        if self._has_replay:
            # deterministic replay: first arrival straight from the table
            return st._replace(next_arrival=self.params.replay_times[0])
        # first arrival (gap from t=0), per generator stream
        if self._n_gen > 1:
            for gi in range(self._n_gen):
                st = self._advance_arrival(
                    st,
                    jax.random.fold_in(key, 1000 + gi),
                    ov,
                    jnp.bool_(True),
                    gen=gi,
                )
            return st
        return self._advance_arrival(
            st,
            jax.random.fold_in(key, 0),
            ov,
            jnp.bool_(True),
        )

    def _next_times(self, st: EngineState):
        """Next event times from the cached pool argmin (see ``nxt_t``)."""
        t_pool = st.nxt_t
        if len(self.plan.timeline_times) > 0:
            ptr = jnp.clip(st.tl_ptr, 0, len(self.plan.timeline_times) - 1)
            t_tl = jnp.where(
                st.tl_ptr < len(self.plan.timeline_times),
                self.params.timeline_times[ptr],
                INF,
            )
        else:
            t_tl = INF
        t_arr = jnp.min(st.next_arrival) if self._n_gen > 1 else st.next_arrival
        return t_pool, t_arr, t_tl

    def _refresh_pool_min(self, st: EngineState) -> EngineState:
        """The single pool scan per iteration: cache argmin index + value so
        ``_cond`` and the next body read scalars.  With a retry policy the
        effective per-slot time is ``min(req_t, req_deadline)`` — a client
        timeout is an event even while the attempt is parked at INF.  With
        a hedge policy the anchor slot's pending hedge timer joins the min
        the same way."""
        eff = st.req_t
        if self._has_retry:
            eff = jnp.minimum(eff, st.req_deadline)
        if self._has_hedge:
            eff = jnp.minimum(eff, st.hg_t)
        i = jnp.argmin(eff).astype(jnp.int32)
        return st._replace(nxt_i=i, nxt_t=eff[i])

    def _cond(self, st: EngineState):
        t_pool, t_arr, t_tl = self._next_times(st)
        t_min = jnp.minimum(jnp.minimum(t_pool, t_arr), t_tl)
        return (t_min < self.plan.horizon) & (st.it < self.plan.max_iterations)

    def _body(self, st: EngineState, ov, weights=None) -> EngineState:
        t_pool, t_arr, t_tl = self._next_times(st)
        now = jnp.minimum(jnp.minimum(t_pool, t_arr), t_tl)
        in_horizon = now < self.plan.horizon
        is_tl = in_horizon & (t_tl <= now)
        is_pool = in_horizon & ~is_tl & (t_pool <= now)
        is_arr = in_horizon & ~is_tl & ~is_pool

        if self._crn:
            # CRN keying: pool events draw from (request spawn sequence,
            # per-request event counter); spawns draw from the arrival
            # sequence.  Domain separation: the arrival family folds 0,
            # pool families fold req_seq + 1 >= 1 (spawned slots >= 2).
            base = jax.random.fold_in(st.key, 0x2E4C_11B7)
            i0 = st.nxt_i
            kit_pool = jax.random.fold_in(
                jax.random.fold_in(base, st.req_seq[i0] + 1),
                st.req_draws[i0],
            )
            kit_arr = jax.random.fold_in(
                jax.random.fold_in(base, 0), st.arr_ctr,
            )
            kit = jnp.where(is_arr, kit_arr, kit_pool)
            st = st._replace(
                it=st.it + 1,
                arr_ctr=st.arr_ctr + jnp.where(is_arr, 1, 0),
                req_draws=st.req_draws.at[i0].add(jnp.where(is_pool, 1, 0)),
            )
        else:
            kit = jax.random.fold_in(st.key, st.it)
            st = st._replace(it=st.it + 1)

        st = self._timeline_branch(st, is_tl)
        st = self._spawn_branch(st, now, kit, ov, is_arr)

        # the pool's next event was cached by the previous iteration's
        # argmin; the spawn/timeline branches above never reduce req_t below
        # `now`, so the cached index stays the pool minimum when is_pool
        i = st.nxt_i
        ev = st.req_ev[i]
        if self._has_retry:
            # the slot fired on its client deadline rather than its own
            # event (deadline <= req_t; on ties the timeout wins, matching
            # the oracle heap's schedule order) — orphan + maybe re-issue;
            # the slot's real event stays pending for a later iteration
            own = st.req_t[i]
            if self._has_hedge:
                own = jnp.minimum(own, st.hg_t[i])
            is_to = is_pool & (st.req_deadline[i] <= own)
            st = self._timeout_branch(st, i, now, kit, ov, is_to)
            is_pool = is_pool & ~is_to
        if self._has_hedge:
            # the anchor's hedge timer fired before (or, on a tie, instead
            # of) the slot's own event: the oracle inserts the hedge timer
            # at spawn — the earliest heap insertion — so ties go to it
            is_hg = is_pool & (st.hg_t[i] <= st.req_t[i])
            st = self._hedge_branch(st, i, now, kit, ov, is_hg)
            is_pool = is_pool & ~is_hg
        if self._has_retry:
            st = self._retry_branch(
                st, i, now, kit, ov, is_pool & (ev == EV_RETRY),
            )
        if self._has_retry or self._has_hedge:
            st = self._client_arrive_branch(
                st, i, now, kit, ov, is_pool & (ev == EV_ARRIVE_CLIENT),
            )
        st = self._arrive_lb_branch(
            st, i, now, kit, ov, is_pool & (ev == EV_ARRIVE_LB), weights,
        )
        st = self._arrive_srv_branch(st, i, now, kit, ov, is_pool & (ev == EV_ARRIVE_SRV))
        st = self._resume_branch(st, i, now, kit, ov, is_pool & (ev == EV_RESUME))
        if self._has_serving:
            st = self._sv_grant_branch(
                st, i, now, kit, ov, is_pool & (ev == EV_SV_GRANT),
            )
        st = self._seg_end_branch(st, i, now, kit, ov, is_pool & (ev == EV_SEG_END))
        if self._has_timeout:
            st = self._abandon_branch(
                st, i, now, kit, ov, is_pool & (ev == EV_ABANDON),
            )
        return self._refresh_pool_min(st)

    def _run_one(self, key, ov: ScenarioOverrides) -> EngineState:
        st = self._init_state(key, ov)
        return jax.lax.while_loop(self._cond, lambda s: self._body(s, ov), st)

    # ==================================================================
    # public entry points
    # ==================================================================

    def init_batch(
        self,
        keys: jnp.ndarray,
        overrides: ScenarioOverrides | None = None,
    ) -> EngineState:
        """Fresh (vmapped) pre-loop state for |keys| scenarios — the entry
        point of the segmented stepping API (:meth:`run_until`)."""
        _base_ov = base_overrides(self.plan)
        ov = (
            fill_overrides(overrides, _base_ov)
            if overrides is not None
            else _base_ov
        )
        axes = ScenarioOverrides(
            *[0 if o.ndim > b.ndim else None
              for o, b in zip(ov, _base_ov)],
        )
        sig = ("init", tuple(axes))
        if sig not in self._compiled:
            self._compiled[sig] = instrument_jit(
                jax.jit(jax.vmap(self._init_state, in_axes=(0, axes))),
                engine="event",
                variant="init",
                pool=self.plan.pool_size,
            )
        return self._compiled[sig](keys, ov)

    def run_until(
        self,
        state: EngineState,
        t_stop,
        overrides: ScenarioOverrides | None = None,
        weights=None,
    ) -> EngineState:
        """Advance every scenario until its next event is at or beyond
        ``t_stop`` (clamped to the horizon) — ONE compiled call for the
        whole batch.

        The RL playground's batched rollout seam: ``state`` comes from
        :meth:`init_batch` or a previous window; ``t_stop`` is a scalar or
        (S,) per-scenario stop time; ``weights`` an optional (S, EL)
        routing-weight action (see :meth:`_lb_pick_weighted`).  Stepping
        to the horizon in windows is bit-identical to one
        :meth:`run_batch` call — the loop body and the per-iteration RNG
        derivation are the same; windows only pause it (events exactly at
        ``t_stop`` run in the next window, matching the oracle kernel's
        ``sim.run(until=...)``)."""
        _base_ov = base_overrides(self.plan)
        ov = (
            fill_overrides(overrides, _base_ov)
            if overrides is not None
            else _base_ov
        )
        axes = ScenarioOverrides(
            *[0 if o.ndim > b.ndim else None
              for o, b in zip(ov, _base_ov)],
        )
        t_stop = jnp.asarray(t_stop, jnp.float32)
        batched_stop = t_stop.ndim > 0
        has_w = weights is not None
        sig = ("until", batched_stop, has_w, tuple(axes))
        if sig not in self._compiled:

            def one(st, stop, ov_, w):
                limit = jnp.minimum(jnp.float32(self.plan.horizon), stop)

                def cond(s):
                    t_pool, t_arr, t_tl = self._next_times(s)
                    t_min = jnp.minimum(jnp.minimum(t_pool, t_arr), t_tl)
                    return (t_min < limit) & (
                        s.it < self.plan.max_iterations
                    )

                return jax.lax.while_loop(
                    cond, lambda s: self._body(s, ov_, w), st,
                )

            self._compiled[sig] = instrument_jit(
                jax.jit(
                    jax.vmap(
                        one,
                        in_axes=(
                            0,
                            0 if batched_stop else None,
                            axes,
                            0 if has_w else None,
                        ),
                    ),
                ),
                engine="event",
                variant="until",
                pool=self.plan.pool_size,
            )
        if has_w:
            weights = jnp.asarray(weights, jnp.float32)
        return self._compiled[sig](state, t_stop, ov, weights)

    def run_batch(
        self,
        keys: jnp.ndarray,
        overrides: ScenarioOverrides | None = None,
        *,
        antithetic: bool = False,
    ) -> EngineState:
        """Run |keys| scenarios in one vmapped kernel.

        ``overrides`` fields may carry a leading scenario axis or be base
        values shared by every scenario.  ``antithetic`` traces/runs the
        reflected-draw program variant (u -> 1-u, z -> -z); pairing it with
        an un-reflected batch under the SAME keys yields antithetic couples
        (docs/guides/mc-inference.md).
        """
        _base_ov = base_overrides(self.plan)
        ov = (
            fill_overrides(overrides, _base_ov)
            if overrides is not None
            else _base_ov
        )
        axes = ScenarioOverrides(
            *[0 if o.ndim > b.ndim else None
              for o, b in zip(ov, _base_ov)],
        )
        sig = (tuple(axes), antithetic)
        # hold the trace flag across the CALL, not just the first trace:
        # a shape-driven retrace inside a cached jit must re-see it
        with antithetic_trace() if antithetic else contextlib.nullcontext():
            if sig not in self._compiled:
                self._compiled[sig] = instrument_jit(
                    jax.jit(jax.vmap(self._run_one, in_axes=(0, axes))),
                    engine="event",
                    variant="vmap",
                    pool=self.plan.pool_size,
                )
            return self._compiled[sig](keys, ov)


def scenario_keys(seed: int, n: int) -> jnp.ndarray:
    """Independent per-scenario PRNG keys, prefix-stable in ``n``.

    Scenario ``i``'s key is ``fold_in(PRNGKey(seed), i)`` — a pure function
    of ``(seed, i)``, so any block ``[a, b)`` of the global deterministic
    grid derives the same keys no matter how the sweep is chunked or
    range-split across runs and hosts.  (``jax.random.split`` is NOT
    prefix-stable in ``n``: the earlier split-based grid silently gave
    ``run(k)`` different streams than the first ``k`` scenarios of
    ``run(n)`` — the substream contract CRN pairing and multi-range sweeps
    depend on; tests/parity/test_sweep_determinism.py pins it.)
    """
    base = jax.random.PRNGKey(seed)
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(n))


def engine_truncated(engine: Engine, state) -> np.ndarray:
    """Did the iteration safety cap fire with work still pending?

    Works on a single scenario's final state or a batched one (leading
    scenario axis); fast-path states have no iteration counter and are never
    truncated.  Reuses the engine's own ``_next_times`` so the detection can
    never drift from the loop's continue condition, and reduces on device so
    only an (S,) bool crosses to the host.
    """
    if hasattr(state, "truncated"):
        # the Pallas engine detects truncation inside its kernel
        return np.asarray(state.truncated).astype(bool)
    if not hasattr(state, "it"):
        return np.zeros(
            np.asarray(getattr(state, "lat_count", 0)).shape,
            dtype=bool,
        )
    plan = engine.plan

    def one(st):
        t_pool, t_arr, t_tl = engine._next_times(st)
        t_min = jnp.minimum(jnp.minimum(t_pool, t_arr), t_tl)
        return (st.it >= plan.max_iterations) & (t_min < plan.horizon)

    batched = np.ndim(state.it) > 0
    return np.asarray(jax.vmap(one)(state) if batched else one(state))


def run_single(
    payload: SimulationPayload,
    *,
    seed: int = 0,
    engine: str = "auto",
    **engine_kw,
) -> SimulationResults:
    """Run one scenario on the JAX backend, reduced to SimulationResults.

    ``engine="auto"`` uses the scan fast path when the compiler proves it
    exact for this plan (it records the same clocks and gauges), otherwise
    the general event engine; ``"event"``/``"fast"`` force one.
    """
    if engine not in ("auto", "fast", "event"):
        msg = f"engine must be 'auto', 'fast' or 'event', got {engine!r}"
        raise ValueError(msg)
    plan = compile_payload(payload)
    # per-hop traces ride the event engine's request rings (the fast path
    # computes trajectories in closed form, no per-hop state to record)
    tracing = bool(engine_kw.pop("collect_traces", False))
    if tracing and engine == "fast":
        msg = "collect_traces needs the event engine (engine='event'/'auto')"
        raise ValueError(msg)
    # the flight recorder runs on both the event engine and the scan fast
    # path (the fast path derives the same spans analytically from per-lane
    # journey state), so tracing no longer forces an engine choice
    trace = engine_kw.pop("trace", None)
    if trace is not None and not isinstance(trace, TraceConfig):
        trace = TraceConfig.model_validate(trace)
    # Gauge recording is gated on the settings like the oracle's collector —
    # unless the caller explicitly forced it, in which case everything
    # recorded is also returned.
    gauges_forced = "collect_gauges" in engine_kw
    engine_kw.setdefault(
        "collect_gauges",
        bool(payload.sim_settings.enabled_sample_metrics),
    )
    engine_kw.setdefault("collect_clocks", True)
    # an explicit pool_size is an event-engine knob: honor it by using that
    # engine rather than silently discarding the tuning on the fast path
    pool_tuned = "pool_size" in engine_kw
    use_fast = engine == "fast" or (
        engine == "auto"
        and plan.fastpath_ok
        and not pool_tuned
        and not tracing
    )
    if use_fast:
        from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine

        if pool_tuned:
            msg = "pool_size applies to the event engine; use max_requests here"
            raise ValueError(msg)
        sim_engine: Engine | FastEngine = FastEngine(plan, trace=trace, **engine_kw)
    else:
        sim_engine = Engine(
            plan, collect_traces=tracing, trace=trace, **engine_kw,
        )
    # chaos campaign: sample scenario 0's fault tables from (seed, index 0)
    # — the SAME draw the sweep path makes for its first scenario, so a
    # single run is bit-identical to sweep scenario 0
    hz_tables = None
    hazard_ov = None
    if plan.has_hazards:
        from asyncflow_tpu.compiler.hazards import hazard_fault_tables

        hz_tables = hazard_fault_tables(plan, seed, 0, 1)
        hazard_ov = ScenarioOverrides(
            None,
            None,
            None,
            None,
            None,
            fault_srv_times=jnp.asarray(hz_tables.srv_times[0]),
            fault_edge_times=jnp.asarray(hz_tables.edge_times[0]),
            fault_srv_down=jnp.asarray(hz_tables.srv_down[0]),
            fault_edge_lat=jnp.asarray(hz_tables.edge_lat[0]),
            fault_edge_drop=jnp.asarray(hz_tables.edge_drop[0]),
        )
    final = sim_engine.run_batch(scenario_keys(seed, 1), hazard_ov)
    state = jax.tree.map(lambda x: np.asarray(x[0]), final)

    if int(state.n_overflow) > 0:
        import warnings

        knob = "max_requests" if use_fast else "pool_size"
        warnings.warn(
            f"request capacity overflowed {int(state.n_overflow)} times; "
            f"latency percentiles are truncated — rerun with a larger {knob}",
            stacklevel=2,
        )
    if not use_fast and engine_truncated(sim_engine, state):
        import warnings

        warnings.warn(
            "the event engine's iteration safety cap fired before the "
            "horizon; results cover only part of the run — rerun with a "
            "shorter horizon or a larger pool/budget",
            stacklevel=2,
        )

    if sim_engine.collect_clocks:
        clock_n = int(state.clock_n)
        capacity = state.clock.shape[0]
        if clock_n > capacity:
            import warnings

            warnings.warn(
                f"clock table overflow: {clock_n - capacity} completions past "
                f"max_requests={capacity} were not recorded; analyzer latency "
                "stats exclude them — rerun with a larger max_requests",
                stacklevel=2,
            )
            clock_n = capacity
        clock = state.clock[:clock_n].astype(np.float64)
    else:
        clock = np.empty((0, 2), dtype=np.float64)

    sampled: dict[str, dict[str, np.ndarray]] = {}
    if sim_engine.collect_gauges:
        series = np.cumsum(state.gauge, axis=0)[1 : plan.n_samples + 1]
        sampled = {
            SampledMetricName.EDGE_CONCURRENT_CONNECTION.value: {
                eid: series[:, plan.gauge_edge(e)]
                for e, eid in enumerate(plan.edge_ids)
            },
            SampledMetricName.READY_QUEUE_LEN.value: {
                sid: series[:, plan.gauge_ready(s)]
                for s, sid in enumerate(plan.server_ids)
            },
            SampledMetricName.EVENT_LOOP_IO_SLEEP.value: {
                sid: series[:, plan.gauge_io(s)]
                for s, sid in enumerate(plan.server_ids)
            },
            SampledMetricName.RAM_IN_USE.value: {
                sid: series[:, plan.gauge_ram(s)]
                for s, sid in enumerate(plan.server_ids)
            },
        }
        if not gauges_forced:
            # reference collector semantics: the edge metric toggles on its
            # own, the three server metrics are all-or-nothing
            # (`/root/reference/src/asyncflow/metrics/collector.py:55-67`)
            enabled = set(payload.sim_settings.enabled_sample_metrics)
            server_metrics = {
                SampledMetricName.READY_QUEUE_LEN,
                SampledMetricName.EVENT_LOOP_IO_SLEEP,
                SampledMetricName.RAM_IN_USE,
            }
            keep: set[str] = set()
            if SampledMetricName.EDGE_CONCURRENT_CONNECTION in enabled:
                keep.add(SampledMetricName.EDGE_CONCURRENT_CONNECTION.value)
            if server_metrics <= enabled:
                keep |= {m.value for m in server_metrics}
            sampled = {k: v for k, v in sampled.items() if k in keep}
    traces = None
    if tracing:
        n_tr = min(int(state.clock_n), state.tr_code.shape[0])
        traces = decode_hop_traces(
            plan, payload, state.tr_code, state.tr_t, state.tr_n, n_tr,
        )
    flight = None
    breaker_timeline = None
    if trace is not None:
        flight = decode_flight(
            state.fr_ev, state.fr_node, state.fr_t, state.fr_n,
        )
        if hasattr(state, "bk_t"):  # the fast path carries no breaker ring
            breaker_timeline = decode_breaker(
                state.bk_t, state.bk_slot, state.bk_state, state.bk_n,
            )

    llm_cost = None
    if (
        (plan.has_llm or plan.has_serving)
        and sim_engine.collect_clocks
        and hasattr(state, "llm_store")
    ):
        llm_cost = state.llm_store[: int(state.clock_n)].astype(np.float64)

    # resilience scorecard: pure functions of the sampled tables + the
    # per-second throughput row — identical math to the sweep path
    unavailable_s = None
    degraded_goodput = None
    hazard_truncated = 0
    time_to_drain = None
    if hz_tables is not None:
        from asyncflow_tpu.compiler import hazards as _hz

        hazard_truncated = int(hz_tables.truncated[0])
        unavailable_s = _hz.unavailable_seconds(
            hz_tables.srv_times, hz_tables.srv_down, plan.horizon,
        )[0]
        thr_row = np.asarray(state.thr, np.float64)
        mask = _hz.degraded_seconds_mask(
            hz_tables, plan.horizon, thr_row.shape[0],
        )
        degraded_goodput = float(thr_row[mask[0]].sum())
        ready_key = SampledMetricName.READY_QUEUE_LEN.value
        if sampled.get(ready_key):
            series = np.stack(
                [sampled[ready_key][sid] for sid in plan.server_ids], axis=-1,
            )[None]
            first, last = _hz.window_span(hz_tables, plan.horizon)
            drain = _hz.time_to_drain(
                series, plan.sample_period, first, last,
            )[0]
            time_to_drain = None if np.isnan(drain) else float(drain)

    blame_grid = None
    blame_lat = None
    blame_req = None
    if getattr(sim_engine, "blame", False):
        blame_grid = np.asarray(state.bl_grid, np.float64)
        blame_lat = np.asarray(state.bl_lat, np.float64)
        if sim_engine.collect_clocks:
            n_bl = min(int(state.clock_n), state.bl_store.shape[0])
            blame_req = np.asarray(state.bl_store[:n_bl], np.float64)

    return SimulationResults(
        settings=payload.sim_settings,
        rqs_clock=clock,
        sampled=sampled,
        total_generated=int(state.n_generated),
        total_dropped=int(state.n_dropped),
        overflow_dropped=int(state.n_overflow),
        total_rejected=int(getattr(state, "n_rejected", 0)),
        server_ids=plan.server_ids,
        edge_ids=plan.edge_ids,
        traces=traces,
        flight=flight,
        breaker_timeline=breaker_timeline,
        llm_cost=llm_cost,
        total_timed_out=int(getattr(state, "n_timed_out", 0)),
        total_retries=int(getattr(state, "n_retries", 0)),
        retry_budget_exhausted=int(getattr(state, "n_budget_exhausted", 0)),
        attempts_hist=(
            np.asarray(state.att_hist)
            if plan.has_retry and hasattr(state, "att_hist")
            else None
        ),
        total_hedges=int(getattr(state, "n_hedges", 0)),
        hedges_won=int(getattr(state, "n_hedges_won", 0)),
        hedges_cancelled=int(getattr(state, "n_hedges_cancelled", 0)),
        lb_ejections=int(getattr(state, "n_ejections", 0)),
        degraded_completions=int(getattr(state, "n_degraded", 0)),
        dark_lost=int(getattr(state, "n_dark_lost", 0)),
        unavailable_s=unavailable_s,
        degraded_goodput=degraded_goodput,
        hazard_truncated=hazard_truncated,
        time_to_drain=time_to_drain,
        kv_evictions=(
            int(state.n_kv_evict)
            if plan.has_serving and hasattr(state, "n_kv_evict")
            else None
        ),
        prefill_tokens=(
            float(state.n_prefill_tok)
            if plan.has_serving and hasattr(state, "n_prefill_tok")
            else None
        ),
        decode_tokens=(
            float(state.n_decode_tok)
            if plan.has_serving and hasattr(state, "n_decode_tok")
            else None
        ),
        blame=blame_grid,
        blame_lat=blame_lat,
        blame_req=blame_req,
    )


def decode_hop_traces(plan, payload, tr_code, tr_t, tr_n, n_tr):
    """Hop-code rings -> the oracle's trace structure, keyed by completed
    clock row: ``{row: [(component type, component id, timestamp), ...]}``.

    Single decoder for every ring producer (jax event engine, native C++
    core) of the Engine.HOP_* code map — 0 generator, 1000+e edge,
    2000+s server, 3000 LB, 4000 client.
    """
    from asyncflow_tpu.config.constants import SystemEdges, SystemNodes

    nodes = payload.topology_graph.nodes
    lb_id = nodes.load_balancer.id if nodes.load_balancer else ""

    generators = payload.generators

    def decode(code: int) -> tuple[str, str]:
        kind, idx = divmod(int(code), 1000)
        if kind == 0:
            return (
                SystemNodes.GENERATOR,
                generators[min(idx, len(generators) - 1)].id,
            )
        if kind == 1:
            return SystemEdges.NETWORK_CONNECTION, plan.edge_ids[idx]
        if kind == 2:
            return SystemNodes.SERVER, plan.server_ids[idx]
        if kind == 3:
            return SystemNodes.LOAD_BALANCER, lb_id
        return SystemNodes.CLIENT, nodes.client.id

    codes = np.asarray(tr_code)[:n_tr].tolist()
    times = np.asarray(tr_t)[:n_tr].tolist()
    counts = np.asarray(tr_n)[:n_tr].tolist()
    return {
        k: [
            (*decode(codes[k][j]), float(times[k][j]))
            for j in range(counts[k])
        ]
        for k in range(n_tr)
    }


def sweep_results(
    engine: Engine,
    final: EngineState,
    settings=None,
    gauge_sel: np.ndarray | None = None,
) -> SweepResults:
    """Reduce a batched final state to host-side SweepResults.

    ``gauge_sel``: indices of the gauges whose streaming time series should
    be materialized (fast path with ``gauge_series_stride``; the cumsum and
    the column slice run on device so only the selected coarse series cross
    to the host).
    """
    from asyncflow_tpu.engines.jaxsim.params import hist_edges as _edges

    gauge_series = None
    series_period = None
    gauge_hist = None
    gauge_hist_cap = None
    stride = getattr(engine, "gauge_series_stride", 0)
    if gauge_sel is not None and stride:
        import jax.numpy as jnp

        from asyncflow_tpu.engines.results import (
            build_gauge_hist,
            gauge_hist_caps,
        )

        # slice the selected columns BEFORE the cumsum: only k columns are
        # materialized, not a second full (S, T+2, n_gauges) grid
        selected = final.gauge[:, :, np.asarray(gauge_sel)]
        gauge_series = np.asarray(jnp.cumsum(selected, axis=1)[:, 1:-1])
        series_period = engine.plan.sample_period * stride
        # fixed-bin value histograms across this chunk's scenario rows
        # (summed across chunks by _concat_sweeps -> SweepResults.gauge_bands).
        # Binning runs on the host over the device-reduced coarse series: one
        # float64 rule shared with every rebuild site (quarantine edits,
        # scenario-axis slicing), so sums and rebuilds are bit-consistent.
        gauge_hist_cap = gauge_hist_caps(engine.plan, gauge_sel)
        gauge_hist = build_gauge_hist(gauge_series, gauge_hist_cap)

    return SweepResults(
        gauge_series=gauge_series,
        gauge_series_period=series_period,
        gauge_hist=gauge_hist,
        gauge_hist_cap=gauge_hist_cap,
        settings=settings,
        completed=np.asarray(final.lat_count),
        latency_hist=np.asarray(final.hist),
        hist_edges=_edges(engine.n_hist_bins),
        latency_sum=np.asarray(final.lat_sum),
        latency_sumsq=np.asarray(final.lat_sumsq),
        latency_min=np.asarray(final.lat_min),
        latency_max=np.asarray(final.lat_max),
        throughput=np.asarray(final.thr),
        total_generated=np.asarray(final.n_generated),
        total_dropped=np.asarray(final.n_dropped),
        llm_cost_sum=(
            np.asarray(final.llm_sum)
            if (engine.plan.has_llm or engine.plan.has_serving)
            and hasattr(final, "llm_sum")
            else None
        ),
        llm_cost_sumsq=(
            np.asarray(final.llm_sumsq)
            if (engine.plan.has_llm or engine.plan.has_serving)
            and hasattr(final, "llm_sumsq")
            else None
        ),
        kv_evictions=(
            np.asarray(final.n_kv_evict)
            if engine.plan.has_serving and hasattr(final, "n_kv_evict")
            else None
        ),
        prefill_tokens=(
            np.asarray(final.n_prefill_tok)
            if engine.plan.has_serving and hasattr(final, "n_prefill_tok")
            else None
        ),
        decode_tokens=(
            np.asarray(final.n_decode_tok)
            if engine.plan.has_serving and hasattr(final, "n_decode_tok")
            else None
        ),
        overflow_dropped=np.asarray(final.n_overflow),
        total_rejected=(
            np.asarray(final.n_rejected)
            if hasattr(final, "n_rejected")
            else None
        ),
        total_timed_out=(
            np.asarray(final.n_timed_out)
            if engine.plan.has_retry and hasattr(final, "n_timed_out")
            else None
        ),
        total_retries=(
            np.asarray(final.n_retries)
            if engine.plan.has_retry and hasattr(final, "n_retries")
            else None
        ),
        retry_budget_exhausted=(
            np.asarray(final.n_budget_exhausted)
            if engine.plan.has_retry and hasattr(final, "n_budget_exhausted")
            else None
        ),
        attempts_hist=(
            np.asarray(final.att_hist)
            if engine.plan.has_retry and hasattr(final, "att_hist")
            else None
        ),
        total_hedges=(
            np.asarray(final.n_hedges)
            if engine.plan.has_hedge and hasattr(final, "n_hedges")
            else None
        ),
        hedges_won=(
            np.asarray(final.n_hedges_won)
            if engine.plan.has_hedge and hasattr(final, "n_hedges_won")
            else None
        ),
        hedges_cancelled=(
            np.asarray(final.n_hedges_cancelled)
            if engine.plan.has_hedge and hasattr(final, "n_hedges_cancelled")
            else None
        ),
        lb_ejections=(
            np.asarray(final.n_ejections)
            if engine.plan.has_health and hasattr(final, "n_ejections")
            else None
        ),
        degraded_completions=(
            np.asarray(final.n_degraded)
            if engine.plan.has_brownout and hasattr(final, "n_degraded")
            else None
        ),
        gauge_means=(
            np.asarray(final.gauge_means)
            if hasattr(final, "gauge_means")
            else None
        ),
        dark_lost=(
            np.asarray(final.n_dark_lost)
            if (engine.plan.has_hazards or engine.plan.has_faults)
            and hasattr(final, "n_dark_lost")
            else None
        ),
        truncated=engine_truncated(engine, final),
        flight_ev=(
            np.asarray(final.fr_ev)
            if getattr(engine, "trace", None) is not None
            else None
        ),
        flight_node=(
            np.asarray(final.fr_node)
            if getattr(engine, "trace", None) is not None
            else None
        ),
        flight_t=(
            np.asarray(final.fr_t)
            if getattr(engine, "trace", None) is not None
            else None
        ),
        flight_n=(
            np.asarray(final.fr_n)
            if getattr(engine, "trace", None) is not None
            else None
        ),
        blame_rows=(
            np.asarray(final.bl_grid, np.float32)
            if getattr(engine, "blame", False)
            else None
        ),
        blame_lat_rows=(
            np.asarray(final.bl_lat, np.float32)
            if getattr(engine, "blame", False)
            else None
        ),
        blame_hist=(
            build_blame_hist(np.asarray(final.bl_grid, np.float32))
            if getattr(engine, "blame", False)
            else None
        ),
        blame_lat_hist=(
            build_blame_hist(np.asarray(final.bl_lat, np.float32))
            if getattr(engine, "blame", False)
            else None
        ),
    )
