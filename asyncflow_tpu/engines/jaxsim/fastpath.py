"""Scan fast path: closed-form vectorized simulation for eligible plans.

For every plan the compiler proves faithful (``_fastpath_analysis`` —
alternating CPU/IO endpoints, round-robin or least-connections routing,
non-binding or uniform-need RAM), the per-scenario discrete-event loop
collapses into pure array code:

1. **Arrivals.**  Within each user-sampling window the reference's gap chain
   is exactly a Poisson process restarted at the boundary
   (`/root/reference/src/asyncflow/samplers/poisson_poisson.py:56-82`): draw
   per-window counts ``K_w ~ Poisson(lam_w * len_w)``, place arrivals as
   sorted uniforms, and subtract each window's dropped residual
   (boundary - last arrival) to recover *simulation* timestamps, which only
   advance by emitted gaps.
2. **Edges.**  Dropout/latency/spike draws are embarrassingly parallel.
3. **Round robin** with fixed membership is a deterministic function of
   LB-arrival *rank* (sort by arrival time, assign ``rank % n_edges``); with
   outage windows, a ``lax.scan`` over time-ordered arrivals carries the
   rotation and applies down/up marks with the event engines' pop /
   reinsert-at-tail discipline.  **Least connections** rides the same scan:
   edge outcomes are pre-drawn per (request, slot), so a per-slot ring of
   outstanding delivery times reproduces the live in-flight counts
   (``_routed_slots_lc``; ring capacity = compile-time 6-sigma bound).
4. **Each server is a FIFO G/G/c core queue visited once per CPU burst**
   (IO sleeps hold no core, `/root/reference/src/asyncflow/runtime/actors/
   server.py:235-255`): the compiler rewrites every alternating CPU/IO
   endpoint as visits ``(pre_io_k, cpu_k)*`` + trailing IO.  All visits of
   all requests form one merged stream ordered by enqueue time; single-core
   waits follow the Lindley recursion
   ``W_k = max(0, W_{k-1} + S_{k-1} - (A_k - A_{k-1}))`` — evaluated in
   log-depth with ``lax.associative_scan`` in max-plus form — and multi-core
   waits use the Kiefer-Wolfowitz workload-vector scan.  Visit k's enqueue
   time depends on earlier visits' waits, so multi-burst plans relax to the
   fixed point (2*kb + 2 sweeps; statistically indistinguishable from the
   oracle — deviations across key ensembles span +/-2-3% at rho 0.6, the
   same spread disjoint oracle ensembles show against each other).  The
   fixed point is only faithful up to nominal utilization RELAX_RHO_MAX
   (0.70): past it the merged-stream FIFO-order approximation biases
   latency high (+28% p95 at rho 0.75, measured), so the compiler fences
   multi-burst servers above the envelope onto the event engine
   (docs/internals/fastpath.md §5).  With one burst per endpoint a single
   sweep is exact at any utilization, reproducing the classic formulation.  Servers whose RAM admission
   can bind are settled by ``_ram_core_scan`` instead: one exact
   arrival-order pass over (admission slots, cores) jointly.
5. Chained servers (app -> DB) are processed in exit-DAG topological order.
6. **Stochastic cache segments** (hit/miss mixtures) are per-request
   duration extras on the visit tables: a miss draw adds ``miss - hit``
   seconds to the burst pre-IO slot or trailing IO the segment occupies
   (compiler: ``_fastpath_lowering``) — the queueing recursions are G/G/c,
   so random service data changes nothing structurally.
7. **Binding DB connection pools** are one extra FIFO G/G/K station per
   server: every endpoint's (single) ``io_db`` query follows its last CPU
   burst, so the station's FIFO wait — Lindley for K=1, Kiefer-Wolfowitz
   for K>1, over the merged per-server stream ordered by station-enqueue
   time — only delays departures, never feeds back into the core queue:
   exact at any utilization.  Shapes outside the model (multiple queries,
   query before a burst, binding RAM + binding pool) decline with named
   reasons and run on the event engines.
8. **Socket capacity** (round 5b): residency is a G/G/K loss system —
   ``_socket_station_scan`` carries a sorted K-vector of connection-exit
   times through one ARRIVAL-order pass, refusing arrivals whose every
   slot exits in their future, composing with the token bucket (prefilter)
   and the cap/deadline ring tests.  Eligibility
   (``compiler/plan._socket_cap_scan_reason``): single burst, no modeled
   RAM tier, no binding pool, uniform burst pre-IO (arrival order must
   equal enqueue order), K <= 128.

Everything is (N,) array work per scenario, vmapped over the batch: the
whole Monte-Carlo sweep becomes sorts + scans + elementwise math — exactly
what the TPU's vector units and XLA's fusion want.  Gauge time series are
reconstructed from [enter, leave) interval endpoints exactly like the event
engine, so metric output is identical in shape and semantics.
"""

from __future__ import annotations

import contextlib
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from asyncflow_tpu.checker.fences import raise_fence
from asyncflow_tpu.compiler.plan import (
    CACHE_POST_DB,
    CACHE_PRE_DB,
    CACHE_UNUSED,
    TARGET_SERVER,
    StaticPlan,
)
from asyncflow_tpu.engines.jaxsim.params import (
    INF,
    ScenarioOverrides,
    base_overrides,
    fill_overrides,
)
from asyncflow_tpu.engines.jaxsim.rotation import (
    rotation_advance,
    rotation_insert,
    rotation_remove,
)
from asyncflow_tpu.engines.jaxsim.sortutil import searchsorted_small, time_rank
from asyncflow_tpu.observability import blame as _blm
from asyncflow_tpu.observability.simtrace import (
    FR_ARRIVE_LB,
    FR_ARRIVE_SRV,
    FR_ABANDON,
    FR_COMPLETE,
    FR_DROP,
    FR_REJECT,
    FR_RETRY,
    FR_RUN,
    FR_SPAWN,
    FR_TIMEOUT,
    FR_TRANSIT,
    FR_WAIT_CPU,
    FR_WAIT_DB,
    FR_WAIT_RAM,
    TraceConfig,
)
from asyncflow_tpu.observability.telemetry import instrument_jit
from asyncflow_tpu.engines.jaxsim.sampling import (
    antithetic_trace,
    as_threefry as _as_threefry,
    D_EXPONENTIAL as _D_EXPONENTIAL,
    D_LOGNORMAL as _D_LOGNORMAL,
    D_NORMAL as _D_NORMAL,
    D_UNIFORM as _D_UNIFORM,
    TINY as _TINY,
    draw_normal,
    draw_uniform,
    exponential_from_u,
    hist_constants,
    latency_bin,
    lognormal,
    sample_bucket,
    truncated_normal,
)


class FastState(NamedTuple):
    """Metric outputs of one scenario (duck-compatible with EngineState)."""

    hist: jnp.ndarray
    lat_count: jnp.ndarray
    lat_sum: jnp.ndarray
    lat_sumsq: jnp.ndarray
    lat_min: jnp.ndarray
    lat_max: jnp.ndarray
    thr: jnp.ndarray
    gauge: jnp.ndarray
    clock: jnp.ndarray
    clock_n: jnp.ndarray
    n_generated: jnp.ndarray
    n_dropped: jnp.ndarray
    n_overflow: jnp.ndarray
    #: (n_gauges,) exact time-average of every gauge over the horizon —
    #: cheap per-scenario what-if statistics even in histogram-only sweeps
    gauge_means: jnp.ndarray
    #: requests refused by overload controls (rate limit / queue cap /
    #: dequeue deadline) or dark fault windows — the event engines'
    #: n_rejected counterpart
    n_rejected: jnp.ndarray
    #: the dark-window subset of n_rejected (arrivals refused because the
    #: server sat inside a fault window) — the availability numerator
    n_dark_lost: jnp.ndarray
    #: client deadlines that fired while the attempt was in flight (the
    #: orphaned attempt keeps consuming resources); 0 without a retry plan
    n_timed_out: jnp.ndarray
    #: granted backoff re-issues (event engines' n_retries counterpart)
    n_retries: jnp.ndarray
    #: retry wants denied by the token-bucket budget
    n_budget_exhausted: jnp.ndarray
    #: (max_attempts,) attempts used per ENDED logical request (completed
    #: or given up); shape (1,) without a retry plan
    att_hist: jnp.ndarray
    #: flight-recorder rings (K, slots)/(K,), identical layout to the event
    #: engine's (observability/simtrace.py) — derived analytically from the
    #: per-lane journey state; (1, 1)/(1,) placeholders when untraced so
    #: untraced programs stay bit-identical to pre-trace builds
    fr_ev: jnp.ndarray
    fr_node: jnp.ndarray
    fr_t: jnp.ndarray
    fr_n: jnp.ndarray
    #: latency attribution grids (observability/blame.py), identical layout
    #: to the event engine's: (n_cells, n_blame_bins) seconds per
    #: (component, phase) keyed by the attempt's coarse latency bin, the
    #: (n_blame_bins,) end-to-end conservation denominator, and — with
    #: collect_clocks — (N, n_cells) per-request rows compacted in clock
    #: order.  (1, 1)/(1,) placeholders when attribution is off so
    #: unattributed programs stay bit-identical to pre-blame builds.
    bl_grid: jnp.ndarray
    bl_lat: jnp.ndarray
    bl_store: jnp.ndarray


def _kw_waits(
    arrivals: jnp.ndarray,
    service: jnp.ndarray,
    valid,
    cores: int,
) -> jnp.ndarray:
    """FIFO G/G/c waiting times via the Kiefer-Wolfowitz workload vector.

    Carries the sorted vector of ABSOLUTE next-free core times; per
    customer: wait on the earliest-free core, add the service, re-sort.
    Sequential in the number of requests (a ``lax.scan``) but the carried
    state is just ``cores`` floats per lane.  Invalid (padding) entries
    compose as the identity and may appear ANYWHERE in the stream — the
    step only reads its own (arrival, service) — so callers may feed a
    shared sorted order whose other lanes are masked out.
    """

    def step(f, x):
        a, svc, ok = x
        wait = jnp.maximum(f[0] - a, 0.0)
        busy = jnp.sort(f.at[0].set(jnp.maximum(f[0], a) + svc))
        return jnp.where(ok, busy, f), jnp.where(ok, wait, 0.0)

    _, waits = jax.lax.scan(
        step,
        jnp.zeros(cores, jnp.float32),
        (
            jnp.where(valid, arrivals, 0.0),
            jnp.where(valid, service, 0.0),
            valid,
        ),
    )
    return waits


def _ram_core_scan(
    arrivals: jnp.ndarray,
    pre: jnp.ndarray,
    svc: jnp.ndarray,
    post: jnp.ndarray,
    valid,
    ram_k: int,
    cores: int,
):
    """Joint FIFO solve of RAM admission + core queue, exact for one burst.

    With at most one CPU burst per endpoint, admission order (FIFO by server
    arrival) and core order (FIFO by grant time, and grants are in arrival
    order) coincide with arrival order, so one sequential pass settles both
    queues with no relaxation.  Carries are *absolute* next-free times of the
    ``ram_k`` admission slots and ``cores`` cores (sorted ascending).

    Per time-sorted request: grant ``g = max(a, slot_free)``, burst start
    ``s = max(g + pre, core_free)``, release ``r = s + svc + post`` (RAM is
    held from grant to endpoint end,
    `/root/reference/src/asyncflow/runtime/actors/server.py:147-149,270-273`).
    Returns ``(admission_wait, core_wait, departure)`` per request in the
    given order.
    """

    def step(carry, x):
        wr, wc = carry
        a, p, d, po, ok = x
        g = jnp.maximum(a, wr[0])
        enq = g + p
        s = jnp.where(d > 0, jnp.maximum(enq, wc[0]), enq)
        r = s + d + po
        wc = jnp.where(ok & (d > 0), jnp.sort(wc.at[0].set(s + d)), wc)
        wr = jnp.where(ok, jnp.sort(wr.at[0].set(r)), wr)
        return (wr, wc), (g - a, s - enq, r)

    init = (jnp.zeros(ram_k, jnp.float32), jnp.zeros(cores, jnp.float32))
    _, (w_ram, w_cpu, dep) = jax.lax.scan(
        step,
        init,
        (arrivals, pre, svc, post, valid),
    )
    return w_ram, w_cpu, dep


def _lindley_waits(arrivals: jnp.ndarray, service: jnp.ndarray, valid) -> jnp.ndarray:
    """FIFO G/G/1 waiting times for time-sorted ``arrivals`` via max-plus scan.

    Works on the service-COMPLETION recursion ``C_k = max(A_k, C_{k-1})
    + S_k`` — element k is ``f_k(x) = max(A_k + S_k, x + S_k)``, built
    only from k's OWN arrival and service, so invalid (padding) entries
    compose as the identity and may appear ANYWHERE in the stream (a
    shared sorted order with other lanes masked out is fine).  The wait is
    ``C_k - S_k - A_k``.
    """
    svc = jnp.where(valid, service, 0.0)
    arr = jnp.where(valid, arrivals, 0.0)
    a = svc
    b = jnp.where(valid, arr + svc, -INF)

    def compose(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 + a2, jnp.maximum(b2, b1 + a2)

    _, cb = jax.lax.associative_scan(compose, (a, b))
    return jnp.maximum(0.0, cb - svc - arr)


def _token_bucket_scan(t_sorted, valid_sorted, rate: float, burst: float):
    """Accepted mask (sorted order) of the arrival token bucket.

    Mirrors the oracle (`engines/oracle/engine.py:186-202`): the bucket
    starts full, refills ``rate * dt`` capped at ``burst``, rejects below
    one whole token, and its refill clock advances on every arrival —
    rejected ones included.  Feed-forward (no queue feedback), so an
    arrival-order scan is exact.
    """

    def step(carry, x):
        tokens, last = carry
        t_i, v = x
        tok = jnp.minimum(jnp.float32(burst), tokens + (t_i - last) * rate)
        acc = v & (tok >= 1.0)
        tok = tok - jnp.where(acc, 1.0, 0.0)
        return (
            jnp.where(v, tok, tokens),
            jnp.where(v, t_i, last),
        ), acc

    _, acc = jax.lax.scan(
        step,
        (jnp.float32(burst), jnp.float32(0.0)),
        (t_sorted, valid_sorted),
    )
    return acc


def _controlled_station_scan(
    enq, dur, valid, n_cores: int, cap: int, timeout: float,
):
    """Exact FIFO G/G/c waits under a ready-queue cap and dequeue deadline.

    One arrival-order pass per controlled server: the carry holds the
    Kiefer-Wolfowitz vector of absolute core-free times plus a ring of the
    last ``cap`` service-start times.  FIFO starts are monotone, so "the
    cap-th most recent start is still in the future at my enqueue" is
    exactly "cap requests are waiting" — the shed test
    (`engines/oracle/engine.py:251-257`).  A request whose wait exceeds
    the deadline abandons at its grant, consuming zero service
    (`engine.py:276-295`): the core's free time becomes the grant instant.

    Returns (wait, shed, abandoned) per sorted element.
    """
    r = max(cap, 1)

    def step(carry, x):
        w, ring = carry
        e, s_dur, v = x
        shed = v & jnp.bool_(cap >= 0) & (ring[0] > e)
        g = jnp.maximum(e, w[0])
        wait = g - e
        live = v & ~shed
        abandoned = live & jnp.bool_(timeout >= 0.0) & (wait > timeout)
        w0 = g + jnp.where(abandoned, 0.0, s_dur)
        w = jnp.where(live, jnp.sort(w.at[0].set(w0)), w)
        ring = jnp.where(
            live, jnp.concatenate([ring[1:], jnp.array([g])]), ring,
        )
        return (w, ring), (wait, shed, abandoned)

    init = (
        jnp.zeros(n_cores, jnp.float32),
        jnp.full((r,), -INF, jnp.float32),
    )
    _, (wait, shed, abandoned) = jax.lax.scan(
        step, init, (enq, dur, valid),
    )
    return wait, shed, abandoned


def _socket_station_scan(
    arr,
    enq,
    dur,
    post,
    is_burst,
    valid,
    n_cores: int,
    conn_cap: int,
    cap: int,
    timeout: float,
):
    """Exact FIFO waits under a socket capacity (+ optional ready-queue cap
    and dequeue deadline), one ARRIVAL-order pass per server.

    Residency is a G/G/K loss system: a sorted K-vector of absolute
    connection-exit times rides the carry (like the KW core vector) — an
    arrival with every slot's exit in its future is refused before
    admission (`engines/oracle/engine.py:203-213`); an admitted request
    frees its slot at its own exit (shed: at its enqueue instant; abandon:
    at its grant; completed: after service + trailing IO; io-only
    endpoints: arrival + trailing IO).  Eligibility
    (`compiler/plan._socket_cap_scan_reason`) guarantees exits are known
    at the lane's own step and that arrival order equals enqueue order
    among burst lanes (uniform pre-IO offset), so the queue-cap ring and
    deadline tests from :func:`_controlled_station_scan` stay exact in
    this ordering and compose.

    Returns (wait, refused, shed, abandoned) per sorted element.
    """
    r = max(cap, 1)

    def step(carry, x):
        w, ring, conn = carry
        a, e, s_dur, po, b, v = x
        refused = v & (conn[0] > a)
        live = v & ~refused
        shed = live & b & jnp.bool_(cap >= 0) & (ring[0] > e)
        g = jnp.maximum(e, w[0])
        wait = jnp.where(b, g - e, 0.0)
        through = live & b & ~shed
        abandoned = through & jnp.bool_(timeout >= 0.0) & (wait > timeout)
        exit_t = jnp.where(
            b,
            jnp.where(shed, e, jnp.where(abandoned, g, g + s_dur + po)),
            a + po,
        )
        conn = jnp.where(live, jnp.sort(conn.at[0].set(exit_t)), conn)
        w0 = g + jnp.where(abandoned, 0.0, s_dur)
        w = jnp.where(through, jnp.sort(w.at[0].set(w0)), w)
        ring = jnp.where(
            through, jnp.concatenate([ring[1:], jnp.array([g])]), ring,
        )
        return (w, ring, conn), (wait, refused, shed, abandoned)

    init = (
        jnp.zeros(n_cores, jnp.float32),
        jnp.full((r,), -INF, jnp.float32),
        jnp.full((conn_cap,), -INF, jnp.float32),
    )
    _, (wait, refused, shed, abandoned) = jax.lax.scan(
        step, init, (arr, enq, dur, post, is_burst, valid),
    )
    return wait, refused, shed, abandoned


class _FlightTape:
    """Per-lane flight-record CANDIDATE stream for the analytic recorder.

    The event engine appends ring entries as its heap processes events; the
    fast path has no event loop, but along any single lane the pipeline
    emits its lifecycle transitions in event-PROCESSING order already (each
    stage's processing time is >= the previous stage's, and the entry chain
    is walked inside the spawn event like the event engine does).  So the
    recorder reduces to: collect ``(code, node, record_time, process_time,
    predicate)`` candidates in emission order, then per traced lane keep the
    predicate-true ones — a masked cumsum scatter, no sort.  ``process_time``
    is kept per candidate only for the retry driver's orphan masking (events
    processed at or after a fired client deadline are invisible, mirroring
    the event engine's ``req_fr`` detach).  Tracing consumes ZERO draws:
    every candidate reuses quantities the journey already computed.
    """

    __slots__ = ("n", "cands")

    def __init__(self, n: int) -> None:
        self.n = n
        self.cands: list[tuple] = []

    def emit(self, code: int, node, rec_t, proc_t, pred) -> None:
        n = self.n
        self.cands.append((
            int(code),
            jnp.broadcast_to(jnp.asarray(node, jnp.int32), (n,)),
            jnp.broadcast_to(jnp.asarray(rec_t, jnp.float32), (n,)),
            jnp.broadcast_to(jnp.asarray(proc_t, jnp.float32), (n,)),
            jnp.broadcast_to(jnp.asarray(pred, bool), (n,)),
        ))

    def emit_slice(
        self, code: int, node, rec_t, proc_t, pred, off: int, n_g: int,
    ) -> None:
        """Emit a candidate that lives on one generator's static slot slice
        (entry-chain hops); lanes outside the slice get a False predicate."""
        z = jnp.zeros(self.n, jnp.float32)
        self.emit(
            code,
            node,
            z.at[off : off + n_g].set(jnp.broadcast_to(rec_t, (n_g,))),
            z.at[off : off + n_g].set(jnp.broadcast_to(proc_t, (n_g,))),
            jnp.zeros(self.n, bool).at[off : off + n_g].set(pred),
        )


def _flight_rings(cands, K: int, slots: int, *, lanes=None, blocks=None):
    """Candidate stream -> ``(fr_ev, fr_node, fr_t, fr_n)`` rings.

    ``lanes``: (K2,) traced lane per trace row (non-retry: spawn-order =
    arrival-time order).  ``blocks = (A, n1)``: retry lane blocks — logical
    request r's ring is the attempt-major concat of its per-block candidate
    columns (lane ``a*n1 + r``), reproducing "a logical request keeps its
    record across re-issues".  Writes past the slot budget are counted in
    ``fr_n`` but not stored — ``FlightRecord.dropped`` stays explicit.
    """
    ev = jnp.stack([jnp.full_like(c[1], c[0]) for c in cands])  # (C, n)
    node = jnp.stack([c[1] for c in cands])
    rec = jnp.stack([c[2] for c in cands])
    pred = jnp.stack([c[4] for c in cands])
    if blocks is not None:
        A, n1 = blocks
        C = ev.shape[0]

        def fold(a):
            return (
                a.reshape(C, A, n1).transpose(1, 0, 2).reshape(A * C, n1)
            )

        k2 = min(K, n1)
        ev, node, rec, pred = (
            fold(ev)[:, :k2],
            fold(node)[:, :k2],
            fold(rec)[:, :k2],
            fold(pred)[:, :k2],
        )
    else:
        k2 = int(lanes.shape[0])
        ev, node, rec, pred = (
            ev[:, lanes],
            node[:, lanes],
            rec[:, lanes],
            pred[:, lanes],
        )
    cnt = jnp.cumsum(pred.astype(jnp.int32), axis=0) - pred
    sloti = jnp.where(pred & (cnt < slots), cnt, slots)  # slots -> dropped
    rows = jnp.broadcast_to(
        jnp.arange(k2, dtype=jnp.int32)[None, :], sloti.shape,
    )
    fr_ev = (
        jnp.zeros((k2, slots), jnp.int32).at[rows, sloti].set(ev, mode="drop")
    )
    fr_node = (
        jnp.zeros((k2, slots), jnp.int32)
        .at[rows, sloti]
        .set(node, mode="drop")
    )
    fr_t = (
        jnp.zeros((k2, slots), jnp.float32)
        .at[rows, sloti]
        .set(rec, mode="drop")
    )
    fr_n = jnp.sum(pred, axis=0).astype(jnp.int32)
    if k2 < K:
        fr_ev = jnp.pad(fr_ev, ((0, K - k2), (0, 0)))
        fr_node = jnp.pad(fr_node, ((0, K - k2), (0, 0)))
        fr_t = jnp.pad(fr_t, ((0, K - k2), (0, 0)))
        fr_n = jnp.pad(fr_n, (0, K - k2))
    return fr_ev, fr_node, fr_t, fr_n


class _BlameTape:
    """Per-lane latency-attribution CANDIDATE stream (analytic recorder).

    The event engine scatters blame as its heap advances each request's
    attribution cursor; the fast path has no loop, but the journey already
    computes every wait and every realized time advance in closed form.  So
    attribution reduces to: collect ``(cell, seconds, predicate)`` credit
    candidates along the pipeline, then scatter each into the pooled grid
    keyed by the lane's final coarse latency bin (``_run_one``).  Transit
    credits use the REALIZED float32 time advance (``(t + delay) - t``) and
    each server's service credit is the exact remainder ``departure -
    arrival - waits``, so a lane's credits telescope to its end-to-end
    latency to within a few float32 ulps (blame.py "Conservation
    precision").  Attribution consumes ZERO draws.
    """

    __slots__ = ("n", "cands")

    def __init__(self, n: int) -> None:
        self.n = n
        self.cands: list[tuple] = []

    def credit(self, cell, secs, pred) -> None:
        n = self.n
        self.cands.append((
            jnp.broadcast_to(jnp.asarray(cell, jnp.int32), (n,)),
            jnp.broadcast_to(jnp.asarray(secs, jnp.float32), (n,)),
            jnp.broadcast_to(jnp.asarray(pred, bool), (n,)),
        ))

    def credit_slice(self, cell, secs, pred, off: int, n_g: int) -> None:
        """Credit lanes on one generator's static slot slice (entry-chain
        hops); lanes outside the slice get a False predicate."""
        z = jnp.zeros(self.n, jnp.float32)
        self.credit(
            cell,
            z.at[off : off + n_g].set(jnp.broadcast_to(secs, (n_g,))),
            jnp.zeros(self.n, bool).at[off : off + n_g].set(pred),
        )


class FastEngine:
    """Batched scan engine for one eligible :class:`StaticPlan`."""

    def __init__(
        self,
        plan: StaticPlan,
        *,
        collect_gauges: bool = False,
        collect_clocks: bool = False,
        n_hist_bins: int = 1024,
        max_requests: int | None = None,
        relax_sweeps: int | None = None,
        relax_damping: float = 0.0,
        gauge_series_stride: int = 0,
        trace=None,
        blame: bool = False,
    ) -> None:
        """``gauge_series_stride``: with ``collect_gauges=False``, a stride
        k > 0 collects every gauge on a grid coarsened k-fold
        (period ``sample_period * k``) — the sweep-scale streaming series:
        device memory per scenario drops from ``n_samples`` rows to
        ``n_samples // k``, and the value at each coarse tick is exactly the
        fine-grid value at that time (the interval-endpoint scatter uses the
        same tick-inclusion rule on either grid).  Ignored when the exact
        grid is already being collected."""
        if not plan.fastpath_ok:
            raise_fence("fastpath.ineligible", detail=plan.fastpath_reason)
        if relax_sweeps is not None and relax_sweeps < 1:
            msg = f"relax_sweeps must be >= 1, got {relax_sweeps}"
            raise ValueError(msg)
        if gauge_series_stride < 0:
            msg = f"gauge_series_stride must be >= 0, got {gauge_series_stride}"
            raise ValueError(msg)
        self.plan = plan
        if trace is not None and not isinstance(trace, TraceConfig):
            trace = TraceConfig.model_validate(trace)
        #: flight recorder config — the rings are assembled analytically
        #: from per-lane journey state (no draws, no event loop); sweep
        #: plumbing reads this attribute to persist flight_* arrays
        self.trace = trace
        self.collect_gauges = collect_gauges
        self.collect_clocks = collect_clocks
        if collect_gauges:
            self._gauge_period = plan.sample_period
            self._gauge_samples = plan.n_samples
        elif gauge_series_stride:
            self._gauge_period = plan.sample_period * gauge_series_stride
            self._gauge_samples = plan.n_samples // gauge_series_stride
        else:
            self._gauge_period = plan.sample_period
            self._gauge_samples = 0
        self._collect_gauge_grid = collect_gauges or gauge_series_stride > 0
        self.gauge_series_stride = 0 if collect_gauges else gauge_series_stride
        self.n_hist_bins = n_hist_bins
        #: latency attribution plane (observability/blame.py).  False =
        #: statically pruned: unattributed programs stay bit-identical to
        #: pre-blame builds (pinned by tests/parity/test_flight_recorder.py).
        self.blame = bool(blame)
        self._bl_cells = (
            _blm.n_cells(plan.n_servers, plan.n_edges) if self.blame else 1
        )
        self._bl_bins = _blm.n_blame_bins(n_hist_bins) if self.blame else 1
        self._bl_stride = _blm.blame_stride(n_hist_bins)
        self.relax_sweeps = relax_sweeps
        self.relax_damping = relax_damping
        #: "zero" (default) or "visit1": start the multi-burst relaxation
        #: from the exact waits of a first-visits-only queue instead of 0
        #: (envelope experiments, docs/internals/fastpath.md §5)
        self.relax_init = "zero"
        if plan.n_generators > 1:
            # superposition (round 5c): every stream owns a static
            # contiguous slot slice sized by its own 6-sigma count bound;
            # an explicit max_requests rescales the slices proportionally
            # (the knob's contract is TOTAL capacity: the slices must sum
            # to exactly max_requests with every stream keeping >= 1 slot)
            base = [int(x) for x in plan.gen_slots]
            if max_requests:
                if max_requests < len(base):
                    msg = (
                        f"max_requests={max_requests} cannot cover "
                        f"{len(base)} generator streams (every stream "
                        "needs at least one slot)"
                    )
                    raise ValueError(msg)
                total = sum(base)
                shares = [b * max_requests / total for b in base]
                scaled = [max(1, int(s)) for s in shares]
                # settle the rounding residual largest-remainder-first so
                # the total lands exactly on max_requests without driving
                # any slice below 1 (max_requests >= n_generators above
                # guarantees enough >1 slices to absorb a deficit)
                by_frac = sorted(
                    range(len(base)),
                    key=lambda g: shares[g] - int(shares[g]),
                    reverse=True,
                )
                residual = max_requests - sum(scaled)
                i = 0
                while residual != 0:
                    g = by_frac[i % len(base)]
                    if residual > 0:
                        scaled[g] += 1
                        residual -= 1
                    elif scaled[g] > 1:
                        scaled[g] -= 1
                        residual += 1
                    i += 1
                base = scaled
            self.gen_n = base
            self.n = sum(base)
        else:
            self.gen_n = []
            self.n = max_requests or plan.max_requests
        # ---- resilience lowering (round 8 fence burn-down) ----
        # Static flags prune every fault/retry op out of unconfigured
        # plans' programs, keeping their draw streams bit-identical.
        self._has_srv_faults = bool(
            np.any(plan.fault_srv_down != 0) or np.any(plan.hz_srv_mask),
        )
        self._has_edge_faults = bool(
            np.any(plan.fault_edge_lat != 1.0)
            or np.any(plan.fault_edge_drop != 0.0)
            or np.any(plan.hz_edge_mask),
        )
        self._attempts = (
            max(int(plan.retry_max_attempts), 1) if plan.has_retry else 1
        )
        if plan.has_retry and self._attempts > 1:
            # lane blocks: block a holds attempt a+1 of logical request i
            # at lane a*n1 + i.  plan.max_requests is already amplified by
            # the attempt cap (_estimate_capacity), so n1 = n // A keeps
            # the logical 6-sigma class bound.
            self._n_logical = max(self.n // self._attempts, 1)
            self.n = self._n_logical * self._attempts
        else:
            self._n_logical = self.n
        self.n_windows = int(np.ceil(plan.horizon / plan.user_window))
        self.n_thr = int(np.ceil(plan.horizon)) or 1
        self.hist_lo, self.hist_scale = hist_constants(n_hist_bins)
        self._dists_present = sorted(set(plan.edge_dist.tolist()))
        self._spike_times = jnp.asarray(plan.spike_times)
        self._spike_values = jnp.asarray(plan.spike_values)
        self._compiled: dict = {}

    def _shares_entry_sort(self, s: int) -> bool:
        """Can server ``s`` reuse the shared entry-tier arrival sort?

        True when its core-queue order provably equals arrival order at
        plan-compile time: the server is entry-tier (nothing exits into
        it, so every request's ``t`` is final from routing), runs exactly
        one CPU burst with a uniform enqueue offset across endpoints, has
        no modeled RAM admission, and no stochastic pre-burst extras that
        would perturb the enqueue order.
        """
        plan = self.plan
        if s in {
            int(x)
            for x, k in zip(plan.exit_target, plan.exit_kind)
            if k == TARGET_SERVER
        }:
            return False
        nep = int(plan.n_endpoints[s])
        kb = int(plan.n_bursts[s, :nep].max()) if nep else 0
        ram_k = int(plan.ram_slots[s]) if len(plan.ram_slots) else 0
        if kb != 1 or ram_k > 0:
            return False
        if nep > 1:
            nb = plan.n_bursts[s, :nep]
            pre0 = plan.burst_pre_io[s, :nep, 0]
            if not (np.all(nb == nb[0]) and np.all(pre0 == pre0[0])):
                return False
        return not (
            plan.fp_cache_slot.size and np.any(plan.fp_cache_slot[s] >= 0)
        )

    # ------------------------------------------------------------------
    # draw helpers
    # ------------------------------------------------------------------

    def _delay(self, dist_id: int, mean, var, u, z):
        if dist_id == _D_UNIFORM:
            return u
        if dist_id == _D_EXPONENTIAL:
            return exponential_from_u(mean, u)
        if dist_id == _D_NORMAL:
            return truncated_normal(mean, var, z)
        if dist_id == _D_LOGNORMAL:
            return lognormal(mean, var, z)
        # unreachable: _fastpath_analysis rejects poisson-latency edges
        raise_fence("fastpath.poisson_edge")

    @staticmethod
    def _fused_drop_rescale(u, p):
        """(dropped, survivor latency uniform): one uniform settles both —
        u | u >= p is uniform on [p, 1), so the rescale is uniform [0, 1)
        and the latency law is unchanged; dropped lanes never consume
        their (negative) rescaled value."""
        return u < p, (u - p) / jnp.maximum(1.0 - p, _TINY)

    def _add_spike(self, delay, t_send, eidx):
        """Active-spike superposition at send time (static or per-lane
        edge index)."""
        idx = searchsorted_small(self._spike_times, t_send, "right") - 1
        return delay + self._spike_values[idx, eidx]

    def _edge_fault(self, eidx, t_send, ov: ScenarioOverrides):
        """(latency factor, dropout boost) active on an edge at send time —
        the event engine's ``_edge_fault`` on whole lane vectors.  Times
        AND value rows both ride the overrides: hand-authored timelines
        broadcast the plan table, chaos campaigns batch a sampled
        (S, M, NE) table per scenario.  ``eidx`` may be a static int or a
        per-lane index vector."""
        idx = jnp.maximum(
            searchsorted_small(
                jnp.asarray(ov.fault_edge_times), t_send, "right",
            )
            - 1,
            0,
        )
        return (
            jnp.asarray(ov.fault_edge_lat)[idx, eidx],
            jnp.asarray(ov.fault_edge_drop)[idx, eidx],
        )

    def _edge_hop(self, key, edge: int, t_send, ov: ScenarioOverrides, u=None):
        """(dropped, delay+spike) vectors for one static edge index.

        ONE uniform settles both dropout and latency (profiling: threefry
        draws dominate the post-sort chunk): ``u < p`` drops, and the
        survivor's latency uniform is the exact conditional rescale
        ``(u - p) / (1 - p)`` — u | u >= p is uniform on [p, 1), so the
        rescale is uniform on [0, 1) and the latency law is unchanged.
        Dropped lanes never consume their latency value.  ``u`` may be a
        caller-shared stream (disjoint request sets draw disjoint lanes).
        """
        dist_id = int(self.plan.edge_dist[edge])
        if u is None:
            u = draw_uniform(jax.random.fold_in(key, 0), t_send.shape)
        drop_p = ov.edge_dropout[edge]
        factor = None
        if self._has_edge_faults:
            # fault window at send time: multiply the latency draw, boost
            # the dropout probability (event engine's _sample_edge order:
            # factor before the spike superposition)
            factor, boost = self._edge_fault(edge, t_send, ov)
            drop_p = jnp.clip(drop_p + boost, 0.0, 1.0)
        dropped, u_lat = self._fused_drop_rescale(u, drop_p)
        z = (
            draw_normal(jax.random.fold_in(key, 2), t_send.shape)
            if dist_id in (_D_NORMAL, _D_LOGNORMAL)
            else 0.0
        )
        delay = self._delay(
            dist_id, ov.edge_mean[edge], ov.edge_var[edge], u_lat, z,
        )
        if factor is not None:
            delay = delay * factor
        if len(self.plan.spike_times) > 1:
            delay = self._add_spike(delay, t_send, edge)
        return dropped, delay

    def _edge_hop_dyn(self, key, eidx_arr, t_send, ov: ScenarioOverrides):
        """(dropped, delay+spike) for a PER-LANE edge index (the routed LB
        edge): one fused dropout+latency uniform, per-lane parameter
        gathers, dist dispatch over the dists present among LB edges."""
        plan = self.plan
        mean = ov.edge_mean[eidx_arr]
        var = ov.edge_var[eidx_arr]
        u = draw_uniform(jax.random.fold_in(key, 0), t_send.shape)
        drop_p = ov.edge_dropout[eidx_arr]
        factor = None
        if self._has_edge_faults:
            factor, boost = self._edge_fault(eidx_arr, t_send, ov)
            drop_p = jnp.clip(drop_p + boost, 0.0, 1.0)
        dropped, u_lat = self._fused_drop_rescale(u, drop_p)
        lb_dists = sorted(
            {int(plan.edge_dist[e]) for e in plan.lb_edge_index.tolist()},
        )
        if len(lb_dists) == 1:
            z = (
                draw_normal(jax.random.fold_in(key, 2), t_send.shape)
                if lb_dists[0] in (_D_NORMAL, _D_LOGNORMAL)
                else 0.0
            )
            delay = self._delay(lb_dists[0], mean, var, u_lat, z)
        else:
            dist = jnp.asarray(plan.edge_dist)[eidx_arr]
            z = (
                draw_normal(jax.random.fold_in(key, 2), t_send.shape)
                if {_D_NORMAL, _D_LOGNORMAL} & set(lb_dists)
                else 0.0
            )
            delay = jnp.zeros_like(t_send)
            for d in lb_dists:
                delay = jnp.where(
                    dist == d, self._delay(d, mean, var, u_lat, z), delay,
                )
        if factor is not None:
            delay = delay * factor
        if len(plan.spike_times) > 1:
            delay = self._add_spike(delay, t_send, eidx_arr)
        return dropped, delay

    # ------------------------------------------------------------------
    # arrivals
    # ------------------------------------------------------------------

    def _arrivals(self, key, ov: ScenarioOverrides, n: int | None = None):
        """(sim_times, valid, overflow) — simulation-clock arrival times.

        Single-stream plans produce one sorted vector; multi-generator
        plans concatenate per-stream constructions (each sorted on its own
        static slot slice — downstream consumers rank, they never assume
        global slot-order sortedness).  ``n`` overrides the single-stream
        slot count (retry plans spawn logical requests on the first lane
        block only)."""
        plan = self.plan
        if plan.n_generators > 1:
            um = jnp.asarray(ov.user_mean)  # (G,)
            rr = jnp.asarray(ov.req_rate)
            ts, alives = [], []
            overflow = jnp.int32(0)
            for g in range(plan.n_generators):
                t_g, v_g, of_g = self._arrivals_stream(
                    jax.random.fold_in(key, 101 + g),
                    um[g],
                    rr[g],
                    float(plan.gen_user_var[g]),
                    float(plan.gen_window[g]),
                    int(np.ceil(plan.horizon / float(plan.gen_window[g]))),
                    self.gen_n[g],
                )
                ts.append(t_g)
                alives.append(v_g)
                overflow = overflow + of_g
            return jnp.concatenate(ts), jnp.concatenate(alives), overflow
        return self._arrivals_stream(
            key,
            ov.user_mean,
            ov.req_rate,
            plan.user_var,
            plan.user_window,
            self.n_windows,
            self.n if n is None else n,
        )

    def _arrivals_stream(
        self, key, user_mean, req_rate, user_var, window_s, nw, n,
    ):
        """One stream's window-Poisson arrival construction (sorted)."""
        plan = self.plan
        window = jnp.float32(window_s)
        starts = jnp.arange(nw, dtype=jnp.float32) * window
        ends = jnp.minimum(starts + window, plan.horizon)
        lens = ends - starts

        if user_var < 0:
            users = jax.random.poisson(
                _as_threefry(jax.random.fold_in(key, 1)),
                jnp.maximum(user_mean, _TINY),
                (nw,),
            ).astype(jnp.float32)
        else:
            z = draw_normal(jax.random.fold_in(key, 1), (nw,))
            users = jnp.maximum(0.0, user_mean + user_var * z)
        lam = users * req_rate

        counts = jax.random.poisson(
            _as_threefry(jax.random.fold_in(key, 2)),
            jnp.maximum(lam * lens, _TINY),
        ).astype(jnp.int32)
        counts = jnp.where(lam > 0, counts, 0)
        offsets = jnp.cumsum(counts)
        total = jnp.minimum(offsets[-1], n)

        slot = jnp.arange(n, dtype=jnp.int32)
        valid = slot < total
        win = searchsorted_small(offsets, slot, "right")
        win = jnp.clip(win, 0, nw - 1)
        # SORTED uniforms per window without a sort (the profiler showed the
        # fast path is sort-dominated): K sorted uniforms are the normalized
        # partial sums of K+1 exponential gaps (the Poisson-process order
        # statistics construction).  One global cumsum + per-window boundary
        # gathers replace the 88k-key sort: S_i within window w is
        # cum[i] - cum[start_w - 1], and the denominator adds one extra gap
        # per window.  Distributionally identical to sorting iid uniforms.
        gaps = -jnp.log1p(-draw_uniform(jax.random.fold_in(key, 3), (n,)))
        cum = jnp.cumsum(gaps)
        prefix = jnp.concatenate([jnp.zeros(1, cum.dtype), cum])  # (n+1,)
        begin = jnp.concatenate([jnp.zeros(1, jnp.int32), offsets[:-1]])
        base = prefix[jnp.clip(begin, 0, n)]  # (nw,) cum before each window
        wsum = prefix[jnp.clip(offsets, 0, n)] - base
        extra = -jnp.log1p(
            -draw_uniform(jax.random.fold_in(key, 4), (nw,)),
        )
        denom = jnp.maximum(wsum + extra, _TINY)
        u = jnp.clip((cum - base[win]) / denom[win], 0.0, 1.0)
        sampler_t = jnp.where(valid, starts[win] + u * lens[win], INF)

        # residual dropped from the sim clock per window: boundary - last
        # arrival (full window length when empty)
        last = jnp.full(nw, -jnp.inf, jnp.float32)
        last = last.at[win].max(jnp.where(valid, sampler_t, -jnp.inf))
        last = jnp.maximum(last, starts)
        residual = jnp.where(lens > 0, ends - last, 0.0)
        cum_res = jnp.concatenate([jnp.zeros(1), jnp.cumsum(residual)])[:-1]
        sim_t = jnp.where(valid, sampler_t - cum_res[win], INF)
        overflow = offsets[-1] - total
        return sim_t, valid, overflow

    # ------------------------------------------------------------------
    # round robin with a mutating rotation (outage timelines)
    # ------------------------------------------------------------------

    def _advance_timeline(self, rot, length, ptr, t_arr):
        """Apply every outage mark with time <= ``t_arr`` to the rotation
        (pop on down, reinsert-at-tail on up — the event engines'
        discipline). Shared by the round-robin and least-connections scans."""
        plan = self.plan
        el = plan.n_lb_edges
        ntl = len(plan.timeline_times)
        if ntl == 0:
            return rot, length, ptr
        tl_times = jnp.asarray(plan.timeline_times)
        tl_down = jnp.asarray(plan.timeline_down)
        tl_slot = jnp.asarray(plan.timeline_slot)

        def tl_cond(c):
            _rot, _length, p = c
            return (p < ntl) & (tl_times[jnp.minimum(p, ntl - 1)] <= t_arr)

        def tl_body(c):
            rot_c, length_c, p = c
            idx = jnp.minimum(p, ntl - 1)
            s = tl_slot[idx]
            down = tl_down[idx] == 1
            act = s >= 0
            rot_c, length_c = rotation_remove(rot_c, length_c, s, act & down, el)
            rot_c, length_c = rotation_insert(rot_c, length_c, s, act & ~down, el)
            return rot_c, length_c, p + 1

        return jax.lax.while_loop(tl_cond, tl_body, (rot, length, ptr))

    def _routed_slots(self, t, alive):
        """(slot, routed) per request: scan arrivals in time order carrying
        the LB rotation, applying down/up timeline marks as time passes —
        the same pop / reinsert-at-tail discipline as the event engines."""
        plan = self.plan
        el = plan.n_lb_edges

        def step(carry, x):
            rot, length, ptr = carry
            t_arr, ok = x
            rot, length, ptr = self._advance_timeline(rot, length, ptr, t_arr)
            empty = length <= 0
            picked = jnp.where(ok & ~empty, rot[0], jnp.int32(-1))
            rot = rotation_advance(rot, length, ok & ~empty, el)
            return (rot, length, ptr), picked

        n = t.shape[0]
        rank = time_rank(t, alive)
        init = (jnp.arange(el, dtype=jnp.int32), jnp.int32(el), jnp.int32(0))
        _, picked_sorted = jax.lax.scan(
            step,
            init,
            (
                jnp.full(n, INF).at[rank].set(jnp.where(alive, t, INF)),
                jnp.zeros(n, bool).at[rank].set(alive),
            ),
        )
        picked = picked_sorted[rank]
        return picked, picked >= 0

    def _routed_slots_lc(self, t, alive, drop_s, delay_s):
        """Least-connections routing as a time-ordered scan.

        The event engines count *edge-transit* connections: +1 at a
        non-dropped send, -1 at delivery
        (`/root/reference/src/asyncflow/runtime/actors/edge.py:88-116`), and
        pick the first minimum in rotation order
        (`runtime/actors/routing/lb_algorithms.py:10-20`).  The scan carries,
        per LB slot, a ring of outstanding delivery times: the live count at
        an arrival is how many ring entries still lie in the future.  Ring
        capacity comes from the compiler's 6-sigma in-flight bound
        (``plan.lc_ring``); on the astronomically-rare overflow the earliest
        delivery is evicted (graceful degradation, not a drop).  Outage
        marks mutate the rotation exactly as in ``_routed_slots``.
        """
        plan = self.plan
        el = plan.n_lb_edges
        ring_b = max(plan.lc_ring, 1)
        deliver = t[:, None] + delay_s  # (n, EL) candidate delivery times

        def step(carry, x):
            rot, length, ptr, rings = carry
            t_arr, ok, drops_i, deliv_i = x
            rot, length, ptr = self._advance_timeline(rot, length, ptr, t_arr)

            # live in-flight count per slot, then first-min in rotation order
            conn = jnp.sum(rings > t_arr, axis=1).astype(jnp.int32)  # (EL,)
            pos = jnp.arange(el, dtype=jnp.int32)
            valid = pos < length
            order_key = jnp.where(valid, conn[rot] * el + pos, jnp.int32(2**30))
            best = jnp.argmin(order_key).astype(jnp.int32)
            empty = length <= 0
            picked_slot = rot[best]
            picked = jnp.where(ok & ~empty, picked_slot, jnp.int32(-1))

            # record the outstanding delivery unless the edge drops the send
            do_ins = ok & ~empty & ~drops_i[jnp.clip(picked_slot, 0, el - 1)]
            row = jnp.clip(picked_slot, 0, el - 1)
            j = jnp.argmin(rings[row]).astype(jnp.int32)
            new_val = jnp.where(do_ins, deliv_i[row], rings[row, j])
            rings = rings.at[row, j].set(new_val)
            return (rot, length, ptr, rings), picked

        n = t.shape[0]
        rank = time_rank(t, alive)
        init = (
            jnp.arange(el, dtype=jnp.int32),
            jnp.int32(el),
            jnp.int32(0),
            jnp.full((el, ring_b), -INF, jnp.float32),
        )
        _, picked_sorted = jax.lax.scan(
            step,
            init,
            (
                jnp.full(n, INF).at[rank].set(jnp.where(alive, t, INF)),
                jnp.zeros(n, bool).at[rank].set(alive),
                jnp.zeros((n, el), bool).at[rank].set(drop_s),
                jnp.full((n, el), -INF).at[rank].set(deliver),
            ),
        )
        picked = picked_sorted[rank]
        return picked, picked >= 0

    # ------------------------------------------------------------------
    # metric recording
    # ------------------------------------------------------------------

    def _bucket(self, t):
        return sample_bucket(t, self._gauge_period, self._gauge_samples)

    def _gauge_intervals(self, gauge, gidx, t0, t1, amount, on):
        """Scatter +amount at enter and -amount at leave times (masked)."""
        if not self._collect_gauge_grid:
            return gauge
        val = jnp.where(on, amount, 0.0)
        gauge = gauge.at[self._bucket(t0), gidx].add(val)
        return gauge.at[self._bucket(t1), gidx].add(-val)

    # ------------------------------------------------------------------
    # main
    # ------------------------------------------------------------------

    def _journey(
        self,
        key,
        ov: ScenarioOverrides,
        t,
        alive,
        gauge,
        gauge_means,
        *,
        record: bool = True,
        tape: _FlightTape | None = None,
        btape: _BlameTape | None = None,
    ):
        """One full pass of the post-arrival pipeline: entry chain ->
        routing -> server topo loop -> completion.

        ``t``/``alive`` are per-lane issue times and liveness (for retry
        plans, lane blocks of re-issue attempts).  Returns ``(finish,
        completed, fail_t, gauge, gauge_means, n_dropped, n_rejected,
        n_dark_lost)``
        where ``fail_t`` is the per-lane client-visible failure time (INF
        when the lane completed or was still in flight at the horizon) —
        entry-chain drops fail at the attempt's ISSUE time (the event
        engine walks the chain inside the spawn event), every other
        fail-fast site at its own event time.  ``record=False`` skips all
        gauge/counter accumulation: the retry driver's relaxation passes
        only need the outcome times.  ``tape`` collects flight-record
        candidates (code, node, record time, processing time, predicate) in
        per-lane event-processing order — the caller assembles the rings.
        ``btape`` collects latency-attribution credit candidates (cell,
        seconds, predicate) — the caller scatters them into the pooled
        blame grid keyed by each lane's final latency bin."""
        plan = self.plan
        n = t.shape[0]
        n_dropped = jnp.int32(0)
        n_rejected = jnp.int32(0)
        n_dark_lost = jnp.int32(0)
        fail_t = jnp.full(n, INF, jnp.float32)
        horizon = jnp.float32(plan.horizon)

        def span(a, b, on, amount=1.0):
            lo = jnp.minimum(a, horizon)
            hi = jnp.minimum(b, horizon)
            return jnp.sum(jnp.where(on, amount * jnp.maximum(hi - lo, 0.0), 0.0))

        # ---- entry chain ------------------------------------------------
        # Each stream walks ITS chain on its static slot slice; all streams
        # converge on the same entry node (compiler fence).  G == 1 is the
        # whole-array special case (fold constants preserved: 16 + j).
        if plan.n_generators > 1:
            chains = [
                plan.gen_entry_edges[g, : plan.gen_entry_len[g]].tolist()
                for g in range(plan.n_generators)
            ]
            sizes = self.gen_n
            stride = max(len(c) for c in chains)
            fold_site = lambda g, j: 1024 + stride * g + j  # noqa: E731
        else:
            chains = [plan.entry_edges.tolist()]
            sizes = [n]
            fold_site = lambda g, j: 16 + j  # noqa: E731
        off = 0
        t_parts, alive_parts, fail_parts = [], [], []
        for g, chain in enumerate(chains):
            n_g = sizes[g]
            t_g = t[off : off + n_g]
            alive_g = alive[off : off + n_g]
            t0_g = t_g  # attempt issue times (entry drops fail here)
            f_g = jnp.full(n_g, INF, jnp.float32)
            for j, eidx in enumerate(chain):
                # a send at t >= horizon never happens in the event engines
                # (events past the horizon don't fire): freeze silently
                alive_g = alive_g & (t_g < plan.horizon)
                dropped, delay = self._edge_hop(
                    jax.random.fold_in(key, fold_site(g, j)), eidx, t_g, ov,
                )
                ok = alive_g & ~dropped
                if record:
                    gauge = self._gauge_intervals(
                        gauge, eidx, t_g, t_g + delay, 1.0, ok,
                    )
                    gauge_means = gauge_means.at[eidx].add(
                        span(t_g, t_g + delay, ok),
                    )
                    n_dropped = n_dropped + jnp.sum(alive_g & dropped)
                if tape is not None:
                    # chain hops are walked inside the spawn event: record
                    # times advance hop by hop, processing time stays at the
                    # attempt's issue instant (event engine spawn branch)
                    tape.emit_slice(
                        FR_DROP, eidx, t_g, t0_g, alive_g & dropped, off, n_g,
                    )
                    tape.emit_slice(
                        FR_TRANSIT, eidx, t_g + delay, t0_g, ok, off, n_g,
                    )
                if btape is not None:
                    # credit the REALIZED float32 advance so a lane's
                    # credits telescope to its end-to-end latency exactly
                    btape.credit_slice(
                        (plan.n_servers + eidx) * _blm.N_PHASES
                        + _blm.PH_TRANSIT,
                        (t_g + delay) - t_g,
                        ok,
                        off,
                        n_g,
                    )
                f_g = jnp.where(alive_g & dropped, t0_g, f_g)
                t_g = jnp.where(ok, t_g + delay, t_g)
                alive_g = ok
            t_parts.append(t_g)
            alive_parts.append(alive_g)
            fail_parts.append(f_g)
            off += n_g
        t = t_parts[0] if len(t_parts) == 1 else jnp.concatenate(t_parts)
        alive = (
            alive_parts[0]
            if len(alive_parts) == 1
            else jnp.concatenate(alive_parts)
        )
        fail_t = (
            fail_parts[0]
            if len(fail_parts) == 1
            else jnp.concatenate(fail_parts)
        )

        # ---- routing ----------------------------------------------------
        alive = alive & (t < plan.horizon)
        srv = jnp.full(n, jnp.int32(max(plan.entry_target, 0)))
        if plan.n_lb_edges > 0:
            if tape is not None:
                tape.emit(FR_ARRIVE_LB, -1, t, t, alive)
            if plan.lb_algo == 1:
                # least connections needs every slot's CANDIDATE delivery
                # time for the in-flight rings, so outcomes are pre-drawn
                # per (request, slot) — distributionally identical to the
                # event engines' draw-after-pick
                drops = []
                delays = []
                for s_idx, eidx in enumerate(plan.lb_edge_index.tolist()):
                    dropped_c, delay_c = self._edge_hop(
                        jax.random.fold_in(key, 32 + s_idx), eidx, t, ov,
                    )
                    drops.append(dropped_c)
                    delays.append(delay_c)
                drop_s = jnp.stack(drops, axis=1)  # (n, EL)
                delay_s = jnp.stack(delays, axis=1)
                slot, routed = self._routed_slots_lc(t, alive, drop_s, delay_s)
                if tape is not None:
                    # no healthy target: dropped at the LB (node -1)
                    tape.emit(FR_DROP, -1, t, t, alive & ~routed)
                if record:
                    n_dropped = n_dropped + jnp.sum(alive & ~routed)
                fail_t = jnp.where(alive & ~routed, t, fail_t)
                alive = alive & routed
                slot = jnp.where(alive, slot, 0)
                lanes = jnp.arange(n)
                dropped = drop_s[lanes, slot]
                delay = delay_s[lanes, slot]
                eidx_arr = jnp.asarray(plan.lb_edge_index)[slot]
            else:
                # round robin picks its slot BEFORE any edge outcome is
                # needed, so one dynamic-edge draw replaces the per-slot
                # pre-draws (threefry streams dominate the post-sort chunk)
                if len(plan.timeline_times) == 0:
                    # fixed membership: round robin is a pure function of
                    # rank; dead lanes rank after every alive lane
                    # (sortutil), so the stable rank IS rank-among-alive
                    rank = time_rank(t, alive)
                    slot = jnp.where(alive, rank % plan.n_lb_edges, 0)
                else:
                    # outages mutate the rotation: scan LB arrivals in time
                    # order, interleaving the outage timeline (slot -1 = no
                    # healthy target, request dropped like the event engines)
                    slot, routed = self._routed_slots(t, alive)
                    if tape is not None:
                        tape.emit(FR_DROP, -1, t, t, alive & ~routed)
                    if record:
                        n_dropped = n_dropped + jnp.sum(alive & ~routed)
                    fail_t = jnp.where(alive & ~routed, t, fail_t)
                    alive = alive & routed
                    slot = jnp.where(alive, slot, 0)
                eidx_arr = jnp.asarray(plan.lb_edge_index)[slot]
                dropped, delay = self._edge_hop_dyn(
                    jax.random.fold_in(key, 32), eidx_arr, t, ov,
                )
            srv = jnp.asarray(plan.lb_target)[slot]
            ok = alive & ~dropped
            if tape is not None:
                tape.emit(FR_DROP, eidx_arr, t, t, alive & dropped)
                tape.emit(FR_TRANSIT, eidx_arr, t + delay, t, ok)
            if btape is not None:
                btape.credit(
                    (plan.n_servers + eidx_arr) * _blm.N_PHASES
                    + _blm.PH_TRANSIT,
                    (t + delay) - t,
                    ok,
                )
            if record:
                gauge = self._gauge_intervals(
                    gauge, eidx_arr, t, t + delay, 1.0, ok,
                )
                lo = jnp.minimum(t, horizon)
                hi = jnp.minimum(t + delay, horizon)
                gauge_means = gauge_means.at[eidx_arr].add(
                    jnp.where(ok, jnp.maximum(hi - lo, 0.0), 0.0),
                )
                n_dropped = n_dropped + jnp.sum(alive & dropped)
            fail_t = jnp.where(alive & dropped, t, fail_t)
            t = jnp.where(ok, t + delay, t)
            alive = ok

        # ---- servers in topological order -------------------------------
        finish = jnp.full(n, INF, jnp.float32)
        completed = jnp.zeros(n, bool)
        n_bursts_t = jnp.asarray(plan.n_bursts)
        burst_dur_t = jnp.asarray(plan.burst_dur)
        burst_pre_t = jnp.asarray(plan.burst_pre_io)
        post_io_t = jnp.asarray(plan.endpoint_post_io)
        endpoint_cum_t = jnp.asarray(plan.endpoint_cum)

        # ONE shared arrival-order sort for every entry-tier server whose
        # core-queue order provably equals arrival order (profiling showed
        # the fast path is sort-dominated: this folds an LB fan-out's
        # per-server argsorts into a single one).  Valid because each
        # request's t is final from routing until its own server processes
        # it, so the permutation's restriction to any one entry-tier
        # server's requests is its arrival order.
        shared_rank = (
            time_rank(t, alive)
            if any(self._shares_entry_sort(s) for s in plan.server_topo_order)
            else None
        )
        # one shared endpoint-pick stream and one shared exit-edge stream
        # when no server chains exist: each request then visits exactly one
        # server, so per-server masked consumers read DISJOINT lanes of the
        # same uniforms — fewer threefry streams, independence intact.
        # (Chained topologies revisit lanes and keep per-server draws.)
        chained = any(int(k) == TARGET_SERVER for k in plan.exit_kind)
        u_ep_shared = (
            None
            if chained
            else draw_uniform(jax.random.fold_in(key, 6), (n,))
        )
        u_exit_shared = (
            None
            if chained
            else draw_uniform(jax.random.fold_in(key, 7), (n,))
        )
        for s in plan.server_topo_order:
            mine = alive & (srv == s) & (t < plan.horizon)

            # dark fault windows: a server that is down at the request's
            # arrival hard-refuses it (event engine checks this BEFORE the
            # rate limit — `_srv_faulted` in engine.py).  Static gate per
            # server keeps unfaulted servers' programs untouched.
            if self._has_srv_faults and bool(
                np.any(np.asarray(plan.fault_srv_down)[:, s] != 0)
                or plan.hz_srv_mask[s],
            ):
                fidx = jnp.maximum(
                    searchsorted_small(
                        jnp.asarray(ov.fault_srv_times), t, "right",
                    )
                    - 1,
                    0,
                )
                dark = mine & (
                    jnp.asarray(ov.fault_srv_down)[fidx, s] == 1
                )
                if tape is not None:
                    tape.emit(FR_REJECT, s, t, t, dark)
                if record:
                    n_rejected = n_rejected + jnp.sum(dark)
                    n_dark_lost = n_dark_lost + jnp.sum(dark)
                fail_t = jnp.where(dark, t, fail_t)
                alive = alive & ~dark
                mine = mine & ~dark

            # token-bucket rate limit at arrival (reference milestone 5):
            # feed-forward, so one arrival-order scan settles it exactly
            rate_s = (
                float(plan.server_rate_limit[s])
                if len(plan.server_rate_limit)
                else -1.0
            )
            if rate_s >= 0:
                rank_rl = time_rank(t, mine)
                nn = t.shape[0]
                acc_sorted = _token_bucket_scan(
                    jnp.full(nn, INF).at[rank_rl].set(jnp.where(mine, t, INF)),
                    jnp.zeros(nn, bool).at[rank_rl].set(mine),
                    rate_s,
                    float(plan.server_rate_burst[s]),
                )
                accepted = acc_sorted[rank_rl]
                limited = mine & ~accepted
                if tape is not None:
                    tape.emit(FR_REJECT, s, t, t, limited)
                if record:
                    n_rejected = n_rejected + jnp.sum(limited)
                fail_t = jnp.where(limited, t, fail_t)
                alive = alive & ~limited
                mine = mine & accepted

            nep = int(plan.n_endpoints[s])
            u = (
                u_ep_shared
                if u_ep_shared is not None
                else draw_uniform(jax.random.fold_in(key, 64 + s), (n,))
            )
            ep = jnp.minimum(
                searchsorted_small(endpoint_cum_t[s], u, "right"),
                nep - 1,
            )
            ram = jnp.asarray(plan.endpoint_ram)[s, ep]
            post = post_io_t[s, ep]
            n_cores = int(plan.server_cores[s])

            # stochastic cache segments: per-request miss draws add
            # (miss - hit) extras to the burst pre-IO slot or trailing IO
            # the segment occupies (compiler: _fastpath_lowering)
            cmax = int(plan.fp_cache_slot.shape[2]) if plan.fp_cache_slot.size else 0
            server_has_cache = cmax > 0 and bool(
                np.any(np.asarray(plan.fp_cache_slot[s]) != CACHE_UNUSED),
            )
            trail_extra = jnp.zeros(n, jnp.float32)
            trail_extra_post_db = jnp.zeros(n, jnp.float32)
            cache_extra_r = None
            cache_slot_r = None
            if server_has_cache:
                u_c = draw_uniform(
                    jax.random.fold_in(key, 160 + s), (n, cmax),
                )
                cache_slot_r = jnp.asarray(plan.fp_cache_slot)[s, ep]  # (n, cmax)
                missed = u_c < jnp.asarray(plan.fp_cache_miss_prob)[s, ep]
                cache_extra_r = jnp.where(
                    missed, jnp.asarray(plan.fp_cache_extra)[s, ep], 0.0,
                )
                trail_extra = jnp.sum(
                    jnp.where(cache_slot_r == CACHE_PRE_DB, cache_extra_r, 0.0),
                    axis=1,
                )
                trail_extra_post_db = jnp.sum(
                    jnp.where(cache_slot_r == CACHE_POST_DB, cache_extra_r, 0.0),
                    axis=1,
                )
                post = post + trail_extra + trail_extra_post_db
            # static per-server visit count: max CPU bursts over its endpoints
            kb = int(plan.n_bursts[s, :nep].max()) if nep else 0
            # RAM admission tier (see compiler): k > 0 models a FIFO
            # admission queue with k concurrency slots; <= 0 never queues
            ram_k = int(plan.ram_slots[s]) if len(plan.ram_slots) else 0
            W_ram = jnp.zeros(n, jnp.float32)
            # per-lane queue waits at THIS server (blame attribution; dead
            # code without a blame tape — XLA prunes the unused arrays)
            bl_cpu = jnp.zeros(n, jnp.float32)
            bl_db = jnp.zeros(n, jnp.float32)

            cap_s = (
                int(plan.server_queue_cap[s])
                if len(plan.server_queue_cap)
                else -1
            )
            qto_s = (
                float(plan.server_queue_timeout[s])
                if len(plan.server_queue_timeout)
                else -1.0
            )
            conn_s = (
                int(plan.server_conn_cap[s])
                if len(plan.server_conn_cap)
                else -1
            )
            controlled = cap_s >= 0 or qto_s >= 0

            if tape is not None and conn_s < 0:
                # socket-capacity servers defer this: their pre-admission
                # refusals must precede FR_ARRIVE_SRV (event arrival order)
                tape.emit(FR_ARRIVE_SRV, s, t, t, mine)
            if conn_s >= 0:
                # socket capacity (+ any cap/deadline): joint arrival-order
                # pass — compiler guarantees kb <= 1, no RAM tier, no
                # binding pool, uniform burst pre-IO, no pre-burst cache
                # extras (`_socket_cap_scan_reason`)
                assert kb <= 1 and ram_k <= 0
                nb = n_bursts_t[s, ep]
                is_b = nb >= 1
                pre0 = jnp.where(is_b, burst_pre_t[s, ep][:, 0], 0.0)
                dur0 = jnp.where(is_b, burst_dur_t[s, ep][:, 0], 0.0)
                arr_c = jnp.where(mine, t, INF)
                rank_c = time_rank(arr_c, mine)
                wait_s_, ref_s, shed_s, aband_s = _socket_station_scan(
                    jnp.full(n, INF).at[rank_c].set(arr_c),
                    jnp.full(n, INF).at[rank_c].set(
                        jnp.where(mine, t + pre0, INF),
                    ),
                    jnp.zeros(n).at[rank_c].set(jnp.where(mine, dur0, 0.0)),
                    jnp.zeros(n).at[rank_c].set(jnp.where(mine, post, 0.0)),
                    jnp.zeros(n, bool).at[rank_c].set(mine & is_b),
                    jnp.zeros(n, bool).at[rank_c].set(mine),
                    n_cores,
                    conn_s,
                    cap_s,
                    qto_s,
                )
                refused = mine & ref_s[rank_c]
                shed = mine & shed_s[rank_c]
                abandoned = mine & aband_s[rank_c]
                W_c = jnp.where(
                    mine & is_b & ~refused & ~shed, wait_s_[rank_c], 0.0,
                )
                rejected = refused | shed | abandoned
                if tape is not None:
                    enq0 = t + pre0
                    qwait = (
                        mine & is_b & ~refused & ~shed & (W_c > 0)
                    )
                    tape.emit(FR_REJECT, s, t, t, refused)
                    tape.emit(FR_ARRIVE_SRV, s, t, t, mine & ~refused)
                    tape.emit(FR_REJECT, s, enq0, enq0, shed)
                    tape.emit(FR_WAIT_CPU, s, enq0, enq0, qwait)
                    tape.emit(FR_RUN, s, enq0 + W_c, enq0 + W_c, qwait)
                    tape.emit(
                        FR_REJECT, s, enq0 + W_c, enq0 + W_c, abandoned,
                    )
                if record:
                    n_rejected = n_rejected + jnp.sum(rejected)
                # refused fail at arrival, shed at enqueue, abandons after
                # waiting out the dequeue deadline (event: _timeout_branch)
                fail_t = jnp.where(refused, t, fail_t)
                fail_t = jnp.where(shed, t + pre0, fail_t)
                fail_t = jnp.where(abandoned, t + pre0 + W_c, fail_t)
                alive = alive & ~rejected
                served = mine & ~rejected
                # gauge shapes shared with the other branches; refused
                # never enqueue, shed enqueue with zero wait
                part = mine & is_b & ~refused
                E = (t + pre0)[:, None]
                W = jnp.where(shed, 0.0, W_c)[:, None]
                pre = pre0[:, None]
                validb = part[:, None]
                dep = t + pre0 + W_c + dur0 + post
                # non-binding RAM held from arrival until the shed/abandon
                # instant (the served interval is added by the shared
                # gauge_ram block below, which only sees `mine`=served)
                rej_end = jnp.where(shed, t + pre0, t + pre0 + W_c)
                rej_ram = (shed | abandoned) & (ram > 0)
                if record:
                    gauge = self._gauge_intervals(
                        gauge, plan.gauge_ram(s), t, rej_end, ram, rej_ram,
                    )
                    gauge_means = gauge_means.at[plan.gauge_ram(s)].add(
                        span(t, rej_end, rej_ram, amount=ram),
                    )
                mine = served
                bl_cpu = jnp.where(mine, W_c, 0.0)
            elif kb == 0 and ram_k <= 0:
                # pure-IO server: no queues, departure is deterministic
                dep = t + post
            elif controlled:
                # ready-queue cap / dequeue deadline: exact joint KW+ring
                # arrival-order scan (compiler guarantees kb == 1, no RAM)
                assert kb == 1 and ram_k <= 0
                nb = n_bursts_t[s, ep]
                pre0 = jnp.where(nb >= 1, burst_pre_t[s, ep][:, 0], 0.0)
                if server_has_cache:
                    # pre-burst stochastic cache extras shift this request's
                    # enqueue time; the scan orders by enqueue, so adding
                    # them here keeps the pass exact (same per-slot fold as
                    # the relaxation branch's pre_extra)
                    pre0 = pre0 + jnp.where(
                        nb >= 1,
                        jnp.sum(
                            jnp.where(cache_slot_r == 0, cache_extra_r, 0.0),
                            axis=1,
                        ),
                        0.0,
                    )
                dur0 = jnp.where(nb >= 1, burst_dur_t[s, ep][:, 0], 0.0)
                part = mine & (nb >= 1)  # io-only endpoints skip the queue
                e_c = jnp.where(part, t + pre0, INF)
                rank_c = time_rank(e_c, part)
                w_s_, shed_s, aband_s = _controlled_station_scan(
                    jnp.full(n, INF).at[rank_c].set(e_c),
                    jnp.zeros(n).at[rank_c].set(jnp.where(part, dur0, 0.0)),
                    jnp.zeros(n, bool).at[rank_c].set(part),
                    n_cores,
                    cap_s,
                    qto_s,
                )
                W_c = jnp.where(part, w_s_[rank_c], 0.0)
                shed = part & shed_s[rank_c]
                abandoned = part & aband_s[rank_c]
                rejected = shed | abandoned
                if tape is not None:
                    enq0 = t + pre0
                    qwait = part & ~shed & (W_c > 0)
                    tape.emit(FR_REJECT, s, enq0, enq0, shed)
                    tape.emit(FR_WAIT_CPU, s, enq0, enq0, qwait)
                    tape.emit(FR_RUN, s, enq0 + W_c, enq0 + W_c, qwait)
                    tape.emit(
                        FR_REJECT, s, enq0 + W_c, enq0 + W_c, abandoned,
                    )
                if record:
                    n_rejected = n_rejected + jnp.sum(rejected)
                # shed never enters the ready queue (fails at enqueue, which
                # includes pre-burst cache extras); abandons wait full W_c
                fail_t = jnp.where(shed, t + pre0, fail_t)
                fail_t = jnp.where(abandoned, t + pre0 + W_c, fail_t)
                alive = alive & ~rejected
                served = mine & ~rejected
                # gauge shapes shared with the other branches: enqueue,
                # wait, pre-IO per (single) visit; shed never enters the
                # ready queue (W forced 0), abandons wait their full W
                E = (t + pre0)[:, None]
                W = jnp.where(shed, 0.0, W_c)[:, None]
                pre = pre0[:, None]
                validb = part[:, None]
                dep = t + pre0 + W_c + dur0 + post
                mine = served
                bl_cpu = jnp.where(mine, W_c, 0.0)
            elif ram_k > 0:
                # Binding RAM (eligibility guarantees at most one burst and a
                # uniform need): admission + core settled jointly in one
                # exact arrival-order pass.
                nb = n_bursts_t[s, ep]
                pre0 = jnp.where(nb >= 1, burst_pre_t[s, ep][:, 0], 0.0)
                dur0 = jnp.where(nb >= 1, burst_dur_t[s, ep][:, 0], 0.0)
                arr = jnp.where(mine, t, INF)
                rank_r = time_rank(arr, mine)
                w_ram_s, w_cpu_s, _dep = _ram_core_scan(
                    jnp.full(n, INF).at[rank_r].set(arr),
                    jnp.zeros(n).at[rank_r].set(pre0),
                    jnp.zeros(n).at[rank_r].set(jnp.where(mine, dur0, 0.0)),
                    jnp.zeros(n).at[rank_r].set(post),
                    jnp.zeros(n, bool).at[rank_r].set(mine),
                    ram_k,
                    n_cores,
                )
                W_ram = w_ram_s[rank_r]
                w_cpu = w_cpu_s[rank_r]
                W_ram = jnp.where(mine, W_ram, 0.0)
                w_cpu = jnp.where(mine & (dur0 > 0), w_cpu, 0.0)
                if tape is not None:
                    # blocked-acquire pattern: WAIT at enqueue + RUN at the
                    # grant, nothing when the resource was free (the event
                    # engine's _resume_branch / _cpu_handoff discipline)
                    rwait = mine & (W_ram > 0)
                    tape.emit(FR_WAIT_RAM, s, t, t, rwait)
                    tape.emit(FR_RUN, s, t + W_ram, t + W_ram, rwait)
                    enq0 = t + W_ram + pre0
                    qwait = mine & (w_cpu > 0)
                    tape.emit(FR_WAIT_CPU, s, enq0, enq0, qwait)
                    tape.emit(FR_RUN, s, enq0 + w_cpu, enq0 + w_cpu, qwait)
                E = (t + W_ram + pre0)[:, None]
                W = w_cpu[:, None]
                pre = pre0[:, None]
                validb = mine[:, None] & (jnp.int32(0) < nb[:, None])
                dep = t + W_ram + pre0 + w_cpu + dur0 + post
                bl_cpu = w_cpu
            else:
                nb = n_bursts_t[s, ep]  # (n,)
                ks = jnp.arange(kb, dtype=jnp.int32)
                validb = mine[:, None] & (ks[None, :] < nb[:, None])  # (n, kb)
                dur = jnp.where(validb, burst_dur_t[s, ep][:, :kb], 0.0)
                pre = jnp.where(validb, burst_pre_t[s, ep][:, :kb], 0.0)
                if server_has_cache:
                    # per-request cache-miss extras on the pre-IO slots
                    pre_extra = jnp.sum(
                        jnp.where(
                            cache_slot_r[:, :, None] == ks[None, None, :],
                            cache_extra_r[:, :, None],
                            0.0,
                        ),
                        axis=1,
                    )
                    pre = pre + jnp.where(validb, pre_extra, 0.0)
                pre_cum = jnp.cumsum(pre, axis=1)

                use_shared = shared_rank is not None and self._shares_entry_sort(s)

                def queue_waits(waits):
                    """One relaxation sweep of the core queue: enqueue times
                    from the current waits, then FIFO waits of the merged
                    visit stream."""
                    busy_prev = jnp.cumsum(waits + dur, axis=1) - (waits + dur)
                    enq = t[:, None] + pre_cum + busy_prev
                    flat_e = jnp.where(validb, enq, INF).reshape(-1)
                    flat_d = dur.reshape(-1)
                    flat_v = validb.reshape(-1)
                    # entry-tier single-burst servers reuse the shared
                    # arrival rank (kb == 1, so the flat stream IS the
                    # request axis); masked lanes interleave harmlessly.
                    # Sorting = scatter by rank, un-sorting = gather by rank
                    # (sortutil.time_rank is the argsort's inverse).
                    rank = (
                        shared_rank if use_shared else time_rank(flat_e, flat_v)
                    )
                    e_s = jnp.full(n * kb, INF).at[rank].set(flat_e)
                    d_s = jnp.zeros(n * kb).at[rank].set(flat_d)
                    v_s = jnp.zeros(n * kb, bool).at[rank].set(flat_v)
                    if n_cores == 1:
                        w_s = _lindley_waits(e_s, d_s, v_s)
                    else:
                        w_s = _kw_waits(e_s, d_s, v_s, n_cores)
                    new = w_s[rank].reshape(n, kb)
                    return jnp.where(validb & (dur > 0), new, 0.0)

                # Visit k's enqueue time depends on earlier visits' waits, so
                # relax to the fixed point; one sweep is exact when kb == 1
                # (enqueue times don't depend on waits).  Multi-burst sweeps
                # converge by ~2*kb+2; at convergence the result is within
                # the oracle's own ensemble noise (+/-2-3% p95 at rho 0.6).
                W = jnp.zeros((n, kb), jnp.float32)
                if self.relax_init == "visit1":
                    # exact waits of the first-visit-only queue: a lower
                    # bound in truth's neighborhood (experimental)
                    first = validb & (ks[None, :] == 0)
                    e1 = jnp.where(first, t[:, None] + pre_cum, INF).reshape(-1)
                    d1 = jnp.where(first, dur, 0.0).reshape(-1)
                    v1 = first.reshape(-1)
                    r1 = time_rank(e1, v1)
                    e1_s = jnp.full(n * kb, INF).at[r1].set(e1)
                    d1_s = jnp.zeros(n * kb).at[r1].set(d1)
                    v1_s = jnp.zeros(n * kb, bool).at[r1].set(v1)
                    if n_cores == 1:
                        w1 = _lindley_waits(e1_s, d1_s, v1_s)
                    else:
                        w1 = _kw_waits(e1_s, d1_s, v1_s, n_cores)
                    W = w1[r1].reshape(n, kb)
                    W = jnp.where(first & (dur > 0), W, 0.0)
                n_sweeps = (
                    self.relax_sweeps
                    if self.relax_sweeps is not None
                    else (1 if kb == 1 else 2 * kb + 2)
                )
                alpha = self.relax_damping
                for _ in range(n_sweeps):
                    W = (
                        queue_waits(W)
                        if alpha == 0.0
                        else (1.0 - alpha) * queue_waits(W) + alpha * W
                    )

                # enqueue times consistent with the final waits (gauges)
                busy_prev = jnp.cumsum(W + dur, axis=1) - (W + dur)
                E = t[:, None] + pre_cum + busy_prev
                busy = jnp.sum(jnp.where(validb, pre + W + dur, 0.0), axis=1)
                dep = t + busy + post
                bl_cpu = jnp.sum(jnp.where(validb, W, 0.0), axis=1)
                if tape is not None:
                    for k in range(kb):
                        qwait = validb[:, k] & (W[:, k] > 0)
                        tape.emit(
                            FR_WAIT_CPU, s, E[:, k], E[:, k], qwait,
                        )
                        tape.emit(
                            FR_RUN,
                            s,
                            E[:, k] + W[:, k],
                            E[:, k] + W[:, k],
                            qwait,
                        )

            # gauges: one ready-wait and one pre-IO interval per visit (the
            # ram_k > 0 branch exposes its single visit in the same shapes;
            # kb == 0 means no visits and the loop is empty)
            for k in range(
                (min(kb, 1) if ram_k > 0 else kb) if record else 0
            ):
                vb = validb[:, k]
                gauge = self._gauge_intervals(
                    gauge,
                    plan.gauge_ready(s),
                    E[:, k],
                    E[:, k] + W[:, k],
                    1.0,
                    vb & (W[:, k] > 0),
                )
                gauge_means = gauge_means.at[plan.gauge_ready(s)].add(
                    span(E[:, k], E[:, k] + W[:, k], vb),
                )
                gauge = self._gauge_intervals(
                    gauge,
                    plan.gauge_io(s),
                    E[:, k] - pre[:, k],
                    E[:, k],
                    1.0,
                    vb & (pre[:, k] > 0),
                )
                gauge_means = gauge_means.at[plan.gauge_io(s)].add(
                    span(E[:, k] - pre[:, k], E[:, k], vb),
                )

            # modeled DB connection pool: one extra FIFO G/G/K station per
            # server.  Every endpoint's (single) query follows its last CPU
            # burst (compiler: _fastpath_lowering), so the station's FIFO
            # wait only delays the departure — no feedback into the core
            # queue, exact at any utilization.  The merged per-server
            # stream is ordered by station-enqueue time; K = 1 rides the
            # log-depth Lindley scan, K > 1 the Kiefer-Wolfowitz vector.
            trail_start = dep - post
            pool_k = int(plan.server_db_pool[s])
            server_has_db = pool_k > 0 and bool(
                np.any(np.asarray(plan.fp_db_dur[s]) > 0),
            )
            if server_has_db:
                db_dur_r = jnp.where(mine, jnp.asarray(plan.fp_db_dur)[s, ep], 0.0)
                db_pre_r = jnp.asarray(plan.fp_db_pre)[s, ep] + trail_extra
                use_db = mine & (db_dur_r > 0)
                enq_db = jnp.where(use_db, trail_start + db_pre_r, INF)
                rank_db = time_rank(enq_db, use_db)
                e_db = jnp.full(n, INF).at[rank_db].set(enq_db)
                d_db = jnp.zeros(n).at[rank_db].set(db_dur_r)
                v_db = jnp.zeros(n, bool).at[rank_db].set(use_db)
                if pool_k == 1:
                    w_s = _lindley_waits(e_db, d_db, v_db)
                else:
                    w_s = _kw_waits(e_db, d_db, v_db, pool_k)
                w_db = w_s[rank_db]
                if tape is not None:
                    dwait = use_db & (w_db > 0)
                    tape.emit(FR_WAIT_DB, s, enq_db, enq_db, dwait)
                    tape.emit(
                        FR_RUN, s, enq_db + w_db, enq_db + w_db, dwait,
                    )
                dep = dep + jnp.where(use_db, w_db, 0.0)
                bl_db = jnp.where(use_db, w_db, 0.0)

            # trailing IO sleep (including any DB pool wait: the reference
            # parks connection waiters in the event loop, counted by the
            # io-sleep gauge) and RAM residency (admission to departure)
            if record:
                gauge = self._gauge_intervals(
                    gauge,
                    plan.gauge_io(s),
                    trail_start,
                    dep,
                    1.0,
                    mine & (dep > trail_start),
                )
                gauge_means = gauge_means.at[plan.gauge_io(s)].add(
                    span(trail_start, dep, mine & (dep > trail_start)),
                )
                gauge = self._gauge_intervals(
                    gauge,
                    plan.gauge_ram(s),
                    t + W_ram,
                    dep,
                    ram,
                    mine & (ram > 0),
                )
                gauge_means = gauge_means.at[plan.gauge_ram(s)].add(
                    span(t + W_ram, dep, mine, amount=ram),
                )

            if btape is not None:
                # queue waits to their phases, then SERVICE as the exact
                # remainder of the server's occupancy — the lane's credits
                # at this server telescope to ``dep - t`` by construction
                base_c = s * _blm.N_PHASES
                btape.credit(
                    base_c + _blm.PH_Q_CPU, bl_cpu, mine & (bl_cpu > 0),
                )
                if ram_k > 0:
                    btape.credit(
                        base_c + _blm.PH_Q_RAM, W_ram, mine & (W_ram > 0),
                    )
                if server_has_db:
                    btape.credit(
                        base_c + _blm.PH_Q_DB, bl_db, mine & (bl_db > 0),
                    )
                svc = jnp.maximum((dep - t) - bl_cpu - W_ram - bl_db, 0.0)
                btape.credit(base_c + _blm.PH_SERVICE, svc, mine)

            # exit edge: the send only happens while the clock is running
            sendable = mine & (dep < plan.horizon)
            eidx = int(plan.exit_edge[s])
            dropped, delay = self._edge_hop(
                jax.random.fold_in(key, 128 + s), eidx, dep, ov,
                u=u_exit_shared,
            )
            ok = sendable & ~dropped
            if tape is not None:
                tape.emit(FR_DROP, eidx, dep, dep, sendable & dropped)
                tape.emit(FR_TRANSIT, eidx, dep + delay, dep, ok)
            if btape is not None:
                btape.credit(
                    (plan.n_servers + eidx) * _blm.N_PHASES
                    + _blm.PH_TRANSIT,
                    (dep + delay) - dep,
                    ok,
                )
            if record:
                gauge = self._gauge_intervals(
                    gauge, eidx, dep, dep + delay, 1.0, ok,
                )
                gauge_means = gauge_means.at[eidx].add(
                    span(dep, dep + delay, ok),
                )
                n_dropped = n_dropped + jnp.sum(sendable & dropped)
            fail_t = jnp.where(sendable & dropped, dep, fail_t)
            if plan.exit_kind[s] == TARGET_SERVER:
                nxt = int(plan.exit_target[s])
                t = jnp.where(ok, dep + delay, t)
                srv = jnp.where(ok, nxt, srv)
                alive = jnp.where(mine, ok, alive)
            else:  # client: completion
                fin = dep + delay
                done = ok & (fin < plan.horizon)
                if tape is not None:
                    # retry plans defer completion to the client-arrival
                    # event (proc = delivery); non-retry exits record it
                    # with the departure (event engine exit flow)
                    tape.emit(
                        FR_COMPLETE,
                        -1,
                        fin,
                        fin if plan.has_retry else dep,
                        done,
                    )
                finish = jnp.where(done, fin, finish)
                completed = completed | done
                alive = jnp.where(mine, False, alive)

        return (
            finish,
            completed,
            fail_t,
            gauge,
            gauge_means,
            n_dropped,
            n_rejected,
            n_dark_lost,
        )

    def _run_one(self, key, ov: ScenarioOverrides) -> FastState:
        plan = self.plan
        n = self.n
        A = self._attempts
        n1 = self._n_logical
        n_gauge_rows = (
            self._gauge_samples + 2 if self._collect_gauge_grid else 1
        )
        n_gauges = plan.n_gauges if self._collect_gauge_grid else 1
        gauge = jnp.zeros((n_gauge_rows, n_gauges), jnp.float32)
        # exact time-integrals of every gauge (divided by the horizon at the
        # end); an interval [a, b) contributes its horizon-clipped length
        gauge_means = jnp.zeros(plan.n_gauges, jnp.float32)
        horizon = jnp.float32(plan.horizon)
        # flight-recorder placeholders: statically pruned to (1, 1)/(1,)
        # when untraced (same discipline as the clock placeholder below) so
        # untraced programs stay bit-identical to pre-trace builds
        fr_ev = jnp.zeros((1, 1), jnp.int32)
        fr_node = jnp.zeros((1, 1), jnp.int32)
        fr_t = jnp.zeros((1, 1), jnp.float32)
        fr_n = jnp.zeros(1, jnp.int32)
        trace_on = self.trace is not None

        if not plan.has_retry:
            # single journey — the program (and its draw stream) is
            # bit-identical to pre-resilience builds for unfaulted plans
            t, alive, overflow = self._arrivals(jax.random.fold_in(key, 0), ov)
            n_generated = jnp.sum(alive)
            tape = None
            btape = _BlameTape(n) if self.blame else None
            if trace_on:
                tape = _FlightTape(n)
                if plan.n_generators > 1:
                    gen_node = jnp.concatenate([
                        jnp.full(ng, g, jnp.int32)
                        for g, ng in enumerate(self.gen_n)
                    ])
                else:
                    gen_node = 0
                tape.emit(FR_SPAWN, gen_node, t, t, alive)
            (
                finish,
                completed,
                _fail_t,
                gauge,
                gauge_means,
                n_dropped,
                n_rejected,
                n_dark_lost,
            ) = self._journey(
                key, ov, t, alive, gauge, gauge_means, tape=tape, btape=btape,
            )
            if trace_on:
                K = int(self.trace.sample_requests)
                slots = int(self.trace.event_slots)
                if plan.n_generators > 1:
                    # traced rows are the first K spawned = arrival-time
                    # order; superposed streams need the explicit rank
                    # (single streams are already time-sorted)
                    rank = time_rank(t, alive)
                    lane_of_rank = (
                        jnp.zeros(n, jnp.int32)
                        .at[rank]
                        .set(jnp.arange(n, dtype=jnp.int32))
                    )
                    lanes = lane_of_rank[: min(K, n)]
                else:
                    lanes = jnp.arange(min(K, n), dtype=jnp.int32)
                fr_ev, fr_node, fr_t, fr_n = _flight_rings(
                    tape.cands, K, slots, lanes=lanes,
                )
            success = completed
            lat_start = t
            # batched-traced zeros: every FastState leaf must carry the
            # vmap batch axis
            zero = jnp.int32(0) * n_generated
            n_timed_out = zero
            n_retries = zero
            n_budget_exhausted = zero
            att_hist = jnp.zeros(self._attempts, jnp.int32) + zero
        else:
            # ---- client deadlines + capped-backoff retries --------------
            # Lane blocks: block a (lanes [a*n1, (a+1)*n1)) holds attempt
            # a+1 of logical request i at lane a*n1 + i.  Logical requests
            # spawn on block 0 only; a failed/timed-out attempt in block a
            # re-issues into block a+1 at its failure time plus backoff.
            # The journey is re-run A times over the full lane array so
            # retry-storm contention feeds back into every block's queue
            # waits (same relaxation discipline as the multi-burst core
            # queue); draws are fixed per (lane, site), so the passes
            # converge deterministically.  Only the last pass records.
            t1, v1, overflow = self._arrivals(
                jax.random.fold_in(key, 0), ov, n=n1,
            )
            n_generated = jnp.sum(v1)
            T = jnp.where(v1, t1, INF)
            if A > 1:
                T = jnp.concatenate(
                    [T, jnp.full(n - n1, INF, jnp.float32)],
                )
            # per-target-block backoff delays (event `_backoff_delay`:
            # min(cap, base * mult**(attempt-1)) times the jitter factor);
            # the jitter draw is per lane at a reserved fold site, clear of
            # every journey site (2048 + block)
            boff = []
            for a in range(1, A):
                d = min(
                    float(plan.retry_backoff_cap),
                    float(plan.retry_backoff_base)
                    * float(plan.retry_backoff_mult) ** float(a - 1),
                )
                if plan.retry_jitter > 0:
                    u = draw_uniform(
                        jax.random.fold_in(key, 2048 + a), (n1,),
                    )
                    d = d * (
                        1.0 + float(plan.retry_jitter) * (2.0 * u - 1.0)
                    )
                else:
                    d = jnp.full(n1, d, jnp.float32)
                boff.append(d)
            boff_all = jnp.concatenate(boff) if boff else None
            rt = jnp.asarray(ov.retry_timeout, jnp.float32)
            blk = jnp.arange(n, dtype=jnp.int32) // n1
            can_retry = blk < (A - 1)
            cap_b = float(plan.retry_budget_tokens)
            tape = None
            btape = None
            for p in range(A):
                last = p == A - 1
                if trace_on and last:
                    tape = _FlightTape(n)
                if self.blame and last:
                    # only the recording pass attributes: the relaxation
                    # passes' outcomes are superseded lane by lane
                    btape = _BlameTape(n)
                issued = T < INF
                (
                    finish,
                    completed,
                    fail_t,
                    gauge,
                    gauge_means,
                    n_dropped,
                    n_rejected,
                    n_dark_lost,
                ) = self._journey(
                    key, ov, T, issued, gauge, gauge_means, record=last,
                    tape=tape, btape=btape,
                )
                # per-attempt resolution: the client notices completion at
                # C, failure at fail_t, or its deadline at D — deadline
                # wins ties (event engine: D <= min(C, F)), and deadlines
                # at or past the horizon never fire
                C = jnp.where(completed, finish, INF)
                D = T + rt
                timed = (
                    issued
                    & (D <= jnp.minimum(C, fail_t))
                    & (D < horizon)
                )
                failed = issued & ~timed & (fail_t < INF)
                R = jnp.where(timed, D, fail_t)  # retry-want time
                want = (timed | failed) & can_retry
                if cap_b >= 0:
                    # one global token-bucket pass over the wants in time
                    # order — the event engines' lazily-refilled budget
                    # bucket advances its clock on every want, denials
                    # included, exactly like the arrival rate limiter
                    wt = jnp.where(want, R, INF)
                    rank_b = time_rank(wt, want)
                    acc = _token_bucket_scan(
                        jnp.full(n, INF).at[rank_b].set(wt),
                        jnp.zeros(n, bool).at[rank_b].set(want),
                        float(plan.retry_budget_refill),
                        cap_b,
                    )
                    grant = want & acc[rank_b]
                else:
                    grant = want
                if not last:
                    # re-issue: block a's granted failure parks block a+1's
                    # lane at R + backoff; parks at or past the horizon
                    # never fire (the token is still consumed — event
                    # engines grant before parking)
                    tn = R[: n - n1] + boff_all
                    T = jnp.concatenate(
                        [
                            T[:n1],
                            jnp.where(
                                grant[: n - n1] & (tn < horizon), tn, INF,
                            ),
                        ],
                    )
            success = issued & ~timed & completed
            lat_start = T
            denied = want & ~grant
            give_up = denied | ((timed | failed) & ~can_retry)
            ended = success | give_up
            n_timed_out = jnp.sum(timed)
            n_retries = jnp.sum(grant)
            n_budget_exhausted = jnp.sum(denied)
            # attempts used per ENDED logical request: the block index IS
            # attempt-1 (event `_record_attempts`); in-flight-at-horizon
            # attempts and granted-but-never-fired re-issues record nothing
            att_hist = jnp.zeros(A, jnp.int32).at[
                jnp.where(ended, blk, A)
            ].add(1, mode="drop")
            if trace_on:
                # ring assembly: a logical request's record is the attempt-
                # major concat of its lane blocks' candidates; each block
                # contributes [SPAWN, journey..., TIMEOUT, RETRY/ABANDON].
                # Orphan masking mirrors the event engine's req_fr detach:
                # a timed-out attempt's events processed at or after its
                # deadline are invisible (the deadline event, pushed at
                # spawn, wins same-instant ties by heap sequence).
                K = int(self.trace.sample_requests)
                slots = int(self.trace.event_slots)
                D = T + rt
                attempt = blk + 1  # node = failed attempt number (1-based)
                cands = [
                    (
                        FR_SPAWN,
                        jnp.zeros(n, jnp.int32),
                        T,
                        T,
                        issued,
                    ),
                ]
                cands += [
                    (code, node, rec, proc, pred & ~(timed & (proc >= D)))
                    for code, node, rec, proc, pred in tape.cands
                ]
                cands += [
                    (FR_TIMEOUT, attempt, D, D, timed),
                    (FR_RETRY, attempt, R, R, grant),
                    (FR_ABANDON, attempt, R, R, (timed | failed) & ~grant),
                ]
                fr_ev, fr_node, fr_t, fr_n = _flight_rings(
                    cands, K, slots, blocks=(A, n1),
                )

        # ---- reductions --------------------------------------------------
        latency = jnp.where(success, finish - lat_start, 0.0)
        lbin = latency_bin(latency, self.hist_lo, self.hist_scale, self.n_hist_bins)
        one = success.astype(jnp.int32)
        hist = jnp.zeros(self.n_hist_bins, jnp.int32).at[
            jnp.where(success, lbin, self.n_hist_bins)
        ].add(1, mode="drop")
        tbin = jnp.clip(jnp.ceil(finish).astype(jnp.int32) - 1, 0, self.n_thr - 1)
        thr = jnp.zeros(self.n_thr, jnp.int32).at[
            jnp.where(success, tbin, self.n_thr)
        ].add(1, mode="drop")

        if self.collect_clocks:
            # clocks in arrival order, compacted to the front
            idx = jnp.where(success, jnp.cumsum(one) - 1, self.n)
            clock = jnp.zeros((self.n, 2), jnp.float32)
            clock = clock.at[idx, 0].set(lat_start, mode="drop")
            clock = clock.at[idx, 1].set(finish, mode="drop")
            clock_n = jnp.sum(one)
        else:
            clock = jnp.zeros((1, 2), jnp.float32)
            clock_n = jnp.sum(one)

        # latency attribution: scatter every credit candidate into the
        # pooled (cell, coarse latency bin) grid — non-successful lanes
        # target the out-of-range bin and drop, which also erases earlier
        # attempts of retried requests (attempt-scoped latency) and
        # orphaned completions past a fired client deadline
        bl_grid = jnp.zeros((1, 1), jnp.float32)
        bl_lat = jnp.zeros(1, jnp.float32)
        bl_store = jnp.zeros((1, 1), jnp.float32)
        if self.blame:
            nbb = self._bl_bins
            cb = jnp.clip(lbin // self._bl_stride, 0, nbb - 1)
            target = jnp.where(success, cb, nbb)
            bl_grid = jnp.zeros((self._bl_cells, nbb), jnp.float32)
            for cell_a, secs, pred in btape.cands:
                bl_grid = bl_grid.at[cell_a, target].add(
                    jnp.where(pred, secs, 0.0), mode="drop",
                )
            bl_lat = (
                jnp.zeros(nbb, jnp.float32)
                .at[target]
                .add(latency, mode="drop")
            )
            if self.collect_clocks:
                # per-request rows compacted in clock order (the
                # conservation property test's witness)
                rows = jnp.zeros((self.n, self._bl_cells), jnp.float32)
                lanes_r = jnp.arange(self.n, dtype=jnp.int32)
                for cell_a, secs, pred in btape.cands:
                    rows = rows.at[lanes_r, cell_a].add(
                        jnp.where(pred & success, secs, 0.0),
                    )
                bl_store = (
                    jnp.zeros_like(rows).at[idx].set(rows, mode="drop")
                )

        return FastState(
            hist=hist,
            lat_count=jnp.sum(one),
            lat_sum=jnp.sum(latency),
            lat_sumsq=jnp.sum(latency * latency),
            lat_min=jnp.min(jnp.where(success, latency, INF)),
            lat_max=jnp.max(jnp.where(success, latency, 0.0)),
            thr=thr,
            gauge=gauge,
            clock=clock,
            clock_n=clock_n,
            n_generated=n_generated,
            n_dropped=n_dropped,
            n_overflow=overflow,
            gauge_means=gauge_means / horizon,
            n_rejected=n_rejected,
            n_dark_lost=n_dark_lost,
            n_timed_out=n_timed_out,
            n_retries=n_retries,
            n_budget_exhausted=n_budget_exhausted,
            att_hist=att_hist,
            fr_ev=fr_ev,
            fr_node=fr_node,
            fr_t=fr_t,
            fr_n=fr_n,
            bl_grid=bl_grid,
            bl_lat=bl_lat,
            bl_store=bl_store,
        )

    def run_batch(
        self,
        keys: jnp.ndarray,
        overrides: ScenarioOverrides | None = None,
        *,
        antithetic: bool = False,
    ) -> FastState:
        """Run |keys| scenarios as one vmapped kernel.

        ``antithetic``: trace/run the reflected-draw program variant (every
        uniform u -> 1-u, every normal z -> -z); pairing a batch with the
        SAME keys run un-reflected gives antithetic couples for variance
        reduction (docs/guides/mc-inference.md).  Off by default —
        bit-identical streams to builds without the hook.
        """
        _base_ov = base_overrides(self.plan)
        ov = (
            fill_overrides(overrides, _base_ov)
            if overrides is not None
            else _base_ov
        )
        axes = ScenarioOverrides(
            *[
                0 if jnp.asarray(o).ndim > jnp.asarray(b).ndim else None
                for o, b in zip(ov, _base_ov)
            ],
        )
        sig = (tuple(axes), antithetic)
        # hold the trace flag across the CALL, not just the first trace:
        # a shape-driven retrace inside a cached jit must re-see it
        with antithetic_trace() if antithetic else contextlib.nullcontext():
            if sig not in self._compiled:
                self._compiled[sig] = instrument_jit(
                    jax.jit(jax.vmap(self._run_one, in_axes=(0, axes))),
                    engine="fast",
                    variant="vmap",
                    n=self.n,
                )
            return self._compiled[sig](keys, ov)

    def scanned_fn(self):
        """The scanned sweep program: ``lax.scan`` over (blocks, inner, ...)
        leading axes of (keys, per-scenario overrides), vmapping
        :meth:`_run_one` across each block.  Single source for execution
        (:meth:`run_batch_scanned`) and for the compile-scaling
        measurement/CI gate (``asyncflow_tpu.utils.program_size``) — both
        must see the SAME program (docs/internals/compile-pathology.md).
        """
        axes = ScenarioOverrides(*([0] * len(ScenarioOverrides._fields)))
        vm = jax.vmap(self._run_one, in_axes=(0, axes))

        def scanned(kb, ob):
            def body(_, xs):
                k, o = xs
                return None, vm(k, o)

            _, out = jax.lax.scan(body, None, (kb, ob))
            return out

        return scanned

    def scanned_inputs(
        self,
        keys: jnp.ndarray,
        overrides: ScenarioOverrides | None = None,
        *,
        inner: int = 16,
        total: int | None = None,
    ) -> tuple[jnp.ndarray, ScenarioOverrides, int, int]:
        """Shape (keys, overrides) into the scanned program's inputs.

        Returns ``(keys_b, ov_b, s, t)``: keys reshaped to (blocks, inner,
        2), every override field materialized to a (blocks, inner, ...)
        batch (scalar-per-sweep fields broadcast, short sweeps edge-padded
        to ``total``), plus the realized (requested, padded) sizes.  Single
        source for execution (:meth:`run_batch_scanned`) and the
        compile-scaling gate (``asyncflow_tpu.utils.program_size``) — the
        gate must trace the SAME program production compiles.
        """
        _base_ov = base_overrides(self.plan)
        ov = (
            fill_overrides(overrides, _base_ov)
            if overrides is not None
            else _base_ov
        )
        s = keys.shape[0]
        t = total or s
        t = max(t, s)
        t += (-t) % inner
        blocks = t // inner

        base = base_overrides(self.plan)

        def batched(field, ref):
            arr = jnp.asarray(field, jnp.float32)
            ref_nd = jnp.asarray(ref).ndim
            if arr.ndim == ref_nd:  # scalar-per-sweep -> broadcast
                arr = jnp.broadcast_to(arr, (s, *arr.shape))
            if s < t:
                pad_width = [(0, t - s)] + [(0, 0)] * (arr.ndim - 1)
                arr = jnp.pad(arr, pad_width, mode="edge")
            return arr.reshape((blocks, inner, *arr.shape[1:]))

        ov_b = ScenarioOverrides(*[batched(o, b) for o, b in zip(ov, base)])
        if s < t:
            pad_width = [(0, t - s)] + [(0, 0)] * (keys.ndim - 1)
            keys = jnp.pad(keys, pad_width, mode="edge")
        keys_b = keys.reshape((blocks, inner, *keys.shape[1:]))
        return keys_b, ov_b, s, t

    def run_batch_scanned(
        self,
        keys: jnp.ndarray,
        overrides: ScenarioOverrides | None = None,
        *,
        inner: int = 16,
        total: int | None = None,
        antithetic: bool = False,
    ) -> FastState:
        """Run |keys| scenarios as a ``lax.scan`` over blocks of ``inner``
        vmapped scenarios inside ONE compiled program.

        Rationale (measured on the tunneled v5e worker): XLA-TPU compile
        time of the vmapped scan program grows pathologically with the
        batch dimension (~2 min at S=16, unfinished after 20 min at S=128),
        while the *execution* of an S=16 block is milliseconds-cheap.  An
        in-program sequential loop keeps compile cost at the S=16 point and
        amortizes the per-dispatch host<->device round trip (~1 s through
        the tunnel) over arbitrarily many scenarios.

        ``total`` fixes the compiled sweep size: any ``keys`` shorter than
        ``total`` is padded (padded rows are simulated and discarded), so
        every call reuses one executable regardless of tail-chunk size.
        """
        keys_b, ov_b, s, t = self.scanned_inputs(
            keys, overrides, inner=inner, total=total,
        )
        blocks = t // inner
        sig = ("scan", inner, blocks, antithetic)
        with antithetic_trace() if antithetic else contextlib.nullcontext():
            if sig not in self._compiled:
                self._compiled[sig] = instrument_jit(
                    jax.jit(self.scanned_fn()),
                    engine="fast",
                    variant="scan",
                    inner=inner,
                    blocks=blocks,
                    n=self.n,
                )
            out = self._compiled[sig](keys_b, ov_b)
        return jax.tree_util.tree_map(
            lambda a: a.reshape((t, *a.shape[2:]))[:s], out,
        )
