"""Scan fast path: closed-form vectorized simulation for eligible plans.

For the common scenario shape (endpoints that are one merged CPU burst + one
IO sleep, provably non-binding RAM, round-robin LB — see
``_fastpath_analysis`` in the compiler), the per-scenario discrete-event loop
collapses into pure array code:

1. **Arrivals.**  Within each user-sampling window the reference's gap chain
   is exactly a Poisson process restarted at the boundary
   (`/root/reference/src/asyncflow/samplers/poisson_poisson.py:56-82`): draw
   per-window counts ``K_w ~ Poisson(lam_w * len_w)``, place arrivals as
   sorted uniforms, and subtract each window's dropped residual
   (boundary - last arrival) to recover *simulation* timestamps, which only
   advance by emitted gaps.
2. **Edges.**  Dropout/latency/spike draws are embarrassingly parallel.
3. **Round robin** with fixed membership is a deterministic function of
   LB-arrival *rank* (sort by arrival time, assign ``rank % n_edges``); with
   outage windows, a ``lax.scan`` over time-ordered arrivals carries the
   rotation and applies down/up marks with the event engines' pop /
   reinsert-at-tail discipline.
4. **Each server is a G/G/c FIFO queue on the CPU burst** (the IO sleep holds
   no core): single-core waits follow the Lindley recursion
   ``W_k = max(0, W_{k-1} + S_{k-1} - (A_k - A_{k-1}))`` — evaluated in
   log-depth with ``lax.associative_scan`` in max-plus form — and multi-core
   waits use the Kiefer-Wolfowitz workload-vector scan.  IO-only requests
   bypass the core (their own wait is zero) but do not disturb the recursion
   (their service term is zero).
5. Chained servers (app -> DB) are processed in exit-DAG topological order.

Everything is (N,) array work per scenario, vmapped over the batch: the
whole Monte-Carlo sweep becomes sorts + scans + elementwise math — exactly
what the TPU's vector units and XLA's fusion want.  Gauge time series are
reconstructed from [enter, leave) interval endpoints exactly like the event
engine, so metric output is identical in shape and semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from asyncflow_tpu.compiler.plan import (
    SEG_CPU,
    SEG_IO,
    TARGET_SERVER,
    StaticPlan,
)
from asyncflow_tpu.engines.jaxsim.params import INF, ScenarioOverrides, base_overrides
from asyncflow_tpu.engines.jaxsim.rotation import (
    rotation_advance,
    rotation_insert,
    rotation_remove,
)
from asyncflow_tpu.engines.jaxsim.sampling import (
    D_EXPONENTIAL as _D_EXPONENTIAL,
    D_LOGNORMAL as _D_LOGNORMAL,
    D_NORMAL as _D_NORMAL,
    D_UNIFORM as _D_UNIFORM,
    TINY as _TINY,
    exponential_from_u,
    hist_constants,
    latency_bin,
    lognormal,
    sample_bucket,
    truncated_normal,
)


class FastState(NamedTuple):
    """Metric outputs of one scenario (duck-compatible with EngineState)."""

    hist: jnp.ndarray
    lat_count: jnp.ndarray
    lat_sum: jnp.ndarray
    lat_sumsq: jnp.ndarray
    lat_min: jnp.ndarray
    lat_max: jnp.ndarray
    thr: jnp.ndarray
    gauge: jnp.ndarray
    clock: jnp.ndarray
    clock_n: jnp.ndarray
    n_generated: jnp.ndarray
    n_dropped: jnp.ndarray
    n_overflow: jnp.ndarray
    #: (n_gauges,) exact time-average of every gauge over the horizon —
    #: cheap per-scenario what-if statistics even in histogram-only sweeps
    gauge_means: jnp.ndarray


def _kw_waits(
    arrivals: jnp.ndarray,
    service: jnp.ndarray,
    valid,
    cores: int,
) -> jnp.ndarray:
    """FIFO G/G/c waiting times via the Kiefer-Wolfowitz workload vector.

    Carry the sorted per-core residual-work vector ``w``; for each customer:
    age it by the inter-arrival gap, wait on the least-loaded core, add the
    service there, re-sort.  Sequential in the number of requests (a
    ``lax.scan``) but the carried state is just ``cores`` floats per lane.
    """
    inter = jnp.diff(arrivals, prepend=arrivals[:1])
    inter = jnp.where(jnp.isfinite(inter), inter, 0.0)

    def step(w, x):
        gap, svc, ok = x
        w = jnp.maximum(w - gap, 0.0)
        wait = w[0]
        busy = jnp.sort(w.at[0].add(svc))
        w = jnp.where(ok, busy, w)
        return w, jnp.where(ok, wait, 0.0)

    _, waits = jax.lax.scan(
        step,
        jnp.zeros(cores, jnp.float32),
        (inter, jnp.where(valid, service, 0.0), valid),
    )
    return waits


def _lindley_waits(arrivals: jnp.ndarray, service: jnp.ndarray, valid) -> jnp.ndarray:
    """FIFO G/G/1 waiting times for time-sorted ``arrivals`` via max-plus scan.

    Invalid (padding) entries must carry ``arrivals=+inf, service=0``; they
    compose as the identity and produce waits that are never used.
    """
    inter = jnp.diff(arrivals, prepend=arrivals[:1])
    d = jnp.concatenate([jnp.array([-INF]), service[:-1] - inter[1:]])
    # element k is f_k(x) = max(b_k, x + a_k); W_k = F_k(0).
    # Padding sorts to the end (arrivals=inf), so d is only consumed where
    # valid; invalid entries compose as the identity.
    a = jnp.where(valid, d, 0.0)
    b = jnp.where(valid, 0.0, -INF)

    def compose(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 + a2, jnp.maximum(b2, b1 + a2)

    ca, cb = jax.lax.associative_scan(compose, (a, b))
    return jnp.maximum(0.0, jnp.maximum(cb, ca))


class FastEngine:
    """Batched scan engine for one eligible :class:`StaticPlan`."""

    def __init__(
        self,
        plan: StaticPlan,
        *,
        collect_gauges: bool = False,
        collect_clocks: bool = False,
        n_hist_bins: int = 1024,
        max_requests: int | None = None,
    ) -> None:
        if not plan.fastpath_ok:
            msg = f"plan not eligible for the fast path: {plan.fastpath_reason}"
            raise ValueError(msg)
        self.plan = plan
        self.collect_gauges = collect_gauges
        self.collect_clocks = collect_clocks
        self.n_hist_bins = n_hist_bins
        self.n = max_requests or plan.max_requests
        self.n_windows = int(np.ceil(plan.horizon / plan.user_window))
        self.n_thr = int(np.ceil(plan.horizon)) or 1
        self.hist_lo, self.hist_scale = hist_constants(n_hist_bins)
        self._dists_present = sorted(set(plan.edge_dist.tolist()))
        self._spike_times = jnp.asarray(plan.spike_times)
        self._spike_values = jnp.asarray(plan.spike_values)
        self._compiled: dict = {}

    # ------------------------------------------------------------------
    # draw helpers
    # ------------------------------------------------------------------

    def _delay(self, dist_id: int, mean, var, u, z):
        if dist_id == _D_UNIFORM:
            return u
        if dist_id == _D_EXPONENTIAL:
            return exponential_from_u(mean, u)
        if dist_id == _D_NORMAL:
            return truncated_normal(mean, var, z)
        if dist_id == _D_LOGNORMAL:
            return lognormal(mean, var, z)
        # unreachable: _fastpath_analysis rejects poisson-latency edges
        msg = "poisson edge latency is not supported on the fast path"
        raise NotImplementedError(msg)

    def _edge_hop(self, key, edge: int, t_send, ov: ScenarioOverrides):
        """(dropped, delay+spike) vectors for one static edge index."""
        dist_id = int(self.plan.edge_dist[edge])
        u_drop = jax.random.uniform(jax.random.fold_in(key, 0), t_send.shape)
        u = jax.random.uniform(jax.random.fold_in(key, 1), t_send.shape)
        z = (
            jax.random.normal(jax.random.fold_in(key, 2), t_send.shape)
            if dist_id in (_D_NORMAL, _D_LOGNORMAL)
            else 0.0
        )
        delay = self._delay(dist_id, ov.edge_mean[edge], ov.edge_var[edge], u, z)
        if len(self.plan.spike_times) > 1:
            idx = (
                jnp.searchsorted(self._spike_times, t_send, side="right").astype(
                    jnp.int32,
                )
                - 1
            )
            delay = delay + self._spike_values[idx, edge]
        return u_drop < ov.edge_dropout[edge], delay

    # ------------------------------------------------------------------
    # arrivals
    # ------------------------------------------------------------------

    def _arrivals(self, key, ov: ScenarioOverrides):
        """(sim_times, valid) — simulation-clock arrival timestamps, sorted."""
        plan = self.plan
        nw, n = self.n_windows, self.n
        window = jnp.float32(plan.user_window)
        starts = jnp.arange(nw, dtype=jnp.float32) * window
        ends = jnp.minimum(starts + window, plan.horizon)
        lens = ends - starts

        if plan.user_var < 0:
            users = jax.random.poisson(
                jax.random.fold_in(key, 1),
                jnp.maximum(ov.user_mean, _TINY),
                (nw,),
            ).astype(jnp.float32)
        else:
            z = jax.random.normal(jax.random.fold_in(key, 1), (nw,))
            users = jnp.maximum(0.0, ov.user_mean + plan.user_var * z)
        lam = users * ov.req_rate

        counts = jax.random.poisson(
            jax.random.fold_in(key, 2),
            jnp.maximum(lam * lens, _TINY),
        ).astype(jnp.int32)
        counts = jnp.where(lam > 0, counts, 0)
        offsets = jnp.cumsum(counts)
        total = jnp.minimum(offsets[-1], n)

        slot = jnp.arange(n, dtype=jnp.int32)
        valid = slot < total
        win = jnp.searchsorted(offsets, slot, side="right").astype(jnp.int32)
        win = jnp.clip(win, 0, nw - 1)
        u = jax.random.uniform(jax.random.fold_in(key, 3), (n,))
        sampler_t = jnp.where(valid, starts[win] + u * lens[win], INF)
        # windows occupy disjoint time ranges and slots are blocked by window,
        # so the global sort preserves each sorted position's window index
        sampler_t = jnp.sort(sampler_t)

        # residual dropped from the sim clock per window: boundary - last
        # arrival (full window length when empty)
        last = jnp.full(nw, -jnp.inf, jnp.float32)
        last = last.at[win].max(jnp.where(valid, sampler_t, -jnp.inf))
        last = jnp.maximum(last, starts)
        residual = jnp.where(lens > 0, ends - last, 0.0)
        cum_res = jnp.concatenate([jnp.zeros(1), jnp.cumsum(residual)])[:-1]
        sim_t = jnp.where(valid, sampler_t - cum_res[win], INF)
        overflow = offsets[-1] - total
        return sim_t, valid, overflow

    # ------------------------------------------------------------------
    # round robin with a mutating rotation (outage timelines)
    # ------------------------------------------------------------------

    def _routed_slots(self, t, alive):
        """(slot, routed) per request: scan arrivals in time order carrying
        the LB rotation, applying down/up timeline marks as time passes —
        the same pop / reinsert-at-tail discipline as the event engines."""
        plan = self.plan
        el = plan.n_lb_edges
        ntl = len(plan.timeline_times)
        tl_times = jnp.asarray(plan.timeline_times)
        tl_down = jnp.asarray(plan.timeline_down)
        tl_slot = jnp.asarray(plan.timeline_slot)

        def step(carry, x):
            rot, length, ptr = carry
            t_arr, ok = x

            def tl_cond(c):
                _rot, _length, p = c
                return (p < ntl) & (tl_times[jnp.minimum(p, ntl - 1)] <= t_arr)

            def tl_body(c):
                rot_c, length_c, p = c
                idx = jnp.minimum(p, ntl - 1)
                s = tl_slot[idx]
                down = tl_down[idx] == 1
                act = s >= 0
                rot_c, length_c = rotation_remove(rot_c, length_c, s, act & down, el)
                rot_c, length_c = rotation_insert(rot_c, length_c, s, act & ~down, el)
                return rot_c, length_c, p + 1

            rot, length, ptr = jax.lax.while_loop(
                tl_cond,
                tl_body,
                (rot, length, ptr),
            )
            empty = length <= 0
            picked = jnp.where(ok & ~empty, rot[0], jnp.int32(-1))
            rot = rotation_advance(rot, length, ok & ~empty, el)
            return (rot, length, ptr), picked

        order = jnp.argsort(jnp.where(alive, t, INF))
        init = (jnp.arange(el, dtype=jnp.int32), jnp.int32(el), jnp.int32(0))
        _, picked_sorted = jax.lax.scan(
            step,
            init,
            (jnp.where(alive, t, INF)[order], alive[order]),
        )
        picked = jnp.zeros(t.shape[0], jnp.int32).at[order].set(picked_sorted)
        return picked, picked >= 0

    # ------------------------------------------------------------------
    # metric recording
    # ------------------------------------------------------------------

    def _bucket(self, t):
        return sample_bucket(t, self.plan.sample_period, self.plan.n_samples)

    def _gauge_intervals(self, gauge, gidx, t0, t1, amount, on):
        """Scatter +amount at enter and -amount at leave times (masked)."""
        if not self.collect_gauges:
            return gauge
        val = jnp.where(on, amount, 0.0)
        gauge = gauge.at[self._bucket(t0), gidx].add(val)
        return gauge.at[self._bucket(t1), gidx].add(-val)

    # ------------------------------------------------------------------
    # main
    # ------------------------------------------------------------------

    def _run_one(self, key, ov: ScenarioOverrides) -> FastState:
        plan = self.plan
        n = self.n
        n_gauge_rows = plan.n_samples + 2 if self.collect_gauges else 1
        n_gauges = plan.n_gauges if self.collect_gauges else 1
        gauge = jnp.zeros((n_gauge_rows, n_gauges), jnp.float32)

        t, alive, overflow = self._arrivals(jax.random.fold_in(key, 0), ov)
        start = t
        n_generated = jnp.sum(alive)
        n_dropped = jnp.int32(0)

        # exact time-integrals of every gauge (divided by the horizon at the
        # end); an interval [a, b) contributes its horizon-clipped length
        gauge_means = jnp.zeros(plan.n_gauges, jnp.float32)
        horizon = jnp.float32(plan.horizon)

        def span(a, b, on, amount=1.0):
            lo = jnp.minimum(a, horizon)
            hi = jnp.minimum(b, horizon)
            return jnp.sum(jnp.where(on, amount * jnp.maximum(hi - lo, 0.0), 0.0))

        # ---- entry chain ------------------------------------------------
        for j, eidx in enumerate(plan.entry_edges.tolist()):
            # a send at t >= horizon never happens in the event engines
            # (events past the horizon don't fire): freeze silently
            alive = alive & (t < plan.horizon)
            dropped, delay = self._edge_hop(
                jax.random.fold_in(key, 16 + j), eidx, t, ov,
            )
            ok = alive & ~dropped
            gauge = self._gauge_intervals(gauge, eidx, t, t + delay, 1.0, ok)
            gauge_means = gauge_means.at[eidx].add(span(t, t + delay, ok))
            n_dropped = n_dropped + jnp.sum(alive & dropped)
            t = jnp.where(ok, t + delay, t)
            alive = ok

        # ---- routing ----------------------------------------------------
        alive = alive & (t < plan.horizon)
        srv = jnp.full(n, jnp.int32(max(plan.entry_target, 0)))
        if plan.n_lb_edges > 0:
            if len(plan.timeline_times) == 0:
                # fixed membership: round robin is a pure function of rank
                order = jnp.argsort(jnp.where(alive, t, INF))
                rank_sorted = jnp.cumsum(alive[order].astype(jnp.int32)) - 1
                rank = jnp.zeros(n, jnp.int32).at[order].set(rank_sorted)
                slot = jnp.where(alive, rank % plan.n_lb_edges, 0)
            else:
                # outages mutate the rotation: scan LB arrivals in time
                # order, interleaving the outage timeline (slot -1 = no
                # healthy target, request dropped like the event engines)
                slot, routed = self._routed_slots(t, alive)
                n_dropped = n_dropped + jnp.sum(alive & ~routed)
                alive = alive & routed
                slot = jnp.where(alive, slot, 0)
            srv = jnp.asarray(plan.lb_target)[slot]
            # per-request edge draws: one pass per LB slot (static, small)
            new_t = t
            new_alive = alive
            for s_idx, eidx in enumerate(plan.lb_edge_index.tolist()):
                mine = alive & (slot == s_idx)
                dropped, delay = self._edge_hop(
                    jax.random.fold_in(key, 32 + s_idx), eidx, t, ov,
                )
                ok = mine & ~dropped
                gauge = self._gauge_intervals(gauge, eidx, t, t + delay, 1.0, ok)
                gauge_means = gauge_means.at[eidx].add(span(t, t + delay, ok))
                n_dropped = n_dropped + jnp.sum(mine & dropped)
                new_t = jnp.where(ok, t + delay, new_t)
                new_alive = jnp.where(mine, ok, new_alive)
            t, alive = new_t, new_alive

        # ---- servers in topological order -------------------------------
        finish = jnp.full(n, INF, jnp.float32)
        completed = jnp.zeros(n, bool)
        seg_kind = jnp.asarray(plan.seg_kind)
        seg_dur = jnp.asarray(plan.seg_dur)
        for s in plan.server_topo_order:
            mine = alive & (srv == s) & (t < plan.horizon)
            nep = int(plan.n_endpoints[s])
            u = jax.random.uniform(jax.random.fold_in(key, 64 + s), (n,))
            ep = jnp.minimum((u * nep).astype(jnp.int32), nep - 1)
            # per-endpoint cpu/io durations of the compiled segments
            k0 = seg_kind[s, ep, 0]
            d0 = seg_dur[s, ep, 0]
            k1 = seg_kind[s, ep, 1] if plan.max_segments > 1 else jnp.zeros(n, jnp.int32)
            d1 = seg_dur[s, ep, 1] if plan.max_segments > 1 else jnp.zeros(n)
            cpu = jnp.where(k0 == SEG_CPU, d0, 0.0)
            io = jnp.where(k0 == SEG_IO, d0, 0.0) + jnp.where(k1 == SEG_IO, d1, 0.0)
            ram = jnp.asarray(plan.endpoint_ram)[s, ep]

            arr = jnp.where(mine, t, INF)
            order = jnp.argsort(arr)
            arr_s = arr[order]
            valid_s = mine[order]
            cpu_s = jnp.where(valid_s, cpu[order], 0.0)
            n_cores = int(plan.server_cores[s])
            if n_cores == 1:
                waits_s = _lindley_waits(arr_s, cpu_s, valid_s)
            else:
                waits_s = _kw_waits(arr_s, cpu_s, valid_s, n_cores)
            # IO-only requests bypass the core: their own wait is zero
            waits_s = jnp.where(cpu_s > 0, waits_s, 0.0)
            wait = jnp.zeros(n).at[order].set(waits_s)

            dep = t + wait + cpu + io
            # gauges: ready queue during the wait, io sleep, ram residency
            gauge = self._gauge_intervals(
                gauge, plan.gauge_ready(s), t, t + wait, 1.0, mine & (wait > 0),
            )
            gauge = self._gauge_intervals(
                gauge,
                plan.gauge_io(s),
                t + wait + cpu,
                dep,
                1.0,
                mine & (io > 0),
            )
            gauge = self._gauge_intervals(
                gauge,
                plan.gauge_ram(s),
                t,
                dep,
                ram,
                mine & (ram > 0),
            )
            gauge_means = gauge_means.at[plan.gauge_ready(s)].add(
                span(t, t + wait, mine),
            )
            gauge_means = gauge_means.at[plan.gauge_io(s)].add(
                span(t + wait + cpu, dep, mine),
            )
            gauge_means = gauge_means.at[plan.gauge_ram(s)].add(
                span(t, dep, mine, amount=ram),
            )

            # exit edge: the send only happens while the clock is running
            sendable = mine & (dep < plan.horizon)
            eidx = int(plan.exit_edge[s])
            dropped, delay = self._edge_hop(
                jax.random.fold_in(key, 128 + s), eidx, dep, ov,
            )
            ok = sendable & ~dropped
            gauge = self._gauge_intervals(gauge, eidx, dep, dep + delay, 1.0, ok)
            gauge_means = gauge_means.at[eidx].add(span(dep, dep + delay, ok))
            n_dropped = n_dropped + jnp.sum(sendable & dropped)
            if plan.exit_kind[s] == TARGET_SERVER:
                nxt = int(plan.exit_target[s])
                t = jnp.where(ok, dep + delay, t)
                srv = jnp.where(ok, nxt, srv)
                alive = jnp.where(mine, ok, alive)
            else:  # client: completion
                fin = dep + delay
                done = ok & (fin < plan.horizon)
                finish = jnp.where(done, fin, finish)
                completed = completed | done
                alive = jnp.where(mine, False, alive)

        # ---- reductions --------------------------------------------------
        latency = jnp.where(completed, finish - start, 0.0)
        lbin = latency_bin(latency, self.hist_lo, self.hist_scale, self.n_hist_bins)
        one = completed.astype(jnp.int32)
        hist = jnp.zeros(self.n_hist_bins, jnp.int32).at[
            jnp.where(completed, lbin, self.n_hist_bins)
        ].add(1, mode="drop")
        tbin = jnp.clip(jnp.ceil(finish).astype(jnp.int32) - 1, 0, self.n_thr - 1)
        thr = jnp.zeros(self.n_thr, jnp.int32).at[
            jnp.where(completed, tbin, self.n_thr)
        ].add(1, mode="drop")

        if self.collect_clocks:
            # clocks in arrival order, compacted to the front
            idx = jnp.where(completed, jnp.cumsum(one) - 1, self.n)
            clock = jnp.zeros((self.n, 2), jnp.float32)
            clock = clock.at[idx, 0].set(start, mode="drop")
            clock = clock.at[idx, 1].set(finish, mode="drop")
            clock_n = jnp.sum(one)
        else:
            clock = jnp.zeros((1, 2), jnp.float32)
            clock_n = jnp.sum(one)

        return FastState(
            hist=hist,
            lat_count=jnp.sum(one),
            lat_sum=jnp.sum(latency),
            lat_sumsq=jnp.sum(latency * latency),
            lat_min=jnp.min(jnp.where(completed, latency, INF)),
            lat_max=jnp.max(jnp.where(completed, latency, 0.0)),
            thr=thr,
            gauge=gauge,
            clock=clock,
            clock_n=clock_n,
            n_generated=n_generated,
            n_dropped=n_dropped,
            n_overflow=overflow,
            gauge_means=gauge_means / horizon,
        )

    def run_batch(
        self,
        keys: jnp.ndarray,
        overrides: ScenarioOverrides | None = None,
    ) -> FastState:
        """Run |keys| scenarios as one vmapped kernel."""
        ov = overrides if overrides is not None else base_overrides(self.plan)
        axes = ScenarioOverrides(
            *[
                0 if jnp.asarray(o).ndim > jnp.asarray(b).ndim else None
                for o, b in zip(ov, base_overrides(self.plan))
            ],
        )
        sig = tuple(axes)
        if sig not in self._compiled:
            self._compiled[sig] = jax.jit(jax.vmap(self._run_one, in_axes=(0, axes)))
        return self._compiled[sig](keys, ov)
