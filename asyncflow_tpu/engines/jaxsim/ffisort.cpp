// XLA:CPU FFI kernel: stable argsort + rank of f32 keys, adaptive.
//
// The scan fast path needs, per scenario, the stable sort permutation of
// ~88k arrival timestamps (see sortutil.py).  XLA's tuple-sort comparator
// costs ~15 ms per lane on one CPU core; the timestamps are NEAR-SORTED
// (sorted base + small iid edge-latency jitter), where an adaptive sort is
// O(n + inversions) ~ 1 ms.  This kernel is the CPU escape hatch, plugged
// in under jax.lax.platform_dependent (TPU keeps the pure-XLA path).
//
// Algorithm: binary-insertion-free plain insertion sort with a move
// budget (stable, cost n + #inversions); on budget overrun (adversarial /
// far-from-sorted input) falls back to std::stable_sort.  Equal keys keep
// index order in both paths, matching jnp.argsort's stability; +inf
// padding lanes therefore land at the tail in lane order.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -I<jax.ffi.include_dir()>.
// Replaces the reference's per-event heap ordering
// (/root/reference/src/asyncflow/runtime/simulation_runner.py:369) with a
// whole-array pass.

#include <algorithm>
#include <cstdint>
#include <limits>
#include <numeric>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// Stable adaptive argsort of the index prefix ob[0..m) (keys via kb).
// Plain IEEE '<' matches jnp.argsort's comparator: -0.0 and +0.0 compare
// equal and stability keeps them in lane order (the pure-XLA path
// canonicalizes -0.0 before its u32 bijection for the same reason).
// Returns false when the move budget is exhausted (caller falls back to
// std::stable_sort).
bool InsertionArgsort(const float* kb, int32_t* ob, int64_t m,
                      int64_t budget) {
  int64_t moves = 0;
  for (int64_t i = 1; i < m; ++i) {
    const int32_t idx = ob[i];
    const float kv = kb[idx];
    int64_t j = i;
    while (j > 0 && kb[ob[j - 1]] > kv) {
      ob[j] = ob[j - 1];
      --j;
      if (++moves > budget) return false;
    }
    ob[j] = idx;
  }
  return true;
}

ffi::Error StableArgsortRankImpl(ffi::Buffer<ffi::F32> keys,
                                 ffi::ResultBuffer<ffi::S32> order,
                                 ffi::ResultBuffer<ffi::S32> rank) {
  const auto dims = keys.dimensions();
  if (dims.size() == 0) {
    return ffi::Error::InvalidArgument("keys must have at least one dim");
  }
  const int64_t n = dims.back();
  const int64_t batch = n == 0 ? 0 : keys.element_count() / n;
  const float* k = keys.typed_data();
  int32_t* o = order->typed_data();
  int32_t* r = rank->typed_data();
  const float kInf = std::numeric_limits<float>::infinity();
  for (int64_t b = 0; b < batch; ++b) {
    const float* kb = k + b * n;
    int32_t* ob = o + b * n;
    // Stable partition: finite keys first (the +inf padding/drop lanes
    // would each travel to the tail and blow the insertion budget; a
    // stable sort sends every +inf/NaN tie to the back in lane order, so
    // emit that block directly).
    int64_t m = 0;
    for (int64_t i = 0; i < n; ++i) {
      if (kb[i] < kInf) ob[m++] = static_cast<int32_t>(i);
    }
    int64_t d = m;
    for (int64_t i = 0; i < n; ++i) {
      if (!(kb[i] < kInf)) ob[d++] = static_cast<int32_t>(i);
    }
    if (!InsertionArgsort(kb, ob, m, /*budget=*/8 * n)) {
      // The bailed insertion pass left ob permuted; stability is relative
      // to the array order, so restore lane order before the real sort.
      int64_t w = 0;
      for (int64_t i = 0; i < n; ++i) {
        if (kb[i] < kInf) ob[w++] = static_cast<int32_t>(i);
      }
      std::stable_sort(ob, ob + m, [kb](int32_t a, int32_t c) {
        return kb[a] < kb[c];
      });
    }
    int32_t* rb = r + b * n;
    for (int64_t j = 0; j < n; ++j) rb[ob[j]] = static_cast<int32_t>(j);
  }
  return ffi::Error::Success();
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(AfStableArgsortRank, StableArgsortRankImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>());
