"""VMEM-resident Pallas event engine: the whole DES loop in one TPU kernel.

The general event engine (`engine.py`) reproduces the reference runtime's
semantics (`/root/reference/src/asyncflow/runtime/actors/server.py:79-276`)
but pays XLA's per-`while_loop`-iteration overhead (~300 us on TPU: each
iteration lowers to dozens of small fused kernels).  This module compiles the
*same state machine* into a single Pallas kernel: a block of scenarios' pool
state lives in VMEM/vector registers as ``(S, P)`` tiles and one
``lax.while_loop`` inside the kernel advances every scenario by one event per
iteration at VPU cost (~a few us per iteration for a whole block), removing
the kernel-launch floor entirely — the design in
``docs/internals/pallas-plan.md``.

Semantics are the event engine's, re-expressed batched:

- every per-slot scatter (``pool.at[i].set``) becomes a one-hot masked
  ``where`` over the 128-lane pool axis (Mosaic-friendly: no dynamic
  scatter/gather is emitted anywhere);
- every static-table lookup is a one-hot reduction over the (small) table;
- randomness is an in-kernel threefry2x32 keyed by the *same per-scenario
  PRNG keys* the event engine uses, with a (iteration, draw-site) counter —
  bit-identical between ``interpret=True`` (CPU tests) and compiled TPU
  runs, distributionally equivalent to the event engine (parity is
  distributional across all engines anyway, SURVEY.md §7);
- per-window user draws (Poisson or truncated Gaussian) are precomputed
  *outside* the kernel with ``jax.random`` — identical distribution to the
  event engine's in-loop draws (`engine.py:246-253`), avoiding an O(lambda)
  in-kernel Poisson loop;
- metric output is sweep-mode (histogram + moments + throughput + counters),
  i.e. exactly what ``SweepRunner`` uses; gauge/clock collection stays on
  the event engine, which remains the single-run engine.

Feature coverage matches the event engine: multi-segment endpoints, lazy
core handoff with FIFO tickets, RAM admission with strict-FIFO grant
cascades, both LB algorithms, outage timelines, spike superposition, all
five edge distributions (Poisson via an in-kernel exp-sum loop), dropout,
server chains, overflow/truncation accounting, weighted endpoint
selection (cumulative-weight one-hot walk), stochastic cache mixtures,
LLM call dynamics (in-kernel Poisson tokens; cost sum/sumsq outputs), and
binding DB connection pools (a second strict-FIFO ticket queue whose
holder sleeps instead of running).  Reachable overload policies stay on
the event engine.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from asyncflow_tpu.checker.fences import raise_fence
from asyncflow_tpu.compiler.plan import (
    SEG_CACHE,
    SEG_CPU,
    SEG_DB,
    SEG_END,
    SEG_IO,
    SEG_LLM,
    TARGET_CLIENT,
    TARGET_LB,
    TARGET_SERVER,
    StaticPlan,
)
from asyncflow_tpu.engines.jaxsim.params import (
    EV_ABANDON,
    EV_ARRIVE_LB,
    EV_ARRIVE_SRV,
    EV_IDLE,
    EV_RESUME,
    EV_SEG_END,
    EV_WAIT_CPU,
    EV_WAIT_DB,
    EV_WAIT_RAM,
    INF,
    NO_TICKET,
    ScenarioOverrides,
    base_overrides,
)
from asyncflow_tpu.engines.jaxsim.sampling import (
    D_EXPONENTIAL,
    D_LOGNORMAL,
    D_NORMAL,
    D_POISSON,
    D_UNIFORM,
    TINY,
    as_threefry,
    hist_constants,
)

# ======================================================================
# in-kernel counter-based RNG (threefry2x32, the same generator JAX uses)
# ======================================================================

_TF_C240 = np.uint32(0x1BD11BDA)
_TF_ROTS = (13, 15, 26, 6, 17, 29, 16, 24)


def _rotl(x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _threefry2x32(k0, k1, x0, x1):
    """One threefry2x32 block (20 rounds); all args uint32 arrays."""
    ks = (k0, k1, _TF_C240 ^ k0 ^ k1)
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    for i in range(5):
        rots = _TF_ROTS[:4] if i % 2 == 0 else _TF_ROTS[4:]
        for r in rots:
            x0 = x0 + x1
            x1 = _rotl(x1, r) ^ x0
        x0 = x0 + ks[(i + 1) % 3]
        x1 = x1 + ks[(i + 2) % 3] + np.uint32(i + 1)
    return x0, x1


def _uniform_from_bits(bits):
    """uint32 -> f32 uniform in [0, 1) with 24-bit resolution.

    Routed through int32: the shifted value is < 2**24 so the reinterpret
    is exact, and Mosaic's TPU lowering has no uint32->f32 cast rule (the
    direct cast raises ``NotImplementedError: Unsupported cast`` at
    lowering — found by scripts/pallas_keepcut.py's cross-lowering probe).
    """
    return (
        (bits >> np.uint32(8)).astype(jnp.int32).astype(jnp.float32)
        * np.float32(2.0**-24)
    )


class _Rng:
    """Per-row counter RNG.

    Draws are addressed by (iteration, site, sequence): the counter words are
    ``x0 = iteration`` and ``x1 = site | seq << 10`` — sites are static
    Python ints < 1024, ``seq`` distinguishes draws inside data-dependent
    loops, so no two draws in a run share a counter.
    """

    def __init__(self, k0, k1):
        self.k0 = k0  # (S, 1) uint32
        self.k1 = k1

    def pair(self, it, site: int, seq=None):
        """Two independent (S, 1) uniform draws for ``(it, site, seq)``."""
        x0 = jnp.broadcast_to(jnp.asarray(it).astype(jnp.uint32), self.k0.shape)
        x1 = jnp.full_like(self.k0, np.uint32(site))
        if seq is not None:
            x1 = x1 + (jnp.asarray(seq).astype(jnp.uint32) << np.uint32(10))
        b0, b1 = _threefry2x32(self.k0, self.k1, x0, x1)
        return _uniform_from_bits(b0), _uniform_from_bits(b1)

    def one(self, it, site: int, seq=None):
        return self.pair(it, site, seq)[0]


# ======================================================================
# batched one-hot primitives (no scatters/gathers: Mosaic-safe)
# ======================================================================


def _sel_col(arr, idx):
    """Per-row column select: arr (S, N), idx (S, 1) -> (S, 1)."""
    s, n = arr.shape
    lane = jax.lax.broadcasted_iota(jnp.int32, (s, n), 1)
    hit = lane == idx
    if arr.dtype == jnp.bool_:
        return jnp.sum(jnp.where(hit, arr, False).astype(jnp.int32), 1, keepdims=True) > 0
    return jnp.sum(jnp.where(hit, arr, jnp.zeros((), arr.dtype)), 1, keepdims=True)


def _set_col(arr, idx, val, pred):
    """Masked per-row column write: arr (S, N) <- val where lane == idx."""
    s, n = arr.shape
    lane = jax.lax.broadcasted_iota(jnp.int32, (s, n), 1)
    return jnp.where(pred & (lane == idx), val, arr)


def _add_col(arr, idx, val, pred):
    s, n = arr.shape
    lane = jax.lax.broadcasted_iota(jnp.int32, (s, n), 1)
    return arr + jnp.where(
        pred & (lane == idx),
        val,
        jnp.zeros((), arr.dtype),
    )


def _tab(table, idx):
    """Table lookup by per-row index: table (1, T) kernel input, idx (S, 1).

    Tables must be kernel *inputs* (Pallas forbids captured constants), so
    callers pass the loaded ``(1, T)`` value.
    """
    s = idx.shape[0]
    t = table.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (s, t), 1)
    return jnp.sum(
        jnp.where(lane == idx, table, jnp.zeros((), table.dtype)),
        1,
        keepdims=True,
    )


def _gather_by_order(order, values):
    """Per-position gather over the (tiny, static) LB slot axis:
    ``out[:, pos] = values[:, order[:, pos]]`` as a one-hot loop."""
    if values.dtype == jnp.bool_:
        # selects PRODUCING i1 vectors have no Mosaic lowering (an i8->i1
        # trunci the real compile rejects); gather in i32, re-mask after
        return _gather_by_order(order, values.astype(jnp.int32)) > 0
    el = values.shape[1]
    out = jnp.zeros(order.shape, values.dtype)
    for j in range(el):
        out = jnp.where(order == j, values[:, j : j + 1], out)
    return out


def _argmin_row(values):
    """Per-row argmin over lanes -> ((S,1) index, (S,1) value).

    Ties resolve to the lowest lane index, matching ``jnp.argmin``.
    """
    s, n = values.shape
    vmin = jnp.min(values, 1, keepdims=True)
    lane = jax.lax.broadcasted_iota(jnp.int32, (s, n), 1)
    idx = jnp.min(jnp.where(values == vmin, lane, n), 1, keepdims=True)
    return idx, vmin


def _argmax_bool_row(mask):
    """Per-row first True lane -> ((S,1) index, (S,1) found)."""
    s, n = mask.shape
    lane = jax.lax.broadcasted_iota(jnp.int32, (s, n), 1)
    idx = jnp.min(jnp.where(mask, lane, n), 1, keepdims=True)
    return jnp.minimum(idx, n - 1), idx < n


# ======================================================================
# batched LB rotation (shift-based: no dynamic gather)
# ======================================================================


def _rot_advance(rot, length, pred):
    """Head to tail within the length-prefix; static roll only."""
    el = rot.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, rot.shape, 1)
    shifted = jnp.roll(rot, -1, axis=1)
    head = rot[:, 0:1]
    rotated = jnp.where(
        lane < length - 1,
        shifted,
        jnp.where(lane == length - 1, head, rot),
    )
    return jnp.where(pred, rotated, rot)


def _rot_remove(rot, length, slot, pred):
    el = rot.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, rot.shape, 1)
    hit = jnp.where((rot == slot) & (lane < length), lane, el)
    at = jnp.min(hit, 1, keepdims=True)
    act = pred & (at < el)
    shifted = jnp.roll(rot, -1, axis=1)
    return (
        jnp.where(act & (lane >= at) & (lane < el - 1), shifted, rot),
        jnp.where(act, length - 1, length),
    )


def _rot_insert(rot, length, slot, pred):
    el = rot.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, rot.shape, 1)
    present = jnp.sum(
        ((rot == slot) & (lane < length)).astype(jnp.int32), 1, keepdims=True,
    ) > 0
    act = pred & ~present
    idx = jnp.clip(length, 0, el - 1)
    return (
        jnp.where(act & (lane == idx), slot, rot),
        jnp.where(act, jnp.minimum(length + 1, el), length),
    )


# ======================================================================
# engine
# ======================================================================


class PallasState(NamedTuple):
    """Sweep-mode outputs, duck-compatible with FastState/EngineState for
    ``sweep_results`` (gauge/clock fields absent: the Pallas engine is the
    sweep engine; single runs with gauges stay on the event engine)."""

    hist: np.ndarray
    lat_count: np.ndarray
    lat_sum: np.ndarray
    lat_sumsq: np.ndarray
    lat_min: np.ndarray
    lat_max: np.ndarray
    thr: np.ndarray
    clock: np.ndarray
    clock_n: np.ndarray
    n_generated: np.ndarray
    n_dropped: np.ndarray
    n_overflow: np.ndarray
    truncated: np.ndarray
    llm_sum: np.ndarray
    llm_sumsq: np.ndarray
    n_rejected: np.ndarray


class PallasEngine:
    """Batched Pallas event engine for one :class:`StaticPlan`.

    Drop-in for ``Engine`` in sweep mode (``collect_gauges=False,
    collect_clocks=False``): same plan, same overrides, same result
    reduction.  ``interpret=None`` auto-selects the Pallas interpreter off
    TPU so the full test suite runs on CPU.
    """

    def __init__(
        self,
        plan: StaticPlan,
        *,
        n_hist_bins: int = 1024,
        pool_size: int | None = None,
        block: int = 128,
        interpret: bool | None = None,
        mesh=None,
        trace=None,
    ) -> None:
        """``mesh``: an optional 1-D scenario mesh; when given, ``run_batch``
        wraps the kernel in :func:`jax.shard_map` so each device runs the
        kernel on its scenario shard (the kernel itself is a single-device
        program — GSPMD cannot partition a ``pallas_call``, so the sharding
        seam has to be explicit)."""
        if trace is not None:
            # canonical refusals from the shared fence registry (the static
            # checker predicts these exact messages)
            raise_fence("trace.pallas")
        if plan.has_faults or plan.has_retry:
            raise_fence("resilience.pallas")
        self.plan = plan
        self.mesh = mesh
        self.n_hist_bins = n_hist_bins
        self.pool = pool_size or plan.pool_size
        self.block = block
        self.interpret = interpret
        self.hist_lo, self.hist_scale = hist_constants(n_hist_bins)
        self.n_thr = int(np.ceil(plan.horizon)) or 1
        # per-generator lam-table layout: gen gi's windows occupy columns
        # [off_gi, off_gi + nw_gi) of the concatenated (S, sum nw) table
        self._n_gen = plan.n_generators
        if self._n_gen > 1:
            self._gen_nw = [
                int(np.ceil(plan.horizon / w)) + 1 for w in plan.gen_window
            ]
        else:
            self._gen_nw = [int(np.ceil(plan.horizon / plan.user_window)) + 1]
        self._gen_lam_off = list(np.cumsum([0] + self._gen_nw[:-1]))
        self.n_windows = int(sum(self._gen_nw))
        self._dists_present = sorted(set(plan.edge_dist.tolist()))
        self._has_ram = bool(np.max(plan.endpoint_ram) > 0)
        self._has_cache = bool(np.any(plan.seg_kind == SEG_CACHE))
        self._has_shed = plan.has_queue_cap
        self._has_conn = plan.has_conn_cap
        self._has_rl = plan.has_rate_limit
        self._has_timeout = plan.has_queue_timeout
        self._has_breaker = plan.breaker_threshold > 0
        self._has_llm = bool(np.any(plan.seg_kind == SEG_LLM))
        self._has_db = bool(np.any(plan.seg_kind == SEG_DB))
        self._has_tl = len(plan.timeline_times) > 0
        self._has_spikes = len(plan.spike_times) > 1
        self._nsegp = plan.seg_kind.shape[2]
        self._nep = max(plan.max_endpoints, 1)
        # Static plan tables become kernel INPUTS (Pallas forbids captured
        # array constants), shaped (1, T) and broadcast to every block.
        # Flattened segment programs allow one-hot lookup by a single index.
        tables: list[tuple[str, np.ndarray]] = [
            ("seg_kind", plan.seg_kind.reshape(-1).astype(np.int32)),
            ("seg_dur", plan.seg_dur.reshape(-1).astype(np.float32)),
            ("ep_ram", plan.endpoint_ram.reshape(-1).astype(np.float32)),
            # endpoint selection by cumulative weight (uniform plans carry
            # the k/nep ladder, weighted plans their weights — one path)
            ("ep_cum", plan.endpoint_cum.reshape(-1).astype(np.float32)),
            ("edge_dist", plan.edge_dist.astype(np.int32)),
            ("exit_edge", plan.exit_edge.astype(np.int32)),
            ("exit_kind", plan.exit_kind.astype(np.int32)),
            ("exit_target", plan.exit_target.astype(np.int32)),
            ("n_endpoints", plan.n_endpoints.astype(np.int32)),
            ("server_cores", plan.server_cores.astype(np.int32)),
            ("server_ram", plan.server_ram.astype(np.float32)),
        ]
        if self._has_cache:
            tables += [
                ("seg_hit_prob", plan.seg_hit_prob.reshape(-1).astype(np.float32)),
                ("seg_miss_dur", plan.seg_miss_dur.reshape(-1).astype(np.float32)),
            ]
        if self._has_llm:
            tables += [
                ("seg_llm_tokens", plan.seg_llm_tokens.reshape(-1).astype(np.float32)),
                ("seg_llm_tpt", plan.seg_llm_tpt.reshape(-1).astype(np.float32)),
                ("seg_llm_cost", plan.seg_llm_cost.reshape(-1).astype(np.float32)),
            ]
        if self._has_shed:
            tables += [
                ("queue_cap", plan.server_queue_cap.astype(np.int32)),
            ]
        if self._has_conn:
            tables += [
                ("conn_cap", plan.server_conn_cap.astype(np.int32)),
            ]
        if self._has_rl:
            tables += [
                ("rate_limit", plan.server_rate_limit.astype(np.float32)),
                ("rate_burst", plan.server_rate_burst.astype(np.float32)),
            ]
        if self._has_timeout:
            tables += [
                ("queue_timeout", plan.server_queue_timeout.astype(np.float32)),
            ]
        if self._has_db:
            tables += [
                # -1 (unlimited) becomes a huge pool so acquire never blocks
                ("db_pool", np.where(
                    plan.server_db_pool >= 0, plan.server_db_pool, 2**30,
                ).astype(np.int32)),
            ]
        if plan.n_lb_edges > 0:
            tables += [
                ("lb_edge_index", plan.lb_edge_index.astype(np.int32)),
                ("lb_target", plan.lb_target.astype(np.int32)),
            ]
        if self._has_tl:
            tables += [
                ("tl_times", plan.timeline_times.astype(np.float32)),
                ("tl_down", plan.timeline_down.astype(np.int32)),
                ("tl_slot", plan.timeline_slot.astype(np.int32)),
            ]
        if self._has_spikes:
            tables += [
                ("spike_times", plan.spike_times.astype(np.float32)),
                ("spike_vals", plan.spike_values.reshape(-1).astype(np.float32)),
            ]
        self._tables = [(name, arr.reshape(1, -1)) for name, arr in tables]
        self._tk: dict = {}  # bound to the loaded refs during kernel tracing
        self._compiled: dict = {}

    # ------------------------------------------------------------------
    # table helpers bound to the plan
    # ------------------------------------------------------------------

    def _seg_idx(self, s, ep, seg):
        return (s * self._nep + ep) * self._nsegp + seg

    def _edge_draw(self, rng: _Rng, it, site: int, edge_idx, t_send, ov_tabs):
        """(dropped, delay incl. spike) for per-row edge index ``edge_idx``.

        ``ov_tabs`` holds the per-scenario (S, NE) parameter tables.
        """
        em, ev_, ed = ov_tabs
        mean = _sel_col(em, edge_idx)
        var = _sel_col(ev_, edge_idx)
        drop_p = _sel_col(ed, edge_idx)
        dist = _tab(self._tk["edge_dist"], edge_idx)

        u_drop, u = rng.pair(it, site)
        delay = jnp.zeros_like(mean)
        if D_UNIFORM in self._dists_present:
            delay = jnp.where(dist == D_UNIFORM, u, delay)
        if D_EXPONENTIAL in self._dists_present:
            g = -mean * jnp.log(jnp.maximum(1.0 - u, np.float32(TINY)))
            delay = jnp.where(dist == D_EXPONENTIAL, g, delay)
        if {D_NORMAL, D_LOGNORMAL} & set(self._dists_present):
            # Box-Muller; scale semantics follow sampling.py (the variance
            # field IS the scale, matching the reference's numpy calls)
            u1, u2 = rng.pair(it, site + 1)
            z = jnp.sqrt(-2.0 * jnp.log(jnp.maximum(u1, np.float32(TINY)))) * jnp.cos(
                np.float32(2.0 * np.pi) * u2,
            )
            if D_NORMAL in self._dists_present:
                delay = jnp.where(
                    dist == D_NORMAL,
                    jnp.maximum(0.0, mean + var * z),
                    delay,
                )
            if D_LOGNORMAL in self._dists_present:
                delay = jnp.where(
                    dist == D_LOGNORMAL,
                    jnp.exp(mean + var * z),
                    delay,
                )
        if D_POISSON in self._dists_present:
            # exp-sum counting process: K ~ Poisson(mean) exactly
            def pcond(c):
                _acc, _k, live, _seq = c
                return jnp.sum(live) > 0

            def pbody(c):
                # `live` rides the carry as i32: i1 vectors in scf.while
                # carries fail Mosaic's yield legalization (found by the
                # real-compile gate, round 5)
                acc, k, live, seq = c
                u_p = rng.one(it, site + 2, seq)
                g = -jnp.log(jnp.maximum(1.0 - u_p, np.float32(TINY)))
                acc2 = acc + g
                over = acc2 > jnp.maximum(mean, np.float32(TINY))
                go = (live > 0) & ~over
                k = jnp.where(go, k + 1, k)
                return acc2, k, go.astype(jnp.int32), seq + 1

            is_pois = dist == D_POISSON
            _, kcnt, _, _ = jax.lax.while_loop(
                pcond,
                pbody,
                (
                    jnp.zeros_like(mean),
                    jnp.zeros_like(mean, jnp.int32),
                    is_pois.astype(jnp.int32),
                    jnp.int32(0),
                ),
            )
            delay = jnp.where(is_pois, kcnt.astype(jnp.float32), delay)

        if self._has_spikes:
            bp = jnp.sum(
                (self._tk["spike_times"] <= t_send).astype(jnp.int32),
                1,
                keepdims=True,
            ) - 1
            delay = delay + _tab(
                self._tk["spike_vals"],
                bp * self.plan.n_edges + edge_idx,
            )
        return u_drop < drop_p, delay

    # ------------------------------------------------------------------
    # kernel body pieces (each takes/returns the state dict)
    # ------------------------------------------------------------------

    def _advance_arrival(self, st, rng, it, lam_tab, pred, gen: int = 0):
        """Batched window-jump gap sampler (`engine.py:229-291`).

        ``gen`` is a STATIC stream index on multi-generator plans: the
        arrival-state fields are (S, G) columns and each stream reads its
        own lam-table block and window length.
        """
        plan = self.plan
        horizon = np.float32(plan.horizon)
        if self._n_gen > 1:
            window = np.float32(plan.gen_window[gen])
        else:
            window = np.float32(plan.user_window)
        off = self._gen_lam_off[gen]
        nw = self._gen_nw[gen]
        lam_tab = lam_tab[:, off : off + nw]
        gcol = slice(gen, gen + 1)

        def cond(c):
            _smp, _we, _widx, status, _gap, _d = c
            return jnp.sum((status == 0).astype(jnp.int32)) > 0

        def body(c):
            smp_now, window_end, widx, status, gap, dctr = c
            active = status == 0
            # exhausted outright: the sampler clock passed the horizon
            done_h = active & (smp_now >= horizon)
            status = jnp.where(done_h, 2, status)
            active = active & ~done_h

            need_window = active & (smp_now >= window_end)
            widx = jnp.where(need_window, widx + 1, widx)
            # lam is a pure function of widx: recompute instead of carrying
            # it — selecting the lane-reduction output of _sel_col against a
            # loop carry asks Mosaic for a concrete->replicated relayout of
            # a non-singleton dim, which the real TPU compile rejects
            # (first observed on-chip, round 5).
            lam = _sel_col(lam_tab, jnp.minimum(widx, nw - 1))
            window_end = jnp.where(need_window, smp_now + window, window_end)

            no_users = lam <= 0.0
            u = jnp.maximum(rng.one(it, 200 + gen, dctr), np.float32(TINY))
            g = -jnp.log(jnp.maximum(1.0 - u, np.float32(TINY))) / jnp.maximum(
                lam, np.float32(TINY),
            )
            beyond = smp_now + g > horizon
            crosses = smp_now + g >= window_end

            smp_next = jnp.where(
                no_users,
                window_end,
                jnp.where(
                    beyond,
                    smp_now,
                    jnp.where(crosses, window_end, smp_now + g),
                ),
            )
            new_status = jnp.where(
                no_users,
                0,
                jnp.where(beyond, 2, jnp.where(crosses, 0, 1)),
            ).astype(jnp.int32)
            smp_now = jnp.where(active, smp_next, smp_now)
            gap = jnp.where(active & (new_status == 1), g, gap)
            status = jnp.where(active, new_status, status)
            return smp_now, window_end, widx, status, gap, dctr + 1

        # Layout anchor: when every init below is constant-derived (the
        # kernel's t=0 call passes pred=True and a zero state), Mosaic gives
        # the loop carries replicated vector layouts, and the RNG-driven
        # (concrete-layout) yields then need an invalid concrete->replicated
        # relayout.  Adding a data-dependent exact zero (k0 >= 0, so
        # min(k0, 0) == 0, and no canonicalizer fold applies) pins every
        # carry to a concrete layout.  Found by real AOT compile, round 5.
        # (uint32->f32 has no Mosaic lowering: shift keeps the int32 cast
        # positive, so min(.,0) is still an exact zero)
        czi = jnp.minimum(
            (rng.k0 >> jnp.uint32(9)).astype(jnp.int32), jnp.int32(0),
        )
        cz = czi.astype(jnp.float32)
        init = (
            st["smp_now"][:, gcol] + cz,
            st["smp_window_end"][:, gcol] + cz,
            st["widx"][:, gcol] + czi,
            jnp.where(pred, 0, 1).astype(jnp.int32) + czi,
            jnp.zeros_like(st["smp_now"][:, gcol]) + cz,
            jnp.int32(0),
        )
        smp_now, window_end, widx, status, gap, _ = jax.lax.while_loop(
            cond, body, init,
        )
        exhausted = status == 2
        prev = st["next_arrival"][:, gcol]
        nxt = jnp.where(exhausted, np.float32(INF), prev + gap)

        def upd(field, new):
            # one-hot column write (a concat of zero-width slices at the
            # edges has no Mosaic lowering)
            merged = jnp.where(pred, new, field[:, gcol])
            lane = jax.lax.broadcasted_iota(jnp.int32, field.shape, 1)
            return jnp.where(lane == gen, merged, field)

        st["smp_now"] = upd(st["smp_now"], smp_now)
        st["smp_window_end"] = upd(st["smp_window_end"], window_end)
        st["widx"] = upd(st["widx"], widx)
        st["next_arrival"] = upd(st["next_arrival"], nxt)
        return st

    def _complete(self, st, i, start, finish, pred):
        latency = finish - start
        if self._has_llm:
            cost = _sel_col(st["req_llm"], i)
            st["llm_sum"] = st["llm_sum"] + jnp.where(pred, cost, 0.0)
            st["llm_sumsq"] = st["llm_sumsq"] + jnp.where(pred, cost * cost, 0.0)
        # identical binning to sampling.latency_bin (shared hist contract)
        lbin = jnp.clip(
            (
                (jnp.log(jnp.maximum(latency, np.float32(1e-6)))
                 - np.float32(self.hist_lo))
                * np.float32(self.hist_scale)
            ).astype(jnp.int32),
            0,
            self.n_hist_bins - 1,
        )
        one = jnp.where(pred, 1, 0)
        lat = jnp.where(pred, latency, 0.0)
        st["hist"] = _add_col(st["hist"], lbin, 1, pred)
        tbin = jnp.clip(jnp.ceil(finish).astype(jnp.int32) - 1, 0, self.n_thr - 1)
        st["thr"] = _add_col(st["thr"], tbin, 1, pred)
        st["lat_count"] = st["lat_count"] + one
        st["lat_sum"] = st["lat_sum"] + lat
        st["lat_sumsq"] = st["lat_sumsq"] + lat * lat
        st["lat_min"] = jnp.where(
            pred, jnp.minimum(st["lat_min"], latency), st["lat_min"],
        )
        st["lat_max"] = jnp.where(
            pred, jnp.maximum(st["lat_max"], latency), st["lat_max"],
        )
        return st

    def _lb_pick(self, st):
        """(slot, rotated order) — `engine.py:297-308`."""
        el = max(self.plan.n_lb_edges, 1)
        if self.plan.lb_algo == 0:
            slot = st["lb_order"][:, 0:1]
            return slot, _rot_advance(st["lb_order"], st["lb_len"], True)
        lane = jax.lax.broadcasted_iota(jnp.int32, st["lb_order"].shape, 1)
        valid = lane < st["lb_len"]
        conn_rot = _gather_by_order(st["lb_order"], st["lb_conn"])
        order_key = jnp.where(valid, conn_rot * el + lane, jnp.int32(2**30))
        best, _ = _argmin_row(order_key)
        return _sel_col(st["lb_order"], best), st["lb_order"]

    def _seg_start(self, st, i, s, ep, seg, now, rng, it, ov_tabs, pred):
        """`engine.py:382-419`."""
        plan = self.plan
        sidx = self._seg_idx(s, ep, seg)
        kind = _tab(self._tk["seg_kind"], sidx)
        dur = _tab(self._tk["seg_dur"], sidx)
        is_cpu = pred & (kind == SEG_CPU)
        is_io = pred & (kind == SEG_IO)
        is_end = pred & (kind == SEG_END)

        if self._has_cache:
            # SEG_CACHE: per-request hit/miss mixture (`engine.py:495-503`)
            is_cache = pred & (kind == SEG_CACHE)
            u_cache = rng.one(it, 24)
            dur = jnp.where(
                is_cache & (u_cache >= _tab(self._tk["seg_hit_prob"], sidx)),
                _tab(self._tk["seg_miss_dur"], sidx),
                dur,
            )
            is_io = is_io | is_cache
        if self._has_llm:
            # SEG_LLM: tokens ~ Poisson(mean) via the in-kernel exp-sum
            # counting process; the sleep stretches by tokens * s/token and
            # the request accrues tokens * cost (`engine.py:505-518`)
            is_llm = pred & (kind == SEG_LLM)
            lam_t = jnp.maximum(
                _tab(self._tk["seg_llm_tokens"], sidx), np.float32(1e-6),
            )

            def lcond(c):
                _acc, _k, live, _seq = c
                return jnp.sum(live) > 0

            def lbody(c):
                # i32 `live` carry: see the edge-Poisson loop note
                acc, k, live, seq = c
                u_p = rng.one(it, 25, seq)
                g = -jnp.log(jnp.maximum(1.0 - u_p, np.float32(TINY)))
                acc2 = acc + g
                over = acc2 > lam_t
                go = (live > 0) & ~over
                k = jnp.where(go, k + 1, k)
                return acc2, k, go.astype(jnp.int32), seq + 1

            _, tok, _, _ = jax.lax.while_loop(
                lcond,
                lbody,
                (
                    jnp.zeros_like(dur),
                    jnp.zeros_like(dur, jnp.int32),
                    is_llm.astype(jnp.int32),
                    jnp.int32(0),
                ),
            )
            tokens = tok.astype(jnp.float32)
            dur = jnp.where(
                is_llm, dur + tokens * _tab(self._tk["seg_llm_tpt"], sidx), dur,
            )
            st["req_llm"] = _add_col(
                st["req_llm"],
                i,
                jnp.where(is_llm, tokens * _tab(self._tk["seg_llm_cost"], sidx), 0.0),
                is_llm,
            )
            is_io = is_io | is_llm

        has_waiters = _sel_col(st["cpu_wait_n"], s) > 0
        can_take = (_sel_col(st["cores_free"], s) > 0) & ~has_waiters
        cpu_run = is_cpu & can_take
        cpu_wait = is_cpu & ~can_take
        shed = jnp.zeros_like(is_cpu)
        if self._has_shed:
            # overload policy: joining a FULL ready queue sheds the
            # request (`engine.py:523-531`)
            cap = _tab(self._tk["queue_cap"], s)
            shed = (
                cpu_wait
                & (cap >= 0)
                & (_sel_col(st["cpu_wait_n"], s) >= cap)
            )
            cpu_wait = cpu_wait & ~shed
        run_now = cpu_run | is_io

        db_wait = jnp.zeros_like(is_cpu)
        if self._has_db:
            # DB connection acquire-or-wait: the core queue's strict-FIFO
            # discipline, but the holder sleeps instead of running
            # (`engine.py:536-552`)
            is_db = pred & (kind == SEG_DB)
            db_can = (_sel_col(st["db_free"], s) > 0) & ~(
                _sel_col(st["db_wait_n"], s) > 0
            )
            db_run = is_db & db_can
            db_wait = is_db & ~db_can
            run_now = run_now | db_run
            st["db_free"] = _add_col(st["db_free"], s, -1, db_run)
            st["db_ticket"] = _add_col(st["db_ticket"], s, 1, db_wait)
            st["db_wait_n"] = _add_col(st["db_wait_n"], s, 1, db_wait)

        st["cores_free"] = _add_col(st["cores_free"], s, -1, cpu_run)
        st["cpu_ticket"] = _add_col(st["cpu_ticket"], s, 1, cpu_wait)
        st["cpu_wait_n"] = _add_col(st["cpu_wait_n"], s, 1, cpu_wait)
        st["req_ev"] = _set_col(
            st["req_ev"],
            i,
            jnp.where(
                run_now,
                EV_SEG_END,
                jnp.where(cpu_wait, EV_WAIT_CPU, EV_WAIT_DB),
            ),
            run_now | cpu_wait | db_wait,
        )
        st["req_t"] = _set_col(
            st["req_t"],
            i,
            jnp.where(run_now, now + dur, np.float32(INF)),
            run_now | cpu_wait | db_wait,
        )
        st["req_ticket"] = _set_col(
            st["req_ticket"], i, _sel_col(st["cpu_ticket"], s), cpu_wait,
        )
        if self._has_db:
            st["req_ticket"] = _set_col(
                st["req_ticket"], i, _sel_col(st["db_ticket"], s), db_wait,
            )
        if self._has_timeout:
            st["req_wait_t"] = _set_col(st["req_wait_t"], i, now, cpu_wait)
        if self._has_shed:
            # shed: release RAM (grant cascade), free the socket slot,
            # leave the system, count rejected (`engine.py:596-616`)
            st = self._release_ram(st, i, s, now, shed)
            if self._has_conn:
                st["srv_conn"] = _add_col(st["srv_conn"], s, -1, shed)
            st["req_ev"] = _set_col(st["req_ev"], i, EV_IDLE, shed)
            st["req_t"] = _set_col(st["req_t"], i, np.float32(INF), shed)
            st["n_rejected"] = st["n_rejected"] + jnp.where(shed, 1, 0)
            st = self._breaker_server_report(
                st, i, now, jnp.full_like(shed, True), shed,
            )
        st["req_seg"] = _set_col(st["req_seg"], i, seg, pred)
        return self._exit_flow(st, i, s, now, rng, it, ov_tabs, is_end)

    def _release_ram(self, st, i, s, now, pred):
        """Release slot ``i``'s RAM on server ``s`` and run the strict-FIFO
        grant cascade (`engine.py`'s ``_release_ram``); shared by the exit
        flow, queue-cap shedding, and deadline abandons."""
        if not self._has_ram:
            return st
        ram_amt = _sel_col(st["req_ram"], i)
        st["ram_free"] = _add_col(
            st["ram_free"], s, jnp.where(pred, ram_amt, 0.0), pred,
        )
        st["req_ram"] = _set_col(st["req_ram"], i, 0.0, pred)

        # strict-FIFO grant cascade: grant heads while they fit
        srv_col = jnp.where(pred, s, -1)

        def gcond(c):
            req_ev, _t, req_tk, ram_free, wait_n, go = c
            waiting = (req_ev == EV_WAIT_RAM) & (st["req_srv"] == srv_col)
            tick = jnp.where(waiting, req_tk, NO_TICKET)
            head, tmin = _argmin_row(tick)
            fits = (tmin < NO_TICKET) & (
                _sel_col(st["req_ram"], head) <= _sel_col(ram_free, srv_col)
            )
            return jnp.sum((go & fits).astype(jnp.int32)) > 0

        def gbody(c):
            req_ev, req_t, req_tk, ram_free, wait_n, go = c
            waiting = (req_ev == EV_WAIT_RAM) & (st["req_srv"] == srv_col)
            tick = jnp.where(waiting, req_tk, NO_TICKET)
            head, tmin = _argmin_row(tick)
            fits = go & (tmin < NO_TICKET) & (
                _sel_col(st["req_ram"], head) <= _sel_col(ram_free, srv_col)
            )
            req_ev = _set_col(req_ev, head, EV_RESUME, fits)
            req_t = _set_col(req_t, head, now, fits)
            req_tk = _set_col(req_tk, head, NO_TICKET, fits)
            ram_free = _add_col(
                ram_free,
                srv_col,
                -jnp.where(fits, _sel_col(st["req_ram"], head), 0.0),
                fits,
            )
            wait_n = _add_col(wait_n, srv_col, -1, fits)
            return req_ev, req_t, req_tk, ram_free, wait_n, go

        (
            st["req_ev"],
            st["req_t"],
            st["req_ticket"],
            st["ram_free"],
            st["ram_wait_n"],
            _,
        ) = jax.lax.while_loop(
            gcond,
            gbody,
            (
                st["req_ev"],
                st["req_t"],
                st["req_ticket"],
                st["ram_free"],
                st["ram_wait_n"],
                pred,
            ),
        )
        return st

    def _exit_flow(self, st, i, s, now, rng, it, ov_tabs, pred):
        """`engine.py:421-529`: release RAM w/ FIFO grants, route exit edge."""
        plan = self.plan
        st = self._release_ram(st, i, s, now, pred)
        if self._has_conn:
            # departing the server releases its socket slot
            st["srv_conn"] = _add_col(st["srv_conn"], s, -1, pred)
        # departing the routed target is the breaker's success signal
        st = self._breaker_server_report(
            st, i, now, jnp.full_like(pred, False), pred,
        )

        e = _tab(self._tk["exit_edge"], s)
        kind = _tab(self._tk["exit_kind"], s)
        target = _tab(self._tk["exit_target"], s)
        dropped, delay = self._edge_draw(rng, it, 48, e, now, ov_tabs)
        arrive = now + delay
        to_server = pred & (kind == TARGET_SERVER) & ~dropped
        to_lb = pred & (kind == TARGET_LB) & ~dropped
        to_client = pred & (kind == TARGET_CLIENT) & ~dropped
        drop_here = pred & dropped

        st = self._complete(
            st,
            i,
            _sel_col(st["req_start"], i),
            arrive,
            to_client & (arrive < np.float32(self.plan.horizon)),
        )
        free = drop_here | to_client
        st["req_ev"] = _set_col(
            st["req_ev"],
            i,
            jnp.where(
                free,
                EV_IDLE,
                jnp.where(to_server, EV_ARRIVE_SRV, EV_ARRIVE_LB),
            ),
            free | to_server | to_lb,
        )
        st["req_t"] = _set_col(
            st["req_t"],
            i,
            jnp.where(free, np.float32(INF), arrive),
            free | to_server | to_lb,
        )
        st["req_srv"] = _set_col(st["req_srv"], i, target, to_server)
        st["req_lbslot"] = _set_col(st["req_lbslot"], i, -1, pred)
        st["n_dropped"] = st["n_dropped"] + jnp.where(drop_here, 1, 0)
        return st

    def _spawn_branch(self, st, now, rng, it, lam_tab, ov_tabs, pred):
        """`engine.py:336-380`: entry chain, pool slot, next arrival."""
        plan = self.plan
        st["n_generated"] = st["n_generated"] + jnp.where(pred, 1, 0)

        if self._n_gen > 1:
            g_idx, _ = _argmin_row(st["next_arrival"])
            chains = [
                plan.gen_entry_edges[gi, : plan.gen_entry_len[gi]].tolist()
                for gi in range(self._n_gen)
            ]
        else:
            g_idx = jnp.zeros_like(st["lb_len"])
            chains = [plan.entry_edges.tolist()]

        sblk = st["req_ev"].shape[0]
        # i32 accumulator: a jnp.where PRODUCING an i1 vector has no Mosaic
        # lowering (same class as _gather_by_order's bool branch)
        alive_i = pred.astype(jnp.int32)
        t_cur = now
        # _edge_draw consumes sites site..site+2 (Box-Muller pair, Poisson
        # loop), so edges need a stride of 4 and streams a block sized to
        # the longest chain; the single-stream range (64 + 4j) is
        # preserved for G == 1
        max_chain = max(len(c) for c in chains)
        for gi, chain in enumerate(chains):
            pred_gi = (alive_i > 0) & (g_idx == gi)
            t_gi = now
            for j, eidx in enumerate(chain):
                e = jnp.full((sblk, 1), np.int32(eidx))
                site = (
                    64 + 4 * j
                    if len(chains) == 1
                    else 600 + gi * 4 * max_chain + 4 * j
                )
                dropped, delay = self._edge_draw(
                    rng, it, site, e, t_gi, ov_tabs,
                )
                survives = pred_gi & ~dropped
                st["n_dropped"] = st["n_dropped"] + jnp.where(
                    pred_gi & dropped, 1, 0,
                )
                t_gi = jnp.where(survives, t_gi + delay, t_gi)
                pred_gi = survives
            t_cur = jnp.where(g_idx == gi, t_gi, t_cur)
            alive_i = jnp.where(
                g_idx == gi, pred_gi.astype(jnp.int32), alive_i,
            )
        alive = alive_i > 0

        slot, has_free = _argmax_bool_row(st["req_ev"] == EV_IDLE)
        overflow = alive & ~has_free
        place = alive & has_free
        if self._n_gen > 1:
            # static per-stream select (no dynamic gather: Mosaic-safe)
            ev0 = jnp.full((sblk, 1), EV_ARRIVE_SRV, jnp.int32)
            entry_target = jnp.zeros((sblk, 1), jnp.int32)
            for gi in range(self._n_gen):
                gmask = g_idx == gi
                ev_gi = (
                    EV_ARRIVE_LB
                    if int(plan.gen_entry_target_kind[gi]) == TARGET_LB
                    else EV_ARRIVE_SRV
                )
                ev0 = jnp.where(gmask, ev_gi, ev0)
                entry_target = jnp.where(
                    gmask,
                    np.int32(max(int(plan.gen_entry_target[gi]), 0)),
                    entry_target,
                )
        else:
            ev0 = (
                EV_ARRIVE_LB
                if plan.entry_target_kind == TARGET_LB
                else EV_ARRIVE_SRV
            )
            entry_target = np.int32(max(plan.entry_target, 0))
        st["req_ev"] = _set_col(st["req_ev"], slot, ev0, place)
        st["req_t"] = _set_col(st["req_t"], slot, t_cur, place)
        st["req_srv"] = _set_col(st["req_srv"], slot, entry_target, place)
        st["req_start"] = _set_col(st["req_start"], slot, now, place)
        st["req_lbslot"] = _set_col(st["req_lbslot"], slot, -1, place)
        st["req_ram"] = _set_col(st["req_ram"], slot, 0.0, place)
        st["req_ticket"] = _set_col(st["req_ticket"], slot, NO_TICKET, place)
        if self._has_llm:
            st["req_llm"] = _set_col(st["req_llm"], slot, 0.0, place)
        st["n_overflow"] = st["n_overflow"] + jnp.where(overflow, 1, 0)
        if self._n_gen > 1:
            for gi in range(self._n_gen):
                st = self._advance_arrival(
                    st, rng, it, lam_tab, pred & (g_idx == gi), gen=gi,
                )
            return st
        return self._advance_arrival(st, rng, it, lam_tab, pred)

    def _timeline_branch(self, st, pred):
        """`engine.py:320-334`."""
        if not self._has_tl:
            return st
        ntl = len(self.plan.timeline_times)
        ptr = jnp.clip(st["tl_ptr"], 0, ntl - 1)
        slot = _tab(self._tk["tl_slot"], ptr)
        down = _tab(self._tk["tl_down"], ptr) == 1
        act = pred & (slot >= 0)
        order, length = _rot_remove(st["lb_order"], st["lb_len"], slot, act & down)
        order, length = _rot_insert(order, length, slot, act & ~down)
        st["lb_order"] = order
        st["lb_len"] = length
        st["tl_ptr"] = st["tl_ptr"] + jnp.where(pred, 1, 0)
        return st

    def _breaker_report(self, st, slot, is_probe, failed, now, pred):
        """One success/failure report to breaker slot ``slot`` (per-row):
        the event engine's state machine batched (`engine.py:883-942`)."""
        plan = self.plan
        probe = pred & is_probe
        plain = pred & ~is_probe
        stt = _sel_col(st["cb_state"], slot)
        st["cb_probes_out"] = jnp.maximum(
            _add_col(st["cb_probes_out"], slot, -1, probe), 0,
        )
        p_fail = probe & failed
        c_fail = plain & failed & (stt == 0)
        consec = _sel_col(st["cb_consec"], slot) + jnp.where(c_fail, 1, 0)
        trips = c_fail & (consec >= plan.breaker_threshold)
        opens = p_fail | trips
        st["cb_consec"] = _set_col(
            st["cb_consec"],
            slot,
            jnp.where(trips | (plain & ~failed & (stt == 0)), 0, consec),
            pred,
        )
        st["cb_state"] = _set_col(st["cb_state"], slot, 1, opens)
        st["cb_open_until"] = _set_col(
            st["cb_open_until"],
            slot,
            now + np.float32(plan.breaker_cooldown),
            opens,
        )
        p_ok = probe & ~failed
        probe_ok = _sel_col(st["cb_probe_ok"], slot) + jnp.where(p_ok, 1, 0)
        closes = p_ok & (stt == 2) & (probe_ok >= plan.breaker_probes)
        st["cb_probe_ok"] = _set_col(st["cb_probe_ok"], slot, probe_ok, probe)
        st["cb_state"] = _set_col(st["cb_state"], slot, 0, closes)
        st["cb_consec"] = _set_col(st["cb_consec"], slot, 0, closes)
        return st

    def _breaker_server_report(self, st, i, now, failed, pred):
        """Report slot ``i``'s routing outcome once (no-op after clearing;
        `engine.py:944-961`)."""
        if not self._has_breaker:
            return st
        slot = _sel_col(st["req_cbslot"], i)
        act = pred & (slot >= 0)
        slot_c = jnp.maximum(slot, 0)
        st = self._breaker_report(
            st, slot_c, _sel_col(st["req_probe"], i) > 0, failed, now, act,
        )
        st["req_cbslot"] = _set_col(st["req_cbslot"], i, -1, act)
        st["req_probe"] = _set_col(st["req_probe"], i, 0, act)
        return st

    def _lb_pick_breaker(self, st, admits):
        """(slot, rotated order, none_admitting): RR picks the FIRST
        admitting rotation member and moves only it to the tail (skip in
        place); LC takes the masked first-min (`engine.py:377-401`)."""
        el = max(self.plan.n_lb_edges, 1)
        lane = jax.lax.broadcasted_iota(jnp.int32, st["lb_order"].shape, 1)
        valid = lane < st["lb_len"]
        # admits[order]: one-hot over the tiny slot count
        elig = valid & _gather_by_order(st["lb_order"], admits)
        any_elig = jnp.sum(elig.astype(jnp.int32), 1, keepdims=True) > 0
        if self.plan.lb_algo == 0:
            pos, _has = _argmax_bool_row(elig)
            slot = _sel_col(st["lb_order"], pos)
            order, length = _rot_remove(
                st["lb_order"], st["lb_len"], slot, any_elig,
            )
            order, _ = _rot_insert(order, length, slot, any_elig)
            return slot, order, ~any_elig
        conn_rot = _gather_by_order(st["lb_order"], st["lb_conn"])
        order_key = jnp.where(elig, conn_rot * el + lane, jnp.int32(2**30))
        best, _ = _argmin_row(order_key)
        return _sel_col(st["lb_order"], best), st["lb_order"], ~any_elig

    def _arrive_lb_branch(self, st, i, now, rng, it, ov_tabs, pred):
        """`engine.py:531-567`."""
        if self.plan.n_lb_edges == 0:
            return st
        empty = st["lb_len"] <= 0
        drop_empty = pred & empty
        route = pred & ~empty

        if self._has_breaker:
            # lazy cooldown expiry: open slots whose cooldown elapsed
            # become half-open with fresh probe slots (`engine.py:879-887`)
            wake = route & (st["cb_state"] == 1) & (now >= st["cb_open_until"])
            st["cb_state"] = jnp.where(wake, 2, st["cb_state"])
            st["cb_probes_out"] = jnp.where(wake, 0, st["cb_probes_out"])
            st["cb_probe_ok"] = jnp.where(wake, 0, st["cb_probe_ok"])
            admits = (st["cb_state"] == 0) | (
                (st["cb_state"] == 2)
                & (st["cb_probes_out"] < self.plan.breaker_probes)
            )
            slot, rotated, none_open = self._lb_pick_breaker(st, admits)
            reject = route & none_open
            route = route & ~none_open
            st["n_rejected"] = st["n_rejected"] + jnp.where(reject, 1, 0)
            st["req_ev"] = _set_col(st["req_ev"], i, EV_IDLE, reject)
            st["req_t"] = _set_col(st["req_t"], i, np.float32(INF), reject)
            probe = route & (_sel_col(st["cb_state"], slot) == 2)
            st["cb_probes_out"] = _add_col(st["cb_probes_out"], slot, 1, probe)
            st["req_cbslot"] = _set_col(st["req_cbslot"], i, slot, route)
            st["req_probe"] = _set_col(
                st["req_probe"], i, jnp.where(probe, 1, 0), route,
            )
        else:
            slot, rotated = self._lb_pick(st)
        st["lb_order"] = jnp.where(route, rotated, st["lb_order"])
        e = _tab(self._tk["lb_edge_index"], slot)
        dropped, delay = self._edge_draw(rng, it, 32, e, now, ov_tabs)
        arrive = now + delay
        ok = route & ~dropped
        drop_edge = route & dropped
        free = drop_empty | drop_edge
        if self._has_breaker:
            # a dropped send on the routing edge is a connection failure
            st = self._breaker_server_report(
                st, i, now, jnp.full_like(drop_edge, True), drop_edge,
            )

        st["lb_conn"] = _add_col(st["lb_conn"], slot, 1, ok)
        st["req_ev"] = _set_col(
            st["req_ev"],
            i,
            jnp.where(free, EV_IDLE, EV_ARRIVE_SRV),
            free | ok,
        )
        st["req_t"] = _set_col(
            st["req_t"],
            i,
            jnp.where(free, np.float32(INF), arrive),
            free | ok,
        )
        st["req_srv"] = _set_col(
            st["req_srv"], i, _tab(self._tk["lb_target"], slot), ok,
        )
        st["req_lbslot"] = _set_col(st["req_lbslot"], i, slot, ok)
        st["n_dropped"] = st["n_dropped"] + jnp.where(free, 1, 0)
        return st

    def _arrive_srv_branch(self, st, i, now, rng, it, ov_tabs, pred):
        """`engine.py:569-621`."""
        plan = self.plan
        s = _sel_col(st["req_srv"], i)

        if plan.n_lb_edges > 0:
            lbslot = _sel_col(st["req_lbslot"], i)
            dec = pred & (lbslot >= 0)
            st["lb_conn"] = _add_col(
                st["lb_conn"], jnp.maximum(lbslot, 0), -1, dec,
            )
            st["req_lbslot"] = _set_col(st["req_lbslot"], i, -1, pred)

        if self._has_rl:
            # token-bucket rate limiter: lazy refill at arrival, refuse
            # without a whole token (`engine.py:1069-1101`)
            rps = _tab(self._tk["rate_limit"], s)
            has_rl = pred & (rps >= 0)
            tokens = jnp.minimum(
                _tab(self._tk["rate_burst"], s),
                _sel_col(st["rl_tokens"], s)
                + (now - _sel_col(st["rl_last"], s)) * jnp.maximum(rps, 0.0),
            )
            limited = has_rl & (tokens < 1.0)
            st["rl_tokens"] = _set_col(
                st["rl_tokens"], s,
                tokens - jnp.where(limited, 0.0, 1.0),
                has_rl,
            )
            st["rl_last"] = _set_col(st["rl_last"], s, now, has_rl)
            st["req_ev"] = _set_col(st["req_ev"], i, EV_IDLE, limited)
            st["req_t"] = _set_col(st["req_t"], i, np.float32(INF), limited)
            st["n_rejected"] = st["n_rejected"] + jnp.where(limited, 1, 0)
            st = self._breaker_server_report(
                st, i, now, jnp.full_like(limited, True), limited,
            )
            pred = pred & ~limited
        if self._has_conn:
            # socket capacity: refuse when the server is at residents cap
            cap = _tab(self._tk["conn_cap"], s)
            refuse = pred & (cap >= 0) & (_sel_col(st["srv_conn"], s) >= cap)
            st["req_ev"] = _set_col(st["req_ev"], i, EV_IDLE, refuse)
            st["req_t"] = _set_col(st["req_t"], i, np.float32(INF), refuse)
            st["n_rejected"] = st["n_rejected"] + jnp.where(refuse, 1, 0)
            st = self._breaker_server_report(
                st, i, now, jnp.full_like(refuse, True), refuse,
            )
            pred = pred & ~refuse
            st["srv_conn"] = _add_col(st["srv_conn"], s, 1, pred)

        u = rng.one(it, 4)
        nep = _tab(self._tk["n_endpoints"], s)
        # endpoint pick by cumulative weight: searchsorted(cum, u, 'right')
        # as a sum of one-hot threshold tests over the (small, static)
        # max-endpoint count — weighted and uniform plans share the path
        # (`engine.py:1008`)
        ep = jnp.zeros_like(s)
        for k in range(self._nep):
            ck = _tab(self._tk["ep_cum"], s * self._nep + k)
            ep = ep + (ck <= u).astype(jnp.int32)
        ep = jnp.minimum(ep, nep - 1)
        st["req_ep"] = _set_col(st["req_ep"], i, ep, pred)

        if not self._has_ram:
            return self._seg_start(
                st, i, s, ep, jnp.zeros_like(ep), now, rng, it, ov_tabs, pred,
            )

        need = _tab(self._tk["ep_ram"], s * self._nep + ep)
        st["req_ram"] = _set_col(st["req_ram"], i, need, pred)

        ram_waiters = _sel_col(st["ram_wait_n"], s) > 0
        granted = pred & (
            (need <= 0) | (~ram_waiters & (_sel_col(st["ram_free"], s) >= need))
        )
        blocked = pred & ~granted

        st["ram_free"] = _add_col(
            st["ram_free"], s, -jnp.where(granted, need, 0.0), granted,
        )
        st["ram_ticket"] = _add_col(st["ram_ticket"], s, 1, blocked)
        st["ram_wait_n"] = _add_col(st["ram_wait_n"], s, 1, blocked)
        st["req_ev"] = _set_col(st["req_ev"], i, EV_WAIT_RAM, blocked)
        st["req_t"] = _set_col(st["req_t"], i, np.float32(INF), blocked)
        st["req_ticket"] = _set_col(
            st["req_ticket"], i, _sel_col(st["ram_ticket"], s), blocked,
        )
        return self._seg_start(
            st, i, s, ep, jnp.zeros_like(ep), now, rng, it, ov_tabs, granted,
        )

    def _resume_branch(self, st, i, now, rng, it, ov_tabs, pred):
        """`engine.py:623-636`."""
        if not self._has_ram:
            return st
        s = _sel_col(st["req_srv"], i)
        ep = _sel_col(st["req_ep"], i)
        return self._seg_start(
            st, i, s, ep, jnp.zeros_like(ep), now, rng, it, ov_tabs, pred,
        )

    def _cpu_handoff(self, st, s, now, was_cpu):
        """Release one core of server ``s`` or grant it to the head FIFO
        waiter; with dequeue deadlines, an expired grantee takes the core
        for ZERO service as an immediate EV_ABANDON (`engine.py:1180-1212`).
        """
        srv_col = jnp.where(was_cpu, s, -1)
        waiting = (st["req_ev"] == EV_WAIT_CPU) & (st["req_srv"] == srv_col)
        tick = jnp.where(waiting, st["req_ticket"], NO_TICKET)
        j, tmin = _argmin_row(tick)
        grant = was_cpu & (tmin < NO_TICKET)
        release = was_cpu & ~grant
        js = _sel_col(st["req_srv"], j)
        jep = _sel_col(st["req_ep"], j)
        jseg = _sel_col(st["req_seg"], j)
        jdur = _tab(self._tk["seg_dur"], self._seg_idx(js, jep, jseg))
        ev_next = jnp.full_like(js, EV_SEG_END)
        t_next = now + jdur
        if self._has_timeout:
            deadline = _tab(self._tk["queue_timeout"], s)
            expired = (
                grant
                & (deadline >= 0)
                & (now - _sel_col(st["req_wait_t"], j) > deadline)
            )
            ev_next = jnp.where(expired, EV_ABANDON, ev_next)
            t_next = jnp.where(expired, now, t_next)
        st["cores_free"] = _add_col(st["cores_free"], s, 1, release)
        st["cpu_wait_n"] = _add_col(st["cpu_wait_n"], s, -1, grant)
        st["req_ev"] = _set_col(st["req_ev"], j, ev_next, grant)
        st["req_t"] = _set_col(st["req_t"], j, t_next, grant)
        st["req_ticket"] = _set_col(st["req_ticket"], j, NO_TICKET, grant)
        return st

    def _abandon_branch(self, st, i, now, rng, it, ov_tabs, pred):
        """Dequeue deadline exceeded: hold the core for zero service, hand
        it onward, release RAM/socket, count rejected (`engine.py:1214-1233`).
        """
        if not self._has_timeout:
            return st
        s = _sel_col(st["req_srv"], i)
        st = self._cpu_handoff(st, s, now, pred)
        st = self._release_ram(st, i, s, now, pred)
        if self._has_conn:
            st["srv_conn"] = _add_col(st["srv_conn"], s, -1, pred)
        st["req_ev"] = _set_col(st["req_ev"], i, EV_IDLE, pred)
        st["req_t"] = _set_col(st["req_t"], i, np.float32(INF), pred)
        st["n_rejected"] = st["n_rejected"] + jnp.where(pred, 1, 0)
        return self._breaker_server_report(
            st, i, now, jnp.full_like(pred, True), pred,
        )

    def _seg_end_branch(self, st, i, now, rng, it, ov_tabs, pred):
        """`engine.py:638-669`: core handoff to longest-waiting, next seg."""
        s = _sel_col(st["req_srv"], i)
        ep = _sel_col(st["req_ep"], i)
        seg = _sel_col(st["req_seg"], i)
        kind = _tab(self._tk["seg_kind"], self._seg_idx(s, ep, seg))
        was_cpu = pred & (kind == SEG_CPU)

        st = self._cpu_handoff(st, s, now, was_cpu)

        if self._has_db:
            # DB connection handoff, mirroring the core queue's discipline
            # (`engine.py:1129-1146`)
            was_db = pred & (kind == SEG_DB)
            srv_col = jnp.where(pred, s, -1)
            dwaiting = (st["req_ev"] == EV_WAIT_DB) & (st["req_srv"] == srv_col)
            dtick = jnp.where(dwaiting, st["req_ticket"], NO_TICKET)
            dj, dtmin = _argmin_row(dtick)
            dgrant = was_db & (dtmin < NO_TICKET)
            drelease = was_db & ~dgrant
            djs = _sel_col(st["req_srv"], dj)
            djep = _sel_col(st["req_ep"], dj)
            djseg = _sel_col(st["req_seg"], dj)
            djdur = _tab(self._tk["seg_dur"], self._seg_idx(djs, djep, djseg))
            st["db_free"] = _add_col(st["db_free"], s, 1, drelease)
            st["db_wait_n"] = _add_col(st["db_wait_n"], s, -1, dgrant)
            st["req_ev"] = _set_col(st["req_ev"], dj, EV_SEG_END, dgrant)
            st["req_t"] = _set_col(st["req_t"], dj, now + djdur, dgrant)
            st["req_ticket"] = _set_col(st["req_ticket"], dj, NO_TICKET, dgrant)

        return self._seg_start(st, i, s, ep, seg + 1, now, rng, it, ov_tabs, pred)

    # ------------------------------------------------------------------
    # the kernel
    # ------------------------------------------------------------------

    def _kernel(self, *refs):
        plan = self.plan
        k0_ref, k1_ref, lam_ref, em_ref, ev_ref, ed_ref = refs[:6]
        ntab = len(self._tables)
        self._tk = {
            name: refs[6 + i][:] for i, (name, _) in enumerate(self._tables)
        }
        hist_ref, thr_ref, momf_ref, momi_ref, trunc_ref = refs[6 + ntab :]
        sblk = k0_ref.shape[0]
        pool = self.pool
        ns = plan.n_servers
        el = max(plan.n_lb_edges, 1)
        horizon = np.float32(plan.horizon)

        rng = _Rng(k0_ref[:], k1_ref[:])
        lam_tab = lam_ref[:]
        ov_tabs = (em_ref[:], ev_ref[:], ed_ref[:])

        def col(v, dtype=jnp.float32):
            return jnp.full((sblk, 1), v, dtype)

        st = {
            "req_t": jnp.full((sblk, pool), np.float32(INF), jnp.float32),
            "req_ev": jnp.zeros((sblk, pool), jnp.int32),
            "req_srv": jnp.zeros((sblk, pool), jnp.int32),
            "req_ep": jnp.zeros((sblk, pool), jnp.int32),
            "req_seg": jnp.zeros((sblk, pool), jnp.int32),
            "req_ram": jnp.zeros((sblk, pool), jnp.float32),
            "req_ticket": jnp.full((sblk, pool), NO_TICKET, jnp.int32),
            "req_start": jnp.zeros((sblk, pool), jnp.float32),
            "req_lbslot": jnp.full((sblk, pool), -1, jnp.int32),
            "cores_free": jnp.broadcast_to(
                self._tk["server_cores"], (sblk, ns),
            ),
            "ram_free": jnp.broadcast_to(self._tk["server_ram"], (sblk, ns)),
            "cpu_ticket": jnp.zeros((sblk, ns), jnp.int32),
            "ram_ticket": jnp.zeros((sblk, ns), jnp.int32),
            "cpu_wait_n": jnp.zeros((sblk, ns), jnp.int32),
            "ram_wait_n": jnp.zeros((sblk, ns), jnp.int32),
            "lb_order": jax.lax.broadcasted_iota(jnp.int32, (sblk, el), 1),
            "lb_len": col(plan.n_lb_edges, jnp.int32),
            "lb_conn": jnp.zeros((sblk, el), jnp.int32),
            "smp_now": jnp.zeros((sblk, self._n_gen), jnp.float32),
            "smp_window_end": jnp.zeros((sblk, self._n_gen), jnp.float32),
            "widx": jnp.full((sblk, self._n_gen), -1, jnp.int32),
            "next_arrival": jnp.zeros((sblk, self._n_gen), jnp.float32),
            "tl_ptr": col(0, jnp.int32),
            "hist": jnp.zeros((sblk, self.n_hist_bins), jnp.int32),
            "thr": jnp.zeros((sblk, self.n_thr), jnp.int32),
            "lat_count": col(0, jnp.int32),
            "lat_sum": col(0.0),
            "lat_sumsq": col(0.0),
            "lat_min": col(INF),
            "lat_max": col(0.0),
            "n_generated": col(0, jnp.int32),
            "n_dropped": col(0, jnp.int32),
            "n_overflow": col(0, jnp.int32),
            "llm_sum": col(0.0),
            "llm_sumsq": col(0.0),
            "n_rejected": col(0, jnp.int32),
        }
        if self._has_conn:
            st["srv_conn"] = jnp.zeros((sblk, ns), jnp.int32)
        if self._has_rl:
            st["rl_tokens"] = jnp.broadcast_to(
                self._tk["rate_burst"], (sblk, ns),
            ).astype(jnp.float32)
            st["rl_last"] = jnp.zeros((sblk, ns), jnp.float32)
        if self._has_timeout:
            st["req_wait_t"] = jnp.zeros((sblk, pool), jnp.float32)
        if self._has_breaker:
            st["cb_state"] = jnp.zeros((sblk, el), jnp.int32)
            st["cb_open_until"] = jnp.zeros((sblk, el), jnp.float32)
            st["cb_consec"] = jnp.zeros((sblk, el), jnp.int32)
            st["cb_probes_out"] = jnp.zeros((sblk, el), jnp.int32)
            st["cb_probe_ok"] = jnp.zeros((sblk, el), jnp.int32)
            st["req_cbslot"] = jnp.full((sblk, pool), -1, jnp.int32)
            st["req_probe"] = jnp.zeros((sblk, pool), jnp.int32)
        if self._has_llm:
            st["req_llm"] = jnp.zeros((sblk, pool), jnp.float32)
        if self._has_db:
            st["db_free"] = jnp.broadcast_to(self._tk["db_pool"], (sblk, ns))
            st["db_ticket"] = jnp.zeros((sblk, ns), jnp.int32)
            st["db_wait_n"] = jnp.zeros((sblk, ns), jnp.int32)
        for gi in range(self._n_gen):
            st = self._advance_arrival(
                st, rng, jnp.int32(0), lam_tab, col(True, jnp.bool_), gen=gi,
            )
        # cached pool argmin (the single pool scan per iteration, refreshed
        # at the end of each body after every branch — same discipline as
        # engine.py's _refresh_pool_min)
        st["nxt_i"], st["nxt_t"] = _argmin_row(st["req_t"])

        keys = sorted(st.keys())
        ntl = len(plan.timeline_times)

        def next_times(sd):
            if ntl > 0:
                ptr = jnp.clip(sd["tl_ptr"], 0, ntl - 1)
                t_tl = jnp.where(
                    sd["tl_ptr"] < ntl,
                    _tab(self._tk["tl_times"], ptr),
                    np.float32(INF),
                )
            else:
                t_tl = jnp.full_like(sd["nxt_t"], np.float32(INF))
            t_arr = jnp.min(sd["next_arrival"], 1, keepdims=True)
            return sd["nxt_i"], sd["nxt_t"], t_arr, t_tl

        def cond(carry):
            it = carry[0]
            sd = dict(zip(keys, carry[1:]))
            _i, t_pool, t_arr, t_tl = next_times(sd)
            t_min = jnp.minimum(jnp.minimum(t_pool, t_arr), t_tl)
            live = jnp.sum((t_min < horizon).astype(jnp.int32)) > 0
            return live & (it < plan.max_iterations)

        def body(carry):
            it = carry[0]
            sd = dict(zip(keys, carry[1:]))
            i, t_pool, t_arr, t_tl = next_times(sd)
            now = jnp.minimum(jnp.minimum(t_pool, t_arr), t_tl)
            in_h = now < horizon
            is_tl = in_h & (t_tl <= now)
            is_pool = in_h & ~is_tl & (t_pool <= now)
            is_arr = in_h & ~is_tl & ~is_pool

            sd = self._timeline_branch(sd, is_tl)
            sd = self._spawn_branch(sd, now, rng, it, lam_tab, ov_tabs, is_arr)

            ev = _sel_col(sd["req_ev"], i)
            sd = self._arrive_lb_branch(
                sd, i, now, rng, it, ov_tabs, is_pool & (ev == EV_ARRIVE_LB),
            )
            sd = self._arrive_srv_branch(
                sd, i, now, rng, it, ov_tabs, is_pool & (ev == EV_ARRIVE_SRV),
            )
            sd = self._resume_branch(
                sd, i, now, rng, it, ov_tabs, is_pool & (ev == EV_RESUME),
            )
            sd = self._seg_end_branch(
                sd, i, now, rng, it, ov_tabs, is_pool & (ev == EV_SEG_END),
            )
            if self._has_timeout:
                sd = self._abandon_branch(
                    sd, i, now, rng, it, ov_tabs, is_pool & (ev == EV_ABANDON),
                )
            sd["nxt_i"], sd["nxt_t"] = _argmin_row(sd["req_t"])
            return (it + 1, *[sd[k] for k in keys])

        final = jax.lax.while_loop(cond, body, (jnp.int32(1), *[st[k] for k in keys]))
        it_end = final[0]
        sd = dict(zip(keys, final[1:]))

        _i, t_pool, t_arr, t_tl = next_times(sd)
        t_min = jnp.minimum(jnp.minimum(t_pool, t_arr), t_tl)
        truncated = (it_end >= plan.max_iterations) & (t_min < horizon)

        hist_ref[:] = sd["hist"]
        thr_ref[:] = sd["thr"]
        momf_ref[:] = jnp.concatenate(
            [
                sd["lat_sum"],
                sd["lat_sumsq"],
                sd["lat_min"],
                sd["lat_max"],
                sd["llm_sum"],
                sd["llm_sumsq"],
            ],
            axis=1,
        )
        momi_ref[:] = jnp.concatenate(
            [
                sd["lat_count"],
                sd["n_generated"],
                sd["n_dropped"],
                sd["n_overflow"],
                sd["n_rejected"],
            ],
            axis=1,
        )
        trunc_ref[:] = truncated.astype(jnp.int32)

    # ------------------------------------------------------------------
    # host-side entry
    # ------------------------------------------------------------------

    def _lam_table(self, keys, user_mean, req_rate):
        """Per-(scenario, window) arrival rates, drawn with jax.random outside
        the kernel (identical distribution to `engine.py:246-255`).

        Multi-generator plans concatenate one block per stream along the
        window axis (`self._gen_lam_off` / `self._gen_nw`); the workload
        fields are then (G,) or (S, G)."""
        plan = self.plan
        s = keys.shape[0]

        def block(gen, nw, user_var):
            def one(key, um, rr):
                kd = jax.random.fold_in(key, 0x77AB + gen)
                if user_var < 0:
                    users = jax.random.poisson(
                        as_threefry(kd), jnp.maximum(um, TINY), (nw,),
                    ).astype(jnp.float32)
                else:
                    z = jax.random.normal(kd, (nw,))
                    users = jnp.maximum(0.0, um + user_var * z)
                return users * rr

            return one

        if self._n_gen > 1:
            um_all = jnp.asarray(user_mean, jnp.float32)
            rr_all = jnp.asarray(req_rate, jnp.float32)
            blocks = []
            for gi in range(self._n_gen):
                um = jnp.broadcast_to(um_all[..., gi], (s,))
                rr = jnp.broadcast_to(rr_all[..., gi], (s,))
                blocks.append(
                    jax.vmap(
                        block(gi, self._gen_nw[gi], float(plan.gen_user_var[gi])),
                    )(keys, um, rr),
                )
            return jnp.concatenate(blocks, axis=1)
        um = jnp.broadcast_to(jnp.asarray(user_mean, jnp.float32), (s,))
        rr = jnp.broadcast_to(jnp.asarray(req_rate, jnp.float32), (s,))
        return jax.vmap(block(0, self.n_windows, plan.user_var))(keys, um, rr)

    def run_batch(
        self,
        keys: jnp.ndarray,
        overrides: ScenarioOverrides | None = None,
        *,
        antithetic: bool = False,
    ) -> PallasState:
        # accepted for sweep-dispatch signature compatibility only: the
        # constructor already refuses VR coupling, so this can never be
        # reached with True (SweepRunner raises at construction)
        if antithetic:  # pragma: no cover - double fence
            msg = "the Pallas kernel does not trace antithetic draw variants"
            raise ValueError(msg)
        args, sig, s = self._prepare(keys, overrides)
        call = self._get_call(sig)
        try:
            hist, thr, momf, momi, trunc = call(*args)
        finally:
            # _kernel binds the traced table refs to self._tk for its
            # helpers; drop them even when tracing/compilation fails so no
            # tracer outlives its trace
            self._tk = {}
        hist = np.asarray(hist[:s])
        thr = np.asarray(thr[:s])
        momf = np.asarray(momf[:s])
        momi = np.asarray(momi[:s])
        trunc = np.asarray(trunc[:s, 0]).astype(bool)
        return PallasState(
            hist=hist,
            lat_count=momi[:, 0],
            lat_sum=momf[:, 0],
            lat_sumsq=momf[:, 1],
            lat_min=momf[:, 2],
            lat_max=momf[:, 3],
            thr=thr,
            clock=np.zeros((1, 2), np.float32),
            clock_n=momi[:, 0],
            n_generated=momi[:, 1],
            n_dropped=momi[:, 2],
            n_overflow=momi[:, 3],
            truncated=trunc,
            llm_sum=momf[:, 4],
            llm_sumsq=momf[:, 5],
            n_rejected=momi[:, 4],
        )

    def lower_tpu(self, keys: jnp.ndarray):
        """Cross-platform-lower the compiled-mode kernel for the TPU target
        (works from the CPU backend — Mosaic IR is embedded at lowering).
        Returns the ``Lowered`` object; used by scripts/pallas_keepcut.py
        to bound the Mosaic half of the compile risk without hardware."""
        args, sig, _ = self._prepare(keys, None, force_interpret=False)
        call = self._get_call(sig)
        try:
            return call.trace(*args).lower(lowering_platforms=("tpu",))
        finally:
            self._tk = {}

    def compile_tpu(self, keys: jnp.ndarray):
        """REAL chipless TPU compile via a compile-only topology client.

        Runs the full Mosaic pipeline including the vector-layout passes
        that ``lower_tpu``'s conversion gate cannot reach (round 5: those
        passes rejected a kernel the lowering gate passed).  Requires local
        libtpu (``utils.tpu_aot.aot_available``); returns the ``Compiled``.
        """
        from asyncflow_tpu.utils.tpu_aot import aot_compile

        args, sig, _ = self._prepare(keys, None, force_interpret=False)
        call = self._get_call(sig)
        try:
            return aot_compile(call, *args)
        finally:
            self._tk = {}

    def _prepare(
        self,
        keys: jnp.ndarray,
        overrides: ScenarioOverrides | None = None,
        *,
        force_interpret: bool | None = None,
    ):
        """(call args, program signature, requested batch size)."""
        ov = overrides if overrides is not None else base_overrides(self.plan)
        s = keys.shape[0]
        ne = self.plan.n_edges
        n_dev = len(self.mesh.devices.flat) if self.mesh is not None else 1
        # block from the per-device shard, not the global batch, so a small
        # sharded chunk doesn't pad every device up to a full global block
        blk = min(self.block, max(-(-s // n_dev), 1))
        # pad so every device's shard is a whole number of blocks; padded
        # rows carry lam=0 and are inert
        pad = (-s) % (blk * n_dev)
        sp = s + pad

        key_data = jax.random.key_data(keys) if jnp.issubdtype(
            keys.dtype, jax.dtypes.prng_key,
        ) else keys
        k0 = jnp.pad(key_data[:, 0].astype(jnp.uint32), (0, pad))[:, None]
        k1 = jnp.pad(key_data[:, 1].astype(jnp.uint32), (0, pad))[:, None]

        lam = self._lam_table(keys, ov.user_mean, ov.req_rate)
        lam = jnp.pad(lam, ((0, pad), (0, 0)))  # padded rows: lam 0 => inert

        def expand(field):
            arr = jnp.asarray(field, jnp.float32)
            if arr.ndim == 1:
                arr = jnp.broadcast_to(arr[None, :], (s, ne))
            return jnp.pad(arr, ((0, pad), (0, 0)))

        em = expand(ov.edge_mean)
        evr = expand(ov.edge_var)
        ed = expand(ov.edge_dropout)

        interpret = (
            force_interpret
            if force_interpret is not None
            else (
                self.interpret
                if self.interpret is not None
                else jax.default_backend() != "tpu"
            )
        )
        rows = sp // n_dev  # per-device rows (== sp when unsharded)
        nblk = rows // blk
        sig = (blk, nblk, interpret, n_dev)
        args = (
            k0,
            k1,
            lam,
            em,
            evr,
            ed,
            *[jnp.asarray(arr) for _, arr in self._tables],
        )
        return args, sig, s

    def _get_call(self, sig):
        """Build (once) and return the jitted pallas_call for ``sig``."""
        from jax.experimental import pallas as pl

        blk, nblk, interpret, n_dev = sig
        ne = self.plan.n_edges
        rows = blk * nblk
        if sig not in self._compiled:
            grid = (nblk,)

            def row_spec(width):
                return pl.BlockSpec((blk, width), lambda b: (b, 0))

            def tab_spec(width):
                return pl.BlockSpec((1, width), lambda b: (0, 0))

            call = pl.pallas_call(
                self._kernel,
                grid=grid,
                in_specs=[
                    row_spec(1),
                    row_spec(1),
                    row_spec(self.n_windows),
                    row_spec(ne),
                    row_spec(ne),
                    row_spec(ne),
                    *[tab_spec(arr.shape[1]) for _, arr in self._tables],
                ],
                out_specs=[
                    row_spec(self.n_hist_bins),
                    row_spec(self.n_thr),
                    row_spec(6),
                    row_spec(5),
                    row_spec(1),
                ],
                out_shape=[
                    jax.ShapeDtypeStruct((rows, self.n_hist_bins), jnp.int32),
                    jax.ShapeDtypeStruct((rows, self.n_thr), jnp.int32),
                    jax.ShapeDtypeStruct((rows, 6), jnp.float32),
                    jax.ShapeDtypeStruct((rows, 5), jnp.int32),
                    jax.ShapeDtypeStruct((rows, 1), jnp.int32),
                ],
                interpret=interpret,
            )
            if self.mesh is not None:
                from jax.sharding import PartitionSpec

                from asyncflow_tpu.parallel.mesh import SCENARIO_AXIS

                row_p = PartitionSpec(SCENARIO_AXIS, None)
                tab_p = PartitionSpec(None, None)
                ntab = len(self._tables)
                call = jax.shard_map(
                    call,
                    mesh=self.mesh,
                    in_specs=(row_p,) * 6 + (tab_p,) * ntab,
                    out_specs=(row_p,) * 5,
                    check_vma=False,
                )
            from asyncflow_tpu.observability.telemetry import instrument_jit

            self._compiled[sig] = instrument_jit(
                jax.jit(call),
                engine="pallas",
                variant="interpret" if interpret else "mosaic",
                block=blk,
                blocks=nblk,
                n_dev=n_dev,
            )
        return self._compiled[sig]
