"""Device-side parameter and state containers for the batched JAX engine."""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from asyncflow_tpu.compiler.plan import StaticPlan

# plain Python scalars: creating jnp values at import time would initialise
# the accelerator backend before users can select a platform
INF = 1e30
NO_TICKET = 2**30

# request-slot event codes
EV_IDLE = 0
EV_ARRIVE_LB = 1
EV_ARRIVE_SRV = 2
EV_SEG_END = 3
EV_RESUME = 4  # RAM granted; start endpoint segments at time t
EV_WAIT_CPU = 5
EV_WAIT_RAM = 6
EV_WAIT_DB = 7  # parked in the server's DB connection-pool FIFO
EV_ABANDON = 8  # granted the core past its dequeue deadline: abandon now
EV_RETRY = 9  # client backoff park: re-issue down the entry chain at t
# final client delivery as a real event (retry plans only): the client
# deadline must race the last transit, exactly like the oracle's heap
EV_ARRIVE_CLIENT = 10
# LLM serving (asyncflow_tpu/serving): passive park in the continuous-
# batching admission FIFO (req_t = INF until the grant cascade wakes it)
# and the grant event itself — scheduled at `now` by the cascade, its
# dispatch starts the prefill sleep (EV_RESUME is hard-wired to the RAM
# grant + segment-0 entry, so admission grants need their own code)
EV_WAIT_SV = 11
EV_SV_GRANT = 12


class PlanParams(NamedTuple):
    """Scenario-invariant plan arrays resident on device."""

    edge_dist: jnp.ndarray
    edge_mean: jnp.ndarray
    edge_var: jnp.ndarray
    edge_dropout: jnp.ndarray
    server_cores: jnp.ndarray
    server_ram: jnp.ndarray
    server_queue_cap: jnp.ndarray  # (NS,) i32 ready-queue cap (-1 unbounded)
    server_conn_cap: jnp.ndarray  # (NS,) i32 socket capacity (-1 unbounded)
    server_rate_limit: jnp.ndarray  # (NS,) f32 token refill rps (-1 none)
    server_rate_burst: jnp.ndarray  # (NS,) i32 token-bucket capacity
    server_queue_timeout: jnp.ndarray  # (NS,) f32 dequeue deadline (-1 none)
    n_endpoints: jnp.ndarray
    seg_kind: jnp.ndarray
    seg_dur: jnp.ndarray
    seg_hit_prob: jnp.ndarray  # SEG_CACHE mixtures (0 = deterministic)
    seg_miss_dur: jnp.ndarray
    seg_llm_tokens: jnp.ndarray  # SEG_LLM Poisson token mean (0 = none)
    seg_llm_tpt: jnp.ndarray  # SEG_LLM decode seconds per token
    seg_llm_cost: jnp.ndarray  # SEG_LLM cost units per token
    endpoint_ram: jnp.ndarray
    endpoint_cum: jnp.ndarray  # (NS, NEP) cumulative selection probs
    exit_edge: jnp.ndarray
    exit_kind: jnp.ndarray
    exit_target: jnp.ndarray
    lb_edge_index: jnp.ndarray
    lb_target: jnp.ndarray
    spike_times: jnp.ndarray
    spike_values: jnp.ndarray
    timeline_times: jnp.ndarray
    timeline_down: jnp.ndarray
    timeline_slot: jnp.ndarray
    user_mean: jnp.ndarray  # scalar, overridable per scenario
    user_var: jnp.ndarray
    req_rate: jnp.ndarray  # requests / user / second
    # NOTE: the fault tables (times AND value rows) all ride the
    # overrides now — chaos campaigns batch a sampled (S, K, ...) table
    # per scenario, so no fault state is plan-static here.
    # brownout degraded-profile factors (the queue THRESHOLD rides the
    # overrides so brownout A/B sweeps can batch per scenario)
    server_brownout_cpu: jnp.ndarray  # (NS,) f32 CPU-duration scale
    server_brownout_ram: jnp.ndarray  # (NS,) f32 RAM-demand scale
    # LLM serving tables (SEG_PREFILL/SEG_DECODE dynamics; (0,0,0) / (0,)
    # placeholders unless the plan has llm_serve steps).  The token-budget
    # axis (serve_tokens) rides the OVERRIDES so KV-pressure sweeps batch
    # per scenario; slots and the eviction cap stay plan-static.
    # (None defaults, not jnp placeholders: creating jnp values at import
    # time would initialise the backend — see the module header)
    sv_tin_mean: jnp.ndarray | None = None  # (NS, NEP, NSEG+1) f32
    sv_tin_var: jnp.ndarray | None = None
    sv_tout_mean: jnp.ndarray | None = None
    sv_tout_var: jnp.ndarray | None = None
    sv_prefill_tpt: jnp.ndarray | None = None  # s per prompt token
    sv_prefill_base: jnp.ndarray | None = None  # s fixed prefill cost
    sv_rate_mean: jnp.ndarray | None = None  # decode tokens/s
    sv_rate_var: jnp.ndarray | None = None
    sv_cost: jnp.ndarray | None = None  # cost units per output token
    serve_slots: jnp.ndarray | None = None  # (NS,) i32 (-1 unlimited)
    serve_evict_max: jnp.ndarray | None = None  # (NS,) i32
    # trace-replay arrival table (None unless the plan replays a log)
    replay_times: jnp.ndarray | None = None  # (R,) f32 sorted spawn times
    replay_tok_in: jnp.ndarray | None = None  # (R,) f32 (-1 = draw)
    replay_tok_out: jnp.ndarray | None = None  # (R,) f32 (-1 = draw)


def params_from_plan(plan: StaticPlan) -> PlanParams:
    """Upload the per-scenario-invariant arrays."""
    return PlanParams(
        edge_dist=jnp.asarray(plan.edge_dist),
        edge_mean=jnp.asarray(plan.edge_mean),
        edge_var=jnp.asarray(plan.edge_var),
        edge_dropout=jnp.asarray(plan.edge_dropout),
        server_cores=jnp.asarray(plan.server_cores),
        server_ram=jnp.asarray(plan.server_ram),
        # size-0 arrays are normalized to (-1,)*NS by StaticPlan.__post_init__
        server_queue_cap=jnp.asarray(plan.server_queue_cap),
        server_conn_cap=jnp.asarray(plan.server_conn_cap),
        server_rate_limit=jnp.asarray(plan.server_rate_limit),
        server_rate_burst=jnp.asarray(plan.server_rate_burst),
        server_queue_timeout=jnp.asarray(plan.server_queue_timeout),
        n_endpoints=jnp.asarray(plan.n_endpoints),
        seg_kind=jnp.asarray(plan.seg_kind),
        seg_dur=jnp.asarray(plan.seg_dur),
        seg_hit_prob=jnp.asarray(plan.seg_hit_prob),
        seg_miss_dur=jnp.asarray(plan.seg_miss_dur),
        seg_llm_tokens=jnp.asarray(plan.seg_llm_tokens),
        seg_llm_tpt=jnp.asarray(plan.seg_llm_tpt),
        seg_llm_cost=jnp.asarray(plan.seg_llm_cost),
        endpoint_ram=jnp.asarray(plan.endpoint_ram),
        endpoint_cum=jnp.asarray(plan.endpoint_cum),
        exit_edge=jnp.asarray(plan.exit_edge),
        exit_kind=jnp.asarray(plan.exit_kind),
        exit_target=jnp.asarray(plan.exit_target),
        lb_edge_index=jnp.asarray(plan.lb_edge_index),
        lb_target=jnp.asarray(plan.lb_target),
        spike_times=jnp.asarray(plan.spike_times),
        spike_values=jnp.asarray(plan.spike_values),
        timeline_times=jnp.asarray(plan.timeline_times),
        timeline_down=jnp.asarray(plan.timeline_down),
        timeline_slot=jnp.asarray(plan.timeline_slot),
        user_mean=jnp.float32(plan.user_mean),
        user_var=jnp.float32(plan.user_var),
        req_rate=jnp.float32(plan.req_per_user_per_sec),
        server_brownout_cpu=jnp.asarray(plan.server_brownout_cpu),
        server_brownout_ram=jnp.asarray(plan.server_brownout_ram),
        **(
            {
                "sv_tin_mean": jnp.asarray(plan.sv_tin_mean),
                "sv_tin_var": jnp.asarray(plan.sv_tin_var),
                "sv_tout_mean": jnp.asarray(plan.sv_tout_mean),
                "sv_tout_var": jnp.asarray(plan.sv_tout_var),
                "sv_prefill_tpt": jnp.asarray(plan.sv_prefill_tpt),
                "sv_prefill_base": jnp.asarray(plan.sv_prefill_base),
                "sv_rate_mean": jnp.asarray(plan.sv_rate_mean),
                "sv_rate_var": jnp.asarray(plan.sv_rate_var),
                "sv_cost": jnp.asarray(plan.sv_cost),
                "serve_slots": jnp.asarray(plan.serve_slots),
                "serve_evict_max": jnp.asarray(plan.serve_evict_max),
            }
            if plan.has_serving
            else {}
        ),
        **(
            {
                "replay_times": jnp.asarray(plan.replay_times, jnp.float32),
                "replay_tok_in": jnp.asarray(plan.replay_tok_in),
                "replay_tok_out": jnp.asarray(plan.replay_tok_out),
            }
            if plan.has_replay
            else {}
        ),
    )


class EngineState(NamedTuple):
    """Loop-carried state of one scenario (vmapped over the batch axis)."""

    # request pool
    req_t: jnp.ndarray  # (P,) f32
    req_ev: jnp.ndarray  # (P,) i32
    req_srv: jnp.ndarray  # (P,) i32
    req_ep: jnp.ndarray  # (P,) i32
    req_seg: jnp.ndarray  # (P,) i32
    req_ram: jnp.ndarray  # (P,) f32
    req_ticket: jnp.ndarray  # (P,) i32
    req_start: jnp.ndarray  # (P,) f32
    req_lbslot: jnp.ndarray  # (P,) i32
    # servers
    cores_free: jnp.ndarray  # (NS,) i32
    ram_free: jnp.ndarray  # (NS,) f32
    cpu_ticket: jnp.ndarray  # (NS,) i32
    ram_ticket: jnp.ndarray  # (NS,) i32
    cpu_wait_n: jnp.ndarray  # (NS,) i32: live CPU waiter counts
    ram_wait_n: jnp.ndarray  # (NS,) i32: live RAM waiter counts
    db_free: jnp.ndarray  # (NS,) i32: free DB connections (big = unlimited)
    srv_conn: jnp.ndarray  # (NS,) i32: accepted arrivals currently resident
    db_ticket: jnp.ndarray  # (NS,) i32
    db_wait_n: jnp.ndarray  # (NS,) i32: live DB-pool waiter counts
    # load balancer
    lb_order: jnp.ndarray  # (EL,) i32
    lb_len: jnp.ndarray  # scalar i32
    lb_conn: jnp.ndarray  # (EL,) i32
    # arrival sampler
    smp_now: jnp.ndarray  # scalar f32 (sampler clock)
    smp_window_end: jnp.ndarray
    smp_lam: jnp.ndarray
    next_arrival: jnp.ndarray  # scalar f32 (simulation clock)
    # milestone-5 overload controls (size (1,) when the plan has none)
    req_wait_t: jnp.ndarray  # (P,) f32: ready-queue park time (deadlines)
    req_cbslot: jnp.ndarray  # (P,) i32: breaker slot awaiting a report
    req_probe: jnp.ndarray  # (P,) i32: 1 while a half-open breaker probe
    rl_tokens: jnp.ndarray  # (NS,) f32: token-bucket fill
    rl_last: jnp.ndarray  # (NS,) f32: last refill timestamp
    cb_state: jnp.ndarray  # (EL,) i32: 0 closed / 1 open / 2 half-open
    cb_consec: jnp.ndarray  # (EL,) i32: consecutive failures (closed)
    cb_open_until: jnp.ndarray  # (EL,) f32: cooldown end (open)
    cb_probes_out: jnp.ndarray  # (EL,) i32: outstanding half-open probes
    cb_probe_ok: jnp.ndarray  # (EL,) i32: successful probes this round
    # per-request hop rings + completed-trace store (round 4, VERDICT #8;
    # size (1, 1) unless collect_traces — mirrors the reference's
    # rqs_state.Hop records, flushed at completion like the oracle)
    req_hops: jnp.ndarray  # (P, H) i32 hop codes
    req_hop_t: jnp.ndarray  # (P, H) f32 hop timestamps
    req_hop_n: jnp.ndarray  # (P,) i32 hops recorded
    tr_code: jnp.ndarray  # (maxN, H) i32 completed traces
    tr_t: jnp.ndarray  # (maxN, H) f32
    tr_n: jnp.ndarray  # (maxN,) i32
    # LLM call dynamics (size (1,) unless the plan has SEG_LLM segments)
    req_llm: jnp.ndarray  # (P,) f32 accumulated cost of the in-flight request
    llm_sum: jnp.ndarray  # scalar f32: total cost of completed requests
    llm_sumsq: jnp.ndarray  # scalar f32
    llm_store: jnp.ndarray  # (maxN,) f32 per-completion cost (clock-aligned)
    # client retry/timeout machinery (size (1,) unless the plan has a
    # retry policy).  req_deadline is the ABSOLUTE client timeout of the
    # slot's in-flight attempt (INF once orphaned / parked / idle);
    # req_attempt the attempt number of the current issue (spawn = 1);
    # req_orphan = 1 after the client abandoned the in-flight attempt
    # (the request keeps consuming server resources but its completion
    # no longer counts).
    req_deadline: jnp.ndarray  # (P,) f32
    req_attempt: jnp.ndarray  # (P,) i32
    req_orphan: jnp.ndarray  # (P,) i32
    rb_tokens: jnp.ndarray  # scalar f32: retry-budget bucket fill
    rb_last: jnp.ndarray  # scalar f32: last budget refill timestamp
    att_hist: jnp.ndarray  # (A,) i32: attempts used per finished request
    n_timed_out: jnp.ndarray  # scalar i32: client timeouts fired
    n_retries: jnp.ndarray  # scalar i32: re-issues performed
    n_budget_exhausted: jnp.ndarray  # scalar i32: retries denied by budget
    # outage timeline cursor
    tl_ptr: jnp.ndarray  # scalar i32
    # cached pool argmin (computed once at the end of each loop body so the
    # loop condition reads a scalar instead of re-scanning the pool)
    nxt_i: jnp.ndarray  # scalar i32: index of the pool's next event
    nxt_t: jnp.ndarray  # scalar f32: its time (== min(req_t))
    # rng
    key: jnp.ndarray
    it: jnp.ndarray  # scalar i32 iteration counter (rng stream + safety)
    # metrics
    hist: jnp.ndarray  # (B,) i32
    lat_count: jnp.ndarray
    lat_sum: jnp.ndarray
    lat_sumsq: jnp.ndarray
    lat_min: jnp.ndarray
    lat_max: jnp.ndarray
    thr: jnp.ndarray  # (TH,) i32
    gauge: jnp.ndarray  # (n_samples + 2, NG) f32 deltas (or (0,0))
    clock: jnp.ndarray  # (maxN, 2) f32 (or (0, 2))
    clock_n: jnp.ndarray
    n_generated: jnp.ndarray
    n_dropped: jnp.ndarray
    n_overflow: jnp.ndarray
    n_rejected: jnp.ndarray  # requests shed by overload policies
    n_dark_lost: jnp.ndarray  # scalar i32: arrivals refused by a dark
    # (fault-window) server — the availability scorecard numerator
    # CRN (common-random-numbers) keying state — size (1,) placeholders
    # unless the engine was built with ``crn=True``.  ``req_seq`` is the
    # slot's spawn sequence number (the arrival counter at spawn),
    # ``req_draws`` its per-request event-draw counter, ``arr_ctr`` the
    # scenario's arrival counter; together they re-key every draw by
    # REQUEST identity instead of global iteration so paired A/B sweeps
    # share substreams (docs/guides/mc-inference.md).
    req_seq: jnp.ndarray  # (P,) i32 (or (1,))
    req_draws: jnp.ndarray  # (P,) i32 (or (1,))
    arr_ctr: jnp.ndarray  # scalar i32
    # flight recorder (observability/simtrace.py) — size (1, 1)/(1,)
    # placeholders unless the engine was built with ``trace=TraceConfig``.
    # The first K spawned logical requests each own one ring row of
    # ``event_slots`` (code, node, t) entries; ``fr_n`` keeps counting past
    # the budget so truncation is explicit.  ``req_fr`` maps a pool slot to
    # its ring row (-1 = untraced / orphaned).  ``bk_*`` is the scenario's
    # circuit-breaker state-transition ring.
    req_fr: jnp.ndarray  # (P,) i32 ring row or -1
    fr_ev: jnp.ndarray  # (K, S) i32 lifecycle codes (simtrace.FR_*)
    fr_node: jnp.ndarray  # (K, S) i32 component index / attempt number
    fr_t: jnp.ndarray  # (K, S) f32 sim timestamps
    fr_n: jnp.ndarray  # (K,) i32 events recorded (may exceed S)
    bk_t: jnp.ndarray  # (C,) f32 breaker transition times
    bk_slot: jnp.ndarray  # (C,) i32 LB rotation slot
    bk_state: jnp.ndarray  # (C,) i32 new state (0/1/2)
    bk_n: jnp.ndarray  # scalar i32
    # latency attribution plane (observability/blame.py) — size (1,)/(1, 1)
    # placeholders unless the engine was built with ``blame=True``.  Each
    # pool slot carries an open attribution cursor: ``bl_t`` the time up to
    # which the slot's in-flight attempt is fully attributed, ``bl_cell``
    # the (component, phase) cell accruing since then, ``req_bl`` the
    # attempt's per-cell seconds so far.  Completion scatters the row into
    # ``bl_grid`` at the attempt's coarse latency bin and adds the
    # end-to-end latency to ``bl_lat`` (the conservation denominator).
    req_bl: jnp.ndarray  # (P, n_cells) f32 per-attempt phase seconds
    bl_t: jnp.ndarray  # (P,) f32 attribution cursor
    bl_cell: jnp.ndarray  # (P,) i32 open cell
    bl_grid: jnp.ndarray  # (n_cells, B) f32 pooled seconds by latency bin
    bl_lat: jnp.ndarray  # (B,) f32 total latency seconds by latency bin
    bl_store: jnp.ndarray  # (N, n_cells) f32 per-request rows (clock-aligned)
    # hedged-request machinery (size (1,) unless the plan has a hedge
    # policy).  ``req_prime`` is the slot index of the logical request's
    # ANCHOR (the primary attempt's spawn slot; the primary points at
    # itself and hedge duplicates point at it); the ``hg_*`` arrays are
    # per-anchor logical-request state indexed by that anchor slot:
    # ``hg_t`` the next hedge-timer fire time (INF = none pending),
    # ``hg_n`` duplicates issued so far, ``hg_live`` the live-attempt
    # refcount that keeps the anchor slot reserved until every sibling
    # drained, ``hg_done`` = 1 once some attempt won the race.
    req_prime: jnp.ndarray  # (P,) i32
    req_is_hedge: jnp.ndarray  # (P,) i32
    hg_t: jnp.ndarray  # (P,) f32
    hg_n: jnp.ndarray  # (P,) i32
    hg_live: jnp.ndarray  # (P,) i32
    hg_done: jnp.ndarray  # (P,) i32
    n_hedges: jnp.ndarray  # scalar i32: duplicates issued
    n_hedges_won: jnp.ndarray  # scalar i32: races won by a duplicate
    n_hedges_cancelled: jnp.ndarray  # scalar i32: losers cancelled en route
    # LB health gate (size (1,) unless the plan has a health policy):
    # per-rotation-slot EWMA failure rate and ejection expiry (0 = in the
    # rotation; > 0 = ejected until that time, lazily readmitted at pick)
    hl_h: jnp.ndarray  # (EL,) f32
    hl_until: jnp.ndarray  # (EL,) f32
    n_ejections: jnp.ndarray  # scalar i32
    # server brownout (size (1,) unless the plan has a brownout policy):
    # per-slot degraded flag, latched at endpoint start
    req_degraded: jnp.ndarray  # (P,) i32
    n_degraded: jnp.ndarray  # scalar i32: degraded completions
    # LLM serving (size (1,) placeholders unless the plan has llm_serve
    # steps).  The admission gate is a two-resource FIFO per server —
    # batch slots + resident KV tokens — run with the ticket discipline of
    # the RAM gate; ``req_sv_hold`` is the slot's resident token hold
    # (prompt after prefill admission, prompt+output during decode),
    # released in full at decode end / eviction.  Token draws are per
    # attempt (-1 = not drawn; replay presets stamp them at spawn).
    sv_slots_free: jnp.ndarray  # (NS,) i32
    sv_tokens_free: jnp.ndarray  # (NS,) f32
    sv_ticket: jnp.ndarray  # (NS,) i32 FIFO ticket counter
    sv_wait_n: jnp.ndarray  # (NS,) i32 live admission waiters
    req_tok_in: jnp.ndarray  # (P,) f32 prompt tokens (-1 undrawn)
    req_tok_out: jnp.ndarray  # (P,) f32 output tokens (-1 undrawn)
    req_sv_evict: jnp.ndarray  # (P,) i32 evictions of this attempt
    req_sv_hold: jnp.ndarray  # (P,) f32 resident KV token hold
    n_prefill_tok: jnp.ndarray  # scalar f32: prompt tokens prefilled
    n_decode_tok: jnp.ndarray  # scalar f32: output tokens decoded
    n_kv_evict: jnp.ndarray  # scalar i32: KV-pressure evictions


class ScenarioOverrides(NamedTuple):
    """Per-scenario parameter overrides for Monte-Carlo sweeps.

    Each field either matches the base plan (scalar broadcast) or carries a
    leading scenario axis.  ``None``-like sentinel is the base value itself.
    """

    edge_mean: jnp.ndarray  # (NE,) or (S, NE)
    edge_var: jnp.ndarray
    edge_dropout: jnp.ndarray
    user_mean: jnp.ndarray  # scalar or (S,)
    req_rate: jnp.ndarray
    # resilience sweep axes: per-scenario fault-window TIMINGS (the value
    # tables stay plan-static in PlanParams) and the client timeout.
    # ``None`` (legacy constructors) means "the base plan's value" —
    # engines normalize through :func:`fill_overrides` before tracing.
    fault_srv_times: jnp.ndarray | None = None  # (K,) or (S, K)
    fault_edge_times: jnp.ndarray | None = None  # (M,) or (S, M)
    retry_timeout: jnp.ndarray | None = None  # scalar or (S,)
    # tail-tolerance sweep axes: the hedge delay (<= 0 disables hedging
    # for that scenario), the brownout ready-queue thresholds (< 0
    # disables), and the health-gate ejection threshold (>= 1 in
    # practice never ejects).  ``None`` = the base plan's value.
    hedge_delay: jnp.ndarray | None = None  # scalar or (S,)
    brownout_q: jnp.ndarray | None = None  # (NS,) or (S, NS)
    health_threshold: jnp.ndarray | None = None  # scalar or (S,)
    # chaos-campaign axes: the fault-table VALUE rows join the overrides
    # (they were PlanParams state before hazards) so sampled campaigns
    # can batch a whole (S, K, ...) window table per scenario, and the
    # hazard intensity knobs become CRN-paired sweep axes.  ``None`` =
    # the base plan's (static) tables / 1.0 scales.
    fault_srv_down: jnp.ndarray | None = None  # (K, NS) or (S, K, NS) i32
    fault_edge_lat: jnp.ndarray | None = None  # (M, NE) or (S, M, NE) f32
    fault_edge_drop: jnp.ndarray | None = None  # (M, NE) or (S, M, NE) f32
    hazard_scale: jnp.ndarray | None = None  # scalar or (S,): divides MTBF
    mttr_scale: jnp.ndarray | None = None  # scalar or (S,): multiplies MTTR
    # serving sweep axes: the per-server resident-token budget (KV
    # pressure; -1 = unlimited) and a scale on the decode rate (capacity
    # what-ifs: faster/slower generation).  ``None`` = the base plan's
    # budget / 1.0 scale.
    serve_tokens: jnp.ndarray | None = None  # (NS,) or (S, NS)
    decode_rate_scale: jnp.ndarray | None = None  # scalar or (S,)


def base_overrides(plan: StaticPlan) -> ScenarioOverrides:
    """Overrides equal to the base plan (no sweep variation).

    On multi-generator plans the workload fields are (G,) vectors — one
    mean/rate per generator — and per-scenario overrides carry (S, G);
    single-generator plans keep the scalar shape.
    """
    if plan.n_generators > 1:
        user_mean = jnp.asarray(plan.gen_user_mean, jnp.float32)
        req_rate = jnp.asarray(plan.gen_rate, jnp.float32)
    else:
        user_mean = jnp.float32(plan.user_mean)
        req_rate = jnp.float32(plan.req_per_user_per_sec)
    return ScenarioOverrides(
        edge_mean=jnp.asarray(plan.edge_mean),
        edge_var=jnp.asarray(plan.edge_var),
        edge_dropout=jnp.asarray(plan.edge_dropout),
        user_mean=user_mean,
        req_rate=req_rate,
        fault_srv_times=jnp.asarray(plan.fault_srv_times),
        fault_edge_times=jnp.asarray(plan.fault_edge_times),
        retry_timeout=jnp.float32(plan.retry_timeout),
        hedge_delay=jnp.float32(plan.hedge_delay),
        brownout_q=jnp.asarray(plan.server_brownout_q),
        health_threshold=jnp.float32(plan.health_threshold),
        fault_srv_down=jnp.asarray(plan.fault_srv_down),
        fault_edge_lat=jnp.asarray(plan.fault_edge_lat),
        fault_edge_drop=jnp.asarray(plan.fault_edge_drop),
        hazard_scale=jnp.float32(1.0),
        mttr_scale=jnp.float32(1.0),
        serve_tokens=jnp.asarray(plan.serve_tokens),
        decode_rate_scale=jnp.float32(1.0),
    )


def fill_overrides(
    ov: ScenarioOverrides,
    base: ScenarioOverrides,
) -> ScenarioOverrides:
    """Replace ``None`` fields (legacy 5-field constructors) with the base
    plan's values so every consumer sees fully-populated overrides."""
    return ScenarioOverrides(
        *[b if o is None else o for o, b in zip(ov, base)],
    )


def hist_edges(n_bins: int) -> np.ndarray:
    """Shared log-spaced latency histogram bin edges (seconds)."""
    return np.logspace(-4, 3, n_bins + 1)
