"""LB rotation primitives shared by the event engine and the scan fast path.

The rotation is a dense prefix of slot ids with an explicit length, mirroring
the reference's ordered mapping: round robin takes the head and moves it to
the tail; an outage removes a slot (shift left); revival reinserts at the
tail (`/root/reference/src/asyncflow/runtime/events/injection.py:201-226`).
All updates are predicated so they compose inside vmapped/scanned code.
"""

from __future__ import annotations

import jax.numpy as jnp


def rotation_remove(rot, length, slot, pred, el: int):
    """Remove ``slot`` from the rotation prefix (no-op when absent/masked)."""
    pos = jnp.arange(el, dtype=jnp.int32)
    hit = jnp.where((rot == slot) & (pos < length), pos, el)
    at = jnp.min(hit).astype(jnp.int32)
    act = pred & (at < el)
    shifted = rot[jnp.minimum(pos + 1, el - 1)]
    return (
        jnp.where((pos >= at) & act, shifted, rot),
        jnp.where(act, length - 1, length),
    )


def rotation_insert(rot, length, slot, pred, el: int):
    """Append ``slot`` at the rotation tail (no-op when present/masked)."""
    pos = jnp.arange(el, dtype=jnp.int32)
    present = jnp.any((rot == slot) & (pos < length))
    act = pred & ~present
    idx = jnp.where(act, jnp.clip(length, 0, el - 1), jnp.int32(el))
    return (
        rot.at[idx].set(slot, mode="drop"),
        jnp.where(act, jnp.minimum(length + 1, el), length),
    )


def rotation_advance(rot, length, pred, el: int):
    """Move the head to the tail (round-robin pick); masked by ``pred``."""
    pos = jnp.arange(el, dtype=jnp.int32)
    rotated = jnp.where(
        pos < length,
        rot[(pos + 1) % jnp.maximum(length, 1)],
        rot,
    )
    return jnp.where(pred, rotated, rot)
