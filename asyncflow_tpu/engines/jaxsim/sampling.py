"""Distribution formulas and metric-bucket helpers shared by both JAX engines.

One home for the per-distribution math keeps the event engine and the scan
fast path from drifting (the reference contract lives here once: uniform
ignores the mean, normal/lognormal use the ``variance`` field as numpy's
scale argument, see ``/root/reference/src/asyncflow/samplers/common_helpers.py``).
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

TINY = 1e-15

# distribution ids (compiler order)
D_UNIFORM, D_POISSON, D_EXPONENTIAL, D_NORMAL, D_LOGNORMAL = range(5)

HIST_LO_S = 1e-4
HIST_HI_S = 1e3


def exponential_from_u(mean, u):
    """Inverse-CDF exponential draw from a uniform."""
    return -mean * jnp.log(jnp.maximum(1.0 - u, TINY))


def truncated_normal(mean, scale, z):
    """Zero-truncated normal; ``scale`` is the reference's variance field."""
    return jnp.maximum(0.0, mean + scale * z)


def lognormal(mean, scale, z):
    """Lognormal with underlying (mean, scale); scale is the variance field."""
    return jnp.exp(mean + scale * z)


def hist_constants(n_bins: int) -> tuple[float, float]:
    """(log-lo, bins-per-log) of the shared latency histogram."""
    lo = float(np.log(HIST_LO_S))
    scale = float(n_bins / (np.log(HIST_HI_S) - np.log(HIST_LO_S)))
    return lo, scale


def latency_bin(latency, lo: float, scale: float, n_bins: int):
    """Log-histogram bin index of a latency value."""
    return jnp.clip(
        ((jnp.log(jnp.maximum(latency, 1e-6)) - lo) * scale).astype(jnp.int32),
        0,
        n_bins - 1,
    )


def sample_bucket(t, period: float, n_samples: int):
    """Sample-tick bucket: a delta at ``t`` affects samples at ticks >= t."""
    b = jnp.ceil(t / period).astype(jnp.int32)
    return jnp.clip(b, 0, n_samples + 1)


# ---------------------------------------------------------------------------
# Variance-reduction hooks (docs/guides/mc-inference.md).
#
# Antithetic sampling is a TRACE-TIME program variant: inside
# :func:`antithetic_trace`, every uniform the engines draw through
# :func:`draw_uniform` is reflected (u -> 1-u) and every standard normal
# through :func:`draw_normal` is negated (z -> -z).  Poisson/counting draws
# are left untouched — an antithetic pair run under the SAME scenario key
# shares its arrival counts exactly and reflects the continuous draws, which
# is a valid (conditional) antithetic coupling for every latency metric.
#
# Outside the context the helpers are literally ``jax.random.uniform`` /
# ``jax.random.normal``: streams are bit-identical to a build without the
# hook.  Callers that compile under the flag must (a) key their jit cache on
# :func:`antithetic_active` and (b) hold the context across the *call*, not
# just the first trace, so shape-driven retraces can never silently lose the
# reflection (see ``FastEngine.run_batch`` / ``Engine.run_batch``).
# ---------------------------------------------------------------------------

_ANTITHETIC = False


def antithetic_active() -> bool:
    """Is the current trace an antithetic (reflected-draw) program?"""
    return _ANTITHETIC


@contextlib.contextmanager
def antithetic_trace():
    """Trace engine programs with reflected uniform/normal draws."""
    global _ANTITHETIC
    prev = _ANTITHETIC
    _ANTITHETIC = True
    try:
        yield
    finally:
        _ANTITHETIC = prev


def draw_uniform(key, shape=(), **kw):
    """``jax.random.uniform`` that reflects (u -> 1-u) in antithetic traces.

    The reflection preserves U(0,1) exactly (including the half-open
    endpoint convention up to float rounding), so every inverse-CDF
    transform downstream keeps its law while becoming monotonically
    anti-correlated with its partner draw.
    """
    import jax

    u = jax.random.uniform(key, shape, **kw)
    return (1.0 - u) if _ANTITHETIC else u


def draw_normal(key, shape=(), **kw):
    """``jax.random.normal`` that negates (z -> -z) in antithetic traces."""
    import jax

    z = jax.random.normal(key, shape, **kw)
    return (-z) if _ANTITHETIC else z


def as_threefry(key):
    """A threefry-typed view of any PRNG key (raw or typed).

    ``jax.random.poisson`` is only implemented for threefry; routing its
    (tiny, per-window) draws through this shim lets the bulk per-request
    draws run under a cheaper global impl (``rbg``) without losing the
    counting-process sampler.  Takes the first 64 key bits.
    """
    import jax

    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        data = jax.random.key_data(key)
    else:
        data = key
    return jax.random.wrap_key_data(
        data[..., :2].astype(jnp.uint32), impl="threefry2x32",
    )
