"""Exact stable time-argsort without a tuple sort.

Profiling the scan fast path (round 4/5, ``prof_trace``) showed the two
surviving per-scenario argsorts — LB routing order and the shared
entry-tier arrival order — are ~44% of device time.  XLA lowers
``jnp.argsort`` to a *tuple* sort (key, iota) whose 4-parameter comparator
region falls off the backend's specialized single-operand path; measured on
XLA:CPU a plain ``u32`` sort of the same 87,840-key shape is ~7x faster
(77 ms vs 565 ms per 16-lane block).

``argsort_time`` reproduces ``jnp.argsort(where(alive, t, INF))`` —
stable, bit-identical — as:

1. map f32 times to their order-isomorphic ``u32`` bit pattern (the
   classic sign-flip bijection: IEEE-754 totally ordered for finite
   values), and give each dead lane the unique key ``0xFF000000 + lane``
   (above every finite alive key when ``t < ~1.7e38``; unique, so the
   whole padding block is tie-free and lands in lane order — exactly what
   a stable sort of equal INF keys produces);
2. ONE single-operand ``lax.sort`` of the keys (the fast comparator path);
3. ranks via vectorized binary search of each key in the sorted array
   (``searchsorted`` side='left');
4. accidental f32 ties among alive lanes (dozens per 88k-arrival scenario:
   ~1e7-8e7 representable values under the time range vs 88k^2/2 pairs)
   share a 'left' rank; a short ``while_loop`` — scatter-min of lane index
   onto contested slots, losers step one slot right — assigns the tied
   block in ascending-lane order, i.e. the stable order.  Trip count =
   largest tie group (2-3 in practice), checked each round.

The result is a true permutation, equal to the stable argsort everywhere.
Replaces the reference's per-event heap ordering
(`/root/reference/src/asyncflow/runtime/simulation_runner.py:369`) at the
whole-array level.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["argsort_time", "searchsorted_small", "sortable_u32", "time_rank"]

# a numpy scalar, NOT jnp: a module-level jnp constant would initialize
# the XLA backend at import time, breaking jax.distributed.initialize()
# in multi-host workers (tests/system/test_sys_multihost.py)
_DEAD_BASE = np.uint32(0xFF000000)

# ---------------------------------------------------------------------------
# CPU escape hatch: adaptive native stable argsort (ffisort.cpp).  The
# arrival keys are near-sorted, where an insertion sort is O(n+inversions)
# ~ 1 ms/lane vs ~15 ms for XLA:CPU's comparator-driven sort.  Built on
# demand with the system g++ against jax.ffi's bundled XLA headers;
# unavailable (no compiler) degrades to the pure-XLA path.
# ---------------------------------------------------------------------------

_FFI_TARGET = "af_stable_argsort_rank"
_ffi_ready: bool | None = None


def _ffi_api():
    """The jax FFI namespace: top-level ``jax.ffi`` (jax >= 0.5) or its
    ``jax.extend.ffi`` predecessor — same four functions either way."""
    try:
        from jax import ffi
    except ImportError:
        from jax.extend import ffi
    return ffi


def _ensure_ffi() -> bool:
    global _ffi_ready
    if _ffi_ready is not None:
        return _ffi_ready
    try:
        ffi = _ffi_api()
        src = Path(__file__).parent / "ffisort.cpp"
        out_dir = Path(tempfile.gettempdir()) / f"asyncflow_tpu_ffi_{os.getuid()}"
        out_dir.mkdir(exist_ok=True, mode=0o700)
        if out_dir.stat().st_uid != os.getuid():
            out_dir = Path(tempfile.mkdtemp(prefix="asyncflow_tpu_ffi_"))
        # key the cache on the jax version too: a jax upgrade changes the
        # bundled XLA FFI headers, and a stale binary would register fine
        # but fail at call time instead of degrading to the XLA path
        out = out_dir / f"_afffisort_jax{jax.__version__}.so"
        if not (out.exists() and out.stat().st_mtime >= src.stat().st_mtime):
            tmp = out_dir / f"{out.name}.{os.getpid()}.tmp"
            subprocess.run(
                [
                    "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                    f"-I{ffi.include_dir()}",
                    str(src), "-o", str(tmp),
                ],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, out)
        lib = ctypes.CDLL(str(out))
        ffi.register_ffi_target(
            _FFI_TARGET,
            ffi.pycapsule(lib.AfStableArgsortRank),
            platform="cpu",
        )
        _ffi_ready = True
    except Exception:  # noqa: BLE001 — any failure means "no native sort"
        _ffi_ready = False
    return _ffi_ready


def _ffi_rank(keys: jnp.ndarray) -> jnp.ndarray:
    """Stable-sort rank of f32 keys via the native kernel (CPU only)."""
    shape = jax.ShapeDtypeStruct(keys.shape, jnp.int32)
    _, rank = _ffi_api().ffi_call(
        _FFI_TARGET, (shape, shape), vmap_method="expand_dims",
    )(keys)
    return rank


#: above this table length the dense compare matrix loses to binary search:
#: the (q, len(table)) intermediate grows unbounded with the table (a 1 s
#: user window over a 3600 s horizon gives a 3600-entry table; with ~1e5
#: query slots that is a ~4e8-element broadcast the log-n search never
#: materializes), while the while-loop overhead the dense form exists to
#: avoid is only ~14 ms per call on TPU — a few hundred entries is where
#: the trade flips
DENSE_TABLE_MAX = 256


def searchsorted_small(table: jnp.ndarray, q: jnp.ndarray, side: str) -> jnp.ndarray:
    """Exact ``jnp.searchsorted`` for a SMALL sorted 1-D ``table``.

    XLA:TPU lowers ``searchsorted`` to a binary-search while loop whose
    per-round gathers cost ~14 ms at the fast path's query shapes (round-5
    on-chip profile: 3 s/chunk spent searching a 21-entry window table).
    For an n-entry table the insertion index is just a count — n broadcast
    compares, fused, gather-free:
    ``side='right'`` counts ``table <= q``; ``side='left'`` counts
    ``table < q`` — the textbook insertion-point definitions.

    Tables longer than :data:`DENSE_TABLE_MAX` fall back to the log-n
    ``jnp.searchsorted`` — the dense compare matrix is a memory/latency
    cliff there, not an optimization.
    """
    if side not in ("left", "right"):
        msg = f"side must be 'left' or 'right', got {side!r}"
        raise ValueError(msg)
    if table.shape[-1] > DENSE_TABLE_MAX:
        return jnp.searchsorted(table, q, side=side).astype(jnp.int32)
    cmp = table <= q[..., None] if side == "right" else table < q[..., None]
    return jnp.sum(cmp, axis=-1).astype(jnp.int32)


def sortable_u32(t: jnp.ndarray) -> jnp.ndarray:
    """Order-isomorphic u32 image of finite f32 (sign-flip bijection).

    -0.0 is canonicalized to +0.0 first: jnp.argsort's comparator treats
    the two as equal (ties broken by lane), while the raw bijection would
    order -0.0 strictly first.
    """
    t = t.astype(jnp.float32)
    t = jnp.where(t == 0.0, jnp.float32(0.0), t)
    b = jax.lax.bitcast_convert_type(t, jnp.uint32)
    neg = (b >> 31) == 1
    return jnp.where(neg, ~b, b | (jnp.uint32(1) << 31))


def time_rank(t: jnp.ndarray, alive: jnp.ndarray) -> jnp.ndarray:
    """Stable sort *rank* of each lane under ``where(alive, t, INF)``.

    ``rank`` is the inverse of the stable argsort permutation:
    ``argsort[rank[i]] == i``.  Consumers sort with a scatter
    (``sorted = empty.at[rank].set(x)`` == ``x[argsort]``) and un-sort with
    a gather (``x_lane = x_sorted[rank]`` == ``empty.at[argsort].set(x)``),
    so most call sites never materialize the permutation itself.

    ``t`` finite f32 (< ~1.7e38 where alive), shape (n,); ``alive`` bool.
    Dead lanes rank last in lane order, tied alive lanes rank in lane
    order — bit-identical to the stable tuple argsort's inverse.
    """
    if _ensure_ffi():
        keys_f = jnp.where(alive, t.astype(jnp.float32), jnp.inf)
        return jax.lax.platform_dependent(
            keys_f, cpu=_ffi_rank, default=_time_rank_xla,
        )
    return _time_rank_xla(jnp.where(alive, t, jnp.inf))


#: TPU rank strategy: "search" = single-operand u32 sort + searchsorted +
#: tie-fix (round-5 default); "kvsort" = ONE stable (key, iota) sort with
#: num_keys=1 — the (values, indices) shape XLA:TPU specializes for top_k;
#: "bitonic" = a pure elementwise sorting network (no sort custom call, no
#: gathers — see _bitonic_rank).  The round-5 on-chip profile showed
#: searchsorted's log-n gather rounds at 244 ms/block vs 79 ms for the
#: sort itself; all three arms are bit-identical, pick by measurement.
_RANK_MODE = os.environ.get("AF_TPU_RANK", "search")
if _RANK_MODE not in ("search", "kvsort", "bitonic"):
    # a typo'd A/B knob must not silently measure the baseline twice
    msg = (
        f"AF_TPU_RANK must be 'search', 'kvsort' or 'bitonic', "
        f"got {_RANK_MODE!r}"
    )
    raise ValueError(msg)


def _bitonic_rank(key: jnp.ndarray, iota: jnp.ndarray) -> jnp.ndarray:
    """Stable rank of u32 ``key`` via a bitonic network on (key, lane).

    The round-5 on-chip profile showed BOTH halves of the sort+search rank
    are dominated by ops the TPU backend serializes (the sort custom call,
    searchsorted's per-round gathers).  A bitonic sorting network is the
    opposite trade: sum(log2 k) = O(log^2 m) stages of pure elementwise
    compare-exchanges — fused VPU min/max/selects, zero gathers, zero
    custom calls.  Sorting the (key, lane) PAIR lexicographically makes
    every element unique, so the network computes exactly the stable rank
    — no tie-fix loop, unconditionally, for any input.

    Batcher's XOR form: partner of i at distance j is i^j, which for
    power-of-2 j is a static (m/2j, 2, j) reshape; the ascending/descending
    direction bit (i & k) lives in the leading reshape axis, so it is a
    broadcasted iota parity — everything static, everything fused.

    Returns the rank (inverse argsort) directly: after the network sorts
    the pairs, the carried lane at sorted position p IS argsort[p]; one
    scatter inverts it.
    """
    n = key.shape[0]
    m = 1 << max(int(n - 1).bit_length(), 1)  # next power of two
    pad = m - n
    # padding sorts after every real element BY THE POS TIEBREAK: pad pos
    # starts at n, above every real pos.  (Key separation alone is not the
    # guarantee — a dead lane's key 0xFF000000+lane reaches the 0xFFFFFFFF
    # pad key at lane = 2^24-1, time_rank's documented limit.)
    key = jnp.concatenate([key, jnp.full((pad,), jnp.uint32(0xFFFFFFFF))])
    pos = jnp.concatenate([iota, jnp.arange(n, m, dtype=jnp.int32)])

    span = 2
    while span <= m:
        half = span // 2
        j = half
        while j >= 1:
            nb = m // (2 * j)
            k2 = key.reshape(nb, 2, j)
            p2 = pos.reshape(nb, 2, j)
            ak, bk = k2[:, 0, :], k2[:, 1, :]
            ai, bi = p2[:, 0, :], p2[:, 1, :]
            gt = (ak > bk) | ((ak == bk) & (ai > bi))
            # direction: descending where (i & span) != 0; bit log2(span)
            # of i is bit log2(span)-log2(2j) of the block index
            desc = (
                jax.lax.broadcasted_iota(jnp.int32, (nb, 1), 0)
                & jnp.int32(span // (2 * j))
            ) != 0
            swap = gt ^ desc
            k2 = jnp.stack(
                [jnp.where(swap, bk, ak), jnp.where(swap, ak, bk)], axis=1,
            )
            p2 = jnp.stack(
                [jnp.where(swap, bi, ai), jnp.where(swap, ai, bi)], axis=1,
            )
            key = k2.reshape(m)
            pos = p2.reshape(m)
            j //= 2
        span *= 2
    # pos[p] = lane of sorted position p (the argsort); invert -> rank
    return (
        jnp.zeros((m,), jnp.int32)
        .at[pos]
        .set(jnp.arange(m, dtype=jnp.int32))[:n]
    )


def _time_rank_xla(t: jnp.ndarray) -> jnp.ndarray:
    """Pure-XLA stable rank of f32 keys (+inf = padding; see time_rank)."""
    alive = t < jnp.inf
    n = t.shape[0]
    if n > 0x0100_0000:  # dead keys are _DEAD_BASE + lane: 24 bits of lane
        msg = f"time_rank supports at most 2**24 lanes, got {n}"
        raise ValueError(msg)
    lane = jnp.arange(n, dtype=jnp.uint32)
    iota = jnp.arange(n, dtype=jnp.int32)
    key = jnp.where(alive, sortable_u32(t), _DEAD_BASE + lane)
    if _RANK_MODE == "kvsort":
        # stable kv-sort: the carried iota IS the argsort; invert by scatter
        _, perm = jax.lax.sort((key, iota), dimension=0, num_keys=1)
        return jnp.zeros((n,), jnp.int32).at[perm].set(iota)
    if _RANK_MODE == "bitonic":
        return _bitonic_rank(key, iota)
    sk = jax.lax.sort(key, dimension=0)
    rank = jnp.searchsorted(sk, key, side="left").astype(jnp.int32)

    # Resolve shared 'left' ranks of tied alive keys: every round the
    # lowest-lane contender keeps the slot, the rest step right.  Dead
    # lanes are unique by construction and never enter the loop; alive tie
    # groups are f32 collisions (dozens per 88k keys), so the trip count —
    # the largest tie group — is 2-3.
    big = jnp.int32(n)

    def body(state):
        pos, _ = state
        winner = jnp.full((n,), big, jnp.int32).at[pos].min(iota)
        lost = winner[pos] != iota
        return pos + lost.astype(jnp.int32), jnp.any(lost)

    def cond(state):
        return state[1]

    pos, _ = jax.lax.while_loop(cond, body, (rank, jnp.bool_(True)))
    return pos


def argsort_time(t: jnp.ndarray, alive: jnp.ndarray) -> jnp.ndarray:
    """Stable ``argsort(where(alive, t, INF))`` (see :func:`time_rank`)."""
    n = t.shape[0]
    rank = time_rank(t, alive)
    return jnp.zeros((n,), jnp.int32).at[rank].set(jnp.arange(n, dtype=jnp.int32))
