"""Sequential CPU discrete-event oracle engine."""

from asyncflow_tpu.engines.oracle.engine import OracleEngine

__all__ = ["OracleEngine"]
