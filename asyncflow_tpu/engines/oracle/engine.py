"""Sequential CPU discrete-event engine — the behavioral oracle.

Re-derives the reference actor model (generator, edges, client, LB, servers,
event injection, sampled-metric collector) on the kernel in
:mod:`asyncflow_tpu.engines.oracle.kernel`.  Semantics cloned from the
reference runtime (`/root/reference/src/asyncflow/runtime/`):

- edges: dropout before anything else, latency + active spike at send time,
  per-edge concurrent-connection gauge (`actors/edge.py:73-116`);
- servers: RAM-first admission, lazy core lock across consecutive CPU steps,
  core released on I/O, FIFO ready queue counting only core-waiters
  (`actors/server.py:79-276`);
- client: a request completes on its second client visit, recorded via the
  hop history exactly like the reference's ``len(history) > 3`` protocol
  (`actors/client.py:43-71`);
- LB: round-robin rotates an ordered mapping, least-connections takes the
  first minimum in rotation order; a down server's edge is removed and
  re-inserted at the end of the rotation on revival
  (`actors/routing/lb_algorithms.py:10-36`, `events/injection.py:201-226`);
- spikes superpose over overlapping windows (`events/injection.py:167-198`);
- one sampling coroutine snapshots gauges every ``sample_period_s``
  (`metrics/collector.py:50-67`).
"""

from __future__ import annotations

import math
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from asyncflow_tpu.config.constants import (
    EndpointStepIO,
    EventDescription,
    LbAlgorithmsName,
    SampledMetricName,
    SystemEdges,
    SystemNodes,
)
from asyncflow_tpu.engines.oracle.kernel import (
    AcquireAmount,
    AcquireServe,
    AcquireToken,
    FifoContainer,
    FifoTokens,
    ServingGate,
    Sim,
    Timeout,
)
from asyncflow_tpu.engines.results import SimulationResults
from asyncflow_tpu.observability import blame as _blm
from asyncflow_tpu.observability.simtrace import (
    FR_ABANDON,
    FR_ARRIVE_LB,
    FR_ARRIVE_SRV,
    FR_CANCEL,
    FR_COMPLETE,
    FR_DECODE,
    FR_DROP,
    FR_EVICT,
    FR_HEDGE,
    FR_PREFILL,
    FR_REJECT,
    FR_RETRY,
    FR_RUN,
    FR_SPAWN,
    FR_TIMEOUT,
    FR_TRANSIT,
    FR_WAIT_CPU,
    FR_WAIT_DB,
    FR_WAIT_RAM,
    FlightRecord,
    TraceConfig,
)
from asyncflow_tpu.samplers.arrivals import arrival_gaps
from asyncflow_tpu.samplers.variates import sample_rv
from asyncflow_tpu.schemas.edges import Edge
from asyncflow_tpu.schemas.nodes import Server
from asyncflow_tpu.schemas.payload import SimulationPayload


@dataclass
class Hop:
    """A single traversal of a node or edge (per-request tracing)."""

    component_type: str
    component_id: str
    timestamp: float


@dataclass
class Request:
    """Mutable state carried by one request through the system."""

    id: int
    initial_time: float
    finish_time: float | None = None
    history: list[Hop] = field(default_factory=list)
    #: LB out-edge that routed this request; cleared after the first
    #: server reports success/failure to the circuit breaker
    lb_edge_id: str | None = None
    #: True while this request is a half-open breaker probe
    probe: bool = False
    #: accumulated LLM cost units (io_llm steps with call dynamics)
    llm_cost: float = 0.0
    #: client retry machinery: attempt number of this issue (spawn = 1),
    #: True once the client abandoned it (timeout fired; the request
    #: keeps consuming server resources but no longer counts), True once
    #: the client-side outcome (completion or failure) is settled.
    attempt: int = 1
    orphan: bool = False
    settled: bool = False
    #: flight-recorder ring of the logical request (None = untraced or
    #: orphaned; the record survives client retries — the re-issue carries
    #: the same object)
    fr: FlightRecord | None = None
    #: hedged-request machinery: the shared race state of this attempt's
    #: logical request (None without a policy), 1 on speculative
    #: duplicates, True once this attempt returned its live refcount
    hedge: _HedgeGroup | None = None
    is_hedge: int = 0
    hg_released: bool = False
    #: True while this attempt runs a server's brownout (cheaper) profile
    degraded: bool = False
    #: serving token draws of this attempt (-1 = not drawn yet; replay
    #: presets stamp them at spawn; eviction redo reuses the same draws)
    tok_in: float = -1.0
    tok_out: float = -1.0
    #: evictions this attempt has suffered (terminal reject past the cap)
    sv_evict: int = 0
    #: latency-attribution row of this attempt (observability/blame.py):
    #: (n_cells,) seconds per (component, phase), lazily allocated on the
    #: first credit; None when attribution is off
    blame: np.ndarray | None = None

    def record_hop(self, kind: str, component_id: str, now: float) -> None:
        self.history.append(Hop(kind, component_id, now))


@dataclass
class _HedgeGroup:
    """One logical request's hedge race, shared by all its attempts.

    ``anchor`` is the attempt currently holding the anchor identity (the
    jax engine's anchor pool slot): duplicates copy its start time and
    attempt number, and every hedge-lifecycle flight-recorder write routes
    through its record.  ``live`` refcounts attempts in flight; at zero
    the logical request is gone — hedging never resurrects it.  ``done``
    means the race is settled: a winner completed, the retry ladder gave
    the request up, or every attempt died.
    """

    anchor: Request
    n: int = 0
    live: int = 1
    done: bool = False


class _EdgeRuntime:
    """Unidirectional link: dropout, stochastic latency, spike, delivery."""

    def __init__(self, engine: OracleEngine, cfg: Edge) -> None:
        self.engine = engine
        self.cfg = cfg
        self.concurrent = 0
        self.total_sent = 0  # cumulative non-dropped sends
        self.series: list[float] = []
        self.deliver_to = None  # set during wiring: callable(Request)

    def transport(self, req: Request) -> None:
        engine = self.engine
        # fault windows gate the traversal: dropout boosted (partition
        # windows boost it to 1), latency draws multiplied
        lat_factor, drop_boost = engine.edge_fault_at(
            self.cfg.id, engine.sim.now,
        )
        drop_p = min(1.0, self.cfg.dropout_rate + drop_boost)
        if engine.rng.uniform() < drop_p:
            req.finish_time = engine.sim.now
            req.record_hop(
                SystemEdges.NETWORK_CONNECTION,
                f"{self.cfg.id}-dropped",
                engine.sim.now,
            )
            engine.total_dropped += 1
            if engine.trace is not None:
                engine._fr(
                    req,
                    FR_DROP,
                    engine._edge_idx[self.cfg.id],
                    engine.sim.now,
                )
            if req.lb_edge_id == self.cfg.id:
                # a dropped send on the routing edge is a connection
                # failure to the breaker
                engine.breaker_failure(req)
            engine.client_fail(req)
            return

        self.concurrent += 1
        self.total_sent += 1
        transit = sample_rv(self.cfg.latency, engine.rng) * lat_factor
        transit += engine.edge_spike.get(self.cfg.id, 0.0)
        t_sent = engine.sim.now

        def deliver() -> None:
            req.record_hop(
                SystemEdges.NETWORK_CONNECTION,
                self.cfg.id,
                engine.sim.now,
            )
            if engine.trace is not None:
                engine._fr(
                    req,
                    FR_TRANSIT,
                    engine._edge_idx[self.cfg.id],
                    engine.sim.now,
                )
            self.concurrent -= 1
            engine._bl(
                req,
                _blm.comp_edge(
                    engine._bl_nsrv, engine._edge_idx[self.cfg.id],
                ),
                _blm.PH_TRANSIT,
                engine.sim.now - t_sent,
            )
            assert self.deliver_to is not None
            self.deliver_to(req)

        engine.sim.after(transit, deliver)


class _ServerRuntime:
    """Event-loop server: RAM-first admission, lazy core lock, FIFO queues."""

    def __init__(self, engine: OracleEngine, cfg: Server) -> None:
        self.engine = engine
        self.cfg = cfg
        self.cpu = FifoTokens(engine.sim, cfg.server_resources.cpu_cores)
        self.ram = FifoContainer(engine.sim, float(cfg.server_resources.ram_mb))
        # DB connection pool (the reference's reserved db_connection_pool
        # field, activated — its roadmap milestone 4): every io_db step
        # must hold one of K FIFO connections for its duration; the wait
        # parks in the event loop (core released, RAM held)
        pool = cfg.server_resources.db_connection_pool
        self.db = FifoTokens(engine.sim, pool) if pool is not None else None
        # overload policy: shed requests that would join a full ready queue
        self.queue_cap = (
            cfg.overload.max_ready_queue if cfg.overload is not None else None
        )
        # socket capacity: refuse arrivals when this many requests are
        # already resident on the server (accepted arrival -> exit)
        self.conn_cap = (
            cfg.overload.max_connections if cfg.overload is not None else None
        )
        # token-bucket rate limiter: refuse arrivals that find no whole
        # token (reference roadmap milestone 5); runs before the socket
        # capacity check
        self.rate_limit = (
            cfg.overload.rate_limit_rps if cfg.overload is not None else None
        )
        self.rl_burst = (
            float(cfg.overload.effective_burst)
            if cfg.overload is not None and cfg.overload.effective_burst
            else 0.0
        )
        self.rl_tokens = self.rl_burst
        self.rl_last = 0.0
        # dequeue deadline on the ready-queue wait (milestone 5)
        self.queue_timeout = (
            cfg.overload.queue_timeout_s if cfg.overload is not None else None
        )
        # brownout: above this ready-queue depth arrivals are served a
        # cheaper profile (scaled CPU/RAM) instead of shed
        self.brownout_q = (
            cfg.overload.brownout_queue_threshold
            if cfg.overload is not None
            else None
        )
        self.brownout_cpu = (
            float(cfg.overload.brownout_cpu_factor)
            if cfg.overload is not None
            else 1.0
        )
        self.brownout_ram = (
            float(cfg.overload.brownout_ram_factor)
            if cfg.overload is not None
            else 1.0
        )
        # LLM continuous batching (serving subsystem): the batch is a
        # two-resource FIFO gate — slots + resident KV tokens — built from
        # the server's ServingPolicy with the SAME min() collapse the
        # compiler lowers into StaticPlan.serve_tokens, so oracle and jax
        # admission decisions agree on identical budgets
        self.serve: ServingGate | None = None
        self.serve_evict_max = 3
        pol = cfg.serving
        if pol is not None:
            budget = math.inf
            if pol.max_batch_tokens is not None:
                budget = float(pol.max_batch_tokens)
            if pol.kv_cache_mb is not None:
                kv_max = max(
                    (
                        float(st.kv_mb_per_token)
                        for ep in cfg.endpoints
                        for st in ep.steps
                        if getattr(st, "is_serving", False)
                    ),
                    default=0.0,
                )
                if kv_max > 0:
                    budget = min(budget, float(pol.kv_cache_mb) / kv_max)
            self.serve = ServingGate(
                engine.sim,
                int(pol.max_batch_requests)
                if pol.max_batch_requests is not None
                else 2**30,
                budget if budget < math.inf else 1e30,
            )
            self.serve_evict_max = int(pol.max_evictions)
        self.residents = 0
        self.ready_queue_len = 0
        self.io_queue_len = 0
        self.ram_in_use = 0.0
        # cumulative endpoint-selection probabilities (selection_weight)
        w = np.array([float(ep.selection_weight) for ep in cfg.endpoints])
        self.endpoint_cum = np.cumsum(w / w.sum())
        self.out_edge: _EdgeRuntime | None = None
        self.series: dict[SampledMetricName, list[float]] = {
            SampledMetricName.READY_QUEUE_LEN: [],
            SampledMetricName.EVENT_LOOP_IO_SLEEP: [],
            SampledMetricName.RAM_IN_USE: [],
        }

    def receive(self, req: Request) -> None:
        engine = self.engine
        if engine.hedge_checkpoint(req):
            # the hedge race is already won: cancel instead of admitting
            return
        if engine.server_faulted(self.cfg.id, engine.sim.now):
            # server-outage fault window: the server is dark and hard-
            # refuses the arrival (the LB only learns via the breaker;
            # the client via its retry policy)
            req.finish_time = engine.sim.now
            req.record_hop(
                SystemNodes.SERVER, f"{self.cfg.id}-outage", engine.sim.now,
            )
            engine.total_rejected += 1
            engine.dark_lost += 1
            engine._fr(
                req, FR_REJECT, engine._server_idx[self.cfg.id], engine.sim.now,
            )
            engine.breaker_failure(req)
            engine.client_fail(req)
            return
        if self.rate_limit is not None:
            now = engine.sim.now
            self.rl_tokens = min(
                self.rl_burst,
                self.rl_tokens + (now - self.rl_last) * self.rate_limit,
            )
            self.rl_last = now
            if self.rl_tokens < 1.0:
                # rate limited: no whole token in the bucket
                req.finish_time = now
                req.record_hop(
                    SystemNodes.SERVER, f"{self.cfg.id}-rate-limited", now,
                )
                engine.total_rejected += 1
                engine._fr(
                    req, FR_REJECT, engine._server_idx[self.cfg.id], now,
                )
                engine.breaker_failure(req)
                engine.client_fail(req)
                return
            self.rl_tokens -= 1.0
        if self.conn_cap is not None and self.residents >= self.conn_cap:
            # connection refused: the server is at socket capacity
            req.finish_time = engine.sim.now
            req.record_hop(
                SystemNodes.SERVER,
                f"{self.cfg.id}-refused",
                engine.sim.now,
            )
            engine.total_rejected += 1
            engine._fr(
                req, FR_REJECT, engine._server_idx[self.cfg.id], engine.sim.now,
            )
            engine.breaker_failure(req)
            engine.client_fail(req)
            return
        self.residents += 1
        engine.sim.process(self._handle(req))

    def _handle(self, req: Request):
        try:
            yield from self._run_endpoint(req)
        finally:
            self.residents -= 1

    def _run_endpoint(self, req: Request):
        engine = self.engine
        req.record_hop(SystemNodes.SERVER, self.cfg.id, engine.sim.now)
        tracing = engine.trace is not None
        srv_idx = engine._server_idx[self.cfg.id]
        if tracing:
            engine._fr(req, FR_ARRIVE_SRV, srv_idx, engine.sim.now)

        endpoints = self.cfg.endpoints
        endpoint = endpoints[
            min(
                int(np.searchsorted(self.endpoint_cum, engine.rng.uniform())),
                len(endpoints) - 1,
            )
        ]
        if engine.has_brownout:
            # brownout decision latched per arrival: above the ready-queue
            # threshold this visit serves the cheaper profile (an
            # unconfigured server resets the flag — the LAST server
            # visited decides, same as the jax engine's per-arrival latch)
            req.degraded = (
                self.brownout_q is not None
                and self.ready_queue_len >= self.brownout_q
            )
        total_ram = sum(step.quantity for step in endpoint.steps if step.is_ram)
        if req.degraded:
            total_ram *= self.brownout_ram

        if total_ram:
            ram_waits = tracing and (
                self.ram.would_block or self.ram.level < total_ram
            )
            if ram_waits:
                engine._fr(req, FR_WAIT_RAM, srv_idx, engine.sim.now)
            t_ram = engine.sim.now
            yield AcquireAmount(self.ram, total_ram)
            engine._bl(req, srv_idx, _blm.PH_Q_RAM, engine.sim.now - t_ram)
            if ram_waits:
                engine._fr(req, FR_RUN, srv_idx, engine.sim.now)
            self.ram_in_use += total_ram

        core_locked = False
        in_io_queue = False
        waiting_cpu = False

        for step in endpoint.steps:
            if getattr(step, "is_serving", False):
                # llm_serve lifecycle: FIFO batch admission (one slot +
                # prompt's KV tokens) -> prefill -> decode extension or
                # eviction.  Eviction redoes the prefill from the tail of
                # the admission queue; past the eviction budget the
                # request is terminally rejected (shed accounting).  The
                # admission park sits OUTSIDE the io-sleep gauge, like
                # the jax engine's EV_WAIT_SV park.
                if core_locked:
                    self.cpu.release()
                    core_locked = False
                if in_io_queue:
                    in_io_queue = False
                    self.io_queue_len -= 1
                gate = self.serve
                assert gate is not None  # schema: policy iff serving steps
                if req.tok_in < 0.0:
                    req.tok_in = engine.draw_tokens(step.input_tokens)
                if req.tok_out < 0.0:
                    req.tok_out = engine.draw_tokens(step.output_tokens)
                while True:
                    t_adm = engine.sim.now
                    yield AcquireServe(gate, req.tok_in)
                    engine._bl(
                        req, srv_idx, _blm.PH_Q_ADMIT,
                        engine.sim.now - t_adm,
                    )
                    # admitted: prompt tokens resident, prefill runs
                    # (io-like sleep; redone in full on every re-admission)
                    in_io_queue = True
                    self.io_queue_len += 1
                    engine.prefill_tokens += req.tok_in
                    if tracing:
                        engine._fr(req, FR_PREFILL, srv_idx, engine.sim.now)
                    t_pf = engine.sim.now
                    yield Timeout(
                        step.prefill_base_s
                        + req.tok_in * step.prefill_time_per_token_s,
                    )
                    engine._bl(
                        req, srv_idx,
                        _blm.PH_KV_REDO if req.sv_evict else _blm.PH_PREFILL,
                        engine.sim.now - t_pf,
                    )
                    if gate.try_extend(req.tok_out):
                        # decode fits: generation holds prompt + output
                        # tokens until completion releases both
                        engine.decode_tokens += req.tok_out
                        req.llm_cost += req.tok_out * step.cost_per_token
                        if tracing:
                            engine._fr(
                                req, FR_DECODE, srv_idx, engine.sim.now,
                            )
                        rate = engine.draw_rate(step.decode_tokens_per_s)
                        t_dc = engine.sim.now
                        yield Timeout(req.tok_out / rate)
                        engine._bl(
                            req, srv_idx, _blm.PH_DECODE,
                            engine.sim.now - t_dc,
                        )
                        gate.release(1, req.tok_in + req.tok_out)
                        break
                    # KV pressure: evict — release the slot and prompt
                    # hold (cascading queued admissions), then re-queue
                    engine.kv_evictions += 1
                    req.sv_evict += 1
                    if tracing:
                        engine._fr(req, FR_EVICT, srv_idx, engine.sim.now)
                    in_io_queue = False
                    self.io_queue_len -= 1
                    gate.release(1, req.tok_in)
                    if req.sv_evict > self.serve_evict_max:
                        # eviction budget spent: terminal reject
                        if total_ram:
                            self.ram_in_use -= total_ram
                            self.ram.release(total_ram)
                        req.finish_time = engine.sim.now
                        req.record_hop(
                            SystemNodes.SERVER,
                            f"{self.cfg.id}-evicted",
                            engine.sim.now,
                        )
                        engine.total_rejected += 1
                        engine._fr(req, FR_REJECT, srv_idx, engine.sim.now)
                        engine.breaker_failure(req)
                        engine.client_fail(req)
                        return
            elif step.is_cpu:
                if in_io_queue:
                    in_io_queue = False
                    self.io_queue_len -= 1
                if not core_locked:
                    if self.cpu.would_block:
                        if (
                            self.queue_cap is not None
                            and self.ready_queue_len >= self.queue_cap
                        ):
                            # overload policy: shed instead of queueing —
                            # release held RAM, count, and leave the system
                            if total_ram:
                                self.ram_in_use -= total_ram
                                self.ram.release(total_ram)
                            req.finish_time = engine.sim.now
                            req.record_hop(
                                SystemNodes.SERVER,
                                f"{self.cfg.id}-rejected",
                                engine.sim.now,
                            )
                            engine.total_rejected += 1
                            engine._fr(
                                req, FR_REJECT, srv_idx, engine.sim.now,
                            )
                            engine.breaker_failure(req)
                            engine.client_fail(req)
                            return
                        waiting_cpu = True
                        self.ready_queue_len += 1
                        if tracing:
                            engine._fr(
                                req, FR_WAIT_CPU, srv_idx, engine.sim.now,
                            )
                    wait_started = engine.sim.now
                    yield AcquireToken(self.cpu)
                    engine._bl(
                        req, srv_idx, _blm.PH_Q_CPU,
                        engine.sim.now - wait_started,
                    )
                    if waiting_cpu:
                        waiting_cpu = False
                        self.ready_queue_len -= 1
                        if tracing:
                            engine._fr(req, FR_RUN, srv_idx, engine.sim.now)
                        if (
                            self.queue_timeout is not None
                            and engine.sim.now - wait_started > self.queue_timeout
                        ):
                            # dequeue deadline exceeded: abandon, consuming
                            # zero service (the core passes straight to the
                            # next FIFO waiter)
                            self.cpu.release()
                            if total_ram:
                                self.ram_in_use -= total_ram
                                self.ram.release(total_ram)
                            req.finish_time = engine.sim.now
                            req.record_hop(
                                SystemNodes.SERVER,
                                f"{self.cfg.id}-timed-out",
                                engine.sim.now,
                            )
                            engine.total_rejected += 1
                            engine._fr(
                                req, FR_REJECT, srv_idx, engine.sim.now,
                            )
                            engine.breaker_failure(req)
                            engine.client_fail(req)
                            return
                    core_locked = True
                t_cpu = engine.sim.now
                yield Timeout(
                    step.quantity * self.brownout_cpu
                    if req.degraded
                    else step.quantity,
                )
                engine._bl(
                    req, srv_idx, _blm.PH_SERVICE, engine.sim.now - t_cpu,
                )
            elif step.is_io:
                if core_locked:
                    self.cpu.release()
                    core_locked = False
                    if not in_io_queue:
                        in_io_queue = True
                        self.io_queue_len += 1
                elif not in_io_queue:
                    in_io_queue = True
                    self.io_queue_len += 1
                if self.db is not None and step.kind == EndpointStepIO.DB:
                    # hold one of K FIFO connections for the query; the
                    # wait (if any) parks in the event loop like any await
                    db_waits = tracing and self.db.would_block
                    if db_waits:
                        engine._fr(req, FR_WAIT_DB, srv_idx, engine.sim.now)
                    t_db = engine.sim.now
                    yield AcquireToken(self.db)
                    engine._bl(
                        req, srv_idx, _blm.PH_Q_DB, engine.sim.now - t_db,
                    )
                    if db_waits:
                        engine._fr(req, FR_RUN, srv_idx, engine.sim.now)
                    t_io = engine.sim.now
                    yield Timeout(step.quantity)
                    engine._bl(
                        req, srv_idx, _blm.PH_SERVICE, engine.sim.now - t_io,
                    )
                    self.db.release()
                elif step.is_stochastic_cache:
                    # per-request hit/miss mixture: hit latency with
                    # probability p, else the backing store's miss latency
                    hit = engine.rng.uniform() < step.cache_hit_probability
                    t_io = engine.sim.now
                    yield Timeout(
                        step.quantity if hit else step.cache_miss_time,
                    )
                    engine._bl(
                        req, srv_idx, _blm.PH_SERVICE, engine.sim.now - t_io,
                    )
                elif step.is_llm:
                    # reserved io_llm kind, activated: output tokens ~
                    # Poisson(mean); sleep = base + tokens * s/token and
                    # the request accrues tokens * cost/token
                    tokens = float(engine.rng.poisson(step.llm_tokens_mean))
                    req.llm_cost += tokens * step.llm_cost_per_token
                    t_io = engine.sim.now
                    yield Timeout(
                        step.quantity + tokens * step.llm_time_per_token,
                    )
                    engine._bl(
                        req, srv_idx, _blm.PH_SERVICE, engine.sim.now - t_io,
                    )
                else:
                    t_io = engine.sim.now
                    yield Timeout(step.quantity)
                    engine._bl(
                        req, srv_idx, _blm.PH_SERVICE, engine.sim.now - t_io,
                    )

        if core_locked:
            self.cpu.release()
        if in_io_queue:
            self.io_queue_len -= 1
        if total_ram:
            self.ram_in_use -= total_ram
            self.ram.release(total_ram)

        engine.breaker_success(req)
        assert self.out_edge is not None
        self.out_edge.transport(req)


class OracleEngine:
    """Builds and runs one scenario sequentially on the CPU."""

    def __init__(
        self,
        payload: SimulationPayload,
        *,
        seed: int | None = None,
        collect_traces: bool = False,
        trace: TraceConfig | None = None,
        blame: bool = False,
        n_hist_bins: int = 1024,
    ) -> None:
        self.payload = payload
        self.settings = payload.sim_settings
        self.sim = Sim()
        self.rng = np.random.default_rng(seed)
        self.collect_traces = collect_traces
        self.traces: dict[int, list[tuple[str, str, float]]] = {}
        #: flight recorder (observability/simtrace.py): same sampling rule
        #: and record layout as the jax event engine, emitted from this
        #: heap loop — the streams are diffable event-by-event.  Recording
        #: consumes no draws, so results are identical with it on or off.
        if trace is not None and not isinstance(trace, TraceConfig):
            trace = TraceConfig.model_validate(trace)
        self.trace = trace
        self.flight: dict[int, FlightRecord] = {}
        self.breaker_timeline: list[tuple[float, int, int]] = []

        self.total_generated = 0
        self.total_dropped = 0
        self.total_rejected = 0
        # resilience: fault tables (same lowering the JAX plan consumes)
        # and the client retry machinery
        from asyncflow_tpu.compiler.faults import (
            lower_faults,
            lower_health,
            lower_hedge,
            lower_retry,
        )

        self._faults = lower_faults(payload)
        self._edge_idx = {
            e.id: i for i, e in enumerate(payload.topology_graph.edges)
        }
        self._server_idx = {
            s.id: i
            for i, s in enumerate(payload.topology_graph.nodes.servers)
        }
        # chaos campaign: sample scenario 0's merged fault tables from
        # (seed, index 0) — identical draws AND identical merged tables to
        # what the JAX engines consume, so oracle parity holds bit-for-bit
        self.dark_lost = 0
        self._hz_tables = None
        if payload.hazard_model is not None:
            from types import SimpleNamespace

            from asyncflow_tpu.compiler.faults import FaultArrays
            from asyncflow_tpu.compiler.hazards import (
                hazard_fault_tables,
                lower_hazards,
            )

            spec = lower_hazards(payload)
            shim = SimpleNamespace(
                hz_mtbf_dist=spec.mtbf_dist,
                hz_mtbf_mean=spec.mtbf_mean,
                hz_mtbf_var=spec.mtbf_var,
                hz_mttr_dist=spec.mttr_dist,
                hz_mttr_mean=spec.mttr_mean,
                hz_mttr_var=spec.mttr_var,
                hz_lat_factor=spec.lat_factor,
                hz_drop_boost=spec.drop_boost,
                hz_srv_targets=spec.srv_targets,
                hz_edge_targets=spec.edge_targets,
                hz_max_faults=spec.max_faults,
                horizon=float(payload.sim_settings.total_simulation_time),
                fault_srv_times=self._faults.srv_times,
                fault_srv_down=self._faults.srv_down,
                fault_edge_times=self._faults.edge_times,
                fault_edge_lat=self._faults.edge_lat,
                fault_edge_drop=self._faults.edge_drop,
            )
            self._hz_tables = hazard_fault_tables(
                shim, int(seed) if seed is not None else 0, 0, 1,
            )
            self._faults = FaultArrays(
                srv_times=self._hz_tables.srv_times[0],
                srv_down=self._hz_tables.srv_down[0],
                edge_times=self._hz_tables.edge_times[0],
                edge_lat=self._hz_tables.edge_lat[0],
                edge_drop=self._hz_tables.edge_drop[0],
            )
        self.retry = lower_retry(payload.retry_policy)
        # tail-tolerance policies (same lowering the JAX plan consumes)
        self.hedge = lower_hedge(payload.hedge_policy)
        _lb_node = payload.topology_graph.nodes.load_balancer
        self.health = lower_health(
            _lb_node.health if _lb_node is not None else None,
        )
        self.has_brownout = any(
            s.overload is not None
            and s.overload.brownout_queue_threshold is not None
            for s in payload.topology_graph.nodes.servers
        )
        self.total_hedges = 0
        self.hedges_won = 0
        self.hedges_cancelled = 0
        self.lb_ejections = 0
        self.degraded_completions = 0
        #: per-LB-out-edge health gate: EWMA failure rate + ejection lapse
        #: (``until`` > 0 means ejected; lazily readmitted at pick time)
        self.health_state: dict[str, dict] = {}
        self.total_timed_out = 0
        self.total_retries = 0
        self.retry_budget_exhausted = 0
        self.attempts_hist = np.zeros(
            max(self.retry.max_attempts, 1), dtype=np.int64,
        )
        self._rb_tokens = (
            self.retry.budget_tokens if self.retry.budget_tokens >= 0 else 0.0
        )
        self._rb_last = 0.0
        self.rqs_clock: list[tuple[float, float]] = []
        self.llm_costs: list[float] = []  # aligned with rqs_clock
        # serving counters (asyncflow_tpu/serving): prefill tokens accrue
        # on EVERY admission (eviction redo included); decode tokens only
        # when the extension fit
        self.kv_evictions = 0
        self.prefill_tokens = 0.0
        self.decode_tokens = 0.0
        self._has_serving = any(
            getattr(step, "is_serving", False)
            for server in payload.topology_graph.nodes.servers
            for ep in server.endpoints
            for step in ep.steps
        )
        # gate the llm_cost OUTPUT on llm presence in the payload (not on
        # observed nonzero costs: cost_per_token=0 is a legal latency-only
        # model and must still report a zeros array, matching the jax
        # engine's plan-gated output).  Serving steps join the gate: their
        # decode cost accrues into the same per-request cost stream.
        self._has_llm = self._has_serving or any(
            step.is_llm
            for server in payload.topology_graph.nodes.servers
            for ep in server.endpoints
            for step in ep.steps
        )
        self.edge_spike: dict[str, float] = {}
        #: latency attribution plane (observability/blame.py): one float64
        #: row per in-flight attempt, scattered into the pooled grid at
        #: completion keyed by the attempt's latency bin.  Recording
        #: consumes no draws, so results are identical with it on or off.
        self.blame = bool(blame)
        self.n_hist_bins = int(n_hist_bins)
        _n_srv = len(payload.topology_graph.nodes.servers)
        _n_edg = len(payload.topology_graph.edges)
        self._bl_nsrv = _n_srv
        self._bl_client = _blm.comp_client(_n_srv, _n_edg)
        self._bl_cells = _blm.n_cells(_n_srv, _n_edg)
        self._bl_nb = _blm.n_blame_bins(self.n_hist_bins)
        self._bl_stride = _blm.blame_stride(self.n_hist_bins)
        self.bl_grid = (
            np.zeros((self._bl_cells, self._bl_nb), np.float64)
            if self.blame
            else None
        )
        self.bl_lat = (
            np.zeros(self._bl_nb, np.float64) if self.blame else None
        )
        self.blame_rows: list[np.ndarray] = []

        graph = payload.topology_graph
        self.servers = {
            server.id: _ServerRuntime(self, server) for server in graph.nodes.servers
        }
        self.edges = {edge.id: _EdgeRuntime(self, edge) for edge in graph.edges}
        self.client_id = graph.nodes.client.id
        self.client_out: _EdgeRuntime | None = None
        self.lb = graph.nodes.load_balancer
        # rotation order of LB out-edges; mutated by routing and outages
        self.lb_out_edges: OrderedDict[str, _EdgeRuntime] = OrderedDict()
        # circuit breaker (reference roadmap milestone 5): independent
        # consecutive-failure breaker per LB out-edge; lazy OPEN ->
        # HALF_OPEN transition at routing time (schemas.nodes.CircuitBreaker)
        self.breaker = self.lb.circuit_breaker if self.lb is not None else None
        self.breaker_state: dict[str, dict] = {}
        # optional routing-weight override (the RL playground's action
        # channel, asyncflow_tpu.rl): edge id -> nonnegative weight; None
        # keeps the configured algorithm.  Breaker eligibility still
        # applies; an all-zero weight vector falls back to uniform.
        self.lb_weights: dict[str, float] | None = None
        self._gen_ids = {g.id for g in payload.generators}
        self.generator_out_by_id: dict[str, _EdgeRuntime] = {}
        # re-issue path for the client retry policy (single generator —
        # enforced by the payload validator)
        self._entry_out: _EdgeRuntime | None = None
        self._entry_gen_id: str | None = None

        self._wire()
        #: generator index (FR_SPAWN node field) in payload order — the
        #: same indexing the jax engine's chains use
        self._gen_fr_idx = {g.id: i for i, g in enumerate(payload.generators)}
        #: LB rotation slot of each out-edge in topology order (the jax
        #: engine's static slot indexing; rotation mutations don't renumber)
        self._lb_slot_idx = {
            eid: k for k, eid in enumerate(self.lb_out_edges)
        }

    # ------------------------------------------------------------------
    # flight recorder (no-ops unless ``trace`` was given; identical record
    # layout to the jax event engine — see observability/simtrace.py)
    # ------------------------------------------------------------------

    def _fr_rec(
        self, rec: FlightRecord | None, code: int, node: int, t: float,
    ) -> None:
        if rec is None or self.trace is None:
            return
        if len(rec.events) < self.trace.event_slots:
            rec.events.append((code, node, t))
        else:
            rec.dropped += 1

    def _fr(self, req: Request, code: int, node: int, t: float) -> None:
        if self.trace is not None:
            self._fr_rec(req.fr, code, node, t)

    # ------------------------------------------------------------------
    # latency attribution (no-ops unless ``blame`` was requested;
    # identical cell layout to the jax engines — observability/blame.py)
    # ------------------------------------------------------------------

    def _bl(self, req: Request, comp: int, phase: int, secs: float) -> None:
        """Credit ``secs`` of ``req``'s latency to ``(component, phase)``."""
        if not self.blame or secs <= 0.0:
            return
        if req.blame is None:
            req.blame = np.zeros(self._bl_cells, np.float64)
        req.blame[comp * _blm.N_PHASES + phase] += secs

    def _bl_complete(self, req: Request) -> None:
        """Scatter the completed attempt's row, keyed by its latency bin."""
        if not self.blame:
            return
        lat = req.finish_time - req.initial_time
        # host replica of jaxsim.sampling.latency_bin / hist_constants
        # (HIST_LO_S=1e-4, HIST_HI_S=1e3), run in float32 so bin choices
        # agree with the device engines at bin edges
        lo = np.float32(np.log(1e-4))
        scale = np.float32(self.n_hist_bins / (np.log(1e3) - np.log(1e-4)))
        fine = int(
            np.clip(
                np.int32(
                    (np.log(np.maximum(np.float32(lat), np.float32(1e-6))) - lo)
                    * scale,
                ),
                0,
                self.n_hist_bins - 1,
            ),
        )
        b = min(fine // self._bl_stride, self._bl_nb - 1)
        row = (
            req.blame
            if req.blame is not None
            else np.zeros(self._bl_cells, np.float64)
        )
        self.bl_grid[:, b] += row
        self.bl_lat[b] += lat
        self.blame_rows.append(row)

    def _bk_rec(self, edge_id: str, state: int, t: float) -> None:
        """One circuit-breaker state transition (bounded like the ring)."""
        if self.trace is None:
            return
        if len(self.breaker_timeline) < self.trace.breaker_slots:
            self.breaker_timeline.append(
                (t, self._lb_slot_idx.get(edge_id, -1), state),
            )

    # ------------------------------------------------------------------
    # serving token draws (variance 0 is exactly the mean in BOTH engines
    # — the variance-0 flight-record parity gate depends on it)
    # ------------------------------------------------------------------

    def draw_tokens(self, rv) -> float:
        """One token-count draw (prompt or output length): the mean at
        variance 0, else normal clamped to at least one token."""
        if rv.variance <= 0.0:
            return max(1.0, float(rv.mean))
        return max(
            1.0, float(self.rng.normal(rv.mean, math.sqrt(rv.variance))),
        )

    def draw_rate(self, rv) -> float:
        """One decode-rate draw, clamped to a 10%-of-mean floor (keeps
        decode durations finite under wide variance)."""
        if rv.variance <= 0.0:
            return float(rv.mean)
        return max(
            0.1 * float(rv.mean),
            float(self.rng.normal(rv.mean, math.sqrt(rv.variance))),
        )

    # ------------------------------------------------------------------
    # build phase
    # ------------------------------------------------------------------

    def _wire(self) -> None:
        graph = self.payload.topology_graph
        lb_id = self.lb.id if self.lb is not None else None

        for edge in graph.edges:
            runtime = self.edges[edge.id]

            if edge.target in self.servers:
                runtime.deliver_to = self.servers[edge.target].receive
            elif edge.target == self.client_id:
                runtime.deliver_to = self._client_receive
            elif edge.target == lb_id:
                runtime.deliver_to = self._lb_receive
            else:  # pragma: no cover - schema validation forbids this
                msg = f"Unknown edge target {edge.target!r}"
                raise ValueError(msg)

            if edge.source in self._gen_ids:
                self.generator_out_by_id[edge.source] = runtime
            elif edge.source == self.client_id:
                self.client_out = runtime
            elif edge.source == lb_id:
                self.lb_out_edges[edge.id] = runtime
            elif edge.source in self.servers:
                self.servers[edge.source].out_edge = runtime

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    def _spawn_request(self, workload_id: str, out: _EdgeRuntime, req: Request) -> None:
        """Shared spawn tail: hop record, trace sampling, client timers,
        entry transport (identical for stochastic and replay arrivals)."""
        req.record_hop(
            SystemNodes.GENERATOR,
            workload_id,
            self.sim.now,
        )
        if self.trace is not None:
            # deterministic sampling: the first K spawns are traced
            seq = self.total_generated - 1
            if seq < self.trace.sample_requests:
                req.fr = self.flight.setdefault(seq, FlightRecord(req=seq))
            self._fr(
                req, FR_SPAWN, self._gen_fr_idx[workload_id], self.sim.now,
            )
        if self.retry.enabled:
            self.sim.after(
                self.retry.timeout,
                lambda r=req: self._on_timeout(r),
            )
        if self.hedge.enabled:
            self._hedge_arm(req)
        out.transport(req)

    def _generator_process(self, workload):
        """One arrival process per generator; multi-generator payloads
        superpose (each with its own workload params and entry edge)."""
        out = self.generator_out_by_id[workload.id]
        if self.retry.enabled or self.hedge.enabled:
            self._entry_out = out
            self._entry_gen_id = workload.id
        if workload.replay is not None:
            # trace replay: the deterministic arrival table replaces the
            # stochastic process outright — request r spawns at
            # replay.times[r] exactly (arrivals past the horizon never
            # spawn), with optional per-request token presets
            replay = workload.replay
            horizon = float(self.settings.total_simulation_time)
            now = 0.0
            for r, t in enumerate(replay.times):
                if t >= horizon:
                    break
                yield Timeout(t - now)
                now = t
                self.total_generated += 1
                req = Request(
                    id=self.total_generated, initial_time=self.sim.now,
                )
                if replay.input_tokens is not None:
                    req.tok_in = float(replay.input_tokens[r])
                if replay.output_tokens is not None:
                    req.tok_out = float(replay.output_tokens[r])
                self._spawn_request(workload.id, out, req)
            return
        for gap in arrival_gaps(
            workload,
            self.settings,
            rng=self.rng,
        ):
            yield Timeout(gap)
            self.total_generated += 1
            req = Request(id=self.total_generated, initial_time=self.sim.now)
            self._spawn_request(workload.id, out, req)

    def _client_receive(self, req: Request) -> None:
        req.record_hop(SystemNodes.CLIENT, self.client_id, self.sim.now)
        # Second client visit == round trip done (reference hop-count protocol:
        # generator + edge + first client visit leave exactly 3 hops).
        if len(req.history) > 3:
            req.finish_time = self.sim.now
            if req.orphan:
                # the client already timed out and moved on: the orphaned
                # completion is invisible (no latency, cost, or trace)
                req.settled = True
                self._hedge_release(req)
                return
            group = req.hedge
            if group is not None:
                if group.done:
                    # a sibling already won the race (or the ladder gave
                    # up): this arrival is a loser — dedup silently
                    self._fr_rec(
                        group.anchor.fr, FR_CANCEL, req.is_hedge, self.sim.now,
                    )
                    req.settled = True
                    self._hedge_release(req)
                    return
                group.done = True
                if req.is_hedge:
                    self.hedges_won += 1
            req.settled = True
            if group is not None:
                # the logical request's record rides the ANCHOR's ring (a
                # winning duplicate completes the primary's record)
                self._fr_rec(group.anchor.fr, FR_COMPLETE, -1, self.sim.now)
            else:
                self._fr(req, FR_COMPLETE, -1, self.sim.now)
            if self.retry.enabled:
                self._record_attempts(req.attempt)
            if req.degraded:
                self.degraded_completions += 1
            self.rqs_clock.append((req.initial_time, req.finish_time))
            self.llm_costs.append(req.llm_cost)
            self._bl_complete(req)
            if self.collect_traces:
                self.traces[req.id] = [
                    (hop.component_type, hop.component_id, hop.timestamp)
                    for hop in req.history
                ]
            self._hedge_release(req)
        else:
            assert self.client_out is not None
            self.client_out.transport(req)

    def _lb_receive(self, req: Request) -> None:
        assert self.lb is not None
        if self.hedge_checkpoint(req):
            # the hedge race is already won: cancel instead of routing
            return
        req.record_hop(SystemNodes.LOAD_BALANCER, self.lb.id, self.sim.now)
        self._fr(req, FR_ARRIVE_LB, -1, self.sim.now)
        if not self.lb_out_edges:
            # Every covered server is down (possible when the LB covers a
            # subset of the declared servers): the request has nowhere to go.
            req.finish_time = self.sim.now
            self.total_dropped += 1
            self._fr(req, FR_DROP, -1, self.sim.now)
            self.client_fail(req)
            return
        out = self._pick_lb_edge()
        if out is None:
            # every rotation member's breaker is open (or saturated with
            # probes): the LB refuses the request — an overload
            # protection, counted rejected like the server-side policies
            req.finish_time = self.sim.now
            req.record_hop(
                SystemNodes.LOAD_BALANCER,
                f"{self.lb.id}-rejected",
                self.sim.now,
            )
            self.total_rejected += 1
            self._fr(req, FR_REJECT, -1, self.sim.now)
            self.client_fail(req)
            return
        if self.breaker is not None or self.health.enabled:
            # arm the report-once outcome channel (feeds the breaker AND
            # the health gate; cleared by the first report)
            req.lb_edge_id = out.cfg.id
            if self.breaker is not None:
                st = self._breaker_st(out.cfg.id)
                if st["state"] == 2:  # half-open: this request is a probe
                    req.probe = True
                    st["probes_out"] += 1
        out.transport(req)

    def _breaker_st(self, edge_id: str) -> dict:
        return self.breaker_state.setdefault(
            edge_id,
            {"state": 0, "consec": 0, "open_until": 0.0,
             "probes_out": 0, "probe_ok": 0},
        )

    def _breaker_admits(self, edge_id: str) -> bool:
        """Lazy state advance + routing eligibility of one rotation slot."""
        if self.breaker is None:
            return True
        st = self._breaker_st(edge_id)
        now = self.sim.now
        if st["state"] == 1:
            if now < st["open_until"]:
                return False
            # cooldown elapsed: half-open with fresh probe slots
            st["state"] = 2
            st["probes_out"] = 0
            st["probe_ok"] = 0
            self._bk_rec(edge_id, 2, now)
        if st["state"] == 2:
            return st["probes_out"] < self.breaker.half_open_probes
        return True

    def _health_st(self, edge_id: str) -> dict:
        return self.health_state.setdefault(
            edge_id, {"h": 0.0, "until": 0.0},
        )

    def _health_admits(self, edge_id: str) -> bool:
        """Lazy readmission + health eligibility of one rotation slot
        (``until`` > 0 means ejected; an elapsed lapse rejoins with a
        fresh EWMA before this pick considers it)."""
        hs = self._health_st(edge_id)
        if hs["until"] > 0.0 and self.sim.now >= hs["until"]:
            hs["h"] = 0.0
            hs["until"] = 0.0
        return hs["until"] <= 0.0

    def _health_pool(self, eligible: list[str]) -> list[str]:
        """Health gate over breaker-admitted members, with panic bypass:
        when EVERY admitted member is ejected, route on breaker admits
        alone — an all-ejected rotation must not blackhole traffic."""
        if not self.health.enabled:
            return eligible
        healthy = [eid for eid in eligible if self._health_admits(eid)]
        return healthy or eligible

    def _pick_lb_edge(self) -> _EdgeRuntime | None:
        assert self.lb is not None
        edges = self.lb_out_edges
        if self.lb_weights is not None:
            eligible = self._health_pool(
                [eid for eid in edges if self._breaker_admits(eid)],
            )
            if not eligible:
                return None
            w = np.array([self.lb_weights.get(eid, 0.0) for eid in eligible])
            if w.sum() <= 0:
                w = np.ones(len(eligible))
            pick = eligible[int(self.rng.choice(len(eligible), p=w / w.sum()))]
            return edges[pick]
        if self.lb.algorithms == LbAlgorithmsName.LEAST_CONNECTIONS:
            eligible = self._health_pool(
                [eid for eid in edges if self._breaker_admits(eid)],
            )
            if not eligible:
                return None
            best_id = min(eligible, key=lambda eid: edges[eid].concurrent)
            return edges[best_id]
        # round robin: first ADMITTING edge in rotation order; only the
        # picked edge rotates to the tail (ineligible edges keep their
        # position — the breaker skips, it does not reorder)
        if not self.health.enabled:
            for eid in list(edges):
                if self._breaker_admits(eid):
                    edges.move_to_end(eid)
                    return edges[eid]
            return None
        pool = set(
            self._health_pool(
                [eid for eid in list(edges) if self._breaker_admits(eid)],
            ),
        )
        for eid in list(edges):
            if eid in pool:
                edges.move_to_end(eid)
                return edges[eid]
        return None

    # routing-outcome feedback (called by edges and servers; no-ops once
    # the request's routing slot has reported) — ONE report feeds both
    # outlier channels: the circuit breaker's consecutive-failure state
    # machine and the LB health gate's EWMA (HealthScalars.observe)

    def breaker_failure(self, req: Request) -> None:
        self._server_report(req, failed=True)

    def breaker_success(self, req: Request) -> None:
        self._server_report(req, failed=False)

    def _server_report(self, req: Request, *, failed: bool) -> None:
        if req.lb_edge_id is None:
            return
        edge_id = req.lb_edge_id
        req.lb_edge_id = None
        now = self.sim.now
        if self.breaker is not None:
            st = self._breaker_st(edge_id)
            if failed:
                if req.probe:
                    req.probe = False
                    st["probes_out"] = max(0, st["probes_out"] - 1)
                    # a probe failure re-opens immediately
                    st["state"] = 1
                    st["open_until"] = now + self.breaker.cooldown_s
                    self._bk_rec(edge_id, 1, now)
                elif st["state"] == 0:
                    st["consec"] += 1
                    if st["consec"] >= self.breaker.failure_threshold:
                        st["state"] = 1
                        st["open_until"] = now + self.breaker.cooldown_s
                        st["consec"] = 0
                        self._bk_rec(edge_id, 1, now)
            elif req.probe:
                req.probe = False
                st["probes_out"] = max(0, st["probes_out"] - 1)
                st["probe_ok"] += 1
                if (
                    st["state"] == 2
                    and st["probe_ok"] >= self.breaker.half_open_probes
                ):
                    st["state"] = 0
                    st["consec"] = 0
                    self._bk_rec(edge_id, 0, now)
            elif st["state"] == 0:
                st["consec"] = 0
        if self.health.enabled:
            hs = self._health_st(edge_id)
            h = self.health.observe(hs["h"], failed)
            in_rotation = hs["until"] <= 0.0
            hs["h"] = h
            if in_rotation and h >= self.health.threshold:
                # outlier ejection: out of rotation until the readmit
                # lapse (in-flight reports to an ejected slot keep
                # updating its EWMA without re-extending the ejection)
                hs["until"] = now + self.health.readmit
                self.lb_ejections += 1

    # ------------------------------------------------------------------
    # resilience: fault lookups + client retry/timeout/backoff
    # ------------------------------------------------------------------

    def edge_fault_at(self, edge_id: str, now: float) -> tuple[float, float]:
        """(latency factor, dropout boost) active on ``edge_id`` at ``now``."""
        if not self._faults.has_faults:
            return 1.0, 0.0
        return self._faults.edge_fault(self._edge_idx[edge_id], now)

    def server_faulted(self, server_id: str, now: float) -> bool:
        """True while ``server_id`` sits inside an outage fault window."""
        return self._faults.has_faults and self._faults.server_down(
            self._server_idx[server_id], now,
        )

    def _retry_token(self) -> bool:
        """Lazily refill the retry-budget bucket and take one token."""
        if self.retry.budget_tokens < 0:
            return True  # unlimited budget
        now = self.sim.now
        self._rb_tokens = min(
            self.retry.budget_tokens,
            self._rb_tokens + (now - self._rb_last) * self.retry.budget_refill,
        )
        self._rb_last = now
        if self._rb_tokens >= 1.0:
            self._rb_tokens -= 1.0
            return True
        self.retry_budget_exhausted += 1
        return False

    def _backoff(self, attempt: int) -> float:
        """Backoff before re-issuing after ``attempt`` failed, with the
        jitter factor drawn from the seeded engine RNG."""
        delay = min(
            self.retry.backoff_cap,
            self.retry.backoff_base
            * self.retry.backoff_mult ** max(attempt - 1, 0),
        )
        if self.retry.jitter > 0:
            delay *= 1.0 + self.retry.jitter * (2.0 * self.rng.uniform() - 1.0)
        return delay

    def _record_attempts(self, attempt: int) -> None:
        self.attempts_hist[
            min(attempt, len(self.attempts_hist)) - 1
        ] += 1

    def issue(self, req: Request) -> None:
        """Send one attempt down the entry chain, arming its client
        deadline (no-op without a retry policy)."""
        out = self._entry_out
        assert out is not None
        if self.retry.enabled:
            self.sim.after(
                self.retry.timeout, lambda: self._on_timeout(req),
            )
        out.transport(req)

    def _on_timeout(self, req: Request) -> None:
        """The client's per-attempt deadline fired: if the attempt is
        still unresolved, orphan it (server-side work continues — the
        retry-storm amplification channel) and maybe re-issue."""
        if req.settled or req.orphan:
            return
        req.orphan = True
        self.total_timed_out += 1
        # the logical request's record detaches from the orphaned attempt
        # (its server-side tail is invisible, like its completion) and
        # rides any re-issue instead
        fr = req.fr
        self._fr_rec(fr, FR_TIMEOUT, req.attempt, self.sim.now)
        req.fr = None
        if self._maybe_reissue(req, fr) and req.hedge is not None:
            # the backoff re-issue is one more live attempt of the SAME
            # logical request (the orphan keeps draining on its own count)
            req.hedge.live += 1

    def client_fail(self, req: Request) -> None:
        """A tracked attempt failed (drop / refusal / shed / abandon /
        outage) and the client notices at failure time: back off and
        re-issue, or give the logical request up.  Orphaned attempts are
        already abandoned — their failures are silent, as are hedge
        duplicates (invisible to the retry ladder: a failed duplicate
        just drops its anchor refcount)."""
        group = req.hedge
        if group is not None and (req.is_hedge or req.orphan or req.settled):
            req.settled = True
            self._hedge_release(req)
            return
        if not self.retry.enabled:
            if group is not None:
                # no ladder: the primary's death ends ITS attempt only —
                # outstanding duplicates may still win the race
                req.settled = True
                self._hedge_release(req)
            return
        if req.orphan or req.settled:
            req.settled = True
            return
        req.settled = True
        if not self._maybe_reissue(req) and group is not None:
            self._hedge_release(req)
        # on a re-issue the backoff attempt inherits this one's refcount

    def _maybe_reissue(
        self, req: Request, fr: FlightRecord | None = None,
    ) -> bool:
        """Back off and re-issue ``req``'s logical request, or give it up.
        Returns True when a re-issue was scheduled."""
        if fr is None:
            fr = req.fr
        group = req.hedge
        if req.attempt >= self.retry.max_attempts or not self._retry_token():
            self._fr_rec(fr, FR_ABANDON, req.attempt, self.sim.now)
            self._record_attempts(req.attempt)
            if group is not None:
                # the client gave the logical request up: the race is over
                # (late siblings dedup as losers; the timer disarms)
                group.done = True
            return False
        self.total_retries += 1
        self._fr_rec(fr, FR_RETRY, req.attempt, self.sim.now)
        delay = self._backoff(req.attempt)
        attempt = req.attempt + 1

        def reissue() -> None:
            new_req = Request(
                id=req.id,
                initial_time=self.sim.now,
                attempt=attempt,
                fr=fr,
                hedge=group,
            )
            if group is not None and group.anchor is req:
                # an in-place re-issue keeps the anchor identity (the jax
                # engine re-parks the anchor slot): duplicates fired later
                # copy the NEW attempt's start time and attempt number
                group.anchor = new_req
            if self._entry_gen_id is not None:
                new_req.record_hop(
                    SystemNodes.GENERATOR, self._entry_gen_id, self.sim.now,
                )
            self._fr(new_req, FR_SPAWN, 0, self.sim.now)
            self.issue(new_req)

        self.sim.after(delay, reissue)
        return True

    # ------------------------------------------------------------------
    # hedged requests (inert without a policy)
    # ------------------------------------------------------------------

    def _hedge_arm(self, req: Request) -> None:
        """Attach the spawn's race state and start its hedge timer."""
        group = _HedgeGroup(anchor=req)
        req.hedge = group
        self.sim.after(self.hedge.delay, lambda: self._hedge_fire(group))

    def _hedge_fire(self, group: _HedgeGroup) -> None:
        """The hedge timer fired: issue a speculative duplicate down the
        entry chain without abandoning the original.  The duplicate
        copies the anchor's identity — start time, attempt number — but
        carries no client deadline (hedges are invisible to the retry
        ladder) and records only FR_HEDGE: its transit noise stays out
        of the flight record.  Re-arms one delay out until the
        per-request budget is spent; stale timers (race won, every
        attempt dead) just disarm."""
        if group.done or group.live <= 0 or group.n >= self.hedge.max_hedges:
            return
        group.n += 1
        ordinal = group.n
        self.total_hedges += 1
        anchor = group.anchor
        self._fr_rec(anchor.fr, FR_HEDGE, ordinal, self.sim.now)
        if ordinal < self.hedge.max_hedges:
            self.sim.after(self.hedge.delay, lambda: self._hedge_fire(group))
        dup = Request(
            id=anchor.id,
            initial_time=anchor.initial_time,
            attempt=anchor.attempt,
            hedge=group,
            is_hedge=1,
        )
        group.live += 1
        # a winning duplicate's clock starts at the ANCHOR's spawn: the
        # gap until this fire is hedge wait, blamed on the client
        self._bl(
            dup, self._bl_client, _blm.PH_HEDGE,
            self.sim.now - anchor.initial_time,
        )
        if self._entry_gen_id is not None:
            dup.record_hop(
                SystemNodes.GENERATOR, self._entry_gen_id, self.sim.now,
            )
        out = self._entry_out
        assert out is not None
        out.transport(dup)

    def _hedge_release(self, req: Request) -> None:
        """Attempt ``req`` drained: drop the race's live refcount.  At
        zero the logical request is gone — hedging duplicates
        OUTSTANDING work; it never resurrects a dead request."""
        group = req.hedge
        if group is None or req.hg_released:
            return
        req.hg_released = True
        group.live -= 1
        if group.live <= 0:
            group.done = True

    def hedge_checkpoint(self, req: Request) -> bool:
        """Routing-boundary cancellation (``cancel_on_first`` only): True
        when the arriving attempt lost an already-settled race and was
        cancelled here instead of admitted.  A cancelled attempt vanishes
        WITHOUT reporting to the breaker/health channels (its half-open
        probe reservation is returned so the round isn't starved)."""
        group = req.hedge
        if group is None or not self.hedge.cancel or not group.done:
            return False
        self._fr_rec(group.anchor.fr, FR_CANCEL, req.is_hedge, self.sim.now)
        if req.probe and req.lb_edge_id is not None:
            st = self._breaker_st(req.lb_edge_id)
            st["probes_out"] = max(0, st["probes_out"] - 1)
        req.probe = False
        req.lb_edge_id = None
        req.finish_time = self.sim.now
        req.settled = True
        self.hedges_cancelled += 1
        self._hedge_release(req)
        return True

    # ------------------------------------------------------------------
    # event injection
    # ------------------------------------------------------------------

    def _schedule_events(self) -> None:
        events = self.payload.events or []
        server_ids = set(self.servers)
        timeline: list[tuple[float, int, str, str, str, float]] = []
        # mark order within identical timestamps: END (0) before START (1)
        for event in events:
            if event.target_id in server_ids and (
                event.start.kind == EventDescription.SERVER_DOWN
            ):
                timeline.append(
                    (event.start.t_start, 1, event.event_id, "server", event.target_id, 0.0),
                )
                timeline.append(
                    (event.end.t_end, 0, event.event_id, "server", event.target_id, 0.0),
                )
            elif event.start.kind == EventDescription.NETWORK_SPIKE_START:
                spike = float(event.start.spike_s or 0.0)
                timeline.append(
                    (event.start.t_start, 1, event.event_id, "edge", event.target_id, spike),
                )
                timeline.append(
                    (event.end.t_end, 0, event.event_id, "edge", event.target_id, -spike),
                )
        timeline.sort(key=lambda entry: (entry[0], entry[1], entry[2], entry[4]))

        server_to_lb_edge = {
            runtime.cfg.target: (edge_id, runtime)
            for edge_id, runtime in self.lb_out_edges.items()
        }

        for time, mark, _event_id, kind, target, delta in timeline:
            if kind == "edge":
                def apply_spike(edge_id: str = target, amount: float = delta) -> None:
                    self.edge_spike[edge_id] = (
                        self.edge_spike.get(edge_id, 0.0) + amount
                    )

                self.sim.at(time, apply_spike)
            else:
                is_down = mark == 1

                def apply_outage(server_id: str = target, down: bool = is_down) -> None:
                    info = server_to_lb_edge.get(server_id)
                    if info is None:
                        return
                    edge_id, runtime = info
                    if down:
                        self.lb_out_edges.pop(edge_id, None)
                    else:
                        self.lb_out_edges[edge_id] = runtime
                        self.lb_out_edges.move_to_end(edge_id)

                self.sim.at(time, apply_outage)

    # ------------------------------------------------------------------
    # metric collection
    # ------------------------------------------------------------------

    def _schedule_collector(self) -> None:
        period = self.settings.sample_period_s
        enabled = self.settings.enabled_sample_metrics
        sample_edges = SampledMetricName.EDGE_CONCURRENT_CONNECTION in enabled
        sample_servers = {
            SampledMetricName.READY_QUEUE_LEN,
            SampledMetricName.EVENT_LOOP_IO_SLEEP,
            SampledMetricName.RAM_IN_USE,
        } <= enabled

        def sample() -> None:
            if sample_edges:
                for edge in self.edges.values():
                    edge.series.append(edge.concurrent)
            if sample_servers:
                for server in self.servers.values():
                    server.series[SampledMetricName.RAM_IN_USE].append(
                        server.ram_in_use,
                    )
                    server.series[SampledMetricName.EVENT_LOOP_IO_SLEEP].append(
                        server.io_queue_len,
                    )
                    server.series[SampledMetricName.READY_QUEUE_LEN].append(
                        server.ready_queue_len,
                    )
            self.sim.after(period, sample)

        self.sim.after(period, sample)

    # ------------------------------------------------------------------
    # run
    # ------------------------------------------------------------------

    def start(self) -> None:
        """Schedule the scenario's processes without running it — the
        setup shared by :meth:`run` and incremental drivers (the RL
        playground steps the clock with ``sim.run(until=...)``)."""
        self._schedule_events()
        for workload in self.payload.generators:
            self.sim.process(self._generator_process(workload))
        self._schedule_collector()

    def run(self) -> SimulationResults:
        """Execute the scenario and reduce to :class:`SimulationResults`."""
        self.start()
        self.sim.run(until=float(self.settings.total_simulation_time))

        sampled: dict[str, dict[str, np.ndarray]] = {}
        enabled = self.settings.enabled_sample_metrics
        if SampledMetricName.EDGE_CONCURRENT_CONNECTION in enabled:
            sampled[SampledMetricName.EDGE_CONCURRENT_CONNECTION.value] = {
                edge_id: np.asarray(edge.series, dtype=np.float64)
                for edge_id, edge in self.edges.items()
            }
        for metric in (
            SampledMetricName.READY_QUEUE_LEN,
            SampledMetricName.EVENT_LOOP_IO_SLEEP,
            SampledMetricName.RAM_IN_USE,
        ):
            if metric in enabled:
                sampled[metric.value] = {
                    server_id: np.asarray(server.series[metric], dtype=np.float64)
                    for server_id, server in self.servers.items()
                }

        clock = (
            np.asarray(self.rqs_clock, dtype=np.float64)
            if self.rqs_clock
            else np.empty((0, 2), dtype=np.float64)
        )

        # resilience scorecard: same pure-table math as the JAX paths
        unavailable_s = None
        degraded_goodput = None
        hazard_truncated = 0
        time_to_drain = None
        if self._hz_tables is not None:
            from asyncflow_tpu.compiler import hazards as _hz

            horizon = float(self.settings.total_simulation_time)
            hazard_truncated = int(self._hz_tables.truncated[0])
            unavailable_s = _hz.unavailable_seconds(
                self._hz_tables.srv_times, self._hz_tables.srv_down, horizon,
            )[0]
            n_thr = int(np.ceil(horizon)) or 1
            thr_row = np.zeros(n_thr)
            if clock.shape[0]:
                # same bucket rule as the device engines: bucket b counts
                # completions with ceil(finish) - 1 == b, clipped in range
                tbin = np.clip(
                    np.ceil(clock[:, 1]).astype(np.int64) - 1, 0, n_thr - 1,
                )
                np.add.at(thr_row, tbin, 1.0)
            mask = _hz.degraded_seconds_mask(self._hz_tables, horizon, n_thr)
            degraded_goodput = float(thr_row[mask[0]].sum())
            ready = sampled.get(SampledMetricName.READY_QUEUE_LEN.value)
            if ready:
                series = np.stack(
                    [ready[sid] for sid in self.servers], axis=-1,
                )[None]
                first, last = _hz.window_span(self._hz_tables, horizon)
                drain = _hz.time_to_drain(
                    series,
                    float(self.settings.sample_period_s),
                    first,
                    last,
                )[0]
                time_to_drain = None if np.isnan(drain) else float(drain)

        return SimulationResults(
            settings=self.settings,
            rqs_clock=clock,
            sampled=sampled,
            total_generated=self.total_generated,
            total_dropped=self.total_dropped,
            total_rejected=self.total_rejected,
            server_ids=list(self.servers),
            edge_ids=list(self.edges),
            traces=self.traces if self.collect_traces else None,
            flight=self.flight if self.trace is not None else None,
            breaker_timeline=(
                self.breaker_timeline if self.trace is not None else None
            ),
            llm_cost=(
                np.asarray(self.llm_costs, dtype=np.float64)
                if self._has_llm
                else None
            ),
            total_timed_out=self.total_timed_out,
            total_retries=self.total_retries,
            retry_budget_exhausted=self.retry_budget_exhausted,
            attempts_hist=(
                self.attempts_hist.copy() if self.retry.enabled else None
            ),
            total_hedges=self.total_hedges,
            hedges_won=self.hedges_won,
            hedges_cancelled=self.hedges_cancelled,
            lb_ejections=self.lb_ejections,
            degraded_completions=self.degraded_completions,
            dark_lost=self.dark_lost,
            unavailable_s=unavailable_s,
            degraded_goodput=degraded_goodput,
            hazard_truncated=hazard_truncated,
            time_to_drain=time_to_drain,
            blame=self.bl_grid if self.blame else None,
            blame_lat=self.bl_lat if self.blame else None,
            blame_req=(
                (
                    np.stack(self.blame_rows)
                    if self.blame_rows
                    else np.empty((0, self._bl_cells), np.float64)
                )
                if self.blame
                else None
            ),
            kv_evictions=self.kv_evictions if self._has_serving else None,
            prefill_tokens=(
                self.prefill_tokens if self._has_serving else None
            ),
            decode_tokens=self.decode_tokens if self._has_serving else None,
        )
