"""Minimal discrete-event kernel for the oracle engine.

A self-contained replacement for the SimPy machinery the reference builds on
(`simpy.Environment` heap + coroutine processes + FIFO `Container`s, see
`/root/reference/src/asyncflow/runtime/simulation_runner.py:369` and
`resources/server_containers.py:34-70`): a binary-heap event loop, a
generator-coroutine driver, and two FIFO resources.

Processes are plain Python generators that yield *awaitables*:

    yield Timeout(0.5)              # resume 0.5 simulated seconds later
    yield AcquireToken(cpu)         # resume when one token is granted
    yield AcquireAmount(ram, 128)   # resume when 128 units are granted

Releases are synchronous (``tokens.release()``, ``container.release(x)``);
woken waiters are scheduled at the current timestamp so ordering stays
heap-driven and FIFO.
"""

from __future__ import annotations

import heapq
from collections import deque
from collections.abc import Callable, Generator
from dataclasses import dataclass
from typing import Any

Process = Generator["Awaitable", Any, None]


class Sim:
    """Binary-heap event loop: (time, seq) ordered callbacks."""

    def __init__(self) -> None:
        self.now: float = 0.0
        self._heap: list[tuple[float, int, Callable[[], None]]] = []
        self._seq: int = 0

    def at(self, time: float, fn: Callable[[], None]) -> None:
        """Schedule ``fn`` at absolute simulated ``time``."""
        heapq.heappush(self._heap, (time, self._seq, fn))
        self._seq += 1

    def after(self, delay: float, fn: Callable[[], None]) -> None:
        """Schedule ``fn`` ``delay`` seconds from now."""
        self.at(self.now + delay, fn)

    def run(self, until: float) -> None:
        """Pop-and-call until the next event would be at ``time >= until``.

        Events scheduled exactly at ``until`` are not executed, matching
        SimPy's ``env.run(until=...)`` semantics the reference relies on.
        """
        while self._heap and self._heap[0][0] < until:
            time, _, fn = heapq.heappop(self._heap)
            self.now = time
            fn()
        self.now = until

    # -- coroutine driver ---------------------------------------------------

    def process(self, gen: Process) -> None:
        """Start driving a generator process from its first yield."""

        def step(value: Any = None) -> None:
            try:
                awaitable = gen.send(value)
            except StopIteration:
                return
            awaitable.arrange(self, step)

        step()


@dataclass(frozen=True)
class Timeout:
    """Resume after a fixed simulated delay."""

    delay: float

    def arrange(self, sim: Sim, resume: Callable[[Any], None]) -> None:
        sim.after(self.delay, resume)


class FifoTokens:
    """Counted tokens with a strict-FIFO wait queue (the CPU-core resource)."""

    def __init__(self, sim: Sim, capacity: int) -> None:
        self._sim = sim
        self.capacity = capacity
        self.available = capacity
        self._waiters: deque[Callable[[Any], None]] = deque()

    @property
    def would_block(self) -> bool:
        """True if an acquire issued right now could not be granted immediately."""
        return self.available <= 0 or bool(self._waiters)

    def _acquire(self, resume: Callable[[Any], None]) -> None:
        if self.available > 0 and not self._waiters:
            self.available -= 1
            self._sim.at(self._sim.now, resume)
        else:
            self._waiters.append(resume)

    def release(self) -> None:
        """Return one token; the longest-waiting acquirer is granted first."""
        if self._waiters:
            resume = self._waiters.popleft()
            self._sim.at(self._sim.now, resume)
        else:
            self.available = min(self.capacity, self.available + 1)


@dataclass(frozen=True)
class AcquireToken:
    """Awaitable wrapper over :class:`FifoTokens`."""

    tokens: FifoTokens

    def arrange(self, sim: Sim, resume: Callable[[Any], None]) -> None:  # noqa: ARG002
        self.tokens._acquire(resume)


class FifoContainer:
    """Continuous-level container with strict-FIFO, head-of-line blocking gets.

    Mirrors the semantics of a pre-filled ``simpy.Container`` used for RAM in
    the reference: a large request at the queue head blocks smaller later
    requests even when they would fit.
    """

    def __init__(self, sim: Sim, capacity: float) -> None:
        self._sim = sim
        self.capacity = capacity
        self.level = capacity
        self._waiters: deque[tuple[float, Callable[[Any], None]]] = deque()

    @property
    def would_block(self) -> bool:
        return bool(self._waiters)

    def _acquire(self, amount: float, resume: Callable[[Any], None]) -> None:
        if not self._waiters and self.level >= amount:
            self.level -= amount
            self._sim.at(self._sim.now, resume)
        else:
            self._waiters.append((amount, resume))

    def release(self, amount: float) -> None:
        """Return ``amount`` units and grant queued head-of-line requests."""
        self.level = min(self.capacity, self.level + amount)
        while self._waiters and self.level >= self._waiters[0][0]:
            head_amount, resume = self._waiters.popleft()
            self.level -= head_amount
            self._sim.at(self._sim.now, resume)


@dataclass(frozen=True)
class AcquireAmount:
    """Awaitable wrapper over :class:`FifoContainer`."""

    container: FifoContainer
    amount: float

    def arrange(self, sim: Sim, resume: Callable[[Any], None]) -> None:  # noqa: ARG002
        self.container._acquire(self.amount, resume)


class ServingGate:
    """Two-resource FIFO admission gate for continuous batching.

    The LLM serving batch is bounded along two axes at once: concurrent
    batch slots (requests) and resident KV tokens.  An admission needs one
    slot AND ``tokens`` token units; grants are strict-FIFO with
    head-of-line blocking (the :class:`FifoContainer` discipline lifted to
    two resources).  Running requests extend their token hold without
    queueing (:meth:`try_extend`) — the decode-start fast path of
    continuous batching, where generation extensions outrank queued
    admissions and a failed extension is an eviction, never a wait.
    """

    def __init__(self, sim: Sim, slots: int, tokens: float) -> None:
        self._sim = sim
        self.slots_free = slots
        self.tokens_free = tokens
        self._waiters: deque[tuple[float, Callable[[Any], None]]] = deque()

    @property
    def would_block(self) -> bool:
        return bool(self._waiters) or self.slots_free <= 0

    def _acquire(self, tokens: float, resume: Callable[[Any], None]) -> None:
        if (
            not self._waiters
            and self.slots_free > 0
            and self.tokens_free >= tokens
        ):
            self.slots_free -= 1
            self.tokens_free -= tokens
            self._sim.at(self._sim.now, resume)
        else:
            self._waiters.append((tokens, resume))

    def try_extend(self, tokens: float) -> bool:
        """Grow a resident request's token hold if it fits, never waiting."""
        if self.tokens_free >= tokens:
            self.tokens_free -= tokens
            return True
        return False

    def release(self, slots: int, tokens: float) -> None:
        """Return resources and cascade head-of-line admission grants."""
        self.slots_free += slots
        self.tokens_free += tokens
        while (
            self._waiters
            and self.slots_free > 0
            and self.tokens_free >= self._waiters[0][0]
        ):
            head_tokens, resume = self._waiters.popleft()
            self.slots_free -= 1
            self.tokens_free -= head_tokens
            self._sim.at(self._sim.now, resume)


@dataclass(frozen=True)
class AcquireServe:
    """Awaitable wrapper over :class:`ServingGate` (one slot + tokens)."""

    gate: ServingGate
    tokens: float

    def arrange(self, sim: Sim, resume: Callable[[Any], None]) -> None:  # noqa: ARG002
        self.gate._acquire(self.tokens, resume)
