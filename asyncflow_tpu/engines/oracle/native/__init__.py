"""ctypes bridge to the native C++ oracle core.

The shared library is compiled on demand from ``core.cpp`` with the system
g++ (no pybind11 in this environment — plain C ABI + ctypes).  When no
compiler is available the caller falls back to the pure-Python oracle.
"""

from __future__ import annotations

import ctypes
import subprocess
import tempfile
from pathlib import Path

import numpy as np

from asyncflow_tpu.checker.fences import raise_fence
from asyncflow_tpu.compiler.plan import StaticPlan
from asyncflow_tpu.config.constants import SampledMetricName
from asyncflow_tpu.engines.results import SimulationResults

_SRC = Path(__file__).parent / "core.cpp"
_LIB_NAME = "_afnative.so"

_i32p = ctypes.POINTER(ctypes.c_int32)
_f32p = ctypes.POINTER(ctypes.c_float)


class _PlanC(ctypes.Structure):
    _fields_ = [
        ("n_edges", ctypes.c_int32),
        ("edge_dist", _i32p),
        ("edge_mean", _f32p),
        ("edge_var", _f32p),
        ("edge_dropout", _f32p),
        ("n_entry", ctypes.c_int32),
        ("entry_edges", _i32p),
        ("entry_target_kind", ctypes.c_int32),
        ("entry_target", ctypes.c_int32),
        ("n_servers", ctypes.c_int32),
        ("max_endpoints", ctypes.c_int32),
        ("max_segments", ctypes.c_int32),
        ("server_cores", _i32p),
        ("server_ram", _f32p),
        ("server_db_pool", _i32p),
        ("server_queue_cap", _i32p),
        ("server_conn_cap", _i32p),
        ("server_rate_limit", _f32p),
        ("server_rate_burst", _i32p),
        ("server_queue_timeout", _f32p),
        ("n_endpoints", _i32p),
        ("seg_kind", _i32p),
        ("seg_dur", _f32p),
        ("seg_hit_prob", _f32p),
        ("seg_miss_dur", _f32p),
        ("seg_llm_tokens", _f32p),
        ("seg_llm_tpt", _f32p),
        ("seg_llm_cost", _f32p),
        ("endpoint_ram", _f32p),
        ("endpoint_cum", _f32p),
        ("exit_edge", _i32p),
        ("exit_kind", _i32p),
        ("exit_target", _i32p),
        ("lb_algo", ctypes.c_int32),
        ("n_lb_edges", ctypes.c_int32),
        ("lb_edge_index", _i32p),
        ("lb_target", _i32p),
        ("breaker_threshold", ctypes.c_int32),
        ("breaker_probes", ctypes.c_int32),
        ("breaker_cooldown", ctypes.c_double),
        ("n_spike_times", ctypes.c_int32),
        ("spike_times", _f32p),
        ("spike_values", _f32p),
        ("n_timeline", ctypes.c_int32),
        ("timeline_times", _f32p),
        ("timeline_down", _i32p),
        ("timeline_slot", _i32p),
        ("user_mean", ctypes.c_double),
        ("user_var", ctypes.c_double),
        ("user_window", ctypes.c_double),
        ("req_rate", ctypes.c_double),
        ("n_generators", ctypes.c_int32),
        ("gen_entry_width", ctypes.c_int32),
        ("gen_user_mean", ctypes.POINTER(ctypes.c_double)),
        ("gen_user_var", ctypes.POINTER(ctypes.c_double)),
        ("gen_window", ctypes.POINTER(ctypes.c_double)),
        ("gen_rate", ctypes.POINTER(ctypes.c_double)),
        ("gen_entry_edges", _i32p),
        ("gen_entry_len", _i32p),
        ("gen_entry_target_kind", _i32p),
        ("gen_entry_target", _i32p),
        ("horizon", ctypes.c_double),
        ("sample_period", ctypes.c_double),
        ("n_samples", ctypes.c_int64),
        ("max_requests", ctypes.c_int64),
    ]


_lib: ctypes.CDLL | None = None
_lib_error: str | None = None


def _build_library() -> Path:
    import os

    # per-user, 0700 cache dir: never load a .so another user could have
    # planted in the shared temp dir
    out_dir = Path(tempfile.gettempdir()) / f"asyncflow_tpu_native_{os.getuid()}"
    out_dir.mkdir(exist_ok=True, mode=0o700)
    if out_dir.stat().st_uid != os.getuid():
        out_dir = Path(tempfile.mkdtemp(prefix="asyncflow_tpu_native_"))
    out = out_dir / _LIB_NAME
    if out.exists() and out.stat().st_mtime >= _SRC.stat().st_mtime:
        return out
    # compile to a unique name, then move into place atomically so concurrent
    # processes never dlopen a half-written library
    tmp = out_dir / f"{_LIB_NAME}.{os.getpid()}.tmp"
    subprocess.run(
        [
            "g++",
            "-O2",
            "-shared",
            "-fPIC",
            "-std=c++17",
            str(_SRC),
            "-o",
            str(tmp),
        ],
        check=True,
        capture_output=True,
    )
    os.replace(tmp, out)
    return out


def load_library() -> ctypes.CDLL | None:
    """Compile (if needed) and load the native core; None when unavailable."""
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    try:
        path = _build_library()
        lib = ctypes.CDLL(str(path))
        lib.afnative_run.restype = ctypes.c_int64
        lib.afnative_run.argtypes = [
            ctypes.POINTER(_PlanC),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_double),
            _f32p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.afnative_run_traced.restype = ctypes.c_int64
        lib.afnative_run_traced.argtypes = [
            *lib.afnative_run.argtypes,
            _i32p,
            _f32p,
            _i32p,
            ctypes.c_int32,
        ]
        _lib = lib
    except (OSError, subprocess.CalledProcessError) as exc:
        _lib_error = str(exc)
    return _lib


def native_available() -> bool:
    return load_library() is not None


def _as_i32(arr: np.ndarray):
    arr = np.ascontiguousarray(arr, dtype=np.int32)
    return arr, arr.ctypes.data_as(_i32p)


def _as_f32(arr: np.ndarray):
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    return arr, arr.ctypes.data_as(_f32p)


def run_native(
    plan: StaticPlan,
    *,
    seed: int = 0,
    collect_gauges: bool = True,
    collect_traces: bool = False,
    payload=None,
    settings=None,
    trace=None,
) -> SimulationResults:
    """Run one scenario on the native core -> :class:`SimulationResults`.

    ``collect_traces=True`` records per-request hop rings through the C
    ABI (``afnative_run_traced``) with the oracle-identical structure
    (component type, component id, timestamp); ``payload`` is then
    required to decode generator/client/LB ids, which the compiled plan
    does not carry."""
    if trace is not None:
        # canonical refusals from the shared fence registry (the static
        # checker predicts these exact messages)
        raise_fence("trace.native")
    if collect_traces and payload is None:
        msg = "collect_traces=True needs the payload to decode component ids"
        raise ValueError(msg)
    if plan.has_faults or plan.has_retry:
        raise_fence("resilience.native")
    lib = load_library()
    if lib is None:
        msg = f"native core unavailable: {_lib_error}"
        raise RuntimeError(msg)

    keep = []  # keep numpy buffers alive across the call

    def i32(arr):
        a, ptr = _as_i32(arr)
        keep.append(a)
        if a.size == 0:
            return _i32p()  # null: the core falls back to legacy scalars
        return ptr

    def f32(arr):
        a, ptr = _as_f32(arr)
        keep.append(a)
        return ptr

    def f64(arr):
        a = np.ascontiguousarray(arr, dtype=np.float64)
        keep.append(a)
        if a.size == 0:
            return ctypes.POINTER(ctypes.c_double)()
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))

    c = _PlanC(
        n_edges=plan.n_edges,
        edge_dist=i32(plan.edge_dist),
        edge_mean=f32(plan.edge_mean),
        edge_var=f32(plan.edge_var),
        edge_dropout=f32(plan.edge_dropout),
        n_entry=len(plan.entry_edges),
        entry_edges=i32(plan.entry_edges),
        entry_target_kind=plan.entry_target_kind,
        entry_target=plan.entry_target,
        n_servers=plan.n_servers,
        max_endpoints=plan.max_endpoints,
        max_segments=plan.max_segments,
        server_cores=i32(plan.server_cores),
        server_ram=f32(plan.server_ram),
        # size-0 arrays are normalized to (-1,)*NS by StaticPlan.__post_init__
        server_db_pool=i32(plan.server_db_pool),
        server_queue_cap=i32(plan.server_queue_cap),
        server_conn_cap=i32(plan.server_conn_cap),
        server_rate_limit=f32(plan.server_rate_limit),
        server_rate_burst=i32(plan.server_rate_burst),
        server_queue_timeout=f32(plan.server_queue_timeout),
        n_endpoints=i32(plan.n_endpoints),
        seg_kind=i32(plan.seg_kind),
        seg_dur=f32(plan.seg_dur),
        seg_hit_prob=f32(plan.seg_hit_prob),
        seg_miss_dur=f32(plan.seg_miss_dur),
        seg_llm_tokens=f32(plan.seg_llm_tokens),
        seg_llm_tpt=f32(plan.seg_llm_tpt),
        seg_llm_cost=f32(plan.seg_llm_cost),
        endpoint_ram=f32(plan.endpoint_ram),
        endpoint_cum=f32(plan.endpoint_cum),
        exit_edge=i32(plan.exit_edge),
        exit_kind=i32(plan.exit_kind),
        exit_target=i32(plan.exit_target),
        lb_algo=plan.lb_algo,
        n_lb_edges=plan.n_lb_edges,
        lb_edge_index=i32(plan.lb_edge_index),
        lb_target=i32(plan.lb_target),
        breaker_threshold=plan.breaker_threshold,
        breaker_probes=plan.breaker_probes,
        breaker_cooldown=plan.breaker_cooldown,
        n_spike_times=len(plan.spike_times),
        spike_times=f32(plan.spike_times),
        spike_values=f32(plan.spike_values),
        n_timeline=len(plan.timeline_times),
        timeline_times=f32(plan.timeline_times),
        timeline_down=i32(plan.timeline_down),
        timeline_slot=i32(plan.timeline_slot),
        user_mean=plan.user_mean,
        user_var=plan.user_var,
        user_window=plan.user_window,
        req_rate=plan.req_per_user_per_sec,
        n_generators=plan.n_generators,
        gen_entry_width=(
            plan.gen_entry_edges.shape[1] if plan.gen_entry_edges.size else 0
        ),
        gen_user_mean=f64(plan.gen_user_mean),
        gen_user_var=f64(plan.gen_user_var),
        gen_window=f64(plan.gen_window),
        gen_rate=f64(plan.gen_rate),
        gen_entry_edges=i32(plan.gen_entry_edges),
        gen_entry_len=i32(plan.gen_entry_len),
        gen_entry_target_kind=i32(plan.gen_entry_target_kind),
        gen_entry_target=i32(plan.gen_entry_target),
        horizon=plan.horizon,
        sample_period=plan.sample_period,
        n_samples=plan.n_samples,
        max_requests=plan.max_requests,
    )

    clock = np.zeros((plan.max_requests, 2), dtype=np.float64)
    gauges = (
        np.zeros((plan.n_samples, plan.n_gauges), dtype=np.float32)
        if collect_gauges
        else None
    )
    counters = np.zeros(5, dtype=np.int64)

    llm = (
        np.zeros(plan.max_requests, dtype=np.float64)
        if plan.has_llm
        else None
    )
    llm_ptr = (
        llm.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        if llm is not None
        else ctypes.POINTER(ctypes.c_double)()
    )
    common = (
        ctypes.byref(c),
        ctypes.c_uint64(seed),
        clock.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        gauges.ctypes.data_as(_f32p) if gauges is not None else _f32p(),
        counters.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        llm_ptr,
    )
    tr_code = tr_t = tr_n = None
    if collect_traces:
        # same ring capacity formula as the jax event engine: sized by the
        # LONGEST generator entry chain
        max_entry = (
            int(plan.gen_entry_len.max())
            if plan.gen_entry_len.size
            else len(plan.entry_edges)
        )
        hop_cap = 1 + 2 * max_entry + 4 * max(plan.n_servers, 1) + 2
        tr_code = np.full((plan.max_requests, hop_cap), -1, dtype=np.int32)
        tr_t = np.zeros((plan.max_requests, hop_cap), dtype=np.float32)
        tr_n = np.zeros(plan.max_requests, dtype=np.int32)
        lib.afnative_run_traced(
            *common,
            tr_code.ctypes.data_as(_i32p),
            tr_t.ctypes.data_as(_f32p),
            tr_n.ctypes.data_as(_i32p),
            ctypes.c_int32(hop_cap),
        )
    else:
        lib.afnative_run(*common)
    generated, dropped, clock_n, clock_overflow, rejected = (
        int(x) for x in counters
    )
    if clock_overflow > 0:
        import warnings

        warnings.warn(
            f"clock table overflow: {clock_overflow} completions past "
            f"max_requests={plan.max_requests} were not recorded; analyzer "
            "latency stats exclude them — recompile the plan with a larger "
            "max_requests",
            stacklevel=2,
        )

    sampled: dict[str, dict[str, np.ndarray]] = {}
    if gauges is not None:
        sampled = {
            SampledMetricName.EDGE_CONCURRENT_CONNECTION.value: {
                eid: gauges[:, plan.gauge_edge(e)].astype(np.float64)
                for e, eid in enumerate(plan.edge_ids)
            },
            SampledMetricName.READY_QUEUE_LEN.value: {
                sid: gauges[:, plan.gauge_ready(s)].astype(np.float64)
                for s, sid in enumerate(plan.server_ids)
            },
            SampledMetricName.EVENT_LOOP_IO_SLEEP.value: {
                sid: gauges[:, plan.gauge_io(s)].astype(np.float64)
                for s, sid in enumerate(plan.server_ids)
            },
            SampledMetricName.RAM_IN_USE.value: {
                sid: gauges[:, plan.gauge_ram(s)].astype(np.float64)
                for s, sid in enumerate(plan.server_ids)
            },
        }

    return SimulationResults(
        settings=settings,
        rqs_clock=clock[:clock_n],
        sampled=sampled,
        total_generated=generated,
        total_dropped=dropped,
        total_rejected=rejected,
        # clock-table truncation surfaced as a counter, not just a warning:
        # sweeps (parallel/sweep.py _NativeSweepEngine) aggregate it into
        # overflow_total so saturated native runs never look clean
        overflow_dropped=clock_overflow,
        server_ids=plan.server_ids,
        edge_ids=plan.edge_ids,
        traces=(
            _decode_traces(plan, payload, tr_code, tr_t, tr_n, clock_n)
            if tr_code is not None
            else None
        ),
        llm_cost=llm[:clock_n] if llm is not None else None,
    )


def _decode_traces(plan, payload, tr_code, tr_t, tr_n, clock_n):
    """Shared decode with the jax event engine (same HOP_* code map)."""
    from asyncflow_tpu.engines.jaxsim.engine import decode_hop_traces

    return decode_hop_traces(plan, payload, tr_code, tr_t, tr_n, clock_n)
