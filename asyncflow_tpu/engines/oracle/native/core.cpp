// Native discrete-event core for the oracle engine.
//
// A sequential C++ implementation of the same actor semantics the Python
// oracle executes (RAM-first FIFO admission, lazy core lock via merged
// CPU/IO segments, FIFO ready queue, dropout-then-spike edges, rotation
// order load balancing, outage timelines) driven by the compiler's
// StaticPlan arrays.  Exposed through a plain C ABI and loaded with ctypes
// (no pybind11 in this environment).  Parity with the Python engines is
// distributional — the RNG stream differs by design.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 core.cpp -o _afnative.so

#include <cstdint>
#include <cmath>
#include <queue>
#include <deque>
#include <random>
#include <vector>
#include <algorithm>

namespace {

// segment kinds (compiler order)
constexpr int SEG_CPU = 1;
constexpr int SEG_IO = 2;
constexpr int SEG_DB = 3;  // io_db holding one of K FIFO pool connections
constexpr int SEG_CACHE = 4;  // io_cache hit/miss mixture sleep
constexpr int SEG_LLM = 5;    // io_llm call dynamics (tokens, time, cost)

// hop targets (compiler order)
constexpr int TARGET_SERVER = 1;
constexpr int TARGET_LB = 2;

// distributions (compiler order)
constexpr int D_UNIFORM = 0;
constexpr int D_POISSON = 1;
constexpr int D_EXPONENTIAL = 2;
constexpr int D_NORMAL = 3;
constexpr int D_LOGNORMAL = 4;

struct PlanC {
    // edges
    int32_t n_edges;
    const int32_t* edge_dist;
    const float* edge_mean;
    const float* edge_var;
    const float* edge_dropout;
    // entry chain
    int32_t n_entry;
    const int32_t* entry_edges;
    int32_t entry_target_kind;
    int32_t entry_target;
    // servers
    int32_t n_servers;
    int32_t max_endpoints;
    int32_t max_segments;  // seg arrays have max_segments + 1 columns
    const int32_t* server_cores;
    const float* server_ram;
    const int32_t* server_db_pool;  // -1 = unlimited / not modeled
    const int32_t* server_queue_cap;  // -1 = unbounded ready queue
    const int32_t* server_conn_cap;   // -1 = unbounded socket capacity
    const float* server_rate_limit;   // token refill rps, -1 = no limiter
    const int32_t* server_rate_burst; // token-bucket capacity
    const float* server_queue_timeout; // dequeue deadline s, -1 = none
    const int32_t* n_endpoints;
    const int32_t* seg_kind;  // [NS][NEP][NSEG+1]
    const float* seg_dur;
    const float* seg_hit_prob;  // SEG_CACHE: hit probability (0 = deterministic)
    const float* seg_miss_dur;  // SEG_CACHE: miss latency
    const float* seg_llm_tokens;  // SEG_LLM: Poisson token mean
    const float* seg_llm_tpt;     // SEG_LLM: seconds per token
    const float* seg_llm_cost;    // SEG_LLM: cost units per token
    const float* endpoint_ram;  // [NS][NEP]
    const float* endpoint_cum;  // [NS][NEP] cumulative selection probs
    const int32_t* exit_edge;
    const int32_t* exit_kind;
    const int32_t* exit_target;
    // load balancer
    int32_t lb_algo;  // 0 = round robin, 1 = least connections
    int32_t n_lb_edges;
    const int32_t* lb_edge_index;
    const int32_t* lb_target;
    // circuit breaker (0 threshold = not modeled)
    int32_t breaker_threshold;
    int32_t breaker_probes;
    double breaker_cooldown;
    // spikes (piecewise-constant cumulative spike per edge)
    int32_t n_spike_times;
    const float* spike_times;
    const float* spike_values;  // [NB][NE]
    // outage timeline
    int32_t n_timeline;
    const float* timeline_times;
    const int32_t* timeline_down;
    const int32_t* timeline_slot;
    // workload
    double user_mean;
    double user_var;  // < 0: Poisson users
    double user_window;
    double req_rate;  // requests / user / second
    // multi-generator workloads (G >= 1; scalar fields above = generator 0)
    int32_t n_generators;
    int32_t gen_entry_width;            // padded chain length L
    const double* gen_user_mean;        // [G]
    const double* gen_user_var;         // [G]
    const double* gen_window;           // [G]
    const double* gen_rate;             // [G]
    const int32_t* gen_entry_edges;     // [G][L], -1 padded
    const int32_t* gen_entry_len;       // [G]
    const int32_t* gen_entry_target_kind;  // [G]
    const int32_t* gen_entry_target;    // [G]
    // geometry
    double horizon;
    double sample_period;
    int64_t n_samples;
    int64_t max_requests;
};

struct Request {
    //: per-hop trace ring (only populated when the caller passes trace
    //: buffers): (code, timestamp) with the jax event engine's code map —
    //: 0 generator, 1000+e edge, 2000+s server, 3000 LB, 4000 client
    std::vector<std::pair<int32_t, double>> hops;
    double start = 0.0;
    double ram = 0.0;
    double wait_start = 0.0;  // ready-queue park time (dequeue deadlines)
    double llm_cost = 0.0;    // accumulated io_llm cost units
    int32_t srv = -1;
    int32_t gen = 0;  // originating generator (entry chain + trace code)
    int32_t ep = 0;
    int32_t seg = 0;   // segment index; hop index during the entry chain
    int32_t lbslot = -1;
    int32_t cbslot = -1;  // breaker slot awaiting this request's report
    bool probe = false;   // half-open breaker probe
};

struct Server {
    int32_t cores_free = 1;
    double ram_free = 0.0;
    double ram_in_use = 0.0;
    double rl_tokens = 0.0;  // token bucket (rate limiter)
    double rl_last = 0.0;
    int32_t ready_len = 0;
    int32_t io_len = 0;
    int32_t db_free = -1;  // -1 = unlimited (pool not modeled)
    int32_t residents = 0; // accepted arrivals currently on the server
    std::deque<int32_t> cpu_wait;                      // request idx, FIFO
    std::deque<std::pair<double, int32_t>> ram_wait;   // (amount, request)
    std::deque<int32_t> db_wait;                       // request idx, FIFO
};

enum EvType : int32_t {
    EV_ARRIVAL = 0,     // generator emits a request
    EV_ENTRY_HOP = 1,   // delivery of entry-chain hop `req.seg`
    EV_ARRIVE_LB = 2,
    EV_ARRIVE_SRV = 3,
    EV_SEG_END = 4,
    EV_RESUME = 5,      // RAM granted
    EV_COMPLETE = 6,    // delivery at the client (second visit)
    EV_TIMELINE = 7,
    EV_SAMPLE = 8,
};

struct Ev {
    double t;
    uint64_t seq;
    int32_t type;
    int32_t req;
    int32_t edge;  // in-flight edge to decrement on delivery, -1 none
    bool operator>(const Ev& o) const {
        return t != o.t ? t > o.t : seq > o.seq;
    }
};

struct Sim {
    const PlanC& p;
    std::mt19937_64 rng;
    std::priority_queue<Ev, std::vector<Ev>, std::greater<Ev>> heap;
    uint64_t seq = 0;
    double now = 0.0;

    std::vector<Request> reqs;
    std::vector<int32_t> free_slots;
    std::vector<Server> servers;
    std::vector<int32_t> lb_rotation;  // slot ids in rotation order
    std::vector<int32_t> lb_conn;
    // per-slot circuit breaker (consecutive-failure; half-open probes)
    struct BState {
        int32_t state = 0;  // 0 closed / 1 open / 2 half-open
        int32_t consec = 0;
        int32_t probes_out = 0;
        int32_t probe_ok = 0;
        double open_until = 0.0;
    };
    std::vector<BState> cb;
    std::vector<int32_t> edge_conn;    // in-flight messages per edge

    // arrival sampler state (sampler clock drifts from sim clock by design)
    // per-generator sampler state (index g; legacy single uses g = 0)
    std::vector<double> smp_now, smp_window_end, smp_lam;

    int32_t tl_ptr = 0;
    int64_t sample_idx = 0;

    // outputs
    double* out_clock = nullptr;  // [max_requests][2]
    int32_t* out_tr_code = nullptr;  // [max_requests x hop_cap]
    float* out_tr_t = nullptr;
    int32_t* out_tr_n = nullptr;
    int32_t hop_cap = 0;  // per-request trace ring capacity
    double* out_llm = nullptr;    // [max_requests] per-completion cost
    int64_t clock_n = 0;
    int64_t clock_overflow = 0;  // completions past the clock capacity
    float* out_gauges = nullptr;  // [n_samples][NG] or nullptr
    int64_t generated = 0, dropped = 0, rejected = 0;

    explicit Sim(const PlanC& plan, uint64_t seed) : p(plan), rng(seed) {
        servers.resize(p.n_servers);
        for (int s = 0; s < p.n_servers; ++s) {
            servers[s].cores_free = p.server_cores[s];
            servers[s].ram_free = p.server_ram[s];
            servers[s].db_free = p.server_db_pool ? p.server_db_pool[s] : -1;
            if (p.server_rate_burst)
                servers[s].rl_tokens = (double)p.server_rate_burst[s];
        }
        cb.resize(p.n_lb_edges);
        lb_rotation.resize(p.n_lb_edges);
        for (int i = 0; i < p.n_lb_edges; ++i) lb_rotation[i] = i;
        lb_conn.assign(p.n_lb_edges, 0);
        edge_conn.assign(p.n_edges, 0);
    }

    void push(double t, int32_t type, int32_t req, int32_t edge = -1) {
        heap.push(Ev{t, seq++, type, req, edge});
    }

    // ---- randomness ---------------------------------------------------
    double uniform() {
        return std::uniform_real_distribution<double>(0.0, 1.0)(rng);
    }
    double sample_edge_delay(int e) {
        double mean = p.edge_mean[e], var = p.edge_var[e];
        switch (p.edge_dist[e]) {
            case D_UNIFORM: return uniform();
            case D_POISSON:
                return (double)std::poisson_distribution<long>(mean)(rng);
            case D_EXPONENTIAL:
                return std::exponential_distribution<double>(1.0 / mean)(rng);
            case D_NORMAL: {
                // reference contract: the variance field is numpy's scale
                double v = std::normal_distribution<double>(mean, var)(rng);
                return v < 0.0 ? 0.0 : v;
            }
            case D_LOGNORMAL:
                return std::lognormal_distribution<double>(mean, var)(rng);
        }
        return 0.0;
    }
    double spike_at(int e, double t) const {
        if (p.n_spike_times <= 1) return 0.0;
        const float* times = p.spike_times;
        int idx = int(std::upper_bound(times, times + p.n_spike_times, (float)t)
                      - times) - 1;
        if (idx < 0) idx = 0;
        return p.spike_values[(int64_t)idx * p.n_edges + e];
    }

    // ---- circuit breaker (schemas.nodes.CircuitBreaker semantics) ------
    bool cb_enabled() const { return p.breaker_threshold > 0; }
    bool cb_admits(int slot) {
        BState& b = cb[slot];
        if (b.state == 1) {
            if (now < b.open_until) return false;
            b.state = 2;  // cooldown elapsed: half-open, fresh probe round
            b.probes_out = 0;
            b.probe_ok = 0;
        }
        if (b.state == 2) return b.probes_out < p.breaker_probes;
        return true;
    }
    void cb_fail(int slot, bool probe) {
        BState& b = cb[slot];
        if (probe) {
            if (b.probes_out > 0) --b.probes_out;
            b.state = 1;  // a probe failure re-opens immediately
            b.open_until = now + p.breaker_cooldown;
            return;
        }
        if (b.state == 0 && ++b.consec >= p.breaker_threshold) {
            b.state = 1;
            b.open_until = now + p.breaker_cooldown;
            b.consec = 0;
        }
    }
    void cb_ok(int slot, bool probe) {
        BState& b = cb[slot];
        if (probe) {
            if (b.probes_out > 0) --b.probes_out;
            if (b.state == 2 && ++b.probe_ok >= p.breaker_probes) {
                b.state = 0;
                b.consec = 0;
            }
            return;
        }
        if (b.state == 0) b.consec = 0;
    }
    void cb_report(Request& r, bool failed) {
        if (!cb_enabled() || r.cbslot < 0) return;
        if (failed) cb_fail(r.cbslot, r.probe);
        else cb_ok(r.cbslot, r.probe);
        r.cbslot = -1;
        r.probe = false;
    }

    // ---- arrival process (window-jump semantics) ----------------------
    // Next emitted gap, or negative when the stream is exhausted.  Window
    // boundary jumps advance the sampler clock only; simulated time advances
    // by emitted gaps, reproducing the reference generator's drift.
    int n_gens() const {
        return p.n_generators > 0 ? p.n_generators : 1;
    }
    double g_user_mean(int g) const {
        return p.gen_user_mean ? p.gen_user_mean[g] : p.user_mean;
    }
    double g_user_var(int g) const {
        return p.gen_user_var ? p.gen_user_var[g] : p.user_var;
    }
    double g_window(int g) const {
        return p.gen_window ? p.gen_window[g] : p.user_window;
    }
    double g_rate(int g) const {
        return p.gen_rate ? p.gen_rate[g] : p.req_rate;
    }

    double next_gap(int g) {
        while (true) {
            if (smp_now[g] >= p.horizon) return -1.0;
            if (smp_now[g] >= smp_window_end[g]) {
                smp_window_end[g] = smp_now[g] + g_window(g);
                double users;
                if (g_user_var(g) < 0) {
                    users = (double)std::poisson_distribution<long>(
                        g_user_mean(g))(rng);
                } else {
                    users = std::normal_distribution<double>(
                        g_user_mean(g), g_user_var(g))(rng);
                    if (users < 0.0) users = 0.0;
                }
                smp_lam[g] = users * g_rate(g);
            }
            if (smp_lam[g] <= 0.0) { smp_now[g] = smp_window_end[g]; continue; }
            double u = uniform();
            if (u < 1e-15) u = 1e-15;
            double gap = -std::log(1.0 - u) / smp_lam[g];
            if (smp_now[g] + gap > p.horizon) return -1.0;
            if (smp_now[g] + gap >= smp_window_end[g]) {
                smp_now[g] = smp_window_end[g];
                continue;
            }
            smp_now[g] += gap;
            return gap;
        }
    }

    // one EV_ARRIVAL stream per generator; the event's `req` field carries
    // the generator index (requests are allocated at arrival time).  Called
    // from generator g's own arrival (or t=0 init), so `now` is its last
    // emitted-arrival time and now+gap accumulates emitted gaps only.
    void schedule_next_arrival(int g) {
        double gap = next_gap(g);
        if (gap >= 0.0) push(now + gap, EV_ARRIVAL, g);
    }

    // ---- request slots ------------------------------------------------
    int32_t alloc() {
        if (!free_slots.empty()) {
            int32_t i = free_slots.back();
            free_slots.pop_back();
            reqs[i] = Request{};
            return i;
        }
        reqs.emplace_back();
        return (int32_t)reqs.size() - 1;
    }
    void release(int32_t i) { free_slots.push_back(i); }

    // Append one hop (first hop_cap kept, like the event engine's rings;
    // edge hops are recorded at SEND with their future delivery time, so
    // ring order matches the oracle's record_hop order exactly).
    void record_hop(int32_t i, int32_t code, double t) {
        if (!out_tr_code) return;
        auto& h = reqs[i].hops;
        if ((int32_t)h.size() < hop_cap) h.emplace_back(code, t);
    }

    // ---- edge traversal ------------------------------------------------
    // Rolls dropout + delay at `now`; on success increments the in-flight
    // counter and schedules `type` at the delivery time.  Returns false when
    // the message was dropped (the request slot is released).
    bool send(int e, int32_t type, int32_t req_idx) {
        if (uniform() < p.edge_dropout[e]) {
            ++dropped;
            if (req_idx >= 0) release(req_idx);
            return false;
        }
        double delay = sample_edge_delay(e) + spike_at(e, now);
        ++edge_conn[e];
        if (req_idx >= 0) record_hop(req_idx, 1000 + e, now + delay);
        push(now + delay, type, req_idx, e);
        return true;
    }

    const int32_t* segs(int s, int ep) const {
        return p.seg_kind + ((int64_t)s * p.max_endpoints + ep)
                                * (p.max_segments + 1);
    }
    const float* durs(int s, int ep) const {
        return p.seg_dur + ((int64_t)s * p.max_endpoints + ep)
                               * (p.max_segments + 1);
    }
    int64_t seg_off(int s, int ep, int k) const {
        return ((int64_t)s * p.max_endpoints + ep) * (p.max_segments + 1) + k;
    }

    // ---- server machinery ---------------------------------------------
    void start_segment(int32_t i) {
        Request& r = reqs[i];
        Server& sv = servers[r.srv];
        int kind = segs(r.srv, r.ep)[r.seg];
        double dur = durs(r.srv, r.ep)[r.seg];
        if (kind == SEG_CPU) {
            if (sv.cores_free > 0 && sv.cpu_wait.empty()) {
                --sv.cores_free;
                push(now + dur, EV_SEG_END, i);
            } else if (p.server_queue_cap && p.server_queue_cap[r.srv] >= 0
                       && (int32_t)sv.cpu_wait.size()
                              >= p.server_queue_cap[r.srv]) {
                // overload policy: the ready queue is full — shed the
                // request (release its RAM, count it, free the slot)
                if (r.ram > 0.0) {
                    sv.ram_free += r.ram;
                    sv.ram_in_use -= r.ram;
                    r.ram = 0.0;
                    grant_ram(r.srv);
                }
                ++rejected;
                --sv.residents;
                cb_report(r, true);
                release(i);
            } else {
                r.wait_start = now;
                sv.cpu_wait.push_back(i);
                ++sv.ready_len;
            }
        } else if (kind == SEG_IO) {
            ++sv.io_len;
            push(now + dur, EV_SEG_END, i);
        } else if (kind == SEG_CACHE) {
            // per-request hit/miss mixture: hit latency (dur) with
            // probability hit_prob, else the backing store's miss latency
            ++sv.io_len;
            int64_t off = seg_off(r.srv, r.ep, r.seg);
            if (uniform() >= p.seg_hit_prob[off]) dur = p.seg_miss_dur[off];
            push(now + dur, EV_SEG_END, i);
        } else if (kind == SEG_LLM) {
            // io_llm call dynamics: tokens ~ Poisson(mean); the sleep
            // stretches by tokens * s/token, cost accrues per token
            ++sv.io_len;
            int64_t off = seg_off(r.srv, r.ep, r.seg);
            double tokens = (double)std::poisson_distribution<long>(
                p.seg_llm_tokens[off])(rng);
            r.llm_cost += tokens * p.seg_llm_cost[off];
            push(now + dur + tokens * p.seg_llm_tpt[off], EV_SEG_END, i);
        } else if (kind == SEG_DB) {
            // hold one of K FIFO connections for the query; the wait (if
            // any) parks in the event loop and counts as io sleep
            ++sv.io_len;
            if (sv.db_free != 0 && sv.db_wait.empty()) {  // -1 = unlimited
                if (sv.db_free > 0) --sv.db_free;
                push(now + dur, EV_SEG_END, i);
            } else {
                sv.db_wait.push_back(i);
            }
        } else {
            exit_server(i);
        }
    }

    void grant_cores(int s) {
        Server& sv = servers[s];
        double dl = p.server_queue_timeout ? p.server_queue_timeout[s] : -1.0;
        while (sv.cores_free > 0 && !sv.cpu_wait.empty()) {
            int32_t j = sv.cpu_wait.front();
            sv.cpu_wait.pop_front();
            --sv.ready_len;
            Request& rj = reqs[j];
            if (dl >= 0.0 && now - rj.wait_start > dl) {
                // dequeue deadline exceeded: abandon with zero service —
                // the core passes straight to the next FIFO waiter
                if (rj.ram > 0.0) {
                    sv.ram_free += rj.ram;
                    sv.ram_in_use -= rj.ram;
                    rj.ram = 0.0;
                    grant_ram(s);
                }
                --sv.residents;
                ++rejected;
                cb_report(rj, true);
                release(j);
                continue;
            }
            --sv.cores_free;
            double dur = durs(rj.srv, rj.ep)[rj.seg];
            push(now + dur, EV_SEG_END, j);
        }
    }

    void grant_ram(int s) {
        Server& sv = servers[s];
        // strict FIFO with head-of-line blocking
        while (!sv.ram_wait.empty() && sv.ram_wait.front().first <= sv.ram_free) {
            auto [amount, j] = sv.ram_wait.front();
            sv.ram_wait.pop_front();
            sv.ram_free -= amount;
            sv.ram_in_use += amount;
            push(now, EV_RESUME, j);
        }
    }

    void exit_server(int32_t i) {
        Request& r = reqs[i];
        int s = r.srv;
        Server& sv = servers[s];
        cb_report(r, false);  // departing the routed target = success
        --sv.residents;
        if (r.ram > 0.0) {
            sv.ram_free += r.ram;
            sv.ram_in_use -= r.ram;
            r.ram = 0.0;
            grant_ram(s);
        }
        int kind = p.exit_kind[s];
        if (kind == TARGET_SERVER) {
            r.srv = p.exit_target[s];
            r.lbslot = -1;
            send(p.exit_edge[s], EV_ARRIVE_SRV, i);
        } else if (kind == TARGET_LB) {
            send(p.exit_edge[s], EV_ARRIVE_LB, i);
        } else {
            send(p.exit_edge[s], EV_COMPLETE, i);
        }
    }

    // ---- event handlers ------------------------------------------------
    const int32_t* gen_chain(int g) const {
        return p.gen_entry_edges
            ? p.gen_entry_edges + (int64_t)g * p.gen_entry_width
            : p.entry_edges;
    }
    int gen_chain_len(int g) const {
        return p.gen_entry_len ? p.gen_entry_len[g] : p.n_entry;
    }

    void on_arrival(int g) {
        ++generated;
        schedule_next_arrival(g);
        int32_t i = alloc();
        reqs[i].start = now;
        reqs[i].seg = 0;  // entry-hop index
        reqs[i].gen = g;
        record_hop(i, g, now);  // generator (code = generator index)
        send(gen_chain(g)[0], EV_ENTRY_HOP, i);
    }

    void on_entry_hop(int32_t i) {
        Request& r = reqs[i];
        int g = r.gen;
        int hop = ++r.seg;  // this delivery completed hop (r.seg - 1)
        if (hop < gen_chain_len(g)) {
            record_hop(i, 4000, now);  // intermediate client visit
            send(gen_chain(g)[hop], EV_ENTRY_HOP, i);
            return;
        }
        r.seg = 0;
        int kind = p.gen_entry_target_kind
            ? p.gen_entry_target_kind[g]
            : p.entry_target_kind;
        if (kind == TARGET_LB) {
            on_arrive_lb(i);
        } else {
            r.srv = p.gen_entry_target
                ? p.gen_entry_target[g]
                : p.entry_target;
            on_arrive_srv(i);
        }
    }

    void on_arrive_lb(int32_t i) {
        record_hop(i, 3000, now);
        if (lb_rotation.empty()) { ++dropped; release(i); return; }
        int slot = -1;
        bool probe = false;
        if (cb_enabled()) {
            // skip-in-place: non-admitting slots keep their rotation
            // positions; only the picked slot rotates to the tail (rr)
            if (p.lb_algo == 0) {
                for (size_t pos = 0; pos < lb_rotation.size(); ++pos) {
                    int c = lb_rotation[pos];
                    if (cb_admits(c)) {
                        slot = c;
                        lb_rotation.erase(lb_rotation.begin() + pos);
                        lb_rotation.push_back(slot);
                        break;
                    }
                }
            } else {
                for (int c : lb_rotation)
                    if (cb_admits(c) && (slot < 0 || lb_conn[c] < lb_conn[slot]))
                        slot = c;
            }
            if (slot < 0) {
                // every rotation member open / probe-saturated: the LB
                // refuses the request (overload protection, rejected)
                ++rejected;
                release(i);
                return;
            }
            BState& b = cb[slot];
            probe = b.state == 2;
            if (probe) ++b.probes_out;
            reqs[i].cbslot = slot;
            reqs[i].probe = probe;
        } else if (p.lb_algo == 0) {  // round robin: head out, to tail
            slot = lb_rotation.front();
            lb_rotation.erase(lb_rotation.begin());
            lb_rotation.push_back(slot);
        } else {  // least connections: first minimum in rotation order
            slot = lb_rotation[0];
            for (size_t pos = 1; pos < lb_rotation.size(); ++pos)
                if (lb_conn[lb_rotation[pos]] < lb_conn[slot])
                    slot = lb_rotation[pos];
        }
        reqs[i].srv = p.lb_target[slot];
        reqs[i].lbslot = slot;
        // dropout is rolled before the connection count, like the Python
        // oracle's transport(): dropped messages never count
        if (send(p.lb_edge_index[slot], EV_ARRIVE_SRV, i)) {
            ++lb_conn[slot];
        } else if (cb_enabled()) {
            // the dropped send is a connection failure to the breaker
            // (the request slot is already released by send())
            cb_fail(slot, probe);
        }
    }

    void on_arrive_srv(int32_t i) {
        Request& r = reqs[i];
        if (r.lbslot >= 0) { --lb_conn[r.lbslot]; r.lbslot = -1; }
        Server& sv = servers[r.srv];
        if (p.server_rate_limit && p.server_rate_limit[r.srv] >= 0.0f) {
            // token bucket: lazy refill at arrival; refuse without a
            // whole token (runs before the socket-capacity check)
            double rps = p.server_rate_limit[r.srv];
            double cap = (double)p.server_rate_burst[r.srv];
            sv.rl_tokens = std::min(cap, sv.rl_tokens + (now - sv.rl_last) * rps);
            sv.rl_last = now;
            if (sv.rl_tokens < 1.0) {
                ++rejected;
                cb_report(r, true);
                release(i);
                return;
            }
            sv.rl_tokens -= 1.0;
        }
        if (p.server_conn_cap && p.server_conn_cap[r.srv] >= 0
            && sv.residents >= p.server_conn_cap[r.srv]) {
            // connection refused: the server is at socket capacity
            ++rejected;
            cb_report(r, true);
            release(i);
            return;
        }
        ++sv.residents;
        record_hop(i, 2000 + r.srv, now);
        int nep = p.n_endpoints[r.srv];
        {
            // weighted endpoint pick (uniform weights -> even table)
            double u = uniform();
            const float* cum = p.endpoint_cum
                + (int64_t)r.srv * p.max_endpoints;
            int e = 0;
            while (e < nep - 1 && u >= cum[e]) ++e;
            r.ep = e;
        }
        r.seg = 0;
        double need = p.endpoint_ram[(int64_t)r.srv * p.max_endpoints + r.ep];
        r.ram = need;
        if (need <= 0.0) { start_segment(i); return; }
        if (sv.ram_wait.empty() && sv.ram_free >= need) {
            sv.ram_free -= need;
            sv.ram_in_use += need;
            start_segment(i);
        } else {
            sv.ram_wait.emplace_back(need, i);
        }
    }

    void on_seg_end(int32_t i) {
        Request& r = reqs[i];
        Server& sv = servers[r.srv];
        int kind = segs(r.srv, r.ep)[r.seg];
        if (kind == SEG_CPU) {
            ++sv.cores_free;
            grant_cores(r.srv);
        } else if (kind == SEG_DB) {
            --sv.io_len;
            if (!sv.db_wait.empty()) {  // hand the connection to the head
                int32_t j = sv.db_wait.front();
                sv.db_wait.pop_front();
                double jdur = durs(reqs[j].srv, reqs[j].ep)[reqs[j].seg];
                push(now + jdur, EV_SEG_END, j);
            } else if (sv.db_free >= 0) {
                ++sv.db_free;
            }
        } else {
            --sv.io_len;
        }
        ++r.seg;
        start_segment(i);
    }

    void on_complete(int32_t i) {
        Request& r = reqs[i];
        if (clock_n < p.max_requests) {
            out_clock[2 * clock_n] = r.start;
            out_clock[2 * clock_n + 1] = now;
            if (out_llm) out_llm[clock_n] = r.llm_cost;
            if (out_tr_code) {
                record_hop(i, 4000, now);  // completing client visit
                int32_t n = (int32_t)r.hops.size();
                out_tr_n[clock_n] = n;
                int32_t* row_c = out_tr_code + (int64_t)clock_n * hop_cap;
                float* row_t = out_tr_t + (int64_t)clock_n * hop_cap;
                for (int32_t j = 0; j < n; ++j) {
                    row_c[j] = r.hops[j].first;
                    row_t[j] = (float)r.hops[j].second;
                }
            }
            ++clock_n;
        } else {
            ++clock_overflow;  // saturated run: surface, don't silently drop
        }
        release(i);
    }

    void on_timeline() {
        int slot = p.timeline_slot[tl_ptr];
        bool down = p.timeline_down[tl_ptr] == 1;
        ++tl_ptr;
        if (slot < 0) return;
        auto it = std::find(lb_rotation.begin(), lb_rotation.end(), slot);
        if (down) {
            if (it != lb_rotation.end()) lb_rotation.erase(it);
        } else if (it == lb_rotation.end()) {
            lb_rotation.push_back(slot);  // revive at the rotation tail
        }
    }

    void on_sample() {
        if (out_gauges && sample_idx < p.n_samples) {
            float* row = out_gauges
                + sample_idx * (p.n_edges + 3 * (int64_t)p.n_servers);
            for (int e = 0; e < p.n_edges; ++e) row[e] = (float)edge_conn[e];
            for (int s = 0; s < p.n_servers; ++s) {
                row[p.n_edges + s] = (float)servers[s].ready_len;
                row[p.n_edges + p.n_servers + s] = (float)servers[s].io_len;
                row[p.n_edges + 2 * p.n_servers + s] =
                    (float)servers[s].ram_in_use;
            }
        }
        ++sample_idx;
        double next = (sample_idx + 1) * p.sample_period;
        if (next < p.horizon) push(next, EV_SAMPLE, -1);
    }

    void run() {
        for (int i = 0; i < p.n_timeline; ++i)
            push(p.timeline_times[i], EV_TIMELINE, -1);
        if (p.sample_period > 0.0 && p.n_samples > 0)
            push(p.sample_period, EV_SAMPLE, -1);
        smp_now.assign(n_gens(), 0.0);
        smp_window_end.assign(n_gens(), 0.0);
        smp_lam.assign(n_gens(), 0.0);
        for (int g = 0; g < n_gens(); ++g) schedule_next_arrival(g);

        while (!heap.empty() && heap.top().t < p.horizon) {
            Ev ev = heap.top();
            heap.pop();
            now = ev.t;
            if (ev.edge >= 0) --edge_conn[ev.edge];
            switch (ev.type) {
                case EV_ARRIVAL: on_arrival(ev.req); break;
                case EV_ENTRY_HOP: on_entry_hop(ev.req); break;
                case EV_ARRIVE_LB: on_arrive_lb(ev.req); break;
                case EV_ARRIVE_SRV: on_arrive_srv(ev.req); break;
                case EV_SEG_END: on_seg_end(ev.req); break;
                case EV_RESUME: start_segment(ev.req); break;
                case EV_COMPLETE: on_complete(ev.req); break;
                case EV_TIMELINE: on_timeline(); break;
                case EV_SAMPLE: on_sample(); break;
            }
        }
    }
};

}  // namespace

extern "C" {

int64_t afnative_run_traced(
    const PlanC* plan,
    uint64_t seed,
    double* out_clock,
    float* out_gauges,
    int64_t* out_counters,
    double* out_llm,
    int32_t* out_tr_code,
    float* out_tr_t,
    int32_t* out_tr_n,
    int32_t hop_cap);

int64_t afnative_run(
    const PlanC* plan,
    uint64_t seed,
    double* out_clock,
    float* out_gauges,  // may be null
    int64_t* out_counters,
    /* [generated, dropped, clock_n, clock_overflow, rejected] */
    double* out_llm  /* may be null: [max_requests] per-completion cost */) {
    // untraced entry = traced entry with null rings (record_hop no-ops)
    return afnative_run_traced(
        plan, seed, out_clock, out_gauges, out_counters, out_llm,
        nullptr, nullptr, nullptr, 0);
}

int64_t afnative_run_traced(
    const PlanC* plan,
    uint64_t seed,
    double* out_clock,
    float* out_gauges,  // may be null
    int64_t* out_counters,
    double* out_llm,      // may be null
    int32_t* out_tr_code, /* [max_requests x hop_cap] */
    float* out_tr_t,      /* [max_requests x hop_cap] */
    int32_t* out_tr_n,    /* [max_requests] */
    int32_t hop_cap) {
    Sim sim(*plan, seed);
    sim.out_clock = out_clock;
    sim.out_llm = out_llm;
    sim.out_gauges = out_gauges;
    sim.out_tr_code = out_tr_code;
    sim.out_tr_t = out_tr_t;
    sim.out_tr_n = out_tr_n;
    sim.hop_cap = hop_cap;
    sim.run();
    out_counters[0] = sim.generated;
    out_counters[1] = sim.dropped;
    out_counters[4] = sim.rejected;
    out_counters[2] = sim.clock_n;
    out_counters[3] = sim.clock_overflow;
    return 0;
}

}  // extern "C"
