"""Engine-agnostic result containers.

Both engines (oracle DES and the batched JAX engine) reduce to this common
shape so the analyzer, plots, and parity tests are backend-blind.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field

import numpy as np

from asyncflow_tpu.schemas.settings import SimulationSettings

#: fixed-bin resolution of the streaming gauge histograms behind
#: :attr:`SweepResults.gauge_bands` — linear bins over [0, cap) per gauge
#: column, so a band value is exact to cap / GAUGE_HIST_BINS.
GAUGE_HIST_BINS = 128

#: the quantiles :attr:`SweepResults.gauge_bands` reports, in row order.
GAUGE_BAND_QS = (50.0, 90.0, 99.0)


def gauge_hist_caps(plan, sel) -> np.ndarray:
    """Per-column value caps for the gauge histograms.

    ``sel`` holds gauge-layout column indices (``[edges | ready | io |
    ram]``, :attr:`StaticPlan.n_gauges`).  Connection/queue gauges are
    bounded by the request pool; RAM by the server's capacity.  Duck-typed
    on ``plan`` (``n_edges`` / ``n_servers`` / ``pool_size`` /
    ``server_ram``) so tests can pass a stand-in.
    """
    sel = np.asarray(sel, np.int64)
    caps = np.full(sel.shape, float(plan.pool_size), np.float64)
    ram0 = plan.n_edges + 2 * plan.n_servers
    is_ram = sel >= ram0
    if np.any(is_ram):
        caps[is_ram] = np.asarray(plan.server_ram, np.float64)[
            sel[is_ram] - ram0
        ]
    return np.maximum(caps, 1e-9)


def build_gauge_hist(
    series: np.ndarray,
    caps: np.ndarray,
    *,
    quarantined: np.ndarray | None = None,
    n_bins: int = GAUGE_HIST_BINS,
) -> np.ndarray:
    """Reduce an ``(S, T_g, k)`` gauge series to ``(T_g, k, B)`` int64
    fixed-bin counts across the scenario axis.

    The single binning rule every build/rebuild site shares (initial chunk
    reduction, quarantine edits, scenario-axis slicing): float64
    ``floor(v / cap * B)`` clipped to ``[0, B-1]``, quarantined rows
    excluded so the bands reflect ``effective_n``.
    """
    series = np.asarray(series)
    if quarantined is not None and np.any(quarantined):
        series = series[~np.asarray(quarantined, bool)]
    _, T, k = series.shape
    caps = np.asarray(caps, np.float64).reshape(1, 1, k)
    idx = np.clip(
        np.floor(series.astype(np.float64) / caps * n_bins).astype(np.int64),
        0,
        n_bins - 1,
    )
    hist = np.zeros((T, k, n_bins), np.int64)
    t_idx = np.broadcast_to(np.arange(T)[None, :, None], idx.shape)
    k_idx = np.broadcast_to(np.arange(k)[None, None, :], idx.shape)
    np.add.at(hist, (t_idx, k_idx, idx), 1)
    return hist


def build_blame_hist(
    rows: np.ndarray,
    *,
    quarantined: np.ndarray | None = None,
) -> np.ndarray:
    """Pool per-scenario blame grids into one float64 grid.

    ``rows`` is ``(S, ...)`` — ``(S, n_cells, B)`` seconds grids or
    ``(S, B)`` latency totals.  The single pooling rule every build/rebuild
    site shares (initial chunk reduction, quarantine edits, scenario-axis
    slicing): float64 sum over the scenario axis, quarantined rows excluded
    so the pooled decomposition reflects ``effective_n``.
    """
    rows = np.asarray(rows)
    if quarantined is not None and np.any(quarantined):
        rows = rows[~np.asarray(quarantined, bool)]
    return rows.astype(np.float64).sum(axis=0)


@dataclass(frozen=True)
class DeviceCounters:
    """Unified request-accounting counters, identical across every engine.

    One schema for the oracle, the native core, the JAX event engine, the
    fast path, and the Pallas kernel — the telemetry layer and the parity
    tests read these instead of engine-specific fields.  ``rejected`` is the
    overload-policy shed count; ``overflow`` the request-pool drop count
    (JAX engines only; always 0 on the oracle); ``truncated`` the number of
    scenarios cut short by the event engine's iteration safety cap.

    The resilience counters (0 without a retry policy): ``timed_out``
    client deadlines fired, ``retries`` re-issues performed,
    ``budget_exhausted`` retries denied by the token-bucket retry budget.
    Goodput is ``completed``; offered load is ``generated + retries``.
    ``quarantined`` counts scenarios masked out by host-fault recovery
    (sweeps only; docs/guides/fault-tolerance.md).

    The tail-tolerance counters (0 without the matching policy): ``hedges``
    duplicate attempts issued by the hedge timer, ``hedges_won`` logical
    requests whose *winning* completion was a hedge duplicate,
    ``hedges_cancelled`` attempts cancelled at a routing boundary because a
    sibling already won, ``ejections`` LB health-gate ejection episodes, and
    ``degraded`` completions served under a server brownout profile.
    Hedge duplicates are NOT spawns: offered load stays
    ``generated + retries``; ``hedges`` measures the extra work injected.
    """

    completed: int
    generated: int
    dropped: int
    overflow: int
    rejected: int
    truncated: int = 0
    timed_out: int = 0
    retries: int = 0
    budget_exhausted: int = 0
    quarantined: int = 0
    hedges: int = 0
    hedges_won: int = 0
    hedges_cancelled: int = 0
    ejections: int = 0
    degraded: int = 0
    #: resilience scorecard (chaos campaigns; 0 without a hazard model):
    #: arrivals refused by dark fault windows, completions landing inside
    #: degraded (fault-active) seconds, and sampled in-horizon windows
    #: dropped by the max_faults_per_component slot budget.
    dark_lost: int = 0
    degraded_goodput: int = 0
    hazard_truncated: int = 0
    #: LLM serving counters (0 without llm_serve steps): KV-pressure
    #: evictions, prompt tokens prefilled (eviction redo counts again),
    #: and output tokens decoded (docs/guides/serving.md).
    kv_evictions: int = 0
    prefill_tokens: int = 0
    decode_tokens: int = 0

    def as_dict(self) -> dict[str, int]:
        return asdict(self)


@dataclass
class SimulationResults:
    """Raw outputs of one simulated scenario."""

    settings: SimulationSettings
    #: (N, 2) float array of [start, finish] for each *completed* request.
    rqs_clock: np.ndarray
    #: metric name -> component id -> fixed-cadence series.
    sampled: dict[str, dict[str, np.ndarray]]
    #: requests emitted by the generator.
    total_generated: int = 0
    #: requests lost to edge dropout.
    total_dropped: int = 0
    #: requests lost because the engine's request pool was full (JAX engine
    #: only; non-zero values mean the pool must be enlarged).
    overflow_dropped: int = 0
    #: requests shed by a server's overload policy (ready-queue cap).
    total_rejected: int = 0
    #: server ids in topology order (stable ordering for accessors/plots).
    server_ids: list[str] = field(default_factory=list)
    #: edge ids in topology order.
    edge_ids: list[str] = field(default_factory=list)
    #: optional per-request traces (oracle or jax event engine with
    #: collect_traces=True; keys are oracle request ids / event-engine
    #: completed-clock row indices respectively — match traces to clocks
    #: WITHIN one engine run, never across engines):
    #: request id -> list of (component_kind, component_id, timestamp) hops,
    #: the OpenTelemetry-style span record of the reference's RequestState
    #: history (`/root/reference/src/asyncflow/runtime/rqs_state.py:12-41`).
    traces: dict[int, list[tuple[str, str, float]]] | None = None
    #: flight recorder (``trace=TraceConfig``): spawn sequence -> the
    #: request's bounded lifecycle record, identical layout on the oracle
    #: and the jax event engine (observability/simtrace.py).  Truncation is
    #: explicit: ``FlightRecord.dropped`` counts events past the ring.
    flight: dict[int, object] | None = None
    #: circuit-breaker state transitions ``(sim_time, lb_slot, new_state)``
    #: in event order (flight recorder only; empty without a breaker).
    breaker_timeline: list[tuple[float, int, int]] | None = None
    #: optional (n_completed,) per-request LLM cost units aligned with
    #: ``rqs_clock`` rows (io_llm steps with call dynamics; the
    #: reference's reserved ``llm_cost`` event metric, activated).
    llm_cost: np.ndarray | None = None
    #: resilience counters (client retry policy; 0 / None without one):
    #: client timeouts fired, re-issues performed, retries denied by the
    #: retry budget, and the per-logical-request attempts histogram
    #: (length = max_attempts; bin k = requests that used k+1 attempts).
    total_timed_out: int = 0
    total_retries: int = 0
    retry_budget_exhausted: int = 0
    attempts_hist: np.ndarray | None = None
    #: tail-tolerance counters (0 without the matching policy): hedge
    #: duplicates issued / logical requests won by a hedge / attempts
    #: cancelled after losing the sibling race; LB health-gate ejection
    #: episodes; completions served under a brownout profile.
    total_hedges: int = 0
    hedges_won: int = 0
    hedges_cancelled: int = 0
    lb_ejections: int = 0
    degraded_completions: int = 0
    #: resilience scorecard (chaos campaigns; zeros/None without a hazard
    #: model): arrivals hard-refused by dark fault windows, (NS,) exact
    #: per-server dark seconds integrated from the sampled tables,
    #: completions landing inside degraded (fault-active) seconds,
    #: in-horizon sampled windows dropped by the slot budget, and the
    #: sim-time from the last window closing until the ready-queue series
    #: re-enter their pre-fault band (None when gauges are off or the
    #: queue never settles).
    dark_lost: int = 0
    unavailable_s: np.ndarray | None = None
    degraded_goodput: float | None = None
    hazard_truncated: int = 0
    time_to_drain: float | None = None
    #: LLM serving counters (None without llm_serve steps, zeros when the
    #: plan has them but nothing evicted/served): KV-pressure evictions,
    #: prompt tokens prefilled (every admission, eviction redo included),
    #: output tokens decoded (fitting extensions only).
    kv_evictions: int | None = None
    prefill_tokens: float | None = None
    decode_tokens: float | None = None
    #: latency attribution plane (``blame=True``; None otherwise):
    #: ``(n_cells, B)`` float64 seconds spent per (component, phase) cell by
    #: requests whose end-to-end latency fell in coarse latency bin b, the
    #: ``(B,)`` float64 total latency seconds per bin (the conservation
    #: denominator), and — oracle only — the ``(N, n_cells)`` per-request
    #: decomposition aligned with ``rqs_clock`` rows (observability/blame.py).
    blame: np.ndarray | None = None
    blame_lat: np.ndarray | None = None
    blame_req: np.ndarray | None = None

    @property
    def latencies(self) -> np.ndarray:
        """Per-completed-request latency in seconds."""
        if self.rqs_clock.size == 0:
            return np.empty(0, dtype=np.float64)
        return self.rqs_clock[:, 1] - self.rqs_clock[:, 0]

    @property
    def offered(self) -> int:
        """Total issues the system saw: spawns + client re-issues."""
        return int(self.total_generated) + int(self.total_retries)

    def counters(self) -> DeviceCounters:
        """The unified counter schema (``completed`` counts recorded clock
        rows, so engines run with ``collect_clocks=False`` report 0)."""
        return DeviceCounters(
            completed=int(self.rqs_clock.shape[0]),
            generated=int(self.total_generated),
            dropped=int(self.total_dropped),
            overflow=int(self.overflow_dropped),
            rejected=int(self.total_rejected),
            timed_out=int(self.total_timed_out),
            retries=int(self.total_retries),
            budget_exhausted=int(self.retry_budget_exhausted),
            hedges=int(self.total_hedges),
            hedges_won=int(self.hedges_won),
            hedges_cancelled=int(self.hedges_cancelled),
            ejections=int(self.lb_ejections),
            degraded=int(self.degraded_completions),
            dark_lost=int(self.dark_lost),
            degraded_goodput=int(self.degraded_goodput or 0),
            hazard_truncated=int(self.hazard_truncated),
            kv_evictions=int(self.kv_evictions or 0),
            prefill_tokens=int(self.prefill_tokens or 0),
            decode_tokens=int(self.decode_tokens or 0),
        )


@dataclass
class SweepResults:
    """Stacked outputs of a Monte-Carlo scenario sweep (JAX engine)."""

    settings: SimulationSettings
    #: (S,) completed-request counts per scenario.
    completed: np.ndarray
    #: (S, B) latency histogram counts per scenario (log-spaced bins).
    latency_hist: np.ndarray
    #: (B + 1,) shared histogram bin edges (seconds).
    hist_edges: np.ndarray
    #: (S,) sums of latency / squared latency for exact mean/std.
    latency_sum: np.ndarray
    latency_sumsq: np.ndarray
    #: (S,) min / max latency per scenario.
    latency_min: np.ndarray
    latency_max: np.ndarray
    #: (S, T) completions per 1-second window.
    throughput: np.ndarray
    #: (S,) generated / dropped / overflow counters.
    total_generated: np.ndarray = field(default_factory=lambda: np.empty(0))
    total_dropped: np.ndarray = field(default_factory=lambda: np.empty(0))
    overflow_dropped: np.ndarray = field(default_factory=lambda: np.empty(0))
    #: (S, n_gauges) exact per-scenario time-averages of every gauge (fast
    #: path only; None otherwise). Layout: [edges | ready | io | ram].
    gauge_means: np.ndarray | None = None
    #: (S,) bool: the event engine's iteration safety cap fired before the
    #: horizon, so this scenario's results cover only part of the run (event
    #: engine only; always False on the fast path).
    truncated: np.ndarray | None = None
    #: (S,) per-scenario totals of completed requests' LLM cost units (and
    #: squared costs, for CIs) — io_llm call dynamics; None when the plan
    #: has none.
    llm_cost_sum: np.ndarray | None = None
    llm_cost_sumsq: np.ndarray | None = None
    #: (S, T_g, k) per-scenario streaming gauge time series on the coarse
    #: resample grid (fast-path sweeps with a gauge_series spec; None
    #: otherwise).  Column j is the j-th selected gauge; the value at row i
    #: is exactly the fine-grid gauge value at t = (i + 1) * series period.
    gauge_series: np.ndarray | None = None
    #: seconds between gauge_series rows (sample_period * stride).
    gauge_series_period: float | None = None
    #: (T_g, k, B) int64 cross-scenario gauge histograms — per coarse time
    #: bin and selected gauge column, ``B = GAUGE_HIST_BINS`` linear value
    #: bins over [0, cap).  Built per chunk from ``gauge_series``, summed
    #: across chunks, quarantine-aware (masked rows hold no counts); feeds
    #: :attr:`gauge_bands`.  None without a gauge_series spec.
    gauge_hist: np.ndarray | None = None
    #: (k,) per-column value caps of the gauge histograms (pool size for
    #: connection/queue gauges, server RAM for ram_in_use).
    gauge_hist_cap: np.ndarray | None = None
    #: (S,) requests shed by overload policies per scenario.  The event and
    #: native engines always populate it (zeros when no cap binds); None
    #: only for engines with no shed channel at all (fast path / Pallas,
    #: which the compiler restricts to plans without reachable caps).
    total_rejected: np.ndarray | None = None
    #: (S,) resilience counters and the (S, A) per-scenario attempts
    #: histogram (event engine on plans with a retry policy; None
    #: otherwise — the compiler routes such plans off the fast path).
    total_timed_out: np.ndarray | None = None
    total_retries: np.ndarray | None = None
    retry_budget_exhausted: np.ndarray | None = None
    attempts_hist: np.ndarray | None = None
    #: (S,) tail-tolerance counters (event engine on plans with the matching
    #: policy; None otherwise — such plans are fenced off the fast path):
    #: hedge duplicates issued / won / cancelled, LB health-gate ejection
    #: episodes, and completions served under a brownout profile.
    total_hedges: np.ndarray | None = None
    hedges_won: np.ndarray | None = None
    hedges_cancelled: np.ndarray | None = None
    lb_ejections: np.ndarray | None = None
    degraded_completions: np.ndarray | None = None
    #: flight-recorder ring buffers (event-engine sweeps with a
    #: ``trace=TraceConfig``; None otherwise): ``(S, K, slots)`` lifecycle
    #: codes / node indices / sim timestamps and the ``(S, K)`` event
    #: counts (counts past ``slots`` are the explicit truncation signal).
    #: Decode per scenario with :meth:`SweepReport.flight_records`.
    flight_ev: np.ndarray | None = None
    flight_node: np.ndarray | None = None
    flight_t: np.ndarray | None = None
    flight_n: np.ndarray | None = None
    #: resilience scorecard (chaos campaigns; None without a hazard
    #: model): (S,) arrivals lost to dark windows, (S, NS) exact
    #: per-server dark seconds, (S,) completions landing inside degraded
    #: seconds, (S,) sim-time from the last window closing until the
    #: ready-queue series re-enter their pre-fault band (NaN = undefined:
    #: no window, no pre-fault samples, or never drained), and (S,)
    #: in-horizon sampled windows dropped by the slot budget.
    dark_lost: np.ndarray | None = None
    unavailable_s: np.ndarray | None = None
    degraded_goodput: np.ndarray | None = None
    time_to_drain: np.ndarray | None = None
    hazard_truncated: np.ndarray | None = None
    #: (S,) LLM serving counters (plans with llm_serve steps; None
    #: otherwise): KV-pressure evictions, prompt tokens prefilled, output
    #: tokens decoded per scenario (docs/guides/serving.md).
    kv_evictions: np.ndarray | None = None
    prefill_tokens: np.ndarray | None = None
    decode_tokens: np.ndarray | None = None
    #: latency attribution plane (``blame=True`` sweeps; None otherwise):
    #: ``(S, n_cells, B)`` float32 per-scenario seconds grids and ``(S, B)``
    #: float32 per-scenario latency totals straight off the device, plus
    #: their pooled float64 reductions over the effective (non-quarantined)
    #: scenario axis — built per chunk by :func:`build_blame_hist`, summed
    #: across chunks, rebuilt from the rows on quarantine splice and
    #: scenario-axis slicing (observability/blame.py has the cell layout).
    blame_rows: np.ndarray | None = None
    blame_lat_rows: np.ndarray | None = None
    blame_hist: np.ndarray | None = None
    blame_lat_hist: np.ndarray | None = None
    #: (S,) bool host-fault quarantine mask: True rows produced non-finite
    #: metrics (or deterministically crashed the engine) and were masked
    #: out — their metric rows are zeroed, ``quarantine_reason`` names why.
    #: None when recovery never fired (docs/guides/fault-tolerance.md).
    quarantined: np.ndarray | None = None
    #: (S,) per-scenario quarantine reason strings ('' for clean rows).
    quarantine_reason: np.ndarray | None = None

    @property
    def n_quarantined(self) -> int:
        """Scenarios masked out by host-fault quarantine (0 without)."""
        return (
            int(np.count_nonzero(self.quarantined))
            if self.quarantined is not None
            else 0
        )

    def effective(self) -> SweepResults:
        """Drop quarantined rows — the estimator-facing effective sweep.

        Per-scenario statistics (means of per-scenario percentiles,
        bootstrap resampling) must not see the zeroed mask rows; pooled
        histogram reductions are already unaffected (masked rows hold no
        counts).
        """
        if self.quarantined is None or not np.any(self.quarantined):
            return self
        return self[~np.asarray(self.quarantined, bool)]

    @property
    def gauge_bands(self) -> np.ndarray | None:
        """(3, T_g, k) cross-scenario quantile bands of the gauge series.

        Row order is :data:`GAUGE_BAND_QS` (p50/p90/p99); column j is the
        j-th selected gauge, time axis the coarse resample grid.  Computed
        from the fixed-bin histograms with the same interpolation rule as
        :func:`hist_percentile`, so a band value is exact to
        ``cap / GAUGE_HIST_BINS``.  Quarantined scenarios hold no counts —
        the bands reflect the effective sweep.  None without a
        gauge_series spec.
        """
        if self.gauge_hist is None or self.gauge_hist_cap is None:
            return None
        T, k, B = self.gauge_hist.shape
        out = np.zeros((len(GAUGE_BAND_QS), T, k))
        for j in range(k):
            edges = np.linspace(0.0, float(self.gauge_hist_cap[j]), B + 1)
            for qi, q in enumerate(GAUGE_BAND_QS):
                out[qi, :, j] = hist_percentile(
                    self.gauge_hist[:, j, :], edges, q,
                )
        return out

    def __getitem__(self, idx) -> SweepResults:
        """Slice along the scenario axis."""
        return SweepResults(
            settings=self.settings,
            completed=self.completed[idx],
            latency_hist=self.latency_hist[idx],
            hist_edges=self.hist_edges,
            latency_sum=self.latency_sum[idx],
            latency_sumsq=self.latency_sumsq[idx],
            latency_min=self.latency_min[idx],
            latency_max=self.latency_max[idx],
            throughput=self.throughput[idx],
            total_generated=self.total_generated[idx],
            total_dropped=self.total_dropped[idx],
            overflow_dropped=self.overflow_dropped[idx],
            gauge_means=(
                self.gauge_means[idx] if self.gauge_means is not None else None
            ),
            truncated=self.truncated[idx] if self.truncated is not None else None,
            gauge_series=(
                self.gauge_series[idx] if self.gauge_series is not None else None
            ),
            gauge_series_period=self.gauge_series_period,
            # the histograms span the scenario axis: rebuild from the kept
            # rows (minus any still-quarantined ones) instead of slicing
            gauge_hist=(
                build_gauge_hist(
                    self.gauge_series[idx],
                    self.gauge_hist_cap,
                    quarantined=(
                        self.quarantined[idx]
                        if self.quarantined is not None
                        else None
                    ),
                )
                if self.gauge_hist is not None and self.gauge_series is not None
                else None
            ),
            gauge_hist_cap=self.gauge_hist_cap,
            total_rejected=(
                self.total_rejected[idx]
                if self.total_rejected is not None
                else None
            ),
            total_timed_out=(
                self.total_timed_out[idx]
                if self.total_timed_out is not None
                else None
            ),
            total_retries=(
                self.total_retries[idx]
                if self.total_retries is not None
                else None
            ),
            retry_budget_exhausted=(
                self.retry_budget_exhausted[idx]
                if self.retry_budget_exhausted is not None
                else None
            ),
            attempts_hist=(
                self.attempts_hist[idx]
                if self.attempts_hist is not None
                else None
            ),
            total_hedges=(
                self.total_hedges[idx]
                if self.total_hedges is not None
                else None
            ),
            hedges_won=(
                self.hedges_won[idx] if self.hedges_won is not None else None
            ),
            hedges_cancelled=(
                self.hedges_cancelled[idx]
                if self.hedges_cancelled is not None
                else None
            ),
            lb_ejections=(
                self.lb_ejections[idx]
                if self.lb_ejections is not None
                else None
            ),
            degraded_completions=(
                self.degraded_completions[idx]
                if self.degraded_completions is not None
                else None
            ),
            llm_cost_sum=(
                self.llm_cost_sum[idx] if self.llm_cost_sum is not None else None
            ),
            llm_cost_sumsq=(
                self.llm_cost_sumsq[idx]
                if self.llm_cost_sumsq is not None
                else None
            ),
            dark_lost=(
                self.dark_lost[idx] if self.dark_lost is not None else None
            ),
            unavailable_s=(
                self.unavailable_s[idx]
                if self.unavailable_s is not None
                else None
            ),
            degraded_goodput=(
                self.degraded_goodput[idx]
                if self.degraded_goodput is not None
                else None
            ),
            time_to_drain=(
                self.time_to_drain[idx]
                if self.time_to_drain is not None
                else None
            ),
            hazard_truncated=(
                self.hazard_truncated[idx]
                if self.hazard_truncated is not None
                else None
            ),
            flight_ev=self.flight_ev[idx] if self.flight_ev is not None else None,
            flight_node=(
                self.flight_node[idx] if self.flight_node is not None else None
            ),
            flight_t=self.flight_t[idx] if self.flight_t is not None else None,
            flight_n=self.flight_n[idx] if self.flight_n is not None else None,
            kv_evictions=(
                self.kv_evictions[idx]
                if self.kv_evictions is not None
                else None
            ),
            prefill_tokens=(
                self.prefill_tokens[idx]
                if self.prefill_tokens is not None
                else None
            ),
            decode_tokens=(
                self.decode_tokens[idx]
                if self.decode_tokens is not None
                else None
            ),
            blame_rows=(
                self.blame_rows[idx] if self.blame_rows is not None else None
            ),
            blame_lat_rows=(
                self.blame_lat_rows[idx]
                if self.blame_lat_rows is not None
                else None
            ),
            # pooled grids span the scenario axis: rebuild from the kept
            # rows (minus any still-quarantined ones) instead of slicing
            blame_hist=(
                build_blame_hist(
                    self.blame_rows[idx],
                    quarantined=(
                        self.quarantined[idx]
                        if self.quarantined is not None
                        else None
                    ),
                )
                if self.blame_rows is not None
                else None
            ),
            blame_lat_hist=(
                build_blame_hist(
                    self.blame_lat_rows[idx],
                    quarantined=(
                        self.quarantined[idx]
                        if self.quarantined is not None
                        else None
                    ),
                )
                if self.blame_lat_rows is not None
                else None
            ),
            quarantined=(
                self.quarantined[idx] if self.quarantined is not None else None
            ),
            quarantine_reason=(
                self.quarantine_reason[idx]
                if self.quarantine_reason is not None
                else None
            ),
        )

    def percentile(self, q: float) -> np.ndarray:
        """Per-scenario latency percentile estimated from the histograms."""
        return hist_percentile(self.latency_hist, self.hist_edges, q)

    def counters(self) -> DeviceCounters:
        """Sweep-total unified counters (summed over the scenario axis)."""
        return DeviceCounters(
            completed=int(np.sum(self.completed)),
            generated=int(np.sum(self.total_generated)),
            dropped=int(np.sum(self.total_dropped)),
            overflow=int(np.sum(self.overflow_dropped)),
            rejected=(
                int(np.sum(self.total_rejected))
                if self.total_rejected is not None
                else 0
            ),
            truncated=(
                int(np.sum(self.truncated)) if self.truncated is not None else 0
            ),
            timed_out=(
                int(np.sum(self.total_timed_out))
                if self.total_timed_out is not None
                else 0
            ),
            retries=(
                int(np.sum(self.total_retries))
                if self.total_retries is not None
                else 0
            ),
            budget_exhausted=(
                int(np.sum(self.retry_budget_exhausted))
                if self.retry_budget_exhausted is not None
                else 0
            ),
            quarantined=self.n_quarantined,
            hedges=(
                int(np.sum(self.total_hedges))
                if self.total_hedges is not None
                else 0
            ),
            hedges_won=(
                int(np.sum(self.hedges_won))
                if self.hedges_won is not None
                else 0
            ),
            hedges_cancelled=(
                int(np.sum(self.hedges_cancelled))
                if self.hedges_cancelled is not None
                else 0
            ),
            ejections=(
                int(np.sum(self.lb_ejections))
                if self.lb_ejections is not None
                else 0
            ),
            degraded=(
                int(np.sum(self.degraded_completions))
                if self.degraded_completions is not None
                else 0
            ),
            dark_lost=(
                int(np.sum(self.dark_lost))
                if self.dark_lost is not None
                else 0
            ),
            degraded_goodput=(
                int(np.sum(self.degraded_goodput))
                if self.degraded_goodput is not None
                else 0
            ),
            hazard_truncated=(
                int(np.sum(self.hazard_truncated))
                if self.hazard_truncated is not None
                else 0
            ),
            kv_evictions=(
                int(np.sum(self.kv_evictions))
                if self.kv_evictions is not None
                else 0
            ),
            prefill_tokens=(
                int(np.sum(self.prefill_tokens))
                if self.prefill_tokens is not None
                else 0
            ),
            decode_tokens=(
                int(np.sum(self.decode_tokens))
                if self.decode_tokens is not None
                else 0
            ),
        )


def hist_percentile(
    counts: np.ndarray,
    edges: np.ndarray,
    q: float,
) -> np.ndarray:
    """Latency percentile from log-binned histogram counts.

    ``counts`` is ``(n_bins,)`` or ``(S, n_bins)``; ``edges`` has
    ``n_bins + 1`` entries.  Linear interpolation inside the first bin whose
    CDF crosses ``q`` — the single percentile definition shared by the sweep
    reports, the bench and the TPU shot scripts.
    """
    counts = np.asarray(counts, np.float64)
    single = counts.ndim == 1
    counts = np.atleast_2d(counts)
    totals = counts.sum(axis=1, keepdims=True)
    cdf = np.cumsum(counts, axis=1) / np.maximum(totals, 1.0)
    idx = np.argmax(cdf >= q / 100.0, axis=1)
    lo = edges[idx]
    hi = edges[idx + 1]
    prev = np.take_along_axis(
        np.pad(cdf, ((0, 0), (1, 0)))[:, :-1],
        idx[:, None],
        axis=1,
    )[:, 0]
    cur = np.take_along_axis(cdf, idx[:, None], axis=1)[:, 0]
    frac = np.where(cur > prev, (q / 100.0 - prev) / (cur - prev), 0.0)
    out = lo + frac * (hi - lo)
    return out[0] if single else out
