"""Metric analysis layer."""

from asyncflow_tpu.metrics.analyzer import ResultsAnalyzer

__all__ = ["ResultsAnalyzer"]
