"""Post-run statistics and visualization.

API mirrors the reference ``ResultsAnalyzer``
(``/root/reference/src/asyncflow/metrics/analyzer.py:36-589``): the same
accessor names (`get_latency_stats`, `format_latency_stats`,
`get_throughput_series`, `get_sampled_metrics`, `get_metric_map`,
`get_series`, `list_server_ids`) and the same stats/throughput semantics
(1-second completion buckets scanned up to the horizon inclusive), but it
consumes the engine-agnostic :class:`SimulationResults` instead of live actor
objects, so both backends share it.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import numpy as np

from asyncflow_tpu.config.constants import LatencyKey, SampledMetricName
from asyncflow_tpu.config.plot_constants import (
    LATENCY_PLOT,
    RAM_PLOT,
    SERVER_QUEUES_PLOT,
    THROUGHPUT_PLOT,
    PlotCfg,
)
from asyncflow_tpu.engines.results import SimulationResults

if TYPE_CHECKING:
    from matplotlib.axes import Axes
    from matplotlib.figure import Figure

Series = tuple[list[float], list[float]]

_STAT_ORDER = [
    LatencyKey.TOTAL_REQUESTS,
    LatencyKey.MEAN,
    LatencyKey.MEDIAN,
    LatencyKey.STD_DEV,
    LatencyKey.P95,
    LatencyKey.P99,
    LatencyKey.MIN,
    LatencyKey.MAX,
]


def _bucket_throughput(
    finish_times: np.ndarray,
    end_time: float,
    window_s: float,
) -> Series:
    """Completions per window, one bucket per ``window_s`` up to the horizon.

    Matches the reference scan (`analyzer.py:107-125`): bucket k covers
    completions with ``finish <= (k+1) * window_s`` not counted earlier, and
    buckets stop at the last window whose end is ``<= end_time``.
    """
    finished = np.sort(finish_times)
    timestamps: list[float] = []
    rps: list[float] = []
    idx = 0
    current_end = window_s
    while current_end <= end_time:
        count = 0
        while idx < finished.size and finished[idx] <= current_end:
            count += 1
            idx += 1
        timestamps.append(current_end)
        rps.append(count / window_s)
        current_end += window_s
    return timestamps, rps


class ResultsAnalyzer:
    """Analyze and visualize the results of a completed simulation."""

    _WINDOW_SIZE_S: float = 1.0

    def __init__(self, results: SimulationResults) -> None:
        self._results = results
        self._settings = results.settings
        self.latency_stats: dict[LatencyKey, float] | None = None
        self.throughput_series: Series | None = None

    # -- core ---------------------------------------------------------------

    @property
    def results(self) -> SimulationResults:
        """The raw engine output backing this analyzer."""
        return self._results

    def process_all_metrics(self) -> None:
        """Compute cached aggregates if not already done."""
        if self.latency_stats is None:
            latencies = self._results.latencies
            if latencies.size:
                self.latency_stats = {
                    LatencyKey.TOTAL_REQUESTS: float(latencies.size),
                    LatencyKey.MEAN: float(np.mean(latencies)),
                    LatencyKey.MEDIAN: float(np.median(latencies)),
                    LatencyKey.STD_DEV: float(np.std(latencies)),
                    LatencyKey.P95: float(np.percentile(latencies, 95)),
                    LatencyKey.P99: float(np.percentile(latencies, 99)),
                    LatencyKey.MIN: float(np.min(latencies)),
                    LatencyKey.MAX: float(np.max(latencies)),
                }
            else:
                self.latency_stats = {}
        if self.throughput_series is None:
            self.throughput_series = _bucket_throughput(
                self._finish_times(),
                float(self._settings.total_simulation_time),
                self._WINDOW_SIZE_S,
            )

    def _finish_times(self) -> np.ndarray:
        clock = self._results.rqs_clock
        return clock[:, 1] if clock.size else np.empty(0)

    # -- accessors ----------------------------------------------------------

    def list_server_ids(self) -> list[str]:
        """Server ids in topology order."""
        return list(self._results.server_ids)

    def get_latency_stats(self) -> dict[LatencyKey, float]:
        """Latency statistics keyed by :class:`LatencyKey`."""
        self.process_all_metrics()
        return self.latency_stats or {}

    def format_latency_stats(self) -> str:
        """Human-readable latency-stats block."""
        stats = self.get_latency_stats()
        if not stats:
            return "Latency stats: (empty)"
        lines = ["======== LATENCY STATS ========"]
        lines += [
            f"{key.name:<20} = {stats[key]:.6f}" for key in _STAT_ORDER if key in stats
        ]
        return "\n".join(lines)

    def get_throughput_series(self, window_s: float | None = None) -> Series:
        """(timestamps, requests/s); recomputed on the fly for custom windows."""
        self.process_all_metrics()
        if window_s is None or window_s == self._WINDOW_SIZE_S:
            return self.throughput_series or ([], [])
        return _bucket_throughput(
            self._finish_times(),
            float(self._settings.total_simulation_time),
            float(window_s),
        )

    def get_sampled_metrics(self) -> dict[str, dict[str, np.ndarray]]:
        """All sampled time series: metric -> component id -> values."""
        return self._results.sampled

    def get_llm_stats(self) -> dict[str, float] | None:
        """Aggregated LLM cost statistics (the reference's reserved
        ``llm_stats`` metric, activated): total / mean / p95 / max cost
        per completed request and cost per simulated second.  None when
        the scenario has no io_llm call dynamics."""
        cost = self._results.llm_cost
        if cost is None or cost.size == 0:
            return None
        horizon = float(self._results.settings.total_simulation_time)
        return {
            "total_cost": float(cost.sum()),
            "mean_cost_per_request": float(cost.mean()),
            "p95_cost_per_request": float(np.percentile(cost, 95)),
            "max_cost_per_request": float(cost.max()),
            "cost_per_second": float(cost.sum() / max(horizon, 1e-9)),
        }

    def get_traces(self) -> dict[int, list[tuple[str, str, float]]]:
        """Per-request hop traces (requires an engine run with tracing on,
        ``engine_options={"collect_traces": True}`` — oracle or jax event
        backend; keys are oracle request ids / completed-clock row indices
        respectively)."""
        return self._results.traces or {}

    def get_metric_map(
        self,
        key: SampledMetricName | str,
    ) -> dict[str, np.ndarray]:
        """Series map for one metric; tolerant to enum or string keys."""
        sampled = self._results.sampled
        if isinstance(key, SampledMetricName):
            key = key.value
        return sampled.get(key, {})

    def get_series(
        self,
        key: SampledMetricName | str,
        entity_id: str,
    ) -> tuple[list[float], np.ndarray]:
        """(times, values) of one sampled metric for one component."""
        values = self.get_metric_map(key).get(entity_id)
        if values is None:
            values = np.empty(0)
        # reference labels sample k at k * period starting from zero
        times = (np.arange(len(values)) * self._settings.sample_period_s).tolist()
        return times, values

    # -- plotting -----------------------------------------------------------

    @staticmethod
    def _styled_axis(ax: Axes, cfg: PlotCfg) -> None:
        ax.set_title(cfg.title)
        ax.set_xlabel(cfg.x_label)
        ax.set_ylabel(cfg.y_label)
        ax.grid(visible=True)

    def plot_latency_distribution(self, ax: Axes, bins: int = 50) -> None:
        """Histogram of completed-request latencies."""
        latencies = self._results.latencies
        cfg = LATENCY_PLOT
        if latencies.size:
            ax.hist(latencies, bins=bins, color=cfg.color, alpha=cfg.alpha)
            stats = self.get_latency_stats()
            for key, style in (
                (LatencyKey.MEAN, "--"),
                (LatencyKey.P95, ":"),
                (LatencyKey.P99, "-."),
            ):
                ax.axvline(
                    stats[key],
                    linestyle=style,
                    color="black",
                    label=f"{key.name.lower()}={stats[key] * 1e3:.1f} ms",
                )
            ax.legend()
        self._styled_axis(ax, cfg)

    def plot_throughput(self, ax: Axes, window_s: float | None = None) -> None:
        """Completed requests per second over time."""
        times, values = self.get_throughput_series(window_s)
        cfg = THROUGHPUT_PLOT
        ax.plot(times, values, color=cfg.color, alpha=cfg.alpha)
        self._styled_axis(ax, cfg)

    def _plot_server_series(
        self,
        ax: Axes,
        metric: SampledMetricName,
        server_id: str,
        cfg: PlotCfg,
        label: str,
    ) -> None:
        times, values = self.get_series(metric, server_id)
        ax.plot(times, values, color=cfg.color, alpha=cfg.alpha, label=label)
        self._styled_axis(ax, cfg)
        ax.legend()

    def plot_single_server_ready_queue(self, ax: Axes, server_id: str) -> None:
        """Ready-queue length for one server."""
        self._plot_server_series(
            ax,
            SampledMetricName.READY_QUEUE_LEN,
            server_id,
            SERVER_QUEUES_PLOT,
            f"{server_id} ready",
        )

    def plot_single_server_io_queue(self, ax: Axes, server_id: str) -> None:
        """I/O-queue length for one server."""
        self._plot_server_series(
            ax,
            SampledMetricName.EVENT_LOOP_IO_SLEEP,
            server_id,
            SERVER_QUEUES_PLOT,
            f"{server_id} io",
        )

    def plot_single_server_ram(self, ax: Axes, server_id: str) -> None:
        """RAM in use for one server."""
        self._plot_server_series(
            ax,
            SampledMetricName.RAM_IN_USE,
            server_id,
            RAM_PLOT,
            f"{server_id} ram",
        )

    def plot_base_dashboard(self) -> Figure:
        """2x2 dashboard: latency, throughput, ready queues, RAM."""
        import matplotlib.pyplot as plt

        fig, axes = plt.subplots(2, 2, figsize=(12, 8))
        self.plot_latency_distribution(axes[0][0])
        self.plot_throughput(axes[0][1])
        for server_id in self.list_server_ids():
            times, values = self.get_series(
                SampledMetricName.READY_QUEUE_LEN,
                server_id,
            )
            axes[1][0].plot(times, values, label=server_id)
            times, values = self.get_series(SampledMetricName.RAM_IN_USE, server_id)
            axes[1][1].plot(times, values, label=server_id)
        self._styled_axis(axes[1][0], SERVER_QUEUES_PLOT)
        self._styled_axis(axes[1][1], RAM_PLOT)
        axes[1][0].legend()
        axes[1][1].legend()
        fig.tight_layout()
        return fig
