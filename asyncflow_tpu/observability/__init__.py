"""Structured run telemetry: phase timers, compile ledger, device counters,
and Chrome-trace/Perfetto export.

The first-class home of the instrumentation the perf milestones were built
with.  Entry points:

- :class:`TelemetryConfig` — what to record and where; pass as
  ``SimulationRunner(..., telemetry=...)`` or
  ``SweepRunner(..., telemetry=...)``.
- :class:`RunTelemetry` — the per-run collector (constructed internally by
  the runners; construct directly to instrument custom loops).
- :class:`CompileLedger` — the persistent jit/AOT compile log beside
  ``.jax_cache``.
- :mod:`~asyncflow_tpu.observability.report` — device-trace summaries
  (the promoted ``scripts/trace_summary.py``).

See docs/guides/observability.md for the workflow.
"""

from asyncflow_tpu.observability.export import (
    load_chrome_trace,
    read_run_records,
    validate_run_record,
    write_chrome_trace,
)
from asyncflow_tpu.observability.ledger import CompileLedger, default_ledger_path
from asyncflow_tpu.observability.phases import PHASES, PhaseRecord, PhaseTimer
from asyncflow_tpu.observability.telemetry import (
    RUN_RECORD_SCHEMA,
    RunTelemetry,
    TelemetryConfig,
    current_telemetry,
    instrument_jit,
    maybe_phase,
    telemetry_session,
)

__all__ = [
    "PHASES",
    "RUN_RECORD_SCHEMA",
    "CompileLedger",
    "PhaseRecord",
    "PhaseTimer",
    "RunTelemetry",
    "TelemetryConfig",
    "current_telemetry",
    "default_ledger_path",
    "instrument_jit",
    "load_chrome_trace",
    "maybe_phase",
    "read_run_records",
    "telemetry_session",
    "validate_run_record",
    "write_chrome_trace",
]
