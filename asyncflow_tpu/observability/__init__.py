"""Structured run telemetry: phase timers, compile ledger, device counters,
and Chrome-trace/Perfetto export.

The first-class home of the instrumentation the perf milestones were built
with.  Entry points:

- :class:`TelemetryConfig` — what to record and where; pass as
  ``SimulationRunner(..., telemetry=...)`` or
  ``SweepRunner(..., telemetry=...)``.
- :class:`RunTelemetry` — the per-run collector (constructed internally by
  the runners; construct directly to instrument custom loops).
- :class:`CompileLedger` — the persistent jit/AOT compile log beside
  ``.jax_cache``.
- :mod:`~asyncflow_tpu.observability.report` — device-trace summaries
  (the promoted ``scripts/trace_summary.py``).

See docs/guides/observability.md for the workflow.
"""

from asyncflow_tpu.observability.export import (
    load_chrome_trace,
    read_run_records,
    sim_trace_events,
    validate_run_record,
    validate_sim_trace,
    write_chrome_trace,
    write_sim_trace,
)
from asyncflow_tpu.observability.ledger import CompileLedger, default_ledger_path
from asyncflow_tpu.observability.phases import PHASES, PhaseRecord, PhaseTimer
from asyncflow_tpu.observability.simtrace import (
    FR_NAMES,
    FlightRecord,
    TraceConfig,
    canonical_spans,
    decode_breaker,
    decode_flight,
    flight_dropped_events,
)
from asyncflow_tpu.observability.telemetry import (
    RUN_RECORD_SCHEMA,
    RunTelemetry,
    TelemetryConfig,
    current_telemetry,
    emit_event_record,
    instrument_jit,
    maybe_phase,
    telemetry_session,
)

__all__ = [
    "FR_NAMES",
    "PHASES",
    "RUN_RECORD_SCHEMA",
    "CompileLedger",
    "FlightRecord",
    "PhaseRecord",
    "PhaseTimer",
    "RunTelemetry",
    "TelemetryConfig",
    "TraceConfig",
    "canonical_spans",
    "current_telemetry",
    "decode_breaker",
    "decode_flight",
    "default_ledger_path",
    "emit_event_record",
    "flight_dropped_events",
    "instrument_jit",
    "load_chrome_trace",
    "maybe_phase",
    "read_run_records",
    "sim_trace_events",
    "telemetry_session",
    "validate_run_record",
    "validate_sim_trace",
    "write_chrome_trace",
    "write_sim_trace",
]
