"""Latency attribution plane: per-phase, per-component tail blame.

Every completed request's end-to-end latency is decomposed on-device into
additive **phase** buckets attributed to the **component** (server, edge, or
the virtual client) where the time was spent, then scattered into pooled
fixed-bin histograms keyed by the request's final latency bin.  The layout is
identical across all three engines (oracle heap loop, vmapped XLA event
engine, scan fast path), so blame grids are directly diffable and poolable in
the ``gauge_hist`` mold: float64 host aggregation, summed across sweep
chunks, persisted in checkpoint chunks, rebuilt on quarantine splice.

Grid layout
-----------
``blame``      — ``(n_cells, n_blame_bins)`` float: seconds spent in cell
                 ``comp * N_PHASES + phase`` by requests whose end-to-end
                 latency fell in coarse latency bin ``b``.
``blame_lat``  — ``(n_blame_bins,)`` float: total end-to-end latency seconds
                 of those requests (the conservation denominator: for every
                 bin, ``blame[:, b].sum() == blame_lat[b]`` within float32
                 tolerance).

Conservation precision
----------------------
Per request, the phase row sums to the attempt's end-to-end latency to
within ±1 ulp of float32 (the row is built from exact realized-timestamp
differences; ``SimulationResults.blame_req`` is the witness).  The POOLED
device grids accumulate in float32 — near-constant increments (a
deterministic service time scattered thousands of times into one cell)
round the same direction for long stretches, so pooled sums drift by up to
~1e-4 relative while the stochastic ``blame_lat`` side drifts differently.
Gate pooled conservation at ``rtol=1e-3`` and per-request conservation
tightly; cross-chunk pooling is float64 on host and adds nothing.

Coarse bins are a stride-decimation of the engines' shared log-spaced
latency histogram (:func:`asyncflow_tpu.engines.jaxsim.params.hist_edges`),
so per-bin request counts need no extra array — they fall out of the fine
histogram by summing stride groups (:func:`coarse_counts`).

Phase taxonomy
--------------
Queue waits are split by the resource waited on (CPU ready queue, RAM
admission, DB connection pool, serving batch admission).  Service covers CPU
bursts and plain/cache/LLM IO sleeps; serving splits out prefill, decode,
and KV-eviction redo (a re-admission's repeated prefill).  Transit is edge
time; hedge is a winning duplicate's wait from the anchor's start to its own
fire time.  ``backoff`` and ``dark`` are reserved: under attempt-scoped
latency (every engine restarts the clock at re-issue) a COMPLETED attempt
never contains client backoff or dark-window loss — those buckets exist so
the layout can absorb logical-request-scoped attribution later without a
schema bump, and are structurally zero today.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# ---------------------------------------------------------------------------
# layout constants
# ---------------------------------------------------------------------------

PH_Q_CPU = 0  # CPU ready-queue wait (core contention)
PH_Q_RAM = 1  # RAM admission wait
PH_Q_DB = 2  # DB connection-pool wait
PH_Q_ADMIT = 3  # serving batch-admission wait (slot/KV gate)
PH_SERVICE = 4  # CPU bursts + plain/cache/LLM IO sleeps
PH_PREFILL = 5  # serving prefill sleep (first admission)
PH_DECODE = 6  # serving decode sleep
PH_KV_REDO = 7  # repeated prefill after a KV eviction re-admission
PH_TRANSIT = 8  # edge traversal (network latency + spikes + fault factors)
PH_BACKOFF = 9  # reserved: client retry backoff (structurally zero today)
PH_HEDGE = 10  # winning duplicate's wait from anchor start to hedge fire
PH_DARK = 11  # reserved: dark-window loss/reissue (structurally zero today)

N_PHASES = 12

PHASE_NAMES = (
    "q_cpu",
    "q_ram",
    "q_db",
    "q_admit",
    "service",
    "prefill",
    "decode",
    "kv_redo",
    "transit",
    "backoff",
    "hedge",
    "dark",
)

#: target coarse-bin count; the actual count divides the fine histogram
BLAME_BINS = 64


def blame_stride(n_hist_bins: int) -> int:
    """Fine-bins-per-coarse-bin decimation stride."""
    return max(1, n_hist_bins // BLAME_BINS)


def n_blame_bins(n_hist_bins: int) -> int:
    """Coarse latency-bin count for an ``n_hist_bins``-bin fine histogram."""
    stride = blame_stride(n_hist_bins)
    return -(-n_hist_bins // stride)  # ceil


def n_components(n_servers: int, n_edges: int) -> int:
    """Servers, then edges, then the virtual client (retry/hedge waits)."""
    return n_servers + n_edges + 1


def comp_server(s: int) -> int:
    return s


def comp_edge(n_servers: int, e: int) -> int:
    return n_servers + e


def comp_client(n_servers: int, n_edges: int) -> int:
    return n_servers + n_edges


def n_cells(n_servers: int, n_edges: int) -> int:
    return n_components(n_servers, n_edges) * N_PHASES


def cell(comp: int, phase: int) -> int:
    """Flat grid row of ``(component, phase)``."""
    return comp * N_PHASES + phase


def component_names(server_ids, edge_ids) -> list[str]:
    """Component labels in canonical index order (client last)."""
    return [*server_ids, *edge_ids, "client"]


def blame_edges(n_hist_bins: int) -> np.ndarray:
    """Coarse latency-bin edges (seconds): every ``stride``-th fine edge."""
    from asyncflow_tpu.engines.jaxsim.params import hist_edges

    fine = hist_edges(n_hist_bins)
    stride = blame_stride(n_hist_bins)
    idx = np.arange(0, n_hist_bins, stride)
    return np.append(fine[idx], fine[-1])


def coarse_counts(hist: np.ndarray) -> np.ndarray:
    """Per-coarse-bin completion counts from the fine latency histogram."""
    hist = np.asarray(hist, dtype=np.float64)
    n = hist.shape[-1]
    stride = blame_stride(n)
    nb = n_blame_bins(n)
    pad = nb * stride - n
    if pad:
        hist = np.concatenate(
            [hist, np.zeros((*hist.shape[:-1], pad), np.float64)], axis=-1,
        )
    return hist.reshape(*hist.shape[:-1], nb, stride).sum(axis=-1)


def phase_grid(blame: np.ndarray, n_servers: int, n_edges: int) -> np.ndarray:
    """Reshape a flat ``(n_cells, B)`` grid to ``(n_comp, N_PHASES, B)``."""
    blame = np.asarray(blame, dtype=np.float64)
    return blame.reshape(n_components(n_servers, n_edges), N_PHASES, -1)


def _shares(totals: np.ndarray) -> np.ndarray:
    denom = float(totals.sum())
    if denom <= 0.0:
        return np.zeros_like(totals, dtype=np.float64)
    return np.asarray(totals, dtype=np.float64) / denom


# ---------------------------------------------------------------------------
# host-side breakdowns (SweepReport.latency_blame / summary shares)
# ---------------------------------------------------------------------------


@dataclass
class BlameReport:
    """One quantile's (or tail's) latency decomposition.

    Shares are fractions of total attributed seconds in the selected bin
    range and sum to 1 when any time was attributed.  ``bin_lo_s`` /
    ``bin_hi_s`` bound the selected coarse latency bins — a point quantile
    is exact to one coarse bin; a tail (``tail_of=q``) covers every bin at
    or above the quantile's bin.
    """

    q: float
    tail: bool
    bin_lo_s: float
    bin_hi_s: float
    n_requests: float
    total_s: float
    phase_shares: dict[str, float]
    component_shares: dict[str, float]
    cells: list[tuple[str, str, float]]  # (component, phase, share) desc

    def top(self, k: int = 5) -> list[tuple[str, str, float]]:
        return self.cells[:k]


def quantile_coarse_bin(hist: np.ndarray, q: float) -> int:
    """Coarse bin holding the pooled ``q``-quantile of the fine histogram."""
    counts = coarse_counts(np.asarray(hist, dtype=np.float64))
    total = counts.sum()
    if total <= 0:
        return 0
    cum = np.cumsum(counts)
    rank = q * total
    return int(np.searchsorted(cum, rank, side="left").clip(0, len(counts) - 1))


def blame_breakdown(
    blame: np.ndarray,
    hist: np.ndarray,
    *,
    n_servers: int,
    n_edges: int,
    server_ids,
    edge_ids,
    q: float = 0.95,
    tail: bool = False,
    min_share: float = 1e-4,
) -> BlameReport:
    """Decompose latency at (or above) the pooled ``q``-quantile.

    ``tail=False`` blames the single coarse bin containing the quantile
    ("what does a p95 request spend its time on"); ``tail=True`` pools every
    bin at or above it ("among requests above the p95...").
    """
    grid = phase_grid(blame, n_servers, n_edges)  # (C, P, B)
    nb = grid.shape[-1]
    fine_n = np.asarray(hist).shape[-1]
    edges = blame_edges(fine_n)
    b = quantile_coarse_bin(hist, q)
    sel = slice(b, nb) if tail else slice(b, b + 1)
    cell_s = grid[:, :, sel].sum(axis=-1)  # (C, P)
    counts = coarse_counts(hist)[sel].sum()
    names = component_names(server_ids, edge_ids)
    phase_shares = dict(zip(PHASE_NAMES, _shares(cell_s.sum(axis=0))))
    comp_shares = dict(zip(names, _shares(cell_s.sum(axis=1))))
    flat = _shares(cell_s).ravel()
    order = np.argsort(flat)[::-1]
    cells = [
        (names[k // N_PHASES], PHASE_NAMES[k % N_PHASES], float(flat[k]))
        for k in order
        if flat[k] >= min_share
    ]
    return BlameReport(
        q=q,
        tail=tail,
        bin_lo_s=float(edges[b]),
        bin_hi_s=float(edges[-1] if tail else edges[b + 1]),
        n_requests=float(counts),
        total_s=float(cell_s.sum()),
        phase_shares=phase_shares,
        component_shares=comp_shares,
        cells=cells,
    )


def blame_shares(blame: np.ndarray) -> dict[str, float]:
    """Whole-run phase shares (``summary()`` keys ``blame_share_<phase>``)."""
    grid = np.asarray(blame, dtype=np.float64)
    ncomp = grid.shape[0] // N_PHASES
    totals = grid.reshape(ncomp, N_PHASES, -1).sum(axis=(0, 2))
    return dict(zip(PHASE_NAMES, _shares(totals)))
