"""Static HTML sweep dashboard: one self-contained file, no external assets.

Renders a telemetry JSONL (and optionally the finished
:class:`~asyncflow_tpu.parallel.SweepReport`) into inline-SVG charts:

- run summary with confidence intervals (when a report is given),
- live progress (scenarios done / EWMA throughput over elapsed time),
- cross-scenario gauge quantile bands over simulated time,
- latency blame waterfall (attributed ``SweepRunner(..., blame=True)`` runs),
- recovery / quarantine timeline,
- phase timers and the compile ledger's warm/cold verdicts.

The output embeds everything (styles, SVG, data) so it can be attached to
a CI artifact or mailed around::

    python -m asyncflow_tpu.observability.dashboard run.jsonl -o sweep.html

Chart rendering is host-side Python producing plain SVG — no JS
dependencies, nothing fetched at view time.
"""

from __future__ import annotations

import argparse
import html
import json
import sys
from pathlib import Path

from asyncflow_tpu.observability.export import read_run_records

_CSS = """
body { font-family: system-ui, sans-serif; margin: 2rem auto; max-width: 70rem;
       color: #1a1a2e; background: #fafafa; }
h1 { font-size: 1.4rem; } h2 { font-size: 1.1rem; margin-top: 2rem;
     border-bottom: 1px solid #ddd; padding-bottom: .3rem; }
table { border-collapse: collapse; font-size: .85rem; }
td, th { padding: .25rem .7rem; border: 1px solid #e0e0e0; text-align: left; }
th { background: #f0f0f4; }
.warm { color: #1b7837; } .cold { color: #b2182b; }
svg { background: #fff; border: 1px solid #e0e0e0; }
.note { color: #666; font-size: .8rem; }
"""

_W, _H, _PAD = 640, 220, 40


def _esc(x) -> str:
    return html.escape(str(x))


def _scale(vals, lo, hi, out_lo, out_hi):
    span = (hi - lo) or 1.0
    return [out_lo + (v - lo) / span * (out_hi - out_lo) for v in vals]


def _axes(x_label: str, y_label: str, x_max, y_max) -> str:
    return (
        f'<line x1="{_PAD}" y1="{_H - _PAD}" x2="{_W - 10}" y2="{_H - _PAD}" '
        'stroke="#999"/>'
        f'<line x1="{_PAD}" y1="10" x2="{_PAD}" y2="{_H - _PAD}" stroke="#999"/>'
        f'<text x="{_W // 2}" y="{_H - 6}" font-size="11" text-anchor="middle">'
        f"{_esc(x_label)} (max {x_max:g})</text>"
        f'<text x="12" y="{_H // 2}" font-size="11" text-anchor="middle" '
        f'transform="rotate(-90 12 {_H // 2})">{_esc(y_label)} '
        f"(max {y_max:g})</text>"
    )


def _polyline(xs, ys, x_max, y_max, color: str) -> str:
    px = _scale(xs, 0.0, x_max, _PAD, _W - 10)
    py = _scale(ys, 0.0, y_max, _H - _PAD, 10)
    pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(px, py))
    return f'<polyline points="{pts}" fill="none" stroke="{color}" stroke-width="1.5"/>'


def _band(xs, lo, hi, x_max, y_max, color: str) -> str:
    px = _scale(xs, 0.0, x_max, _PAD, _W - 10)
    plo = _scale(lo, 0.0, y_max, _H - _PAD, 10)
    phi = _scale(hi, 0.0, y_max, _H - _PAD, 10)
    ring = list(zip(px, phi)) + list(zip(px[::-1], plo[::-1]))
    pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in ring)
    return f'<polygon points="{pts}" fill="{color}" fill-opacity="0.25" stroke="none"/>'


def _svg(body: str) -> str:
    return f'<svg viewBox="0 0 {_W} {_H}" width="{_W}" height="{_H}">{body}</svg>'


def _kv_table(pairs) -> str:
    rows = "".join(
        f"<tr><th>{_esc(k)}</th><td>{_esc(v)}</td></tr>" for k, v in pairs
    )
    return f"<table>{rows}</table>"


# ---------------------------------------------------------------------------
# sections
# ---------------------------------------------------------------------------


def _summary_section(sweep: dict | None, report,
                     progress: list[dict] | None = None) -> str:
    out = ["<h2>Summary</h2>"]
    if sweep is not None:
        meta = sweep.get("meta", {})
        keys = (
            "engine", "backend", "n_scenarios", "seed", "wall_seconds",
            "scenarios_per_second", "n_quarantined", "recovery_actions",
            "horizon_s", "n_devices",
        )
        out.append(_kv_table([(k, meta[k]) for k in keys if k in meta]))
    else:
        out.append('<p class="note">no terminal kind="sweep" record yet — '
                   "the sweep is still running or was preempted.</p>")
    if report is not None:
        point, lo, hi = report.per_scenario_percentile_mean_ci(95)
        est = report.pooled_percentile_ci(99)
        out.append("<h3>Confidence intervals</h3>")
        out.append(_kv_table([
            ("mean per-scenario p95 (95% CI)",
             f"{point:.4f}s  [{lo:.4f}, {hi:.4f}]"),
            ("pooled p99 (95% CI)",
             f"{est.point:.4f}s  [{est.lo:.4f}, {est.hi:.4f}]"),
            ("effective scenarios",
             report.n_scenarios - report.n_quarantined),
        ]))
    serving = _serving_rows(progress, report)
    if serving:
        out.append("<h3>Serving</h3>")
        out.append(_kv_table(serving))
    return "\n".join(out)


def _serving_rows(progress: list[dict] | None,
                  report) -> list[tuple[str, object]]:
    """LLM serving counters for the summary (docs/guides/serving.md):
    from the finished report when available, else the last heartbeat."""
    res = getattr(report, "results", None)
    if res is not None and getattr(res, "decode_tokens", None) is not None:
        import numpy as np

        decode = int(np.asarray(res.decode_tokens).sum())
        horizon = float(res.settings.total_simulation_time)
        n_scen = int(np.asarray(res.decode_tokens).shape[0])
        rows = [
            ("prefill tokens", int(np.asarray(res.prefill_tokens).sum())),
            ("decode tokens", decode),
            ("tokens/s (per simulated second, pooled)",
             f"{decode / max(horizon * n_scen, 1e-300):.2f}"),
        ]
        if getattr(res, "kv_evictions", None) is not None:
            rows.append(
                ("KV evictions", int(np.asarray(res.kv_evictions).sum())),
            )
        return rows
    meta = (progress or [{}])[-1].get("meta", {})
    return [
        (key.replace("_", " "), meta[key])
        for key in ("prefill_tokens", "decode_tokens", "tokens_per_s",
                    "kv_evictions")
        if key in meta
    ]


def _progress_section(progress: list[dict]) -> str:
    if not progress:
        return ""
    metas = [p.get("meta", {}) for p in progress]
    xs = [m.get("elapsed_s", 0.0) for m in metas]
    done = [m.get("scenarios_done", 0) for m in metas]
    rate = [m.get("ewma_scenarios_per_second", 0.0) for m in metas]
    x_max = max(xs) or 1.0
    body = _axes("elapsed s", "scenarios done", x_max, max(done) or 1)
    body += _polyline(xs, done, x_max, max(done) or 1, "#2166ac")
    chart1 = _svg(body)
    body = _axes("elapsed s", "EWMA scen/s", x_max, max(rate) or 1.0)
    body += _polyline(xs, rate, x_max, max(rate) or 1.0, "#542788")
    chart2 = _svg(body)
    return f"<h2>Progress</h2>{chart1}\n{chart2}"


def _bands_section(report) -> str:
    if report is None or report.results.gauge_bands is None:
        return ""
    from asyncflow_tpu.engines.results import GAUGE_BAND_QS

    out = ["<h2>Gauge quantile bands</h2>",
           '<p class="note">across-scenario p50/p90/p99 of the streamed '
           "gauge at each coarse tick (histogram-backed, quarantine-"
           "excluded).</p>"]
    for cid in report.gauge_series_ids:
        times, bands = report.gauge_bands(cid)
        xs = list(map(float, times))
        x_max = max(xs) or 1.0
        y_max = float(bands.max()) or 1.0
        body = _axes("sim time s", _esc(cid), x_max, y_max)
        body += _band(xs, bands[0].tolist(), bands[2].tolist(), x_max, y_max,
                      "#2166ac")
        for qi, color in enumerate(("#2166ac", "#542788", "#b2182b")):
            body += _polyline(xs, bands[qi].tolist(), x_max, y_max, color)
        legend = " / ".join(
            f"p{q:g}" for q in GAUGE_BAND_QS
        )
        out.append(f"<h3>{_esc(cid)} <span class='note'>({legend})</span></h3>")
        out.append(_svg(body))
    return "\n".join(out)


def _scorecard_section(sweep: dict | None, report) -> str:
    """Resilience scorecard (docs/guides/resilience.md, "Chaos campaigns"):
    availability, dark-window losses, degraded-window goodput, drain times
    — rendered only when the run carried the fault/hazard machinery."""
    rows: list[tuple[str, object]] = []
    res = getattr(report, "results", None)
    if res is not None and getattr(res, "dark_lost", None) is not None:
        import numpy as np

        completed = int(np.asarray(res.completed).sum())
        dark = int(np.asarray(res.dark_lost).sum())
        rows.append(("requests lost to dark windows", dark))
        rows.append((
            "availability fraction",
            f"{completed / max(completed + dark, 1):.4f}",
        ))
        if res.unavailable_s is not None:
            per_server = np.asarray(res.unavailable_s).sum(axis=0)
            rows.append((
                "unavailable seconds (per server, summed over scenarios)",
                ", ".join(f"{v:.1f}" for v in per_server),
            ))
        if res.degraded_goodput is not None:
            rows.append((
                "goodput inside degraded windows",
                int(np.asarray(res.degraded_goodput).sum()),
            ))
        if res.time_to_drain is not None:
            ttd = np.asarray(res.time_to_drain, np.float64)
            finite = ttd[np.isfinite(ttd)]
            rows.append((
                "time to drain (mean over measured scenarios)",
                f"{finite.mean():.2f}s ({finite.size} measured)"
                if finite.size
                else "unmeasured (stream a ready_queue_len gauge series)",
            ))
        if res.hazard_truncated is not None:
            rows.append((
                "hazard windows truncated (slot budget)",
                int(np.asarray(res.hazard_truncated).sum()),
            ))
    elif sweep is not None:
        counters = sweep.get("counters") or {}
        if not counters.get("dark_lost"):
            return ""
        for key in ("dark_lost", "degraded_goodput", "hazard_truncated"):
            if key in counters:
                rows.append((key, counters[key]))
    if not rows:
        return ""
    return (
        "<h2>Resilience scorecard</h2>"
        '<p class="note">chaos-campaign availability metrics '
        "(docs/guides/resilience.md).</p>" + _kv_table(rows)
    )


def _blame_section(report) -> str:
    """Latency blame waterfall (docs/guides/observability.md, "Where does
    the tail come from"): horizontal bars of the (component, phase) cells
    that make up the p95 request's latency, with the tail-conditional
    decomposition beside it — rendered only for attributed sweeps."""
    res = getattr(report, "results", None)
    if res is None or getattr(res, "blame_hist", None) is None:
        return ""
    out = ["<h2>Latency blame waterfall</h2>",
           '<p class="note">additive decomposition of where requests near '
           "each quantile spent their time (pooled per-phase histograms; "
           "docs/guides/observability.md).</p>"]
    for tail, label in ((False, "p95 bin"), (True, "tail above p95")):
        br = report.latency_blame(q=0.95, tail=tail)
        top = br.top(12)
        if not top:
            continue
        total = sum(s for _, _, s in top) or 1.0
        bar_w = _W - 280
        rows = []
        offset = 0.0
        for i, (comp, phase, secs) in enumerate(top):
            y = 14 + i * 22
            x = 180 + offset / total * bar_w
            w = max(secs / total * bar_w, 1.0)
            offset += secs
            rows.append(
                f'<text x="4" y="{y + 12}" font-size="11">'
                f"{_esc(comp)} / {_esc(phase)}</text>"
                f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" height="16" '
                'fill="#542788"/>'
                f'<text x="{min(x + w + 4, _W - 72):.1f}" y="{y + 12}" '
                f'font-size="11">'
                f"{secs / max(br.n_requests, 1):.4f}s/req</text>",
            )
        height = 22 * len(top) + 20
        svg = (f'<svg viewBox="0 0 {_W} {height}" width="{_W}" '
               f'height="{height}">{"".join(rows)}</svg>')
        out.append(
            f"<h3>{_esc(label)} <span class='note'>"
            f"({br.n_requests} requests, "
            f"[{br.bin_lo_s:.4f}s, {br.bin_hi_s:.4f}s))</span></h3>",
        )
        out.append(svg)
    return "\n".join(out)


def _recovery_section(progress: list[dict], recovery: list[dict]) -> str:
    actions = [a for r in recovery for a in r.get("meta", {}).get("actions", [])]
    if not actions and not any(
        p.get("meta", {}).get("n_quarantined") for p in progress
    ):
        return ('<h2>Recovery / quarantine</h2>'
                '<p class="note">no recovery actions recorded.</p>')
    rows = "".join(
        "<tr>"
        f"<td>{_esc(a.get('kind', '?'))}</td>"
        f"<td>{_esc(a.get('scenario', a.get('scenario_start', '')))}</td>"
        f"<td>{_esc(a.get('reason', a.get('error', '')))[:200]}</td>"
        "</tr>"
        for a in actions
    )
    table = (
        "<table><tr><th>action</th><th>scenario</th><th>detail</th></tr>"
        f"{rows}</table>"
    )
    # quarantine tally over elapsed time, from the heartbeats
    metas = [p.get("meta", {}) for p in progress]
    xs = [m.get("elapsed_s", 0.0) for m in metas]
    qs = [m.get("n_quarantined", 0) for m in metas]
    chart = ""
    if xs and max(qs):
        body = _axes("elapsed s", "quarantined", max(xs) or 1.0, max(qs))
        body += _polyline(xs, qs, max(xs) or 1.0, max(qs), "#b2182b")
        chart = _svg(body)
    return f"<h2>Recovery / quarantine</h2>{table}\n{chart}"


def _phases_section(sweep: dict | None) -> str:
    if sweep is None or not sweep.get("phase_totals_s"):
        return ""
    totals = sweep["phase_totals_s"]
    t_max = max(totals.values()) or 1.0
    bar_w = _W - 180
    rows = []
    for i, (name, secs) in enumerate(
        sorted(totals.items(), key=lambda kv: -kv[1]),
    ):
        y = 14 + i * 22
        w = max(secs / t_max * bar_w, 1.0)
        rows.append(
            f'<text x="4" y="{y + 12}" font-size="11">{_esc(name)}</text>'
            f'<rect x="120" y="{y}" width="{w:.1f}" height="16" '
            'fill="#2166ac"/>'
            f'<text x="{124 + w:.1f}" y="{y + 12}" font-size="11">'
            f"{secs:.3f}s</text>",
        )
    height = 22 * len(totals) + 20
    svg = (f'<svg viewBox="0 0 {_W} {height}" width="{_W}" '
           f'height="{height}">{"".join(rows)}</svg>')
    return f"<h2>Phase timers</h2>{svg}"


def _compiles_section(sweep: dict | None) -> str:
    if sweep is None or not sweep.get("compiles"):
        return ""
    rows = []
    for c in sweep["compiles"]:
        warm = bool(c.get("cache_hit"))
        verdict = ('<span class="warm">warm</span>' if warm
                   else '<span class="cold">cold</span>')
        secs = c.get("compile_s")
        rows.append(
            "<tr>"
            f"<td>{_esc(c.get('key', '?'))[:60]}</td>"
            f"<td>{_esc(c.get('engine', ''))}</td>"
            f"<td>{verdict}</td>"
            f"<td>{'' if secs is None else f'{secs:.3f}s'}</td>"
            "</tr>",
        )
    return (
        "<h2>Compile ledger</h2>"
        "<table><tr><th>program</th><th>engine</th><th>verdict</th>"
        f"<th>compile</th></tr>{''.join(rows)}</table>"
    )


# ---------------------------------------------------------------------------
# assembly
# ---------------------------------------------------------------------------


def build_dashboard(
    records: list[dict],
    *,
    report=None,
    title: str = "asyncflow sweep",
) -> str:
    """Render the run records (+ optional finished report) to an HTML page."""
    progress = [r for r in records if r.get("kind") == "progress"]
    recovery = [r for r in records if r.get("kind") == "recovery"]
    sweeps = [r for r in records if r.get("kind") == "sweep"]
    sweep = sweeps[-1] if sweeps else None
    sections = [
        _summary_section(sweep, report, progress),
        _progress_section(progress),
        _bands_section(report),
        _blame_section(report),
        _scorecard_section(sweep, report),
        _recovery_section(progress, recovery),
        _phases_section(sweep),
        _compiles_section(sweep),
    ]
    body = "\n".join(s for s in sections if s)
    return (
        "<!DOCTYPE html>\n<html><head><meta charset='utf-8'>"
        f"<title>{_esc(title)}</title><style>{_CSS}</style></head>"
        f"<body><h1>{_esc(title)}</h1>\n{body}\n"
        f"<p class='note'>records: {len(records)} "
        f"(progress {len(progress)}, recovery {len(recovery)}, "
        f"sweep {len(sweeps)})</p></body></html>"
    )


def write_dashboard(
    jsonl_path: str | Path,
    out_path: str | Path,
    *,
    report=None,
    title: str | None = None,
) -> Path:
    """Read a telemetry JSONL and write the dashboard HTML beside it."""
    records = read_run_records(jsonl_path)
    page = build_dashboard(
        records,
        report=report,
        title=title or f"asyncflow sweep — {Path(jsonl_path).name}",
    )
    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(page)
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m asyncflow_tpu.observability.dashboard",
        description="Render a sweep telemetry JSONL to a static HTML page.",
    )
    parser.add_argument("jsonl", help="telemetry JSONL path")
    parser.add_argument(
        "-o", "--out", default=None,
        help="output HTML path (default: <jsonl stem>.html beside the input)",
    )
    args = parser.parse_args(argv)
    out = args.out or str(Path(args.jsonl).with_suffix(".html"))
    path = write_dashboard(args.jsonl, out)
    n = len(read_run_records(args.jsonl))
    print(f"wrote {path} ({n} records)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
