"""Oracle <-> JAX divergence finder: localize the first disagreement.

Parity bugs used to be binary-searchable only: a percentile drifted, and
the offending mechanism had to be guessed from the topology.  This module
turns the flight recorder (:mod:`~asyncflow_tpu.observability.simtrace`)
into a diff tool:

- **flight mode** (:func:`find_first_divergence`): run two engines (any
  pair of ``oracle`` / ``event`` / ``fast`` — the scan fast path now
  carries the recorder) on the same payload/seed with tracing on,
  canonicalize both event streams (per-request RELATIVE timelines — the
  engines' RNG/sampling families differ, so absolute times are
  incomparable; on deterministic-latency scenarios like
  ``examples/yaml_input/data/trace_parity.yml`` the relative timelines
  must agree exactly), and report the first differing event with an
  aligned context window.  Zero divergence on the parity scenario is a
  smoke-tier gate, and ``--engines fast,event`` is the event-level gate
  on the fast path's resilient journey rewrite.
- **stats mode** (:func:`stat_divergence`): for stochastic scenarios,
  compare seed ensembles statistic-by-statistic in lifecycle order
  (count, mean, then quantiles) against an oracle-vs-oracle split-half
  noise floor — the first statistic whose deviation exceeds both the
  tolerance AND the noise floor is the localized divergence; deviations
  inside the noise floor are the seed lottery, not an engine bug.

CLI::

    python -m asyncflow_tpu.observability.diverge scenario.yml \
        [--mode flight|stats] [--seed N] [--seeds N] [--engine event|fast]
        [--engines oracle,event|fast,event|...] [--requests K] [--slots N]
        [--tol-us 50] [--tol 0.05] [--json]

Exit status: 0 = no divergence, 2 = divergence found (1 = usage error).
"""

from __future__ import annotations

import json
import sys
from dataclasses import dataclass, field

import numpy as np

from asyncflow_tpu.observability.simtrace import (
    FlightRecord,
    TraceConfig,
    canonical_spans,
)


@dataclass
class Divergence:
    """First differing event between two canonicalized streams."""

    request: int
    index: int  #: event index within the request's span record
    kind: str  #: "code" | "node" | "time" | "length"
    oracle_event: tuple | None
    jax_event: tuple | None
    #: aligned context windows (formatted lines, divergence marked)
    context_oracle: list[str] = field(default_factory=list)
    context_jax: list[str] = field(default_factory=list)


@dataclass
class DivergenceReport:
    """Outcome of one flight-mode comparison."""

    equal: bool
    requests_compared: int
    divergence: Divergence | None = None
    #: request indices present on only one side (arrival-count tail
    #: mismatch near the horizon — reported, but not a divergence)
    only_oracle: list[int] = field(default_factory=list)
    only_jax: list[int] = field(default_factory=list)
    #: the engine pair the records came from (labels the summary; the
    #: ``*_oracle``/``*_jax`` field names stay stable for JSON consumers)
    engines: tuple[str, str] = ("oracle", "jax")

    def summary(self) -> str:
        ea, eb = self.engines
        if self.equal:
            return (
                f"no divergence ({ea} vs {eb}): {self.requests_compared} "
                "request span record(s) identical after canonicalization"
            )
        d = self.divergence
        lines = [
            f"first divergence ({ea} vs {eb}) at request {d.request}, "
            f"event {d.index} ({d.kind}):",
            f"  {ea}: {d.oracle_event}",
            f"  {eb}: {d.jax_event}",
            f"  context ({ea} | {eb}), '>' marks the divergence:",
        ]
        width = max((len(s) for s in d.context_oracle), default=0)
        for left, right in zip(d.context_oracle, d.context_jax):
            lines.append(f"    {left:<{width}} | {right}")
        extra = max(len(d.context_oracle), len(d.context_jax)) - min(
            len(d.context_oracle), len(d.context_jax),
        )
        if extra:
            longer = (
                d.context_oracle
                if len(d.context_oracle) > len(d.context_jax)
                else d.context_jax
            )
            side = ea if longer is d.context_oracle else eb
            for line in longer[-extra:]:
                lines.append(f"    ({side} only) {line}")
        return "\n".join(lines)


def _fmt_event(ev: tuple, mark: bool) -> str:
    from asyncflow_tpu.observability.simtrace import FR_NAMES

    code, node, t_us = ev
    name = FR_NAMES.get(code, f"code{code}")
    return f"{'>' if mark else ' '} +{t_us / 1e3:.3f}ms {name}[{node}]"


def compare_flight(
    flight_oracle: dict[int, FlightRecord],
    flight_jax: dict[int, FlightRecord],
    *,
    horizon: float | None = None,
    tol_us: float = 50.0,
    context: int = 4,
    engines: tuple[str, str] = ("oracle", "jax"),
) -> DivergenceReport:
    """Diff two flight-record sets after canonicalization.

    Codes and node ids must match exactly; relative timestamps within
    ``tol_us`` microseconds (the jax engine's float32 sim clock carries
    ~8 us of rounding at a 120 s horizon — exact-quantization comparison
    would flag pure precision noise).  ``engines`` labels the two sides
    in the summary (the record dicts themselves are engine-agnostic).
    """
    spans_o = canonical_spans(flight_oracle, horizon=horizon)
    spans_j = canonical_spans(flight_jax, horizon=horizon)
    common = sorted(set(spans_o) & set(spans_j))
    report = DivergenceReport(
        equal=True,
        requests_compared=len(common),
        only_oracle=sorted(set(spans_o) - set(spans_j)),
        only_jax=sorted(set(spans_j) - set(spans_o)),
        engines=engines,
    )
    for req in common:
        a, b = spans_o[req], spans_j[req]
        n = min(len(a), len(b))
        diverged_at = None
        kind = None
        for k in range(n):
            (ca, na, ta), (cb, nb, tb) = a[k], b[k]
            if ca != cb:
                diverged_at, kind = k, "code"
            elif na != nb:
                diverged_at, kind = k, "node"
            elif abs(ta - tb) > tol_us:
                diverged_at, kind = k, "time"
            if diverged_at is not None:
                break
        if diverged_at is None and len(a) != len(b):
            diverged_at, kind = n, "length"
        if diverged_at is None:
            continue
        lo = max(0, diverged_at - context)
        hi = diverged_at + context + 1
        report.equal = False
        report.divergence = Divergence(
            request=req,
            index=diverged_at,
            kind=kind,
            oracle_event=a[diverged_at] if diverged_at < len(a) else None,
            jax_event=b[diverged_at] if diverged_at < len(b) else None,
            context_oracle=[
                _fmt_event(a[k], k == diverged_at)
                for k in range(lo, min(hi, len(a)))
            ],
            context_jax=[
                _fmt_event(b[k], k == diverged_at)
                for k in range(lo, min(hi, len(b)))
            ],
        )
        return report
    return report


#: engines the flight recorder runs on (pallas/native stay fenced)
FLIGHT_ENGINES = ("oracle", "event", "fast")


def _flight_records(payload, engine: str, seed: int, trace: TraceConfig):
    """One engine's flight-record dict for ``payload``/``seed``."""
    if engine == "oracle":
        from asyncflow_tpu.engines.oracle.engine import OracleEngine

        return OracleEngine(payload, seed=seed, trace=trace).run().flight
    if engine in ("event", "fast"):
        from asyncflow_tpu.engines.jaxsim.engine import run_single

        return run_single(payload, seed=seed, engine=engine, trace=trace).flight
    msg = (
        f"flight mode compares {'/'.join(FLIGHT_ENGINES)} engines, "
        f"got {engine!r}"
    )
    raise ValueError(msg)


def find_first_divergence(
    payload,
    *,
    seed: int = 0,
    trace: TraceConfig | None = None,
    tol_us: float = 50.0,
    context: int = 4,
    engines: tuple[str, str] = ("oracle", "event"),
) -> DivergenceReport:
    """Run two traced engines on ``payload``/``seed`` with the flight
    recorder on and diff the canonicalized streams.

    ``engines`` picks the pair (default the historical oracle↔event
    diff); ``("fast", "event")`` is the event-level gate on the scan
    fast path's analytically derived records.
    """
    ea, eb = engines
    trace = trace or TraceConfig()
    horizon = float(payload.sim_settings.total_simulation_time)
    flight_a = _flight_records(payload, ea, seed, trace)
    flight_b = _flight_records(payload, eb, seed, trace)
    return compare_flight(
        flight_a,
        flight_b,
        horizon=horizon,
        tol_us=tol_us,
        context=context,
        engines=(ea, eb),
    )


# ---------------------------------------------------------------------------
# stats mode: ensembles vs the oracle's own noise floor
# ---------------------------------------------------------------------------


@dataclass
class StatRow:
    stat: str
    oracle: float
    jax: float
    rel_delta: float  #: |jax - oracle| / |oracle|
    noise_floor: float  #: oracle split-half |delta| on the same stat
    exceeds: bool  #: rel_delta > tol AND rel_delta > noise floor


@dataclass
class StatReport:
    engine: str
    seeds: int
    tol: float
    rows: list[StatRow]
    first_exceeding: str | None

    @property
    def equal(self) -> bool:
        return self.first_exceeding is None

    @property
    def engine_pair(self) -> tuple[str, str]:
        """The two engines this report compared (self-describing CI logs)."""
        return ("oracle", self.engine)

    def summary(self) -> str:
        lines = [
            f"ensemble comparison (engine pair: oracle vs {self.engine}): "
            f"{self.seeds} seeds, tol {self.tol:.1%}:",
        ]
        for r in self.rows:
            mark = ">" if r.exceeds else " "
            lines.append(
                f" {mark} {r.stat:>6}: oracle {r.oracle:.6f}  "
                f"{self.engine} {r.jax:.6f}  delta {r.rel_delta:+.2%}  "
                f"(oracle split-half noise {r.noise_floor:.2%})",
            )
        if self.first_exceeding is None:
            lines.append(
                "no statistic exceeds both the tolerance and the oracle's "
                "own split-half noise floor: deviations are seed lottery, "
                "not a localized engine bug",
            )
        else:
            lines.append(
                f"first diverging statistic: {self.first_exceeding} "
                f"(oracle vs {self.engine})",
            )
        return "\n".join(lines)


def _stats(lat: np.ndarray, quantiles) -> dict[str, float]:
    out = {"count": float(lat.size), "mean": float(lat.mean())}
    for q in quantiles:
        out[f"p{q:g}"] = float(np.percentile(lat, q))
    return out


def stat_divergence(
    payload,
    *,
    engine: str = "fast",
    seeds: int = 8,
    tol: float = 0.05,
    quantiles=(50, 90, 95),
) -> StatReport:
    """Compare oracle and JAX-engine latency ensembles stat-by-stat.

    The reference point for "diverged" is the oracle's own split-half
    deviation on the same statistic: a delta inside that noise floor is
    what disjoint same-engine ensembles produce at these settings (the
    seed lottery), so only deltas exceeding BOTH the tolerance and the
    noise floor localize a real divergence.
    """
    from asyncflow_tpu.compiler import compile_payload
    from asyncflow_tpu.engines.jaxsim.engine import (
        Engine,
        scenario_keys,
    )
    from asyncflow_tpu.engines.oracle.engine import OracleEngine

    per_seed = [
        OracleEngine(payload, seed=s).run().latencies for s in range(seeds)
    ]
    lat_o = np.concatenate(per_seed)
    half = max(1, seeds // 2)
    lat_a = np.concatenate(per_seed[:half])
    lat_b = np.concatenate(per_seed[half:]) if seeds > 1 else lat_a

    plan = compile_payload(payload)
    if engine == "fast":
        from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine

        if not plan.fastpath_ok:
            msg = (
                f"this plan is not fast-path eligible "
                f"({plan.fastpath_reason}); use engine='event'"
            )
            raise ValueError(msg)
        eng = FastEngine(plan, collect_clocks=True)
    elif engine == "event":
        eng = Engine(plan, collect_clocks=True)
    else:
        msg = f"engine must be 'fast' or 'event', got {engine!r}"
        raise ValueError(msg)
    final = eng.run_batch(scenario_keys(11, seeds))
    clock = np.asarray(final.clock)
    counts = np.asarray(final.clock_n)
    lat_j = np.concatenate(
        [
            clock[i, : min(counts[i], clock.shape[1]), 1]
            - clock[i, : min(counts[i], clock.shape[1]), 0]
            for i in range(seeds)
        ],
    )

    s_o = _stats(lat_o, quantiles)
    s_j = _stats(lat_j, quantiles)
    s_a = _stats(lat_a, quantiles)
    s_b = _stats(lat_b, quantiles)
    rows = []
    first = None
    for stat in s_o:
        o, j = s_o[stat], s_j[stat]
        rel = abs(j - o) / abs(o) if o else float("inf")
        noise = (
            abs(s_a[stat] - s_b[stat]) / abs(s_o[stat]) if s_o[stat] else 0.0
        )
        exceeds = rel > tol and rel > noise
        if exceeds and first is None:
            first = stat
        rows.append(
            StatRow(
                stat=stat,
                oracle=o,
                jax=j,
                rel_delta=rel,
                noise_floor=noise,
                exceeds=exceeds,
            ),
        )
    return StatReport(
        engine=engine, seeds=seeds, tol=tol, rows=rows, first_exceeding=first,
    )


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m asyncflow_tpu.observability.diverge",
        description=(
            "Run the oracle and the JAX engine on one scenario and report "
            "the first divergence between their event streams (flight "
            "mode) or latency ensembles (stats mode)."
        ),
    )
    parser.add_argument("scenario", help="YAML scenario file")
    parser.add_argument(
        "--mode", choices=("flight", "stats"), default="flight",
    )
    parser.add_argument("--seed", type=int, default=0, help="flight mode seed")
    parser.add_argument(
        "--seeds", type=int, default=8, help="stats mode ensemble size",
    )
    parser.add_argument(
        "--engine",
        choices=("event", "fast"),
        default="fast",
        help="stats mode JAX engine (compared against the oracle ensemble)",
    )
    parser.add_argument(
        "--engines",
        default="oracle,event",
        help=(
            "flight mode engine pair as 'A,B' (each of oracle/event/fast); "
            "'fast,event' is the fast-path event-level gate"
        ),
    )
    parser.add_argument(
        "--requests", type=int, default=8, help="traced requests per scenario",
    )
    parser.add_argument(
        "--slots", type=int, default=48, help="event slots per traced request",
    )
    parser.add_argument(
        "--tol-us",
        type=float,
        default=50.0,
        help="flight mode: relative-timestamp tolerance (microseconds)",
    )
    parser.add_argument(
        "--tol",
        type=float,
        default=0.05,
        help="stats mode: relative-deviation tolerance",
    )
    parser.add_argument("--context", type=int, default=4)
    parser.add_argument(
        "--json", action="store_true", help="machine-readable report",
    )
    args = parser.parse_args(argv)

    import yaml

    from asyncflow_tpu.schemas.payload import SimulationPayload

    payload = SimulationPayload.model_validate(
        yaml.safe_load(open(args.scenario).read()),
    )

    if args.mode == "flight":
        pair = tuple(p.strip() for p in args.engines.split(","))
        if len(pair) != 2 or any(p not in FLIGHT_ENGINES for p in pair):
            parser.error(
                f"--engines must be 'A,B' with each of "
                f"{'/'.join(FLIGHT_ENGINES)}, got {args.engines!r}"
            )
        report = find_first_divergence(
            payload,
            seed=args.seed,
            trace=TraceConfig(
                sample_requests=args.requests, event_slots=args.slots,
            ),
            tol_us=args.tol_us,
            context=args.context,
            engines=pair,
        )
        if args.json:
            from dataclasses import asdict

            print(json.dumps(asdict(report), default=str))
        else:
            print(report.summary())
        return 0 if report.equal else 2

    report = stat_divergence(
        payload,
        engine=args.engine,
        seeds=args.seeds,
        tol=args.tol,
    )
    if args.json:
        from dataclasses import asdict

        print(json.dumps(asdict(report), default=str))
    else:
        print(report.summary())
    return 0 if report.equal else 2


if __name__ == "__main__":
    sys.exit(main())
