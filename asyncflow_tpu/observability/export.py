"""Exporters: JSONL run records and Chrome-trace/Perfetto host timelines.

The Chrome trace format (the ``traceEvents`` JSON that Perfetto,
``chrome://tracing``, and ``scripts/trace_summary.py`` all read) is the
lingua franca of this repo's profiling work; the host phase timeline is
emitted in the same format so one UI shows both the XLA device trace
(``jax.profiler``) and the library's own phase spans.
"""

from __future__ import annotations

import gzip
import json
from pathlib import Path

from asyncflow_tpu.observability.phases import PHASES, PhaseTimer

#: synthetic pid/tid for the host phase track (Chrome traces need both)
HOST_PID = 1
HOST_TID = 1


def chrome_trace_events(
    timer: PhaseTimer,
    *,
    counters: dict | None = None,
    label: str = "asyncflow-run",
) -> list[dict]:
    """Phase records -> Chrome ``traceEvents`` (complete 'X' spans).

    Timestamps are microseconds from the timer's epoch; chunk-tagged spans
    carry the chunk index in ``args`` so Perfetto can group/filter them.
    Counter totals are appended as one 'C' (counter) event at the end of
    the timeline.
    """
    events: list[dict] = [
        {
            "ph": "M",
            "pid": HOST_PID,
            "name": "process_name",
            "args": {"name": f"asyncflow host ({label})"},
        },
        {
            "ph": "M",
            "pid": HOST_PID,
            "tid": HOST_TID,
            "name": "thread_name",
            "args": {"name": "run phases"},
        },
    ]
    end_us = 0.0
    for rec in timer.events:
        args: dict = {}
        if rec.chunk is not None:
            args["chunk"] = rec.chunk
        if rec.meta:
            args.update(rec.meta)
        start_us = rec.start_s * 1e6
        dur_us = rec.duration_s * 1e6
        end_us = max(end_us, start_us + dur_us)
        events.append(
            {
                "ph": "X",
                "pid": HOST_PID,
                "tid": HOST_TID,
                "name": rec.name,
                "ts": start_us,
                "dur": dur_us,
                "args": args,
            },
        )
    if counters:
        events.append(
            {
                "ph": "C",
                "pid": HOST_PID,
                "name": "device counters",
                "ts": end_us,
                "args": {k: int(v) for k, v in counters.items()},
            },
        )
    return events


def write_chrome_trace(
    path: str | Path,
    timer: PhaseTimer,
    *,
    counters: dict | None = None,
    label: str = "asyncflow-run",
) -> Path:
    """Write the host phase timeline as a Chrome-trace file.

    ``path`` ending in ``.gz`` writes gzip (the format
    ``scripts/trace_summary.py`` and Perfetto both accept).
    """
    path = Path(path)
    payload = {
        "displayTimeUnit": "ms",
        "traceEvents": chrome_trace_events(timer, counters=counters, label=label),
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    data = json.dumps(payload).encode()
    if path.suffix == ".gz":
        with gzip.open(path, "wb") as fh:
            fh.write(data)
    else:
        path.write_bytes(data)
    return path


def load_chrome_trace(path: str | Path) -> dict:
    """Read a Chrome-trace file written by :func:`write_chrome_trace`."""
    path = Path(path)
    if path.suffix == ".gz":
        with gzip.open(path, "rb") as fh:
            return json.load(fh)
    return json.loads(path.read_text())


def read_run_records(path: str | Path) -> list[dict]:
    """Load every run record from a telemetry JSONL file (oldest first)."""
    path = Path(path)
    if not path.exists():
        return []
    out = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue  # torn tail line from a killed process
    return out


def validate_run_record(record: dict) -> list[str]:
    """Schema check for one run record; returns problems (empty = valid).

    The smoke tier runs this against a fresh record so schema drift is
    caught per-commit without loading an accelerator.
    """
    problems: list[str] = []

    def need(key: str, types) -> None:
        if key not in record:
            problems.append(f"missing key {key!r}")
        elif not isinstance(record[key], types):
            problems.append(
                f"{key!r} has type {type(record[key]).__name__}, "
                f"expected {types}",
            )

    need("schema", str)
    need("ts", (int, float))
    need("kind", str)
    need("phase_totals_s", dict)
    need("phases", list)
    need("compiles", list)
    need("counters", dict)
    if problems:
        return problems
    if not record["schema"].startswith("asyncflow-telemetry/"):
        problems.append(f"unknown schema {record['schema']!r}")
    for i, ph in enumerate(record["phases"]):
        for key in ("name", "start_s", "duration_s"):
            if key not in ph:
                problems.append(f"phases[{i}] missing {key!r}")
        if ph.get("duration_s", 0) < 0:
            problems.append(f"phases[{i}] negative duration")
    known = set(PHASES)
    for name in record["phase_totals_s"]:
        if name not in known and not name.startswith("x-"):
            # unknown phases are allowed but must opt in via the x- prefix,
            # so typos in canonical names fail the smoke tier loudly
            problems.append(f"non-canonical phase name {name!r}")
    for i, c in enumerate(record["compiles"]):
        for key in ("key", "engine", "cache_hit"):
            if key not in c:
                problems.append(f"compiles[{i}] missing {key!r}")
    for key, value in record["counters"].items():
        if not isinstance(value, (int, float)):
            problems.append(f"counter {key!r} is not numeric")
    return problems
