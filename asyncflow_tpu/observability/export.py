"""Exporters: JSONL run records and Chrome-trace/Perfetto timelines.

The Chrome trace format (the ``traceEvents`` JSON that Perfetto,
``chrome://tracing``, and ``scripts/trace_summary.py`` all read) is the
lingua franca of this repo's profiling work; the host phase timeline is
emitted in the same format so one UI shows both the XLA device trace
(``jax.profiler``) and the library's own phase spans.

Two time domains share the format:

- **host time** (:func:`write_chrome_trace`): wall-clock phases, compiles,
  device counters — what the machine did;
- **simulated time** (:func:`write_sim_trace`): the flight recorder's
  request spans, per-server/per-edge gauge timelines, breaker state, and
  fault-window occupancy — what happened inside the simulated world, with
  one simulated microsecond per trace microsecond.  One track group per
  server/edge, one thread per traced request (docs/guides/observability.md).
"""

from __future__ import annotations

import gzip
import json
from pathlib import Path

from asyncflow_tpu.observability.phases import PHASES, PhaseTimer
from asyncflow_tpu.observability.simtrace import (
    FR_ABANDON,
    FR_ARRIVE_LB,
    FR_ARRIVE_SRV,
    FR_COMPLETE,
    FR_DROP,
    FR_NAMES,
    FR_REJECT,
    FR_RETRY,
    FR_RUN,
    FR_SPAWN,
    FR_TIMEOUT,
    FR_TRANSIT,
    FR_WAIT_CPU,
    FR_WAIT_DB,
    FR_WAIT_RAM,
)

#: synthetic pid/tid for the host phase track (Chrome traces need both)
HOST_PID = 1
HOST_TID = 1


def chrome_trace_events(
    timer: PhaseTimer,
    *,
    counters: dict | None = None,
    label: str = "asyncflow-run",
) -> list[dict]:
    """Phase records -> Chrome ``traceEvents`` (complete 'X' spans).

    Timestamps are microseconds from the timer's epoch; chunk-tagged spans
    carry the chunk index in ``args`` so Perfetto can group/filter them.
    Counter totals are appended as one 'C' (counter) event at the end of
    the timeline.
    """
    events: list[dict] = [
        {
            "ph": "M",
            "pid": HOST_PID,
            "name": "process_name",
            "args": {"name": f"asyncflow host ({label})"},
        },
        {
            "ph": "M",
            "pid": HOST_PID,
            "tid": HOST_TID,
            "name": "thread_name",
            "args": {"name": "run phases"},
        },
    ]
    end_us = 0.0
    for rec in timer.events:
        args: dict = {}
        if rec.chunk is not None:
            args["chunk"] = rec.chunk
        if rec.meta:
            args.update(rec.meta)
        start_us = rec.start_s * 1e6
        dur_us = rec.duration_s * 1e6
        end_us = max(end_us, start_us + dur_us)
        events.append(
            {
                "ph": "X",
                "pid": HOST_PID,
                "tid": HOST_TID,
                "name": rec.name,
                "ts": start_us,
                "dur": dur_us,
                "args": args,
            },
        )
    if counters:
        events.append(
            {
                "ph": "C",
                "pid": HOST_PID,
                "name": "device counters",
                "ts": end_us,
                "args": {k: int(v) for k, v in counters.items()},
            },
        )
    return events


def write_chrome_trace(
    path: str | Path,
    timer: PhaseTimer,
    *,
    counters: dict | None = None,
    label: str = "asyncflow-run",
) -> Path:
    """Write the host phase timeline as a Chrome-trace file.

    ``path`` ending in ``.gz`` writes gzip (the format
    ``scripts/trace_summary.py`` and Perfetto both accept).
    """
    path = Path(path)
    payload = {
        "displayTimeUnit": "ms",
        "traceEvents": chrome_trace_events(timer, counters=counters, label=label),
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    data = json.dumps(payload).encode()
    if path.suffix == ".gz":
        with gzip.open(path, "wb") as fh:
            fh.write(data)
    else:
        path.write_bytes(data)
    return path


# ---------------------------------------------------------------------------
# simulated-time export (flight recorder + gauge timelines)
# ---------------------------------------------------------------------------

#: pid layout of the simulated-time trace (one "process" per track group)
SIM_PID_REQUESTS = 10
SIM_PID_BREAKER = 20
SIM_PID_SERVER = 100  # + server index
SIM_PID_EDGE = 300  # + edge index

_WAIT_NAMES = {
    FR_WAIT_CPU: "wait cpu",
    FR_WAIT_RAM: "wait ram",
    FR_WAIT_DB: "wait db",
}
_INSTANT_CODES = frozenset(
    {FR_SPAWN, FR_ARRIVE_LB, FR_ARRIVE_SRV, FR_RUN, FR_TIMEOUT, FR_DROP,
     FR_REJECT, FR_COMPLETE, FR_ABANDON, FR_RETRY},
)


def _meta(pid: int, name: str, tid: int | None = None) -> dict:
    ev: dict = {
        "ph": "M",
        "pid": pid,
        "name": "process_name" if tid is None else "thread_name",
        "args": {"name": name},
    }
    if tid is not None:
        ev["tid"] = tid
    return ev


def _span(pid: int, tid: int, name: str, t0: float, t1: float, **args) -> dict:
    return {
        "ph": "X",
        "pid": pid,
        "tid": tid,
        "name": name,
        "ts": t0 * 1e6,
        "dur": max(t1 - t0, 0.0) * 1e6,
        "args": args,
    }


def _request_events(results, events_out: list) -> None:
    """One thread per traced request: activity spans between lifecycle
    transitions plus instant markers for the transitions themselves."""
    server_ids = results.server_ids
    edge_ids = results.edge_ids
    events_out.append(_meta(SIM_PID_REQUESTS, "simulated requests"))
    for req in sorted(results.flight):
        rec = results.flight[req]
        tid = req + 1
        label = f"request {req}"
        if rec.dropped:
            label += f" (+{rec.dropped} events dropped)"
        events_out.append(_meta(SIM_PID_REQUESTS, label, tid))
        prev = None
        for code, node, t in rec.events:
            # activity span ending at this transition
            if prev is not None:
                p_code, p_node, p_t = prev
                name = None
                if code == FR_TRANSIT:
                    edge = edge_ids[node] if 0 <= node < len(edge_ids) else "?"
                    name = f"transit {edge}"
                elif code == FR_RUN and p_code in _WAIT_NAMES:
                    srv = (
                        server_ids[node]
                        if 0 <= node < len(server_ids)
                        else "?"
                    )
                    name = f"{_WAIT_NAMES[p_code]} {srv}"
                elif code == FR_SPAWN and p_code == FR_RETRY:
                    name = "backoff"
                if name is not None and t > p_t:
                    events_out.append(
                        _span(SIM_PID_REQUESTS, tid, name, p_t, t),
                    )
            # instant marker for the transition itself
            if code in _INSTANT_CODES or code in _WAIT_NAMES:
                name = FR_NAMES.get(code, f"code{code}")
                if code in (FR_ARRIVE_SRV, FR_RUN, FR_REJECT) and (
                    0 <= node < len(server_ids)
                ):
                    name += f" {server_ids[node]}"
                elif code == FR_DROP and 0 <= node < len(edge_ids):
                    name += f" {edge_ids[node]}"
                elif code in (FR_RETRY, FR_TIMEOUT, FR_ABANDON):
                    name += f" (attempt {node})"
                events_out.append(
                    {
                        "ph": "i",
                        "pid": SIM_PID_REQUESTS,
                        "tid": tid,
                        "name": name,
                        "ts": t * 1e6,
                        "s": "t",
                    },
                )
            prev = (code, node, t)


def _gauge_events(results, resolution_s: float | None, events_out: list) -> None:
    """Per-server / per-edge counter tracks from the sampled gauge series,
    resampled to ``resolution_s`` (stride over the native sample grid)."""
    import numpy as np

    sampled = results.sampled or {}
    period = float(results.settings.sample_period_s)
    stride = 1
    if resolution_s is not None:
        stride = max(1, round(float(resolution_s) / period))

    server_metrics = {
        "ready_queue_len": "queue depth",
        "event_loop_io_sleep": "io inflight",
        "ram_in_use": "ram held (mb)",
    }
    declared: set[int] = set()
    for metric, series_by_id in sampled.items():
        for comp_id, series in series_by_id.items():
            series = np.asarray(series)
            if metric in server_metrics and comp_id in results.server_ids:
                pid = SIM_PID_SERVER + results.server_ids.index(comp_id)
                group = f"server {comp_id}"
                name = server_metrics[metric]
            elif comp_id in results.edge_ids:
                pid = SIM_PID_EDGE + results.edge_ids.index(comp_id)
                group = f"edge {comp_id}"
                name = "inflight"
            else:  # pragma: no cover - unknown component id
                continue
            if pid not in declared:
                declared.add(pid)
                events_out.append(_meta(pid, group))
            for k in range(0, series.shape[0], stride):
                events_out.append(
                    {
                        "ph": "C",
                        "pid": pid,
                        "name": name,
                        "ts": (k + 1) * period * 1e6,
                        "args": {name: float(series[k])},
                    },
                )


def _breaker_events(results, horizon: float, events_out: list) -> None:
    """Breaker state as a stepped counter per LB rotation slot."""
    timeline = results.breaker_timeline or []
    if not timeline:
        return
    events_out.append(_meta(SIM_PID_BREAKER, "circuit breakers"))
    slots = sorted({slot for _t, slot, _s in timeline})
    for slot in slots:
        name = f"breaker slot {slot}"
        steps = [(0.0, 0)] + [
            (t, state) for t, s, state in timeline if s == slot
        ]
        for t, state in steps:
            events_out.append(
                {
                    "ph": "C",
                    "pid": SIM_PID_BREAKER,
                    "name": name,
                    "ts": t * 1e6,
                    "args": {"state(0=closed,1=open,2=half)": int(state)},
                },
            )


def _fault_events(results, payload, events_out: list) -> None:
    """Fault-window occupancy spans on the owning server/edge track."""
    timeline = getattr(payload, "fault_timeline", None) if payload else None
    if timeline is None or not timeline.events:
        return
    for fault in timeline.events:
        if fault.target_id in results.server_ids:
            pid = SIM_PID_SERVER + results.server_ids.index(fault.target_id)
        elif fault.target_id in results.edge_ids:
            pid = SIM_PID_EDGE + results.edge_ids.index(fault.target_id)
        else:  # pragma: no cover - schema validation forbids this
            continue
        events_out.append(_meta(pid, "faults", 99))
        events_out.append(
            _span(
                pid,
                99,
                f"{fault.kind} ({fault.fault_id})",
                float(fault.t_start),
                float(fault.t_end),
                latency_factor=fault.latency_factor,
                dropout_boost=fault.dropout_boost,
            ),
        )


def sim_trace_events(
    results,
    *,
    payload=None,
    resolution_s: float | None = None,
    label: str = "asyncflow-sim",
) -> list[dict]:
    """SimulationResults -> simulated-time Chrome ``traceEvents``.

    Timestamps are simulated microseconds (1 sim second = 1e6 ts units).
    Track groups: one per server (queue depth / io inflight / RAM held +
    fault windows), one per edge (inflight + fault windows), one thread
    per traced request (flight-recorder spans), breaker state counters.
    ``results`` needs a flight recorder and/or sampled gauges; ``payload``
    (optional) contributes the fault-window occupancy spans.
    """
    events: list[dict] = [
        {
            "ph": "M",
            "pid": SIM_PID_REQUESTS,
            "name": "process_name",
            "args": {"name": f"asyncflow simulated world ({label})"},
        },
    ]
    horizon = float(results.settings.total_simulation_time)
    if results.flight:
        _request_events(results, events)
    _gauge_events(results, resolution_s, events)
    _breaker_events(results, horizon, events)
    _fault_events(results, payload, events)
    return events


def write_sim_trace(
    path: str | Path,
    results,
    *,
    payload=None,
    resolution_s: float | None = None,
    label: str = "asyncflow-sim",
) -> Path:
    """Write the simulated-world timeline as a Chrome-trace file
    (``.json`` or ``.json.gz``; open in Perfetto / ``chrome://tracing``)."""
    path = Path(path)
    doc = {
        "displayTimeUnit": "ms",
        "traceEvents": sim_trace_events(
            results, payload=payload, resolution_s=resolution_s, label=label,
        ),
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    data = json.dumps(doc).encode()
    if path.suffix == ".gz":
        with gzip.open(path, "wb") as fh:
            fh.write(data)
    else:
        path.write_bytes(data)
    return path


def validate_sim_trace(doc: dict) -> list[str]:
    """Schema check for a simulated-time trace document; [] = valid.

    The smoke tier writes a tiny traced scenario and runs this so format
    drift (Perfetto compatibility) is caught per-commit.
    """
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["trace document is not an object"]
    if "traceEvents" not in doc or not isinstance(doc["traceEvents"], list):
        return ["missing traceEvents list"]
    seen_request_thread = False
    for i, ev in enumerate(doc["traceEvents"]):
        for key in ("ph", "pid", "name"):
            if key not in ev:
                problems.append(f"traceEvents[{i}] missing {key!r}")
        ph = ev.get("ph")
        if ph not in ("M", "X", "C", "i"):
            problems.append(f"traceEvents[{i}] unknown phase {ph!r}")
        if ph in ("X", "C", "i") and not isinstance(
            ev.get("ts"), (int, float),
        ):
            problems.append(f"traceEvents[{i}] non-numeric ts")
        if ph == "X":
            if not isinstance(ev.get("dur"), (int, float)):
                problems.append(f"traceEvents[{i}] span without dur")
            elif ev["dur"] < 0:
                problems.append(f"traceEvents[{i}] negative dur")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not all(
                isinstance(v, (int, float)) for v in args.values()
            ):
                problems.append(f"traceEvents[{i}] non-numeric counter args")
        if (
            ph == "M"
            and ev.get("pid") == SIM_PID_REQUESTS
            and ev.get("name") == "thread_name"
        ):
            seen_request_thread = True
    if not seen_request_thread:
        problems.append("no traced-request thread present")
    return problems


def load_chrome_trace(path: str | Path) -> dict:
    """Read a Chrome-trace file written by :func:`write_chrome_trace`."""
    path = Path(path)
    if path.suffix == ".gz":
        with gzip.open(path, "rb") as fh:
            return json.load(fh)
    return json.loads(path.read_text())


def read_run_records(path: str | Path) -> list[dict]:
    """Load every run record from a telemetry JSONL file (oldest first)."""
    path = Path(path)
    if not path.exists():
        return []
    out = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue  # torn tail line from a killed process
    return out


def validate_run_record(record: dict) -> list[str]:
    """Schema check for one run record; returns problems (empty = valid).

    The smoke tier runs this against a fresh record so schema drift is
    caught per-commit without loading an accelerator.
    """
    problems: list[str] = []

    def need(key: str, types) -> None:
        if key not in record:
            problems.append(f"missing key {key!r}")
        elif not isinstance(record[key], types):
            problems.append(
                f"{key!r} has type {type(record[key]).__name__}, "
                f"expected {types}",
            )

    need("schema", str)
    need("ts", (int, float))
    need("kind", str)
    need("phase_totals_s", dict)
    need("phases", list)
    need("compiles", list)
    need("counters", dict)
    if problems:
        return problems
    if not record["schema"].startswith("asyncflow-telemetry/"):
        problems.append(f"unknown schema {record['schema']!r}")
    for i, ph in enumerate(record["phases"]):
        for key in ("name", "start_s", "duration_s"):
            if key not in ph:
                problems.append(f"phases[{i}] missing {key!r}")
        if ph.get("duration_s", 0) < 0:
            problems.append(f"phases[{i}] negative duration")
    known = set(PHASES)
    for name in record["phase_totals_s"]:
        if name not in known and not name.startswith("x-"):
            # unknown phases are allowed but must opt in via the x- prefix,
            # so typos in canonical names fail the smoke tier loudly
            problems.append(f"non-canonical phase name {name!r}")
    for i, c in enumerate(record["compiles"]):
        for key in ("key", "engine", "cache_hit"):
            if key not in c:
                problems.append(f"compiles[{i}] missing {key!r}")
    for key, value in record["counters"].items():
        if not isinstance(value, (int, float)):
            problems.append(f"counter {key!r} is not numeric")
    return problems
