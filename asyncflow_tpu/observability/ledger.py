"""Persistent compile ledger: every jit/AOT compile, on the record.

The compile pathology work (docs/internals/compile-pathology.md) was
reconstructed from scattered session logs; the ledger makes that history a
first-class artifact.  One JSONL file lives beside the persistent XLA
compile cache (``.jax_cache`` — :mod:`asyncflow_tpu.utils.compile_cache`)
and every library-level compile appends one line::

    {"ts": ..., "key": "...", "engine": "fast", "variant": "scan",
     "shape": {"chunk": 512, "inner": 16, "blocks": 32}, "lower_s": ...,
     "compile_s": ..., "cache_hit": false, "backend": "tpu", "pid": ...}

``cache_hit`` is the *ledger's* warm/cold verdict: a program key already
recorded by an earlier process should be served by the persistent XLA
cache, so its re-compile is a cache load, not a fresh XLA compile.  The
duration columns keep the verdict honest — a "hit" at cold-compile cost is
the signal the cache directory was moved or evicted.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

_SCHEMA = "asyncflow-compile-ledger/1"
LEDGER_BASENAME = "compile_ledger.jsonl"


def default_ledger_path() -> str:
    """The ledger's home: INSIDE the persistent XLA compile-cache directory.

    It used to sit beside ``.jax_cache`` — which, with the default cache
    location, meant the repo root, where generated JSONL kept landing in
    commits.  Inside the cache dir it shares the cache's lifecycle (moved
    by ``ASYNCFLOW_COMPILE_CACHE``, wiped with the cache, ignored by git).
    """
    from asyncflow_tpu.utils.compile_cache import cache_location

    return os.path.join(cache_location(), LEDGER_BASENAME)


class CompileLedger:
    """Append-only JSONL compile log with warm/cold detection.

    Construction loads the keys of every prior entry; :meth:`record`
    appends one entry, marking ``cache_hit`` when the key was already on
    file (a previous process — or an earlier chunk shape of this one —
    compiled the same program).
    """

    def __init__(self, path: str | Path | None = None) -> None:
        self.path = Path(path) if path is not None else Path(default_ledger_path())
        self._seen: set[str] = set()
        if self.path.exists():
            for line in self.path.read_text().splitlines():
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line from a killed process
                key = entry.get("key")
                if key:
                    self._seen.add(key)

    def __len__(self) -> int:
        return len(self._seen)

    def seen(self, key: str) -> bool:
        return key in self._seen

    def record(
        self,
        key: str,
        *,
        engine: str,
        variant: str = "",
        shape: dict | None = None,
        lower_s: float | None = None,
        compile_s: float | None = None,
        backend: str = "",
        extra: dict | None = None,
    ) -> dict:
        """Append one compile entry; returns it (with the hit verdict)."""
        entry = {
            "schema": _SCHEMA,
            "ts": time.time(),
            "key": key,
            "engine": engine,
            "variant": variant,
            "shape": shape or {},
            "lower_s": round(lower_s, 6) if lower_s is not None else None,
            "compile_s": round(compile_s, 6) if compile_s is not None else None,
            "cache_hit": key in self._seen,
            "backend": backend,
            "pid": os.getpid(),
        }
        if extra:
            entry.update(extra)
        self._seen.add(key)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        with self.path.open("a") as fh:
            fh.write(json.dumps(entry) + "\n")
        return entry

    def entries(self) -> list[dict]:
        """Every parseable entry currently on file (oldest first)."""
        if not self.path.exists():
            return []
        out = []
        for line in self.path.read_text().splitlines():
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return out
