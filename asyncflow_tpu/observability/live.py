"""Live sweep follower: tail a telemetry JSONL and render fleet progress.

``SweepRunner.run(..., telemetry=TelemetryConfig(jsonl_path=...))`` appends
one ``kind="progress"`` record per finished chunk (scenarios done, EWMA
throughput, ETA, quarantine/recovery tallies) and a final ``kind="sweep"``
record.  This module follows that file from another terminal::

    python -m asyncflow_tpu.observability.live run.jsonl

``--once`` renders the current state and exits (the smoke/CI mode);
without it the follower polls until the terminal ``kind="sweep"`` record
lands.  Pure stdlib — safe to run on hosts without jax installed.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import Iterator

#: meta keys every progress record carries (validated by the smoke tier)
PROGRESS_META_KEYS = (
    "phase",
    "engine",
    "seed",
    "n_scenarios",
    "scenarios_done",
    "chunk_rows",
    "elapsed_s",
    "scenarios_per_second",
    "ewma_scenarios_per_second",
    "eta_s",
    "n_quarantined",
    "recovery_actions",
)


def validate_progress_record(record: dict) -> list[str]:
    """Schema check for one ``kind="progress"`` record (empty = valid)."""
    problems: list[str] = []
    if record.get("kind") != "progress":
        problems.append(f"kind is {record.get('kind')!r}, expected 'progress'")
    meta = record.get("meta")
    if not isinstance(meta, dict):
        return [*problems, "missing meta dict"]
    for key in PROGRESS_META_KEYS:
        if key not in meta:
            problems.append(f"missing meta key {key!r}")
    for key in ("scenarios_done", "n_scenarios", "chunk_rows"):
        if key in meta and not isinstance(meta[key], int):
            problems.append(f"meta[{key!r}] is not an int")
    return problems


def iter_records(path: str | Path, *, poll_s: float = 0.5, follow: bool = True) -> Iterator[dict]:
    """Yield records from ``path`` oldest-first, then (with ``follow``)
    poll for appended lines until a terminal ``kind="sweep"`` record.

    Torn tail lines (a chunk heartbeat from a killed process) are held
    until their newline arrives, never dropped or mis-parsed.
    """
    path = Path(path)
    offset = 0
    buf = ""
    while True:
        if path.exists():
            with path.open() as fh:
                fh.seek(offset)
                buf += fh.read()
                offset = fh.tell()
            done = False
            while "\n" in buf:
                line, buf = buf.split("\n", 1)
                if not line.strip():
                    continue
                try:
                    record = json.loads(line)
                except json.JSONDecodeError:
                    continue
                yield record
                if record.get("kind") == "sweep":
                    done = True
            if done or not follow:
                return
        elif not follow:
            return
        time.sleep(poll_s)


def _bar(done: int, total: int, width: int = 30) -> str:
    filled = int(width * done / max(total, 1))
    return "#" * filled + "-" * (width - filled)


def format_progress(record: dict) -> str:
    """One follower line for a ``kind="progress"`` record."""
    m = record.get("meta", {})
    done, total = m.get("scenarios_done", 0), m.get("n_scenarios", 0)
    line = (
        f"[{_bar(done, total)}] {done}/{total} "
        f"{m.get('ewma_scenarios_per_second', 0.0):8.1f} scen/s "
        f"eta {m.get('eta_s', 0.0):7.1f}s "
        f"({m.get('engine', '?')}/{m.get('phase', '?')})"
    )
    if m.get("n_quarantined"):
        line += f"  quarantined={m['n_quarantined']}"
    if m.get("recovery_actions"):
        line += f"  recovery={m['recovery_actions']}"
    # LLM serving heartbeat counters (present only when the plan carries
    # llm_serve steps — docs/guides/serving.md)
    if "tokens_per_s" in m:
        line += f"  {m['tokens_per_s']:.1f} tok/s"
    if m.get("kv_evictions"):
        line += f"  kv_evict={m['kv_evictions']}"
    return line


def format_final(record: dict) -> str:
    """The terminal line once the ``kind="sweep"`` record lands."""
    m = record.get("meta", {})
    return (
        f"done: {m.get('n_scenarios', '?')} scenarios on "
        f"'{m.get('engine', '?')}' in {m.get('wall_seconds', 0.0)}s "
        f"({m.get('scenarios_per_second', 0.0)} scen/s), "
        f"{m.get('n_quarantined', 0)} quarantined, "
        f"{m.get('recovery_actions', 0)} recovery action(s)"
    )


def format_recovery(record: dict) -> str:
    m = record.get("meta", {})
    kinds: dict[str, int] = {}
    for action in m.get("actions", []):
        kinds[action.get("kind", "?")] = kinds.get(action.get("kind", "?"), 0) + 1
    summary = ", ".join(f"{k}x{n}" for k, n in sorted(kinds.items()))
    return f"recovery: {summary or 'no actions'}"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m asyncflow_tpu.observability.live",
        description="Follow a sweep's telemetry JSONL and render progress.",
    )
    parser.add_argument("jsonl", help="telemetry JSONL path (may not exist yet)")
    parser.add_argument(
        "--poll", type=float, default=0.5, help="poll interval seconds",
    )
    parser.add_argument(
        "--once",
        action="store_true",
        help="render the records present now and exit (no follow)",
    )
    args = parser.parse_args(argv)

    saw_final = False
    for record in iter_records(args.jsonl, poll_s=args.poll, follow=not args.once):
        kind = record.get("kind")
        if kind == "progress":
            print(format_progress(record), flush=True)
        elif kind == "recovery":
            print(format_recovery(record), flush=True)
        elif kind == "sweep":
            print(format_final(record), flush=True)
            saw_final = True
    if not saw_final and not args.once:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
