"""Phase timers: the run-level host timeline.

:class:`PhaseTimer` extends :class:`asyncflow_tpu.utils.profiling.Stopwatch`
(the tiny accumulator the ad-hoc perf scripts used) with an *event record*
per section — start/end wall offsets plus an optional chunk tag — so a run
can be replayed as a timeline (Chrome trace / Perfetto) instead of only a
totals table.  The canonical phase names are the run pipeline stages::

    validate -> build_plan -> lower -> compile -> transfer -> execute
             -> fetch -> postprocess

Phases may nest (``execute`` wraps ``lower``/``compile`` on a cold chunk);
the exporter renders nesting as stacked spans on one track.
"""

from __future__ import annotations

import contextlib
import time
from collections.abc import Iterator
from dataclasses import dataclass, field

from asyncflow_tpu.utils.profiling import Stopwatch

#: the canonical pipeline phases, in order (exporters sort unknown names last)
PHASES = (
    "validate",
    "build_plan",
    "lower",
    "compile",
    "transfer",
    "execute",
    "fetch",
    "postprocess",
)


@dataclass(frozen=True)
class PhaseRecord:
    """One timed section: a closed span on the host timeline."""

    name: str
    #: seconds since the timer's epoch (its construction)
    start_s: float
    duration_s: float
    #: sweep chunk index the span belongs to (None for run-level phases)
    chunk: int | None = None
    #: free-form annotations (program signature, shape, ...)
    meta: dict | None = None

    def as_dict(self) -> dict:
        out = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_s": round(self.duration_s, 6),
        }
        if self.chunk is not None:
            out["chunk"] = self.chunk
        if self.meta:
            out["meta"] = self.meta
        return out


@dataclass
class PhaseTimer(Stopwatch):
    """Stopwatch that also keeps the per-section event records.

    ``sections`` (inherited) stays the name -> total-seconds accumulator;
    ``events`` is the ordered span list the exporters consume.
    """

    events: list[PhaseRecord] = field(default_factory=list)
    epoch: float = field(default_factory=time.perf_counter)
    #: wall-clock (epoch seconds) at construction, so exported timelines can
    #: be aligned across processes
    epoch_unix: float = field(default_factory=time.time)

    @contextlib.contextmanager
    def section(
        self,
        name: str,
        *,
        chunk: int | None = None,
        meta: dict | None = None,
    ) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            self.sections[name] = self.sections.get(name, 0.0) + end - start
            self.events.append(
                PhaseRecord(
                    name=name,
                    start_s=start - self.epoch,
                    duration_s=end - start,
                    chunk=chunk,
                    meta=meta,
                ),
            )

    def record(
        self,
        name: str,
        duration_s: float,
        *,
        start_s: float = 0.0,
        chunk: int | None = None,
        meta: dict | None = None,
    ) -> None:
        """Append an externally-measured span (e.g. a front door's
        validation cost measured before the timer existed)."""
        self.sections[name] = self.sections.get(name, 0.0) + duration_s
        self.events.append(
            PhaseRecord(
                name=name,
                start_s=start_s,
                duration_s=duration_s,
                chunk=chunk,
                meta=meta,
            ),
        )

    def phase_totals(self) -> dict[str, float]:
        """name -> accumulated seconds, canonical phases first."""
        known = {p: self.sections[p] for p in PHASES if p in self.sections}
        rest = {
            k: v for k, v in sorted(self.sections.items()) if k not in known
        }
        return {**known, **rest}
