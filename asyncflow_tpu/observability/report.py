"""Device-trace reporting: ``scripts/trace_summary.py``, as a library.

The round-5 profile analysis (68% of device time in sortutil's rank
machinery) was produced by an ad-hoc script; the TPU session ladders now
consume these functions instead of forking it.  Input is a
``jax.profiler`` trace directory (or an already-loaded Chrome trace dict —
including the host timelines :mod:`asyncflow_tpu.observability.export`
writes); output is a structured summary plus a formatter for the ladder
logs.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
from dataclasses import dataclass, field
from pathlib import Path


def find_trace_files(prof_dir: str | Path) -> list[str]:
    """Every ``*.trace.json.gz`` under a ``jax.profiler`` directory
    (sorted; the newest — last — is the one a summary should use)."""
    return sorted(
        glob.glob(
            os.path.join(str(prof_dir), "**", "*.trace.json.gz"),
            recursive=True,
        ),
    )


def load_trace(prof_dir: str | Path) -> dict:
    """Load the newest ``*.trace.json.gz`` under a ``jax.profiler`` dir.

    Also accepts a direct path to a ``.json``/``.json.gz`` trace file (the
    host timelines written by
    :func:`asyncflow_tpu.observability.export.write_chrome_trace`).
    """
    prof_dir = str(prof_dir)
    if os.path.isfile(prof_dir):
        if prof_dir.endswith(".gz"):
            with gzip.open(prof_dir) as f:
                return json.load(f)
        with open(prof_dir) as f:
            return json.load(f)
    paths = find_trace_files(prof_dir)
    if not paths:
        msg = f"no *.trace.json.gz under {prof_dir}"
        raise FileNotFoundError(msg)
    with gzip.open(paths[-1]) as f:
        return json.load(f)


@dataclass
class TraceSummary:
    """Device time attributed by op and by source line."""

    #: pid -> process name, straight from the trace metadata
    processes: dict[int, str | None] = field(default_factory=dict)
    #: total attributed device op microseconds (nested ops double-count
    #: inside their parents — same caveat the script always carried)
    total_us: int = 0
    #: op name -> device microseconds
    by_op: dict[str, int] = field(default_factory=dict)
    #: source attribution -> device microseconds
    by_source: dict[str, int] = field(default_factory=dict)

    def top_ops(self, n: int = 15) -> list[tuple[str, int]]:
        return collections.Counter(self.by_op).most_common(n)

    def top_sources(self, n: int = 15) -> list[tuple[str, int]]:
        return collections.Counter(self.by_source).most_common(n)


def summarize_trace(trace: dict) -> TraceSummary:
    """Device time by op and by source from a loaded Chrome trace.

    Device processes are recognized by "TPU"/"GPU" in their process name;
    the outermost ``jit_*`` containers are skipped to avoid double counting
    in the total (exactly the old script's accounting).
    """
    ev = trace["traceEvents"]
    pids = {
        e["pid"]: e["args"].get("name")
        for e in ev
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    device_pids = {
        p for p, n in pids.items() if n and ("TPU" in n or "GPU" in n)
    }

    summary = TraceSummary(processes=pids)
    by_op: collections.Counter = collections.Counter()
    by_src: collections.Counter = collections.Counter()
    for e in ev:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        name = e.get("name", "?")
        if name.startswith("jit_"):
            continue
        dur = e.get("dur", 0)
        by_op[name] += dur
        summary.total_us += dur
        src = (e.get("args") or {}).get("source")
        if src:
            by_src[src] += dur
    summary.by_op = dict(by_op)
    summary.by_source = dict(by_src)
    return summary


def format_summary(summary: TraceSummary, *, top: int = 15) -> str:
    """The ladder-log report (the old script's stdout, verbatim shape)."""
    lines = [
        f"processes: { {p: n for p, n in summary.processes.items()} }",
        "",
        f"attributed device op time: {summary.total_us / 1e6:.2f}s "
        "(nested ops double-count inside their parents)",
        "",
        f"== top {top} device ops ==",
    ]
    lines += [
        f"  {d / 1e6:8.3f}s  {name[:100]}" for name, d in summary.top_ops(top)
    ]
    lines += ["", f"== top {top} source attributions =="]
    lines += [
        f"  {d / 1e6:8.3f}s  {src}" for src, d in summary.top_sources(top)
    ]
    return "\n".join(lines)


def summarize_profile_dir(prof_dir: str | Path, *, top: int = 15) -> str:
    """One-call convenience: load + summarize + format."""
    return format_summary(summarize_trace(load_trace(prof_dir)), top=top)
