"""Simulation-domain tracing: the request flight recorder's shared layout.

Host-side run telemetry (:mod:`asyncflow_tpu.observability.telemetry`) shows
what the *host* did — compiles, transfers, kernel walls.  This module is the
vocabulary for what happened inside the *simulated world*: a bounded
per-request event record (the "flight recorder") that the JAX event engine
writes as fixed-size on-device ring buffers inside its vmapped loop and the
Python oracle emits from its heap loop — one layout, two producers, so the
streams can be diffed event-by-event (:mod:`~asyncflow_tpu.observability.
diverge`) and rendered as simulated-time Perfetto tracks
(:func:`~asyncflow_tpu.observability.export.write_sim_trace`).

Record layout (identical across engines):

- a scenario traces its first ``sample_requests`` spawned logical requests
  (deterministic sampling — no draw is consumed picking them);
- each traced request owns ``event_slots`` ring entries of
  ``(code, node, sim-time)``; writes past the budget are counted, not
  stored, so truncation is always explicit (:attr:`FlightRecord.dropped`);
- a logical request keeps its record across client retries (the re-issue
  appends to the same ring); orphaned attempts stop recording at the
  client timeout, mirroring the oracle's "orphan completions are
  invisible" contract.

``node`` is an integer whose meaning depends on the code: generator index
for :data:`FR_SPAWN`, edge index for :data:`FR_TRANSIT`/:data:`FR_DROP`,
server index for the server-side codes, the failed attempt number for the
retry-machinery codes, and ``-1`` where no component applies (LB, client).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
from pydantic import BaseModel, Field

# ---------------------------------------------------------------------------
# lifecycle event codes (shared verbatim by the jax event engine, the
# oracle, and every decoder — renumbering breaks recorded artifacts)
# ---------------------------------------------------------------------------

FR_SPAWN = 1  #: generator emitted (or client re-issued) the request
FR_TRANSIT = 2  #: an edge traversal DELIVERED (t = delivery time)
FR_ARRIVE_LB = 3  #: arrived at the load balancer
FR_ARRIVE_SRV = 4  #: accepted by a server (refusals are FR_REJECT)
FR_WAIT_RAM = 5  #: parked in the RAM admission FIFO
FR_WAIT_CPU = 6  #: joined a ready queue (core busy or waiters ahead)
FR_WAIT_DB = 7  #: parked in a DB connection-pool FIFO
FR_RUN = 8  #: a wait resolved — service granted (core/RAM/connection)
FR_RETRY = 9  #: client scheduled a backoff re-issue (node = failed attempt)
FR_TIMEOUT = 10  #: client deadline fired; the attempt is orphaned
FR_DROP = 11  #: lost to edge dropout / an empty LB rotation
FR_REJECT = 12  #: refused (outage, rate limit, socket cap, shed, abandon,
#: fully-open breaker rotation, pool overflow)
FR_COMPLETE = 13  #: delivered back to the client — the request is done
FR_ABANDON = 14  #: client gave the logical request up (node = last attempt)
FR_HEDGE = 15  #: hedge timer fired — a duplicate issued (node = hedge ordinal)
FR_CANCEL = 16  #: attempt cancelled en route (its sibling won the race)
# serving lifecycle (asyncflow_tpu/serving, docs/guides/serving.md):
FR_PREFILL = 17  #: admitted to the batch — prefill started (KV += prompt)
FR_DECODE = 18  #: decode extension fit — generation started (KV += output)
FR_EVICT = 19  #: KV pressure evicted the request (prefill will be redone)

FR_NAMES: dict[int, str] = {
    FR_SPAWN: "spawn",
    FR_TRANSIT: "transit",
    FR_ARRIVE_LB: "arrive_lb",
    FR_ARRIVE_SRV: "arrive_srv",
    FR_WAIT_RAM: "wait_ram",
    FR_WAIT_CPU: "wait_cpu",
    FR_WAIT_DB: "wait_db",
    FR_RUN: "run",
    FR_RETRY: "retry",
    FR_TIMEOUT: "timeout",
    FR_DROP: "drop",
    FR_REJECT: "reject",
    FR_COMPLETE: "complete",
    FR_ABANDON: "abandon",
    FR_HEDGE: "hedge",
    FR_CANCEL: "cancel",
    FR_PREFILL: "prefill",
    FR_DECODE: "decode",
    FR_EVICT: "evict",
}

#: codes whose ``node`` field is an edge index
_EDGE_CODES = frozenset({FR_TRANSIT, FR_DROP})
#: codes whose ``node`` field is a server index
_SERVER_CODES = frozenset(
    {FR_ARRIVE_SRV, FR_WAIT_RAM, FR_WAIT_CPU, FR_WAIT_DB, FR_RUN,
     FR_PREFILL, FR_DECODE, FR_EVICT},
)


class TraceConfig(BaseModel):
    """What the flight recorder samples and how much it may store.

    The budgets are STATIC: they size the on-device ring buffers baked into
    the jax engine's compiled program, so changing them re-specializes the
    kernel (same rule as ``pool_size``).  Tracing never consumes a random
    draw and never changes simulation results — with ``trace=None`` the
    engines compile the exact pre-trace program (a test pins
    bit-identity).
    """

    #: trace the first K spawned logical requests of every scenario
    sample_requests: int = Field(default=8, ge=1, le=4096)
    #: ring entries per traced request; writes past this are counted in
    #: :attr:`FlightRecord.dropped` instead of stored
    event_slots: int = Field(default=48, ge=4, le=4096)
    #: circuit-breaker state-transition ring entries per scenario
    breaker_slots: int = Field(default=64, ge=1, le=4096)
    #: gauge-timeline resample resolution for the Perfetto export (seconds);
    #: ``None`` keeps the scenario's native ``sample_period_s``
    resolution_s: float | None = Field(default=None, gt=0.0)


@dataclass
class FlightRecord:
    """One traced request's lifecycle, in event order.

    ``events`` entries are ``(code, node, sim_time_s)``; ``dropped`` counts
    lifecycle transitions that happened after the ring filled (explicit
    truncation — the record covers the FIRST ``event_slots`` transitions).
    """

    req: int  #: spawn sequence number within the scenario (0-based)
    events: list[tuple[int, int, float]] = field(default_factory=list)
    dropped: int = 0

    def codes(self) -> list[int]:
        return [code for code, _node, _t in self.events]

    def describe(self, *, server_ids=None, edge_ids=None) -> list[str]:
        """Human-readable event lines (component ids resolved when given)."""
        out = []
        for code, node, t in self.events:
            name = FR_NAMES.get(code, f"code{code}")
            comp = ""
            if code in _EDGE_CODES and edge_ids and 0 <= node < len(edge_ids):
                comp = f" {edge_ids[node]}"
            elif (
                code in _SERVER_CODES
                and server_ids
                and 0 <= node < len(server_ids)
            ):
                comp = f" {server_ids[node]}"
            elif code in (FR_RETRY, FR_TIMEOUT, FR_ABANDON):
                comp = f" attempt={node}"
            elif code == FR_HEDGE:
                comp = f" hedge={node}"
            elif node >= 0:
                comp = f" #{node}"
            out.append(f"t={t:.6f}s {name}{comp}")
        if self.dropped:
            out.append(f"... {self.dropped} later event(s) dropped (ring full)")
        return out


def decode_flight(
    fr_ev: np.ndarray,
    fr_node: np.ndarray,
    fr_t: np.ndarray,
    fr_n: np.ndarray,
) -> dict[int, FlightRecord]:
    """Ring arrays ``(K, slots)`` + counts ``(K,)`` -> per-request records.

    Rows that never spawned (count 0) are omitted; ``fr_n`` keeps counting
    past the slot budget, so the overflow IS the dropped-events counter.
    """
    fr_ev = np.asarray(fr_ev)
    fr_node = np.asarray(fr_node)
    fr_t = np.asarray(fr_t)
    fr_n = np.asarray(fr_n)
    slots = fr_ev.shape[1]
    out: dict[int, FlightRecord] = {}
    for row in range(fr_ev.shape[0]):
        n = int(fr_n[row])
        if n <= 0:
            continue
        stored = min(n, slots)
        out[row] = FlightRecord(
            req=row,
            events=[
                (int(fr_ev[row, j]), int(fr_node[row, j]), float(fr_t[row, j]))
                for j in range(stored)
            ],
            dropped=n - stored,
        )
    return out


def flight_dropped_events(flight: dict[int, FlightRecord] | None) -> int:
    """Total lifecycle transitions lost to full rings (0 without tracing)."""
    if not flight:
        return 0
    return sum(rec.dropped for rec in flight.values())


def decode_breaker(
    bk_t: np.ndarray,
    bk_slot: np.ndarray,
    bk_state: np.ndarray,
    bk_n,
) -> list[tuple[float, int, int]]:
    """Breaker ring -> ``[(sim_time, lb_slot, new_state), ...]`` in order.

    ``new_state`` uses the engine encoding: 0 closed, 1 open, 2 half-open.
    """
    n = min(int(bk_n), np.asarray(bk_t).shape[0])
    return [
        (float(bk_t[j]), int(bk_slot[j]), int(bk_state[j])) for j in range(n)
    ]


def canonical_spans(
    flight: dict[int, FlightRecord],
    *,
    horizon: float | None = None,
    resolution_us: float = 1.0,
    relative: bool = True,
) -> dict[int, tuple[tuple[int, int, int], ...]]:
    """Canonicalize records for cross-engine comparison.

    Two engines with independent RNG families cannot share absolute event
    times, but a request's *relative* timeline is deterministic whenever its
    path is (fixed service times, variance-0 edges, no contention).  So the
    canonical form is per request: events with ``t >= horizon`` dropped
    (the oracle heap never executes them; the jax engine records some
    forward-dated deliveries), timestamps taken relative to the request's
    first event, and quantized to ``resolution_us`` microseconds (float32
    device times vs float64 host times agree at micro-resolution, which is
    also Perfetto's display unit).
    """
    out: dict[int, tuple[tuple[int, int, int], ...]] = {}
    for req, rec in flight.items():
        events = [
            (code, node, t)
            for code, node, t in rec.events
            if horizon is None or t < horizon
        ]
        if not events:
            continue
        t0 = events[0][2] if relative else 0.0
        out[req] = tuple(
            (code, node, int(round((t - t0) * 1e6 / resolution_us)))
            for code, node, t in events
        )
    return out
